"""All-to-all hash-repartition exchange: rows routed to key-owning shards.

The missing shuffle primitive (ROADMAP item 3): both join strategies
funnel through a single-node bottleneck — broadcast materializes the
whole build side on every probe path, sort-merge pays a full columnsort
of both sides — because nothing could *repartition rows by key*. This
module is that primitive, built from the same pieces the existing mesh
ops already exercise:

- **device-side splitmix64 key hashing on uint32 pairs** — the exact
  splitmix64 the host sketches use (``relational/sketch.py``), but
  implemented as 64-bit arithmetic over two uint32 lanes so the program
  compiles and hashes identically with ``jax_enable_x64`` OFF (the
  chip-independent prep ROADMAP item 2 asks for: TPU int32/f32 worlds
  and x64 CPU tests place every row the same way for device-exact key
  dtypes);
- **per-shard bucket counts via the traced-survivor-count trick** from
  ``dfilter``: a first tiny program returns each shard's per-destination
  counts as an output read back on the host (``S*S`` int32s — counted in
  ``mesh.interstage_host_bytes``), which sizes the static exchange
  buffers;
- **static-shape ``all_to_all`` with validity masks**: each shard
  scatters its rows into ``[S, cap]`` destination buckets, one
  ``all_to_all`` swaps bucket ``d`` to shard ``d`` (the dsort
  contiguous-chunk idiom), received rows compact stably to the front and
  the per-source counts become the result's ``shard_valid``;
- **string ride-alongs re-laid out host-side exactly like reshard**:
  the program carries a global row id; host (non-tensor) columns replay
  the placement on the host from it.

Every dispatch rides the established contracts: ``elastic_call``
(device-loss shrink/reshard/re-run), ledger admission on the exchange
buffers (``memory.estimate.exchange_buffer_bytes`` + ``make_room``,
results registered spillable), compiled-program LRU caching, and the
skew observability surface (``mesh.exchange_*`` counters, an
``explain()`` imbalance line wired to ``TFT_SKEW_WARN``, and
``record_stream_feedback`` — groundwork for ROADMAP item 4).

``TFT_SHUFFLE=0`` is the kill switch: the CONSUMERS (``join()``
routing, :func:`shuffle_daggregate`, ``partitioned_hash_join``) fall
back to the broadcast/chunked/sort-merge paths bit-identically by
construction; the primitive itself stays callable either way.

Output order: received rows are ordered by (source shard, source row)
— i.e. the original global row order restricted to each shard's key
range — so consumers that need the pre-exchange order (the partitioned
join's probe side) restore it with one stable sort on a carried row id.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..utils.compat import shard_map
from .. import memory as _memory
from ..engine import ops as _ops
from ..frame import TensorFrame
from ..observability import flight as _flight
from ..observability.events import current_trace, traced_query
from ..resilience import invariants as _invariants
from ..resilience.policy import env_bool, env_int
from ..utils.logging import get_logger
from ..utils.tracing import counters, span
from . import elastic as _elastic

__all__ = ["dexchange", "shuffle_daggregate", "shuffle_enabled",
           "shuffle_agg_groups_threshold", "exchange_hash_host"]

_log = get_logger("parallel.exchange")


def shuffle_enabled() -> bool:
    """The shuffle kill switch (``TFT_SHUFFLE``, default on). Off, the
    consumers — ``join()`` auto-routing, ``partitioned_hash_join``,
    :func:`shuffle_daggregate` and the ``daggregate`` high-cardinality
    auto-route — restore the broadcast/chunked/sort-merge paths
    bit-identically by construction."""
    return env_bool("TFT_SHUFFLE", True)


def shuffle_agg_groups_threshold() -> Optional[int]:
    """Group count above which ``daggregate``'s monoid host-key path
    auto-routes to the shuffle-partitioned aggregation
    (``TFT_SHUFFLE_AGG_GROUPS``, default 131072; <= 0 disables the
    auto-route)."""
    v = env_int("TFT_SHUFFLE_AGG_GROUPS", 1 << 17)
    return v if v and v > 0 else None


# ---------------------------------------------------------------------------
# splitmix64 on uint32 pairs (works with jax_enable_x64 off)
# ---------------------------------------------------------------------------
# The three 64-bit constants of the host _splitmix64
# (relational/sketch.py), split into (hi, lo) uint32 halves.

_SM_GAMMA = (0x9E3779B9, 0x7F4A7C15)
_SM_MUL1 = (0xBF58476D, 0x1CE4E5B9)
_SM_MUL2 = (0x94D049BB, 0x133111EB)


def _add64(ah, al, bh, bl):
    """(a + b) mod 2^64 over (hi, lo) uint32 pairs."""
    lo = al + bl
    carry = (lo < al).astype(jnp.uint32)
    return ah + bh + carry, lo


def _mul32_wide(a, b):
    """The full 64-bit product of two uint32 lanes as a (hi, lo) pair
    — 16-bit limb products, each exact in uint32."""
    a0 = a & jnp.uint32(0xFFFF)
    a1 = a >> 16
    b0 = b & jnp.uint32(0xFFFF)
    b1 = b >> 16
    ll = a0 * b0
    lh = a0 * b1
    mid = lh + a1 * b0
    carry_mid = (mid < lh).astype(jnp.uint32)
    lo = ll + (mid << 16)
    carry_lo = (lo < ll).astype(jnp.uint32)
    hi = a1 * b1 + (mid >> 16) + (carry_mid << 16) + carry_lo
    return hi, lo


def _mul64(ah, al, bh, bl):
    """(a * b) mod 2^64 over (hi, lo) uint32 pairs."""
    hi, lo = _mul32_wide(al, bl)
    return hi + al * bh + ah * bl, lo


def _xorshr64(h, l, n: int):
    """z ^ (z >> n) for 0 < n < 32, over a (hi, lo) uint32 pair."""
    return h ^ (h >> n), l ^ ((l >> n) | (h << (32 - n)))


def _splitmix64_pair(h, l):
    """The splitmix64 finalizer over (hi, lo) uint32 pairs — the same
    constants and shift schedule as the host ``_splitmix64``, so for
    device-exact key dtypes (ints, bools, f64 under x64) the device
    hash equals the host hash bit for bit."""
    h, l = _add64(h, l, jnp.uint32(_SM_GAMMA[0]), jnp.uint32(_SM_GAMMA[1]))
    h, l = _xorshr64(h, l, 30)
    h, l = _mul64(h, l, jnp.uint32(_SM_MUL1[0]), jnp.uint32(_SM_MUL1[1]))
    h, l = _xorshr64(h, l, 27)
    h, l = _mul64(h, l, jnp.uint32(_SM_MUL2[0]), jnp.uint32(_SM_MUL2[1]))
    return _xorshr64(h, l, 31)


def _key_pair(a):
    """A device key column as the (hi, lo) uint32 pair of the 64-bit
    value the host ``_hash64`` would hash: ints sign-extend to 64-bit
    two's complement, floats canonicalize -0.0 and NaN first. f32
    columns (x64 off) hash their own 32-bit pattern — deterministic and
    identical on both join sides (key dtypes must match), just not the
    host's f64 widening."""
    dt = a.dtype
    if jnp.issubdtype(dt, jnp.floating):
        if np.dtype(dt).itemsize < 4:
            a = a.astype(jnp.float32)
        a = jnp.where(a == 0, jnp.zeros((), a.dtype), a)
        a = jnp.where(jnp.isnan(a), jnp.full((), jnp.nan, a.dtype), a)
        if np.dtype(a.dtype).itemsize == 8:
            pair = jax.lax.bitcast_convert_type(a, jnp.uint32)
            return pair[..., 1], pair[..., 0]
        lo = jax.lax.bitcast_convert_type(a, jnp.uint32)
        return jnp.zeros_like(lo), lo
    if dt == jnp.bool_:
        lo = a.astype(jnp.uint32)
        return jnp.zeros_like(lo), lo
    if np.dtype(dt).itemsize == 8:  # int64 / uint64 (x64 on)
        pair = jax.lax.bitcast_convert_type(a, jnp.uint32)
        return pair[..., 1], pair[..., 0]
    if jnp.issubdtype(dt, jnp.unsignedinteger):
        lo = a.astype(jnp.uint32)
        return jnp.zeros_like(lo), lo
    i = a.astype(jnp.int32)
    lo = jax.lax.bitcast_convert_type(i, jnp.uint32)
    hi = jnp.where(i < 0, jnp.uint32(0xFFFFFFFF), jnp.uint32(0))
    return hi, lo


def _hash_pairs(key_cols):
    """Chain-combine per-key hashes exactly like the host sketches:
    ``h = hash(k0); h = splitmix64(h ^ hash(k))`` for each further key,
    where ``hash(k) = splitmix64(bits64(k))``."""
    h = l = None
    for a in key_cols:
        kh, kl = _splitmix64_pair(*_key_pair(a))
        if h is None:
            h, l = kh, kl
        else:
            h, l = _splitmix64_pair(h ^ kh, l ^ kl)
    return h, l


def _dest_from_hash(h, l, S: int):
    """``hash64 % S`` without 64-bit arithmetic:
    ``((hi % S) * (2^32 % S) + lo % S) % S`` — exact for S < 2^16."""
    m = jnp.uint32(S)
    r = jnp.uint32((1 << 32) % S)
    return (((h % m) * r + (l % m)) % m).astype(jnp.int32)


def exchange_hash_host(key_arrays: Sequence[np.ndarray]) -> np.ndarray:
    """The host twin of the device key hash (uint64 lanes): the sketch
    ``_hash64`` chain. Used for string / mixed key columns (which never
    enter the sharded program) and by the placement property tests —
    for device-exact key dtypes ``exchange_hash_host(keys) % S`` IS the
    destination shard the device program picks."""
    from ..relational.sketch import _hash64, _splitmix64
    h = _hash64(np.asarray(key_arrays[0]))
    for k in key_arrays[1:]:
        h = _splitmix64(h ^ _hash64(np.asarray(k)))
    return h


# ---------------------------------------------------------------------------
# the exchange programs (LRU-cached like _dsort_cache)
# ---------------------------------------------------------------------------

_exchange_cache: "OrderedDict[tuple, object]" = OrderedDict()
_EXCHANGE_CACHE_CAP = 32


def _cached_program(key, build):
    fn = _exchange_cache.get(key)
    if fn is not None:
        _exchange_cache.move_to_end(key)
        return fn
    fn = jax.jit(build())
    _exchange_cache[key] = fn
    while len(_exchange_cache) > _EXCHANGE_CACHE_CAP:
        _exchange_cache.popitem(last=False)
    return fn


def _counts_program(mesh, rows_per: int, S: int, key_specs, hash_on_device):
    """Per-shard per-destination bucket counts ([S] int32 out, sharded
    over the axis → global [S*S]) — the dfilter survivor-count trick,
    run first so the exchange buffers get a static size."""
    axis = mesh.data_axis
    key = ("counts", mesh.mesh, axis, rows_per, S, hash_on_device,
           key_specs)
    in_specs = (P(axis),) + tuple(P(axis) for _ in key_specs)
    out_specs = P(axis)

    def build():
        def shard_fn(cnt, *keys):
            if hash_on_device:
                dest = _dest_from_hash(*_hash_pairs(keys), S)
            else:
                dest = keys[0]
            valid = jnp.arange(rows_per) < cnt[0]
            d = jnp.where(valid, jnp.clip(dest, 0, S - 1), S)
            return jnp.zeros((S,), jnp.int32).at[d].add(
                jnp.where(valid, jnp.int32(1), jnp.int32(0)), mode="drop")

        return shard_map(shard_fn, mesh=mesh.mesh, in_specs=in_specs,
                         out_specs=out_specs)

    return _cached_program(key, build)


def _exchange_program(mesh, rows_per: int, S: int, cap: int, col_specs,
                      key_idx, hash_on_device, want_rowid: bool):
    """The exchange itself: stable bucket scatter into ``[S, cap]``,
    one ``all_to_all`` per column (+ the bucket counts), validity-mask
    compaction of the received slots, per-shard received total out."""
    axis = mesh.data_axis
    key = ("exchange", mesh.mesh, axis, rows_per, S, cap, col_specs,
           tuple(key_idx), hash_on_device, want_rowid)
    in_specs = (P(axis),) + tuple(
        P(axis, *([None] * (len(cell) )))
        for _, cell, _ in col_specs)
    n_cols = len(col_specs)
    out_col_specs = tuple(
        P(axis, *([None] * (len(cell))))
        for _, cell, _ in col_specs)
    out_specs = out_col_specs + ((P(axis),) if want_rowid else ()) \
        + (P(axis),)

    def build():
        def shard_fn(cnt, *cols):
            me = jax.lax.axis_index(axis)
            if hash_on_device:
                dest = _dest_from_hash(
                    *_hash_pairs([cols[i] for i in key_idx]), S)
            else:
                dest = cols[key_idx[0]]
            valid = jnp.arange(rows_per) < cnt[0]
            d = jnp.where(valid, jnp.clip(dest, 0, S - 1), S)
            # stable sort by destination: each bucket's rows keep their
            # source order, so receivers see original global row order
            order = jnp.argsort(d.astype(jnp.int32), stable=True)
            d_s = jnp.take(d, order)
            bcounts = jnp.zeros((S,), jnp.int32).at[d].add(
                jnp.where(valid, jnp.int32(1), jnp.int32(0)), mode="drop")
            starts = jnp.concatenate(
                [jnp.zeros((1,), jnp.int32), jnp.cumsum(bcounts)[:-1]])
            within = jnp.arange(rows_per, dtype=jnp.int32) - jnp.take(
                starts, jnp.clip(d_s, 0, S - 1))
            pos = jnp.where(d_s < S,
                            jnp.clip(d_s, 0, S - 1) * cap + within,
                            S * cap)  # pads scatter out of range: dropped

            def xchg(buf):
                b = buf.reshape((S, cap) + buf.shape[1:])
                b = jax.lax.all_to_all(b, axis, 0, 0, tiled=False)
                return b.reshape((S * cap,) + buf.shape[1:])

            rc = jax.lax.all_to_all(
                bcounts.reshape(S, 1), axis, 0, 0, tiled=False
            ).reshape(S)
            slot = jnp.arange(S * cap, dtype=jnp.int32)
            recv_valid = (slot % cap) < jnp.take(rc, slot // cap)
            corder = jnp.argsort(
                jnp.where(recv_valid, jnp.int8(0), jnp.int8(1)),
                stable=True)

            def route(c):
                cs = jnp.take(c, order, axis=0)
                buf = jnp.zeros((S * cap,) + c.shape[1:], c.dtype)
                buf = buf.at[pos].set(cs, mode="drop")
                return jnp.take(xchg(buf), corder, axis=0)

            outs = tuple(route(c) for c in cols)
            if want_rowid:
                rowid = (me * rows_per
                         + jnp.arange(rows_per)).astype(jnp.int32)
                outs = outs + (route(rowid),)
            return outs + (jnp.sum(rc, dtype=jnp.int32)[None],)

        return shard_map(shard_fn, mesh=mesh.mesh, in_specs=in_specs,
                         out_specs=out_specs)

    return _cached_program(key, build), n_cols


# ---------------------------------------------------------------------------
# the public exchange
# ---------------------------------------------------------------------------

def _meta_dexchange(keys=None, dist=None, *a, **k):
    dist = k.get("dist", dist)
    keys = k.get("keys", keys)
    if dist is None:
        return {}
    m = dist.mesh
    return {"mesh_shape": dict(m.mesh.shape),
            "shards": m.num_data_shards, "rows": dist.num_rows,
            "keys": [keys] if isinstance(keys, str) else list(keys or ())}


def dexchange(keys, dist):
    """Hash-repartition ``dist`` so every row lives on the shard owning
    its key's hash range (``splitmix64(key) % shards``).

    Placement is a pure function of the key VALUES and the shard count —
    two frames exchanged by equal-dtype keys on the same mesh colocate
    equal keys on the same shard (the partitioned-join invariant), and
    repeated exchanges of the same data place identically. Keys must be
    scalar columns; numeric keys hash on device (the uint32-pair
    splitmix64 — x64 not required), string / mixed key sets hash on the
    host and ship a destination column instead. Host (string) ride-along
    columns re-lay out host-side from the carried row ids, exactly like
    ``reshard``. Dispatch crosses ``elastic_call``: a device loss
    shrinks the mesh, re-shards, and re-runs — same rows, fewer (wider)
    hash ranges.

    Returns a frame with per-shard validity (``shard_valid``) whose
    received rows are ordered by original global row order within each
    shard. Single-shard meshes return ``dist`` unchanged.
    """
    lz = getattr(dist, "_tft_lazy_dist", False)
    if lz:
        from ..plan import dist as _dplan
        dist = _dplan.materialize(dist)
    keys = [keys] if isinstance(keys, str) else list(keys)
    if not keys:
        raise ValueError("dexchange needs at least one key column")
    for k in keys:
        f = dist.schema.get(k)
        if f is None:
            raise KeyError(
                f"No key column {k!r}; columns: {dist.schema.names}")
        if f.sql_rank != 0:
            raise _ops.InvalidTypeError(
                f"dexchange key {k!r} must be a scalar column")
    if dist.mesh.num_data_shards <= 1:
        return dist
    return _dexchange_eager(keys, dist)


@traced_query("dexchange", _meta_dexchange)
def _dexchange_eager(keys, dist):
    return _elastic.elastic_call("dexchange", dist,
                                 lambda d: _dexchange(keys, d))


def _dexchange(keys, dist):
    from .distributed import DistributedFrame, _read_global
    mesh = dist.mesh
    S = mesh.num_data_shards
    if S <= 1:
        return dist
    if dist.padded_rows % S != 0:
        # non-tiling (trim/global-result) frames first normalize to the
        # even prefix layout — the same host round-trip reshard uses
        dist = _elastic.reshard(dist, mesh)
    if dist.padded_rows >= 2 ** 31:
        raise ValueError(
            f"dexchange carries int32 row ids; {dist.padded_rows} padded "
            f"rows overflow them")
    t_start = time.perf_counter()
    axis = mesh.data_axis
    rows_per = dist.padded_rows // S
    schema = dist.schema
    tensor_names = [f.name for f in schema if f.dtype.tensor]
    host_names = [f.name for f in schema if not f.dtype.tensor]
    hash_on_device = all(schema[k].dtype.tensor for k in keys)

    counts_host = dist.per_shard_valid().astype(np.int32)
    cnt_dev = jax.make_array_from_callback(
        (S,), mesh.row_sharding(1), lambda idx: counts_host[idx])

    arrays = [dist.columns[n] for n in tensor_names]
    col_specs = tuple((n, tuple(a.shape[1:]), str(a.dtype))
                      for n, a in zip(tensor_names, arrays))

    if hash_on_device:
        key_arrays = [dist.columns[k] for k in keys]
        key_specs = tuple((k, str(dist.columns[k].dtype)) for k in keys)
    else:
        # string / mixed keys: destinations computed on the host with
        # the sketch hash chain, shipped in as one int32 column (both
        # join sides take this path — key dtypes must match — so
        # placement stays consistent)
        host_keys = [dist.host_read_padded(k) for k in keys]
        dest_host = (exchange_hash_host(host_keys)
                     % np.uint64(S)).astype(np.int32)
        key_arrays = [jax.make_array_from_callback(
            (dist.padded_rows,), mesh.row_sharding(1),
            lambda idx: dest_host[idx])]
        key_specs = (("_tft_dest", "int32"),)

    # -- phase 1: bucket counts (the traced-survivor-count trick) ---------
    cfn = _counts_program(mesh, rows_per, S, key_specs, hash_on_device)
    with span("dexchange.counts"):
        c_global = _read_global(cfn(cnt_dev, *key_arrays))
    counters.inc("mesh.interstage_host_bytes", 4 * S * S)
    cmat = np.asarray(c_global, np.int64).reshape(S, S)  # [src, dst]
    maxc = int(cmat.max()) if cmat.size else 0
    # round the static bucket capacity up so near-miss sizes reuse the
    # compiled program; never beyond rows_per (a bucket cannot exceed it)
    cap = min(max(((max(maxc, 1) + 15) // 16) * 16, 1), rows_per)

    # -- ledger admission on the receive buffers ---------------------------
    from ..memory.estimate import exchange_buffer_bytes
    est = exchange_buffer_bytes(
        [(cell, dt) for _, cell, dt in col_specs], S, cap,
        rowid_bytes=4 if host_names else 0)
    mgr = _memory.active()
    if mgr is not None and est:
        mgr.make_room(est)
    counters.inc("mesh.exchange_bytes", est)

    # -- phase 2: the exchange --------------------------------------------
    want_rowid = bool(host_names)
    prog_arrays = list(arrays)
    key_idx = []
    if hash_on_device:
        key_idx = [tensor_names.index(k) for k in keys]
    else:
        prog_arrays = prog_arrays + key_arrays
        col_specs = col_specs + (("_tft_dest", (), "int32"),)
        key_idx = [len(tensor_names)]
    fn, n_cols = _exchange_program(mesh, rows_per, S, cap, col_specs,
                                   key_idx, hash_on_device, want_rowid)
    trace = current_trace()
    t0 = 0.0
    if trace is not None:
        from .distributed import _trace_shards, _trace_mesh_done
        t0 = _trace_shards(trace, "dexchange", dist=dist)
        trace.add("collective", name="all_to_all", ts=t0, op="dexchange",
                  columns=len(col_specs))
    with span("dexchange.dispatch"):
        outs = fn(cnt_dev, *prog_arrays)
    if trace is not None:
        _trace_mesh_done(trace, list(outs), t0, "dexchange", mesh=mesh)
    counters.inc("mesh.dispatches")

    n_tensor = len(tensor_names)
    new_cols: Dict[str, jax.Array] = dict(zip(tensor_names, outs[:n_tensor]))
    recv = _read_global(outs[-1]).astype(np.int64)  # [S] per-shard totals
    counters.inc("mesh.interstage_host_bytes", 4 * S)
    total = int(recv.sum())
    if total != dist.num_rows:
        # raises in EVERY mode (resilience/invariants.py): rows lost
        # across an all-to-all are never a count-and-continue condition
        _invariants.conserve(
            dist.num_rows, total,
            f"dexchange (per-shard {recv.tolist()})")

    per_out = S * cap
    if want_rowid:
        rowid_g = _read_global(outs[n_cols])
        counters.inc("mesh.interstage_host_bytes", 4 * S * per_out)
        vmask = (np.arange(S * per_out) % per_out) < np.repeat(recv, per_out)
        for n in host_names:
            src = np.asarray(dist.columns[n], object)
            out_a = np.full(S * per_out, None, object)
            out_a[vmask] = src[rowid_g[vmask]]
            new_cols[n] = out_a

    if mgr is not None and mgr.spill_enabled:
        new_cols = _memory.spillable_columns(
            f"dexchange@{id(dist):x}", new_cols, mgr)
    result = DistributedFrame(mesh, schema, new_cols, dist.num_rows,
                              shard_valid=recv)
    _note_exchange_skew(result, recv, total, S,
                        time.perf_counter() - t_start)
    return result


def _note_exchange_skew(result, recv: np.ndarray, total: int, S: int,
                        wall_s: float) -> None:
    """The exchange's skew observability surface: ``mesh.exchange_*``
    counters, the ``explain()`` imbalance line (``result._exchange``),
    a flight-recorder anomaly past ``TFT_SKEW_WARN``, and the adaptive
    layer's stream feedback (ROADMAP item 4 groundwork)."""
    from ..observability.report import _skew_threshold
    counters.inc("mesh.exchange_dispatches")
    counters.inc("mesh.exchange_rows", total)
    med = float(np.median(recv))
    mx = float(recv.max()) if recv.size else 0.0
    ratio = (mx / med) if med > 0 else (float("inf") if mx else 0.0)
    thr = _skew_threshold()
    result._exchange = {"op": "dexchange",
                        "per_shard": [int(v) for v in recv],
                        "ratio": ratio, "threshold": thr}
    if ratio > thr:
        counters.inc("mesh.exchange_skew_events")
        _flight.record("mesh.exchange_skew", op="dexchange",
                       ratio=round(min(ratio, 1e9), 3), threshold=thr,
                       rows=total,
                       per_shard=[int(v) for v in recv[:16]])
        _log.info(
            "dexchange: partition imbalance %.2f over TFT_SKEW_WARN=%.2f "
            "(per-shard rows %s)", ratio, thr, [int(v) for v in recv])
    try:
        from ..plan.adaptive import record_stream_feedback
        occupancy = (total / S) / mx if mx else None
        record_stream_feedback("dexchange", blocks=S, rows=total,
                               wall_s=max(wall_s, 1e-9),
                               occupancy=occupancy)
    except Exception as e:  # noqa: BLE001 - feedback is advisory
        _log.debug("exchange stream feedback failed: %s", e)


# ---------------------------------------------------------------------------
# shuffle-partitioned aggregation (high-cardinality keys)
# ---------------------------------------------------------------------------

def shuffle_daggregate(fetches, dist, keys) -> TensorFrame:
    """Keyed aggregation by hash-repartition: rows exchange to their
    key-owning shards, each shard aggregates ONLY its own (disjoint)
    key ranges, and the per-shard results concatenate + reorder to
    ``daggregate``'s canonical ascending group order.

    For high-cardinality keys this replaces ``daggregate``'s dense
    ``[groups, ...]`` per-shard tables (every shard holds EVERY group)
    with O(groups / shards) state per device — beyond what hot-key
    salting addresses (salting spreads few huge groups; this spreads
    many). ``daggregate``'s monoid host-key path auto-routes here above
    ``TFT_SHUFFLE_AGG_GROUPS`` groups. Same result frame: same groups,
    same order, same dtypes — exact for discrete combiners (min/max,
    int sums); float sums may reassociate, like any resharding
    (``docs/joins.md``). ``TFT_SHUFFLE=0``, single-shard meshes,
    sketch combiners, and non-monoid fetches delegate to
    ``daggregate`` unchanged.
    """
    from .distributed import daggregate
    keys = [keys] if isinstance(keys, str) else list(keys)
    from ..engine.ops import _is_sketch, _monoid_mapping
    if (not shuffle_enabled() or dist.mesh.num_data_shards <= 1
            or not _monoid_mapping(fetches)
            or any(_is_sketch(v) for v in fetches.values())):
        return daggregate(fetches, dist, keys)
    if dist.num_rows == 0:
        raise ValueError("aggregate on an empty distributed frame")
    return _shuffle_daggregate_impl(fetches, dist, keys)


def _shuffle_daggregate_impl(fetches, dist, keys) -> TensorFrame:
    """The exchanged monoid aggregation (callers validated the route)."""
    from .. import api as _api
    from ..engine.ops import _factorize_keys
    from ..frame import Block
    from ..schema import Field, Schema
    from ..shape import Unknown

    fetch_names = sorted(fetches)
    needed = list(dict.fromkeys(list(keys) + fetch_names))
    sub = dist.select(needed) if set(needed) != set(dist.schema.names) \
        else dist
    with span("daggregate.shuffle"):
        ex = dexchange(keys, sub)
        S = ex.mesh.num_data_shards
        valid = ex.per_shard_valid()
        rows_per = ex.padded_rows // S
        host = {n: ex.host_read_padded(n) for n in needed}
        schema = ex.schema
        parts: List[Block] = []
        for s in range(S):
            k = int(valid[s])
            if k == 0:
                continue
            cols = {}
            for n in needed:
                a = host[n][s * rows_per: s * rows_per + k]
                f = schema[n]
                if isinstance(a, np.ndarray) and f.dtype.tensor \
                        and a.dtype != f.dtype.np_storage:
                    a = a.astype(f.dtype.np_storage)
                cols[n] = a
            shard_frame = TensorFrame.from_columns(
                cols, schema=schema.select(needed))
            part = _api.aggregate(dict(fetches),
                                  shard_frame.group_by(*keys))
            parts.append(Block.concat(part.blocks(), part.schema))
        out_fields = [schema[k] for k in keys] + [
            Field(f, schema[f].dtype,
                  block_shape=(schema[f].block_shape.with_lead(Unknown)
                               if schema[f].block_shape is not None
                               else None),
                  sql_rank=schema[f].sql_rank)
            for f in fetch_names]
        out_schema = Schema(out_fields)
        merged = Block.concat(parts, out_schema)
        # shards own disjoint hash ranges, not contiguous key ranges —
        # one stable lexsort restores daggregate's ascending group order
        fact = _factorize_keys([np.asarray(merged.columns[k])
                                for k in keys])
        order = fact.order
        cols = {n: (merged.columns[n][order]
                    if isinstance(merged.columns[n], np.ndarray)
                    else [merged.columns[n][i] for i in order])
                for n in out_schema.names}
        counters.inc("mesh.shuffle_daggregates")
        return TensorFrame.from_blocks(
            [Block(cols, merged.num_rows)], out_schema)
