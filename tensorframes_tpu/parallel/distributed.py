"""Distributed frames: mesh-sharded columns and mesh-level map/reduce.

The TPU-native re-expression of the reference's executor-side distribution
(SURVEY.md §2.3). A :class:`DistributedFrame` holds each column as ONE global
``jax.Array`` row-sharded over the mesh's data axis — partitions become
shards, the broadcast-the-graph step becomes XLA program replication, and:

- :func:`dmap_blocks` — the ``rdd.mapPartitions`` analogue
  (``DebugRowOps.scala:372-386``): one jit dispatch executes every shard in
  parallel with no cross-device traffic;
- :func:`dreduce_blocks` — the block-reduce + Spark-tree-combine analogue
  (``DebugRowOps.scala:490-513``). For the associative monoid combiners
  (sum/min/max/prod) it lowers to one ``shard_map`` program whose
  cross-shard combine is a ``psum``-family ICI collective, with pad rows
  masked to the combiner's neutral element; arbitrary user computations
  take the per-device path — one async jit dispatch per shard device (JAX's
  async dispatch overlaps them), partials stacked and reduced once, which
  preserves the reference's "combine order unspecified" contract exactly.

Multi-host: build the mesh over ``jax.devices()`` after
``jax.distributed.initialize`` and the same code spans hosts — data-axis
collectives ride ICI within a slice and DCN across slices.
"""

from __future__ import annotations

from typing import Dict, Mapping, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P
from jax import shard_map

from .. import dtypes as _dt
from ..engine import ops as _ops
from ..frame import Block, TensorFrame
from ..schema import Schema
from .collectives import COMBINERS
from .mesh import DeviceMesh

__all__ = ["DistributedFrame", "distribute", "dmap_blocks",
           "dreduce_blocks", "daggregate"]

def _jitted(comp):
    """One jitted wrapper per live Computation, stored on the object so it
    is collected with it: repeated dmap/dreduce calls on the same
    computation reuse the trace instead of re-wrapping jax.jit."""
    fn = getattr(comp, "_tft_jitted", None)
    if fn is None:
        fn = jax.jit(comp.fn)
        comp._tft_jitted = fn
    return fn


class DistributedFrame:
    """Columns as global row-sharded jax Arrays + the true row count.

    ``num_rows`` is the un-padded row count; rows are padded up to a
    multiple of the data-axis size so every shard is equal (XLA's static
    world), and consumers mask or slice the pad away.
    """

    def __init__(self, mesh: DeviceMesh, schema: Schema,
                 columns: Dict[str, jax.Array], num_rows: int):
        self.mesh = mesh
        self.schema = schema
        self.columns = columns
        self.num_rows = num_rows

    @property
    def padded_rows(self) -> int:
        first = next(iter(self.columns.values()))
        return first.shape[0]

    def collect_frame(self, num_partitions: Optional[int] = None) -> TensorFrame:
        """Bring the data back to the host as a TensorFrame (pad dropped)."""
        cols = {n: np.asarray(a)[: self.num_rows]
                for n, a in self.columns.items()}
        host_cols = {}
        for f in self.schema:
            a = cols[f.name]
            if a.dtype != f.dtype.np_storage and f.dtype is not _dt.bfloat16:
                a = a.astype(f.dtype.np_storage)
            host_cols[f.name] = a
        return TensorFrame.from_columns(
            host_cols, schema=self.schema,
            num_partitions=num_partitions or self.mesh.num_data_shards)

    def __repr__(self):
        return (f"DistributedFrame[{', '.join(self.schema.names)}] "
                f"rows={self.num_rows} mesh={self.mesh!r}")


def distribute(df: TensorFrame, mesh: DeviceMesh) -> DistributedFrame:
    """Shard a host frame over the mesh's data axis.

    The analogue of Spark scattering partitions to executors — except the
    placement is an explicit ``device_put`` with a ``NamedSharding``, and
    the "partitions" are equal shards of one global array (pad rows, zero
    filled, make up the remainder; ``num_rows`` remembers the truth).
    """
    merged = Block.concat(df.blocks(), df.schema)
    n = merged.num_rows
    shards = mesh.num_data_shards
    padded = ((n + shards - 1) // shards) * shards if n else shards
    cols: Dict[str, jax.Array] = {}
    for f in df.schema:
        a = merged.dense(f.name)
        dd = _dt.device_dtype(f.dtype)
        if a.dtype != dd:
            from .. import native as _native
            a = _native.convert(a, dd)
        if padded != n:
            pad = [(0, padded - n)] + [(0, 0)] * (a.ndim - 1)
            a = np.pad(a, pad)
        cols[f.name] = jax.device_put(a, mesh.row_sharding(a.ndim))
    return DistributedFrame(mesh, df.schema, cols, n)


def dmap_blocks(fetches, dist: DistributedFrame, trim: bool = False,
                row_aligned: Optional[bool] = None) -> DistributedFrame:
    """Mesh-parallel map: one jit dispatch, all shards in parallel.

    Without ``trim``, outputs ride alongside the inputs and must be
    row-local (each output row depends on its input row and replicated
    constants); pad rows flow through and are dropped at collect. With
    ``trim=True`` the computation sees the GLOBAL padded array and may
    change the row count (e.g. an in-graph pre-aggregation emitting one
    global row — the ``kmeans_demo.py:128-140`` pattern at mesh scale);
    XLA/GSPMD inserts whatever cross-shard collectives the program needs.
    Such computations must mask pad rows themselves (``dist.num_rows`` is
    the true count; ``padded_rows`` what they will see).

    ``row_aligned`` declares how a trim output relates to the input rows:
    ``True`` — output rows correspond 1:1 to input rows (pad structure
    survives, dropped at collect); ``False`` — the output is a fresh global
    result (every emitted row is real). Default ``None`` infers from the
    row count (equal to ``padded_rows`` -> aligned); pass the flag
    explicitly when the sizes could coincide.
    """
    schema = dist.schema
    if row_aligned is False and not trim:
        raise ValueError(
            "row_aligned=False only makes sense for trim=True outputs: "
            "without trim the untrimmed input columns ride along and still "
            "contain pad rows, which declaring every output row real would "
            "surface as data")
    comp = _ops._map_computation(fetches, schema, block_level=True)
    out_schema = _ops._validate_map(comp, schema, block_level=True, trim=trim)
    mesh = dist.mesh

    jitted = _jitted(comp)
    out = jitted({n: dist.columns[n] for n in comp.input_names})
    leads = {out[s.name].shape[0] for s in comp.outputs}
    if len(leads) > 1:
        raise ValueError(
            f"Distributed map fetches disagree on output row count: "
            f"{ {s.name: out[s.name].shape[0] for s in comp.outputs} }")
    n_out = leads.pop() if leads else dist.padded_rows
    if n_out != dist.padded_rows and not trim:
        raise ValueError(
            f"Distributed map output changed the row count ({n_out} vs "
            f"{dist.padded_rows}); use trim=True for row-count-changing "
            f"(global) computations")
    if row_aligned is None:
        row_aligned = n_out == dist.padded_rows
    elif row_aligned and n_out != dist.padded_rows:
        raise ValueError(
            f"row_aligned=True but the output has {n_out} rows and the "
            f"input {dist.padded_rows}")
    cols = {} if trim else dict(dist.columns)
    for spec in comp.outputs:
        cols[spec.name] = out[spec.name]
    num_rows = dist.num_rows if row_aligned else n_out
    return DistributedFrame(mesh, out_schema, cols, num_rows)


def dreduce_blocks(fetches, dist: DistributedFrame):
    """Mesh-parallel reduce to one row.

    Two strategies:

    - ``fetches`` is a mapping ``{column: combiner-name}`` (sum/min/max/
      prod): ONE compiled ``shard_map`` program — local shard reduce, pad
      rows masked to the combiner's neutral element, cross-shard combine as
      an ICI collective (``lax.psum``/``pmin``/``pmax``). This is the
      BASELINE north-star path.
    - ``fetches`` is a computation (z/z_input contract): generic combine —
      per-shard async jit dispatches, partials stacked, one final reduce.
    """
    if isinstance(fetches, Mapping) and all(
            isinstance(v, str) for v in fetches.values()):
        return _collective_reduce(fetches, dist)
    return _generic_reduce(fetches, dist)


# Compiled collective-reduce programs, keyed by everything that shapes the
# program (mesh, axis, column names/padded shapes/dtypes, combiners). The
# valid-row count is a traced scalar argument, not baked in, so frames whose
# padded global shapes coincide share one executable. LRU-bounded: distinct
# padded shapes otherwise accumulate executables without limit.
from collections import OrderedDict

_collective_cache: "OrderedDict[tuple, object]" = OrderedDict()
_COLLECTIVE_CACHE_CAP = 64


def _collective_reduce(col_combiners: Mapping[str, str],
                       dist: DistributedFrame) -> Dict[str, np.ndarray]:
    mesh = dist.mesh
    axis = mesh.data_axis
    if dist.num_rows == 0:
        raise ValueError("reduce on an empty distributed frame")
    combs = {}
    for name, cname in col_combiners.items():
        if name not in dist.schema:
            raise KeyError(f"No column {name!r}")
        if cname not in COMBINERS:
            raise KeyError(
                f"Unknown combiner {cname!r}; known: {sorted(COMBINERS)}")
        combs[name] = COMBINERS[cname]

    names = sorted(col_combiners)
    arrays = [dist.columns[n] for n in names]
    key = (mesh.mesh, axis,
           tuple((n, col_combiners[n], a.shape, str(a.dtype))
                 for n, a in zip(names, arrays)))
    fn = _collective_cache.get(key)
    if fn is not None:
        _collective_cache.move_to_end(key)
    else:
        in_specs = (P(),) + tuple(
            P(axis, *([None] * (a.ndim - 1))) for a in arrays)
        out_specs = tuple(P() for _ in arrays)

        def shard_fn(n_valid, *shards):
            outs = []
            rows = shards[0].shape[0]
            idx = jax.lax.axis_index(axis) * rows + jnp.arange(rows)
            valid = idx < n_valid
            for name, s in zip(names, shards):
                c = combs[name]
                mask = valid.reshape((rows,) + (1,) * (s.ndim - 1))
                neutral = jnp.asarray(c.neutral(s.dtype))
                masked = jnp.where(mask, s, neutral)
                local = c.local(masked, 0)
                outs.append(c.collective(local, axis))
            return tuple(outs)

        fn = jax.jit(shard_map(shard_fn, mesh=mesh.mesh,
                               in_specs=in_specs, out_specs=out_specs))
        _collective_cache[key] = fn
        while len(_collective_cache) > _COLLECTIVE_CACHE_CAP:
            _collective_cache.popitem(last=False)
    outs = fn(jnp.asarray(dist.num_rows, jnp.int32), *arrays)
    result = {}
    for name, a in zip(names, outs):
        v = np.asarray(a)
        f = dist.schema[name]
        if v.dtype != f.dtype.np_storage and f.dtype is not _dt.bfloat16:
            v = v.astype(f.dtype.np_storage)
        result[name] = v
    return result


def daggregate(col_combiners: Mapping[str, str], dist: DistributedFrame,
               keys) -> TensorFrame:
    """Mesh-distributed keyed aggregation over the monoid combiners.

    The reference's Catalyst shuffle + UDAF (``DebugRowOps.scala:533-681``)
    re-expressed TPU-first: instead of moving rows between workers by key,
    each shard segment-reduces its LOCAL rows into a dense ``[groups, ...]``
    table (one one-hot-matmul/segment kernel launch) and the tables are
    combined with a single ``psum``-family collective over the data axis —
    the shuffle becomes an ICI all-reduce of a small table. Only the scalar
    KEY columns visit the host (to build dense group ids); the values never
    leave their shards.

    ``keys``: key column name or list of names. Returns a host
    :class:`TensorFrame` of one row per group (keys + fetches, fetches
    sorted by name), like :func:`~tensorframes_tpu.api.aggregate`.
    """
    from ..engine.ops import (InvalidTypeError, _factorize_keys,
                              _validate_monoid_fetches)
    from ..ops.segment_reduce import segment_sum as _segsum

    if isinstance(keys, str):
        keys = [keys]
    keys = list(keys)
    mesh = dist.mesh
    axis = mesh.data_axis
    schema = dist.schema
    for k in keys:
        if k not in schema:
            raise KeyError(f"No key column {k!r}; columns: {schema.names}")
    value_names = [n for n in schema.names if n not in keys]
    _validate_monoid_fetches(col_combiners, value_names,
                             "before distribute()")
    n = dist.num_rows
    if n == 0:
        raise ValueError("aggregate on an empty distributed frame")

    key_host = []
    for k in keys:
        fld = schema[k]
        a = np.asarray(dist.columns[k])[:n]
        if a.ndim != 1:
            raise InvalidTypeError(f"Key column {k!r} must be scalar-typed")
        if a.dtype != fld.dtype.np_storage and fld.dtype is not _dt.bfloat16:
            # distribute() stored this column in its device dtype; if that
            # narrowed the storage type (long->int / double->float with x64
            # off), distinct keys may already have collapsed on device —
            # group identity is unrecoverable, so fail loudly instead of
            # silently merging groups
            if np.dtype(a.dtype).itemsize < np.dtype(fld.dtype.np_storage).itemsize:
                raise InvalidTypeError(
                    f"Key column {k!r} ({fld.dtype.name}) was narrowed to "
                    f"{a.dtype} on device, which can merge distinct keys; "
                    f"cast the key to a device-exact type (e.g. int) before "
                    f"distribute(), or enable x64")
            a = a.astype(fld.dtype.np_storage)
        key_host.append(a)
    fact = _factorize_keys(key_host)
    ids, uniques, num_groups = fact.ids, fact.uniques, fact.num_groups
    ids_padded = np.full(dist.padded_rows, -1, np.int32)  # -1: pad, dropped
    ids_padded[:n] = ids
    ids_dev = jax.device_put(ids_padded, mesh.row_sharding(1))

    fetch_names = sorted(col_combiners)
    arrays = [dist.columns[f] for f in fetch_names]
    in_specs = (P(axis),) + tuple(
        P(axis, *([None] * (a.ndim - 1))) for a in arrays)
    out_specs = tuple(P() for _ in fetch_names)

    def shard_fn(ids_local, *vals_local):
        outs = []
        for f, v in zip(fetch_names, vals_local):
            cname = col_combiners[f]
            if cname == "sum":
                local = _segsum(v, ids_local, num_groups)
            else:
                # mask pad/out-of-range rows to the combiner's neutral and
                # clamp their id to 0 so XLA's segment primitive sees only
                # in-range indices
                c = COMBINERS[cname]
                valid = ids_local >= 0
                vmask = valid.reshape((-1,) + (1,) * (v.ndim - 1))
                neutral = jnp.asarray(c.neutral(v.dtype))
                masked = jnp.where(vmask, v, neutral)
                safe_ids = jnp.where(valid, ids_local, 0)
                seg = {"min": jax.ops.segment_min,
                       "max": jax.ops.segment_max,
                       "prod": jax.ops.segment_prod}[cname]
                local = seg(masked, safe_ids, num_segments=num_groups)
                # a group absent from this shard holds the identity; for
                # min/max that identity is +-inf, which the cross-shard
                # collective absorbs (every group exists somewhere)
            outs.append(COMBINERS[cname].collective(local, axis))
        return tuple(outs)

    fn = jax.jit(shard_map(shard_fn, mesh=mesh.mesh,
                           in_specs=in_specs, out_specs=out_specs))
    tables = fn(ids_dev, *arrays)

    cols: Dict[str, np.ndarray] = {k: u for k, u in zip(keys, uniques)}
    for f, t in zip(fetch_names, tables):
        v = np.asarray(t)
        fld = schema[f]
        if v.dtype != fld.dtype.np_storage and fld.dtype is not _dt.bfloat16:
            v = v.astype(fld.dtype.np_storage)
        cols[f] = v
    from ..schema import Field
    from ..shape import Unknown
    out_fields = [schema[k] for k in keys] + [
        Field(f, schema[f].dtype,
              block_shape=(schema[f].block_shape.with_lead(Unknown)
                           if schema[f].block_shape is not None else None),
              sql_rank=schema[f].sql_rank)
        for f in fetch_names]
    return TensorFrame.from_blocks([Block(cols, num_groups)],
                                   Schema(out_fields))


def _generic_reduce(fetches, dist: DistributedFrame) -> Dict[str, np.ndarray]:
    """Generic (arbitrary-computation) mesh reduce, entirely on device.

    One compiled program: a ``shard_map`` stage runs the user block-reduce
    on every shard's local rows in parallel (SPMD — pad-only shards compute
    a garbage partial that is statically sliced away), the ragged tail
    shard's valid prefix is re-reduced on its own, and the partials are
    combined with one final stacked block-reduce. The only host transfer is
    the final one-cell result — the reference's driver-collect analogue
    (``DebugRowOps.scala:511-512``), with the per-shard data never leaving
    its device.
    """
    schema = dist.schema
    comp = _ops._reduce_computation(fetches, schema, ("_input",),
                                    block_level=True)
    _ops._validate_reduce(comp, schema, ("_input",), rank_delta=1)
    fetch_names = comp.output_names
    mesh = dist.mesh
    axis = mesh.data_axis
    shards = mesh.num_data_shards
    n = dist.num_rows
    if n == 0:
        raise ValueError("reduce on an empty distributed frame")
    rows_per = dist.padded_rows // shards
    full = n // rows_per          # shards whose rows are all valid
    tail = n - full * rows_per    # valid rows in the boundary shard

    names = sorted(fetch_names)
    arrays = [dist.columns[f] for f in names]
    cache = getattr(comp, "_tft_dreduce_cache", None)
    if cache is None:
        cache = comp._tft_dreduce_cache = {}
    key = (mesh.mesh, axis, n,
           tuple((f, a.shape, str(a.dtype)) for f, a in zip(names, arrays)))
    fn = cache.get(key)
    if fn is None:
        in_specs = tuple(P(axis, *([None] * (a.ndim - 1))) for a in arrays)
        # each shard emits its partial with a unit lead axis; stacking over
        # the data axis yields a (shards, *cell) global array
        out_specs = tuple(P(axis) for _ in names)

        def shard_fn(*local):
            out = comp.fn(
                {f + "_input": s for f, s in zip(names, local)})
            return tuple(out[f][None] for f in names)

        def program(*cols):
            stacked = shard_map(shard_fn, mesh=mesh.mesh,
                                in_specs=in_specs,
                                out_specs=out_specs)(*cols)
            parts = {f: st[:full] for f, st in zip(names, stacked)}
            if tail:
                t = comp.fn({
                    f + "_input":
                        jax.lax.slice_in_dim(c, full * rows_per,
                                             full * rows_per + tail, axis=0)
                    for f, c in zip(names, cols)})
                parts = ({f: t[f][None] for f in names} if full == 0 else
                         {f: jnp.concatenate([parts[f], t[f][None]])
                          for f in names})
            return comp.fn({f + "_input": parts[f] for f in names})

        fn = jax.jit(program)
        cache[key] = fn
    final = fn(*arrays)
    out = {}
    for f in fetch_names:
        v = np.asarray(final[f])
        fld = schema.get(f)
        if fld is not None and v.dtype != fld.dtype.np_storage \
                and fld.dtype is not _dt.bfloat16:
            v = v.astype(fld.dtype.np_storage)
        out[f] = v
    return out
