"""Distributed frames: mesh-sharded columns and mesh-level map/reduce.

The TPU-native re-expression of the reference's executor-side distribution
(SURVEY.md §2.3). A :class:`DistributedFrame` holds each column as ONE global
``jax.Array`` row-sharded over the mesh's data axis — partitions become
shards, the broadcast-the-graph step becomes XLA program replication, and:

- :func:`dmap_blocks` — the ``rdd.mapPartitions`` analogue
  (``DebugRowOps.scala:372-386``): one jit dispatch executes every shard in
  parallel with no cross-device traffic;
- :func:`dreduce_blocks` — the block-reduce + Spark-tree-combine analogue
  (``DebugRowOps.scala:490-513``). For the associative monoid combiners
  (sum/min/max/prod) it lowers to one ``shard_map`` program whose
  cross-shard combine is a ``psum``-family ICI collective, with pad rows
  masked to the combiner's neutral element; arbitrary user computations
  take the per-device path — one async jit dispatch per shard device (JAX's
  async dispatch overlaps them), partials stacked and reduced once, which
  preserves the reference's "combine order unspecified" contract exactly.

Multi-host: build the mesh over ``jax.devices()`` after
``jax.distributed.initialize`` and the same code spans hosts — data-axis
collectives ride ICI within a slice and DCN across slices.
"""

from __future__ import annotations

import functools
from collections import OrderedDict
from typing import Dict, Mapping, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P
from ..utils.compat import shard_map

from .. import dtypes as _dt
from .. import memory as _memory
from ..engine import ops as _ops
from ..frame import Block, TensorFrame
from ..resilience import default_policy as _default_policy, faults as _faults
from ..schema import Schema
from .collectives import COMBINERS
from .mesh import DeviceMesh
from . import elastic as _elastic
from ..observability.events import (DEVICE_TRACK_BASE, current_trace,
                                    traced_query)
from ..utils.logging import get_logger
from ..utils.tracing import counters, span

__all__ = ["DistributedFrame", "distribute", "dmap_blocks", "dfilter",
           "dsort", "dreduce_blocks", "daggregate"]


def _lazy_input(dist):
    """The lazy recording view when ``dist`` is one (``frame.lazy()``),
    else None — the d-op entry points continue recorded chains instead
    of forcing them (``plan/dist.py``)."""
    return dist if getattr(dist, "_tft_lazy_dist", False) else None

_cached_reduce_computation = _ops.cached_reduce_computation


def _jitted(comp):
    """One jitted wrapper per live Computation, stored on the object so it
    is collected with it: repeated dmap/dreduce calls on the same
    computation reuse the trace instead of re-wrapping jax.jit."""
    fn = getattr(comp, "_tft_jitted", None)
    if fn is None:
        fn = jax.jit(comp.fn)
        comp._tft_jitted = fn
    return fn


# ---------------------------------------------------------------------------
# mesh-level trace instrumentation (zero-cost-when-off: every helper is
# called only behind a `trace is not None` check — no events, no
# per-shard introspection, and no extra readiness barriers otherwise)
# ---------------------------------------------------------------------------

def _fetch_names(fetches):
    """Best-effort fetch names for self-describing trace metadata: a
    Computation's declared outputs or a mapping's keys; ``None`` for an
    untraced callable (its outputs exist only after tracing)."""
    names = getattr(fetches, "output_names", None)
    if names:
        return sorted(names)
    if isinstance(fetches, Mapping):
        return sorted(str(n) for n in fetches)
    return None


def _mesh_meta(dist) -> Dict:
    m = dist.mesh
    return {"mesh_shape": dict(m.mesh.shape), "shards": m.num_data_shards,
            "devices": m.num_devices, "rows": dist.num_rows,
            "padded_rows": dist.padded_rows}


def _meta_with_fetches(fetches=None, dist=None, *a, **k):
    dist = k.get("dist", dist)
    fetches = k.get("fetches", fetches)
    if dist is None:
        return {}
    meta = _mesh_meta(dist)
    names = _fetch_names(fetches)
    if names is not None:
        meta["fetches"] = names
    return meta


def _meta_dfilter(predicate=None, dist=None, *a, **k):
    dist = k.get("dist", dist)
    return _mesh_meta(dist) if dist is not None else {}


def _meta_dsort(keys=None, dist=None, *a, **k):
    dist = k.get("dist", dist)
    keys = k.get("keys", keys)
    if dist is None:
        return {}
    meta = _mesh_meta(dist)
    meta["keys"] = [keys] if isinstance(keys, str) else list(keys or ())
    return meta


def _meta_daggregate(fetches=None, dist=None, keys=None, *a, **k):
    meta = _meta_with_fetches(fetches, dist, **k)
    keys = k.get("keys", keys)
    if meta and keys is not None:
        meta["keys"] = [keys] if isinstance(keys, str) else list(keys)
    return meta


def _meta_distribute(df=None, mesh=None, *a, **k):
    mesh = k.get("mesh", mesh)
    if mesh is None:
        return {}
    return {"mesh_shape": dict(mesh.mesh.shape),
            "shards": mesh.num_data_shards, "devices": mesh.num_devices}


def _trace_shards(trace, op: str, dist=None, mesh=None,
                  arrays=None) -> float:
    """Record one ``shard`` event per data shard (rows where known, an
    even-split byte estimate) on the device tracks; returns the dispatch
    start timestamp for :func:`_trace_mesh_done`."""
    if dist is not None:
        mesh = dist.mesh
        arrays = list(dist.columns.values())
        try:
            rows = dist.per_shard_valid()
        except Exception:
            rows = None
    else:
        rows = None
    S = mesh.num_data_shards
    nbytes = 0
    for a in arrays or ():
        nb = getattr(a, "nbytes", None)
        if nb:
            nbytes += int(nb)
    per_dev = nbytes // S if S else 0
    for i in range(S):
        trace.add("shard", name=f"{op} shard {i}", device=i,
                  rows=(int(rows[i]) if rows is not None else None),
                  bytes=per_dev, track=DEVICE_TRACK_BASE + i)
    return trace.clock()


def _trace_mesh_done(trace, outs, t0: float, op: str,
                     native: bool = False, mesh=None) -> None:
    """Per-device readiness timings + the op-level mesh dispatch span.

    Readiness is measured by waiting on each device's output shard in
    data-shard order, so a measured duration is the time until that
    device AND every earlier one were ready — the max (the straggler) is
    exact, earlier devices' times are conservative upper bounds. Only
    runs with tracing on; the untraced path keeps jax's async dispatch
    barrier-free. When ``mesh`` is given, the measured durations also
    feed the elastic layer's skew tracker (the signal behind
    skew-adaptive repartitioning, ``parallel/elastic.py``).
    """
    if not native:
        try:
            arr = next((a for a in outs
                        if hasattr(a, "addressable_shards")), None)
            if arr is not None:
                shards = list(arr.addressable_shards)
                by_start = {}
                for sh in shards:
                    idx = sh.index
                    sl = idx[0] if idx else None
                    start = (sl.start or 0) if isinstance(sl, slice) else 0
                    by_start.setdefault(start, sh)
                if len(by_start) > 1:  # row-sharded: data-shard order
                    ordered = [by_start[k] for k in sorted(by_start)]
                else:  # replicated result: one copy per device
                    ordered = sorted(
                        shards, key=lambda sh: getattr(sh.device, "id", 0))
                durs = []
                for i, sh in enumerate(ordered):
                    jax.block_until_ready(sh.data)
                    t = trace.clock()
                    durs.append(max(t - t0, 0.0))
                    trace.add("shard_compute", name=f"{op} d{i}", ts=t0,
                              dur=durs[-1], device=i,
                              track=DEVICE_TRACK_BASE + i)
                if mesh is not None and len(durs) >= 2:
                    _elastic.note_dispatch(mesh, op, durs)
        except Exception as e:
            get_logger("distributed").debug(
                "per-device readiness trace failed for %s: %s", op, e)
    trace.add("mesh_dispatch", name=op, ts=t0,
              dur=max(trace.clock() - t0, 0.0), native=native)


class DistributedFrame:
    """Columns as global row-sharded jax Arrays + the true row count.

    ``num_rows`` is the un-padded row count; rows are padded up to a
    multiple of the data-axis size so every shard is equal (XLA's static
    world), and consumers mask or slice the pad away.

    ``shard_valid`` (multi-host frames, from ``cluster.distribute_local``):
    per-data-shard valid-row counts, for frames whose pad rows are NOT a
    global suffix — each process padded its own block. ``None`` means
    prefix semantics (single-host ``distribute``): the first ``num_rows``
    rows are the real ones.
    """

    def __init__(self, mesh: DeviceMesh, schema: Schema,
                 columns: Dict[str, jax.Array], num_rows: int,
                 shard_valid: Optional[np.ndarray] = None):
        self.mesh = mesh
        self.schema = schema
        self.columns = columns
        self.num_rows = num_rows
        self.shard_valid = shard_valid
        # group-id factorizations memoized per key tuple: frames are
        # immutable (every op returns a new frame), so repeated
        # aggregations over the same keys skip the host transfer +
        # lexsort (host path) / sort-unique program (device path).
        # LRU-capped: entries hold device arrays sized like the frame, so
        # a long-lived frame swept over many key tuples / caps must not
        # retain HBM indefinitely (same policy as _dsort_cache).
        self._group_ids_cache: "OrderedDict[tuple, tuple]" = OrderedDict()

    @property
    def padded_rows(self) -> int:
        # shape metadata must NOT fault a spilled frame back to the
        # device (collect_frame/valid_row_mask route through here; a
        # larger-than-budget collect would re-resident the whole frame)
        cols = self.columns
        if isinstance(cols, _memory.SpillableColumns):
            return cols.leading_rows()
        first = next(iter(cols.values()))
        return first.shape[0]

    def per_shard_valid(self) -> np.ndarray:
        """Valid-row count of every data shard, [num_data_shards]."""
        S = self.mesh.num_data_shards
        if self.shard_valid is not None:
            return np.asarray(self.shard_valid, np.int64)
        rows_per = self.padded_rows // S
        if rows_per * S != self.padded_rows:
            # a global (row_aligned=False) result need not tile the data
            # axis (e.g. ONE summary row on an 8-shard mesh); such frames
            # carry no pad rows, and XLA lays the array out in ceil-div
            # chunks
            if self.num_rows != self.padded_rows:
                raise ValueError(
                    f"frame rows ({self.padded_rows}) do not tile the "
                    f"{S}-shard data axis yet only {self.num_rows} are "
                    f"valid — pad to a multiple of the shard count")
            chunk = -(-self.padded_rows // S)
            starts = np.minimum(np.arange(S) * chunk, self.padded_rows)
            ends = np.minimum(starts + chunk, self.padded_rows)
            return (ends - starts).astype(np.int64)
        out = np.full(S, rows_per, np.int64)
        full, tail = divmod(self.num_rows, rows_per)
        out[full:] = 0
        if full < S:
            out[full] = tail
        return out

    def valid_row_mask(self) -> np.ndarray:
        """Host bool mask [padded_rows]: True where the row is real."""
        S = self.mesh.num_data_shards
        rows_per = self.padded_rows // S
        if rows_per * S != self.padded_rows:
            self.per_shard_valid()  # validates num_rows == padded_rows
            return np.ones(self.padded_rows, bool)
        idx = np.arange(self.padded_rows) % rows_per
        return idx < np.repeat(self.per_shard_valid(), rows_per)

    def host_read_padded(self, name: str) -> np.ndarray:
        """The full padded global column on THIS host.

        Fully-addressable arrays read directly; multi-host arrays gather
        the process-local blocks (process-contiguous row layout, the
        ``cluster.distribute_local`` invariant) with one allgather.
        Spilled columns (``docs/memory.md``) are served from their
        pinned host buffers WITHOUT faulting back to the device — a
        larger-than-budget frame can be collected without ever being
        device-resident again.
        """
        if isinstance(self.columns, _memory.SpillableColumns) \
                and self.columns.mem_is_spilled():
            return self.columns.host_value(name)
        return _read_global(self.columns[name])

    def collect_frame(self, num_partitions: Optional[int] = None) -> TensorFrame:
        """Bring the data back to the host as a TensorFrame (pad dropped).

        Multi-host frames gather every process's rows — each host gets the
        FULL frame (the driver-collect contract of the reference,
        ``ExperimentalOperations.scala:91``)."""
        mask = self.valid_row_mask()
        host_cols = {}
        for f in self.schema:
            a = self.host_read_padded(f.name)
            a = a[mask] if self.shard_valid is not None else a[: self.num_rows]
            if a.dtype != f.dtype.np_storage and f.dtype is not _dt.bfloat16:
                a = a.astype(f.dtype.np_storage)
            host_cols[f.name] = a
        return TensorFrame.from_columns(
            host_cols, schema=self.schema,
            num_partitions=num_partitions or self.mesh.num_data_shards)

    def select(self, names) -> "DistributedFrame":
        """A view with only ``names`` (no data movement — the reduce ops
        require every column to back a fetch, so dropping ride-along
        columns first is the normal prelude)."""
        if isinstance(names, str):
            names = [names]
        names = list(names)
        missing = [n for n in names if n not in self.schema]
        if missing:
            raise KeyError(
                f"No column(s) {missing}; columns: {self.schema.names}")
        return DistributedFrame(self.mesh, self.schema.select(names),
                                {n: self.columns[n] for n in names},
                                self.num_rows, shard_valid=self.shard_valid)

    def count(self) -> int:
        """True (un-padded) global row count."""
        return self.num_rows

    def lazy(self):
        """A RECORDING view of this frame: subsequent ``dmap_blocks`` /
        ``dfilter`` / ``select`` calls record distributed plan nodes
        instead of dispatching, and the chain forces as ONE fused GSPMD
        program per mesh stage with shard intermediates staying
        device-resident (terminal monoid ``dreduce_blocks`` /
        ``daggregate`` fold into the same program). Returns ``self``
        when fusion cannot apply — ``TFT_FUSE=0``, the native ``pjrt``
        executor, multi-process meshes, frames whose rows do not tile
        the data axis — so chains then run eagerly per-op,
        bit-identical by construction. See ``docs/plan.md``.
        """
        from ..plan import dist as _dplan
        return _dplan.lazy_frame(self)

    def explain(self) -> str:
        """Schema + placement report (the mesh-side ``explain`` /
        ``print_schema`` analogue): per-column dtype, declared shape,
        device sharding, plus mesh/pad layout."""
        lines = [f"DistributedFrame: {self.num_rows} rows "
                 f"(padded {self.padded_rows}) on {self.mesh!r}",
                 f"  validity: "
                 + ("prefix" if self.shard_valid is None
                    else f"per-shard {list(map(int, self.shard_valid))}")]
        rb = getattr(self, "_rebalance", None)
        if rb:
            lines.append(
                f"  rebalance: skew {rb['ratio']:.2f} during {rb['op']}; "
                f"per-shard rows {rb['before']} -> {rb['after']} "
                f"(proportional to observed device throughput)")
        ex = getattr(self, "_exchange", None)
        if ex:
            flag = (" OVER TFT_SKEW_WARN"
                    if ex["ratio"] > ex["threshold"] else "")
            lines.append(
                f"  exchange: partition imbalance {ex['ratio']:.2f} "
                f"(threshold {ex['threshold']:.2f}{flag}); per-shard "
                f"rows {ex['per_shard']}")
        for f in self.schema:
            col = self.columns[f.name]
            if isinstance(col, np.ndarray):
                place = "host (ride-along)"
            else:
                try:
                    place = str(col.sharding.spec)
                except Exception:
                    place = type(col).__name__
            lines.append(f"  {f.describe()} sharding={place}")
        info = getattr(self, "_dplan_info", None)
        if info:
            # the distributed plan section (docs/plan.md): fused stage
            # layout, resident shard edges, fallback reasons — set by
            # plan.dist when this frame came out of a lazy chain
            lines.extend(info)
        return "\n".join(lines)

    def __repr__(self):
        return (f"DistributedFrame[{', '.join(self.schema.names)}] "
                f"rows={self.num_rows} mesh={self.mesh!r}")


def _host_side_column(a: np.ndarray, field, padded_rows: int) -> np.ndarray:
    """Pad a non-tensor (string) column for the host-side ride-along.

    Such columns cannot live in device memory; they travel in the same
    padded global layout as the device columns — pass-through / group-key
    only, exactly the host engine's contract for them (dtypes.py:
    tensor=False). Stored as the schema's np_storage (object), so
    downstream dtype guards never mistake a '<U1' numpy view for device
    narrowing. Host-side columns are process-local, so THIS helper
    rejects them in multi-process runs (both distribute entry points
    route through here).
    """
    if jax.process_count() > 1:
        raise ValueError(
            f"column {field.name!r}: non-tensor (string) columns are not "
            f"supported across processes — drop them with select() or key "
            f"on an integer column")
    a = np.asarray(a, field.dtype.np_storage)
    if a.shape[0] != padded_rows:
        a = np.concatenate(
            [a, np.full(padded_rows - a.shape[0], None, a.dtype)])
    return a


def _read_global(a) -> np.ndarray:
    """A (possibly multi-host) row-sharded global array as host numpy.

    Fully-addressable arrays read directly; otherwise each process
    concatenates its distinct row blocks and one allgather assembles the
    global array (row-contiguous process layout, the
    ``cluster.distribute_local`` invariant).
    """
    if getattr(a, "is_fully_addressable", True):
        return np.asarray(a)
    from jax.experimental import multihost_utils

    def start(s):
        sl = s.index[0]
        return 0 if sl.start is None else sl.start

    # replication over non-data mesh axes repeats each row block across
    # devices; keep one shard per distinct row range
    by_start = {}
    for s in a.addressable_shards:
        by_start.setdefault(start(s), s)
    shards = [by_start[k] for k in sorted(by_start)]
    local = np.concatenate([np.asarray(s.data) for s in shards])
    gathered = np.asarray(multihost_utils.process_allgather(local))
    return gathered.reshape((-1,) + tuple(a.shape[1:]))


@traced_query("distribute", _meta_distribute)
def distribute(df: TensorFrame, mesh: DeviceMesh) -> DistributedFrame:
    """Shard a host frame over the mesh's data axis.

    The analogue of Spark scattering partitions to executors — except the
    placement is an explicit ``device_put`` with a ``NamedSharding``, and
    the "partitions" are equal shards of one global array (pad rows, zero
    filled, make up the remainder; ``num_rows`` remembers the truth).
    """
    with span("distribute.concat"):
        merged = Block.concat(df.blocks(), df.schema)
    n = merged.num_rows
    shards = mesh.num_data_shards
    padded = ((n + shards - 1) // shards) * shards if n else shards
    mem_mgr = _memory.active()
    cols: Dict[str, jax.Array] = {}
    for f in df.schema:
        a = merged.dense(f.name)
        if not f.dtype.tensor:
            cols[f.name] = _host_side_column(a, f, padded)
            continue
        dd = _dt.device_dtype(f.dtype)
        if padded != n:
            # one allocation pads AND casts (assignment casting); empty +
            # explicit tail zero writes each byte once, where zeros-then-
            # assign wrote the data region twice
            with span("distribute.convert_pad"):
                out = np.empty((padded,) + a.shape[1:], dd)
                out[:n] = a
                out[n:] = 0
            a = out
        elif a.dtype != dd:
            # cast-only: the native kernel threads large buffers
            with span("distribute.convert_pad"):
                from .. import native as _native
                a = _native.convert(a, dd)
        if mem_mgr is not None:
            # spill colder frames before placing this column (a single
            # column larger than the whole budget still proceeds —
            # docs/memory.md degradation matrix)
            mem_mgr.make_room(int(a.nbytes))
        with span("distribute.device_put"):
            cols[f.name] = jax.device_put(a, mesh.row_sharding(a.ndim))
    if mem_mgr is not None and mem_mgr.spill_enabled:
        # the frame's device columns become one LRU spill candidate:
        # cold mesh frames move to pinned host buffers under pressure
        # and fault back transparently on the next column access
        cols = _memory.spillable_columns(
            f"distribute:{df._plan.split('(', 1)[0]}@{id(df):x}", cols,
            mem_mgr)
    result = DistributedFrame(mesh, df.schema, cols, n)
    trace = current_trace()
    if trace is not None:
        t0 = _trace_shards(trace, "distribute", dist=result)
        _trace_mesh_done(trace, [c for c in cols.values()
                                 if not isinstance(c, np.ndarray)],
                         t0, "distribute")
    return result


def dmap_blocks(fetches, dist: DistributedFrame, trim: bool = False,
                row_aligned: Optional[bool] = None) -> DistributedFrame:
    """Mesh-parallel map: one jit dispatch, all shards in parallel.

    Without ``trim``, outputs ride alongside the inputs and must be
    row-local (each output row depends on its input row and replicated
    constants); pad rows flow through and are dropped at collect. With
    ``trim=True`` the computation sees the GLOBAL padded array and may
    change the row count (e.g. an in-graph pre-aggregation emitting one
    global row — the ``kmeans_demo.py:128-140`` pattern at mesh scale);
    XLA/GSPMD inserts whatever cross-shard collectives the program needs.
    Such computations must mask pad rows themselves (``dist.num_rows`` is
    the true count; ``padded_rows`` what they will see).

    ``row_aligned`` declares how a trim output relates to the input rows:
    ``True`` — output rows correspond 1:1 to input rows (pad structure
    survives, dropped at collect); ``False`` — the output is a fresh global
    result (every emitted row is real). Default ``None`` infers from the
    row count (equal to ``padded_rows`` -> aligned); pass the flag
    explicitly when the sizes could coincide.

    Like every mesh op, the dispatch runs through the elastic boundary
    (``parallel/elastic.py``): a classified device loss shrinks the mesh,
    re-shards, and re-runs; persistent skew re-partitions first.

    On a LAZY frame (:meth:`DistributedFrame.lazy`) a proven
    row-preserving non-trim map RECORDS a plan node and defers — the
    chain forces as one fused GSPMD program (``docs/plan.md``); trim /
    unprovable computations materialize the chain and dispatch eagerly.
    """
    lz = _lazy_input(dist)
    if lz is not None:
        from ..plan import dist as _dplan
        out = _dplan.record_map(fetches, lz, trim, row_aligned)
        if out is not None:
            return out
        dist = _dplan.materialize(lz)
    return _dmap_blocks_eager(fetches, dist, trim, row_aligned)


@traced_query("dmap_blocks", _meta_with_fetches)
def _dmap_blocks_eager(fetches, dist: DistributedFrame, trim: bool = False,
                       row_aligned: Optional[bool] = None
                       ) -> DistributedFrame:
    return _elastic.elastic_call(
        "dmap_blocks", dist,
        lambda d: _dmap_blocks(fetches, d, trim, row_aligned))


def _dmap_blocks(fetches, dist: DistributedFrame, trim: bool,
                 row_aligned: Optional[bool]) -> DistributedFrame:
    schema = dist.schema
    if row_aligned is False and not trim:
        raise ValueError(
            "row_aligned=False only makes sense for trim=True outputs: "
            "without trim the untrimmed input columns ride along and still "
            "contain pad rows, which declaring every output row real would "
            "surface as data")
    comp = _ops._map_computation(fetches, schema, block_level=True)
    out_schema = _ops._validate_map(comp, schema, block_level=True, trim=trim)
    mesh = dist.mesh

    # TFT_EXECUTOR=pjrt: row-aligned maps run as ONE GSPMD-partitioned
    # executable inside the native C++ core (trim/global programs and
    # unsupported dtypes fall back to the jax dispatch below)
    nm = _native_mesh(mesh) if not trim else None
    if nm is not None:
        try:
            outs_np = nm.dmap(comp, dist)
        except Exception as e:
            _native_mesh_fallback(e)
            outs_np = None
        if outs_np is not None:
            counters.inc("mesh.dispatches")
            # per-key copy through __getitem__: dict()'s raw fast-path
            # copy would bypass SpillableColumns' fault-back and hand a
            # concurrently-spilled frame's None placeholders downstream
            cols = {n: dist.columns[n] for n in dist.columns}
            for spec in comp.outputs:
                a = outs_np[spec.name]
                cols[spec.name] = jax.device_put(
                    a, mesh.row_sharding(a.ndim))
            return DistributedFrame(mesh, out_schema, cols, dist.num_rows,
                                    shard_valid=dist.shard_valid)

    jitted = _jitted(comp)
    policy = _default_policy()

    def _dispatch():
        _faults.check("dmap")
        with span("dmap_blocks.dispatch"):
            out = jitted({n: dist.columns[n] for n in comp.input_names})
            if policy.max_attempts > 1:
                # jax dispatch is async: without this barrier an
                # execution failure would surface at a later consumption
                # of `out`, outside the retry. TFT_RETRY_MAX_ATTEMPTS=1
                # restores fire-and-forget pipelining for hot loops.
                jax.block_until_ready(out)
            return out

    # one jit dispatch covers every shard: a transient PJRT failure here
    # would otherwise kill the whole mesh map
    trace = current_trace()
    t0 = (_trace_shards(trace, "dmap_blocks", dist=dist)
          if trace is not None else 0.0)
    out = policy.call(_dispatch, op="dmap_blocks.dispatch")
    counters.inc("mesh.dispatches")
    if trace is not None:
        _trace_mesh_done(trace, [out[s.name] for s in comp.outputs], t0,
                         "dmap_blocks", mesh=mesh)
    leads = {out[s.name].shape[0] for s in comp.outputs}
    if len(leads) > 1:
        raise ValueError(
            f"Distributed map fetches disagree on output row count: "
            f"{ {s.name: out[s.name].shape[0] for s in comp.outputs} }")
    n_out = leads.pop() if leads else dist.padded_rows
    if n_out != dist.padded_rows and not trim:
        raise ValueError(
            f"Distributed map output changed the row count ({n_out} vs "
            f"{dist.padded_rows}); use trim=True for row-count-changing "
            f"(global) computations")
    if row_aligned is None:
        row_aligned = n_out == dist.padded_rows
    elif row_aligned and n_out != dist.padded_rows:
        raise ValueError(
            f"row_aligned=True but the output has {n_out} rows and the "
            f"input {dist.padded_rows}")
    # per-key copy through __getitem__ (see the native-mesh branch):
    # dict() would bypass a spilled SpillableColumns' fault-back
    cols = {} if trim else {n: dist.columns[n] for n in dist.columns}
    for spec in comp.outputs:
        cols[spec.name] = out[spec.name]
    num_rows = dist.num_rows if row_aligned else n_out
    # row-aligned outputs keep the input's pad layout; a fresh global
    # result (row_aligned=False) has no pad rows at all
    return DistributedFrame(mesh, out_schema, cols, num_rows,
                            shard_valid=(dist.shard_valid if row_aligned
                                         else None))


def dfilter(predicate, dist: DistributedFrame) -> DistributedFrame:
    """Mesh filter: keep the rows where ``predicate`` holds (nonzero).

    The TPU-first shape of a row filter: global array shapes cannot
    change per data (XLA's static world), so one ``shard_map`` program
    computes the mask per shard, stably compacts each shard's kept rows
    to the front (argsort on the negated mask + gather), and reports the
    per-shard survivor counts — the padded global layout is untouched and
    the result's validity becomes per-shard (``shard_valid`` semantics,
    exactly the multi-host frame layout every consumer already handles).
    Host-side ride-along columns (strings) replay the same per-shard
    permutation on the host from the returned mask.

    ``predicate`` follows :func:`tensorframes_tpu.filter_rows`'s
    contract: named args select columns, one rank-1 boolean/integer
    fetch.

    On a LAZY frame the filter RECORDS: its compaction fragment runs
    INSIDE the chain's fused program and the survivor counts stay
    traced between ops (no host readback until the chain forces).
    """
    lz = _lazy_input(dist)
    if lz is not None:
        from ..plan import dist as _dplan
        out = _dplan.record_filter(predicate, lz)
        if out is not None:
            return out
        dist = _dplan.materialize(lz)
    return _dfilter_eager(predicate, dist)


@traced_query("dfilter", _meta_dfilter)
def _dfilter_eager(predicate, dist: DistributedFrame) -> DistributedFrame:
    return _elastic.elastic_call("dfilter", dist,
                                 lambda d: _dfilter(predicate, d))


def _dfilter(predicate, dist: DistributedFrame) -> DistributedFrame:
    schema = dist.schema
    comp = _ops._filter_computation(predicate, schema)
    bad = [n for n in comp.input_names
           if (f := schema.get(n)) is not None and not f.dtype.tensor]
    if bad:
        raise _ops.InvalidTypeError(
            f"dfilter predicate reads host-side (non-tensor) column(s) "
            f"{bad}: string columns ride along on the mesh but cannot "
            f"enter the sharded program. Filter on the host instead "
            f"(tensorframes_tpu.filter_rows / TensorFrame.filter) before "
            f"distribute().")
    pname = comp.output_names[0]
    mesh = dist.mesh
    axis = mesh.data_axis
    S = mesh.num_data_shards
    rows_per = dist.padded_rows // S
    in_names = comp.input_names
    tensor_names = [f.name for f in schema if f.dtype.tensor]
    host_names = [f.name for f in schema if not f.dtype.tensor]

    counts_host = dist.per_shard_valid().astype(np.int32)
    cnt_dev = jax.make_array_from_callback(
        (S,), mesh.row_sharding(1), lambda idx: counts_host[idx])
    arrays = [dist.columns[n] for n in tensor_names]

    cache = getattr(comp, "_tft_dfilter_cache", None)
    if cache is None:
        cache = comp._tft_dfilter_cache = {}
    key = (mesh.mesh, axis, rows_per,
           tuple((n, a.shape, str(a.dtype))
                 for n, a in zip(tensor_names, arrays)))

    in_specs = (P(axis),) + tuple(
        P(axis, *([None] * (a.ndim - 1))) for a in arrays)
    out_specs = tuple(
        P(axis, *([None] * (a.ndim - 1))) for a in arrays
    ) + (P(axis), P(axis))

    def build_prog():
        def shard_fn(cnt, *cols):
            local = dict(zip(tensor_names, cols))
            m = comp.fn({n: local[n] for n in in_names})[pname]
            rowid = jnp.arange(rows_per)
            keep = (m != 0) & (rowid < cnt[0])
            order = jnp.argsort((~keep).astype(jnp.int8), stable=True)
            permuted = tuple(jnp.take(c, order, axis=0) for c in cols)
            return permuted + (jnp.sum(keep, dtype=jnp.int32)[None], keep)

        return shard_map(shard_fn, mesh=mesh.mesh, in_specs=in_specs,
                         out_specs=out_specs)

    # TFT_EXECUTOR=pjrt: per-shard mask + compaction as one GSPMD
    # executable in the native core
    outs = None
    nm = _native_mesh(mesh)
    if nm is not None:
        in_shardings = (mesh.row_sharding(1),) + tuple(
            mesh.row_sharding(a.ndim) for a in arrays)
        out_shardings = tuple(
            mesh.row_sharding(a.ndim) for a in arrays
        ) + (mesh.row_sharding(1), mesh.row_sharding(1))
        try:
            outs_np = nm.run_sharded(
                ("dfilter",) + key, build_prog,
                [cnt_dev] + list(arrays), in_shardings,
                list(out_shardings), mesh, owner=comp)
        except Exception as e:
            _native_mesh_fallback(e)
            outs_np = None
        if outs_np is not None:
            outs = [jax.device_put(a, s)
                    for a, s in zip(outs_np, out_shardings)]
    if outs is None:
        fn = cache.get(key)
        if fn is None:
            fn = jax.jit(build_prog())
            cache[key] = fn
        trace = current_trace()
        t0 = (_trace_shards(trace, "dfilter", dist=dist)
              if trace is not None else 0.0)
        with span("dfilter.dispatch"):
            outs = fn(cnt_dev, *arrays)
        if trace is not None:
            _trace_mesh_done(trace, list(outs), t0, "dfilter", mesh=mesh)
    counters.inc("mesh.dispatches")
    new_cols: Dict[str, jax.Array] = dict(zip(tensor_names, outs))
    counts = _read_global(outs[len(tensor_names)]).astype(np.int64)
    # the survivor counts (and, with host ride-alongs, the keep mask)
    # cross to the host between this op and the next — the inter-stage
    # transfer the fused plan keeps traced (docs/plan.md)
    counters.inc("mesh.interstage_host_bytes", 4 * S)
    # feedback selectivity (ROADMAP 2a): observed rows-in/rows-out
    # sharpen estimates for later plans over the same predicate
    from ..plan.nodes import record_selectivity
    record_selectivity(comp, dist.num_rows, int(counts.sum()))
    if host_names:
        counters.inc("mesh.interstage_host_bytes", dist.padded_rows)
        keep_host = _read_global(outs[len(tensor_names) + 1])
        for n in host_names:
            a = dist.columns[n]
            out_a = np.empty_like(a)
            for s in range(S):
                sl = slice(s * rows_per, (s + 1) * rows_per)
                order = np.argsort(~keep_host[sl], kind="stable")
                out_a[sl] = a[sl][order]
            new_cols[n] = out_a
    return DistributedFrame(mesh, schema, new_cols, int(counts.sum()),
                            shard_valid=counts)


_dsort_cache: "OrderedDict[tuple, object]" = OrderedDict()
_DSORT_CACHE_CAP = 32


def dsort(keys, dist: DistributedFrame, descending: bool = False
          ) -> DistributedFrame:
    """Rows globally sorted by scalar key column(s), on the mesh.

    Multi-shard frames sort by **columnsort** (Leighton's 8-step
    sorting-network generalization): four LOCAL per-shard sorts
    interleaved with three static exchanges (two ``all_to_all`` reshuffles
    and a half-block ``ppermute`` shift). Every step has static shapes
    and per-device O(m log m) work — no shard ever sorts (or even holds)
    the global array, unlike a GSPMD-partitioned global ``argsort``,
    which gathers the key column to every device and replicates the full
    n·log n sort. Stability and pad handling ride in the sort key itself:
    a validity flag is the most significant key (frame pads and the
    internal columnsort padding sink to the global tail), the original
    row id is the least significant (stable ties), and each user key is
    order-transformed for ``descending`` (float negation; bitwise-not
    for ints, which never overflows). Correctness needs
    rows-per-shard ≥ 2(S-1)² and divisibility by 2S, achieved by padding
    inside the program, with the final global slice restoring the frame's
    layout. Single-shard meshes and frames whose rows do not tile the
    data axis use a plain local-sort program instead.

    The result has prefix validity: pad rows are all at the tail,
    whatever the input layout (so ``dsort`` also normalizes a
    ``dfilter``/multi-host mask layout back to prefix semantics).

    Keys must be device (numeric) columns; sort by a string key on the
    host via ``TensorFrame.order_by`` instead. Host-side string
    ride-along columns are permuted on the host from the same order.

    A LAZY frame materializes first (its pending chain forces fused;
    the sort consumes the still-device-resident result — the resident
    shard edge between mesh stages).
    """
    lz = _lazy_input(dist)
    if lz is not None:
        from ..plan import dist as _dplan
        dist = _dplan.materialize(lz)
    if isinstance(keys, str):
        keys = [keys]
    keys = list(keys)
    return _dsort_eager(keys, dist, descending)


@traced_query("dsort", _meta_dsort)
def _dsort_eager(keys, dist: DistributedFrame, descending: bool = False
                 ) -> DistributedFrame:
    ext = _dsort_external_if_needed(keys, dist, descending)
    if ext is not None:
        return ext
    return _elastic.elastic_call("dsort", dist,
                                 lambda d: _dsort(keys, d, descending))


def _validate_dsort_keys(schema: Schema, keys) -> None:
    for k in keys:
        f = schema.get(k)
        if f is None:
            raise KeyError(f"No key column {k!r}; columns: {schema.names}")
        if not f.dtype.tensor:
            raise _ops.InvalidTypeError(
                f"dsort key {k!r} is a host-side (string) column; sort on "
                f"the host with order_by, or key on a numeric column")
        if f.block_shape is not None and len(f.block_shape.dims) != 1:
            raise _ops.InvalidShapeError(
                f"dsort key {k!r} must be a scalar column")


def _dsort_external_if_needed(keys, dist: DistributedFrame,
                              descending: bool
                              ) -> Optional[DistributedFrame]:
    """Route a larger-than-budget frame to the external-memory sort.

    Engages only under an active device budget
    (``TFT_MEM_LIMIT_BYTES`` / the derived HBM budget) when the frame's
    tensor columns exceed ``TFT_MEM_SORT_FRACTION`` of it — the
    in-device columnsort would hold input + exchange buffers resident
    at once. Sizes are read WITHOUT faulting spilled columns back.
    """
    mgr = _memory.active()
    if mgr is None or not mgr.spill_enabled:
        return None
    threshold = mgr.external_sort_threshold()
    if threshold is None:
        return None
    tensor_names = [f.name for f in dist.schema if f.dtype.tensor]
    total = sum(_memory.value_nbytes(dist.columns, n)
                for n in tensor_names)
    if total <= threshold:
        return None
    _validate_dsort_keys(dist.schema, keys)
    return _dsort_external(keys, dist, descending, mgr)


def _dsort_external(keys, dist: DistributedFrame, descending: bool,
                    mgr) -> DistributedFrame:
    """Out-of-core dsort: budget-sized device runs + host k-way merge
    (``memory.external_sort``), result bit-identical to the in-memory
    path — stable by original row order, pads at the global tail
    (prefix validity), host ride-along columns permuted alongside.
    """
    mesh = dist.mesh
    schema = dist.schema
    tensor_names = [f.name for f in schema if f.dtype.tensor]
    host_names = [f.name for f in schema if not f.dtype.tensor]
    with span("dsort.external"):
        mask = dist.valid_row_mask()
        valid_idx = np.flatnonzero(mask)
        host_cols = {}
        for n in tensor_names:
            a = _memory.host_value(dist.columns, n)
            host_cols[n] = a[mask]
        sorted_cols, order, stats = _memory.external_sort(
            host_cols, keys, descending=descending, manager=mgr)
        trace = current_trace()
        if trace is not None:
            trace.add("external_sort", rows=stats["rows"],
                      runs=stats["runs"], bytes=stats["bytes"])
        padded = dist.padded_rows
        n_valid = len(valid_idx)
        new_cols: Dict[str, jax.Array] = {}
        for n in tensor_names:
            s = sorted_cols[n]
            if padded != n_valid:
                out = np.zeros((padded,) + s.shape[1:], s.dtype)
                out[:n_valid] = s
                s = out
            mgr.make_room(int(s.nbytes))
            with span("dsort.external_put"):
                new_cols[n] = jax.device_put(s, mesh.row_sharding(s.ndim))
        for n in host_names:
            col = np.asarray(dist.columns[n], object)
            g = col[valid_idx[order]]
            if padded != n_valid:
                g = np.concatenate(
                    [g, np.full(padded - n_valid, None, object)])
            new_cols[n] = g
        cols = _memory.spillable_columns(
            f"dsort.external@{id(dist):x}", new_cols, mgr)
        get_logger("dsort").info(
            "dsort took the external-memory path: %d rows (%d B) in %d "
            "run(s), k-way merged on the host", stats["rows"],
            stats["bytes"], stats["runs"])
        return DistributedFrame(mesh, schema, cols, dist.num_rows)


def _dsort(keys, dist: DistributedFrame, descending: bool
           ) -> DistributedFrame:
    schema = dist.schema
    _validate_dsort_keys(schema, keys)
    mesh = dist.mesh
    S = mesh.num_data_shards
    tensor_names = [f.name for f in schema if f.dtype.tensor]
    host_names = [f.name for f in schema if not f.dtype.tensor]
    arrays = [dist.columns[n] for n in tensor_names]

    valid_host = dist.valid_row_mask()
    if dist.padded_rows % S == 0:
        valid_dev = jax.make_array_from_callback(
            (dist.padded_rows,), mesh.row_sharding(1),
            lambda idx: valid_host[idx])
    else:
        # non-tiling (trim/global-result) frames cannot carry an evenly
        # row-sharded mask; the local program runs replicated for them
        valid_dev = jax.device_put(valid_host, mesh.replicated())

    want_order = bool(host_names)
    if S > 1 and dist.padded_rows % S == 0:
        outs = _dsort_columnsort(dist, keys, descending, tensor_names,
                                 arrays, valid_dev, want_order)
    else:
        if S > 1:
            _warn_dsort_gather(dist, S)
        outs = _dsort_local(dist, keys, descending, tensor_names, arrays,
                            valid_dev, want_order)
    new_cols: Dict[str, jax.Array] = dict(zip(tensor_names, outs))
    if want_order:
        order_host = _read_global(outs[len(tensor_names)])
        for n in host_names:
            new_cols[n] = dist.columns[n][order_host]
    return DistributedFrame(mesh, schema, new_cols, dist.num_rows)


_dsort_gather_warned = False


def _warn_dsort_gather(dist, S: int):
    """Warn ONCE when a multi-shard frame takes the local-argsort program.

    The local program's GSPMD lowering gathers the key column to every
    device — the exact pathology columnsort exists to kill — so its
    silent return on an S>1 mesh (rows not tiling the data axis, e.g. a
    trim/global map result) must be visible. One warning per process,
    like the native-mesh fallback."""
    global _dsort_gather_warned
    if _dsort_gather_warned:
        return
    get_logger("dsort").warning(
        "dsort on a %d-shard mesh fell back to the global-argsort program "
        "because the frame's %d rows do not tile the data axis — GSPMD "
        "will gather the key column to every device. Pad or repartition "
        "the frame to a multiple of the shard count to get columnsort "
        "(warned once)", S, dist.padded_rows)
    _dsort_gather_warned = True


def _key_transform(kv, descending: bool):
    """Order-reversing transforms with no overflow for descending: float
    negation, and bitwise-not for ints (~k = -k-1 is strictly decreasing
    for signed AND unsigned — raw negation wraps uint 0 onto itself and
    overflows iinfo.min)."""
    if not descending:
        return kv
    return -kv if jnp.issubdtype(kv.dtype, jnp.floating) else ~kv


def _dsort_local(dist, keys, descending, tensor_names, arrays, valid_dev,
                 want_order):
    """Fallback sort program (single-shard meshes / non-tiling frames):
    one jit, global stable argsort chain; on a multi-shard mesh GSPMD
    would gather the key column, which is why multi-shard frames take
    :func:`_dsort_columnsort` instead."""
    mesh = dist.mesh
    ckey = ("local", mesh.mesh, tuple(keys), descending, want_order,
            tuple((n, a.shape, str(a.dtype))
                  for n, a in zip(tensor_names, arrays)))
    fn = _dsort_cache.get(ckey)
    if fn is None:
        def program(valid, *cols):
            named = dict(zip(tensor_names, cols))
            n = valid.shape[0]
            # ONE fused lexicographic lax.sort: (invalid, keys...,
            # original position). The validity flag is the primary key so
            # pad/invalid rows sink stably to the tail with no value
            # sentinel — real rows keyed NaN / +inf / iinfo.max cannot be
            # displaced into the pad region (NaNs end up last WITHIN the
            # valid prefix, XLA's float total order), pads strictly
            # after. The position key makes the tuple a total order, so
            # ties keep original order (stable).
            pos = jnp.arange(n)
            ops = ((~valid).astype(jnp.int8),) + tuple(
                _key_transform(named[k], descending) for k in keys
            ) + (pos,)
            sorted_ops = jax.lax.sort(ops, num_keys=len(ops))
            order = sorted_ops[-1]
            outs = tuple(jnp.take(c, order, axis=0) for c in cols)
            return outs + ((order,) if want_order else ())

        if dist.padded_rows % mesh.num_data_shards == 0:
            shard_of = mesh.row_sharding
        else:
            # uneven row counts cannot be expressed as a row sharding
            # (jit rejects non-divisible out_shardings); these frames are
            # small global results, so replication is the honest layout
            def shard_of(_ndim):
                return mesh.replicated()
        shardings = tuple(shard_of(a.ndim) for a in arrays)
        if want_order:
            shardings = shardings + (shard_of(1),)
        fn = jax.jit(program, out_shardings=shardings)
        _dsort_cache[ckey] = fn
        while len(_dsort_cache) > _DSORT_CACHE_CAP:
            _dsort_cache.popitem(last=False)
    else:
        _dsort_cache.move_to_end(ckey)

    trace = current_trace()
    t0 = (_trace_shards(trace, "dsort", dist=dist)
          if trace is not None else 0.0)
    with span("dsort.dispatch"):
        outs = fn(valid_dev, *arrays)
    counters.inc("mesh.dispatches")
    if trace is not None:
        _trace_mesh_done(trace, list(outs), t0, "dsort", mesh=mesh)
    return outs


def _dsort_columnsort(dist, keys, descending, tensor_names, arrays,
                      valid_dev, want_order):
    """Columnsort over the data axis (see :func:`dsort` docstring).

    Shards are the matrix "columns" (r rows each); the 8 steps:
    1. sort columns; 2. deal rows round-robin across shards
    (``all_to_all``); 3. sort; 4. inverse deal (contiguous chunks out,
    interleave in); 5. sort; 6. shift half-blocks to the next shard
    (``ppermute``); 7. sort the shifted column (the conceptual extra
    column s is ``[last shard's bottom, +inf]``, already sorted — free);
    8. unshift. Requires r ≥ 2(S-1)² and 2S | r, met by padding each
    shard with flag-2 sentinel rows inside the program; the final global
    slice drops them (they sort strictly after the frame's own pad
    rows). A flag column (−5 min-sentinel < 0 real < 1 frame-pad <
    2 internal-pad < 9 max-sentinel) is the most significant sort key
    and the original global row id the least, so the whole pipeline is
    stable and pad-safe; row ids double as the host-column permutation.
    """
    mesh = dist.mesh
    axis = mesh.data_axis
    S = mesh.num_data_shards
    padded = dist.padded_rows
    r = padded // S
    # internal per-shard row count: multiple of 2S, >= 2(S-1)^2 (Leighton's
    # validity condition), >= r
    need = max(r, 2 * (S - 1) * (S - 1))
    rp = ((need + 2 * S - 1) // (2 * S)) * (2 * S)
    h = rp // 2
    idx_dt = jnp.int32 if padded < 2 ** 31 else jnp.int64

    ckey = ("columnsort", mesh.mesh, tuple(keys), descending, want_order,
            rp, tuple((n, a.shape, str(a.dtype))
                      for n, a in zip(tensor_names, arrays)))

    def build_full():
        key_idx = [tensor_names.index(k) for k in keys]

        def colsort(flag, rowid, cols):
            """One column (shard-local) sort by (flag, keys..., rowid).

            ONE fused ``lax.sort`` with ``num_keys`` (XLA sorts the
            lexicographic tuple in a single pass) instead of a stable
            argsort-per-key chain — the chain cost K+2 sorts plus
            gathers per step and dominated the columnsort wall. rowid is
            unique, so the tuple is a total order and stability is
            implied. Payload columns (incl. vector cells, which XLA Sort
            cannot carry alongside rank-1 keys) gather through the
            sorted positions."""
            m = flag.shape[0]
            ops = (flag,) + tuple(
                _key_transform(cols[ki], descending) for ki in key_idx
            ) + (rowid, jnp.arange(m, dtype=rowid.dtype))
            sorted_ops = jax.lax.sort(ops, num_keys=len(ops) - 1)
            order = sorted_ops[-1]
            return (sorted_ops[0], sorted_ops[-2],
                    [jnp.take(c, order, axis=0) for c in cols])

        def deal(a):
            # step 2: row i -> shard i%S, landing at j*(rp/S) + i//S from
            # source shard j (column-major read, row-major reshape)
            a2 = a.reshape((rp // S, S) + a.shape[1:]).swapaxes(0, 1)
            a2 = jax.lax.all_to_all(a2, axis, 0, 0, tiled=False)
            return a2.reshape((rp,) + a.shape[1:])

        def undeal(a):
            # step 4: contiguous chunk c -> shard c, received rows
            # interleave back (row-major read, column-major reshape)
            a2 = a.reshape((S, rp // S) + a.shape[1:])
            a2 = jax.lax.all_to_all(a2, axis, 0, 0, tiled=False)
            return a2.swapaxes(0, 1).reshape((rp,) + a.shape[1:])

        fwd = [(j, j + 1) for j in range(S - 1)]
        bwd = [(j + 1, j) for j in range(S - 1)]

        def shard_fn(valid, *cols):
            me = jax.lax.axis_index(axis)
            # flags: 0 real, 1 frame pad; internal pad rows (flag 2) are
            # appended to reach rp
            flag = jnp.where(valid, jnp.int8(0), jnp.int8(1))
            # widen axis_index before the multiply: me*r in int32 wraps
            # for frames at/above 2^31 padded rows (idx_dt is int64 then)
            rowid = me.astype(idx_dt) * r + jnp.arange(r, dtype=idx_dt)
            pad_n = rp - r
            flag = jnp.concatenate([flag, jnp.full(pad_n, 2, jnp.int8)])
            rowid = jnp.concatenate(
                [rowid, jnp.zeros(pad_n, idx_dt)])
            cs = [jnp.concatenate(
                [c, jnp.zeros((pad_n,) + c.shape[1:], c.dtype)])
                for c in cols]

            # named_scope per step: the whole pipeline is ONE compiled
            # program, so host spans cannot see the rounds — the scopes
            # label them in jax profiler traces instead (the measured
            # per-step costs live in benchmarks/dsort_steps_bench.py)
            with jax.named_scope("columnsort.s1_sort"):
                flag, rowid, cs = colsort(flag, rowid, cs)      # 1
            with jax.named_scope("columnsort.s2_deal"):
                flag, rowid = deal(flag), deal(rowid)           # 2
                cs = [deal(c) for c in cs]
            with jax.named_scope("columnsort.s3_sort"):
                flag, rowid, cs = colsort(flag, rowid, cs)      # 3
            with jax.named_scope("columnsort.s4_undeal"):
                flag, rowid = undeal(flag), undeal(rowid)       # 4
                cs = [undeal(c) for c in cs]
            with jax.named_scope("columnsort.s5_sort"):
                flag, rowid, cs = colsort(flag, rowid, cs)      # 5

            # 6: shifted column = [prev shard's bottom | own top]. Shard 0
            # receives no message and must see a MIN sentinel half: flags
            # travel offset by +16, so ppermute's zero-fill decodes to -16
            # (< every real flag) while real flags restore exactly. The
            # sentinel rows sort to shard 0's B1 top, which step 8 never
            # reads (only B1 bottoms and RIGHTWARD-shifted tops survive).
            with jax.named_scope("columnsort.s6_shift"):
                prev_flag = (jax.lax.ppermute(
                    flag[h:] + jnp.int8(16), axis, fwd) - jnp.int8(16))
                b1_flag = jnp.concatenate([prev_flag, flag[:h]])
                b1_rowid = jnp.concatenate(
                    [jax.lax.ppermute(rowid[h:], axis, fwd), rowid[:h]])
                b1_cs = [jnp.concatenate(
                    [jax.lax.ppermute(c[h:], axis, fwd), c[:h]])
                    for c in cs]
            with jax.named_scope("columnsort.s7_sort"):
                b1_flag, b1_rowid, b1_cs = colsort(
                    b1_flag, b1_rowid, b1_cs)                   # 7
            # the conceptual extra column S is [last shard's bottom | +inf
            # sentinel] — both parts already sorted, so it needs no sort

            # 8: unshift — own top = B1 bottom; own bottom = next shard's
            # B1 top (last shard: the extra column's top = its own step-5
            # bottom). ppermute zero-fill is overwritten by the where.
            last = me == S - 1

            def unshift(b1, own_step5):
                nxt = jax.lax.ppermute(b1[:h], axis, bwd)
                bottom = jnp.where(last, own_step5[h:], nxt)
                return jnp.concatenate([b1[h:], bottom])

            with jax.named_scope("columnsort.s8_unshift"):
                out_flag = unshift(b1_flag, flag)
                out_rowid = unshift(b1_rowid, rowid)
                out_cs = [unshift(b, c) for b, c in zip(b1_cs, cs)]
            del out_flag  # flags exist only to steer the sort
            return tuple(out_cs) + ((out_rowid,) if want_order else ())

        in_specs = (P(axis),) + tuple(
            P(axis, *([None] * (a.ndim - 1))) for a in arrays)
        out_specs = tuple(
            P(axis, *([None] * (a.ndim - 1))) for a in arrays)
        if want_order:
            out_specs = out_specs + (P(axis),)
        prog = shard_map(shard_fn, mesh=mesh.mesh, in_specs=in_specs,
                         out_specs=out_specs)

        def full(valid, *cols):
            outs = prog(valid, *cols)
            # drop the internal padding: the global [S*rp] result is
            # sorted with flag-2 rows strictly after the frame's own pad
            # rows, so the first `padded` rows ARE the frame's layout
            return tuple(o[:padded] for o in outs)

        return full

    out_shardings = tuple(mesh.row_sharding(a.ndim) for a in arrays)
    if want_order:
        out_shardings = out_shardings + (mesh.row_sharding(1),)

    # TFT_EXECUTOR=pjrt: the whole columnsort pipeline — local sorts AND
    # the all_to_all/ppermute exchanges — compiles as one GSPMD
    # executable in the native C++ core
    nm = _native_mesh(mesh)
    if nm is not None:
        in_shardings = (mesh.row_sharding(1),) + tuple(
            mesh.row_sharding(a.ndim) for a in arrays)
        try:
            outs_np = nm.run_sharded(
                ("dsort",) + ckey[1:], build_full,
                [valid_dev] + list(arrays), in_shardings,
                list(out_shardings), mesh)
        except Exception as e:
            _native_mesh_fallback(e)
            outs_np = None
        if outs_np is not None:
            return tuple(jax.device_put(a, s)
                         for a, s in zip(outs_np, out_shardings))

    fn = _dsort_cache.get(ckey)
    if fn is None:
        fn = jax.jit(build_full(), out_shardings=out_shardings)
        _dsort_cache[ckey] = fn
        while len(_dsort_cache) > _DSORT_CACHE_CAP:
            _dsort_cache.popitem(last=False)
    else:
        _dsort_cache.move_to_end(ckey)

    trace = current_trace()
    t0 = 0.0
    if trace is not None:
        t0 = _trace_shards(trace, "dsort", dist=dist)
        # the compiled pipeline's static exchange schedule (steps 2/4/6/8)
        trace.add("collective", name="all_to_all", ts=t0, count=2,
                  op="dsort.columnsort")
        trace.add("collective", name="ppermute", ts=t0, count=2,
                  op="dsort.columnsort")
    with span("dsort.columnsort_dispatch"):
        outs = fn(valid_dev, *arrays)
    counters.inc("mesh.dispatches")
    if trace is not None:
        _trace_mesh_done(trace, list(outs), t0, "dsort", mesh=mesh)
    return outs


def dreduce_blocks(fetches, dist: DistributedFrame):
    """Mesh-parallel reduce to one row.

    Two strategies:

    - ``fetches`` is a mapping ``{column: combiner-name}`` (sum/min/max/
      prod): ONE compiled ``shard_map`` program — local shard reduce, pad
      rows masked to the combiner's neutral element, cross-shard combine as
      an ICI collective (``lax.psum``/``pmin``/``pmax``). This is the
      BASELINE north-star path.
    - ``fetches`` is a computation (z/z_input contract): generic combine —
      per-shard async jit dispatches, partials stacked, one final reduce.

    On a LAZY frame a monoid reduce FOLDS into the pending chain's
    fused program as the terminal combiner (one mesh dispatch for chain
    + reduction, DrJAX-style); generic computations materialize the
    chain and run the eager path.
    """
    lz = _lazy_input(dist)
    if lz is not None:
        from ..plan import dist as _dplan
        out = _dplan.record_reduce(fetches, lz)
        if out is not None:
            return out
        dist = _dplan.materialize(lz)
    return _dreduce_blocks_eager(fetches, dist)


@traced_query("dreduce_blocks", _meta_with_fetches)
def _dreduce_blocks_eager(fetches, dist: DistributedFrame):
    if isinstance(fetches, Mapping) and all(
            isinstance(v, str) for v in fetches.values()):
        return _elastic.elastic_call(
            "dreduce_blocks", dist,
            lambda d: _collective_reduce(fetches, d))
    return _elastic.elastic_call(
        "dreduce_blocks", dist, lambda d: _generic_reduce(fetches, d))


# Compiled collective-reduce programs, keyed by everything that shapes the
# program (mesh, axis, column names/padded shapes/dtypes, combiners). The
# valid-row count is a traced scalar argument, not baked in, so frames whose
# padded global shapes coincide share one executable. LRU-bounded: distinct
# padded shapes otherwise accumulate executables without limit.
from collections import OrderedDict

_collective_cache: "OrderedDict[tuple, object]" = OrderedDict()
_COLLECTIVE_CACHE_CAP = 64

_native_mesh_warned = False


def _native_mesh(mesh: DeviceMesh):
    """The native GSPMD mesh executor when ``TFT_EXECUTOR=pjrt`` routes
    mesh ops through the C++ core, else ``None`` (the jax path)."""
    import os

    if os.environ.get("TFT_EXECUTOR") != "pjrt":
        return None
    from . import native_mesh

    return native_mesh.executor_for(mesh)


def _native_mesh_fallback(e: Exception):
    global _native_mesh_warned
    if not _native_mesh_warned:
        from ..utils.logging import get_logger

        get_logger("native_mesh").warning(
            "native mesh dispatch failed (%s); falling back to the jax "
            "path for this and subsequent calls that hit the same error",
            e)
        _native_mesh_warned = True


def _collective_shard_fn(names, combs, axis):
    """The per-shard masked-reduce + collective program — ONE source of
    truth shared by the jax ``shard_map`` path and the native GSPMD path."""

    def shard_fn(nv, *shards):
        outs = []
        rows = shards[0].shape[0]
        valid = jnp.arange(rows) < nv[0]
        for name, s in zip(names, shards):
            c = combs[name]
            mask = valid.reshape((rows,) + (1,) * (s.ndim - 1))
            neutral = jnp.asarray(c.neutral(s.dtype))
            masked = jnp.where(mask, s, neutral)
            local = c.local(masked, 0)
            outs.append(c.collective(local, axis))
        return tuple(outs)

    return shard_fn


def _collective_reduce(col_combiners: Mapping[str, str],
                       dist: DistributedFrame) -> Dict[str, np.ndarray]:
    mesh = dist.mesh
    axis = mesh.data_axis
    if dist.num_rows == 0:
        raise ValueError("reduce on an empty distributed frame")
    combs = {}
    for name, cname in col_combiners.items():
        if name not in dist.schema:
            raise KeyError(f"No column {name!r}")
        if cname not in COMBINERS:
            raise KeyError(
                f"Unknown combiner {cname!r}; known: {sorted(COMBINERS)}")
        combs[name] = COMBINERS[cname]

    names = sorted(col_combiners)
    arrays = [dist.columns[n] for n in names]
    key = (mesh.mesh, axis,
           tuple((n, col_combiners[n], a.shape, str(a.dtype))
                 for n, a in zip(names, arrays)))
    # per-shard valid-row counts ride in sharded over the axis: pads are
    # masked wherever they fall (a multi-host frame pads per process,
    # not in a global suffix)
    in_specs = (P(axis),) + tuple(
        P(axis, *([None] * (a.ndim - 1))) for a in arrays)

    outs = None
    nm = _native_mesh(mesh)
    if nm is not None:
        try:
            outs = nm.dreduce_collective(
                _collective_shard_fn(names, combs, axis), in_specs, names,
                dist, dist.per_shard_valid(), key)
        except Exception as e:
            _native_mesh_fallback(e)
            outs = None
    if outs is None:
        fn = _collective_cache.get(key)
        if fn is not None:
            _collective_cache.move_to_end(key)
        else:
            out_specs = tuple(P() for _ in arrays)
            fn = jax.jit(shard_map(
                _collective_shard_fn(names, combs, axis), mesh=mesh.mesh,
                in_specs=in_specs, out_specs=out_specs))
            _collective_cache[key] = fn
            while len(_collective_cache) > _COLLECTIVE_CACHE_CAP:
                _collective_cache.popitem(last=False)
        nv_dev = jax.make_array_from_callback(
            (mesh.num_data_shards,), mesh.row_sharding(1),
            lambda idx: dist.per_shard_valid().astype(np.int32)[idx])
        trace = current_trace()
        t0 = 0.0
        if trace is not None:
            t0 = _trace_shards(trace, "dreduce_blocks", dist=dist)
            for name in names:
                trace.add("collective", name=combs[name].ici, ts=t0,
                          column=name, op="dreduce_blocks")
        with span("dreduce_blocks.collective_dispatch"):
            outs = fn(nv_dev, *arrays)
        if trace is not None:
            _trace_mesh_done(trace, list(outs), t0, "dreduce_blocks",
                             mesh=mesh)
    counters.inc("mesh.dispatches")
    result = {}
    for name, a in zip(names, outs):
        v = np.asarray(a)
        f = dist.schema[name]
        if v.dtype != f.dtype.np_storage and f.dtype is not _dt.bfloat16:
            v = v.astype(f.dtype.np_storage)
        result[name] = v
    return result


def _cached_group_ids(dist: DistributedFrame, keys, max_groups):
    """Memoized key factorization (see ``DistributedFrame._group_ids_cache``).

    Returns ``(ids_dev, uniques, uniq_dev, count_dev, num_groups)`` —
    ``uniques`` is None on the device path, ``uniq_dev``/``count_dev``
    are None on the host path.
    """
    if max_groups is not None:
        ckey = ("device", tuple(keys), max_groups)
        hit = _group_ids_cache_get(dist, ckey)
        if hit is None:
            hit = _device_key_ids(dist, keys, max_groups)
            _group_ids_cache_put(dist, ckey, hit)
        ids_dev, uniq_dev, count_dev, num_groups = hit
        return ids_dev, None, uniq_dev, count_dev, num_groups
    ckey = ("host", tuple(keys))
    hit = _group_ids_cache_get(dist, ckey)
    if hit is None:
        hit = _host_group_ids(dist, keys)
        _group_ids_cache_put(dist, ckey, hit)
    ids_dev, uniques, num_groups = hit
    return ids_dev, uniques, None, None, num_groups


_GROUP_IDS_CACHE_CAP = 8


def _group_ids_cache_get(dist: DistributedFrame, ckey: tuple):
    hit = dist._group_ids_cache.get(ckey)
    if hit is not None:
        dist._group_ids_cache.move_to_end(ckey)
    return hit


def _group_ids_cache_put(dist: DistributedFrame, ckey: tuple, hit: tuple):
    dist._group_ids_cache[ckey] = hit
    while len(dist._group_ids_cache) > _GROUP_IDS_CACHE_CAP:
        dist._group_ids_cache.popitem(last=False)


def _monoid_group_plan(dist: DistributedFrame, keys):
    """Host-key group ids + the hot-key salt plan for a monoid
    aggregation — ONE definition shared by ``_daggregate``'s jax path
    and the fused distributed plan's folded ``daggregate``
    (``plan/dist.py``), so the two can never drift.

    Returns ``(ids_dev, uniques, num_groups, salt_plan)``; salting is
    cached per (frame, keys, threshold) like the group ids themselves.
    """
    ids_dev, uniques, _, _, num_groups = _cached_group_ids(
        dist, keys, None)
    salt_plan = None
    if dist.mesh.num_data_shards > 1:
        frac = _elastic.salt_fraction()
        if frac is not None:
            skey = ("salt", tuple(keys), frac)
            cached = _group_ids_cache_get(dist, skey)
            if cached is None:
                cached = (_elastic.plan_key_salt(
                    dist, ids_dev, num_groups,
                    dist.mesh.num_data_shards),)
                _group_ids_cache_put(dist, skey, cached)
            salt_plan = cached[0]
    return ids_dev, uniques, num_groups, salt_plan


def _monoid_agg_shard_fn(fetch_names, col_combiners, axis,
                         prog_groups: int, seg_impl=None):
    """The per-shard monoid segment-reduce + collective fragment — ONE
    definition shared by ``_daggregate`` (jax AND native routes), the
    fused distributed plan's folded ``daggregate``, and the streaming
    mesh fold (``plan/dist.py``), so the four dispatch paths can never
    drift."""
    from ..ops.segment_reduce import segment_sum as _segsum

    def shard_fn(ids_local, *vals_local):
        outs = []
        for f, v in zip(fetch_names, vals_local):
            cname = col_combiners[f]
            if cname == "sum":
                local = _segsum(v, ids_local, prog_groups,
                                impl=seg_impl)
            else:
                # mask pad/out-of-range rows to the combiner's neutral
                # and clamp their id to 0 so XLA's segment primitive
                # sees only in-range indices
                c = COMBINERS[cname]
                valid = ids_local >= 0
                vmask = valid.reshape((-1,) + (1,) * (v.ndim - 1))
                neutral = jnp.asarray(c.neutral(v.dtype))
                masked = jnp.where(vmask, v, neutral)
                safe_ids = jnp.where(valid, ids_local, 0)
                seg = {"min": jax.ops.segment_min,
                       "max": jax.ops.segment_max,
                       "prod": jax.ops.segment_prod}[cname]
                local = seg(masked, safe_ids,
                            num_segments=prog_groups)
                # a group absent from this shard holds the identity;
                # for min/max that identity is +-inf, which the
                # cross-shard collective absorbs (every group exists
                # somewhere)
            outs.append(COMBINERS[cname].collective(local, axis))
        return tuple(outs)

    return shard_fn


def _monoid_agg_result(schema: Schema, keys, fetch_names, tables,
                       key_cols, num_out: int) -> TensorFrame:
    """Host assembly of a monoid aggregation's result frame (key
    columns + sliced/cast fetch tables) — shared by ``_daggregate``
    and the fused plan's folded ``daggregate``."""
    from ..schema import Field
    from ..shape import Unknown

    cols = dict(key_cols)
    for f, t in zip(fetch_names, tables):
        v = np.asarray(t)[:num_out]
        fld = schema[f]
        if v.dtype != fld.dtype.np_storage and fld.dtype is not _dt.bfloat16:
            v = v.astype(fld.dtype.np_storage)
        cols[f] = v
    out_fields = [schema[k] for k in keys] + [
        Field(f, schema[f].dtype,
              block_shape=(schema[f].block_shape.with_lead(Unknown)
                           if schema[f].block_shape is not None else None),
              sql_rank=schema[f].sql_rank)
        for f in fetch_names]
    return TensorFrame.from_blocks([Block(cols, num_out)],
                                   Schema(out_fields))


def _host_group_ids(dist: DistributedFrame, keys):
    """Key columns → dense group ids on the mesh (host factorization).

    Only the scalar KEY columns visit the host; ids come back row-sharded
    with pad rows marked ``-1`` (dropped by every consumer). Returns
    ``(ids_dev, uniques, num_groups)``.
    """
    from ..engine.ops import InvalidTypeError, _factorize_keys

    mesh = dist.mesh
    schema = dist.schema
    mask = dist.valid_row_mask()
    key_host = []
    for k in keys:
        fld = schema[k]
        a = dist.host_read_padded(k)
        a = a[mask] if dist.shard_valid is not None else a[: dist.num_rows]
        if a.ndim != 1:
            raise InvalidTypeError(f"Key column {k!r} must be scalar-typed")
        if a.dtype != fld.dtype.np_storage and fld.dtype is not _dt.bfloat16:
            # distribute() stored this column in its device dtype; if that
            # narrowed the storage type (long->int / double->float with x64
            # off), distinct keys may already have collapsed on device —
            # group identity is unrecoverable, so fail loudly instead of
            # silently merging groups
            if np.dtype(a.dtype).itemsize < np.dtype(fld.dtype.np_storage).itemsize:
                raise InvalidTypeError(
                    f"Key column {k!r} ({fld.dtype.name}) was narrowed to "
                    f"{a.dtype} on device, which can merge distinct keys; "
                    f"cast the key to a device-exact type (e.g. int) before "
                    f"distribute(), or enable x64")
            a = a.astype(fld.dtype.np_storage)
        key_host.append(a)
    fact = _factorize_keys(key_host)
    ids_padded = np.full(dist.padded_rows, -1, np.int32)  # -1: pad, dropped
    if dist.shard_valid is not None:
        ids_padded[mask] = fact.ids
    else:
        ids_padded[: dist.num_rows] = fact.ids
    ids_dev = jax.make_array_from_callback(
        (dist.padded_rows,), mesh.row_sharding(1),
        lambda idx: ids_padded[idx])
    return ids_dev, fact.uniques, fact.num_groups


def _device_group_ids(dist: DistributedFrame, key: str, max_groups: int,
                      valid=None):
    """Dense group ids computed ON DEVICE for a single integer key column.

    The host-factorization path ships the whole key column driver-side per
    call (the reference's Catalyst groupBy did the same in the JVM,
    ``DebugRowOps.scala:533-578``); at 100k+ groups that transfer and the
    host lexsort dominate. Here the key column never leaves the mesh: a
    device sort-unique (``jnp.unique`` with a static size cap) builds the
    group table and a ``searchsorted`` maps rows to ids — XLA inserts the
    cross-shard gather for the sort, which IS the shuffle, on ICI.

    ``max_groups`` caps the static table size (XLA needs static shapes);
    ``valid`` (row-sharded bool [padded]) is built when absent so
    composite-key callers upload it once. Returns the raw
    ``(ids_dev, uniques_dev, count_dev, sentinel_hit)`` from
    :func:`_build_device_ids` — ids are ``-1`` for pad rows; cap overflow
    and the sentinel flag are the CALLER's to read back and raise on.
    """
    kcol = dist.columns[key]
    if not jnp.issubdtype(kcol.dtype, jnp.integer):
        raise _ops.InvalidTypeError(
            f"device-side aggregation needs an integer key column; {key!r} "
            f"is {kcol.dtype} (use the host path)")
    fld = dist.schema[key]
    if np.dtype(kcol.dtype).itemsize < np.dtype(fld.dtype.np_storage).itemsize:
        # same hazard _host_group_ids guards: device narrowing (long->int
        # with x64 off) can merge distinct keys — unrecoverable, so fail
        raise _ops.InvalidTypeError(
            f"Key column {key!r} ({fld.dtype.name}) was narrowed to "
            f"{kcol.dtype} on device, which can merge distinct keys; cast "
            f"the key to a device-exact type (e.g. int) before "
            f"distribute(), or enable x64")
    if valid is None:
        valid = _valid_dev(dist)
    # NB: returns traced/async values incl. the sentinel flag — callers
    # read back and raise (lets the composite path dispatch every key's
    # program before the first synchronization)
    return _build_device_ids(kcol, valid, max_groups)


def _sentinel_check(sentinel_hit, key: str) -> None:
    if bool(sentinel_hit):
        raise _ops.InvalidTypeError(
            f"key column {key!r} contains the dtype's max value, which the "
            f"device path reserves as its pad sentinel; use the host path "
            f"(max_groups=None) for such keys")


def _valid_dev(dist: DistributedFrame):
    valid_host = dist.valid_row_mask()
    return jax.make_array_from_callback(
        (dist.padded_rows,), dist.mesh.row_sharding(1),
        lambda idx: valid_host[idx])


@functools.partial(jax.jit, static_argnums=(2,))
def _build_device_ids(kc, vm, max_groups: int):
    """Sort-unique group table + per-row dense ids, one compiled program
    (module-level jit: re-invocations with the same shapes/cap reuse it)."""
    sentinel = jnp.iinfo(kc.dtype).max
    sentinel_hit = jnp.any(vm & (kc == sentinel))
    masked = jnp.where(vm, kc, sentinel)
    uniq = jnp.unique(masked, size=max_groups + 1, fill_value=sentinel)
    count = jnp.sum(uniq != sentinel)
    ids = jnp.searchsorted(uniq, masked).astype(jnp.int32)
    ids = jnp.where(vm, ids, -1)
    return ids, uniq, count, sentinel_hit


@functools.partial(jax.jit, static_argnums=(2,))
def _combine_ids(acc, ids_k, radix: int):
    """Mixed-radix combination of dense per-key ids (int32 throughout —
    the device path must work with x64 disabled, so no int64 packing)."""
    return acc * np.int32(radix) + ids_k


def _device_key_ids(dist: DistributedFrame, keys, max_groups: int):
    """Shared entry to the device-keys path (monoid + generic daggregate).

    One key: sort-unique + searchsorted on the mesh (the key never visits
    the host). Composite keys: each key column factorizes to dense ids the
    same way, the ids combine into one mixed-radix int32 id space
    (``radix = max_groups + 1`` per position — every key's distinct count
    is bounded by the final group count, so one cap serves all), and one
    more sort-unique over the combined ids yields the dense group table.
    All arithmetic stays int32: ``(cap+1)^k`` must fit, which bounds the
    cap at ~46k for two keys (checked loudly; the host path has no cap).

    Returns ``(ids_dev, key_table, count_dev, table_groups)`` where
    ``key_table`` carries what :func:`_device_key_columns` needs to
    rebuild the key columns and ``table_groups`` is the static table size
    (cap + sentinel slot)."""
    if len(keys) == 1:
        ids_dev, uniq_dev, count_dev, sent = _device_group_ids(
            dist, keys[0], max_groups)
        _sentinel_check(sent, keys[0])
        return ids_dev, ("single", uniq_dev), count_dev, max_groups + 1

    radix = max_groups + 1
    if radix ** len(keys) >= 2 ** 31 - 1:
        raise ValueError(
            f"max_groups={max_groups} with {len(keys)} key columns "
            f"overflows the int32 combined-id space ((cap+1)^k must stay "
            f"below 2^31); lower the cap or use the host path "
            f"(max_groups=None)")
    # one valid-mask upload serves every per-key program and the final
    # combine; all dispatches go out before the first readback
    valid = _valid_dev(dist)
    per = [_device_group_ids(dist, k, max_groups, valid=valid)
           for k in keys]
    combined = None
    for ids_k, _, _, _ in per:
        combined = (ids_k if combined is None
                    else _combine_ids(combined, ids_k, radix))
    ids, uniq_c, count, _ = _build_device_ids(combined, valid, max_groups)
    for k, (_, _, count_k, sent_k) in zip(keys, per):
        _sentinel_check(sent_k, k)
        if int(count_k) > max_groups:
            # a truncated per-key table would silently merge distinct
            # keys before the final overflow check could see them
            raise ValueError(
                f"more than max_groups={max_groups} distinct values in "
                f"key column {k!r}; raise max_groups (the static table "
                f"cap)")
    per_uniq = [u for _, u, _, _ in per]
    return ids, ("multi", uniq_c, per_uniq, radix), count, max_groups + 1


def _device_key_columns(dist: DistributedFrame, keys, key_table,
                        count_dev, max_groups: int):
    """Overflow check + host materialization of the device group table(s).
    Returns ``({key name: values}, num_groups)``."""
    count = int(count_dev)
    if count > max_groups:
        raise ValueError(
            f"more than max_groups={max_groups} distinct keys in "
            f"{keys}; raise max_groups (the static table cap)")

    def cast(vals, key):
        kfld = dist.schema[key]
        if vals.dtype != kfld.dtype.np_storage:  # integer keys only
            vals = vals.astype(kfld.dtype.np_storage)
        return vals

    if key_table[0] == "single":
        return {keys[0]: cast(np.asarray(key_table[1])[:count],
                              keys[0])}, count
    _, uniq_c, per_uniq, radix = key_table
    comb = np.asarray(uniq_c)[:count].astype(np.int64)
    digits = []
    for _ in keys:                       # least-significant digit first
        digits.append(comb % radix)
        comb = comb // radix
    return {k: cast(np.asarray(per_uniq[i])[digits[len(keys) - 1 - i]], k)
            for i, k in enumerate(keys)}, count


def daggregate(fetches, dist: DistributedFrame, keys,
               max_groups: Optional[int] = None) -> TensorFrame:
    """Mesh-distributed keyed aggregation.

    The reference's Catalyst shuffle + UDAF (``DebugRowOps.scala:533-681``)
    re-expressed TPU-first: instead of moving rows between workers by key,
    each shard reduces its LOCAL rows into a dense ``[groups, ...]`` table
    and the tables are combined across the data axis — the shuffle becomes
    an ICI collective over a small table. Only the scalar KEY columns visit
    the host (to build dense group ids); the values never leave their
    shards.

    Two paths, mirroring :func:`~tensorframes_tpu.api.aggregate`:

    - ``fetches`` is a mapping ``{column: combiner-name}`` (sum/min/max/
      prod): one segment-reduce launch per column (the Pallas one-hot
      matmul for float sums) + one ``psum``-family collective;
    - ``fetches`` is a computation (block-level ``<col>_input`` reduce,
      the UDAF contract): per-shard sort-by-id + segmented
      ``associative_scan`` whose pair-combiner IS the user computation on
      two-row blocks, segment tails scattered into a ``[groups, ...]``
      partial table, then a cross-shard masked fold of the stacked tables
      with the same combiner. Combine order is contractually unspecified
      (the compaction contract — the computation must tolerate arbitrary
      regrouping, ``core.py:96-97``), which is exactly what makes the
      O(log rows) scan legal.

    ``keys``: key column name or list of names. Returns a host
    :class:`TensorFrame` of one row per group (keys + fetches, fetches
    sorted by name), like :func:`~tensorframes_tpu.api.aggregate`.

    ``max_groups``: opt into DEVICE-side group ids for integer key(s)
    (``_device_key_ids``): the key columns never visit the host — at
    100k+ groups the host path's driver-side transfer + lexsort dominate
    (``benchmarks/daggregate_bench.py`` measures both). The value caps
    the static group-table size; exceeding it raises. Composite keys
    combine per-key dense ids in a mixed-radix int32 space, which bounds
    the cap at ``(cap+1)^k < 2^31``.

    Under ``TFT_EXECUTOR=pjrt`` the aggregation program runs in the
    native C++ core, whose dispatch marshals ids and value columns
    through host numpy per call (the documented correctness-proof
    trade, ``native_mesh`` module docstring) — so the device-residency
    promises above (values stay on their shards; ``max_groups`` keys
    never visit the host) hold on the default jax dispatch, not on the
    native route. Latency-sensitive iterative workloads should keep the
    jax path for this op.

    Skew: on the monoid host-key jax path, a key group holding more
    than ``TFT_HOT_KEY_FRACTION`` of the rows is **salted** across the
    data shards (``parallel/elastic.py``) — per-salt partials fold back
    on the host, so results keep the same groups and order (float sums
    may reassociate, like any resharding).
    """
    if isinstance(keys, str):
        keys = [keys]
    keys = list(keys)
    if not keys:
        raise ValueError("daggregate needs at least one key column")
    lz = _lazy_input(dist)
    if lz is not None:
        # a monoid host-key aggregation over a filter-free chain whose
        # keys pass through untouched FOLDS into the fused program as
        # the terminal combiner; anything else materializes the chain
        # (still fused among itself) and runs the eager op on the
        # device-resident result
        from ..plan import dist as _dplan
        out = _dplan.record_aggregate(fetches, lz, keys, max_groups)
        if out is not None:
            return out
        dist = _dplan.materialize(lz)
    return _daggregate_eager(fetches, dist, keys, max_groups)


@traced_query("daggregate", _meta_daggregate)
def _daggregate_eager(fetches, dist: DistributedFrame, keys,
                      max_groups: Optional[int] = None) -> TensorFrame:
    return _elastic.elastic_call(
        "daggregate", dist,
        lambda d: _daggregate(fetches, d, keys, max_groups))


def _daggregate(fetches, dist: DistributedFrame, keys,
                max_groups: Optional[int]) -> TensorFrame:
    schema = dist.schema
    for k in keys:
        if k not in schema:
            raise KeyError(f"No key column {k!r}; columns: {schema.names}")
    from ..engine.ops import _is_sketch, _monoid_mapping
    if not _monoid_mapping(fetches):
        return _generic_daggregate(fetches, dist, keys,
                                   max_groups=max_groups)
    if any(_is_sketch(v) for v in fetches.values()):
        return _daggregate_sketch(fetches, dist, keys, max_groups)
    col_combiners = fetches

    from ..engine.ops import _validate_monoid_fetches

    mesh = dist.mesh
    axis = mesh.data_axis
    value_names = [n for n in schema.names if n not in keys]
    _validate_monoid_fetches(col_combiners, value_names,
                             "before distribute()")
    n = dist.num_rows
    if n == 0:
        raise ValueError("aggregate on an empty distributed frame")

    device_keys = max_groups is not None
    if device_keys:
        ids_dev, uniques, uniq_dev, count_dev, num_groups = \
            _cached_group_ids(dist, keys, max_groups)
        salt_plan = None
    else:
        ids_dev, uniques, num_groups, salt_plan = _monoid_group_plan(
            dist, keys)
        uniq_dev = count_dev = None
        # high-cardinality keys: the dense per-shard tables below hold
        # EVERY group on EVERY shard — beyond TFT_SHUFFLE_AGG_GROUPS,
        # hash-repartition instead so each device aggregates only its
        # own key range (O(groups/shards) state; parallel/exchange.py)
        from .exchange import (shuffle_agg_groups_threshold,
                               shuffle_enabled)
        thr = shuffle_agg_groups_threshold()
        if (thr is not None and shuffle_enabled()
                and num_groups > thr and mesh.num_data_shards > 1):
            from .exchange import _shuffle_daggregate_impl
            counters.inc("mesh.shuffle_agg_routes")
            return _shuffle_daggregate_impl(fetches, dist, keys)
    if salt_plan is not None:
        prog_ids, prog_groups = salt_plan[0], salt_plan[1]
    else:
        prog_ids, prog_groups = ids_dev, num_groups

    fetch_names = sorted(col_combiners)
    arrays = [dist.columns[f] for f in fetch_names]
    in_specs = (P(axis),) + tuple(
        P(axis, *([None] * (a.ndim - 1))) for a in arrays)
    out_specs = tuple(P() for _ in fetch_names)

    # TFT_EXECUTOR=pjrt: the per-shard segment reduce + collective runs as
    # ONE GSPMD executable in the native C++ core (the last mesh op to
    # gain the route — reference property: every UDAF compaction ran in
    # the C++ session, DebugRowOps.scala:617-662). The XLA scatter-add
    # segment_sum flavor is forced: the Pallas flavor lowers to Mosaic
    # custom calls the native core's backends cannot compile.
    pkey = ("daggregate", mesh.mesh, axis, prog_groups,
            tuple((f, col_combiners[f]) for f in fetch_names),
            tuple((a.shape, str(a.dtype)) for a in arrays))
    tables = None
    # salted programs stay on the jax path: the host-side fold below is
    # the salting's second half, and the native route re-marshals anyway
    nm = None if salt_plan is not None else _native_mesh(mesh)
    if nm is not None:
        def build_prog():
            return shard_map(
                _monoid_agg_shard_fn(fetch_names, col_combiners, axis,
                                     prog_groups, seg_impl="xla"),
                mesh=mesh.mesh, in_specs=in_specs, out_specs=out_specs)

        in_shardings = [mesh.row_sharding(1)] + [
            mesh.row_sharding(a.ndim) for a in arrays]
        out_shardings = [mesh.replicated() for _ in fetch_names]
        try:
            tables = nm.run_sharded(pkey, build_prog,
                                    [ids_dev] + list(arrays),
                                    in_shardings, out_shardings, mesh)
        except Exception as e:
            _native_mesh_fallback(e)
            tables = None
    if tables is None:
        # cache the jitted program (the closure is fresh per call, so
        # jax's own jit cache would miss and retrace every dispatch)
        fn = _collective_cache.get(pkey)
        if fn is not None:
            _collective_cache.move_to_end(pkey)
        else:
            fn = jax.jit(shard_map(
                _monoid_agg_shard_fn(fetch_names, col_combiners, axis,
                                     prog_groups),
                mesh=mesh.mesh, in_specs=in_specs, out_specs=out_specs))
            _collective_cache[pkey] = fn
            while len(_collective_cache) > _COLLECTIVE_CACHE_CAP:
                _collective_cache.popitem(last=False)
        trace = current_trace()
        t0 = 0.0
        if trace is not None:
            t0 = _trace_shards(trace, "daggregate", dist=dist)
            for f in fetch_names:
                trace.add("collective", name=COMBINERS[col_combiners[f]].ici,
                          ts=t0, column=f, op="daggregate")
        with span("daggregate.dispatch"):
            tables = fn(prog_ids, *arrays)
        if trace is not None:
            _trace_mesh_done(trace, list(tables), t0, "daggregate",
                             mesh=mesh)
    counters.inc("mesh.dispatches")

    if salt_plan is not None:
        tables = [_elastic.fold_salted(t, salt_plan[2], col_combiners[f])
                  for f, t in zip(fetch_names, tables)]
    if device_keys:
        key_cols, num_out = _device_key_columns(dist, keys, uniq_dev,
                                                count_dev, max_groups)
    else:
        key_cols = {k: u for k, u in zip(keys, uniques)}
        num_out = num_groups
    out = _monoid_agg_result(schema, keys, fetch_names, tables,
                             key_cols, num_out)
    if salt_plan is not None:
        attach_hot_keys(out, keys, uniques, salt_plan)
    return out


def attach_hot_keys(frame: TensorFrame, keys, uniques,
                    salt_plan) -> None:
    """Record the hot-key OBSERVATIONS that triggered salting on the
    result frame — the public surface is ``frame.hot_keys()`` and an
    ``explain()`` line (the PR 7 salting decisions were previously
    visible only as counters/log lines). Shared by the eager
    ``_daggregate`` and the fused distributed plan's folded daggregate.
    """
    hot, K = salt_plan[2]
    fracs = salt_plan[3] if len(salt_plan) > 3 else None
    records = []
    for j, g in enumerate(hot):
        kv = {}
        for k, u in zip(keys, uniques):
            v = u[int(g)]
            kv[k] = v.item() if hasattr(v, "item") else v
        records.append({
            "keys": kv,
            "fraction": (float(fracs[j]) if fracs is not None
                         else None),
            "salt_slots": int(K),
        })
    frame._hot_keys = records


def _daggregate_sketch(fetches, dist: DistributedFrame, keys,
                       max_groups: Optional[int]) -> TensorFrame:
    """The sketch half of a mesh aggregation (``docs/joins.md``).

    Sketch combiners hash/bucket on the HOST in float64 (the
    determinism contract that makes aggregate == daggregate == stream
    bit-identical), so their partials fold from the host copies of the
    value columns — read per shard layout under the surrounding
    ``elastic_call`` (a device lost mid-read shrinks/reshards/retries
    like any mesh op). Scalar combiners mixed into the same mapping
    keep the full device segment-reduce + collective path; both halves
    share ONE cached group factorization, so their group order is
    identical by construction.
    """
    from ..engine.ops import (_is_sketch, _validate_monoid_fetches)
    from ..schema import Schema as _Schema

    schema = dist.schema
    if max_groups is not None:
        raise ValueError(
            "max_groups= (device-side group ids) does not compose with "
            "sketch combiners — sketches hash on the host; drop "
            "max_groups or the sketch fetches")
    value_names = [n for n in schema.names if n not in keys]
    _validate_monoid_fetches(fetches, value_names,
                             "before distribute()", schema=schema)
    if dist.num_rows == 0:
        raise ValueError("aggregate on an empty distributed frame")
    scalars = {f: c for f, c in fetches.items() if not _is_sketch(c)}
    sketches = {f: c for f, c in fetches.items() if _is_sketch(c)}

    ids_dev, uniques, num_groups, salt_plan = _monoid_group_plan(
        dist, keys)
    # the scalar half sees only its own columns (no spurious
    # ride-along warnings about the sketch fetches); the group order
    # is identical by construction — same key data, same deterministic
    # host factorization
    scalar_out = (_daggregate(
        scalars, dist.select(list(keys) + sorted(scalars)), keys, None)
        if scalars else None)

    ids_host = np.asarray(ids_dev)
    valid = ids_host >= 0
    ids = ids_host[valid].astype(np.int64)
    mask = dist.valid_row_mask()
    sketch_cols: Dict[str, np.ndarray] = {}
    with span("daggregate.sketch_fold"):
        for f in sorted(sketches):
            sk = sketches[f]
            a = _memory.host_value(dist.columns, f)
            vals = a[mask] if dist.shard_valid is not None \
                else a[: dist.num_rows]
            table = sk.block_partial(np.asarray(vals), ids, num_groups)
            counters.inc("relational.sketch_folds")
            sketch_cols.update(sk.finalize(f, table))

    # assemble: keys + sorted fetch columns (sketch multi-outputs
    # inline after their fetch name)
    out_fields = [schema[k] for k in keys]
    cols: Dict[str, np.ndarray] = {}
    if scalar_out is not None:
        sb = Block.concat(scalar_out.blocks(), scalar_out.schema)
        for k in keys:
            cols[k] = sb.columns[k]
    else:
        for k, u in zip(keys, uniques):
            cols[k] = np.asarray(u)
    for f in sorted(fetches):
        if f in sketches:
            for fld in sketches[f].out_fields(f, schema[f]):
                out_fields.append(fld)
                cols[fld.name] = sketch_cols[fld.name]
        else:
            out_fields.append(scalar_out.schema[f])
            cols[f] = sb.columns[f]
    out = TensorFrame.from_blocks(
        [Block(cols, num_groups)], _Schema(out_fields))
    if salt_plan is not None:
        attach_hot_keys(out, keys, uniques, salt_plan)
    return out


def _segmented_fold(comp, names, mesh: DeviceMesh, arrays, ids_dev,
                    G: int) -> Dict[str, jax.Array]:
    """Per-group fold of an arbitrary reduce computation on the mesh.

    Requires a vmappable computation: deserialized (``exported.call``)
    computations have no batching rule and are rejected with a clear
    error at trace time by jax.

    ``ids_dev``: row-sharded dense group ids ([padded_rows] int32, ``-1``
    for pad rows). Per shard: stable sort by id, segmented
    ``associative_scan`` whose operator applies ``comp`` to a stacked
    two-row block when both elements share an id, segment tails scattered
    into a ``[G, ...]`` table + presence mask; the stacked per-shard
    tables are folded pairwise with the same combiner, and ``comp`` is
    applied once more over each group's single-row block (at-least-once
    parity with the host ``CompactionBuffer.evaluate``). Returns
    ``{fetch: [G, ...cell]}`` device arrays. The jitted program is cached
    on ``comp`` keyed by (mesh, G, shapes).
    """
    axis = mesh.data_axis

    def pair(av, bv):
        """User computation over the stacked two-row block {a; b}."""
        out = comp.fn({f + "_input": jnp.stack([av[f], bv[f]])
                       for f in names})
        return {f: out[f] for f in names}

    def single(av):
        out = comp.fn({f + "_input": av[f][None] for f in names})
        return {f: out[f] for f in names}

    pair_v = jax.vmap(pair)
    single_v = jax.vmap(single)

    in_specs = (P(axis),) + tuple(
        P(axis, *([None] * (a.ndim - 1))) for a in arrays)
    # each shard emits its [1, G, ...] table slice; stacking over the data
    # axis yields the global [shards, G, ...] partials
    out_specs = (tuple(P(axis) for _ in names), P(axis))

    def shard_fn(ids_local, *vals_local):
        R = ids_local.shape[0]
        # pad rows (-1) sort to the end as group G and are dropped by the
        # mode="drop" scatter below
        sort_ids = jnp.where(ids_local < 0, G, ids_local)
        order = jnp.argsort(sort_ids, stable=True)
        sid = sort_ids[order]
        svals = {f: v[order] for f, v in zip(names, vals_local)}

        def op(a, b):
            a_id, a_v = a
            b_id, b_v = b
            same = a_id == b_id
            comb = pair_v(a_v, b_v)
            out_v = {}
            for f in names:
                m = same.reshape((-1,) + (1,) * (comb[f].ndim - 1))
                out_v[f] = jnp.where(m, comb[f], b_v[f])
            return (b_id, out_v)

        _, scanned = jax.lax.associative_scan(op, (sid, svals), axis=0)
        tail = jnp.concatenate(
            [sid[1:] != sid[:-1], jnp.ones((1,), bool)])
        target = jnp.where(tail & (sid < G), sid, G)  # G → dropped
        table = {}
        for f in names:
            z = jnp.zeros((G,) + scanned[f].shape[1:], scanned[f].dtype)
            table[f] = z.at[target].set(scanned[f], mode="drop")
        present = jnp.zeros((G,), bool).at[target].set(
            jnp.ones((R,), bool), mode="drop")
        return tuple(table[f][None] for f in names), present[None]

    def program(ids, *cols):
        stacked, present = shard_map(
            shard_fn, mesh=mesh.mesh, in_specs=in_specs,
            out_specs=out_specs)(ids, *cols)
        tabs = dict(zip(names, stacked))  # each [S, G, ...cell]
        S = present.shape[0]
        acc = {f: tabs[f][0] for f in names}
        acc_p = present[0]
        for s in range(1, S):
            comb = pair_v({f: acc[f] for f in names},
                          {f: tabs[f][s] for f in names})
            both = acc_p & present[s]
            for f in names:
                m_both = both.reshape((-1,) + (1,) * (acc[f].ndim - 1))
                m_new = present[s].reshape(
                    (-1,) + (1,) * (acc[f].ndim - 1))
                acc[f] = jnp.where(m_both, comb[f],
                                   jnp.where(m_new, tabs[f][s], acc[f]))
            acc_p = acc_p | present[s]
        # at-least-once application of the computation (host parity for
        # single-row groups, where the scan never ran the combiner)
        return single_v(acc)

    # TFT_EXECUTOR=pjrt: the whole generic-aggregation program — per-shard
    # sort + segmented scan + scatter AND the cross-shard masked fold —
    # compiles as one GSPMD executable in the native C++ core (cached on
    # the Computation; un-routable programs latch to the jax path)
    nm = _native_mesh(mesh)
    if nm is not None:
        def build_prog():
            def prog(ids, *cols):
                out = program(ids, *cols)
                return tuple(out[f] for f in names)
            return prog

        in_shardings = [mesh.row_sharding(1)] + [
            mesh.row_sharding(a.ndim) for a in arrays]
        out_shardings = [mesh.replicated() for _ in names]
        nkey = ("dagg_generic", mesh.mesh, axis, G,
                tuple((f, a.shape, str(a.dtype))
                      for f, a in zip(names, arrays)))
        try:
            outs = nm.run_sharded(nkey, build_prog,
                                  [ids_dev] + list(arrays),
                                  in_shardings, out_shardings, mesh,
                                  owner=comp)
        except Exception as e:
            _native_mesh_fallback(e)
            outs = None
        if outs is not None:
            return dict(zip(names, outs))

    cache = getattr(comp, "_tft_segfold_cache", None)
    if cache is None:
        cache = comp._tft_segfold_cache = OrderedDict()
    key = (mesh.mesh, axis, G,
           tuple((f, a.shape, str(a.dtype)) for f, a in zip(names, arrays)))
    fn = cache.get(key)
    if fn is not None:
        cache.move_to_end(key)
    else:
        fn = cache[key] = jax.jit(program)
        # G is data-dependent (distinct group counts), so bound the cache
        # like _collective_cache does
        while len(cache) > 16:
            cache.popitem(last=False)
    trace = current_trace()
    t0 = (_trace_shards(trace, "daggregate", mesh=mesh, arrays=arrays)
          if trace is not None else 0.0)
    with span("daggregate.segmented_fold_dispatch"):
        outs = fn(ids_dev, *arrays)
    counters.inc("mesh.dispatches")
    if trace is not None:
        _trace_mesh_done(trace, [outs[f] for f in names], t0,
                         "daggregate", mesh=mesh)
    return outs


def _generic_daggregate(fetches, dist: DistributedFrame, keys,
                        max_groups: Optional[int] = None) -> TensorFrame:
    """Arbitrary-computation keyed aggregation on the mesh.

    The distributed form of the reference's UDAF-inside-the-shuffle
    (``DebugRowOps.scala:587-681``), built from compiler-friendly pieces
    instead of a row shuffle:

    1. per shard (SPMD, inside one ``shard_map``): stable-sort local rows
       by group id (pad rows to the end), then one segmented
       ``jax.lax.associative_scan`` whose operator applies the user
       computation to a stacked two-row block when both elements share a
       group id — the fold of each contiguous segment lands on its last
       row (O(log rows) combiner applications, all vmapped);
    2. scatter each segment tail into a dense ``[groups, ...cell]`` partial
       table (+ a presence mask for groups absent on the shard);
    3. stack the tables over the data axis and fold them pairwise with the
       same two-row combiner, masked by presence;
    4. apply the computation once more over each group's single-row block —
       the host path's ``CompactionBuffer.evaluate`` always applies the
       computation at least once, so single-row groups must see it too.

    Legal for exactly the computations the host compaction path accepts:
    the combine must tolerate arbitrary regrouping of rows and partials
    (the UDAF contract, ``core.py:96-97``).
    """
    from ..schema import Field
    from ..shape import Unknown

    schema = dist.schema
    mesh = dist.mesh
    if dist.num_rows == 0:
        raise ValueError("aggregate on an empty distributed frame")
    value_schema = schema.select([m for m in schema.names if m not in keys])
    comp = _cached_reduce_computation(fetches, value_schema, ("_input",),
                                      block_level=True)
    _ops._validate_reduce(comp, value_schema, ("_input",), rank_delta=1)
    names = sorted(comp.output_names)

    # device-side keys (max_groups=): ids + group table built on the
    # mesh, the key column(s) never visit the host (composite keys
    # combine in the mixed-radix id space, _device_key_ids)
    ids_dev, uniques, uniq_dev, count_dev, table_groups = _cached_group_ids(
        dist, keys, max_groups)
    final = _segmented_fold(comp, names, mesh,
                            [dist.columns[f] for f in names],
                            ids_dev, table_groups)

    if max_groups is not None:
        cols, num_groups = _device_key_columns(dist, keys, uniq_dev,
                                               count_dev, max_groups)
    else:
        num_groups = table_groups
        cols = {k: u for k, u in zip(keys, uniques)}
    for f in names:
        v = np.asarray(final[f])[:num_groups]
        fld = schema[f]
        if v.dtype != fld.dtype.np_storage and fld.dtype is not _dt.bfloat16:
            v = v.astype(fld.dtype.np_storage)
        cols[f] = v
    out_fields = [schema[k] for k in keys] + [
        Field(s.name, s.dtype, block_shape=s.shape.prepend(Unknown),
              sql_rank=s.shape.ndim)
        for s in comp.outputs]
    return TensorFrame.from_blocks([Block(cols, num_groups)],
                                   Schema(out_fields))


def _generic_reduce(fetches, dist: DistributedFrame) -> Dict[str, np.ndarray]:
    """Generic (arbitrary-computation) mesh reduce, entirely on device.

    One compiled program: a ``shard_map`` stage runs the user block-reduce
    on every shard's local rows in parallel (SPMD — pad-only shards compute
    a garbage partial that is statically sliced away), the ragged tail
    shard's valid prefix is re-reduced on its own, and the partials are
    combined with one final stacked block-reduce. On the default jax
    dispatch the only host transfer is the final one-cell result — the
    reference's driver-collect analogue (``DebugRowOps.scala:511-512``),
    with the per-shard data never leaving its device. (Under
    ``TFT_EXECUTOR=pjrt`` the native route marshals the columns through
    host numpy per call — the documented correctness-proof trade,
    ``native_mesh`` module docstring.)
    """
    schema = dist.schema
    comp = _cached_reduce_computation(fetches, schema, ("_input",),
                                      block_level=True)
    _ops._validate_reduce(comp, schema, ("_input",), rank_delta=1)
    fetch_names = comp.output_names
    mesh = dist.mesh
    axis = mesh.data_axis
    shards = mesh.num_data_shards
    n = dist.num_rows
    if n == 0:
        raise ValueError("reduce on an empty distributed frame")
    rows_per = dist.padded_rows // shards
    full = n // rows_per          # shards whose rows are all valid
    tail = n - full * rows_per    # valid rows in the boundary shard

    names = sorted(fetch_names)
    arrays = [dist.columns[f] for f in names]

    if dist.shard_valid is not None:
        # multi-host frames pad per process, not in a global suffix — the
        # prefix slicing below cannot express that. Fold every valid row
        # into one group through the segmented-scan machinery instead.
        ids_host = np.where(dist.valid_row_mask(), 0, -1).astype(np.int32)
        ids_dev = jax.make_array_from_callback(
            (dist.padded_rows,), mesh.row_sharding(1),
            lambda idx: ids_host[idx])
        final_t = _segmented_fold(comp, names, mesh, arrays, ids_dev, 1)
        out = {}
        for f in fetch_names:
            v = np.asarray(final_t[f][0])
            fld = schema.get(f)
            if fld is not None and v.dtype != fld.dtype.np_storage \
                    and fld.dtype is not _dt.bfloat16:
                v = v.astype(fld.dtype.np_storage)
            out[f] = v
        return out
    cache = getattr(comp, "_tft_dreduce_cache", None)
    if cache is None:
        cache = comp._tft_dreduce_cache = {}
    key = (mesh.mesh, axis, n,
           tuple((f, a.shape, str(a.dtype)) for f, a in zip(names, arrays)))
    in_specs = tuple(P(axis, *([None] * (a.ndim - 1))) for a in arrays)
    # each shard emits its partial with a unit lead axis; stacking over
    # the data axis yields a (shards, *cell) global array
    out_specs = tuple(P(axis) for _ in names)

    def make_program():
        def shard_fn(*local):
            out = comp.fn(
                {f + "_input": s for f, s in zip(names, local)})
            return tuple(out[f][None] for f in names)

        def program(*cols):
            stacked = shard_map(shard_fn, mesh=mesh.mesh,
                                in_specs=in_specs,
                                out_specs=out_specs)(*cols)
            parts = {f: st[:full] for f, st in zip(names, stacked)}
            if tail:
                t = comp.fn({
                    f + "_input":
                        jax.lax.slice_in_dim(c, full * rows_per,
                                             full * rows_per + tail, axis=0)
                    for f, c in zip(names, cols)})
                parts = ({f: t[f][None] for f in names} if full == 0 else
                         {f: jnp.concatenate([parts[f], t[f][None]])
                          for f in names})
            return comp.fn({f + "_input": parts[f] for f in names})

        return program

    # TFT_EXECUTOR=pjrt: the whole generic reduce — per-shard partials,
    # the ragged-tail re-reduce, and the final stacked combine — compiles
    # as one GSPMD executable in the native C++ core
    final = None
    nm = _native_mesh(mesh)
    if nm is not None:
        def build_prog():
            program = make_program()

            def prog(*cols):
                out = program(*cols)
                return tuple(out[f] for f in names)
            return prog

        in_shardings = [mesh.row_sharding(a.ndim) for a in arrays]
        out_shardings = [mesh.replicated() for _ in names]
        try:
            outs = nm.run_sharded(("dreduce_generic",) + key, build_prog,
                                  arrays, in_shardings, out_shardings,
                                  mesh, owner=comp)
        except Exception as e:
            _native_mesh_fallback(e)
            outs = None
        if outs is not None:
            final = dict(zip(names, outs))
    if final is None:
        fn = cache.get(key)
        if fn is None:
            fn = cache[key] = jax.jit(make_program())
        trace = current_trace()
        t0 = (_trace_shards(trace, "dreduce_blocks", dist=dist)
              if trace is not None else 0.0)
        with span("dreduce_blocks.generic_dispatch"):
            final = fn(*arrays)
        counters.inc("mesh.dispatches")
        if trace is not None:
            _trace_mesh_done(trace, [final[f] for f in names], t0,
                             "dreduce_blocks", mesh=mesh)
    out = {}
    for f in fetch_names:
        v = np.asarray(final[f])
        fld = schema.get(f)
        if fld is not None and v.dtype != fld.dtype.np_storage \
                and fld.dtype is not _dt.bfloat16:
            v = v.astype(fld.dtype.np_storage)
        out[f] = v
    return out
