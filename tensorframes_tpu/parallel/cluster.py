"""Multi-host operation: process bootstrap + process-local distribution.

The reference genuinely spanned processes — a driver JVM plus N executor
JVMs, with partitions resident in executors and closures shipped over
Spark RPC (``DebugRowOps.scala:372-386``, ``ExperimentalOperations.scala:91``).
The TPU-native equivalent is JAX's multi-controller SPMD: every host runs
the same program, :func:`initialize` joins them into one cluster
(``jax.distributed``), and a :class:`~.mesh.DeviceMesh` built over the
GLOBAL device set makes the cross-host topology just another mesh — data
collectives ride ICI within a slice and DCN across hosts, with no
framework-level RPC at all.

:func:`distribute_local` is the executor-side entry: each process
contributes its OWN rows (the analogue of partitions already living in
that executor) and gets back a :class:`~.distributed.DistributedFrame`
whose columns are global arrays. Per-process padding is tracked with a
per-shard validity vector, so reductions and aggregations mask pad rows
wherever they fall — not just in a global suffix.

The 2-process CPU test (``tests/test_cluster.py``) runs dmap/dreduce/
daggregate end-to-end through this module; on TPU pods the same code runs
unchanged with ``initialize()`` reading the cluster env.
"""

from __future__ import annotations

from typing import Dict, Mapping, Optional, Sequence

import jax
import numpy as np

from .. import dtypes as _dt
from ..frame import TensorFrame
from ..resilience import (ClusterInitError, DeadlineExceeded, deadline,
                          default_policy, env_bool, env_float, faults,
                          is_transient, remaining_time)
from ..schema import Schema
from ..utils.compat import distributed_is_initialized
from ..utils.logging import get_logger
from ..utils.tracing import counters
from .distributed import DistributedFrame
from .mesh import DeviceMesh

__all__ = ["initialize", "cluster_mesh", "distribute_local",
           "process_index", "process_count", "process_identity"]

_log = get_logger("parallel.cluster")

# default bound on the whole bootstrap (connect + retries); jax's own
# default (300s) is tuned for pod schedulers, far too patient for the
# "coordinator address is simply wrong" failure mode at the heart of
# multi-process bring-up problems (TF-HPC, arXiv:1903.04364 §5)
_DEFAULT_BOOTSTRAP_TIMEOUT = 60.0


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               timeout: Optional[float] = None,
               **kwargs) -> bool:
    """Join this process to the cluster (idempotent). Returns True when
    the process is part of a multi-process cluster afterwards, False when
    it degraded to (or already was) single-process.

    Policy wrapper over ``jax.distributed.initialize``: explicit
    arguments win, otherwise ``TFT_COORDINATOR`` / ``TFT_NUM_PROCESSES`` /
    ``TFT_PROCESS_ID`` are read, otherwise jax's own autodetection (TPU
    pod metadata, SLURM, ...) runs. Call before the first jax operation.

    Robustness semantics (see ``docs/resilience.md``):

    - a partially-specified cluster env (e.g. a coordinator address with
      no process count) raises ``ValueError`` immediately instead of
      handing jax a spec that hangs;
    - the whole bootstrap is bounded by ``timeout`` (or
      ``TFT_BOOTSTRAP_TIMEOUT``, default 60s) and retried with backoff:
      an explicitly-configured cluster keeps retrying until that deadline
      (the coordinator may simply not be up yet), autodetection retries
      under the attempt-counted process policy (``TFT_RETRY_*`` knobs);
    - when the bootstrap still fails, the process degrades to a
      single-process mesh with a LOUD warning — unless
      ``TFT_REQUIRE_CLUSTER=1``, which turns degradation into a
      :class:`~..resilience.ClusterInitError` raised within the deadline.
    """
    import os

    if distributed_is_initialized():  # already up
        return jax.process_count() > 1

    coordinator_address = coordinator_address or os.environ.get(
        "TFT_COORDINATOR")
    if num_processes is None and os.environ.get("TFT_NUM_PROCESSES"):
        num_processes = int(os.environ["TFT_NUM_PROCESSES"])
    if process_id is None and os.environ.get("TFT_PROCESS_ID"):
        process_id = int(os.environ["TFT_PROCESS_ID"])

    spec = {"TFT_COORDINATOR / coordinator_address": coordinator_address,
            "TFT_NUM_PROCESSES / num_processes": num_processes,
            "TFT_PROCESS_ID / process_id": process_id}
    given = [k for k, v in spec.items() if v is not None]
    missing = [k for k, v in spec.items() if v is None]
    if given and missing:
        # a partial spec reaches jax.distributed as a malformed cluster
        # and surfaces as an opaque hang/grpc error; fail fast instead
        raise ValueError(
            f"partially-specified cluster environment: {given} set but "
            f"{missing} missing — set all three (or none, for "
            f"single-process / autodetection)")
    if coordinator_address is not None:
        # malformed addresses fail fast like the partial spec above —
        # retrying (or degrading on) a typo helps nobody
        _parse_hostport(coordinator_address)

    if timeout is None:
        timeout = env_float("TFT_BOOTSTRAP_TIMEOUT",
                            _DEFAULT_BOOTSTRAP_TIMEOUT)
    require_cluster = env_bool("TFT_REQUIRE_CLUSTER", False)
    if given:
        # an explicitly-configured cluster is retried until the bootstrap
        # deadline, not for an attempt count: connection-refused is
        # near-instant while the coordinator has not bound its port yet
        # (the normal worker-before-coordinator launch race), so a
        # 3-attempt budget would give up in milliseconds and split-brain
        # the job. The retry loop's deadline accounting ends the loop.
        policy = default_policy(max_attempts=1_000_000)
    else:
        # autodetection: a handful of tries is plenty — "no cluster
        # detected" answers quickly and is usually the final answer
        policy = default_policy()

    def attempt() -> None:
        faults.check("cluster_init")
        if distributed_is_initialized():
            return  # a slow earlier attempt won the race after all
        left = remaining_time()
        if coordinator_address is not None and process_id not in (None, 0):
            # probe the coordinator over plain TCP FIRST: on several
            # jaxlib versions a failed in-process connect ends in
            # LOG(FATAL) (the distributed client terminates the whole
            # process), which no Python-level retry could survive. A
            # refused/timed-out socket here raises ConnectionError /
            # TimeoutError — both transient, both retried.
            _probe_coordinator(coordinator_address,
                               min(left, 10.0) if left else 10.0)
        kw = dict(kwargs)
        if left is not None and "initialization_timeout" not in kw:
            # per-attempt bound: jax's own default (300s) would swallow
            # the whole budget in one try
            kw["initialization_timeout"] = max(1, int(left))
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id, **kw)

    try:
        with deadline(timeout):
            policy.call(attempt, op="cluster_init")
    except Exception as e:
        if require_cluster:
            counters.inc("cluster_init.failures")
            raise ClusterInitError(
                f"cluster bootstrap failed within {timeout}s and "
                f"TFT_REQUIRE_CLUSTER is set: {e}") from e
        if (not given and not isinstance(e, DeadlineExceeded)
                and not is_transient(e)):
            # nothing was configured and autodetection said "no cluster
            # here" — the normal single-process case, not a failure (no
            # counter). A TRANSIENT error that survived the retry budget
            # is different: a cluster was within reach and bootstrap
            # genuinely failed, which must be a loud degradation.
            _log.debug("no cluster detected (%s); running single-process",
                       e)
            return False
        counters.inc("cluster_init.failures")
        counters.inc("cluster_init.degraded")
        _log.warning(
            "DEGRADED TO SINGLE-PROCESS: cluster bootstrap failed (%s). "
            "Collectives will only span this process's devices; set "
            "TFT_REQUIRE_CLUSTER=1 to make this fatal instead.", e)
        return False
    return jax.process_count() > 1


def _parse_hostport(address: str):
    """``host:port`` / ``[v6]:port`` → ``(host, port)``; ``ValueError``
    on anything a socket connect could not use."""
    host, sep, port_s = address.rpartition(":")
    if host.startswith("[") and host.endswith("]"):
        host = host[1:-1]  # bracketed IPv6 literal
    try:
        port = int(port_s)
    except ValueError:
        port = -1
    if not sep or not 0 < port < 65536:
        raise ValueError(
            f"coordinator address {address!r} is not host:port")
    return host or "127.0.0.1", port


def _probe_coordinator(address: str, timeout: float) -> None:
    """One TCP connect to the coordinator, bounded by ``timeout``.

    Raises ``ConnectionError`` (refused/reset) or ``TimeoutError``
    (unroutable) — the transient classifications the retry loop expects.
    """
    import socket

    host, port = _parse_hostport(address)
    try:
        sock = socket.create_connection((host, port),
                                        timeout=max(timeout, 0.001))
    except socket.timeout as e:  # pre-3.10 spelling of TimeoutError
        raise TimeoutError(
            f"coordinator {address} unreachable within {timeout:.1f}s"
        ) from e
    except OSError as e:
        raise ConnectionError(
            f"coordinator {address} not accepting connections: {e}"
        ) from e
    sock.close()


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def process_identity() -> str:
    """A stable worker-id string for THIS process (``p<i>of<n>``).

    The serving fabric's per-process identity in real multi-process
    deployments: ``serve/fabric.py`` seeds worker ids from it and the
    flight recorder stamps it on records and dump headers
    (``TFT_FLIGHT_DUMP``), so per-process JSONL dumps merge
    unambiguously in ``tft.doctor()``. Safe before :func:`initialize`
    (a single uninitialized process is ``p0of1``)."""
    try:
        return f"p{jax.process_index()}of{jax.process_count()}"
    except Exception as e:
        _log.debug("process_identity before backend init: %s", e)
        return "p0of1"


def cluster_mesh(axis_names: Sequence[str] = ("data",),
                 shape: Optional[Sequence[int]] = None) -> DeviceMesh:
    """A mesh over the GLOBAL device set (every process's chips).

    The data axis must lead (``distribute_local`` relies on data-major
    device order to lay process rows contiguously).
    """
    devices = jax.devices()
    n = len(devices)
    if shape is None:
        shape = (n,) + (1,) * (len(axis_names) - 1)
    if int(np.prod(shape)) != n:
        raise ValueError(f"Mesh shape {shape} does not cover {n} devices")
    from jax.sharding import Mesh

    arr = np.array(devices).reshape(tuple(shape))
    return DeviceMesh(Mesh(arr, tuple(axis_names)),
                      data_axis=axis_names[0])


def _allgather_host_ints(values: Sequence[int]) -> np.ndarray:
    """Allgather small host ints across processes → [P, len(values)]."""
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(
        np.asarray(values, np.int64)))


def distribute_local(local: Mapping[str, np.ndarray] | TensorFrame,
                     mesh: DeviceMesh,
                     schema: Optional[Schema] = None) -> DistributedFrame:
    """Build a global :class:`DistributedFrame` from process-local rows.

    Every process calls this collectively with its OWN row block (local
    row counts may differ). Rows land process-contiguously in the global
    order; each process's block is zero-padded up to its shards, and the
    per-shard valid-row counts ride along so every reduction masks pads
    wherever they fall (``DistributedFrame.shard_valid``).
    """
    if isinstance(local, TensorFrame):
        from ..frame import Block

        merged = Block.concat(local.blocks(), local.schema)
        schema = local.schema
        cols_in: Dict[str, np.ndarray] = {
            f.name: merged.dense(f.name) for f in schema}
        n_local = merged.num_rows
    else:
        if schema is None:
            df = TensorFrame.from_columns(dict(local))
            schema = df.schema
        cols_in = {k: np.asarray(v) for k, v in local.items()}
        n_local = next(iter(cols_in.values())).shape[0] if cols_in else 0

    dev_mesh = mesh.mesh
    axis = mesh.data_axis
    if dev_mesh.axis_names[0] != axis:
        raise ValueError(
            f"distribute_local needs the data axis {axis!r} leading in the "
            f"mesh (axes: {dev_mesh.axis_names}) for process-contiguous "
            f"row layout")
    S = mesh.num_data_shards
    # process owning each data shard (data-major device order)
    shard_proc = [d.process_index
                  for d in dev_mesh.devices.reshape(S, -1)[:, 0]]
    my = jax.process_index()
    my_shards = [s for s in range(S) if shard_proc[s] == my]
    if not my_shards:
        raise ValueError(f"process {my} owns no data shards of {mesh!r}")

    counts = _allgather_host_ints([n_local])[:, 0]  # [P]
    # uniform rows-per-shard across the global mesh (XLA's equal-shard
    # world); sized for the largest process block
    per_proc_shards = {p: sum(1 for s in shard_proc if s == p)
                       for p in set(shard_proc)}
    rows_per = max(
        (int(counts[p]) + per_proc_shards[p] - 1) // per_proc_shards[p]
        for p in per_proc_shards)
    rows_per = max(rows_per, 1)

    # per-shard valid counts, globally (every process computes identically)
    shard_valid = np.zeros(S, np.int64)
    seen: Dict[int, int] = {p: 0 for p in per_proc_shards}
    for s in range(S):
        p = shard_proc[s]
        got = seen[p]
        shard_valid[s] = min(max(int(counts[p]) - got, 0), rows_per)
        seen[p] = got + rows_per
    num_rows = int(counts.sum())

    local_padded = len(my_shards) * rows_per
    columns: Dict[str, jax.Array] = {}
    for f in schema:
        a = cols_in[f.name]
        if not f.dtype.tensor:
            from .distributed import _host_side_column

            columns[f.name] = _host_side_column(a, f, local_padded)
            continue
        dd = _dt.device_dtype(f.dtype)
        if a.dtype != dd:
            from .. import native as _native
            a = _native.convert(np.ascontiguousarray(a), dd)
        if a.shape[0] != local_padded:
            pad = [(0, local_padded - a.shape[0])] + [(0, 0)] * (a.ndim - 1)
            a = np.pad(a, pad)
        sharding = mesh.row_sharding(a.ndim)
        global_shape = (S * rows_per,) + a.shape[1:]
        columns[f.name] = jax.make_array_from_process_local_data(
            sharding, a, global_shape)
    return DistributedFrame(mesh, schema, columns, num_rows,
                            shard_valid=shard_valid)
