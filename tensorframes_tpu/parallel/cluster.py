"""Multi-host operation: process bootstrap + process-local distribution.

The reference genuinely spanned processes — a driver JVM plus N executor
JVMs, with partitions resident in executors and closures shipped over
Spark RPC (``DebugRowOps.scala:372-386``, ``ExperimentalOperations.scala:91``).
The TPU-native equivalent is JAX's multi-controller SPMD: every host runs
the same program, :func:`initialize` joins them into one cluster
(``jax.distributed``), and a :class:`~.mesh.DeviceMesh` built over the
GLOBAL device set makes the cross-host topology just another mesh — data
collectives ride ICI within a slice and DCN across hosts, with no
framework-level RPC at all.

:func:`distribute_local` is the executor-side entry: each process
contributes its OWN rows (the analogue of partitions already living in
that executor) and gets back a :class:`~.distributed.DistributedFrame`
whose columns are global arrays. Per-process padding is tracked with a
per-shard validity vector, so reductions and aggregations mask pad rows
wherever they fall — not just in a global suffix.

The 2-process CPU test (``tests/test_cluster.py``) runs dmap/dreduce/
daggregate end-to-end through this module; on TPU pods the same code runs
unchanged with ``initialize()`` reading the cluster env.
"""

from __future__ import annotations

from typing import Dict, Mapping, Optional, Sequence

import jax
import numpy as np

from .. import dtypes as _dt
from ..frame import TensorFrame
from ..schema import Schema
from .distributed import DistributedFrame
from .mesh import DeviceMesh

__all__ = ["initialize", "cluster_mesh", "distribute_local",
           "process_index", "process_count"]


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               **kwargs) -> None:
    """Join this process to the cluster (idempotent).

    Thin policy wrapper over ``jax.distributed.initialize``: explicit
    arguments win, otherwise ``TFT_COORDINATOR`` / ``TFT_NUM_PROCESSES`` /
    ``TFT_PROCESS_ID`` are read, otherwise jax's own autodetection (TPU
    pod metadata, SLURM, ...) runs. Call before the first jax operation.
    """
    import os

    if jax.distributed.is_initialized():  # already up
        return
    coordinator_address = coordinator_address or os.environ.get(
        "TFT_COORDINATOR")
    if num_processes is None and os.environ.get("TFT_NUM_PROCESSES"):
        num_processes = int(os.environ["TFT_NUM_PROCESSES"])
    if process_id is None and os.environ.get("TFT_PROCESS_ID"):
        process_id = int(os.environ["TFT_PROCESS_ID"])
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id, **kwargs)


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def cluster_mesh(axis_names: Sequence[str] = ("data",),
                 shape: Optional[Sequence[int]] = None) -> DeviceMesh:
    """A mesh over the GLOBAL device set (every process's chips).

    The data axis must lead (``distribute_local`` relies on data-major
    device order to lay process rows contiguously).
    """
    devices = jax.devices()
    n = len(devices)
    if shape is None:
        shape = (n,) + (1,) * (len(axis_names) - 1)
    if int(np.prod(shape)) != n:
        raise ValueError(f"Mesh shape {shape} does not cover {n} devices")
    from jax.sharding import Mesh

    arr = np.array(devices).reshape(tuple(shape))
    return DeviceMesh(Mesh(arr, tuple(axis_names)),
                      data_axis=axis_names[0])


def _allgather_host_ints(values: Sequence[int]) -> np.ndarray:
    """Allgather small host ints across processes → [P, len(values)]."""
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(
        np.asarray(values, np.int64)))


def distribute_local(local: Mapping[str, np.ndarray] | TensorFrame,
                     mesh: DeviceMesh,
                     schema: Optional[Schema] = None) -> DistributedFrame:
    """Build a global :class:`DistributedFrame` from process-local rows.

    Every process calls this collectively with its OWN row block (local
    row counts may differ). Rows land process-contiguously in the global
    order; each process's block is zero-padded up to its shards, and the
    per-shard valid-row counts ride along so every reduction masks pads
    wherever they fall (``DistributedFrame.shard_valid``).
    """
    if isinstance(local, TensorFrame):
        from ..frame import Block

        merged = Block.concat(local.blocks(), local.schema)
        schema = local.schema
        cols_in: Dict[str, np.ndarray] = {
            f.name: merged.dense(f.name) for f in schema}
        n_local = merged.num_rows
    else:
        if schema is None:
            df = TensorFrame.from_columns(dict(local))
            schema = df.schema
        cols_in = {k: np.asarray(v) for k, v in local.items()}
        n_local = next(iter(cols_in.values())).shape[0] if cols_in else 0

    dev_mesh = mesh.mesh
    axis = mesh.data_axis
    if dev_mesh.axis_names[0] != axis:
        raise ValueError(
            f"distribute_local needs the data axis {axis!r} leading in the "
            f"mesh (axes: {dev_mesh.axis_names}) for process-contiguous "
            f"row layout")
    S = mesh.num_data_shards
    # process owning each data shard (data-major device order)
    shard_proc = [d.process_index
                  for d in dev_mesh.devices.reshape(S, -1)[:, 0]]
    my = jax.process_index()
    my_shards = [s for s in range(S) if shard_proc[s] == my]
    if not my_shards:
        raise ValueError(f"process {my} owns no data shards of {mesh!r}")

    counts = _allgather_host_ints([n_local])[:, 0]  # [P]
    # uniform rows-per-shard across the global mesh (XLA's equal-shard
    # world); sized for the largest process block
    per_proc_shards = {p: sum(1 for s in shard_proc if s == p)
                       for p in set(shard_proc)}
    rows_per = max(
        (int(counts[p]) + per_proc_shards[p] - 1) // per_proc_shards[p]
        for p in per_proc_shards)
    rows_per = max(rows_per, 1)

    # per-shard valid counts, globally (every process computes identically)
    shard_valid = np.zeros(S, np.int64)
    seen: Dict[int, int] = {p: 0 for p in per_proc_shards}
    for s in range(S):
        p = shard_proc[s]
        got = seen[p]
        shard_valid[s] = min(max(int(counts[p]) - got, 0), rows_per)
        seen[p] = got + rows_per
    num_rows = int(counts.sum())

    local_padded = len(my_shards) * rows_per
    columns: Dict[str, jax.Array] = {}
    for f in schema:
        a = cols_in[f.name]
        if not f.dtype.tensor:
            from .distributed import _host_side_column

            columns[f.name] = _host_side_column(a, f, local_padded)
            continue
        dd = _dt.device_dtype(f.dtype)
        if a.dtype != dd:
            from .. import native as _native
            a = _native.convert(np.ascontiguousarray(a), dd)
        if a.shape[0] != local_padded:
            pad = [(0, local_padded - a.shape[0])] + [(0, 0)] * (a.ndim - 1)
            a = np.pad(a, pad)
        sharding = mesh.row_sharding(a.ndim)
        global_shape = (S * rows_per,) + a.shape[1:]
        columns[f.name] = jax.make_array_from_process_local_data(
            sharding, a, global_shape)
    return DistributedFrame(mesh, schema, columns, num_rows,
                            shard_valid=shard_valid)
