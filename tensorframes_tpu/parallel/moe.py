"""Expert parallelism: a Switch-style top-1 MoE FFN, GSPMD-sharded.

The reference has no notion of experts (its only parallelism is Spark
partitions, SURVEY.md §2.3); this module exists because expert parallelism
is a first-class mesh axis in the TPU design. It is written the idiomatic
GSPMD way: the dispatch/combine are one-hot einsums (MXU work, no scatter),
the expert weights and the dispatched token buffer carry ``expert``-axis
sharding constraints, and **XLA inserts the all_to_all pair** between the
token-sharded and expert-sharded layouts — no hand-written collective, the
same lay-out-then-let-XLA recipe the rest of the framework uses.

Capacity semantics: each expert processes at most
``capacity = ceil(tokens/experts * capacity_factor)`` tokens; overflow
tokens are dropped (their FFN delta is zero — the residual connection
carries them through unchanged), the standard Switch-Transformer contract.

Router details: softmax gate, top-1 expert, position-in-expert by cumsum,
auxiliary load-balancing loss (mean gate mass x mean assignment share per
expert, scaled by E) returned alongside.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from .mesh import DeviceMesh

__all__ = ["init_switch_ffn", "switch_ffn"]


def init_switch_ffn(rng: jax.Array, d_model: int, d_ff: int,
                    num_experts: int, dtype=jnp.float32) -> Dict:
    kr, k1, k2 = jax.random.split(rng, 3)
    scale_in = np.sqrt(1.0 / d_model).astype(np.float32)
    scale_out = np.sqrt(1.0 / d_ff).astype(np.float32)
    return {
        "router": jax.random.normal(kr, (d_model, num_experts),
                                    jnp.float32) * scale_in,
        "w1": jax.random.normal(k1, (num_experts, d_model, d_ff),
                                dtype) * scale_in,
        "w2": jax.random.normal(k2, (num_experts, d_ff, d_model),
                                dtype) * scale_out,
    }


def switch_ffn(x: jax.Array, params: Dict,
               capacity_factor: float = 1.25,
               mesh: Optional[DeviceMesh] = None,
               expert_axis: Optional[str] = None,
               ) -> Tuple[jax.Array, jax.Array]:
    """Top-1 MoE FFN. ``x``: [T, D] tokens -> ([T, D], aux_loss).

    With ``mesh``+``expert_axis``, the [E, C, D] dispatched buffer and the
    [E, D, F]/[E, F, D] expert weights are constrained to the expert axis;
    tokens stay wherever their activations live (typically data-sharded).
    """
    T, D = x.shape
    E = params["w1"].shape[0]
    capacity = max(1, int(np.ceil(T / E * capacity_factor)))

    def c(a, *spec):
        if mesh is not None and expert_axis is not None:
            return jax.lax.with_sharding_constraint(
                a, jax.sharding.NamedSharding(mesh.mesh, P(*spec)))
        return a

    logits = x.astype(jnp.float32) @ params["router"]        # [T, E]
    gates = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(gates, axis=-1)                      # [T]
    gate = jnp.max(gates, axis=-1)                           # [T]

    onehot = jax.nn.one_hot(expert, E, dtype=jnp.float32)    # [T, E]
    # position of each token within its expert's queue (0-based)
    pos = jnp.cumsum(onehot, axis=0) * onehot - onehot       # [T, E]
    keep = (pos < capacity).astype(jnp.float32) * onehot
    pos_oh = jax.nn.one_hot(jnp.sum(pos, axis=-1).astype(jnp.int32),
                            capacity, dtype=jnp.float32)     # [T, C]
    dispatch = jnp.einsum("te,tc->tec", keep, pos_oh)        # [T, E, C]

    # Routing math stays f32 (cumsum counts, gate probabilities); the
    # expert matmuls run in the model dtype so bf16 keeps MXU throughput,
    # with f32 accumulation via preferred_element_type. The dispatch and
    # un-dispatch einsums are pure 0/1 token permutations (each
    # expert-capacity slot holds at most one token), so the model dtype is
    # exact for them; the continuous gate factor is applied afterwards per
    # token in f32 to avoid rounding the routing weights to bf16.
    cdt = x.dtype
    xs = jnp.einsum("td,tec->ecd", x, dispatch.astype(cdt))
    xs = c(xs, expert_axis, None, None)                      # all_to_all in
    w1 = c(params["w1"], expert_axis, None, None)
    w2 = c(params["w2"], expert_axis, None, None)
    h = jax.nn.gelu(jnp.einsum(
        "ecd,edf->ecf", xs, w1.astype(cdt),
        preferred_element_type=jnp.float32)).astype(cdt)
    ys = jnp.einsum("ecf,efd->ecd", h, w2.astype(cdt),
                    preferred_element_type=jnp.float32).astype(cdt)
    ys = c(ys, expert_axis, None, None)
    routed = jnp.einsum("ecd,tec->td", ys,
                        dispatch.astype(cdt))                # all_to_all out
    kept_gate = gate * jnp.sum(keep, axis=-1)  # 0 for dropped tokens
    out = routed.astype(jnp.float32) * kept_gate[:, None]

    # load-balancing auxiliary (Switch eq. 4): E * sum_e f_e * P_e
    density = jnp.mean(onehot, axis=0)                       # f_e
    gate_mass = jnp.mean(gates, axis=0)                      # P_e
    aux = E * jnp.sum(density * gate_mass)
    return out.astype(x.dtype), aux
