"""Columnar IO: parquet / pandas / npz in and out of TensorFrames.

The reference had no IO layer of its own — Spark WAS the loader, and
frames arrived as Catalyst DataFrames. A standalone TPU-native framework
needs its own ingestion story, and it must be columnar end to end: a
parquet row group is already the column-block layout ``TensorFrame``
wants, so reading maps row groups to partitions with zero row-at-a-time
work (the reference's convert/convertBack hot loop,
``DataOps.scala:158-283``, does not exist on this path at all).

Scope: scalar columns (float/double/int/long/bool/string),
fixed-size-list columns (vector cells), and variable-length list columns
— the latter load as RAGGED columns (one numpy cell per row, the
engine's in-memory ragged format: ``map_rows`` consumes them directly,
``pad_column`` densifies them for block ops; ``read_parquet(...,
pad_ragged=...)`` does that at load time).

All entry points are lazy-import (pyarrow/pandas only load when used) so
the core package stays dependency-light.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from .frame import TensorFrame

__all__ = ["read_parquet", "write_parquet", "from_pandas", "to_pandas",
           "read_npz", "write_npz", "read_csv", "write_csv"]


class _RaggedParts:
    """A variable-length list column decoded as its arrow buffers.

    Holds the flattened value buffer plus the per-row offsets — the
    columnar form the pad path consumes DIRECTLY (one vectorized scatter,
    no per-cell Python work: the reference's acknowledged per-row boxing
    weakness, ``DataOps.scala:30-33``, eliminated at the IO boundary).
    ``cells()`` materializes the engine's in-memory ragged format (one
    numpy view per row) for frames that stay ragged. Internal to
    :func:`read_parquet` — never escapes into a TensorFrame.
    """

    __slots__ = ("flat", "offs")

    def __init__(self, flat: np.ndarray, offs: np.ndarray):
        self.flat = flat
        self.offs = offs

    def __len__(self) -> int:
        return len(self.offs) - 1

    @property
    def lens(self) -> np.ndarray:
        return self.offs[1:] - self.offs[:-1]

    def cells(self) -> list:
        flat, offs = self.flat, self.offs
        return [flat[offs[i]:offs[i + 1]] for i in range(len(offs) - 1)]

    def pad(self, width: int, dtype) -> tuple:
        """-> (dense [rows, width], mask int32, lens int64), vectorized."""
        lens = self.lens
        r = len(lens)
        m = np.arange(width) < lens[:, None]
        dense = np.zeros((r, width), dtype)
        dense[m] = self.flat  # row-major fill == concatenated cell order
        return dense, m.astype(np.int32), lens.astype(np.int64)


def _column_to_numpy(col, name: str):
    """One pyarrow ChunkedArray/Array -> dense numpy column (or
    :class:`_RaggedParts` for variable-length list columns)."""
    import pyarrow as pa

    if isinstance(col, pa.ChunkedArray):
        col = col.combine_chunks()
    t = col.type
    if pa.types.is_fixed_size_list(t):
        flat = col.flatten().to_numpy(zero_copy_only=False)
        return np.asarray(flat).reshape(len(col), t.list_size)
    if pa.types.is_list(t) or pa.types.is_large_list(t):
        import pyarrow.compute as pc

        if col.null_count:
            raise ValueError(
                f"column {name!r}: {col.null_count} null list cell(s); "
                f"vector columns must be dense to load from parquet")
        lengths = pc.unique(pc.list_value_length(col)).to_pylist()
        if len(lengths) == 1:
            width = lengths[0]
            flat = col.flatten().to_numpy(zero_copy_only=False)
            return np.asarray(flat).reshape(len(col), width)
        # variable-length lists: keep the (values, offsets) buffer pair —
        # cells slice out lazily, and the pad path never makes cells
        flat = np.asarray(col.flatten().to_numpy(zero_copy_only=False))
        offs = np.asarray(col.offsets).astype(np.int64)
        return _RaggedParts(flat, offs)
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return np.asarray(col.to_pylist(), dtype=object)
    return col.to_numpy(zero_copy_only=False)


def _arrow_field_to_field(af):
    """Footer type -> schema Field matching what the EAGER decode would
    infer from materialized data, or ``None`` for types the lazy scan
    does not cover (variable-length lists, dates, decimals...)."""
    import pyarrow as pa

    from . import dtypes as _dt
    from .schema import Field
    from .shape import Shape, Unknown

    t = af.type
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return Field(af.name, _dt.string, sql_rank=0)
    if pa.types.is_fixed_size_list(t):
        try:
            dt = _dt.from_numpy(np.dtype(t.value_type.to_pandas_dtype()))
        except Exception:
            return None
        if not dt.tensor:
            return None
        return Field(af.name, dt,
                     block_shape=Shape(Unknown, t.list_size), sql_rank=1)
    if pa.types.is_floating(t) or pa.types.is_integer(t) \
            or pa.types.is_boolean(t):
        try:
            dt = _dt.from_numpy(np.dtype(t.to_pandas_dtype()))
        except Exception:
            return None
        return Field(af.name, dt, block_shape=Shape(Unknown), sql_rank=0)
    return None


def read_parquet(path: str, columns: Optional[Sequence[str]] = None,
                 num_partitions: Optional[int] = None,
                 pad_ragged=False,
                 row_group_offset: int = 0,
                 row_group_limit: Optional[int] = None) -> TensorFrame:
    """Read a parquet file into a TensorFrame, row groups → partitions.

    ``columns=`` projects at READ time: only the named columns' chunks
    are decoded (footer-driven — unrequested columns' bytes are never
    touched), composing with ``row_group_offset``/``row_group_limit``.

    ``num_partitions=None`` keeps the file's row-group structure (the
    natural block layout); an explicit value re-blocks after load.

    Variable-length list columns become RAGGED columns (usable by
    ``map_rows``/``pad_column`` directly). ``pad_ragged=True`` pads every
    ragged column at load (``pad_column`` semantics: dense ``[rows, L]``
    plus ``_mask``/``_len`` columns); a sequence of names pads just
    those.

    ``row_group_offset`` skips the first N row groups — only groups at
    index >= offset are read (one footer read, no data touched for the
    skipped groups); ``row_group_limit`` caps how many groups are read
    from there. The incremental-read primitives behind
    ``stream.ParquetTailSource``: a tail re-poll reads only what was
    appended, and a limit of 1 pinpoints an unreadable group. An offset
    at/past the end returns an EMPTY frame whose columns are still
    typed from the parquet schema.

    Files of scalar / fixed-size-list / string columns load LAZILY: only
    the footer is read here; data reads happen at forcing, which lets
    the logical plan (``docs/plan.md``) push column pruning into the
    read — a chain that references two of six columns touches two
    columns' bytes. The row-group range is pinned at footer time, so a
    concurrently-appended file never changes what a frame reads.
    ``pad_ragged`` or any other column type falls back to the eager
    read, byte-for-byte today's behavior.
    """
    import pyarrow as pa
    import pyarrow.parquet as pq

    if tuple(int(x) for x in pa.__version__.split(".")[:1]) < (11,):
        raise ImportError(
            f"read_parquet needs pyarrow >= 11 (found {pa.__version__}): "
            f"it relies on ParquetFile context management and "
            f"Schema.empty_table")
    if row_group_offset < 0:
        raise ValueError(
            f"row_group_offset must be >= 0, got {row_group_offset}")
    if row_group_limit is not None and row_group_limit < 1:
        raise ValueError(
            f"row_group_limit must be >= 1, got {row_group_limit}")

    with pq.ParquetFile(path) as pf:
        file_names = list(pf.schema_arrow.names)
        names = list(columns) if columns is not None else file_names
        missing = [n for n in names if n not in file_names]
        if missing:
            raise ValueError(
                f"read_parquet: column(s) {missing} not in {path!r}; "
                f"file columns: {file_names}")
        lazy = None
        if not pad_ragged:
            lazy = _lazy_parquet_frame(pf, path, names, num_partitions,
                                       row_group_offset, row_group_limit)
        if lazy is not None:
            return lazy
        # eager fallback reuses the already-open footer (one parse
        # per call, not two)
        return _read_parquet_eager(path, names, num_partitions,
                                   pad_ragged, row_group_offset,
                                   row_group_limit, pf=pf)


def _lazy_parquet_frame(pf, path, names, num_partitions,
                        row_group_offset, row_group_limit):
    """A lazy scan frame from the footer alone, or ``None`` when the
    file needs the eager decode (unsupported types, nothing to read)."""
    import weakref

    from .frame import _split_even
    from .plan.nodes import ParquetScanNode, attach
    from .schema import Schema

    fields = []
    for n in names:
        f = _arrow_field_to_field(pf.schema_arrow.field(n))
        if f is None:
            return None
        fields.append(f)
    if not fields:
        return None
    md = pf.metadata
    end_group = md.num_row_groups
    if row_group_limit is not None:
        end_group = min(end_group, row_group_offset + row_group_limit)
    n_groups = end_group - row_group_offset
    if n_groups < 1:
        return None  # empty range: the eager typed-empty frame is cheap
    # null policy: the eager decode materializes int/bool-with-nulls as
    # float64 NaN / object (pyarrow to_numpy), so a footer-typed schema
    # would silently disagree with the data. Floating scalars are safe
    # (null -> NaN, dtype unchanged); every other column must PROVE
    # zero nulls via chunk statistics, else the eager path decides.
    import pyarrow as pa
    lax_nulls = {n for n in names
                 if pa.types.is_floating(pf.schema_arrow.field(n).type)}
    rows = 0
    col_bytes = {n: 0 for n in names}
    want = set(names)
    for g in range(row_group_offset, end_group):
        rg = md.row_group(g)
        rows += rg.num_rows
        for j in range(rg.num_columns):
            c = rg.column(j)
            base = c.path_in_schema.split(".", 1)[0]
            if base not in want:
                continue
            col_bytes[base] += int(c.total_uncompressed_size)
            if base not in lax_nulls:
                stats = c.statistics
                if stats is None or stats.null_count is None \
                        or stats.null_count > 0:
                    return None
    if num_partitions is None:
        parts = n_groups
    else:
        parts = len(_split_even(rows, num_partitions))

    def thunk():
        return _read_parquet_eager(path, names, num_partitions, False,
                                   row_group_offset, n_groups).blocks()

    import os as _os
    frame = TensorFrame(
        Schema(fields), thunk, parts,
        plan=f"parquet({_os.path.basename(path)})",
        rows_hint=rows, bytes_hint=sum(col_bytes.values()),
        col_bytes_hint=col_bytes)
    node = ParquetScanNode(path, names, row_group_offset, n_groups,
                           num_partitions, frame.schema, rows, col_bytes)
    node.frame_ref = weakref.ref(frame)
    attach(frame, node)
    return frame


def _read_parquet_eager(path: str, columns: Optional[Sequence[str]],
                        num_partitions: Optional[int], pad_ragged,
                        row_group_offset: int,
                        row_group_limit: Optional[int],
                        pf=None) -> TensorFrame:
    """The materializing read (the pre-plan ``read_parquet`` body): row
    groups decode NOW, the returned frame's blocks already exist.
    ``pf`` reuses a caller's already-open ``ParquetFile`` (one footer
    parse per ``read_parquet`` call)."""
    import contextlib

    import pyarrow.parquet as pq

    with (pq.ParquetFile(path) if pf is None
          else contextlib.nullcontext(pf)) as pf:
        names = list(columns) if columns is not None else [
            c for c in pf.schema_arrow.names]
        blocks: List[dict] = []
        end_group = pf.num_row_groups
        if row_group_limit is not None:
            end_group = min(end_group, row_group_offset + row_group_limit)
        for rg in range(row_group_offset, end_group):
            tbl = pf.read_row_group(rg, columns=names)
            blocks.append({n: _column_to_numpy(tbl.column(n), n)
                           for n in names})
        if not blocks:
            # empty file: type the empty columns from the parquet schema,
            # not as float64
            empty = pf.schema_arrow.empty_table()
            blocks = [{n: _column_to_numpy(empty.column(n), n)
                       for n in names}]
    if not names:  # explicit empty selection: an empty frame
        return TensorFrame.from_columns({})
    ragged_names = [n for n in names
                    if any(isinstance(b[n], _RaggedParts) for b in blocks)]
    # which ragged columns pad at load (fused: straight from the arrow
    # buffers); non-ragged pad requests fall through to pad_column below
    if pad_ragged:
        to_pad = list(ragged_names) if pad_ragged is True else [
            n for n in pad_ragged]
    else:
        to_pad = []
    fused_pad = [n for n in to_pad if n in ragged_names]
    if not ragged_names:
        first = TensorFrame.from_columns(blocks[0])
        schema = first.schema
    else:
        # a row group whose lists HAPPEN to share one length decodes
        # dense; rebuild its (values, offsets) form so every block agrees
        for b in blocks:
            for n in ragged_names:
                c = b[n]
                if isinstance(c, np.ndarray):
                    w = c.shape[1] if c.ndim > 1 else 0
                    b[n] = _RaggedParts(
                        np.ascontiguousarray(c).reshape(-1),
                        np.arange(len(c) + 1, dtype=np.int64) * w)
        from . import dtypes as _dt
        from .schema import Field, Schema
        from .shape import Shape, Unknown

        # global pad width per fused column (what pad_column's length
        # scan computes, here from the offsets alone)
        widths = {n: max((int(b[n].lens.max()) if len(b[n]) else 0)
                         for b in blocks) for n in fused_pad}
        fields = []
        for n in names:
            if n in ragged_names:
                # dtype probe over ALL blocks: the first one may hold
                # only empty cells
                probe = next(
                    (b[n].flat for b in blocks if b[n].flat.size),
                    np.empty(0))
                dt = _dt.from_numpy(probe.dtype)
                if n in fused_pad:
                    fields.append(Field(
                        n, dt, block_shape=Shape(Unknown, widths[n]),
                        sql_rank=1))
                else:
                    fields.append(Field(n, dt, sql_rank=1))
            else:
                fields.append(
                    Schema.from_numpy_columns(
                        {n: blocks[0][n]}).fields[0])
        for n in fused_pad:  # mask/len fields append in pad order
            for extra in (f"{n}_mask", f"{n}_len"):
                if extra in names:
                    raise ValueError(f"Column {extra!r} already exists")
            fields.append(Field(f"{n}_mask", _dt.int32,
                                block_shape=Shape(Unknown, widths[n]),
                                sql_rank=1))
            fields.append(Field(f"{n}_len", _dt.int64,
                                block_shape=Shape(Unknown), sql_rank=0))
        schema = Schema(fields)
        for b in blocks:
            for n in names:
                c = b[n]
                if not isinstance(c, _RaggedParts):
                    continue
                if n in fused_pad:
                    dense, mask, lens = c.pad(widths[n],
                                              schema[n].dtype.np_storage)
                    b[n] = dense
                    b[f"{n}_mask"] = mask
                    b[f"{n}_len"] = lens
                else:
                    b[n] = c.cells()
    from .frame import Block

    out_names = schema.names
    fblocks = [Block({n: b[n] for n in out_names},
                     len(b[names[0]])) for b in blocks]
    first = TensorFrame.from_blocks(fblocks, schema)
    if num_partitions is not None:
        merged = Block.concat(first.blocks(), first.schema)
        from .frame import _split_even

        spans = _split_even(merged.num_rows, num_partitions)
        fblocks = [Block({n: merged.columns[n][a:b] for n in out_names},
                         b - a) for a, b in spans]
        first = TensorFrame.from_blocks(fblocks, schema)
    for n in to_pad:
        if n not in fused_pad:  # non-ragged pad request: pad_column path
            first = first.pad_column(n)
    return first


def _frame_block_to_table(b, schema):
    """One frame Block -> a pyarrow Table (shared by :func:`write_parquet`
    and the streaming ``ParquetSink`` appender)."""
    import pyarrow as pa

    arrays = {}
    for name in schema.names:
        if b.is_ragged(name):
            # ragged 1-d cells -> a variable-length list column
            cells = b.columns[name]
            if any(np.asarray(c).ndim != 1 for c in cells):
                raise ValueError(
                    f"column {name!r}: only 1-d ragged cells map "
                    f"to parquet lists")
            arrays[name] = pa.array(
                [np.asarray(c).tolist() for c in cells])
            continue
        a = b.dense(name)
        if a.ndim == 1:
            arrays[name] = pa.array(a.tolist() if a.dtype == object
                                    else a)
        elif a.ndim == 2:
            arrays[name] = pa.FixedSizeListArray.from_arrays(
                pa.array(a.reshape(-1)), a.shape[1])
        else:
            raise ValueError(
                f"column {name!r}: rank-{a.ndim} cells do not map "
                f"to parquet; flatten first")
    return pa.table(arrays)


def write_parquet(df: TensorFrame, path: str) -> None:
    """Write a TensorFrame to parquet, partitions → row groups."""
    import pyarrow.parquet as pq

    writer = None
    try:
        for b in df.blocks():
            tbl = _frame_block_to_table(b, df.schema)
            if writer is None:
                writer = pq.ParquetWriter(path, tbl.schema)
            writer.write_table(tbl)
    finally:
        if writer is not None:
            writer.close()


def from_pandas(pdf, num_partitions: int = 1) -> TensorFrame:
    """pandas DataFrame → TensorFrame (object/string dtypes pass through)."""
    cols = {}
    for name in pdf.columns:
        s = pdf[name]
        a = s.to_numpy()
        if a.dtype.kind in ("U", "S") or (
                a.dtype == object and len(a) and isinstance(a[0], str)):
            a = np.asarray(a, dtype=object)
        cols[str(name)] = a
    return TensorFrame.from_columns(cols, num_partitions=num_partitions)


def to_pandas(df: TensorFrame):
    """TensorFrame → pandas DataFrame (vector cells become object lists)."""
    import pandas as pd

    from .frame import Block

    merged = Block.concat(df.blocks(), df.schema)
    data = {}
    for name in df.schema.names:
        a = merged.dense(name)
        data[name] = list(a) if a.ndim > 1 else a
    return pd.DataFrame(data)


def read_npz(path: str, num_partitions: int = 1) -> TensorFrame:
    """Load a ``.npz`` archive as one column per entry."""
    with np.load(path, allow_pickle=False) as z:
        cols = {k: z[k] for k in z.files}
    return TensorFrame.from_columns(cols, num_partitions=num_partitions)


def write_npz(df: TensorFrame, path: str) -> None:
    from .frame import Block

    merged = Block.concat(df.blocks(), df.schema)
    cols = {}
    for n in df.schema.names:
        a = merged.dense(n)
        if a.dtype == object:
            raise ValueError(
                f"column {n!r}: string/object columns do not round-trip "
                f"through npz; use write_parquet, or select() them away")
        cols[n] = a
    # write through an open handle so np.savez cannot silently append
    # '.npz' and land at a different path than requested
    with open(path, "wb") as fh:
        np.savez(fh, **cols)


def read_csv(path: str, num_partitions: int = 1,
             columns: Optional[Sequence[str]] = None,
             dtypes: Optional[dict] = None) -> TensorFrame:
    """Load a CSV (header row required) as a TensorFrame.

    Parsing rides pandas (baked in); dtypes map through the same policy
    as :func:`from_pandas` — float/int/bool columns become tensor
    columns, everything else (strings) becomes object pass-through
    columns. ``dtypes`` (column -> numpy dtype) pins parse dtypes — e.g.
    ``{"key": "int32"}`` for columns that will become device-side group
    keys (x64 is off on TPU, so int64 keys would hit the narrowing
    guard).
    """
    import pandas as pd

    pdf = pd.read_csv(
        path, usecols=list(columns) if columns is not None else None,
        dtype=dtypes)
    if columns is not None:
        pdf = pdf[list(columns)]  # usecols returns file order; honor ours
    return from_pandas(pdf, num_partitions=num_partitions)


def write_csv(df: TensorFrame, path: str) -> None:
    """Write a frame of scalar columns as CSV (vector cells are rejected:
    CSV has no faithful encoding for them — use parquet)."""
    for f in df.schema:
        if f.sql_rank != 0:
            raise ValueError(
                f"column {f.name!r} holds rank-{f.sql_rank} cells; CSV "
                f"cannot represent tensor cells — use write_parquet")
    to_pandas(df).to_csv(path, index=False)
