"""Columnar IO: parquet / pandas / npz in and out of TensorFrames.

The reference had no IO layer of its own — Spark WAS the loader, and
frames arrived as Catalyst DataFrames. A standalone TPU-native framework
needs its own ingestion story, and it must be columnar end to end: a
parquet row group is already the column-block layout ``TensorFrame``
wants, so reading maps row groups to partitions with zero row-at-a-time
work (the reference's convert/convertBack hot loop,
``DataOps.scala:158-283``, does not exist on this path at all).

Scope (honest): scalar columns (float/double/int/long/bool/string) and
fixed-size-list columns (vector cells). Ragged lists are rejected with a
clear error — the engine's ragged support is for in-memory frames.

All entry points are lazy-import (pyarrow/pandas only load when used) so
the core package stays dependency-light.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from .frame import TensorFrame

__all__ = ["read_parquet", "write_parquet", "from_pandas", "to_pandas",
           "read_npz", "write_npz", "read_csv", "write_csv"]


def _column_to_numpy(col, name: str) -> np.ndarray:
    """One pyarrow ChunkedArray/Array -> dense numpy column."""
    import pyarrow as pa

    if isinstance(col, pa.ChunkedArray):
        col = col.combine_chunks()
    t = col.type
    if pa.types.is_fixed_size_list(t):
        flat = col.flatten().to_numpy(zero_copy_only=False)
        return np.asarray(flat).reshape(len(col), t.list_size)
    if pa.types.is_list(t) or pa.types.is_large_list(t):
        import pyarrow.compute as pc

        if col.null_count:
            raise ValueError(
                f"column {name!r}: {col.null_count} null list cell(s); "
                f"vector columns must be dense to load from parquet")
        lengths = pc.unique(pc.list_value_length(col)).to_pylist()
        if len(lengths) == 1:
            width = lengths[0]
            flat = col.flatten().to_numpy(zero_copy_only=False)
            return np.asarray(flat).reshape(len(col), width)
        raise ValueError(
            f"column {name!r}: ragged list values (lengths "
            f"{sorted(lengths)[:5]}...); only fixed-width vector columns "
            f"load from parquet")
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return np.asarray(col.to_pylist(), dtype=object)
    return col.to_numpy(zero_copy_only=False)


def read_parquet(path: str, columns: Optional[Sequence[str]] = None,
                 num_partitions: Optional[int] = None) -> TensorFrame:
    """Read a parquet file into a TensorFrame, row groups → partitions.

    ``num_partitions=None`` keeps the file's row-group structure (the
    natural block layout); an explicit value re-blocks after load.
    """
    import pyarrow as pa
    import pyarrow.parquet as pq

    if tuple(int(x) for x in pa.__version__.split(".")[:1]) < (11,):
        raise ImportError(
            f"read_parquet needs pyarrow >= 11 (found {pa.__version__}): "
            f"it relies on ParquetFile context management and "
            f"Schema.empty_table")
    with pq.ParquetFile(path) as pf:
        names = list(columns) if columns is not None else [
            c for c in pf.schema_arrow.names]
        blocks: List[dict] = []
        for rg in range(pf.num_row_groups):
            tbl = pf.read_row_group(rg, columns=names)
            blocks.append({n: _column_to_numpy(tbl.column(n), n)
                           for n in names})
        if not blocks:
            # empty file: type the empty columns from the parquet schema,
            # not as float64
            empty = pf.schema_arrow.empty_table()
            blocks = [{n: _column_to_numpy(empty.column(n), n)
                       for n in names}]
    first = TensorFrame.from_columns(blocks[0])
    if len(blocks) > 1:
        from .frame import Block

        schema = first.schema
        fblocks = [Block({n: b[n] for n in names},
                         len(next(iter(b.values())))) for b in blocks]
        first = TensorFrame.from_blocks(fblocks, schema)
    if num_partitions is not None:
        from .frame import Block as _B

        merged = _B.concat(first.blocks(), first.schema)
        cols = {n: merged.dense(n) for n in names}
        first = TensorFrame.from_columns(cols, schema=first.schema,
                                         num_partitions=num_partitions)
    return first


def write_parquet(df: TensorFrame, path: str) -> None:
    """Write a TensorFrame to parquet, partitions → row groups."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    writer = None
    try:
        for b in df.blocks():
            arrays = {}
            for name in df.schema.names:
                a = b.dense(name)
                if a.ndim == 1:
                    arrays[name] = pa.array(a.tolist() if a.dtype == object
                                            else a)
                elif a.ndim == 2:
                    arrays[name] = pa.FixedSizeListArray.from_arrays(
                        pa.array(a.reshape(-1)), a.shape[1])
                else:
                    raise ValueError(
                        f"column {name!r}: rank-{a.ndim} cells do not map "
                        f"to parquet; flatten first")
            tbl = pa.table(arrays)
            if writer is None:
                writer = pq.ParquetWriter(path, tbl.schema)
            writer.write_table(tbl)
    finally:
        if writer is not None:
            writer.close()


def from_pandas(pdf, num_partitions: int = 1) -> TensorFrame:
    """pandas DataFrame → TensorFrame (object/string dtypes pass through)."""
    cols = {}
    for name in pdf.columns:
        s = pdf[name]
        a = s.to_numpy()
        if a.dtype.kind in ("U", "S") or (
                a.dtype == object and len(a) and isinstance(a[0], str)):
            a = np.asarray(a, dtype=object)
        cols[str(name)] = a
    return TensorFrame.from_columns(cols, num_partitions=num_partitions)


def to_pandas(df: TensorFrame):
    """TensorFrame → pandas DataFrame (vector cells become object lists)."""
    import pandas as pd

    from .frame import Block

    merged = Block.concat(df.blocks(), df.schema)
    data = {}
    for name in df.schema.names:
        a = merged.dense(name)
        data[name] = list(a) if a.ndim > 1 else a
    return pd.DataFrame(data)


def read_npz(path: str, num_partitions: int = 1) -> TensorFrame:
    """Load a ``.npz`` archive as one column per entry."""
    with np.load(path, allow_pickle=False) as z:
        cols = {k: z[k] for k in z.files}
    return TensorFrame.from_columns(cols, num_partitions=num_partitions)


def write_npz(df: TensorFrame, path: str) -> None:
    from .frame import Block

    merged = Block.concat(df.blocks(), df.schema)
    cols = {}
    for n in df.schema.names:
        a = merged.dense(n)
        if a.dtype == object:
            raise ValueError(
                f"column {n!r}: string/object columns do not round-trip "
                f"through npz; use write_parquet, or select() them away")
        cols[n] = a
    # write through an open handle so np.savez cannot silently append
    # '.npz' and land at a different path than requested
    with open(path, "wb") as fh:
        np.savez(fh, **cols)


def read_csv(path: str, num_partitions: int = 1,
             columns: Optional[Sequence[str]] = None,
             dtypes: Optional[dict] = None) -> TensorFrame:
    """Load a CSV (header row required) as a TensorFrame.

    Parsing rides pandas (baked in); dtypes map through the same policy
    as :func:`from_pandas` — float/int/bool columns become tensor
    columns, everything else (strings) becomes object pass-through
    columns. ``dtypes`` (column -> numpy dtype) pins parse dtypes — e.g.
    ``{"key": "int32"}`` for columns that will become device-side group
    keys (x64 is off on TPU, so int64 keys would hit the narrowing
    guard).
    """
    import pandas as pd

    pdf = pd.read_csv(
        path, usecols=list(columns) if columns is not None else None,
        dtype=dtypes)
    if columns is not None:
        pdf = pdf[list(columns)]  # usecols returns file order; honor ours
    return from_pandas(pdf, num_partitions=num_partitions)


def write_csv(df: TensorFrame, path: str) -> None:
    """Write a frame of scalar columns as CSV (vector cells are rejected:
    CSV has no faithful encoding for them — use parquet)."""
    for f in df.schema:
        if f.sql_rank != 0:
            raise ValueError(
                f"column {f.name!r} holds rank-{f.sql_rank} cells; CSV "
                f"cannot represent tensor cells — use write_parquet")
    to_pandas(df).to_csv(path, index=False)
