"""Shape analysis: the deep-scan ``analyze`` and schema introspection.

Analogue of the reference's ``ExperimentalOperations`` /
``ExtraOperations.deepAnalyzeDataFrame``
(``/root/reference/src/main/scala/org/tensorframes/ExperimentalOperations.scala:34-156``):
walk the data partition by partition, derive every column's cell shape,
merge within a partition (dims that disagree become Unknown), prepend the
partition's row count, merge across partitions, and stamp the result into
the frame's schema metadata — after which block ops can run on non-scalar
columns without rescanning.

The columnar layout makes the scan cheap: a dense numpy column *is* its own
shape evidence (one ``.shape`` read per partition instead of a walk over
every cell); only ragged columns need the per-cell merge.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from .frame import Block, TensorFrame
from .schema import Field, Schema
from .shape import Shape, Unknown

__all__ = ["analyze", "print_schema", "explain"]


def _column_block_shape(block: Block, name: str) -> Optional[Shape]:
    """Block-level shape of one column in one partition, or None when the
    partition is empty (contributes no evidence)."""
    if block.num_rows == 0:
        return None
    col = block.columns[name]
    if isinstance(col, np.ndarray):
        return Shape(col.shape)
    # ragged: merge the per-cell shapes, then prepend the row count
    cell: Optional[Shape] = None
    for a in col:
        s = Shape(np.asarray(a).shape)
        if cell is None:
            cell = s
        else:
            merged = cell.merge(s)
            if merged is None:
                raise ValueError(
                    f"Column {name!r} mixes cell ranks "
                    f"({cell} vs {s}); not analyzable")
            cell = merged
    assert cell is not None
    return cell.prepend(block.num_rows)


def analyze(df: TensorFrame) -> TensorFrame:
    """Scan the data and return the same frame with tensor-shape metadata
    stamped on every column. Nullable/None cells are rejected by the
    marshalling layer. Eager (it is a full-data scan by design)."""
    blocks = df.blocks()
    fields: List[Field] = []
    for f in df.schema:
        if not f.dtype.tensor:
            fields.append(f)  # string etc: pass-through, no tensor shape
            continue
        shapes = [s for s in
                  (_column_block_shape(b, f.name) for b in blocks)
                  if s is not None]
        if not shapes:
            # no data: only the scalar default survives
            fields.append(f if f.block_shape is not None
                          else f.with_block_shape(Shape(Unknown)))
            continue
        acc = shapes[0]
        for s in shapes[1:]:
            merged = acc.merge(s)
            if merged is None:
                raise ValueError(
                    f"Column {f.name!r} has incompatible shapes across "
                    f"partitions ({acc} vs {s})")
            acc = merged
        # the lead dim is per-partition row count; it only stays concrete
        # when every partition agrees (merge() already handles that)
        fields.append(f.with_block_shape(acc))
    return df.with_schema(Schema(fields))


def explain(df: TensorFrame) -> str:
    """Pretty-print the frame's tensor info (DataFrameInfo.explain
    analogue, reference ``DataFrameInfo.scala:24-38``).

    This is the SCHEMA description (reference-parity surface). For the
    execution report of a forcing — rows/blocks/bytes, retries, wall
    time by stage — use the method ``df.explain()``
    (``docs/observability.md``)."""
    lines = [f"TensorFrame with {len(df.schema)} column(s), "
             f"{df.num_partitions} partition(s):"]
    for f in df.schema:
        lines.append(" " + f.describe())
    return "\n".join(lines)


def print_schema(df: TensorFrame) -> None:
    """Print the schema including tensor metadata
    (reference ``core.py:258-267``)."""
    print(df.schema.tree_string())
