"""Logging shim for tensorframes-tpu.

The reference carries a tiny logging facade (``Logging.scala:5-9`` —
``logDebug/logInfo/logTrace`` over scala-logging/slf4j), a packaged log4j
config defaulting the framework's package to DEBUG
(``src/main/resources/org/tensorframes/log4j.properties:1-7``), and a
Python-side ``initialize_logging`` that repairs PySpark's log4j
misconfiguration (``PythonInterface.scala:26-41``, ``core.py:14``). The
TPU-native equivalents here:

 - every module grabs a child of the ``tensorframes_tpu`` logger via
   :func:`get_logger` (the ``Logging`` trait analogue);
 - :func:`initialize_logging` installs a handler/format once and sets the
   framework level — callable by users the way PySpark users called
   ``tfs.core._java_api().initialize_logging()``;
 - a TRACE level below DEBUG mirrors the reference's ``logTrace`` narration
   of marshalling hot loops (``datatypes.scala:280-284``).
"""

from __future__ import annotations

import logging
import os
import sys
from typing import Optional

__all__ = ["TRACE", "get_logger", "initialize_logging", "set_level"]

# slf4j has TRACE below DEBUG; python logging does not. Register it once.
TRACE = 5
if logging.getLevelName(TRACE) != "TRACE":
    logging.addLevelName(TRACE, "TRACE")

_ROOT_NAME = "tensorframes_tpu"
_initialized = False
_handler: Optional[logging.StreamHandler] = None


def _trace(self: logging.Logger, msg, *args, **kwargs):
    """The ``logTrace`` analogue, bound onto framework loggers."""
    if self.isEnabledFor(TRACE):
        self._log(TRACE, msg, args, **kwargs)


def _framework_logger(name: str) -> logging.Logger:
    logger = logging.getLogger(name)
    if not hasattr(logger, "trace"):
        logger.trace = _trace.__get__(logger)
    return logger


_root_logger = _framework_logger(_ROOT_NAME)


def get_logger(name: Optional[str] = None) -> logging.Logger:
    """Return the framework logger or a child of it.

    ``get_logger("engine.executor")`` -> ``tensorframes_tpu.engine.executor``.
    Child loggers inherit the level/handler installed by
    :func:`initialize_logging` and carry a ``trace`` method (slf4j's level
    below DEBUG).
    """
    if not name or name == _ROOT_NAME:
        return _root_logger
    if name.startswith(_ROOT_NAME + "."):
        name = name[len(_ROOT_NAME) + 1:]
    return _framework_logger(_ROOT_NAME + "." + name)


def initialize_logging(level: Optional[int] = None,
                       stream=None) -> logging.Logger:
    """Install a stderr handler + format on the framework logger (idempotent).

    Level resolution order: explicit ``level`` arg, the ``TFT_LOG_LEVEL``
    environment variable (name or number), else WARNING — the packaged
    default config analogue (the reference ships DEBUG in its log4j
    properties; we default quieter and let tests/users opt in).
    """
    global _initialized, _handler
    if level is None:
        env = os.environ.get("TFT_LOG_LEVEL")
        if env:
            known = getattr(logging, env.upper(), None)
            if isinstance(known, int):
                level = known
            elif env.upper() == "TRACE":
                level = TRACE
            else:
                try:
                    level = int(env)
                except ValueError:
                    _root_logger.warning(
                        "unrecognized TFT_LOG_LEVEL=%r; using WARNING", env)
                    level = logging.WARNING
        else:
            level = logging.WARNING
    if not _initialized:
        _handler = logging.StreamHandler(stream or sys.stderr)
        _handler.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s %(name)s: %(message)s",
            datefmt="%H:%M:%S"))
        _root_logger.addHandler(_handler)
        _root_logger.propagate = False
        _initialized = True
    elif stream is not None:
        _handler.setStream(stream)  # re-init with a new sink: honor it
    _root_logger.setLevel(level)
    return _root_logger


def set_level(level) -> None:
    """Set the framework log level (accepts names, including "TRACE")."""
    if isinstance(level, str):
        name = level.upper()
        if name == "TRACE":
            level = TRACE
        else:
            level = getattr(logging, name, None)
            if not isinstance(level, int):
                raise ValueError(
                    f"Unknown log level {name!r}; expected one of "
                    f"TRACE, DEBUG, INFO, WARNING, ERROR, CRITICAL")
    _root_logger.setLevel(level)
