"""Backend-selection plumbing shared by benchmarks, demos, and scripts.

This image's sitecustomize registers the tunnelled-TPU platform via
``jax.config`` at interpreter start, OVERRIDING the ``JAX_PLATFORMS`` env
var — so any entry point that should honor an explicit CPU request must
force the config back after importing jax, before first backend use. One
helper, so the workaround cannot drift.
"""

from __future__ import annotations

import os

__all__ = ["force_cpu_if_requested"]


def force_cpu_if_requested() -> None:
    """Honor ``JAX_PLATFORMS=cpu`` from the environment (call after
    ``import jax``, before any backend use)."""
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
