"""Tracing / profiling hooks.

The reference has no tracer — only ``logDebug`` narration along the
execution path and self-timed perf suites (``System.nanoTime``,
``perf/ConvertPerformanceSuite.scala:44-53``; SURVEY.md §5). The TPU-native
replacement is real instrumentation:

 - :func:`span` — a context manager timing a named stage on the host AND
   annotating it into the XLA device trace via
   ``jax.profiler.TraceAnnotation``, so host stages line up with device ops
   in the profiler UI;
 - :class:`Timings` — a process-wide registry of per-stage statistics
   (count / total / min / max seconds), the structured replacement for the
   reference's log-line narration; the engine's hot stages (validate,
   convert, execute, convertBack) report here;
 - :func:`profile` — wraps ``jax.profiler.start_trace/stop_trace`` for a
   whole-program device trace dump viewable in TensorBoard/XProf.

All hooks are zero-cost-when-off: ``span`` skips stat collection and device
annotation unless tracing is enabled (it is during :func:`profile`, under
``TFT_TRACE=1``, or after :func:`enable`).
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Dict, Iterator, Optional

from .logging import get_logger

__all__ = ["Timings", "timings", "Counters", "counters", "span", "gauge",
           "enable", "disable", "enabled", "profile"]

_log = get_logger("utils.tracing")


class _Stat:
    __slots__ = ("count", "total", "min", "max")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = 0.0

    def add(self, dt: float):
        self.count += 1
        self.total += dt
        if dt < self.min:
            self.min = dt
        if dt > self.max:
            self.max = dt

    def as_dict(self) -> Dict[str, float]:
        return {"count": self.count, "total_s": self.total,
                "mean_s": self.total / self.count if self.count else 0.0,
                "min_s": self.min if self.count else 0.0, "max_s": self.max}


class Timings:
    """Thread-safe per-stage timing registry."""

    def __init__(self):
        self._stats: Dict[str, _Stat] = {}
        self._lock = threading.Lock()

    def add(self, name: str, dt: float) -> None:
        with self._lock:
            stat = self._stats.get(name)
            if stat is None:
                stat = self._stats[name] = _Stat()
            stat.add(dt)

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {k: v.as_dict() for k, v in self._stats.items()}

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()

    def report(self) -> str:
        snap = self.snapshot()
        if not snap:
            return "(no spans recorded; enable tracing first)"
        width = max(len(k) for k in snap)
        lines = ["%-*s %8s %12s %12s" % (width, "span", "count",
                                         "total_s", "mean_s")]
        for name in sorted(snap, key=lambda k: -snap[k]["total_s"]):
            s = snap[name]
            lines.append("%-*s %8d %12.6f %12.6f"
                         % (width, name, s["count"], s["total_s"], s["mean_s"]))
        return "\n".join(lines)


timings = Timings()


class Counters:
    """Thread-safe named event counters (retries, giveups, fallbacks).

    Unlike :class:`Timings` spans these are ALWAYS on: the resilience
    layer's retry/giveup counts must be observable after the fact even
    when span timing was disabled during the failure (the moment you most
    want them). Incrementing an int under a lock is cheap enough.
    """

    def __init__(self):
        self._counts: Dict[str, int] = {}
        self._lock = threading.Lock()

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + n

    def get(self, name: str) -> int:
        with self._lock:
            return self._counts.get(name, 0)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()


counters = Counters()

_enabled = os.environ.get("TFT_TRACE", "") not in ("", "0", "false")


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def enabled() -> bool:
    return _enabled


def _device_annotation(name: str):
    try:
        import jax.profiler
        return jax.profiler.TraceAnnotation(name)
    except Exception:  # profiler unavailable on some backends
        return contextlib.nullcontext()


@contextlib.contextmanager
def span(name: str) -> Iterator[None]:
    """Time a named stage; no-op (two dict lookups) when tracing is off."""
    if not _enabled:
        yield
        return
    with _device_annotation(name):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            timings.add(name, dt)
            _log.trace("span %s: %.6fs", name, dt)


def gauge(name: str, value: float) -> None:
    """Sample a dimensionless value into the :data:`timings` registry.

    Same zero-cost-when-off contract as :func:`span`, but for quantities
    that are levels rather than durations — e.g. the pipelined engine
    samples its in-flight window size into ``pipeline.occupancy`` at every
    submit, so ``timings.snapshot()['pipeline.occupancy']['mean_s']`` reads
    as the mean window occupancy (the ``_s`` suffix is vestigial for
    gauges). No-op unless tracing is enabled.
    """
    if _enabled:
        timings.add(name, float(value))


@contextlib.contextmanager
def profile(log_dir: str, host_spans: bool = True) -> Iterator[None]:
    """Capture a full XLA device trace to ``log_dir`` (TensorBoard format).

    Also enables host spans for the duration so the :data:`timings` registry
    covers the same window.
    """
    import jax

    was = _enabled
    jax.profiler.start_trace(log_dir)  # before enable(): a failure here
    if host_spans:                     # must not leave spans on forever
        enable()
    try:
        yield
    finally:
        if not was:
            disable()
        jax.profiler.stop_trace()
        _log.info("profile written to %s", log_dir)
