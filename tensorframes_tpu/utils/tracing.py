"""Tracing / profiling hooks.

The reference has no tracer — only ``logDebug`` narration along the
execution path and self-timed perf suites (``System.nanoTime``,
``perf/ConvertPerformanceSuite.scala:44-53``; SURVEY.md §5). The TPU-native
replacement is real instrumentation:

 - :func:`span` — a context manager timing a named stage on the host AND
   annotating it into the XLA device trace via
   ``jax.profiler.TraceAnnotation``, so host stages line up with device ops
   in the profiler UI;
 - :class:`Timings` — a process-wide registry of per-stage statistics
   (count / total / min / max seconds) plus dimensionless gauges, the
   structured replacement for the reference's log-line narration; the
   engine's hot stages (validate, convert, execute, convertBack) report
   here;
 - :func:`profile` — wraps ``jax.profiler.start_trace/stop_trace`` for a
   whole-program device trace dump viewable in TensorBoard/XProf.

All hooks are zero-cost-when-off: ``span`` skips stat collection and device
annotation unless tracing is enabled (it is during :func:`profile`, under
``TFT_TRACE=1``, or after :func:`enable`).

Per-QUERY attribution (which query's block 17, which query's retry) lives
one layer up in :mod:`tensorframes_tpu.observability`, which registers a
span observer here (:func:`set_span_observer`) so every span is also
credited to the active query trace.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Callable, Dict, Iterator, Optional

from .logging import get_logger

__all__ = ["Timings", "timings", "Counters", "counters", "Histograms",
           "histograms", "span", "gauge", "enable", "disable", "enabled",
           "profile", "dump_stats", "set_span_observer"]

_log = get_logger("utils.tracing")


class _Stat:
    __slots__ = ("count", "total", "min", "max")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = 0.0

    def add(self, dt: float):
        self.count += 1
        self.total += dt
        if dt < self.min:
            self.min = dt
        if dt > self.max:
            self.max = dt

    def as_dict(self) -> Dict[str, float]:
        return {"count": self.count, "total_s": self.total,
                "mean_s": self.total / self.count if self.count else 0.0,
                "min_s": self.min if self.count else 0.0, "max_s": self.max}


class _GaugeStat:
    """Stats for a sampled LEVEL (window occupancy, queue depth): gauges
    are dimensionless, so their stat keys carry no ``_s`` unit suffix and
    they track ``last`` (the newest sample) instead of ``total``."""

    __slots__ = ("count", "total", "min", "max", "last")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self.last = 0.0

    def add(self, value: float):
        self.count += 1
        self.total += value
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value
        self.last = value

    def as_dict(self) -> Dict[str, float]:
        mean = self.total / self.count if self.count else 0.0
        return {"count": self.count, "mean": mean,
                "min": self.min if self.count else 0.0,
                "max": self.max if self.count else 0.0,
                "last": self.last}


class Timings:
    """Thread-safe per-stage timing registry (+ gauge samples)."""

    def __init__(self):
        self._stats: Dict[str, _Stat] = {}
        self._gauges: Dict[str, _GaugeStat] = {}
        self._lock = threading.Lock()

    def add(self, name: str, dt: float) -> None:
        with self._lock:
            stat = self._stats.get(name)
            if stat is None:
                stat = self._stats[name] = _Stat()
            stat.add(dt)

    def add_gauge(self, name: str, value: float) -> None:
        with self._lock:
            stat = self._gauges.get(name)
            if stat is None:
                stat = self._gauges[name] = _GaugeStat()
            stat.add(value)

    def spans_snapshot(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {k: v.as_dict() for k, v in self._stats.items()}

    def gauges_snapshot(self) -> Dict[str, Dict[str, float]]:
        # gauge entries carry ONLY the unit-less stat keys
        # (mean/min/max/last). The pre-0.2 duration-suffixed aliases
        # (`mean_s`/...) that `pipeline.occupancy` kept for one release
        # are gone as scheduled.
        with self._lock:
            return {k: v.as_dict() for k, v in self._gauges.items()}

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """Spans and gauges in one dict; span entries use ``*_s`` keys,
        gauge entries unit-less ``mean``/``min``/``max``/``last``."""
        out = self.spans_snapshot()
        out.update(self.gauges_snapshot())
        return out

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()
            self._gauges.clear()

    def report(self, include_counters: bool = True) -> str:
        """One merged human-readable report: spans, gauges, and (by
        default) the always-on :data:`counters`."""
        spans = self.spans_snapshot()
        gauges = self.gauges_snapshot()
        counts = counters.snapshot() if include_counters else {}
        if not spans and not gauges and not counts:
            return "(no spans recorded; enable tracing first)"
        lines = []
        if spans:
            width = max(len(k) for k in spans)
            lines.append("%-*s %8s %12s %12s" % (width, "span", "count",
                                                 "total_s", "mean_s"))
            for name in sorted(spans, key=lambda k: -spans[k]["total_s"]):
                s = spans[name]
                lines.append("%-*s %8d %12.6f %12.6f"
                             % (width, name, s["count"], s["total_s"],
                                s["mean_s"]))
        else:
            lines.append("(no spans recorded; enable tracing first)")
        if gauges:
            width = max(len(k) for k in gauges)
            lines.append("")
            lines.append("%-*s %8s %12s %12s %12s" % (width, "gauge",
                                                      "count", "mean",
                                                      "max", "last"))
            for name in sorted(gauges):
                g = gauges[name]
                lines.append("%-*s %8d %12.4f %12.4f %12.4f"
                             % (width, name, g["count"], g["mean"],
                                g["max"], g["last"]))
        if counts:
            width = max(len(k) for k in counts)
            lines.append("")
            lines.append("%-*s %8s" % (width, "counter", "value"))
            for name in sorted(counts):
                lines.append("%-*s %8d" % (width, name, counts[name]))
        return "\n".join(lines)


timings = Timings()


class Counters:
    """Thread-safe named event counters (retries, giveups, fallbacks).

    Unlike :class:`Timings` spans these are ALWAYS on: the resilience
    layer's retry/giveup counts must be observable after the fact even
    when span timing was disabled during the failure (the moment you most
    want them). Incrementing an int under a lock is cheap enough.
    """

    def __init__(self):
        self._counts: Dict[str, int] = {}
        self._lock = threading.Lock()

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + n

    def get(self, name: str) -> int:
        with self._lock:
            return self._counts.get(name, 0)

    def get_many(self, names) -> Dict[str, int]:
        """Read several counters under one lock acquisition without
        copying the whole registry (the per-query cost capture reads a
        fixed family set on every serve completion)."""
        with self._lock:
            g = self._counts.get
            return {n: g(n, 0) for n in names}

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()


counters = Counters()


# Default histogram buckets (seconds): spans compile times (sub-ms jit
# cache-assembly on reuse up to tens of seconds for a first TPU compile)
# and per-query latencies. Cumulative `le` semantics are applied at
# render time (observability.metrics); here each bucket holds its own
# non-cumulative count.
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)


class _Hist:
    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, buckets):
        self.buckets = tuple(buckets)
        self.counts = [0] * (len(self.buckets) + 1)  # +1: the +Inf bucket
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        i = 0
        for i, le in enumerate(self.buckets):
            if value <= le:
                break
        else:
            i = len(self.buckets)  # +Inf
        self.counts[i] += 1
        self.sum += value
        self.count += 1

    def as_dict(self) -> Dict[str, object]:
        return {"les": self.buckets + (float("inf"),),
                "counts": list(self.counts),
                "sum": self.sum, "count": self.count}


class Histograms:
    """Thread-safe histogram registry (Prometheus-style bucketed counts).

    ALWAYS on, like :class:`Counters` — the observation sites are rare
    events (a compile-cache miss, a finished query), so one lock + one
    bucket increment per observation never shows up on a hot path.
    Keyed by ``(family, labels)``: one family (e.g. ``compile_seconds``)
    renders as one Prometheus histogram metric with one ``le`` series per
    label set.
    """

    def __init__(self):
        self._hists: Dict[tuple, _Hist] = {}
        self._lock = threading.Lock()

    def observe(self, family: str, value: float, buckets=None,
                **labels) -> None:
        key = (family, tuple(sorted(labels.items())))
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = _Hist(buckets or DEFAULT_BUCKETS)
            h.observe(float(value))

    def snapshot(self) -> Dict[tuple, Dict[str, object]]:
        with self._lock:
            return {k: v.as_dict() for k, v in self._hists.items()}

    def family_sum(self, family: str) -> float:
        """Summed observations across every label set of one family,
        without materializing bucket copies (the per-query cost capture
        reads the ``compile_seconds`` total on every serve completion)."""
        with self._lock:
            return sum(h.sum for (fam, _), h in self._hists.items()
                       if fam == family)

    def reset(self) -> None:
        with self._lock:
            self._hists.clear()


histograms = Histograms()


def dump_stats(file=None) -> None:
    """Print spans + gauges + counters in one report (the quick "what did
    that run do" convenience; ``tft.dump_stats()``)."""
    print(timings.report(include_counters=True), file=file)


_enabled = os.environ.get("TFT_TRACE", "") not in ("", "0", "false")


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def enabled() -> bool:
    return _enabled


# the observability layer's per-query stage attribution: called as
# (name, dt_seconds) at the end of every recorded span. One slot, set
# once at import of tensorframes_tpu.observability.
_span_observer: Optional[Callable[[str, float], None]] = None


def set_span_observer(fn: Optional[Callable[[str, float], None]]) -> None:
    global _span_observer
    _span_observer = fn


def _device_annotation(name: str):
    try:
        import jax.profiler
        return jax.profiler.TraceAnnotation(name)
    except Exception:  # profiler unavailable on some backends
        return contextlib.nullcontext()


@contextlib.contextmanager
def span(name: str) -> Iterator[None]:
    """Time a named stage; no-op (two dict lookups) when tracing is off.

    Monotonic-safe against the device annotation: a trace-annotation
    context that fails on entry or exit (some backends raise once the
    profiler session is torn down) can neither lose the host timing nor
    mask the body's own exception — annotation failures are logged and
    swallowed.
    """
    if not _enabled:
        yield
        return
    ann = _device_annotation(name)
    try:
        ann.__enter__()
    except Exception as e:  # annotation is best-effort decoration
        _log.debug("trace annotation enter failed for %s: %s", name, e)
        ann = None
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        timings.add(name, dt)
        obs = _span_observer
        if obs is not None:
            obs(name, dt)
        _log.trace("span %s: %.6fs", name, dt)
        if ann is not None:
            try:
                ann.__exit__(None, None, None)
            except Exception as e:
                _log.debug("trace annotation exit failed for %s: %s",
                           name, e)


def gauge(name: str, value: float) -> None:
    """Sample a dimensionless value into the :data:`timings` registry.

    Same zero-cost-when-off contract as :func:`span`, but for quantities
    that are levels rather than durations — e.g. the pipelined engine
    samples its in-flight window size into ``pipeline.occupancy`` at every
    submit, so ``timings.snapshot()['pipeline.occupancy']['mean']`` reads
    as the mean window occupancy. Gauges keep their own stat family
    (``mean``/``min``/``max``/``last``, no seconds suffix). No-op unless
    tracing is enabled.
    """
    if _enabled:
        timings.add_gauge(name, float(value))


@contextlib.contextmanager
def profile(log_dir: str, host_spans: bool = True) -> Iterator[None]:
    """Capture a full XLA device trace to ``log_dir`` (TensorBoard format).

    Also enables host spans for the duration so the :data:`timings` registry
    covers the same window. A failing ``stop_trace`` (a torn-down or
    double-stopped profiler session) is logged, never raised — it must not
    mask an exception from the profiled body, nor fail a body that
    succeeded.
    """
    import jax

    was = _enabled
    jax.profiler.start_trace(log_dir)  # before enable(): a failure here
    if host_spans:                     # must not leave spans on forever
        enable()
    try:
        yield
    finally:
        if not was:
            disable()
        try:
            jax.profiler.stop_trace()
        except Exception as e:
            _log.error("jax.profiler.stop_trace() failed (trace in %s "
                       "may be incomplete): %s", log_dir, e)
        else:
            _log.info("profile written to %s", log_dir)
