"""Cross-cutting utilities: logging facade and tracing/profiling hooks.

The reference's analogues: ``Logging.scala`` (the logging trait every class
mixes in), the packaged log4j config, and the log-line narration that stood
in for a tracer (SURVEY.md §5). Here logging and tracing are first-class
modules the engine imports.
"""

from . import checkpoint
from .logging import TRACE, get_logger, initialize_logging, set_level
from .tracing import (Timings, disable, dump_stats, enable, enabled,
                      profile, span, timings)

__all__ = [
    "checkpoint",
    "TRACE",
    "get_logger",
    "initialize_logging",
    "set_level",
    "Timings",
    "timings",
    "span",
    "enable",
    "disable",
    "enabled",
    "profile",
    "dump_stats",
]
