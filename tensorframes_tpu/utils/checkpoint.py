"""Checkpoint / resume for train states and parameter pytrees.

The reference has NO checkpointing (SURVEY.md §5: iterative state lived in
driver numpy arrays re-embedded as constants each round — the k-means
pattern). This framework trains real models over meshes, so durable state
is part of the runtime: a thin wrapper over Orbax that

 - saves any pytree of (possibly sharded) jax Arrays / numpy arrays;
 - restores either to host numpy (no template) or to the exact shardings of
   a template state (resume-on-mesh — each host reads only its shards);
 - keeps the call surface to two functions, so driver loops stay as simple
   as the reference's numpy round-tripping.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import numpy as np

from .logging import get_logger

__all__ = ["save", "restore", "latest_step", "save_step", "restore_step"]

_log = get_logger("utils.checkpoint")


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def save(path: str, state: Any) -> None:
    """Save a pytree of arrays to ``path`` (a directory, created fresh)."""
    import jax

    path = os.path.abspath(path)
    ckpt = _checkpointer()
    # numpy scalar leaves (np.float32(x)) are not in Orbax's supported
    # leaf set; store them as 0-d arrays, which round-trip losslessly
    state = jax.tree_util.tree_map(
        lambda x: np.asarray(x) if isinstance(x, np.generic) else x, state)
    ckpt.save(path, state, force=True)
    ckpt.wait_until_finished()
    _log.debug("checkpoint saved to %s", path)


def restore(path: str, like: Optional[Any] = None) -> Any:
    """Restore a pytree from ``path``.

    With ``like`` (a matching pytree of arrays — e.g. a freshly built train
    state), every leaf is restored with that leaf's sharding/dtype: resuming
    a sharded state puts each shard straight on its device. Without it,
    leaves come back as host numpy arrays.
    """
    import jax

    path = os.path.abspath(path)
    ckpt = _checkpointer()
    if like is None:
        return ckpt.restore(path)
    abstract = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        if isinstance(x, jax.Array) else x, like)
    return ckpt.restore(path, abstract)


# -- stepped checkpoints (train loops) --------------------------------------

def save_step(root: str, step: int, state: Any) -> str:
    """Save under ``root/step_<n>``; returns the checkpoint path."""
    path = os.path.join(os.path.abspath(root), f"step_{step:08d}")
    save(path, state)
    return path


def latest_step(root: str) -> Optional[int]:
    """Highest step saved under ``root``, or None."""
    root = os.path.abspath(root)
    if not os.path.isdir(root):
        return None
    steps = []
    for name in os.listdir(root):
        if name.startswith("step_"):
            try:
                steps.append(int(name[5:]))
            except ValueError:
                pass
    return max(steps) if steps else None


def restore_step(root: str, state_like: Optional[Any] = None,
                 step: Optional[int] = None):
    """Restore ``(state, step)`` from ``root`` (latest step by default);
    returns ``(None, None)`` when nothing is saved — the cold-start case a
    resume-capable driver loop checks first."""
    if step is None:
        step = latest_step(root)
    if step is None:
        return None, None
    path = os.path.join(os.path.abspath(root), f"step_{step:08d}")
    return restore(path, like=state_like), step
