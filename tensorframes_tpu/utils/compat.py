"""Cross-version jax compatibility shims.

The package tracks a moving jax API surface: ``shard_map`` graduated from
``jax.experimental`` to a top-level export, avals grew ``vma``
(varying-manual-axes) tracking, ``jax.typeof`` appeared, and
``ShapeDtypeStruct`` learned a ``vma=`` parameter. Everything
version-sensitive is probed ONCE here; the rest of the package imports
the symbols instead of sniffing jax inline.
"""

from __future__ import annotations

import inspect
from typing import FrozenSet

import jax

__all__ = ["shard_map", "typeof", "vma_of", "shape_dtype_struct",
           "tpu_compiler_params", "HAS_VMA"]

try:  # jax >= 0.6: top-level export
    from jax import shard_map as shard_map  # type: ignore[attr-defined]
except ImportError:  # older jax: experimental home
    from jax.experimental.shard_map import shard_map  # noqa: F401


def typeof(a):
    """``jax.typeof`` where it exists, else the abstract value — the same
    duck type for our purposes (shape / dtype / maybe ``vma``)."""
    fn = getattr(jax, "typeof", None)
    if fn is not None:
        return fn(a)
    return jax.core.get_aval(a)


def vma_of(a) -> FrozenSet[str]:
    """The mesh axes ``a`` varies over, empty on jax builds without vma
    tracking (where shard_map's rep checker has no such concept)."""
    return frozenset(getattr(typeof(a), "vma", None) or ())


try:
    _SDS_HAS_VMA = "vma" in inspect.signature(
        jax.ShapeDtypeStruct.__init__).parameters
except (ValueError, TypeError):  # pragma: no cover - C-impl signature
    _SDS_HAS_VMA = True

# True when this jax tracks varying-manual-axes through shard_map (and so
# pallas out_shapes must declare them); vma-specific code paths and tests
# gate on this.
HAS_VMA = _SDS_HAS_VMA and hasattr(jax, "typeof")


def shape_dtype_struct(shape, dtype, vma=None):
    """``jax.ShapeDtypeStruct`` that forwards ``vma=`` only where the
    running jax accepts it (older builds have no vma to declare)."""
    if _SDS_HAS_VMA and vma is not None:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def distributed_is_initialized() -> bool:
    """``jax.distributed.is_initialized()`` where it exists; older jax
    exposes the same fact as the private distributed state's client."""
    fn = getattr(jax.distributed, "is_initialized", None)
    if fn is not None:
        return bool(fn())
    from jax._src.distributed import global_state
    return getattr(global_state, "client", None) is not None


def serialize_stablehlo_artifact(module, version) -> bytes:
    """MLIR text/bytecode → portable StableHLO artifact, across the move
    of ``serialize_portable_artifact`` from the stablehlo dialect module
    into jax's private ``_jax`` extension."""
    try:
        from jax._src.lib import _jax as _jaxlib
        return _jaxlib.mlir.serialize_portable_artifact(module, version)
    except ImportError:
        from jaxlib.mlir.dialects import stablehlo as _sh
        if isinstance(module, bytes):
            module = module.decode()
        return _sh.serialize_portable_artifact_str(module, version)


def deserialize_stablehlo_artifact(bytecode: bytes):
    """Portable StableHLO artifact → MLIR text, across the same API move
    as :func:`serialize_stablehlo_artifact`."""
    try:
        from jax._src.lib import _jax as _jaxlib
        return _jaxlib.mlir.deserialize_portable_artifact(bytecode)
    except ImportError:
        # the older binding returns a parsed module (its _str sibling
        # returns raw MLIR bytecode, not text)
        from jaxlib.mlir import ir
        from jaxlib.mlir.dialects import stablehlo as _sh

        with ir.Context() as ctx:
            ctx.allow_unregistered_dialects = True
            module = _sh.deserialize_portable_artifact(ctx, bytecode)
            return str(module)


def tpu_compiler_params(**kwargs):
    """Pallas TPU compiler params across the ``TPUCompilerParams`` →
    ``CompilerParams`` rename."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kwargs)
