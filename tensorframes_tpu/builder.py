"""Op builder: run SERIALIZED computations against frames.

The reference's Python↔JVM surface is a builder
(``PythonInterface.scala:83-139``): ``api.map_blocks(df, trim)`` returns a
``PythonOpBuilder`` on which the driver sets ``.graph(bytes)`` (the
serialized GraphDef), ``.shape(names, shapes)`` (the ShapeDescription
side-channel) and ``.fetches(names)``, then calls ``buildDF()`` /
``buildRow()``. This module is the same contract for this framework: the
"graph bytes" are a serialized :class:`~tensorframes_tpu.computation.
Computation` (StableHLO + spec header, self-describing — shape hints are
optional overrides rather than required), and the builder dispatches into
the six-op engine. It is how a computation produced by ANOTHER process or
host (the reference's driver→executor ship) enters this one.

``save_computation`` / ``load_computation`` are the ``graph.pb``-fixture
analogue (reference ``dsl/TestUtilities.scala:20-23``, ``test/dsl.scala:
109-112``): computations as files on disk.
"""

from __future__ import annotations

from typing import Dict, Mapping, Optional, Sequence

from . import dtypes as _dt
from .computation import Computation, TensorSpec
from .engine import ops as _ops
from .frame import GroupedFrame, TensorFrame
from .shape import Shape

__all__ = ["OpBuilder", "load_computation", "save_computation",
           "map_blocks_builder", "map_rows_builder",
           "reduce_blocks_builder", "reduce_rows_builder",
           "aggregate_builder"]


def save_computation(comp: Computation, path: str) -> None:
    with open(path, "wb") as f:
        f.write(comp.serialize())


def load_computation(path: str) -> Computation:
    with open(path, "rb") as f:
        return Computation.deserialize(f.read())


class OpBuilder:
    """Builder for one op invocation from a serialized computation.

    Usage (mirrors ``PythonOpBuilder``)::

        out = (map_blocks_builder(df, trim=True)
               .graph(blob)              # serialized Computation bytes
               .fetches(["z"])           # optional output subset
               .build())
    """

    def __init__(self, op: str, df: TensorFrame,
                 grouped: Optional[GroupedFrame] = None, trim: bool = False):
        self._op = op
        self._df = df
        self._grouped = grouped
        self._trim = trim
        self._comp: Optional[Computation] = None
        self._raw_module: Optional[bytes] = None
        self._sig_inputs: Optional[Sequence[TensorSpec]] = None
        self._sig_outputs: Optional[Sequence[TensorSpec]] = None
        self._fetches: Optional[Sequence[str]] = None
        self._shapes: Dict[str, Shape] = {}

    # -- configuration -----------------------------------------------------
    def graph(self, data: bytes) -> "OpBuilder":
        """Attach the serialized computation (the ``.graph(bytes)`` leg).

        ``data`` is either this library's ``TFTPU1`` blob
        (self-describing) or a BARE StableHLO/MLIR module produced by any
        exporter (``jax.jit(fn).lower(...).as_text()``, a portable
        bytecode artifact, ...) — the foreign-graph entry the reference
        had via raw ``GraphDef`` bytes. Bare modules carry no signature,
        so call :meth:`signature` with the input (and optionally output)
        specs before :meth:`build`.
        """
        if isinstance(data, str):
            data = data.encode()
        if data.startswith(b"TFTPU"):
            self._comp = Computation.deserialize(data)
            self._raw_module = None
        elif data.startswith(b"ML\xefR") or b"func.func" in data[:4096] \
                or data.lstrip()[:6] == b"module":
            self._raw_module = data
            self._comp = None
        else:
            # let deserialize produce its canonical error
            self._comp = Computation.deserialize(data)
            self._raw_module = None
        return self

    def signature(self, inputs: Sequence[TensorSpec],
                  outputs: Optional[Sequence[TensorSpec]] = None
                  ) -> "OpBuilder":
        """Declare a bare module's signature (explicit TensorSpecs; the
        ShapeDescription role for foreign graphs). Outputs may be omitted
        — they are then inferred from the module's ``@main`` results."""
        self._sig_inputs = list(inputs)
        self._sig_outputs = list(outputs) if outputs is not None else None
        return self

    def computation(self, comp: Computation) -> "OpBuilder":
        """Attach a live computation (same slot, no round-trip)."""
        self._comp = comp
        return self

    def shape(self, shapes: Mapping[str, Shape]) -> "OpBuilder":
        """Override output shapes (the ShapeDescription hint side-channel;
        normally unnecessary — serialized computations are self-describing).
        """
        self._shapes.update(
            {n: s if isinstance(s, Shape) else Shape(s)
             for n, s in shapes.items()})
        return self

    def fetches(self, names: Sequence[str]) -> "OpBuilder":
        """Restrict the outputs to ``names`` (the requested-fetch list)."""
        self._fetches = list(names)
        return self

    # -- build -------------------------------------------------------------
    def _resolved(self) -> Computation:
        if self._comp is None and self._raw_module is not None:
            if self._sig_inputs is None:
                raise ValueError(
                    "A bare StableHLO module carries no signature; call "
                    ".signature(inputs=[TensorSpec...]) before .build()")
            self._comp = Computation.from_stablehlo(
                self._raw_module, self._sig_inputs, self._sig_outputs)
        if self._comp is None:
            raise ValueError("No computation attached; call .graph(bytes) "
                             "or .computation(comp) first")
        comp = self._comp
        if self._shapes:
            outs = [TensorSpec(s.name, s.dtype,
                               self._shapes.get(s.name, s.shape))
                    for s in comp.outputs]
            comp = Computation(comp.fn, list(comp.inputs), outs)
        if self._fetches is not None:
            missing = [f for f in self._fetches
                       if f not in comp.output_names]
            if missing:
                raise ValueError(
                    f"Requested fetches {missing} not among computation "
                    f"outputs {comp.output_names}")
            keep = set(self._fetches)
            inner = comp.fn
            outs = [s for s in comp.outputs if s.name in keep]

            def filtered(d):
                return {k: v for k, v in inner(d).items() if k in keep}

            comp = Computation(filtered, list(comp.inputs), outs)
        return comp

    def build(self):
        """Dispatch. Frame-shaped ops return a TensorFrame (`buildDF`);
        reduces return the one-row result (`buildRow`)."""
        comp = self._resolved()
        if self._op == "map_blocks":
            return _ops.map_blocks(comp, self._df, trim=self._trim)
        if self._op == "map_rows":
            return _ops.map_rows(comp, self._df)
        if self._op == "reduce_blocks":
            return _ops.reduce_blocks(comp, self._df)
        if self._op == "reduce_rows":
            return _ops.reduce_rows(comp, self._df)
        if self._op == "aggregate":
            return _ops.aggregate(comp, self._grouped)
        raise AssertionError(f"unknown op {self._op}")


def map_blocks_builder(df: TensorFrame, trim: bool = False) -> OpBuilder:
    return OpBuilder("map_blocks", df, trim=trim)


def map_rows_builder(df: TensorFrame) -> OpBuilder:
    return OpBuilder("map_rows", df)


def reduce_blocks_builder(df: TensorFrame) -> OpBuilder:
    return OpBuilder("reduce_blocks", df)


def reduce_rows_builder(df: TensorFrame) -> OpBuilder:
    return OpBuilder("reduce_rows", df)


def aggregate_builder(grouped: GroupedFrame) -> OpBuilder:
    return OpBuilder("aggregate", grouped.frame, grouped=grouped)
