"""Jax-free executor runtime for serialized computations.

The reference's executors needed no graph-authoring stack: they parsed
shipped GraphDef bytes and ran them in the C++ session layer
(``TensorFlowOps.scala:46-52``). This module is that lean executor half
for the TPU-native design: it understands the serialized-computation wire
format (``computation.Computation.serialize``), and drives the native
PJRT core (``native/libtfrpjrt.so``) to refine the shipped dynamic
StableHLO at concrete shapes, compile, and execute — using ONLY the
stdlib, numpy and ctypes. No jax, no flax, no package import.

Deliberately self-contained (duplicating the few dtype/ABI tables it
needs) so a host can load it by file path without importing
``tensorframes_tpu``::

    spec = importlib.util.spec_from_file_location(
        "native_runtime", ".../tensorframes_tpu/native_runtime.py")

``tests/test_native_pjrt.py`` runs it in a subprocess whose jax import is
blocked, proving the executor path carries zero jax dependency.
"""

from __future__ import annotations

import ctypes
import json
import os
import struct
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

__all__ = ["NativeComputation", "NativeRuntime", "load_computation"]

_MAGIC = b"TFTPU1\x00"
_ERRLEN = 4096

# tfr_dtype codes (native/tfrpjrt.h) keyed by numpy dtype: the module's
# TRACED argument dtypes ride in the header ("arg_dtypes" — they depend on
# the authoring host's x64 policy, e.g. a 'double' column traces as f32
# with x64 off), so this runtime never guesses the storage policy.
_CODES = {
    np.dtype(np.float32): 1,
    np.dtype(np.float64): 2,
    np.dtype(np.int32): 3,
    np.dtype(np.int64): 4,
    np.dtype(np.bool_): 6,
}
_NP_FROM_CODE = {1: np.dtype(np.float32), 2: np.dtype(np.float64),
                 3: np.dtype(np.int32), 4: np.dtype(np.int64),
                 6: np.dtype(np.bool_)}
_BF16_STORAGE = np.dtype(np.uint16)


class NativeRuntimeError(RuntimeError):
    pass


class NativeComputation:
    """A deserialized computation: specs + the raw dynamic module."""

    def __init__(self, inputs: List[dict], outputs: List[dict],
                 module: bytes, cc_version: int,
                 platforms: Tuple[str, ...],
                 arg_dtypes: Sequence[str]):
        self.inputs = inputs      # [{"name", "dtype", "shape"}]
        self.outputs = outputs
        self.module = module
        self.cc_version = cc_version
        self.platforms = platforms
        # traced (module-parameter) dtypes, one per input, in order
        self.arg_dtypes = [np.dtype(d) for d in arg_dtypes]

    @property
    def input_names(self) -> List[str]:
        return [s["name"] for s in self.inputs]

    @property
    def output_names(self) -> List[str]:
        return [s["name"] for s in self.outputs]


def load_computation(data: bytes) -> NativeComputation:
    """Parse serialized computation bytes (no jax)."""
    if not data.startswith(_MAGIC):
        raise NativeRuntimeError(
            "Not a serialized tensorframes-tpu computation")
    off = len(_MAGIC)
    (hlen,) = struct.unpack_from("<I", data, off)
    off += 4
    header = json.loads(data[off:off + hlen].decode("utf-8"))
    native = header.get("native")
    if not native:
        raise NativeRuntimeError(
            "blob predates the native section; re-serialize with a "
            "current authoring host (jax path still accepts it)")
    payload = data[off + hlen:]
    arg_dtypes = native.get("arg_dtypes")
    if not arg_dtypes:
        raise NativeRuntimeError(
            "blob lacks traced argument dtypes (older wire format); "
            "re-serialize with a current authoring host")
    return NativeComputation(header["inputs"], header["outputs"],
                             payload[: native["module_len"]],
                             native["cc_version"],
                             tuple(native["platforms"]), arg_dtypes)


def _find_library() -> Optional[str]:
    cand = os.environ.get("TFT_PJRT_LIB")
    if cand and os.path.exists(cand):
        return cand
    here = os.path.dirname(os.path.abspath(__file__))
    for rel in (os.path.join(here, "..", "native", "libtfrpjrt.so"),
                os.path.join(here, "libtfrpjrt.so")):
        p = os.path.abspath(rel)
        if os.path.exists(p):
            return p
    return None


def _destroy_exes(lib, per_nc: dict) -> None:
    for exe in per_nc.values():
        lib.tfr_pjrt_exe_destroy(exe)
    per_nc.clear()


class NativeRuntime:
    """A PJRT client + per-signature executable cache, jax-free.

    ``backend``: ``cpu[:n]`` or ``plugin:<path>[?opts]`` — the same specs
    the full binding accepts (``native_pjrt.PjrtCoreClient``).
    """

    def __init__(self, backend: str = "cpu",
                 lib_path: Optional[str] = None):
        path = lib_path or _find_library()
        if path is None:
            raise NativeRuntimeError(
                "libtfrpjrt.so not found; build with `make -C native pjrt`")
        lib = ctypes.CDLL(path)
        vp, ci, cll = ctypes.c_void_p, ctypes.c_int, ctypes.c_longlong
        lib.tfr_pjrt_client_create.argtypes = [ctypes.c_char_p,
                                               ctypes.c_char_p, ci]
        lib.tfr_pjrt_client_create.restype = vp
        lib.tfr_pjrt_client_platform.argtypes = [vp, ctypes.c_char_p, ci]
        lib.tfr_pjrt_client_platform.restype = ci
        lib.tfr_pjrt_compile_dynamic.argtypes = [
            vp, ctypes.c_char_p, ctypes.c_long, ci, ctypes.c_char_p,
            ctypes.c_char_p, ci, ctypes.POINTER(ci), ctypes.POINTER(ci),
            ctypes.POINTER(cll), ctypes.c_char_p, ci]
        lib.tfr_pjrt_compile_dynamic.restype = vp
        lib.tfr_pjrt_execute.argtypes = [vp, vp, ci, ctypes.POINTER(ci),
                                         ctypes.POINTER(ci),
                                         ctypes.POINTER(cll),
                                         ctypes.POINTER(vp),
                                         ctypes.c_char_p, ci]
        lib.tfr_pjrt_execute.restype = vp
        lib.tfr_pjrt_results_count.argtypes = [vp]
        lib.tfr_pjrt_results_count.restype = ci
        lib.tfr_pjrt_result_meta.argtypes = [vp, ci, ctypes.POINTER(ci),
                                             ctypes.POINTER(ci),
                                             ctypes.POINTER(cll)]
        lib.tfr_pjrt_result_meta.restype = ci
        lib.tfr_pjrt_result_read.argtypes = [vp, ci, vp, cll,
                                             ctypes.c_char_p, ci]
        lib.tfr_pjrt_result_read.restype = ci
        lib.tfr_pjrt_results_destroy.argtypes = [vp]
        lib.tfr_pjrt_exe_destroy.argtypes = [vp]
        lib.tfr_pjrt_client_destroy.argtypes = [vp]
        self._lib = lib
        err = ctypes.create_string_buffer(_ERRLEN)
        self._client = lib.tfr_pjrt_client_create(backend.encode(), err,
                                                  _ERRLEN)
        if not self._client:
            raise NativeRuntimeError(
                f"client create failed: "
                f"{err.value.decode(errors='replace')}")
        buf = ctypes.create_string_buffer(256)
        lib.tfr_pjrt_client_platform(self._client, buf, 256)
        self.platform = buf.value.decode()
        # weakly keyed by the live NativeComputation: entries die with it,
        # so id() recycling cannot alias a dead computation's program
        import weakref

        self._exes: "weakref.WeakKeyDictionary[NativeComputation, Dict[tuple, ctypes.c_void_p]]" = \
            weakref.WeakKeyDictionary()

    def _device_view(self, want: np.dtype,
                     a: np.ndarray) -> Tuple[np.ndarray, int]:
        if want == _BF16_STORAGE:
            if a.dtype != _BF16_STORAGE:
                raise NativeRuntimeError(
                    "bfloat16 inputs must arrive as uint16 storage")
            return np.ascontiguousarray(a), 5
        code = _CODES.get(want)
        if code is None:
            raise NativeRuntimeError(f"unsupported traced dtype {want}")
        if a.dtype != want:
            a = a.astype(want)
        return np.ascontiguousarray(a), code

    def run(self, nc: NativeComputation,
            arrays: Mapping[str, np.ndarray]) -> Dict[str, np.ndarray]:
        lib = self._lib
        if nc.platforms and self.platform not in nc.platforms:
            raise NativeRuntimeError(
                f"computation was lowered for {nc.platforms}, not for this "
                f"runtime's platform {self.platform!r}")
        views: List[np.ndarray] = []
        codes: List[int] = []
        for spec, want in zip(nc.inputs, nc.arg_dtypes):
            v, code = self._device_view(want,
                                        np.asarray(arrays[spec["name"]]))
            views.append(v)
            codes.append(code)
        n = len(views)
        ci, cll, vp = ctypes.c_int, ctypes.c_longlong, ctypes.c_void_p
        dtypes = (ci * n)(*codes)
        ndims = (ci * n)(*[v.ndim for v in views])
        flat: List[int] = []
        for v in views:
            flat.extend(v.shape)
        dims = (cll * max(1, len(flat)))(*flat)

        sig = tuple((c, v.shape) for c, v in zip(codes, views))
        per_nc = self._exes.get(nc)
        if per_nc is None:
            import weakref

            per_nc = self._exes[nc] = {}
            # free this computation's executables when it is collected
            # (the WeakKeyDictionary entry alone would just vanish)
            weakref.finalize(nc, _destroy_exes, self._lib, per_nc)
        exe = per_nc.get(sig)
        err = ctypes.create_string_buffer(_ERRLEN)
        if exe is None:
            exe = lib.tfr_pjrt_compile_dynamic(
                self._client, nc.module, len(nc.module), nc.cc_version,
                ",".join(nc.platforms).encode(), self.platform.encode(),
                n, dtypes, ndims, dims, err, _ERRLEN)
            if not exe:
                raise NativeRuntimeError(
                    f"dynamic compile failed: "
                    f"{err.value.decode(errors='replace')}")
            per_nc[sig] = exe

        datas = (vp * n)(*[v.ctypes.data_as(vp) for v in views])
        res = lib.tfr_pjrt_execute(self._client, exe, n, dtypes, ndims,
                                   dims, datas, err, _ERRLEN)
        if not res:
            raise NativeRuntimeError(
                f"execute failed: {err.value.decode(errors='replace')}")
        try:
            outs = []
            for i in range(lib.tfr_pjrt_results_count(res)):
                dt = ci()
                nd = ci()
                odims = (cll * 8)()
                if lib.tfr_pjrt_result_meta(res, i, ctypes.byref(dt),
                                            ctypes.byref(nd), odims):
                    raise NativeRuntimeError(f"result {i}: meta failed")
                shape = tuple(odims[k] for k in range(nd.value))
                np_dt = (_BF16_STORAGE if dt.value == 5
                         else _NP_FROM_CODE.get(dt.value))
                if np_dt is None:
                    raise NativeRuntimeError(
                        f"result {i}: unsupported dtype code {dt.value}")
                out = np.empty(shape, np_dt)
                if lib.tfr_pjrt_result_read(
                        res, i, out.ctypes.data_as(vp), out.nbytes, err,
                        _ERRLEN):
                    raise NativeRuntimeError(
                        f"result {i}: "
                        f"{err.value.decode(errors='replace')}")
                outs.append(out)
        finally:
            lib.tfr_pjrt_results_destroy(res)
        return dict(zip(nc.output_names, outs))

    def close(self):
        """Free compiled executables and the native client."""
        if self._client:
            for per_nc in self._exes.values():
                # clears each per-computation dict in place so the
                # weakref finalizers see empty dicts (no double destroy)
                _destroy_exes(self._lib, per_nc)
            self._exes.clear()
            self._lib.tfr_pjrt_client_destroy(self._client)
            self._client = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
