"""TensorFrame: a partitioned, columnar DataFrame for tensor programs.

The TPU-native replacement for the Spark ``DataFrame`` the reference operates
on. Where the reference wraps Spark (JVM row objects, RDD partitions,
Catalyst metadata), a :class:`TensorFrame` is: a :class:`~.schema.Schema`
carrying tensor metadata + a list of **blocks** (one per partition), each a
dict of columnar numpy arrays — the exact unit the reference's executors
rebuilt from ``Array[Row]`` on every call (``DataOps.convert``). Columns are
kept columnar end-to-end, so feeding the TPU is a ``device_put`` instead of a
row-by-row repack.

Laziness matches the reference contract: ``map_*`` return a lazy frame (the
plan is a thunk chain, forced by ``collect``/``blocks``/``count``), while
``reduce_*``/``aggregate`` are eager (reference ``core.py:107, 141, 232``).

Ragged columns (rows holding vectors of varying length) are representable —
stored as lists of per-row arrays — because ``map_rows`` must handle them
(reference ``BasicOperationsSuite`` "Identity - 1 dim with unknown size").
Dense block materialization of a ragged column raises, as the reference's
block path does.
"""

from __future__ import annotations

import itertools
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple, Union

import numpy as np

from . import dtypes as _dt
from .marshal import Column, columns_to_rows, rows_to_columns
from .observability import events as _obs
from .schema import Field, Schema
from .shape import Shape, Unknown

__all__ = ["Row", "Block", "TensorFrame", "GroupedFrame", "frame"]


class Row(tuple):
    """A result row: a tuple with named-field access (Spark Row analogue)."""

    _fields: Tuple[str, ...]

    def __new__(cls, values: Iterable, fields: Sequence[str]):
        self = super().__new__(cls, values)
        self._fields = tuple(fields)
        return self

    def __getitem__(self, key):
        if isinstance(key, str):
            try:
                key = self._fields.index(key)
            except ValueError:
                raise KeyError(f"No field {key!r}; fields: {self._fields}")
        return super().__getitem__(key)

    def as_dict(self) -> Dict[str, object]:
        return dict(zip(self._fields, self))

    @property
    def fields(self) -> Tuple[str, ...]:
        return self._fields

    def __repr__(self):
        inner = ", ".join(f"{n}={v!r}" for n, v in zip(self._fields, self))
        return f"Row({inner})"


class Block:
    """One partition's worth of rows, stored columnar."""

    __slots__ = ("columns", "num_rows")

    def __init__(self, columns: Dict[str, Column], num_rows: Optional[int] = None):
        self.columns = columns
        if num_rows is None:
            if not columns:
                raise ValueError("Empty block needs an explicit num_rows")
            num_rows = len(next(iter(columns.values())))
        self.num_rows = int(num_rows)
        for name, col in columns.items():
            if len(col) != self.num_rows:
                raise ValueError(
                    f"Column {name!r} has {len(col)} rows; expected "
                    f"{self.num_rows}")

    def is_ragged(self, name: str) -> bool:
        return not isinstance(self.columns[name], np.ndarray)

    def dense(self, name: str) -> np.ndarray:
        col = self.columns[name]
        if not isinstance(col, np.ndarray):
            raise ValueError(
                f"Column {name!r} contains cells of varying shape in this "
                f"block; block operations require uniform cells — use "
                f"map_rows instead (reference core.py:193-194)")
        return col

    def select(self, names: Sequence[str]) -> "Block":
        return Block({n: self.columns[n] for n in names}, self.num_rows)

    def row(self, i: int, names: Sequence[str]) -> Tuple:
        return tuple(self.columns[n][i] for n in names)

    @staticmethod
    def from_rows(rows: Sequence[Sequence], schema: Schema) -> "Block":
        cols = rows_to_columns(rows, schema)
        return Block(cols, len(rows))

    @staticmethod
    def concat(blocks: Sequence["Block"], schema: Schema) -> "Block":
        # 0-row columns carry no shape evidence (their zero-filled cell dims
        # need not match the real blocks'); they are ignored when unifying.
        nonempty = [b for b in blocks if b.num_rows > 0]
        if len(nonempty) == 1:
            # single-partition frames concat for free: callers treat
            # blocks as immutable, so the columns can be shared, not
            # copied (np.concatenate of one array still copies)
            b = nonempty[0]
            return Block({f.name: b.columns[f.name] for f in schema},
                         b.num_rows)
        if not nonempty:
            if blocks:
                return Block({f.name: blocks[0].columns[f.name]
                              for f in schema}, 0)
            return Block({f.name: np.empty((0,), f.dtype.np_storage)
                          for f in schema}, 0)
        out: Dict[str, Column] = {}
        for f in schema:
            cols = [b.columns[f.name] for b in nonempty]
            if all(isinstance(c, np.ndarray) for c in cols) and \
                    len({c.shape[1:] for c in cols}) == 1:
                out[f.name] = np.concatenate(cols)
            else:
                ragged: List[np.ndarray] = []
                for c in cols:
                    ragged.extend(list(c))
                out[f.name] = ragged
        return Block(out, sum(b.num_rows for b in nonempty))


def _infer_schema_from_rows(rows: Sequence[Sequence],
                            names: Sequence[str]) -> Schema:
    """Infer field dtypes/ranks from the first row (Spark-style: python
    float -> double, int -> long)."""
    if not rows:
        raise ValueError("Cannot infer a schema from zero rows; pass schema=")
    first = rows[0]
    if len(first) != len(names):
        raise ValueError(
            f"Row width {len(first)} != number of column names {len(names)}")
    fields = []
    for name, cell in zip(names, first):
        rank = 0
        probe = cell
        while isinstance(probe, (list, tuple, np.ndarray)):
            rank += 1
            if len(probe) == 0:
                probe = 0.0
                break
            probe = probe[0]
        dt = _dt.string if isinstance(probe, (str, np.str_, bytes)) \
            else _dt.from_python_value(probe)
        f = Field(name, dt, sql_rank=rank)
        if rank == 0 and dt.tensor:
            f = f.with_block_shape(Shape(Unknown))
        fields.append(f)
    return Schema(fields)


def _blocks_hints(blocks: Sequence[Block]) -> Dict[str, object]:
    """Exact size hints for a source frame whose blocks already exist
    (``from_rows``/``from_columns``/``from_blocks`` build them eagerly).
    Includes the per-column split the plan cost model seeds from."""
    from .memory.estimate import blocks_estimate, column_nbytes
    rows, nbytes = blocks_estimate(blocks)
    col_bytes: Dict[str, int] = {}
    for b in blocks:
        for name, col in b.columns.items():
            col_bytes[name] = col_bytes.get(name, 0) + column_nbytes(col)
    return {"rows_hint": rows, "bytes_hint": nbytes,
            "col_bytes_hint": col_bytes}


def _split_even(n: int, parts: int) -> List[Tuple[int, int]]:
    """Split n rows into at most ``parts`` non-empty spans (Spark-style:
    never more partitions than rows)."""
    return _split_exact(n, max(1, min(parts, max(n, 1))))


def _split_exact(n: int, parts: int) -> List[Tuple[int, int]]:
    """Split n rows into exactly ``parts`` spans (possibly empty)."""
    base, extra = divmod(n, parts)
    spans, start = [], 0
    for i in range(parts):
        size = base + (1 if i < extra else 0)
        spans.append((start, start + size))
        start += size
    return spans


class TensorFrame:
    """A lazily-evaluated, partitioned columnar DataFrame."""

    def __init__(self, schema: Schema,
                 thunk: Callable[[], List[Block]],
                 num_partitions: int,
                 plan: str = "source",
                 rows_hint: Optional[int] = None,
                 bytes_hint: Optional[int] = None,
                 col_bytes_hint: Optional[Dict[str, int]] = None):
        self._schema = schema
        self._thunk = thunk
        self._cache: Optional[List[Block]] = None
        self._num_partitions = num_partitions
        self._plan = plan
        # the QueryTrace of this frame's forcing (None until forced with
        # tracing enabled); rendered by explain()
        self._trace = None
        # plan-derived size hints (docs/memory.md): exact at source
        # constructors, scaled through ops — what gives UNFORCED frames
        # a serve-admission estimate; None means unknown
        self._rows_hint = rows_hint
        self._bytes_hint = bytes_hint
        # per-column bytes at source constructors: the logical plan's
        # per-column cost model seeds from these (docs/plan.md)
        self._col_bytes_hint = col_bytes_hint
        # logical-plan IR (docs/plan.md): lazy ops record a PlanNode
        # here; forcing offers it to the optimizer first, falling back
        # to the per-op thunk above. _plan_info carries the optimized
        # plan's rendering for explain() after a fused forcing.
        self._plan_node = None
        self._plan_info = None
        # bumped by uncache(): the plan-fingerprint result cache
        # (docs/adaptive.md) keys on it, so an explicit re-force can
        # never be served a stale interned result
        self._version = 0

    # -- construction ------------------------------------------------------
    @staticmethod
    def from_rows(rows: Sequence[Sequence], columns: Sequence[str] = None,
                  schema: Optional[Schema] = None,
                  num_partitions: int = 1) -> "TensorFrame":
        rows = [tuple(r) if not isinstance(r, tuple) else r for r in rows]
        if schema is None:
            if columns is None:
                raise ValueError("Pass columns=[...] names or schema=")
            schema = _infer_schema_from_rows(rows, columns)
        spans = _split_even(len(rows), num_partitions)
        blocks = [Block.from_rows(rows[a:b], schema) for a, b in spans]
        return TensorFrame(schema, lambda: blocks, len(blocks),
                           **_blocks_hints(blocks))

    @staticmethod
    def from_columns(cols: Dict[str, np.ndarray],
                     schema: Optional[Schema] = None,
                     num_partitions: int = 1) -> "TensorFrame":
        cols = {n: np.asarray(c) for n, c in cols.items()}
        if schema is None:
            schema = Schema.from_numpy_columns(cols)
        ns = {len(c) for c in cols.values()}
        if len(ns) > 1:
            raise ValueError(f"Columns disagree on row count: {ns}")
        n = ns.pop() if ns else 0
        spans = _split_even(n, num_partitions)
        blocks = [Block({k: v[a:b] for k, v in cols.items()}, b - a)
                  for a, b in spans]
        return TensorFrame(schema, lambda: blocks, len(blocks),
                           **_blocks_hints(blocks))

    @staticmethod
    def from_blocks(blocks: List[Block], schema: Schema) -> "TensorFrame":
        return TensorFrame(schema, lambda: blocks, len(blocks),
                           **_blocks_hints(blocks))

    # -- basic properties --------------------------------------------------
    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def columns(self) -> List[str]:
        return self._schema.names

    @property
    def num_partitions(self) -> int:
        return self._num_partitions

    def __repr__(self):
        return (f"TensorFrame[{', '.join(self._schema.names)}] "
                f"({self._num_partitions} partition(s), plan={self._plan})")

    # -- evaluation --------------------------------------------------------
    def blocks(self) -> List[Block]:
        if self._cache is None:
            if self._plan_node is not None:
                # plan-fingerprint result cache (docs/adaptive.md): a
                # repeated hot query — same sources at the same
                # versions, same canonical computations — costs zero
                # dispatches. Misses, one-off chains, and
                # TFT_RESULT_CACHE=0 fall through to the forcing below.
                from .plan import adaptive as _adaptive
                hit = _adaptive.cached_result(self)
                if hit is not None:
                    self._cache = hit
                    self._plan_info = [
                        "  result   : served from the plan-fingerprint "
                        f"result cache — {len(hit)} block(s), zero "
                        "dispatches (TFT_RESULT_CACHE=1, "
                        "docs/adaptive.md)"]
                    from . import memory as _memory
                    _memory.note_frame_cache(self)
                    return self._cache
            # forcing IS the query: open a correlated trace (no-op with
            # tracing off; a forcing nested inside another query joins
            # the ambient trace and yields None here)
            with _obs.query_trace(self._plan.split("(", 1)[0],
                                  plan=self._plan) as t:
                blocks = None
                if self._plan_node is not None:
                    # logical-plan path (docs/plan.md): fuse row-local
                    # op chains into one dispatch per block, prune
                    # columns, chain stages device-resident. Returns
                    # None (fusion off / unplannable chain) to defer to
                    # the per-op thunk — TFT_FUSE=0 is bit-identical to
                    # the pre-plan engine by construction.
                    from .plan import maybe_run as _plan_maybe_run
                    blocks = _plan_maybe_run(self)
                self._cache = blocks if blocks is not None \
                    else self._thunk()
            if t is not None:
                self._trace = t
            if self._plan_node is not None:
                # two-touch admission: interned only when this exact
                # fingerprint repeats (hot dashboards), never for
                # one-off chains or per-batch streaming frames
                from .plan import adaptive as _adaptive
                _adaptive.offer_result(self, self._cache)
            # under an active device budget the forced block cache joins
            # the host-side accounting (tft_memory_frame_cache_bytes);
            # one global read otherwise
            from . import memory as _memory
            _memory.note_frame_cache(self)
        return self._cache

    def uncache(self) -> "TensorFrame":
        """Drop the forced block cache (the next ``blocks()`` re-runs
        the plan) and release it from the memory manager's host-side
        accounting. The inverse of :meth:`cache`."""
        self._cache = None
        # re-version: interned results keyed on (or validated against)
        # this frame can no longer hit — uncache() is an explicit
        # request to re-run the plan (docs/adaptive.md)
        self._version += 1
        from . import memory as _memory
        _memory.forget_frame_cache(self)
        return self

    def estimated_rows(self) -> Optional[int]:
        """Best-effort row count: exact when forced, the plan hint
        otherwise, ``None`` when unknown (``docs/memory.md``)."""
        from .memory.estimate import frame_estimate
        rows, _ = frame_estimate(self)
        return int(rows) if rows is not None else None

    def estimated_bytes(self) -> Optional[int]:
        """Best-effort host byte size: exact when forced, the plan hint
        (an upper bound through filters) otherwise, ``None`` when
        unknown. The serve scheduler's admission estimate for unforced
        frames reads this."""
        from .memory.estimate import frame_estimate
        _, nbytes = frame_estimate(self)
        return nbytes

    def collect(self) -> List[Row]:
        names = self._schema.names
        out: List[Row] = []
        for b in self.blocks():
            for tup in columns_to_rows(b.columns, self._schema):
                out.append(Row(tup, names))
        return out

    def count(self) -> int:
        return sum(b.num_rows for b in self.blocks())

    def first(self) -> Row:
        for b in self.blocks():
            if b.num_rows:
                tup = columns_to_rows(
                    Block({k: v[:1] for k, v in b.columns.items()}, 1).columns,
                    self._schema)[0]
                return Row(tup, self._schema.names)
        raise ValueError("Frame is empty")

    def cache(self) -> "TensorFrame":
        self.blocks()
        return self

    # -- transformations ---------------------------------------------------
    def select(self, names: Sequence[str]) -> "TensorFrame":
        names = list(names)
        schema = self._schema.select(names)
        from .memory.estimate import propagate_hints
        rows_h, bytes_h = propagate_hints(self, schema)
        out = TensorFrame(
            schema, lambda: [b.select(names) for b in self.blocks()],
            self._num_partitions, plan=f"select({self._plan})",
            rows_hint=rows_h, bytes_hint=bytes_h)
        from .plan.nodes import SelectNode, attach, node_for
        attach(out, SelectNode(node_for(self), schema, names))
        return out

    def with_schema(self, schema: Schema) -> "TensorFrame":
        """Same data, refined metadata (used by ``analyze``)."""
        return TensorFrame(schema, self.blocks, self._num_partitions,
                           plan=self._plan)

    def repartition(self, n: int) -> "TensorFrame":
        """Redistribute rows into exactly ``n`` partitions (some possibly
        empty when there are fewer rows than partitions)."""
        n = max(1, int(n))

        def thunk():
            merged = Block.concat(self.blocks(), self._schema)
            out = []
            for a, b in _split_exact(merged.num_rows, n):
                cols: Dict[str, Column] = {}
                for name, col in merged.columns.items():
                    cols[name] = col[a:b] if isinstance(col, np.ndarray) \
                        else list(col[a:b])
                out.append(Block(cols, b - a))
            return out

        from .memory.estimate import propagate_hints
        rows_h, bytes_h = propagate_hints(self, self._schema)
        return TensorFrame(self._schema, thunk, n,
                           plan=f"repartition({self._plan})",
                           rows_hint=rows_h, bytes_hint=bytes_h)

    def pad_column(self, name: str, max_len: Optional[int] = None,
                   pow2: bool = False, mask_col: Optional[str] = None,
                   len_col: Optional[str] = None) -> "TensorFrame":
        """Pad a ragged 1-d column to a dense ``[rows, L]`` column plus a
        validity-mask column and a length column — making it usable by the
        block-level ops despite XLA's static-shape world (SURVEY.md §7 hard
        part #1: bucketed padding + mask). Eager on the column lengths.

        ``pow2`` rounds L up to a power of two so frames of many ragged
        profiles share compile signatures downstream.
        """
        field = self._schema.get(name)
        if field is None:
            raise KeyError(f"No column {name!r}")
        mask_col = mask_col or f"{name}_mask"
        len_col = len_col or f"{name}_len"
        for c in (mask_col, len_col):
            if c in self._schema:
                raise ValueError(f"Column {c!r} already exists")
        blocks = self.blocks()

        def cell_list(b: Block) -> List[np.ndarray]:
            col = b.columns[name]
            return [np.asarray(col[i]) for i in range(b.num_rows)]

        # eager only on the length/rank scan; padded blocks build lazily
        longest = 0
        for b in blocks:
            for c in cell_list(b):
                if c.ndim != 1:
                    raise ValueError(
                        f"pad_column supports 1-d cells; {name!r} has a "
                        f"rank-{c.ndim} cell")
                longest = max(longest, c.size)
        L = max_len if max_len is not None else longest
        if pow2:
            p = 1
            while p < L:
                p *= 2
            L = p

        from . import native as _native

        def pad_block(b: Block) -> Block:
            cols = dict(b.columns)
            if b.num_rows == 0:
                cols[name] = np.zeros((0, L), field.dtype.np_storage)
                cols[mask_col] = np.zeros((0, L), np.int32)
                cols[len_col] = np.zeros((0,), np.int64)
            else:
                cells = cell_list(b)
                dense, mask = _native.pad_ragged(
                    cells, max_len=L, dtype=field.dtype.np_storage)
                cols[name] = dense
                cols[mask_col] = mask.astype(np.int32)
                cols[len_col] = np.array([c.size for c in cells], np.int64)
            return Block(cols, b.num_rows)

        fields = []
        for f in self._schema:
            if f.name == name:
                fields.append(Field(name, f.dtype,
                                    block_shape=Shape(Unknown, L),
                                    sql_rank=1))
            else:
                fields.append(f)
        fields.append(Field(mask_col, _dt.int32,
                            block_shape=Shape(Unknown, L), sql_rank=1))
        fields.append(Field(len_col, _dt.int64,
                            block_shape=Shape(Unknown), sql_rank=0))
        return TensorFrame(Schema(fields),
                           lambda: [pad_block(b) for b in blocks],
                           self._num_partitions,
                           plan=f"pad_column({self._plan})")

    def group_by(self, *cols: str) -> "GroupedFrame":
        for c in cols:
            if c not in self._schema:
                raise KeyError(f"No column {c!r}")
        return GroupedFrame(self, list(cols))

    # -- fluent op sugar (reference dsl/Implicits.scala:12-123) ------------
    def map_blocks(self, fetches, trim: bool = False,
                   executor=None) -> "TensorFrame":
        from . import api
        return api.map_blocks(fetches, self, trim=trim, executor=executor)

    def map_rows(self, fetches, executor=None) -> "TensorFrame":
        from . import api
        return api.map_rows(fetches, self, executor=executor)

    def reduce_blocks(self, fetches, executor=None):
        from . import api
        return api.reduce_blocks(fetches, self, executor=executor)

    def reduce_rows(self, fetches, executor=None):
        from . import api
        return api.reduce_rows(fetches, self, executor=executor)

    def filter(self, predicate, executor=None) -> "TensorFrame":
        from . import api
        return api.filter_rows(predicate, self, executor=executor)

    def join(self, other: "TensorFrame", on, how: str = "inner",
             strategy: Optional[str] = None, mesh=None,
             indicator: Optional[str] = None) -> "TensorFrame":
        """Join this frame against ``other`` (lazy). Strategies: a
        broadcast hash join for small build sides (default), a
        shuffle-partitioned hash join for big builds on a multi-shard
        mesh (``strategy="partitioned"`` / auto when ``mesh=`` is given
        and the build side is over ``TFT_BROADCAST_LIMIT_BYTES`` —
        string keys included), or a mesh sort-merge join
        (``strategy="sort_merge"`` / auto for numeric keys when
        ``TFT_SHUFFLE=0``). The auto-routing decision is
        flight-recorded (``tft.why()``) and rendered by ``explain()``.
        See ``docs/joins.md``."""
        from .relational.join import join as _join
        return _join(self, other, on, how=how, strategy=strategy,
                     mesh=mesh, indicator=indicator)

    def hot_keys(self) -> List[Dict]:
        """The hot-key observations recorded when this frame was
        produced by a salted ``daggregate`` (eager or fused): one dict
        per hot group — ``{"keys": {col: value}, "fraction":
        observed-row-fraction, "salt_slots": K}``. Empty for frames no
        salting touched. The same observations feed the top-k sketch
        and render as an ``explain()`` line (``docs/joins.md``)."""
        return list(getattr(self, "_hot_keys", ()) or ())

    def submit(self, fetches=None, *, tenant: str = "default",
               deadline: Optional[float] = None, **kwargs):
        """Defer this frame's forcing to the multi-tenant query
        scheduler (``tft.submit``): queued under ``tenant``'s quotas,
        admitted against the HBM watermark, executed under the weighted-
        fair scheduler. Returns a ``serve.SubmittedQuery`` future —
        ``.result()`` yields the forced frame. See ``docs/serving.md``.
        """
        from . import api
        return api.submit(self, fetches, tenant=tenant, deadline=deadline,
                          **kwargs)

    def limit(self, n: int) -> "TensorFrame":
        """The first ``n`` rows (in block order). Lazy."""
        if n < 0:
            raise ValueError(f"limit({n}): n must be >= 0")

        def run() -> List[Block]:
            from .marshal import _concrete_cell

            out: List[Block] = []
            left = n
            for b in self.blocks():
                if left <= 0:
                    break
                take = min(left, b.num_rows)
                if take == b.num_rows:
                    out.append(b)
                else:
                    out.append(Block(
                        {k: v[:take] for k, v in b.columns.items()}, take))
                left -= take
            return out or [Block(
                {f.name: np.empty((0,) + _concrete_cell(f),
                                  f.dtype.np_storage)
                 for f in self._schema}, 0)]

        from .memory.estimate import frame_estimate
        est_rows, est_bytes = frame_estimate(self)
        if est_rows:
            take = min(n, int(est_rows))
            lim_bytes = (int(est_bytes * take / est_rows)
                         if est_bytes is not None else None)
        else:
            take, lim_bytes = None, None
        return TensorFrame(self._schema, run, self._num_partitions,
                           plan=f"limit({n})({self._plan})",
                           rows_hint=take, bytes_hint=lim_bytes)

    def sample(self, fraction: float, seed: int = 0) -> "TensorFrame":
        """A Bernoulli row sample (each row kept independently with
        probability ``fraction``). Lazy; deterministic for a given seed."""
        if not 0.0 <= fraction <= 1.0:
            raise ValueError(f"sample fraction {fraction} not in [0, 1]")

        def run() -> List[Block]:
            out: List[Block] = []
            for i, b in enumerate(self.blocks()):
                rng = np.random.default_rng((seed, i))
                mask = rng.random(b.num_rows) < fraction
                keep = int(mask.sum())
                out.append(Block(
                    {k: (v[mask] if isinstance(v, np.ndarray)
                         else [v[j] for j in np.flatnonzero(mask)])
                     for k, v in b.columns.items()}, keep))
            return out

        from .memory.estimate import frame_estimate
        est_rows, est_bytes = frame_estimate(self)
        return TensorFrame(
            self._schema, run, self._num_partitions,
            plan=f"sample({fraction})({self._plan})",
            rows_hint=(int(est_rows * fraction)
                       if est_rows is not None else None),
            bytes_hint=(int(est_bytes * fraction)
                        if est_bytes is not None else None))

    def show(self, n: int = 20) -> None:
        """Print the first ``n`` rows as a small aligned table (the Spark
        ``df.show()`` convenience)."""
        rows = self.limit(n).collect()
        names = self._schema.names

        def fmt(v):
            if isinstance(v, float):
                return f"{v:.6g}"
            if isinstance(v, np.ndarray):
                flat = np.asarray(v).reshape(-1)
                s = ", ".join(f"{x:.4g}" if isinstance(x, float)
                              else str(x) for x in flat[:4])
                return f"[{s}{', ...' if flat.size > 4 else ''}]"
            return str(v)

        table = [[fmt(r[i]) for i in range(len(names))] for r in rows]
        widths = [max(len(nm), *(len(t[i]) for t in table))
                  if table else len(nm) for i, nm in enumerate(names)]
        line = "+" + "+".join("-" * (w + 2) for w in widths) + "+"
        print(line)
        print("|" + "|".join(f" {nm:<{w}} "
                             for nm, w in zip(names, widths)) + "|")
        print(line)
        for t in table:
            print("|" + "|".join(f" {c:<{w}} "
                                 for c, w in zip(t, widths)) + "|")
        print(line)

    def order_by(self, *cols: str, descending: bool = False,
                 num_partitions: Optional[int] = None) -> "TensorFrame":
        """Rows globally sorted by scalar key column(s). Lazy.

        Beyond the reference's surface (its users ordered through Spark's
        relational API). Multi-key: first name is the primary key. Stable
        within equal keys. The result is re-partitioned evenly
        (``num_partitions`` defaults to the input's count) — a global sort
        cannot preserve partition boundaries.
        """
        if not cols:
            raise ValueError("order_by needs at least one key column")
        for c in cols:
            f = self._schema.get(c)
            if f is None:
                raise KeyError(
                    f"No column {c!r}; columns: {self._schema.names}")
            if f.sql_rank != 0:
                raise ValueError(
                    f"order_by key {c!r} must be a scalar column")
        parts = num_partitions or self._num_partitions

        def run() -> List[Block]:
            # Blockwise: only the KEY columns are ever concatenated; the
            # value columns gather from their blocks one OUTPUT block at a
            # time. Peak host memory is input + output + keys, not the 3x
            # a whole-frame merge costs (the reference streamed partitions
            # and never held the dataset in one buffer).
            blocks = self.blocks()
            sizes = [b.num_rows for b in blocks]
            offsets = np.concatenate([[0], np.cumsum(sizes)]).astype(
                np.int64)
            n = int(offsets[-1])
            # np.lexsort: LAST key is primary; stable. Descending negates
            # the key instead of reversing the result, which would
            # un-stabilize ties. Float keys negate the values directly so
            # NaN stays LAST (np.lexsort sinks NaN; dsort's descending
            # negation behaves the same) — rank-negation via np.unique
            # would rank NaN highest and float NaN rows would surface
            # first, diverging from the mesh sort. Non-float keys
            # (strings, ints) negate the dense rank, which is
            # overflow-safe and works for objects.
            keys = []
            for c in reversed(cols):
                parts_c = [np.asarray(b.columns[c]) for b in blocks
                           if b.num_rows]
                k = (np.concatenate(parts_c) if parts_c
                     else np.empty(0))
                if descending:
                    if k.dtype.kind == "f":
                        k = -k
                    else:
                        k = -np.unique(k, return_inverse=True)[1]
                keys.append(k)
            order = np.lexsort(keys) if n else np.empty(0, np.int64)
            del keys
            if n < 2 ** 31:
                order = order.astype(np.int32)  # halve the index footprint
            spans = _split_even(n, parts)
            out_blocks = []
            col_srcs = {name: [b.columns[name] for b in blocks]
                        for name in self._schema.names}
            col_dense = {name: all(isinstance(s, np.ndarray)
                                   for s in srcs)
                         for name, srcs in col_srcs.items()}
            for a, e in spans:
                # per-span index mapping: global blk_of/loc arrays would
                # add another ~1x of int64 per 8-byte row
                osel = order[a:e]
                bsel = np.searchsorted(offsets[1:], osel,
                                       side="right").astype(np.int32)
                lsel = osel - offsets[bsel]
                # source-block masks computed once per span, shared by
                # every column
                span_blocks = np.unique(bsel)
                masks = [(bi, bsel == bi) for bi in span_blocks]
                cols_out: Dict[str, Column] = {}
                for name in self._schema.names:
                    srcs = col_srcs[name]
                    if col_dense[name] and srcs:
                        first = srcs[bsel[0]] if e > a else srcs[0]
                        out_a = np.empty((e - a,) + first.shape[1:],
                                         first.dtype)
                        for bi, m in masks:
                            out_a[m] = srcs[bi][lsel[m]]
                        cols_out[name] = out_a
                    else:  # ragged list columns reorder by index
                        cols_out[name] = [srcs[bi][i]
                                          for bi, i in zip(bsel, lsel)]
                out_blocks.append(Block(cols_out, e - a))
            return out_blocks

        from .memory.estimate import propagate_hints
        rows_h, bytes_h = propagate_hints(self, self._schema)
        return TensorFrame(self._schema, run, parts,
                           plan=f"order_by{cols}({self._plan})",
                           rows_hint=rows_h, bytes_hint=bytes_h)

    def analyze(self) -> "TensorFrame":
        from . import api
        return api.analyze(self)

    # -- introspection -----------------------------------------------------
    def explain(self) -> str:
        """Human-readable execution report of this frame's forcing: rows,
        blocks, bytes marshalled, retries, OOM splits, sync fallbacks,
        compile-cache behavior (with compile seconds), wall time by
        stage, and — when the forcing touched the mesh layer — a mesh
        section with per-device rows/bytes/time, a straggler ratio, and
        HBM watermarks where the backend reports memory stats
        (``docs/observability.md``).

        Renders the trace recorded when the frame was forced with tracing
        enabled (``TFT_TRACE=1``). An untraced (or unforced) frame is
        (re-)forced once with tracing temporarily enabled process-wide —
        i.e. calling ``explain()`` post-hoc re-executes this frame's plan
        and pays that cost; force under ``TFT_TRACE=1`` to avoid it. For
        eager results (``reduce_*``/``aggregate``) use
        ``tft.last_query_report()``. Distinct from the function
        ``tft.explain(df)``, which describes the SCHEMA (reference
        parity).
        """
        from .observability import frame_report
        return frame_report(self)

    def explain_tensors(self) -> str:
        return self._schema.tree_string()


class GroupedFrame:
    """The result of ``TensorFrame.group_by`` (RelationalGroupedDataset
    analogue) — consumed by ``aggregate``."""

    def __init__(self, frame: TensorFrame, keys: List[str]):
        self.frame = frame
        self.keys = keys

    def __repr__(self):
        return f"GroupedFrame(keys={self.keys}, frame={self.frame!r})"


def frame(data, columns: Sequence[str] = None,
          schema: Optional[Schema] = None,
          num_partitions: int = 1) -> TensorFrame:
    """Convenience constructor: rows (list of tuples) or dict of columns."""
    if isinstance(data, dict):
        return TensorFrame.from_columns(data, schema=schema,
                                        num_partitions=num_partitions)
    return TensorFrame.from_rows(data, columns=columns, schema=schema,
                                 num_partitions=num_partitions)
