"""ctypes binding to the C++ PJRT execution core (``native/libtfrpjrt.so``).

The reference bottoms out every graph execution in C++ — a libtensorflow
``Session.Run`` reached through JNI (``TensorFlowOps.scala:46-64``,
``DebugRowOps.scala:776-788``). This is the TPU-native equivalent: the
driver (Python) authors and lowers a computation to StableHLO, and the
native core compiles + executes it against XLA **in C++** — XLA:CPU linked
in-process for local runs, or any PJRT C API plugin (``libtpu.so``) on TPU
hosts. Results are written straight into caller-allocated numpy arrays
(the ``tensor_data().asBuffer()`` zero-copy read analogue,
``DataOps.scala:373``).

Routing: :class:`PjrtBlockExecutor` drops into the engine anywhere a
:class:`~tensorframes_tpu.engine.executor.BlockExecutor` is accepted, or
set ``TFT_EXECUTOR=pjrt`` to make it the process default. The jax
in-process path remains the default and the fallback.
"""

from __future__ import annotations

import ctypes
import os
import threading
import time
from typing import Dict, Mapping, Optional, Tuple

import numpy as np

from . import dtypes as _dt
from .computation import Computation
from .observability import events as _obs
from .utils.logging import get_logger
from .utils.tracing import counters as _counters
from .utils.tracing import enabled as _tracing_enabled
from .utils.tracing import histograms as _histograms

__all__ = ["available", "PjrtCoreClient", "PjrtBlockExecutor",
           "PjrtDeviceBuffer"]

_log = get_logger("native_pjrt")

# tfr_dtype codes from native/tfrpjrt.h
_CODES = {
    np.dtype(np.float32): 1,
    np.dtype(np.float64): 2,
    np.dtype(np.int32): 3,
    np.dtype(np.int64): 4,
    np.dtype(np.bool_): 6,
}
_NP_FROM_CODE = {1: np.dtype(np.float32), 2: np.dtype(np.float64),
                 3: np.dtype(np.int32), 4: np.dtype(np.int64),
                 6: np.dtype(np.bool_)}
_BF16_CODE = 5

_lib: Optional[ctypes.CDLL] = None
_load_attempted = False
_ERRLEN = 4096


def _find_library() -> Optional[str]:
    cand = os.environ.get("TFT_PJRT_LIB")
    if cand and os.path.exists(cand):
        return cand
    here = os.path.dirname(os.path.abspath(__file__))
    for rel in (os.path.join(here, "..", "native", "libtfrpjrt.so"),
                os.path.join(here, "libtfrpjrt.so")):
        p = os.path.abspath(rel)
        if os.path.exists(p):
            return p
    return None


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    if _load_attempted:
        return _lib
    _load_attempted = True
    if os.environ.get("TFT_DISABLE_NATIVE"):
        return None
    path = _find_library()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError as e:
        _log.warning("libtfrpjrt.so failed to load: %s", e)
        return None
    vp = ctypes.c_void_p
    ci = ctypes.c_int
    cll = ctypes.c_longlong
    lib.tfr_pjrt_client_create.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                           ci]
    lib.tfr_pjrt_client_create.restype = vp
    lib.tfr_pjrt_client_destroy.argtypes = [vp]
    lib.tfr_pjrt_client_device_count.argtypes = [vp]
    lib.tfr_pjrt_client_device_count.restype = ci
    lib.tfr_pjrt_client_platform.argtypes = [vp, ctypes.c_char_p, ci]
    lib.tfr_pjrt_client_platform.restype = ci
    lib.tfr_pjrt_compile.argtypes = [vp, ctypes.c_char_p, ctypes.c_long,
                                     ctypes.c_char_p, ci]
    lib.tfr_pjrt_compile.restype = vp
    lib.tfr_pjrt_compile_dynamic.argtypes = [
        vp, ctypes.c_char_p, ctypes.c_long, ci, ctypes.c_char_p,
        ctypes.c_char_p, ci, ctypes.POINTER(ci), ctypes.POINTER(ci),
        ctypes.POINTER(cll), ctypes.c_char_p, ci]
    lib.tfr_pjrt_compile_dynamic.restype = vp
    lib.tfr_pjrt_compile_dynamic_n.argtypes = [
        vp, ctypes.c_char_p, ctypes.c_long, ci, ctypes.c_char_p,
        ctypes.c_char_p, ci, ctypes.POINTER(ci), ctypes.POINTER(ci),
        ctypes.POINTER(cll), ci, ctypes.c_char_p, ci]
    lib.tfr_pjrt_compile_dynamic_n.restype = vp
    lib.tfr_pjrt_compile_n.argtypes = [vp, ctypes.c_char_p, ctypes.c_long,
                                       ci, ctypes.c_char_p, ci]
    lib.tfr_pjrt_compile_n.restype = vp
    lib.tfr_pjrt_compile_spmd.argtypes = [vp, ctypes.c_char_p,
                                          ctypes.c_long, ci,
                                          ctypes.c_char_p, ci]
    lib.tfr_pjrt_compile_spmd.restype = vp
    lib.tfr_pjrt_execute_replicated.argtypes = [
        vp, vp, ci, ci, ctypes.POINTER(ci), ctypes.POINTER(ci),
        ctypes.POINTER(cll), ctypes.POINTER(vp), ctypes.c_char_p, ci]
    lib.tfr_pjrt_execute_replicated.restype = vp
    lib.tfr_pjrt_exe_destroy.argtypes = [vp]
    lib.tfr_pjrt_execute.argtypes = [vp, vp, ci, ctypes.POINTER(ci),
                                     ctypes.POINTER(ci),
                                     ctypes.POINTER(cll),
                                     ctypes.POINTER(vp), ctypes.c_char_p, ci]
    lib.tfr_pjrt_execute.restype = vp
    lib.tfr_pjrt_results_count.argtypes = [vp]
    lib.tfr_pjrt_results_count.restype = ci
    lib.tfr_pjrt_result_meta.argtypes = [vp, ci, ctypes.POINTER(ci),
                                         ctypes.POINTER(ci),
                                         ctypes.POINTER(cll)]
    lib.tfr_pjrt_result_meta.restype = ci
    lib.tfr_pjrt_result_read.argtypes = [vp, ci, vp, cll, ctypes.c_char_p,
                                         ci]
    lib.tfr_pjrt_result_read.restype = ci
    lib.tfr_pjrt_results_destroy.argtypes = [vp]
    try:
        lib.tfr_pjrt_result_release_buffer.argtypes = [vp, ci]
        lib.tfr_pjrt_result_release_buffer.restype = vp
        lib.tfr_pjrt_buffer_meta.argtypes = [vp, ctypes.POINTER(ci),
                                             ctypes.POINTER(ci),
                                             ctypes.POINTER(cll)]
        lib.tfr_pjrt_buffer_meta.restype = ci
        lib.tfr_pjrt_buffer_destroy.argtypes = [vp]
        lib.tfr_pjrt_execute_replicated_mixed.argtypes = [
            vp, vp, ci, ci, ctypes.POINTER(ci), ctypes.POINTER(ci),
            ctypes.POINTER(cll), ctypes.POINTER(vp), ctypes.POINTER(vp),
            ctypes.c_char_p, ci]
        lib.tfr_pjrt_execute_replicated_mixed.restype = vp
        lib._tfr_has_resident = True
    except AttributeError:
        # an older libtfrpjrt.so without the device-resident surface;
        # execute(keep_outputs=...) / device-buffer args will raise
        lib._tfr_has_resident = False
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def resolve_backend_spec(backend: str) -> str:
    """Expand backend shorthands into full native-core specs.

    ``"axon"`` / ``"axon:<ordinal>"`` expands to the tunnelled-TPU PJRT
    plugin (``PJRT_LIBRARY_PATH``) with the NamedValue create options the
    axon proxy requires — the same option set jax's plugin registration
    sends (topology/session/compile-mode), so the native core reaches the
    identical chip jax does. Everything else passes through unchanged
    (``cpu[:n]``, ``plugin:<path>[?opts]``).
    """
    if backend != "axon" and not backend.startswith("axon:"):
        return backend
    import uuid

    lib = os.environ.get("PJRT_LIBRARY_PATH")
    if not lib or not os.path.exists(lib):
        raise PjrtCoreError(
            "backend 'axon' needs PJRT_LIBRARY_PATH pointing at the axon "
            "PJRT plugin (.so)")
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")
    remote = 1 if os.environ.get("PALLAS_AXON_REMOTE_COMPILE") == "1" else 0
    # TFT_AXON_TOPOLOGY overrides for multi-chip grants — a 1x1x1 grant has
    # one addressable device, so 'axon:<ordinal>' with ordinal > 0 needs it
    topology = os.environ.get("TFT_AXON_TOPOLOGY", f"{gen}:1x1x1")
    opts = [
        ("remote_compile", remote),
        ("local_only", 0),
        ("priority", 0),
        ("topology", topology),
        ("n_slices", 1),
        ("session_id", str(uuid.uuid4())),
        # monoclient sentinel rank (axon.register.MULTIHOST_RANK)
        ("rank", 0xFFFF_FFFF),
    ]
    if ":" in backend:
        opts.append(("tfr_device", int(backend.split(":", 1)[1])))
    qs = "&".join(f"{k}={v}" for k, v in opts)
    return f"plugin:{lib}?{qs}"


class PjrtCoreError(RuntimeError):
    pass


def _dtype_code(dt: np.dtype) -> int:
    code = _CODES.get(dt)
    if code is None:
        if dt == _dt.bfloat16.np_storage:
            return _BF16_CODE
        raise PjrtCoreError(f"unsupported input dtype {dt}")
    return code


def _read_results(lib, res) -> list:
    """Decode every result buffer of a tfr_pjrt_results into numpy
    (shared by the single and replicated execute paths)."""
    err = ctypes.create_string_buffer(_ERRLEN)
    outs = []
    for i in range(lib.tfr_pjrt_results_count(res)):
        dt = ctypes.c_int()
        nd = ctypes.c_int()
        odims = (ctypes.c_longlong * 8)()
        if lib.tfr_pjrt_result_meta(res, i, ctypes.byref(dt),
                                    ctypes.byref(nd), odims):
            raise PjrtCoreError(f"result {i}: meta query failed")
        shape = tuple(odims[k] for k in range(nd.value))
        np_dt = (_dt.bfloat16.np_storage if dt.value == _BF16_CODE
                 else _NP_FROM_CODE.get(dt.value))
        if np_dt is None:
            raise PjrtCoreError(
                f"result {i}: unsupported dtype code {dt.value}")
        out = np.empty(shape, np_dt)
        if lib.tfr_pjrt_result_read(
                res, i, out.ctypes.data_as(ctypes.c_void_p),
                out.nbytes, err, _ERRLEN):
            raise PjrtCoreError(
                f"result {i}: {err.value.decode(errors='replace')}")
        outs.append(out)
    return outs


def _device_views(comp: "Computation", arrays: Mapping) -> Dict:
    """Inputs as contiguous device-dtype arrays (shared input prep)."""
    dev = {}
    for spec in comp.inputs:
        a = np.ascontiguousarray(arrays[spec.name])
        dd = _dt.device_dtype(spec.dtype)
        if a.dtype != dd:
            from . import native as _native
            a = _native.convert(a, dd)
        dev[spec.name] = a
    return dev


def _to_storage(comp: "Computation", outs) -> Dict:
    """Zip outputs back to names + storage dtypes (shared output conv)."""
    rec = {}
    for spec, a in zip(comp.outputs, outs):
        storage = spec.dtype.np_storage
        if a.dtype != storage and spec.dtype is not _dt.bfloat16:
            from . import native as _native
            a = _native.convert(a, storage)
        rec[spec.name] = a
    return rec


class PjrtCoreClient:
    """A native PJRT client: the per-host analogue of the reference's
    per-executor TF C++ session factory (``TensorFlowOps.withSession``).

    ``backend``: ``"cpu"``/``"cpu:<n>"`` for in-process XLA:CPU, or
    ``"plugin:<path.so>"`` for a PJRT C API plugin (TPU: libtpu.so).
    """

    def __init__(self, backend: str = "cpu"):
        lib = _load()
        if lib is None:
            raise PjrtCoreError(
                "libtfrpjrt.so is not available; build it with "
                "`make -C native pjrt`")
        self._lib = lib
        backend = resolve_backend_spec(backend)
        err = ctypes.create_string_buffer(_ERRLEN)
        self._client = lib.tfr_pjrt_client_create(
            backend.encode(), err, _ERRLEN)
        if not self._client:
            raise PjrtCoreError(
                f"client create failed: {err.value.decode(errors='replace')}")
        self.backend = backend

    @property
    def device_count(self) -> int:
        return self._lib.tfr_pjrt_client_device_count(self._client)

    @property
    def platform(self) -> str:
        buf = ctypes.create_string_buffer(256)
        self._lib.tfr_pjrt_client_platform(self._client, buf, 256)
        return buf.value.decode()

    def compile(self, stablehlo: bytes) -> "PjrtExecutable":
        err = ctypes.create_string_buffer(_ERRLEN)
        h = self._lib.tfr_pjrt_compile(self._client, stablehlo,
                                       len(stablehlo), err, _ERRLEN)
        if not h:
            raise PjrtCoreError(
                f"compile failed: {err.value.decode(errors='replace')}")
        return PjrtExecutable(self, h)

    def compile_dynamic(self, module: bytes, cc_version: int, platforms,
                        arg_dtypes, arg_shapes, n_replicas: int = 1):
        """Compile a serialized dynamic-shape module (jax.export wire
        format) at concrete shapes: refinement happens in the native core,
        no jax involved. ``arg_dtypes``: numpy dtypes; ``arg_shapes``:
        tuples. ``n_replicas > 1`` compiles SPMD-replicated and returns a
        :class:`PjrtReplicatedExecutable`."""
        n = len(arg_dtypes)
        dtypes = (ctypes.c_int * n)()
        ndims = (ctypes.c_int * n)()
        flat = []
        for i, (dt, shp) in enumerate(zip(arg_dtypes, arg_shapes)):
            dtypes[i] = _dtype_code(np.dtype(dt))
            ndims[i] = len(shp)
            flat.extend(shp)
        dims = (ctypes.c_longlong * max(1, len(flat)))(*flat)
        select = self.platform
        if select not in platforms and platforms:
            raise PjrtCoreError(
                f"computation was lowered for {platforms}, not for this "
                f"client's platform {select!r}")
        err = ctypes.create_string_buffer(_ERRLEN)
        h = self._lib.tfr_pjrt_compile_dynamic_n(
            self._client, module, len(module), cc_version,
            ",".join(platforms).encode(), select.encode(), n, dtypes,
            ndims, dims, n_replicas, err, _ERRLEN)
        if not h:
            raise PjrtCoreError(
                f"dynamic compile failed: "
                f"{err.value.decode(errors='replace')}")
        if n_replicas > 1:
            return PjrtReplicatedExecutable(self, h, n_replicas)
        return PjrtExecutable(self, h)

    def compile_replicated(self, stablehlo: bytes,
                           n_replicas: int) -> "PjrtReplicatedExecutable":
        """Compile for ``n_replicas`` devices (SPMD replication); run all
        replicas in one native call via the returned executable."""
        err = ctypes.create_string_buffer(_ERRLEN)
        h = self._lib.tfr_pjrt_compile_n(self._client, stablehlo,
                                         len(stablehlo), n_replicas, err,
                                         _ERRLEN)
        if not h:
            raise PjrtCoreError(
                f"replicated compile failed: "
                f"{err.value.decode(errors='replace')}")
        return PjrtReplicatedExecutable(self, h, n_replicas)

    def compile_spmd(self, stablehlo: bytes,
                     n_partitions: int) -> "PjrtReplicatedExecutable":
        """GSPMD-partitioned compile: ONE logical program spanning
        ``n_partitions`` devices. ``stablehlo`` is a jax mesh lowering
        (GSPMD flavor, ``mhlo.sharding``-annotated global shapes); XLA's
        SPMD partitioner inside the native core derives the per-device
        program and its collectives. Execute with per-device SHARDS
        (device-major, equal shapes); sharded outputs come back as
        per-device shards, replicated outputs as one copy per device."""
        err = ctypes.create_string_buffer(_ERRLEN)
        h = self._lib.tfr_pjrt_compile_spmd(self._client, stablehlo,
                                            len(stablehlo), n_partitions,
                                            err, _ERRLEN)
        if not h:
            raise PjrtCoreError(
                f"spmd compile failed: "
                f"{err.value.decode(errors='replace')}")
        return PjrtReplicatedExecutable(self, h, n_partitions)

    def close(self):
        if self._client:
            self._lib.tfr_pjrt_client_destroy(self._client)
            self._client = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class PjrtExecutable:
    """A compiled program held by the native core."""

    def __init__(self, client: PjrtCoreClient, handle):
        self._client = client
        self._h = handle

    def execute(self, arrays) -> list:
        """Run on dense row-major host arrays; returns numpy arrays."""
        lib = self._client._lib
        n = len(arrays)
        arrays = [np.ascontiguousarray(a) for a in arrays]
        dtypes = (ctypes.c_int * n)()
        ndims = (ctypes.c_int * n)()
        flat_dims = []
        datas = (ctypes.c_void_p * n)()
        for i, a in enumerate(arrays):
            dtypes[i] = _dtype_code(a.dtype)
            ndims[i] = a.ndim
            flat_dims.extend(a.shape)
            datas[i] = a.ctypes.data_as(ctypes.c_void_p)
        dims = (ctypes.c_longlong * max(1, len(flat_dims)))(*flat_dims)
        err = ctypes.create_string_buffer(_ERRLEN)
        res = lib.tfr_pjrt_execute(self._client._client, self._h, n, dtypes,
                                   ndims, dims, datas, err, _ERRLEN)
        if not res:
            raise PjrtCoreError(
                f"execute failed: {err.value.decode(errors='replace')}")
        try:
            return _read_results(lib, res)
        finally:
            lib.tfr_pjrt_results_destroy(res)

    def close(self):
        if self._h:
            self._client._lib.tfr_pjrt_exe_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class PjrtDeviceBuffer:
    """A DEVICE-RESIDENT buffer detached from a replicated result set.

    Holds device (HBM) memory owned by the native core; pass it back as
    an input slot of :meth:`PjrtReplicatedExecutable.execute` to chain
    dispatches without the per-call host round-trip (the residency the
    jax path gets from ``jax.Array``). The buffer lives on the replica
    device that produced it — reuse it only in the same replica slot.
    """

    def __init__(self, client: PjrtCoreClient, handle, dtype: np.dtype,
                 shape: Tuple[int, ...]):
        self._client = client
        self._h = handle
        self.dtype = np.dtype(dtype)
        self.shape = tuple(shape)

    def close(self):
        if self._h:
            self._client._lib.tfr_pjrt_buffer_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class PjrtReplicatedExecutable:
    """A program compiled for N devices; one ``execute`` call runs every
    replica in parallel inside the native core — the in-process analogue
    of the reference's fleet of executor sessions each running the same
    shipped graph on its partition (``DebugRowOps.scala:372-386``)."""

    def __init__(self, client: PjrtCoreClient, handle, n_replicas: int):
        self._client = client
        self._h = handle
        self.n_replicas = n_replicas

    def execute(self, per_replica_args, keep_outputs: bool = False) -> list:
        """``per_replica_args``: list of ``n_replicas`` argument lists
        (equal shapes/dtypes across replicas — XLA's static world). An
        argument may be a :class:`PjrtDeviceBuffer` (device-resident, no
        host upload for that slot). Returns one output list per replica —
        numpy arrays, or :class:`PjrtDeviceBuffer` handles when
        ``keep_outputs`` (no host download; feed them back in)."""
        lib = self._client._lib
        if len(per_replica_args) != self.n_replicas:
            raise PjrtCoreError(
                f"expected {self.n_replicas} replica argument lists, got "
                f"{len(per_replica_args)}")
        nargs = len(per_replica_args[0])
        views = [[a if isinstance(a, PjrtDeviceBuffer)
                  else np.ascontiguousarray(a) for a in rep]
                 for rep in per_replica_args]
        first = views[0]
        has_dev = any(isinstance(a, PjrtDeviceBuffer)
                      for rep in views for a in rep)
        if (has_dev or keep_outputs) and \
                not getattr(lib, "_tfr_has_resident", False):
            raise PjrtCoreError(
                "this libtfrpjrt.so predates device-resident buffers; "
                "rebuild with make -C native pjrt")
        dtypes = (ctypes.c_int * nargs)()
        ndims = (ctypes.c_int * nargs)()
        flat_dims = []
        for i, a in enumerate(first):
            dtypes[i] = _dtype_code(a.dtype)
            ndims[i] = len(a.shape)
            flat_dims.extend(a.shape)
        for rep in views[1:]:
            if len(rep) != nargs or any(
                    b.shape != a.shape or b.dtype != a.dtype
                    for a, b in zip(first, rep)):
                raise PjrtCoreError(
                    "replica argument lists must share shapes and dtypes")
        dims = (ctypes.c_longlong * max(1, len(flat_dims)))(*flat_dims)
        n_total = self.n_replicas * nargs
        datas = (ctypes.c_void_p * n_total)()
        err = ctypes.create_string_buffer(_ERRLEN)
        if has_dev or keep_outputs:
            devs = (ctypes.c_void_p * n_total)()
            for r, rep in enumerate(views):
                for i, a in enumerate(rep):
                    if isinstance(a, PjrtDeviceBuffer):
                        if not a._h:
                            raise PjrtCoreError(
                                f"replica {r} arg {i}: device buffer "
                                f"already closed")
                        devs[r * nargs + i] = a._h
                    else:
                        datas[r * nargs + i] = a.ctypes.data_as(
                            ctypes.c_void_p)
            res = lib.tfr_pjrt_execute_replicated_mixed(
                self._client._client, self._h, self.n_replicas, nargs,
                dtypes, ndims, dims, datas, devs, err, _ERRLEN)
        else:
            for r, rep in enumerate(views):
                for i, a in enumerate(rep):
                    datas[r * nargs + i] = a.ctypes.data_as(ctypes.c_void_p)
            res = lib.tfr_pjrt_execute_replicated(
                self._client._client, self._h, self.n_replicas, nargs,
                dtypes, ndims, dims, datas, err, _ERRLEN)
        if not res:
            raise PjrtCoreError(
                f"replicated execute failed: "
                f"{err.value.decode(errors='replace')}")
        try:
            if keep_outputs:
                outs = self._release_all(lib, res)
            else:
                outs = _read_results(lib, res)
        finally:
            lib.tfr_pjrt_results_destroy(res)
        per_rep = len(outs) // self.n_replicas
        return [outs[r * per_rep:(r + 1) * per_rep]
                for r in range(self.n_replicas)]

    def _release_all(self, lib, res) -> list:
        """Detach every result as a device-resident buffer handle."""
        outs = []
        for i in range(lib.tfr_pjrt_results_count(res)):
            dt = ctypes.c_int()
            nd = ctypes.c_int()
            odims = (ctypes.c_longlong * 8)()
            if lib.tfr_pjrt_result_meta(res, i, ctypes.byref(dt),
                                        ctypes.byref(nd), odims):
                raise PjrtCoreError(f"result {i}: meta query failed")
            np_dt = (_dt.bfloat16.np_storage if dt.value == _BF16_CODE
                     else _NP_FROM_CODE.get(dt.value))
            if np_dt is None:
                raise PjrtCoreError(
                    f"result {i}: unsupported dtype code {dt.value}")
            h = lib.tfr_pjrt_result_release_buffer(res, i)
            if not h:
                raise PjrtCoreError(f"result {i}: buffer release failed")
            outs.append(PjrtDeviceBuffer(
                self._client, h, np_dt,
                tuple(odims[k] for k in range(nd.value))))
        return outs

    def close(self):
        if self._h:
            self._client._lib.tfr_pjrt_exe_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# Engine integration
# ---------------------------------------------------------------------------

class _PjrtPending:
    """In-flight native dispatch: ``drain()`` joins the worker future.

    The worker already executed through the executor's full resilient
    path, so a failure here re-raises (attributed to this block by the
    pipeline's FIFO drain) rather than re-running.
    """

    __slots__ = ("_future",)

    def __init__(self, future):
        self._future = future

    def drain(self) -> Dict[str, np.ndarray]:
        return self._future.result()


def _lower_stablehlo(comp: Computation, arrays: Mapping[str, np.ndarray],
                     in_names, out_names) -> bytes:
    """Lower a LIVE computation at these concrete shapes to StableHLO text.

    The driver-side authoring step (the reference built a GraphDef with real
    TF in Python, ``core.py:37-40``); jax is used for *tracing only* — the
    compile and every execution happen in the native core. Deserialized
    computations never come through here: their raw dynamic module is
    refined and compiled natively (``PjrtCoreClient.compile_dynamic``), so
    an executing host needs no jax at all.
    """
    import jax

    def flat_fn(*args):
        out = comp.fn(dict(zip(in_names, args)))
        return tuple(out[n] for n in out_names)

    avals = [jax.ShapeDtypeStruct(arrays[n].shape, arrays[n].dtype)
             for n in in_names]
    lowered = jax.jit(flat_fn).lower(*avals)
    text = str(lowered.compiler_ir("stablehlo")).encode()
    if b"?" not in text:
        return text
    # Legacy fallback only: blobs serialized before the raw-module section
    # existed deserialize with symbolic inner dims and no _native_dynamic;
    # refine them through jaxlib if it still exposes the pass. New blobs
    # never reach this (they compile via compile_dynamic, jax-free).
    try:
        from jax._src.lib import _jax as _jaxlib

        return _jaxlib.mlir.refine_polymorphic_shapes(
            text, enable_shape_assertions=True,
            validate_static_shapes=True)
    except (ImportError, AttributeError) as e:
        raise PjrtCoreError(
            "this computation carries symbolic dims but no raw dynamic "
            "module (a pre-native serialized blob) and this jax exposes "
            f"no refinement pass ({e}); re-serialize it with a current "
            "authoring host") from e


class PjrtBlockExecutor:
    """Block executor routing through the native PJRT core.

    Drop-in for :class:`~tensorframes_tpu.engine.executor.BlockExecutor`
    where an ``executor=`` argument is accepted: same ``run`` contract,
    same per-signature compile cache, but compilation and execution happen
    in C++ (per-executor sessions ↔ one native client per executor
    object). No ``pad_rows`` mode: the native path compiles exact shapes.
    """

    def __init__(self, backend: Optional[str] = None):
        import weakref

        backend = backend or os.environ.get("TFT_PJRT_BACKEND", "cpu")
        self.client = PjrtCoreClient(backend)
        self.pad_rows = False
        # weakly keyed by the live Computation (mirrors BlockExecutor):
        # entries die with it, so id() recycling cannot alias programs
        self._cache: "weakref.WeakKeyDictionary[Computation, Dict[Tuple, PjrtExecutable]]" = \
            weakref.WeakKeyDictionary()
        self._lock = threading.Lock()
        self._pool = None  # lazily-built single worker for submit()
        self.compile_count = 0

    def _compiled(self, comp: Computation, dev_arrays: Dict,
                  n_replicas: int = 1):
        """Per-(comp, signature[, replicas]) compile cache. Shipped
        computations (``_native_dynamic``) refine + compile natively;
        live ones lower through jax tracing."""
        in_names = [s.name for s in comp.inputs]
        sig = tuple((n, dev_arrays[n].shape, str(dev_arrays[n].dtype))
                    for n in in_names)
        if n_replicas > 1:
            sig = ("replicated", n_replicas) + sig
        per_comp = self._cache.get(comp)
        exe = None if per_comp is None else per_comp.get(sig)
        if exe is not None:
            if _tracing_enabled():  # hit stats must not lock the fast path
                _counters.inc("compile_cache.hits")
                _obs.add_event("compile_cache", hit=True, native=True)
            return exe
        with self._lock:
            per_comp = self._cache.setdefault(comp, {})
            exe = per_comp.get(sig)
            if exe is not None:
                if _tracing_enabled():
                    _counters.inc("compile_cache.hits")
                    _obs.add_event("compile_cache", hit=True, native=True)
                return exe
            t_c = time.perf_counter()  # native compiles are synchronous
            dyn = getattr(comp, "_native_dynamic", None)
            if dyn:
                exe = self.client.compile_dynamic(
                    dyn["module"], dyn["cc_version"], dyn["platforms"],
                    [dev_arrays[n].dtype for n in in_names],
                    [dev_arrays[n].shape for n in in_names],
                    n_replicas=n_replicas)
            else:
                hlo = _lower_stablehlo(comp, dev_arrays, in_names,
                                       [s.name for s in comp.outputs])
                exe = (self.client.compile_replicated(hlo, n_replicas)
                       if n_replicas > 1 else self.client.compile(hlo))
            dt = time.perf_counter() - t_c
            per_comp[sig] = exe
            self.compile_count += 1
            _counters.inc("compile_cache.misses")
            _histograms.observe("compile_seconds", dt, engine="native")
            _obs.add_event("compile_cache", hit=False, native=True)
            _obs.add_event("compile", name="native", dur=dt,
                           engine="native")
            _log.debug("native compile #%d for %s", self.compile_count,
                       sig)
            return exe

    def run(self, comp: Computation, arrays: Mapping[str, np.ndarray],
            pad_ok: bool = True) -> Dict[str, np.ndarray]:
        del pad_ok  # exact-shape compiles; padding never applies
        from .resilience import default_policy, faults

        in_names = [s.name for s in comp.inputs]
        dev_arrays = _device_views(comp, arrays)

        def attempt():
            faults.check("pjrt_execute")
            exe = self._compiled(comp, dev_arrays)
            outs = exe.execute([dev_arrays[n] for n in in_names])
            return _to_storage(comp, outs)

        # PjrtCoreError carries the PJRT status word (UNAVAILABLE /
        # ABORTED / ...) in its message, which is exactly what the
        # transient classifier keys on
        trace = _obs.current_trace()
        if trace is None:
            return default_policy().call(attempt, op="pjrt.execute")
        t0 = trace.clock()
        out = default_policy().call(attempt, op="pjrt.execute")
        trace.add("dispatch", name="pjrt.execute", ts=t0,
                  dur=trace.clock() - t0)
        return out

    def submit(self, comp: Computation, arrays: Mapping[str, np.ndarray],
               pad_ok: bool = True) -> "_PjrtPending":
        """Submit half for the pipelined engine (``engine/pipeline.py``):
        the native dispatch runs on a dedicated worker thread — the
        ctypes execute call releases the GIL, so the main thread marshals
        the next blocks while C++ computes this one. The worker runs the
        FULL resilient :meth:`run` (retry policy included), so ``drain``
        re-raises a failure instead of re-running it; one worker keeps
        device dispatches serialized like the serial path.
        """
        pool = self._pool
        if pool is None:
            from concurrent.futures import ThreadPoolExecutor
            with self._lock:
                if self._pool is None:
                    self._pool = ThreadPoolExecutor(
                        max_workers=1,
                        thread_name_prefix="tfr-pjrt-submit")
                pool = self._pool
        # wrap_context carries the submitting query's correlation id
        # (contextvars) onto the worker thread, so events the resilient
        # run records over there still attach to the right QueryTrace
        return _PjrtPending(pool.submit(_obs.wrap_context(self.run),
                                        comp, arrays, pad_ok))

    def run_blocks_parallel(self, comp: Computation, blocks,
                            ) -> "list[Dict[str, np.ndarray]]":
        """Run one map computation over MANY blocks in parallel — native
        replicated dispatches in device-count-sized waves when the blocks
        share shapes, else the sequential per-block path.

        The parallel case is the reference's executor fleet in-process:
        every device runs the same compiled program on its own partition,
        one C++ call per wave. Works for shipped (jax-free) computations
        too — the replicated compile goes through the native refinement.
        """
        blocks = list(blocks)
        if not blocks:
            return []
        in_names = [s.name for s in comp.inputs]
        prepared = [_device_views(comp, arrays) for arrays in blocks]
        sig0 = tuple((n, prepared[0][n].shape, str(prepared[0][n].dtype))
                     for n in in_names)
        uniform = all(
            tuple((n, p[n].shape, str(p[n].dtype)) for n in in_names)
            == sig0 for p in prepared[1:])
        wave = min(len(prepared), self.client.device_count)
        if not uniform or wave < 2:
            return [self.run(comp, p, pad_ok=False) for p in prepared]

        results: "list[Dict[str, np.ndarray]]" = []
        i = 0
        # full waves run replicated; the ragged tail (< wave blocks, a
        # different replica count) takes the sequential path rather than
        # paying a second replicated compile
        while len(prepared) - i >= wave:
            exe = self._compiled(comp, prepared[i], n_replicas=wave)
            rep_outs = exe.execute(
                [[p[nm] for nm in in_names]
                 for p in prepared[i:i + wave]])
            results.extend(_to_storage(comp, outs) for outs in rep_outs)
            i += wave
        for p in prepared[i:]:
            results.append(self.run(comp, p, pad_ok=False))
        return results

    def clear(self):
        with self._lock:
            for per_comp in self._cache.values():
                for exe in per_comp.values():
                    exe.close()
            self._cache.clear()
