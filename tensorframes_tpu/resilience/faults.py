"""Deterministic fault injection for the resilience layer.

The retry/fallback paths in this package exist for failures that cannot
be produced on demand — a TPU coordinator timing out, a bucketed compile
dying inside PJRT, an HBM OOM. This harness makes them reproducible:
instrumented sites in the execution layers call :func:`check(site)
<check>`, which raises a scripted :class:`InjectedFault` while that
site's budget lasts, then goes quiet. The tier-1 resilience suite drives
every recovery path end-to-end on CPU this way.

Two drivers:

- context manager (tests): ``with inject("compile", fail_n=2): ...`` —
  the first two ``check("compile")`` calls raise, the third passes.
- environment (whole-process experiments): ``TFT_FAULTS="compile:2,
  dispatch:1"`` arms the same budgets at import time — useful for
  chaos-testing a real run without editing code.

Instrumented sites (see ``docs/resilience.md``):

========== ===========================================================
site        raised from
========== ===========================================================
cluster_init ``parallel.cluster.initialize`` bootstrap attempt
compile      ``engine.executor.BlockExecutor`` signature compile
dispatch     ``engine.executor.BlockExecutor`` block dispatch
pad_compile  ``engine.executor.PaddingExecutor`` bucketed-compile path
oom          ``engine.executor.BlockExecutor`` dispatch, OOM-shaped
drain        ``engine.executor.PendingBlock.drain`` pipelined readback
pjrt_execute ``native_pjrt.PjrtBlockExecutor`` native-core dispatch
dmap         ``parallel.distributed.dmap_blocks`` mesh dispatch
batch        ``stream.runtime.StreamHandle`` per-batch processing
device       ``parallel.elastic.elastic_call`` mesh-op dispatch boundary
             (device-loss shaped: the elastic layer shrinks the mesh)
preempt      ``engine.preempt.boundary`` pipelined block boundary — NOT
             raised out of the query: the active preemption scope
             converts the fault into a preempt request, so
             ``TFT_FAULTS=preempt:N`` deterministically parks a running
             query at its next N block boundaries (``docs/serving.md``)
worker       ``engine.preempt.boundary`` (running query) and
             ``serve.fabric`` heartbeat (idle worker) — like ``preempt``
             it is NOT raised out of the query: the scope parks the
             query (checkpoint persisted) and flags the worker as
             crashed, so ``TFT_FAULTS=worker:1`` deterministically kills
             one serving worker mid-query; the fabric declares it
             ``worker_lost`` and resumes elsewhere (``docs/serving.md``)
perf         ``plan.execute`` forcings and ``plan.dist`` fused-stage
             dispatch — NEVER raises: :func:`slowdown` consumes the
             budget and SLEEPS ``TFT_FAULT_PERF_S`` seconds (default
             0.05) inside the timed stage, so
             ``TFT_FAULTS=perf:1`` deterministically makes the next
             forcing slower with correct stage attribution — the
             performance-regression sentinel's drill
             (``docs/observability.md``)
disk         ``memory.persist`` artifact reads (checkpoints / results /
             baselines) — never raised out of a query: the persist tier
             is best-effort, so an injected read failure degrades that
             load to the cold path (counted). Arm with a message
             containing ``corrupt`` to flip payload bytes instead of
             failing the read, driving the sha256 checksum-mismatch
             path (``memory.persist_corrupt``)
========== ===========================================================

The same table is exported programmatically as :func:`sites` — chaos
schedules (``resilience/chaos.py``) and the conformance meta-test
validate against it, and :func:`arm` warns loudly on a site it does not
know so a typo in ``TFT_FAULTS``/``TFT_CHAOS`` can never arm a vacuous
drill silently.

Counting is deterministic (a lock-guarded integer per site, decremented
per check), so a test asserting "succeeds on the 3rd attempt" is exact,
never flaky. The chaos scheduler composes on top of this: while a
schedule is active, a :func:`check` whose site has no scripted budget
consults it, and a seed-deterministic firing arms a one-shot budget
through :func:`arm` — same machinery, same counters, same shaping.
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Dict, Iterator, Optional

from ..utils.logging import get_logger
from ..utils.tracing import counters

__all__ = ["InjectedFault", "inject", "check", "arm", "reset", "active",
           "may_fire", "slowdown", "sites", "set_chaos_hook"]

_log = get_logger("resilience.faults")

# the full site table, programmatically: site -> where it fires (the
# docstring table's machine-readable twin). Chaos schedules validate
# their site lists against this, and the conformance meta-test asserts
# every entry is driven by at least one tier-1 test.
_SITES = {
    "cluster_init": "parallel.cluster.initialize bootstrap attempt",
    "compile": "engine.executor.BlockExecutor signature compile",
    "dispatch": "engine.executor.BlockExecutor block dispatch",
    "pad_compile": "engine.executor.PaddingExecutor bucketed compile",
    "oom": "engine.executor.BlockExecutor dispatch, OOM-shaped",
    "drain": "engine.executor.PendingBlock.drain pipelined readback",
    "pjrt_execute": "native_pjrt.PjrtBlockExecutor native-core dispatch",
    "dmap": "parallel.distributed.dmap_blocks mesh dispatch",
    "batch": "stream.runtime.StreamHandle per-batch processing",
    "device": "parallel.elastic.elastic_call dispatch (device-loss "
              "shaped: the elastic layer shrinks the mesh)",
    "worker": "engine.preempt.boundary / serve.fabric heartbeat "
              "(worker-loss shaped: park + fabric re-placement)",
    "preempt": "engine.preempt.boundary (converted to a park request, "
               "never raised out of the query)",
    "perf": "plan.execute / plan.dist timed stages (slowdown: sleeps "
            "TFT_FAULT_PERF_S inside the stage, never raises)",
    "disk": "memory.persist artifact reads (read failure, or checksum "
            "corruption when armed with a 'corrupt' message)",
}


def sites() -> Dict[str, str]:
    """The instrumented fault-site table: ``{site: where it fires}``.

    The single source of truth for what :func:`arm` can usefully arm —
    chaos schedules (``resilience/chaos.py``) reject sites outside it,
    and the docs conformance test keeps ``docs/resilience.md`` in sync
    with it."""
    return dict(_SITES)


class InjectedFault(RuntimeError):
    """A scripted failure from :func:`check`.

    ``transient=True`` (default) makes it retryable under
    :func:`~.classify.is_transient`; ``message`` can be shaped to hit
    other classifiers (e.g. ``RESOURCE_EXHAUSTED`` for the OOM split
    path — :func:`inject` does this automatically for the ``oom`` site).
    """

    def __init__(self, site: str, message: Optional[str] = None,
                 transient: bool = True):
        self.site = site
        self.transient = transient
        super().__init__(
            message or f"injected transient fault at site {site!r}")


class _State:
    def __init__(self):
        self.lock = threading.Lock()
        self.budgets: Dict[str, int] = {}
        self.messages: Dict[str, Optional[str]] = {}
        self.transient: Dict[str, bool] = {}
        self._armed_env = False


_state = _State()

# the "oom" site must be caught by classify.is_oom, not retried
_OOM_MESSAGE = ("RESOURCE_EXHAUSTED: injected fault: out of memory "
                "allocating scratch for block")

# the "device" site must be caught by classify.is_device_lost (mesh
# shrink), not the retry loop; the device index in the message is what
# parallel.elastic parses to pick the shard to drop
_DEVICE_MESSAGE = ("DEVICE_LOST: injected fault: device %d is lost "
                   "(chip failure simulated)")

# the "worker" site must be caught by classify.is_worker_lost (fabric
# re-placement + checkpoint resume), never the retry loop
_WORKER_MESSAGE = ("WORKER_LOST: injected fault: worker process died "
                   "(crash simulated)")

# the "disk" site never escapes memory.persist (its reads are
# best-effort try/except); non-transient so nothing would retry it if
# an instrumentation point outside that layer ever picked it up
_DISK_MESSAGE = ("injected disk fault: persist artifact read failed "
                 "(I/O error simulated)")

# set by resilience.chaos while a schedule is active: called with the
# site on every budget-exhausted check; returns True after arming a
# one-shot seed-deterministic budget for it (None costs one load)
_chaos_hook = None


def set_chaos_hook(hook) -> None:
    """Install (or clear with ``None``) the chaos scheduler's consult
    hook — owned by ``resilience.chaos``; not a public tuning point."""
    global _chaos_hook
    _chaos_hook = hook


def _arm_from_env() -> None:
    """Parse ``TFT_FAULTS="site:count,site:count"`` once per process."""
    with _state.lock:
        if _state._armed_env:
            return
        _state._armed_env = True
        raw = os.environ.get("TFT_FAULTS", "")
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        site, _, count = part.partition(":")
        try:
            arm(site.strip(), int(count) if count else 1)
        except ValueError:
            _log.warning("ignoring malformed TFT_FAULTS entry %r", part)
    # the chaos twin: TFT_CHAOS arms a seeded schedule the same lazy
    # way (memoized inside; a no-op without the knob)
    from . import chaos as _chaos
    _chaos.maybe_start_from_env()


def arm(site: str, fail_n: int = 1, message: Optional[str] = None,
        transient: Optional[bool] = None) -> None:
    """Arm ``site`` to fail its next ``fail_n`` checks.

    ``transient`` defaults to True except for the ``oom`` site, whose
    faults must reach the OOM classifier (split-block re-dispatch), not
    the retry loop, and the ``device`` site, whose faults must reach the
    device-loss classifier (mesh shrink + re-shard, ``TFT_FAULT_DEVICE``
    selects the reported device index, default 0). The ``worker`` and
    ``disk`` sites are likewise non-transient by default (re-placement
    and the persist cold path respectively, never a retry).
    """
    if fail_n < 0:
        raise ValueError(f"fail_n must be >= 0, got {fail_n}")
    if site not in _SITES:
        # loud, not fatal: arming still proceeds (a nothing-checks-it
        # site is harmless) but a typo in TFT_FAULTS / TFT_CHAOS must
        # never turn a drill vacuous silently
        counters.inc("faults.unknown_sites")
        _log.warning(
            "arming UNKNOWN fault site %r — no instrumentation point "
            "checks it, so this budget will never fire; known sites: "
            "%s (faults.sites())", site, ", ".join(sorted(_SITES)))
    if site == "oom":
        if message is None:
            message = _OOM_MESSAGE
        if transient is None:
            transient = False
    elif site == "device":
        if message is None:
            from .policy import env_int
            message = _DEVICE_MESSAGE % env_int("TFT_FAULT_DEVICE", 0)
        if transient is None:
            transient = False
    elif site == "worker":
        if message is None:
            message = _WORKER_MESSAGE
        if transient is None:
            transient = False
    elif site == "disk":
        if message is None:
            message = _DISK_MESSAGE
        if transient is None:
            transient = False
    elif transient is None:
        transient = True
    with _state.lock:
        _state.budgets[site] = fail_n
        _state.messages[site] = message
        _state.transient[site] = transient
    _log.debug("fault site %r armed for %d failure(s)", site, fail_n)


def reset(site: Optional[str] = None) -> None:
    """Disarm one site, or every site when ``site`` is None."""
    with _state.lock:
        if site is None:
            _state.budgets.clear()
            _state.messages.clear()
            _state.transient.clear()
        else:
            _state.budgets.pop(site, None)
            _state.messages.pop(site, None)
            _state.transient.pop(site, None)


def active(site: str) -> int:
    """Remaining scripted failures for ``site`` (0 when disarmed)."""
    _arm_from_env()
    with _state.lock:
        return _state.budgets.get(site, 0)


def may_fire(site: str) -> bool:
    """True when a :func:`check` of ``site`` could raise right now: a
    scripted budget is armed, or an active chaos schedule names the
    site. For gated instrumentation points
    (``engine.preempt.boundary``) that only enter their fault branch
    when something might fire — gating on :func:`active` alone would
    make those sites invisible to chaos schedules."""
    if active(site) > 0:
        return True
    if _chaos_hook is None:
        return False
    from . import chaos as _chaos
    sched = _chaos.active()
    return sched is not None and site in sched.sites


def _consume(site: str):
    """Take one unit of ``site``'s budget, returning ``(left, message,
    transient)`` — or ``None`` when the site is disarmed."""
    with _state.lock:
        left = _state.budgets.get(site, 0)
        if left <= 0:
            return None
        _state.budgets[site] = left - 1
        return (left - 1, _state.messages.get(site),
                _state.transient.get(site, True))


def check(site: str) -> None:
    """Raise the site's scripted fault while its budget lasts.

    Instrumentation points call this unconditionally: the disarmed path
    is one env read (memoized) plus a dict lookup under a lock (plus
    one global load for the chaos hook). With a chaos schedule active
    and no scripted budget, the schedule decides seed-deterministically
    whether this check fires — a firing arms a one-shot budget via
    :func:`arm` (site-correct message shaping included) and consumes it
    here, so chaos faults are indistinguishable from scripted ones.
    """
    _arm_from_env()
    got = _consume(site)
    if got is None:
        hook = _chaos_hook
        if hook is None or not hook(site):
            return
        got = _consume(site)  # the firing armed a one-shot budget
        if got is None:
            return  # lost a race with reset(); the firing was recorded
    left, message, transient = got
    counters.inc(f"faults.{site}.injected")
    _log.info("injecting fault at site %r (%d more scripted)",
              site, left)
    raise InjectedFault(site, message, transient=transient)


def slowdown(site: str = "perf") -> float:
    """The sleep-shaped sibling of :func:`check`: while the site's
    budget lasts, sleep ``TFT_FAULT_PERF_S`` seconds (default 0.05)
    INSIDE the caller's timed region and return the duration slept —
    never raises, so the query completes normally, just slower. This is
    how the regression sentinel's drill injects a deterministic,
    correctly-attributed slowdown (``TFT_FAULTS=perf:1``). Returns 0.0
    on the disarmed path (one memoized env read + a locked dict
    lookup, same as :func:`check`). A chaos schedule naming this site
    can fire it too — seed-deterministic, like :func:`check`."""
    _arm_from_env()
    got = _consume(site)
    if got is None:
        hook = _chaos_hook
        if hook is None or not hook(site):
            return 0.0
        got = _consume(site)
        if got is None:
            return 0.0
    left = got[0]
    from .policy import env_float
    dur = max(env_float("TFT_FAULT_PERF_S", 0.05), 0.0)
    counters.inc(f"faults.{site}.injected")
    _log.info("injecting %.3fs slowdown at site %r (%d more scripted)",
              dur, site, left)
    if dur:
        import time
        time.sleep(dur)
    return dur


@contextlib.contextmanager
def inject(site: str, fail_n: int = 1, message: Optional[str] = None,
           transient: Optional[bool] = None) -> Iterator[None]:
    """Scoped fault injection: the next ``fail_n`` ``check(site)`` calls
    inside the block raise; the site is disarmed on exit either way."""
    arm(site, fail_n, message=message, transient=transient)
    try:
        yield
    finally:
        reset(site)
