"""Exception classification for the resilience layer.

Three buckets, three responses:

- **transient** — worth retrying: coordinator/connection hiccups, PJRT
  ``UNAVAILABLE``/``ABORTED``/``DEADLINE_EXCEEDED`` statuses, injected
  faults. Retried under a :class:`~.policy.RetryPolicy`.
- **oom** — ``RESOURCE_EXHAUSTED`` / out-of-memory shapes: retrying the
  same program would fail identically, but HALVING the rows and running
  the two halves usually succeeds for row-local computations
  (``engine/executor.py``'s split-block re-dispatch).
- **device_lost** — a mesh device died (``DEVICE_LOST`` statuses, the
  ``device`` fault site): neither retrying nor splitting helps; the
  elastic layer (``parallel.elastic``) rebuilds a shrunken mesh over
  the surviving devices, re-shards, and re-runs the op.
- **worker_lost** — a serving WORKER PROCESS died (missed heartbeats on
  the fabric coordinator, the ``worker`` fault site): like a lost
  device, retrying against the dead worker is pointless; the recovery
  is structural — the serving fabric (``serve/fabric.py``) re-places
  the worker's tenants and resumes its running queries from their
  persisted checkpoints on a survivor.
- **permanent** — everything else (shape errors, type errors, compile
  diagnostics): fail fast, loudly, once.

Classification is string-based on purpose: the error types that matter
(``XlaRuntimeError``, ``PjrtCoreError``, grpc errors) cross a C++/Python
boundary where the *status word* in the message is the stable contract,
not the Python class.
"""

from __future__ import annotations

import os

__all__ = ["is_transient", "is_oom", "is_permanent", "is_device_lost",
           "is_worker_lost", "error_kind",
           "ServeRejected", "QueueFull", "OverQuota", "AdmissionDeadline",
           "QueryQuarantined", "InvariantViolation",
           "DeviceLost", "WorkerLost",
           "QueryInterrupted", "QueryPreempted", "QueryCancelled",
           "TRANSIENT_MARKERS", "OOM_MARKERS", "DEVICE_LOST_MARKERS",
           "WORKER_LOST_MARKERS"]


class DeviceLost(RuntimeError):
    """A device of the mesh is gone (chip failure, host eviction, a
    lost ICI neighbor). Retrying the identical program would dispatch to
    the same dead device and fail identically, so this is NOT transient;
    the recovery is structural — ``parallel.elastic`` rebuilds a
    shrunken mesh over the survivors, re-shards the frame, and re-runs
    the op. Classified ``device_lost``.
    """

    kind = "device_lost"


class WorkerLost(RuntimeError):
    """A serving worker process is gone (crash, eviction, missed
    heartbeats past the fabric's lease). The process-group analogue of
    :class:`DeviceLost`: retrying against the dead worker would fail
    identically, so this is NOT transient; the recovery is structural —
    the serving fabric (``serve/fabric.py``) re-places the worker's
    tenants across the survivors and resumes its running queries from
    their persisted checkpoints (``memory/persist.py``), cold re-running
    only on a checkpoint mismatch. Classified ``worker_lost``.
    """

    kind = "worker_lost"


class QueryInterrupted(RuntimeError):
    """An operator- or scheduler-driven interruption of a running query
    (``serve/`` preemption and cancellation, ``engine/preempt.py``).

    NOT transient: retrying would re-run work the scheduler just asked
    to stop. The scheduler — not the retry loop — owns what happens
    next (re-queue a preempted query's checkpoint for resume; fail a
    cancelled one's future). Classified by ``kind``.
    """

    kind = "interrupted"
    retryable = False


class QueryPreempted(QueryInterrupted):
    """A running query was preempted at a block boundary: its in-flight
    blocks drained, its completed block outputs parked as a
    :class:`~..memory.checkpoint.QueryCheckpoint`, and the query
    re-queued — resume re-dispatches only the remaining blocks,
    bit-identical to an uninterrupted run (``docs/serving.md``)."""

    kind = "preempted"


class QueryCancelled(QueryInterrupted):
    """A query was cancelled (``QueryScheduler.cancel``): queued queries
    never run; running ones stop at the next block boundary and their
    checkpoint is freed. Surfaces on the query's future."""

    kind = "cancelled"


class ServeRejected(RuntimeError):
    """A load-related rejection from the serving layer (``serve/``).

    Unlike the engine's failures these are *policy* decisions: the query
    never ran, and the classification tells the client whether retrying
    later may succeed. ``kind`` is the classifier label exported on
    retry/giveup events and server stats; ``retryable`` feeds
    :func:`is_transient` (a full queue or an exhausted rows/sec budget
    clears with time; an admission-deadline shed does not retry itself).
    """

    kind = "rejected"
    retryable = True


class QueueFull(ServeRejected):
    """Per-tenant submission queue at its bounded depth (backpressure):
    the submit is rejected instead of queuing unboundedly. Retryable —
    the queue drains."""

    kind = "rejected"
    retryable = True


class OverQuota(ServeRejected):
    """The tenant's rows/sec budget (token bucket) cannot cover the
    query's estimated rows. Retryable — the bucket refills."""

    kind = "over_quota"
    retryable = True


class AdmissionDeadline(ServeRejected):
    """Admission control could not clear the query within its wait
    budget or deadline (estimated HBM footprint would cross the
    high-water mark): the query is shed instead of OOMing mid-flight.
    Not transient — the caller decides whether to resubmit."""

    kind = "deadline_admission"
    retryable = False


class QueryQuarantined(ServeRejected):
    """The query's plan fingerprint is quarantined: it failed
    permanently ``TFT_QUARANTINE_AFTER`` times in a row, so the
    scheduler fast-rejects it at submit instead of letting a
    deterministically-crashing plan eat retries, checkpoints, and
    worker restarts across the fabric (``serve/quarantine.py``). Not
    retryable as-is — the quarantine expires after its TTL (one probe
    re-admission) or is lifted manually with ``tft.unquarantine()``."""

    kind = "quarantined"
    retryable = False


class InvariantViolation(RuntimeError):
    """A cross-cutting invariant auditor found unbalanced books at a
    quiesce point (``resilience/invariants.py``): a leaked slot lease,
    an unbalanced memory reservation, rows lost across a plan or an
    exchange, inconsistent scheduler accounting. Raised in strict
    (chaos/test) mode; always-on mode flight-records and counts
    instead. NOT transient and NOT retryable: the state the next
    attempt would run on is exactly the state the auditor just proved
    wrong. Classified ``invariant``."""

    kind = "invariant"

# XLA/PJRT status words + socket-layer phrases that indicate the failure
# was environmental, not the program's fault.
TRANSIENT_MARKERS = (
    "UNAVAILABLE",
    "ABORTED",
    "DEADLINE_EXCEEDED",
    "CANCELLED",
    "connection refused",
    "connection reset",
    "socket closed",
    "temporarily unavailable",
    "injected transient fault",  # resilience.faults
)

OOM_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "out of memory",
    "Out of memory",
    "OOM",
)

# Status words that indicate a DEVICE died, not the program or the
# network: the PJRT/runtime phrasing a lost chip surfaces under.
# Checked BEFORE the transient markers — "UNAVAILABLE: device lost"
# must shrink the mesh, not spin the retry loop against a dead chip.
DEVICE_LOST_MARKERS = (
    "DEVICE_LOST",
    "device lost",
    "device is lost",
    "lost device",
)

# Status words that indicate a serving WORKER PROCESS died, not the
# program: missed-heartbeat declarations from the fabric coordinator and
# the `worker` fault site surface under these. Checked BEFORE the
# transient markers for the same reason as DEVICE_LOST: the recovery is
# re-placement, never a retry against the dead worker.
WORKER_LOST_MARKERS = (
    "WORKER_LOST",
    "worker lost",
    "worker is lost",
    "lost worker",
    "worker process died",
)


def _extra_transient_markers() -> tuple:
    """Operator-extensible marker list: ``TFT_TRANSIENT_ERRORS`` is a
    comma-separated set of additional substrings to treat as transient
    (an escape hatch for backend-specific status texts)."""
    raw = os.environ.get("TFT_TRANSIENT_ERRORS", "")
    return tuple(m.strip() for m in raw.split(",") if m.strip())


def is_oom(exc: BaseException) -> bool:
    """True when the failure is an out-of-memory shape — NOT retried
    as-is; the executor's split-block path handles it."""
    if isinstance(exc, MemoryError):
        return True
    msg = str(exc)
    return any(m in msg for m in OOM_MARKERS)


def is_device_lost(exc: BaseException) -> bool:
    """True when a mesh device is gone — NOT retried as-is; the elastic
    layer (``parallel.elastic``) shrinks the mesh and re-runs."""
    if isinstance(exc, DeviceLost):
        return True
    msg = str(exc)
    return any(m in msg for m in DEVICE_LOST_MARKERS)


def is_worker_lost(exc: BaseException) -> bool:
    """True when a serving worker process is gone — NOT retried as-is;
    the serving fabric (``serve/fabric.py``) re-places its tenants and
    resumes its queries from their persisted checkpoints."""
    if isinstance(exc, WorkerLost):
        return True
    msg = str(exc)
    return any(m in msg for m in WORKER_LOST_MARKERS)


def is_transient(exc: BaseException) -> bool:
    """True when retrying the same operation may legitimately succeed."""
    from .faults import InjectedFault

    if isinstance(exc, InjectedFault):
        return exc.transient
    if isinstance(exc, QueryInterrupted):
        # checked BEFORE the message markers: "CANCELLED" is a transient
        # PJRT status word, but a scheduler cancellation/preemption must
        # never spin a retry loop against the scheduler's own decision
        return False
    if isinstance(exc, ServeRejected):
        return exc.retryable  # queue drains / bucket refills; sheds don't
    if isinstance(exc, InvariantViolation):
        # the books the next attempt would run on are the books the
        # auditor just proved wrong — never spin a retry loop on them
        return False
    if is_device_lost(exc):
        return False  # same program, same dead device: shrink, don't retry
    if is_worker_lost(exc):
        return False  # same dead worker: re-place, don't retry
    if is_oom(exc):
        return False  # same program, same memory: split, don't retry
    if isinstance(exc, (ConnectionError, TimeoutError)):
        return True
    msg = str(exc)
    if any(m in msg for m in TRANSIENT_MARKERS):
        return True
    extra = _extra_transient_markers()
    return bool(extra) and any(m in msg for m in extra)


def is_permanent(exc: BaseException) -> bool:
    return not is_transient(exc) and not is_oom(exc)


def error_kind(exc: BaseException) -> str:
    """The classifier's verdict as a stable label: the serving layer's
    own kinds (``rejected`` / ``over_quota`` / ``deadline_admission``)
    when the exception carries one, else ``device_lost`` / ``oom`` /
    ``transient`` / ``permanent``. Exported on retry/giveup trace
    events and in server stats so dashboards never re-derive the
    classification."""
    if isinstance(exc, QueryInterrupted):
        return exc.kind  # preempted / cancelled
    if isinstance(exc, ServeRejected):
        return exc.kind  # rejected / over_quota / … / quarantined
    if isinstance(exc, InvariantViolation):
        return exc.kind  # "invariant"
    if is_device_lost(exc):
        return "device_lost"
    if is_worker_lost(exc):
        return "worker_lost"
    if is_oom(exc):
        return "oom"
    if is_transient(exc):
        return "transient"
    return "permanent"
