"""Cross-cutting invariant auditors at quiesce points.

Eighteen PRs of subsystems each keep local books: the slot pool counts
leases (``engine/pipeline.py``), the memory ledger counts reserved and
resident bytes (``memory/manager.py``), the scheduler counts queued and
running queries per tenant (``serve/scheduler.py``), the exchange
counts rows across the all-to-all (``parallel/exchange.py``),
checkpoints carry resume cursors (``memory/checkpoint.py``). Each book
is balanced by construction on the paths its own tests drive. This
module audits the books *against each other* at quiesce points — query
finish, stream batch boundary, scheduler close, chaos soak checkpoints
— where a composed fault (``.chaos``) would surface as a leak no single
subsystem can see: a lease left behind by an error that unwound through
two layers, a reservation released twice, a query neither queued nor
running nor finished.

Two modes, one knob pair:

- **always-on** (the default): every :func:`audit` runs, violations are
  flight-recorded (``invariant.violation``), counted
  (``invariants.violations`` + ``invariants.<auditor>.violations``) and
  logged — never raised. Overhead is bounded by auditing only at
  quiesce points (<2%, measured by ``bench.py invariant_overhead``);
  ``TFT_INVARIANTS=0`` bypasses even that.
- **strict** (chaos schedules, tests, ``TFT_INVARIANTS_STRICT=1``, or
  the :func:`strict` context): a violation additionally raises a
  classified :class:`~.classify.InvariantViolation` at the quiesce
  point, so a drill fails loudly at the first unbalanced book instead
  of asserting green over silently-wrong state.

Built-in auditors (consulted live at each audit — nothing to register,
no teardown races): slot-pool lease balance, memory-ledger reservation
balance + spillable-registry consistency, scheduler queue/running
accounting, fabric no-orphan accounting. :func:`register` adds
process-wide custom auditors (tests, soak drills).

Per-query row conservation is threaded, not global: ``plan/execute.py``
opens a :func:`row_ledger` around a row-local fused plan, filter stages
:func:`note_filtered` their masked-out rows, and the close checks
``rows in == rows out + rows filtered``. A preemption resume restoring
a prior attempt's prefix calls :func:`taint_rows` — the restored
blocks' filter counts were noted in the PRIOR attempt's ledger, so the
equation no longer balances and the check is skipped, not faked.
``parallel/exchange.py``'s shuffle conservation check goes through
:func:`conserve`, which raises REGARDLESS of mode — that check
predates this module and losing rows across an all-to-all was never a
count-and-continue condition.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import threading
from typing import Callable, Dict, Iterator, List, Optional

from ..utils.logging import get_logger
from ..utils.tracing import counters
from .classify import InvariantViolation

__all__ = ["audit", "register", "unregister", "strict", "strict_mode",
           "enabled", "violate", "check", "conserve", "row_ledger",
           "note_filtered", "note_emitted", "taint_rows",
           "InvariantViolation"]

_log = get_logger("resilience.invariants")

_lock = threading.Lock()
_strict_depth = 0
_custom: Dict[str, Callable[[str], List[str]]] = {}

# the open per-query row ledger, if any: {"filtered": int, "tainted":
# bool} — contextvar so concurrent serve queries keep separate books
_row_ledger: "contextvars.ContextVar[Optional[dict]]" = \
    contextvars.ContextVar("tft_row_ledger", default=None)


def enabled() -> bool:
    """Auditors run unless ``TFT_INVARIANTS=0`` (the bench bypass)."""
    return os.environ.get("TFT_INVARIANTS", "1") != "0"


def strict_mode() -> bool:
    """Raise on violation? True inside :func:`strict`, under an active
    chaos schedule, or with ``TFT_INVARIANTS_STRICT=1``."""
    if _strict_depth > 0:
        return True
    if os.environ.get("TFT_INVARIANTS_STRICT", "") not in ("", "0"):
        return True
    from . import chaos as _chaos
    return _chaos.active() is not None


@contextlib.contextmanager
def strict() -> Iterator[None]:
    """Scoped strict mode (tests/drills): violations raise."""
    global _strict_depth
    with _lock:
        _strict_depth += 1
    try:
        yield
    finally:
        with _lock:
            _strict_depth -= 1


def register(name: str, fn: Callable[[str], List[str]]) -> None:
    """Add a process-wide auditor: ``fn(point)`` returns violation
    messages (empty list = clean)."""
    with _lock:
        _custom[name] = fn


def unregister(name: str) -> None:
    with _lock:
        _custom.pop(name, None)


def _record(auditor: str, point: str, msg: str) -> None:
    counters.inc("invariants.violations")
    counters.inc(f"invariants.{auditor}.violations")
    from ..observability import flight as _flight
    _flight.record("invariant.violation", auditor=auditor, point=point,
                   detail=msg)
    _log.warning("INVARIANT VIOLATION [%s @ %s]: %s", auditor, point, msg)


def violate(auditor: str, msg: str, point: str = "inline") -> None:
    """Report one violation found outside :func:`audit` (e.g. a
    checkpoint cursor check): count + flight-record always, raise
    :class:`InvariantViolation` in strict mode."""
    _record(auditor, point, msg)
    if strict_mode():
        raise InvariantViolation(f"[{auditor} @ {point}] {msg}")


def check(cond: bool, auditor: str, msg: str,
          point: str = "inline") -> bool:
    """``violate`` unless ``cond``; returns ``cond`` (always-on mode
    lets callers cold-path instead of trusting bad state)."""
    if not cond and enabled():
        violate(auditor, msg, point)
    return cond


def conserve(expected: int, actual: int, what: str) -> None:
    """Row-conservation assertion that raises in EVERY mode — losing or
    duplicating rows is never a count-and-continue condition. Counted
    like any other violation so soaks see it in one place."""
    if expected == actual:
        return
    msg = f"{what} row conservation violated: {expected} in, {actual} out"
    _record("rows", what, msg)
    raise InvariantViolation(msg)


# -- per-query row ledger --------------------------------------------------
@contextlib.contextmanager
def row_ledger(rows_in: int, what: str) -> Iterator[None]:
    """Audit ``rows in == rows out + rows filtered`` across a row-local
    plan execution. The body yields; on clean exit the caller-visible
    output rows are read from the ledger's ``out`` slot (set via
    :func:`note_emitted`)."""
    if not enabled():
        yield
        return
    ledger = {"filtered": 0, "out": None, "tainted": False}
    token = _row_ledger.set(ledger)
    try:
        yield
    finally:
        _row_ledger.reset(token)
    counters.inc("invariants.audits")
    if ledger["tainted"] or ledger["out"] is None:
        return
    rows_out = ledger["out"]
    filtered = ledger["filtered"]
    if rows_in != rows_out + filtered:
        violate("rows",
                f"{what}: {rows_in} rows admitted != {rows_out} emitted "
                f"+ {filtered} filtered", point=what)


def note_filtered(n: int) -> None:
    """A filter stage masked out ``n`` rows of the current query."""
    ledger = _row_ledger.get()
    if ledger is not None:
        ledger["filtered"] += int(n)


def note_emitted(n: int) -> None:
    """The current query's final emitted row count."""
    ledger = _row_ledger.get()
    if ledger is not None:
        ledger["out"] = int(n)


def taint_rows(reason: str) -> None:
    """Void the open row ledger (e.g. a resume restored a prior
    attempt's prefix, whose filter counts this ledger never saw)."""
    ledger = _row_ledger.get()
    if ledger is not None and not ledger["tainted"]:
        ledger["tainted"] = True
        counters.inc("invariants.rows.tainted")
        _log.debug("row ledger tainted: %s", reason)


# -- built-in auditors -----------------------------------------------------
def _audit_slots(point: str) -> List[str]:
    from ..engine import pipeline as _pipeline
    pool = _pipeline.current_slot_pool()
    if pool is None:
        return []
    leased = pool.leased()
    out = []
    if leased < 0:
        out.append(f"slot pool leased count is negative ({leased}): "
                   f"a release without an acquire")
    elif leased > pool.slots:
        out.append(f"slot pool over-leased: {leased} leases against "
                   f"{pool.slots} slots")
    elif leased != 0 and point.endswith(".close"):
        out.append(f"slot pool still holds {leased} lease(s) at "
                   f"{point}: leaked by an unwound stream")
    return out


def _audit_memory(point: str) -> List[str]:
    from .. import memory as _memory
    m = _memory.active()
    if m is None:
        return []
    return m.audit()


def _audit_scheduler(point: str) -> List[str]:
    from ..serve.scheduler import live_schedulers
    out: List[str] = []
    for s in live_schedulers():
        out.extend(s.audit_invariants(point))
    return out


def _audit_fabric(point: str) -> List[str]:
    from ..serve.fabric import live_fabric
    f = live_fabric()
    if f is None:
        return []
    return f.audit_invariants(point)


_BUILTIN = (("slots", _audit_slots), ("memory", _audit_memory),
            ("scheduler", _audit_scheduler), ("fabric", _audit_fabric))


def audit(point: str) -> List[str]:
    """Run every auditor at a quiesce point; returns the violation
    messages (empty = clean). Always-on: count + flight-record; strict:
    raise one classified :class:`InvariantViolation` naming them all.

    An auditor that itself crashes is a violation too — a broken book
    is not a balanced book."""
    if not enabled():
        return []
    counters.inc("invariants.audits")
    with _lock:
        extra = list(_custom.items())
    found: List[str] = []
    for name, fn in tuple(_BUILTIN) + tuple(extra):
        try:
            msgs = fn(point)
        except InvariantViolation:
            raise  # already recorded + strict
        except Exception as e:
            msgs = [f"auditor crashed: {e!r}"]
        for msg in msgs:
            _record(name, point, msg)
            found.append(f"[{name}] {msg}")
    if found and strict_mode():
        raise InvariantViolation(
            f"{len(found)} invariant violation(s) at {point}: "
            + "; ".join(found))
    return found
