"""Retry policy + deadlines: the control knobs of the resilience layer.

:class:`RetryPolicy` is the one retry loop in the package — cluster
bootstrap, engine dispatch, and the native core all call
:meth:`RetryPolicy.call` rather than hand-rolling ``for attempt in
range(...)`` loops, so backoff, deadline accounting, counter export and
log narration behave identically at every layer.

Deadlines compose through a thread-local stack: ``with deadline(30):``
bounds everything inside it, nested deadlines only shrink the budget,
and :meth:`RetryPolicy.call` consults the ambient deadline before every
attempt and every backoff sleep — a retry loop can never outlive its
caller's time budget.

Observability contract (used by the tier-1 resilience suite):

- every attempt runs inside a ``resilience.<op>.attempt`` tracing span;
- every retry increments ``retry.<op>.retries`` in
  :data:`~..utils.tracing.counters` and logs a WARNING;
- every giveup increments ``retry.<op>.giveups`` and logs an ERROR
  before the final exception propagates.

Backoff jitter is **deterministic** (keyed on op name and attempt
number): two processes retrying the same op de-synchronize, while a
test replaying a scenario sees identical timing every run.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import threading
import time
from typing import Callable, Optional, TypeVar

from ..observability import flight as _flight
from ..observability.events import add_event as _obs_event
from ..observability.events import current_trace as _current_trace
from ..utils.logging import get_logger
from ..utils.tracing import counters, span

__all__ = ["RetryPolicy", "DeadlineExceeded",
           "ClusterInitError", "DEFAULT_POLICY", "default_policy",
           "deadline", "remaining_time", "check_deadline",
           "env_float", "env_int", "env_bool"]

_log = get_logger("resilience.policy")

T = TypeVar("T")


class DeadlineExceeded(TimeoutError):
    """An operation (or its retry loop) ran out of its time budget."""


class ClusterInitError(RuntimeError):
    """Cluster bootstrap failed and ``TFT_REQUIRE_CLUSTER`` forbids the
    single-process degradation."""


# -- deadlines ---------------------------------------------------------------

_local = threading.local()


def _stack():
    s = getattr(_local, "deadlines", None)
    if s is None:
        s = _local.deadlines = []
    return s


class deadline:
    """Bound the wall-clock time of a block (thread-local, nestable).

    ``with deadline(30): ...`` — code inside that calls
    :func:`check_deadline` (the retry loop does, between attempts and
    sleeps) raises :class:`DeadlineExceeded` once 30s have elapsed.
    Nested deadlines only ever shrink the budget. ``None`` seconds means
    no new bound (useful for optional knobs).
    """

    def __init__(self, seconds: Optional[float]):
        if seconds is not None and seconds <= 0:
            raise ValueError(f"deadline must be positive, got {seconds}")
        self.seconds = seconds
        self._pushed = False

    def __enter__(self) -> "deadline":
        if self.seconds is not None:
            expires = time.monotonic() + self.seconds
            s = _stack()
            if s:
                expires = min(expires, s[-1])
            s.append(expires)
            self._pushed = True
        return self

    def __exit__(self, *exc):
        if self._pushed:
            _stack().pop()
        return False


def remaining_time() -> Optional[float]:
    """Seconds left on the tightest ambient deadline, or None."""
    s = _stack()
    if not s:
        return None
    return s[-1] - time.monotonic()


def check_deadline(op: str = "operation") -> None:
    """Raise :class:`DeadlineExceeded` when the ambient deadline is up."""
    left = remaining_time()
    if left is not None and left <= 0:
        counters.inc(f"deadline.{op}.expired")
        raise DeadlineExceeded(
            f"{op}: deadline expired ({-left:.3f}s past)")


# -- retry policy ------------------------------------------------------------

def _error_kind(exc: BaseException) -> str:
    """The classifier's verdict as an event label (oom / transient /
    permanent, plus the serving layer's rejected / over_quota /
    deadline_admission) — what the retry decision was actually based
    on."""
    from .classify import error_kind
    return error_kind(exc)


def env_float(name: str, default: Optional[float]) -> Optional[float]:
    """Float env knob; unset/empty/malformed (warned) → ``default``."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return float(raw)
    except ValueError:
        _log.warning("ignoring malformed %s=%r", name, raw)
        return default


def env_int(name: str, default: int) -> int:
    """Int env knob; unset/empty/malformed (warned) → ``default``."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return int(raw)
    except ValueError:
        _log.warning("ignoring malformed %s=%r", name, raw)
        return default


def env_bool(name: str, default: bool) -> bool:
    """Bool env knob; unset/empty → ``default``, ``0/false/False`` →
    False, anything else → True. The one truthiness parser for every
    resilience switch (``TFT_REQUIRE_CLUSTER``, ``TFT_OOM_SPLIT``, ...)."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    return raw not in ("0", "false", "False")


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded retry with exponential backoff, jitter, and a deadline.

    ``max_attempts`` counts every try including the first; ``deadline``
    (seconds) bounds the whole :meth:`call` including sleeps — ``None``
    defers to whatever ambient :func:`deadline` is in effect.
    """

    max_attempts: int = 3
    base_delay: float = 0.05
    multiplier: float = 2.0
    max_delay: float = 2.0
    jitter: float = 0.25
    deadline: Optional[float] = None

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(
                f"max_attempts must be >= 1, got {self.max_attempts}")
        if not 0 <= self.jitter < 1:
            raise ValueError(f"jitter must be in [0, 1), got {self.jitter}")

    def backoff(self, attempt: int, op: str = "") -> float:
        """Sleep before attempt ``attempt + 1`` (0-based failed attempt).

        Exponential with a cap, jittered deterministically from
        ``(op, attempt)`` so concurrent processes spread out but test
        replays are exact.
        """
        raw = min(self.base_delay * (self.multiplier ** attempt),
                  self.max_delay)
        if not self.jitter:
            return raw
        digest = hashlib.sha256(f"{op}:{attempt}".encode()).digest()
        frac = digest[0] / 255.0  # [0, 1], stable across runs
        return raw * (1.0 - self.jitter + 2.0 * self.jitter * frac)

    def call(self, fn: Callable[[], T], *, op: str,
             classify: Optional[Callable[[BaseException], bool]] = None,
             sleep: Callable[[float], None] = time.sleep) -> T:
        """Run ``fn`` under this policy.

        ``classify(exc) -> bool`` marks an exception retryable (default:
        :func:`~.classify.is_transient`). Non-retryable exceptions
        propagate immediately; retryable ones retry up to
        ``max_attempts`` within the deadline, then propagate (the last
        one) after a ``retry.<op>.giveups`` count + ERROR log.
        """
        if classify is None:
            from .classify import is_transient as classify
        with deadline(self.deadline):
            last: Optional[BaseException] = None
            for attempt in range(self.max_attempts):
                check_deadline(op)
                try:
                    with span(f"resilience.{op}.attempt"):
                        return fn()
                except BaseException as e:  # noqa: BLE001 - reclassified
                    if not classify(e):
                        raise
                    last = e
                if attempt + 1 >= self.max_attempts:
                    break
                delay = self.backoff(attempt, op)
                left = remaining_time()
                if left is not None and delay >= left:
                    # sleeping would blow the deadline: give up now with
                    # the deadline error, carrying the real failure
                    counters.inc(f"retry.{op}.giveups")
                    if _current_trace() is not None:
                        # kind classification only when a trace listens:
                        # the giveup/retry paths must stay zero-cost
                        # with tracing off (re-classifying str(exc) per
                        # attempt is not free)
                        _obs_event("giveup", name=op,
                                   attempts=attempt + 1,
                                   error=type(last).__name__,
                                   kind=_error_kind(last), deadline=True)
                    # giveups are rare enough to classify always-on for
                    # the flight ring, and a classified giveup is one
                    # of the recorder's auto-dump triggers
                    _flight.record("resilience.giveup", op=op,
                                   attempts=attempt + 1,
                                   error=type(last).__name__,
                                   error_kind=_error_kind(last),
                                   deadline=True)
                    _flight.maybe_dump("giveup")
                    _log.error(
                        "%s: transient failure and only %.3fs left on "
                        "the deadline (backoff %.3fs); giving up", op,
                        max(left, 0.0), delay)
                    raise DeadlineExceeded(
                        f"{op}: deadline reached after {attempt + 1} "
                        f"attempt(s)") from last
                counters.inc(f"retry.{op}.retries")
                if _current_trace() is not None:
                    _obs_event("retry", name=op, attempt=attempt + 1,
                               backoff_s=delay,
                               error=type(last).__name__,
                               kind=_error_kind(last))
                _log.warning(
                    "%s: transient failure (attempt %d/%d), retrying in "
                    "%.3fs: %s", op, attempt + 1, self.max_attempts,
                    delay, last)
                sleep(delay)
            counters.inc(f"retry.{op}.giveups")
            if _current_trace() is not None:
                _obs_event("giveup", name=op, attempts=self.max_attempts,
                           error=type(last).__name__,
                           kind=_error_kind(last))
            _flight.record("resilience.giveup", op=op,
                           attempts=self.max_attempts,
                           error=type(last).__name__,
                           error_kind=_error_kind(last))
            _flight.maybe_dump("giveup")
            _log.error("%s: giving up after %d attempt(s): %s",
                       op, self.max_attempts, last)
            assert last is not None
            raise last


DEFAULT_POLICY = RetryPolicy()


def default_policy(prefix: str = "TFT_RETRY",
                   **overrides) -> RetryPolicy:
    """The process-default policy, shaped by environment knobs.

    ``TFT_RETRY_MAX_ATTEMPTS`` / ``TFT_RETRY_BASE_DELAY`` /
    ``TFT_RETRY_MAX_DELAY`` / ``TFT_RETRY_DEADLINE`` override the
    dataclass defaults; keyword ``overrides`` win over both (callers pin
    what their layer must control, e.g. the cluster bootstrap deadline).
    Re-read per call: the knobs are cheap and tests flip them.
    """
    params = dict(
        max_attempts=env_int(f"{prefix}_MAX_ATTEMPTS",
                             DEFAULT_POLICY.max_attempts),
        base_delay=env_float(f"{prefix}_BASE_DELAY",
                             DEFAULT_POLICY.base_delay),
        max_delay=env_float(f"{prefix}_MAX_DELAY",
                            DEFAULT_POLICY.max_delay),
        deadline=env_float(f"{prefix}_DEADLINE", DEFAULT_POLICY.deadline),
    )
    params.update(overrides)
    return RetryPolicy(**params)
