"""Seeded chaos schedules: composed multi-site fault injection.

Every recovery path in this library was proven by arming ONE
deterministic fault site (:mod:`.faults`) and asserting one contract.
Production faults arrive *composed* — a device loss during an exchange
while a whale is being preempted and a worker restarts warm off a disk
tier that may itself be rotten. This module composes the existing sites
into reproducible multi-site schedules:

    TFT_CHAOS="seed:42,rate:0.05,sites:device|worker|oom|preempt|disk"

While a schedule is active, every :func:`~.faults.check` (and
:func:`~.faults.slowdown`) whose site is named by the schedule and has
no scripted budget consults it. The decision for the *n*-th consult of
a site is a pure hash of ``(seed, site, n)`` against ``rate`` — no RNG
state, no wall clock — so the same seed over the same workload fires
the same ``(site, step)`` sequence, per site, regardless of how other
sites interleave. A firing arms a ONE-SHOT budget through
:func:`~.faults.arm` (which shapes the message for the site's
classifier: OOM-shaped for ``oom``, ``DEVICE_LOST`` for ``device``, …)
and the very next consume raises it — chaos faults are
indistinguishable from scripted ones downstream.

Every firing is flight-recorded (``chaos.fire`` with seed/site/step)
and kept on the schedule (:meth:`ChaosSchedule.firings`), so a failure
under chaos replays exactly: re-run with the same seed and the same
workload, and the drill fires the same schedule
(:meth:`ChaosSchedule.fingerprint`).

Invariant auditors (:mod:`.invariants`) treat an active schedule as
strict mode: a violation surfaced mid-drill raises a classified
``InvariantViolation`` instead of only counting.

Drivers: :func:`inject` (scoped, tests), :func:`start`/:func:`stop`
(whole-process, ``tools/chaos_soak.py``), or the ``TFT_CHAOS``
environment knob (armed lazily by the first fault-site check, like
``TFT_FAULTS``). Site names are validated against
:func:`~.faults.sites` — a typo raises instead of arming a vacuous
drill.
"""

from __future__ import annotations

import contextlib
import hashlib
import threading
from typing import Dict, Iterator, List, Optional, Tuple

from ..utils.logging import get_logger
from ..utils.tracing import counters
from . import faults as _faults

__all__ = ["ChaosSchedule", "parse", "start", "stop", "active", "inject",
           "maybe_start_from_env"]

_log = get_logger("resilience.chaos")

_lock = threading.Lock()
_active: Optional["ChaosSchedule"] = None
_env_armed = False


class ChaosSchedule:
    """One seeded multi-site schedule (see the module docstring).

    ``rate`` is the per-consult firing probability; the decision for a
    site's *n*-th consult is ``hash64(seed, site, n) / 2**64 < rate`` —
    probabilistic in distribution, fully determined by the seed.
    """

    def __init__(self, seed: int, rate: float, sites: List[str]):
        known = _faults.sites()
        unknown = [s for s in sites if s not in known]
        if unknown:
            raise ValueError(
                f"chaos schedule names unknown fault site(s) "
                f"{unknown!r}; known sites: {sorted(known)} "
                f"(faults.sites()) — refusing to arm a vacuous drill")
        if not sites:
            raise ValueError("chaos schedule needs at least one site")
        if not 0.0 < rate <= 1.0:
            raise ValueError(f"chaos rate must be in (0, 1], got {rate}")
        self.seed = int(seed)
        self.rate = float(rate)
        self.sites = tuple(dict.fromkeys(sites))  # de-duped, ordered
        self._lock = threading.Lock()
        self._steps: Dict[str, int] = {}
        self._firings: List[Tuple[str, int]] = []

    def consult(self, site: str) -> bool:
        """The :func:`~.faults.check` hook: count the consult, decide
        seed-deterministically, arm a one-shot budget on a firing."""
        if site not in self.sites:
            return False
        with self._lock:
            step = self._steps.get(site, 0) + 1
            self._steps[site] = step
        h = hashlib.sha256(
            f"{self.seed}:{site}:{step}".encode()).digest()
        if int.from_bytes(h[:8], "big") / 2.0 ** 64 >= self.rate:
            return False
        with self._lock:
            self._firings.append((site, step))
        counters.inc("chaos.fired")
        counters.inc(f"chaos.{site}.fired")
        from ..observability import flight as _flight
        _flight.record("chaos.fire", site=site, step=step,
                       seed=self.seed, rate=self.rate)
        _log.info("chaos: firing site %r at step %d (seed %d)",
                  site, step, self.seed)
        _faults.arm(site, 1)
        return True

    def firings(self) -> List[Tuple[str, int]]:
        """Every ``(site, step)`` this schedule fired, in firing order
        — the replay record (same seed + same workload => same list)."""
        with self._lock:
            return list(self._firings)

    def fingerprint(self) -> Tuple[Tuple[str, int], ...]:
        """The firing sequence as a hashable identity: two runs of the
        same workload under the same seed compare equal."""
        return tuple(self.firings())

    def stats(self) -> dict:
        with self._lock:
            return {"seed": self.seed, "rate": self.rate,
                    "sites": list(self.sites),
                    "consults": dict(self._steps),
                    "fired": len(self._firings)}

    def __repr__(self):
        return (f"ChaosSchedule(seed={self.seed}, rate={self.rate:g}, "
                f"sites={'|'.join(self.sites)}, "
                f"fired={len(self.firings())})")


def parse(spec: str) -> ChaosSchedule:
    """``"seed:42,rate:0.05,sites:device|worker|disk"`` -> schedule.

    Order-free; ``seed`` defaults to 0, ``rate`` to 0.05. ``sites`` is
    required. Malformed entries and unknown sites raise — a chaos spec
    is an operator statement of intent, never best-effort."""
    seed, rate, sites = 0, 0.05, []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        key, sep, value = part.partition(":")
        key = key.strip()
        if not sep:
            raise ValueError(f"malformed TFT_CHAOS entry {part!r} "
                             f"(expected key:value)")
        if key == "seed":
            seed = int(value)
        elif key == "rate":
            rate = float(value)
        elif key == "sites":
            sites = [s.strip() for s in value.split("|") if s.strip()]
        else:
            raise ValueError(
                f"unknown TFT_CHAOS key {key!r} (seed/rate/sites)")
    return ChaosSchedule(seed, rate, sites)


def active() -> Optional[ChaosSchedule]:
    """The installed schedule, or ``None``. Invariant auditors read
    this to decide strictness."""
    return _active


def start(spec_or_schedule) -> ChaosSchedule:
    """Install a schedule process-wide (replacing any active one) and
    hook it into the fault sites. Returns the installed schedule."""
    sched = (spec_or_schedule
             if isinstance(spec_or_schedule, ChaosSchedule)
             else parse(spec_or_schedule))
    global _active
    with _lock:
        _active = sched
    _faults.set_chaos_hook(_consult)
    _log.info("chaos schedule active: %r", sched)
    return sched


def stop() -> Optional[ChaosSchedule]:
    """Uninstall the active schedule (returning it) and disarm any
    fired-but-unconsumed one-shot budgets on its sites, so a stopped
    drill can never leak a pending fault into later work."""
    global _active
    with _lock:
        sched, _active = _active, None
    _faults.set_chaos_hook(None)
    if sched is not None:
        for site in sched.sites:
            _faults.reset(site)
        _log.info("chaos schedule stopped: %r", sched)
    return sched


def _consult(site: str) -> bool:
    sched = _active
    return sched is not None and sched.consult(site)


def maybe_start_from_env() -> None:
    """Arm ``TFT_CHAOS`` once per process — called lazily by the first
    :func:`~.faults.check`, mirroring ``TFT_FAULTS``. A malformed spec
    raises: silently skipping it would run the exact vacuous drill the
    validation exists to prevent."""
    global _env_armed
    with _lock:
        if _env_armed:
            return
        _env_armed = True
    import os
    spec = os.environ.get("TFT_CHAOS", "").strip()
    if spec:
        start(spec)


@contextlib.contextmanager
def inject(spec_or_schedule) -> Iterator[ChaosSchedule]:
    """Scoped chaos for tests/drills: install on entry, :func:`stop`
    on exit either way."""
    sched = start(spec_or_schedule)
    try:
        yield sched
    finally:
        stop()
