"""Resilient execution: retries, deadlines, fault injection, degradation.

The reference inherited all of its fault tolerance from Spark — task
retry, straggler re-execution, executor replacement — and the TPU-native
port dropped that layer entirely: a transient PJRT error, a slow
coordinator, or a failing padded compile killed the whole job. This
subsystem restores an explicit reliability story at the three layers that
can fail:

- **policy** (:mod:`.policy`): :class:`RetryPolicy` — bounded attempts,
  exponential backoff with deterministic jitter, an overall deadline —
  plus the :func:`deadline` context helper. Every retry/giveup is
  exported through :data:`~..utils.tracing.counters` and the framework
  logger, and each attempt runs inside a tracing span.
- **classification** (:mod:`.classify`): which exceptions are transient
  (retry), which are out-of-memory (split the block), and which are
  permanent (fail fast). Misclassifying a deterministic error as
  transient turns one failure into ``max_attempts`` failures, so the
  default set is conservative and extensible via ``TFT_TRANSIENT_ERRORS``.
- **faults** (:mod:`.faults`): a deterministic fault-injection harness
  (``with faults.inject("compile", fail_n=2): ...``) that the tier-1
  resilience suite uses to prove every retry/fallback path end-to-end on
  CPU — no real TPU failures required.
- **chaos** (:mod:`.chaos`): seeded multi-site schedules over the fault
  sites (``TFT_CHAOS="seed:42,rate:0.05,sites:device|worker|disk"``) —
  probabilistic in distribution, fully replayable by seed — for proving
  the contracts survive *composed* faults, not just single drills.
- **invariants** (:mod:`.invariants`): cross-cutting auditors at
  quiesce points (slot leases, memory ledger, row conservation,
  scheduler/fabric accounting); violations raise a classified
  :class:`InvariantViolation` in strict/chaos mode and flight-record +
  count always-on.

Consumers: ``parallel/cluster.py`` (bootstrap timeout, retry, graceful
single-process degradation), ``engine/executor.py`` (dispatch retry,
exact-shape fallback from bucketed compiles, OOM split-block re-dispatch),
``native_pjrt.py`` (native core dispatch retry), and ``serve/`` — the
multi-tenant scheduler's load rejections (:class:`QueueFull`,
:class:`OverQuota`, :class:`AdmissionDeadline`) are classified here so
clients and retry loops see ``rejected`` / ``over_quota`` /
``deadline_admission`` kinds instead of anonymous RuntimeErrors. The
degradation matrix — what falls back versus what fails fast — is
documented in ``docs/resilience.md``.
"""

from .classify import (AdmissionDeadline, DeviceLost, InvariantViolation,
                       OverQuota, QueryCancelled, QueryInterrupted,
                       QueryPreempted, QueryQuarantined, QueueFull,
                       ServeRejected, WorkerLost, error_kind,
                       is_device_lost, is_oom, is_permanent, is_transient,
                       is_worker_lost)
from .faults import InjectedFault, inject
from .policy import (DEFAULT_POLICY, ClusterInitError, DeadlineExceeded,
                     RetryPolicy, check_deadline, deadline, default_policy,
                     env_bool, env_float, env_int, remaining_time)
from . import faults

__all__ = [
    "RetryPolicy", "DeadlineExceeded", "ClusterInitError",
    "DEFAULT_POLICY", "default_policy", "deadline", "remaining_time",
    "check_deadline",
    "is_transient", "is_oom", "is_permanent", "is_device_lost",
    "is_worker_lost", "error_kind",
    "ServeRejected", "QueueFull", "OverQuota", "AdmissionDeadline",
    "QueryQuarantined", "InvariantViolation",
    "DeviceLost", "WorkerLost",
    "QueryInterrupted", "QueryPreempted", "QueryCancelled",
    "env_bool", "env_float", "env_int",
    "faults", "inject", "InjectedFault",
]
