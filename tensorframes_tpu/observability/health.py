"""``tft.health()``: one machine-readable snapshot across every
subsystem.

Each subsystem already answers its own "how am I doing" — the memory
ledger's :meth:`~..memory.manager.MemoryManager.snapshot`, the
scheduler's per-tenant queue/in-flight state, the elastic layer's lost
pool, per-stream watermarks, the cache hit counters — but an operator
(or a readiness probe) wants ONE call that sees across them. This
module is the first layer with that cross-cutting view; it aggregates,
it never measures: every number here is read from state the subsystems
maintain anyway, so ``health()`` is safe to poll.

The snapshot's top-level ``warnings`` list is the triage summary (the
same heuristics ``tft.doctor()`` narrates): overflow admissions mean
the ledger is being overrun, a non-empty lost pool means shrunken
meshes are waiting on re-admission, a burn rate over 1.0 means an SLO
budget is being spent too fast, deep queues mean admission or capacity
trouble.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List

from ..utils import tracing
from ..utils.logging import get_logger
from . import baseline as _baseline
from . import flight as _flight
from . import slo as _slo

__all__ = ["health"]

_log = get_logger("observability.health")


def _memory_section(counts: Dict[str, int]) -> Dict[str, Any]:
    out: Dict[str, Any] = {
        "limited": False, "limit_bytes": 0, "headroom_bytes": None,
        "inflight_bytes": 0, "resident_bytes": 0, "resident_buffers": 0,
        "spilled_bytes": 0, "spilled_buffers": 0,
    }
    try:
        from .. import memory as _memory
        mgr = _memory.active()
    except Exception as e:  # noqa: BLE001 - health must render regardless
        _log.debug("health: memory manager unavailable: %s", e)
        mgr = None
    if mgr is not None:
        out.update(mgr.snapshot())
        out["limited"] = mgr.limited
        out["headroom_bytes"] = mgr.headroom()
    out["spills"] = counts.get("memory.spills", 0)
    out["spill_bytes_total"] = counts.get("memory.spill_bytes", 0)
    out["faults"] = counts.get("memory.faults", 0)
    out["overflow_admissions"] = counts.get("memory.overflow_admissions",
                                            0)
    out["proactive_splits"] = counts.get("memory.proactive_splits", 0)
    return out


def _backend_initialized() -> bool:
    """Whether a JAX backend already exists — WITHOUT creating one.
    ``health()`` is documented safe-to-poll; ``jax.devices()`` on a
    fresh process would block on (and claim) the TPU runtime as a side
    effect of a health check."""
    try:
        from jax._src import xla_bridge as _xb
    except Exception:  # noqa: BLE001 - private module moved
        try:
            from jax.lib import xla_bridge as _xb
        except Exception:
            return False
    return bool(getattr(_xb, "_backends", None))


def _mesh_section(counts: Dict[str, int]) -> Dict[str, Any]:
    visible = None
    try:
        if _backend_initialized():
            import jax
            visible = len(jax.devices())
        # else: None — "not initialized yet", not "no devices"
    except Exception as e:  # noqa: BLE001 - backend may not be up yet
        _log.debug("health: device enumeration failed: %s", e)
    lost: List[int] = []
    try:
        from ..parallel import elastic as _elastic
        lost = _elastic.lost_pool()
    except Exception as e:  # noqa: BLE001 - optional subsystem
        _log.debug("health: elastic lost pool unavailable: %s", e)
    return {
        "visible_devices": visible,
        "lost_pool": lost,
        "devices_lost": counts.get("mesh.devices_lost", 0),
        "shrinks": counts.get("mesh.shrinks", 0),
        "grows": counts.get("mesh.grows", 0),
        "rebalances": counts.get("mesh.rebalances", 0),
        "dispatches": counts.get("mesh.dispatches", 0),
    }


def _serve_section() -> Dict[str, Any]:
    try:
        from ..serve.scheduler import live_scheduler
        sched = live_scheduler()
    except Exception as e:  # noqa: BLE001 - optional subsystem
        _log.debug("health: serve layer unavailable: %s", e)
        sched = None
    if sched is None:
        return {"running": False}
    snap = sched.snapshot()
    return {
        "running": True,
        "name": sched.name,
        "workers": sched.workers,
        "slots": sched.slot_pool.slots,
        "queued": sum(s["queued"] for s in snap.values()),
        "inflight": sum(s["inflight"] for s in snap.values()),
        "tenants": {t: {"queued": s["queued"],
                        "inflight": s["inflight"],
                        "completed": s["completed"],
                        "failed": s["failed"],
                        "shed": s["shed"],
                        "rejected": s["rejected"]}
                    for t, s in snap.items()},
    }


def _fabric_section() -> Dict[str, Any]:
    try:
        from ..serve.fabric import live_fabric
        fab = live_fabric()
    except Exception as e:  # noqa: BLE001 - optional subsystem
        _log.debug("health: fabric unavailable: %s", e)
        fab = None
    if fab is None:
        return {"running": False}
    try:
        return fab.health_snapshot()
    except Exception as e:  # noqa: BLE001 - a closing fabric is not news
        _log.debug("health: fabric snapshot failed: %s", e)
        return {"running": False}


def _cache_section(counts: Dict[str, int]) -> Dict[str, Any]:
    def ratio(hits: int, misses: int):
        total = hits + misses
        return (hits / total) if total else None

    compile_cache = None
    try:
        from ..serve.scheduler import live_scheduler
        sched = live_scheduler()
        if sched is not None and sched.compile_cache is not None:
            st = sched.compile_cache.stats()
            compile_cache = {**st,
                             "hit_ratio": ratio(st["hits"], st["misses"])}
    except Exception as e:  # noqa: BLE001 - optional subsystem
        _log.debug("health: compile cache unavailable: %s", e)
    result = {"entries": 0, "bytes": 0}
    try:
        from ..plan.adaptive import result_cache_stats
        result = result_cache_stats()
    except Exception as e:  # noqa: BLE001 - optional subsystem
        _log.debug("health: result cache unavailable: %s", e)
    rc_hits = counts.get("plan.result_cache_hits", 0)
    rc_misses = counts.get("plan.result_cache_misses", 0)
    return {
        "compile": compile_cache,
        "result": {**result, "hits": rc_hits, "misses": rc_misses,
                   "hit_ratio": ratio(rc_hits, rc_misses)},
        "engine_compile_hits": counts.get("compile_cache.hits", 0),
        "engine_compile_misses": counts.get("compile_cache.misses", 0),
    }


def _stream_section() -> Dict[str, Any]:
    handles = []
    try:
        from ..stream.runtime import live_handles
        handles = live_handles()
    except Exception as e:  # noqa: BLE001 - optional subsystem
        _log.debug("health: stream handles unavailable: %s", e)
    out: Dict[str, Any] = {}
    for h in handles:
        try:
            m = h.metrics()
        except Exception as e:  # noqa: BLE001 - a dying handle is not news
            _log.debug("health: stream %s metrics failed: %s",
                       getattr(h, "name", "?"), e)
            continue
        out[h.name] = {
            "batches": m["batches"],
            "batches_skipped": m["batches_skipped"],
            "watermark": m["watermark"],
            "batch_lag_s": m["batch_lag_s"],
            "state_rows": m["state_rows"],
            "state_bytes": m["state_bytes"],
            "late_rows": m["late_rows"],
            "done": h.done(),
        }
    return out


def _invariants_section(counts: Dict[str, int]) -> Dict[str, Any]:
    out: Dict[str, Any] = {
        "enabled": True, "strict": False, "audits": 0, "violations": 0,
        "rows_tainted": 0, "chaos": None,
    }
    try:
        from ..resilience import invariants as _invariants
        out["enabled"] = _invariants.enabled()
        out["strict"] = _invariants.strict_mode()
    except Exception as e:  # noqa: BLE001 - optional subsystem
        _log.debug("health: invariants unavailable: %s", e)
    out["audits"] = counts.get("invariants.audits", 0)
    out["violations"] = counts.get("invariants.violations", 0)
    out["rows_tainted"] = counts.get("invariants.rows.tainted", 0)
    try:
        from ..resilience import chaos as _chaos
        sched = _chaos.active()
        if sched is not None:
            out["chaos"] = sched.stats()
    except Exception as e:  # noqa: BLE001 - optional subsystem
        _log.debug("health: chaos schedule unavailable: %s", e)
    return out


def _history_section() -> Dict[str, Any]:
    try:
        from . import history as _history
        return _history.stats()
    except Exception as e:  # noqa: BLE001 - optional subsystem
        _log.debug("health: history archive unavailable: %s", e)
        return {"enabled": False}


def _quarantine_section() -> Dict[str, Any]:
    try:
        from ..serve import quarantine as _quarantine
        return _quarantine.status()
    except Exception as e:  # noqa: BLE001 - optional subsystem
        _log.debug("health: quarantine registry unavailable: %s", e)
        return {"active": {}, "streaks": {}}


def _warnings(snap: Dict[str, Any]) -> List[str]:
    warns: List[str] = []
    mem = snap["memory"]
    if mem["overflow_admissions"]:
        warns.append(
            f"memory: {mem['overflow_admissions']} overflow "
            f"admission(s) — dispatches ran OVER the device budget; "
            f"shrink blocks or raise TFT_MEM_LIMIT_BYTES")
    mesh = snap["mesh"]
    if mesh["lost_pool"]:
        warns.append(
            f"mesh: device(s) {mesh['lost_pool']} lost and not "
            f"re-admitted — meshes are running shrunken "
            f"(parallel.elastic.admit_devices)")
    serve = snap["serve"]
    if serve.get("running"):
        for t, s in serve["tenants"].items():
            if s["shed"] or s["rejected"]:
                warns.append(
                    f"serve: tenant {t!r} had {s['shed']} shed / "
                    f"{s['rejected']} rejected quer(ies) — admission "
                    f"or queue pressure")
    fab = snap.get("fabric") or {}
    if fab.get("running") and fab.get("lost"):
        warns.append(
            f"fabric: {fab['lost']} worker(s) declared lost — their "
            f"tenants re-placed and queries re-dispatched; restart "
            f"them (ServeFabric.restart_worker) to restore capacity")
    for t, s in snap["slo"].items():
        burn = s.get("burn_rate")
        if burn is not None and burn > 1.0:
            warns.append(
                f"slo: tenant {t!r} burning its error budget at "
                f"{burn:.1f}x the sustainable rate "
                f"({s['objective_ms']:g} ms @ {s['target']:.4g})")
    for name, s in snap["streams"].items():
        if s["batches_skipped"]:
            warns.append(
                f"stream: {name!r} skipped {s['batches_skipped']} "
                f"poisoned batch(es)")
    perf = snap.get("perf") or {}
    for r in perf.get("recent_regressions", []):
        warns.append(
            f"perf: query {r['query']} regressed {r['sigma']}x sigma "
            f"past its baseline (plan {r['fingerprint']}…, most-moved "
            f"{r['component']}) — tft.regressions() has the record")
    inv = snap.get("invariants") or {}
    if inv.get("violations"):
        warns.append(
            f"invariants: {inv['violations']} cross-cutting invariant "
            f"violation(s) recorded — accounting drifted somewhere; "
            f"the flight ring's invariant.violation records name the "
            f"auditor and quiesce point")
    hs = snap.get("history") or {}
    if hs.get("unclean"):
        u = hs["unclean"]
        warns.append(
            f"history: UNCLEAN SHUTDOWN detected — pid {u.get('pid')} "
            + (f"(worker {u['worker']}) " if u.get("worker") else "")
            + f"died without its clean-exit hook; tft.postmortem() "
            f"has the triage report")
    if hs.get("corrupt_segments"):
        warns.append(
            f"history: {hs['corrupt_segments']} archive segment(s) "
            f"went cold (corrupt/truncated, unlinked) — records lost, "
            f"never wrong; earlier segments remain readable")
    quar = snap.get("quarantine") or {}
    for fp, info in (quar.get("active") or {}).items():
        warns.append(
            f"quarantine: plan {fp[:16]}… fast-rejected after "
            f"{info['failures']} permanent failure(s) — lifts in "
            f"{info['ttl_remaining_s']:.0f}s, or tft.unquarantine() "
            f"now")
    return warns


def health() -> Dict[str, Any]:
    """One cross-subsystem snapshot: ledger headroom and spill
    pressure, mesh population and the lost-device pool, serve queue
    depths and in-flight, compile/result cache hit ratios, per-stream
    watermark lag and state size, SLO burn, and the flight recorder's
    own liveness — plus a ``warnings`` triage list. Always-on and
    read-only; see the module docstring."""
    counts = tracing.counters.snapshot()
    snap: Dict[str, Any] = {
        "ts": time.time(),
        "memory": _memory_section(counts),
        "mesh": _mesh_section(counts),
        "serve": _serve_section(),
        "fabric": _fabric_section(),
        "caches": _cache_section(counts),
        "streams": _stream_section(),
        "slo": _slo.slo_status(),
        "flight": _flight.stats(),
        "history": _history_section(),
        "perf": _baseline.perf_stats(),
        "invariants": _invariants_section(counts),
        "quarantine": _quarantine_section(),
        "resilience": {
            "giveups": sum(v for k, v in counts.items()
                           if k.startswith("retry.")
                           and k.endswith(".giveups")),
            "retries": sum(v for k, v in counts.items()
                           if k.startswith("retry.")
                           and k.endswith(".retries")),
            "sync_fallbacks": counts.get("pipeline.sync_fallbacks", 0),
            "oom_splits": counts.get("oom_split.dispatches", 0),
            "plan_oom_fallbacks": counts.get("plan.oom_fallbacks", 0),
            "dplan_fallbacks": counts.get("dplan.fallbacks", 0),
        },
    }
    snap["warnings"] = _warnings(snap)
    return snap
