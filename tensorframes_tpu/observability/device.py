"""Device-memory accounting: HBM watermarks attached to query traces.

The backend's allocator statistics (``Device.memory_stats()`` — populated
by the TPU/GPU PJRT clients, typically ``None`` on CPU) are sampled at
query start/end and around block drains, so a finished
:class:`~.events.QueryTrace` carries the live/peak HBM bytes the query
actually saw — and an OOM split (``engine/executor.py``) is tagged with
the watermark observed at the moment it fired, turning OOM forensics from
guesswork into data.

Zero-cost-when-off: every entry point is called only with an ACTIVE query
trace (``TFT_TRACE`` set), so with tracing off no ``memory_stats()`` call
ever happens. On backends that report nothing (CPU), the first all-``None``
sample latches the module off for the process — traced CPU runs pay one
probe, not one per sample (:func:`_reset` re-arms, for tests).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

from ..utils.logging import get_logger

__all__ = ["raw_memory_stats", "sample", "watermark", "supported"]

_log = get_logger("observability.device")

_lock = threading.Lock()
_unsupported = False  # latched after the first all-None sample


def _local_devices() -> List[Any]:
    """Indirection over ``jax.local_devices()`` (patchable in tests; jax
    imported lazily so this module never forces backend init on import)."""
    import jax

    return jax.local_devices()


def _reset() -> None:
    """Re-arm the unsupported latch (tests patch ``_local_devices``)."""
    global _unsupported
    with _lock:
        _unsupported = False


def raw_memory_stats() -> Optional[List[Tuple[int, Dict[str, Any]]]]:
    """``[(device_index, stats_dict), ...]`` for every local device that
    reports allocator statistics, or ``None`` when the backend supports
    none (CPU) — in which case the module latches off until :func:`_reset`.
    """
    global _unsupported
    with _lock:
        if _unsupported:
            return None
    try:
        devices = _local_devices()
    except Exception as e:  # backend init failure must never kill a query
        _log.debug("local_devices() failed during memory sample: %s", e)
        return None
    out: List[Tuple[int, Dict[str, Any]]] = []
    for i, d in enumerate(devices):
        try:
            ms = d.memory_stats()
        except Exception:
            ms = None
        if ms:
            out.append((i, ms))
    if not out:
        with _lock:
            _unsupported = True
        return None
    return out


def watermark() -> Optional[Dict[str, int]]:
    """Aggregate ``{"live_bytes", "peak_bytes", "limit_bytes",
    "devices"}`` across local devices, or ``None`` when the backend
    reports nothing. ``limit_bytes`` is 0 when no device reports an
    allocator limit — the serving layer's admission control treats that
    as "no enforceable bound" (``TFT_SERVE_HBM_LIMIT_BYTES`` overrides).
    """
    stats = raw_memory_stats()
    if stats is None:
        return None
    live = peak = limit = 0
    for _, ms in stats:
        live += int(ms.get("bytes_in_use") or 0)
        peak += int(ms.get("peak_bytes_in_use") or ms.get("bytes_in_use")
                    or 0)
        limit += int(ms.get("bytes_limit") or 0)
    return {"live_bytes": live, "peak_bytes": peak, "limit_bytes": limit,
            "devices": len(stats)}


def sample(trace, tag: str, per_device: bool = False
           ) -> Optional[Dict[str, int]]:
    """Record one ``hbm_sample`` event on ``trace`` (aggregate across
    devices; ``per_device=True`` additionally puts one event per device on
    its device track). Returns the aggregate watermark, or ``None`` when
    the backend reports no memory stats — the graceful CPU fallback.
    """
    if trace is None:
        return None
    stats = raw_memory_stats()
    if stats is None:
        return None
    from .events import DEVICE_TRACK_BASE

    live = peak = 0
    for i, ms in stats:
        d_live = int(ms.get("bytes_in_use") or 0)
        d_peak = int(ms.get("peak_bytes_in_use") or d_live)
        live += d_live
        peak += d_peak
        if per_device:
            trace.add("hbm_sample", name=tag, tag=tag, device=i,
                      live_bytes=d_live, peak_bytes=d_peak,
                      track=DEVICE_TRACK_BASE + i)
    trace.add("hbm_sample", name=tag, tag=tag, live_bytes=live,
              peak_bytes=peak, devices=len(stats))
    return {"live_bytes": live, "peak_bytes": peak, "devices": len(stats)}


def supported() -> bool:
    """Whether memory-stats sampling is still armed. Reflects only the
    LAST probe: True until a probe has latched the module off (so it is
    optimistically True before any probe, even on a backend that will
    turn out to report nothing — :func:`raw_memory_stats` is the actual
    test)."""
    with _lock:
        return not _unsupported
