"""Query-scoped structured events: the correlation layer over tracing.

The flat ``span``/``counters`` registry (:mod:`..utils.tracing`) answers
"how much time did ``executor.dispatch`` take, process-wide" — but once
the pipelined engine overlaps blocks (and queries overlap each other on
worker threads), nobody can say where block 17 of *this* query spent its
time, or which query's retry tripped the OOM split. This module adds the
missing dimension:

- every public API forcing opens a :class:`QueryTrace` with a unique
  query id (``q<N>``) via :func:`query_trace`;
- the trace rides a :mod:`contextvars` context variable, so any layer —
  engine, pipeline, resilience, native PJRT — attaches typed events with
  plain :func:`add_event` calls and the correlation id survives the
  pipeline's worker threads (:func:`wrap_context` carries it across
  ``ThreadPoolExecutor`` boundaries);
- finished traces land in a bounded process-wide ring buffer
  (:func:`recent_events`) and, when ``TFT_TRACE_FILE`` is set, in a JSONL
  file sink;
- :meth:`QueryTrace.to_chrome_trace` exports a chrome://tracing /
  Perfetto-loadable timeline where each in-flight pipeline slot is its
  own track, so depth tuning becomes visual.

Zero-cost-when-off: :func:`query_trace` yields ``None`` unless tracing is
enabled (``TFT_TRACE=1`` / :func:`~..utils.tracing.enable`), so with
tracing off the whole layer is a handful of ``None`` checks — no events
are ever recorded. Existing ``span``/``counters`` call sites are
untouched; this layer wraps them (a span observer credits every span to
the active trace as well as to the flat registry).
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import json
import os
import threading
import time
from collections import deque
from typing import (Any, Callable, Dict, Iterator, List, Mapping, Optional,
                    Tuple)

from ..utils import tracing
from ..utils.logging import get_logger
from . import flight as _flight

__all__ = ["Event", "QueryTrace", "query_trace", "current_trace",
           "add_event", "wrap_context", "traced_query", "last_query",
           "recent_events", "clear_ring", "block_meta", "bypass",
           "DEVICE_TRACK_BASE"]

_log = get_logger("observability.events")

# chrome-trace track (tid) namespace: 0 = query, 1..depth = pipeline
# slots, DEVICE_TRACK_BASE+i = mesh device i (per-device shard events,
# HBM samples). Far above any realistic pipeline depth, so the two
# namespaces can never collide.
DEVICE_TRACK_BASE = 1000


def _env_int(name: str, default: int) -> int:
    # local twin of resilience.env_int: this module must stay importable
    # from resilience/policy.py without a circular import
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return int(raw)
    except ValueError:
        _log.warning("ignoring malformed %s=%r", name, raw)
        return default


_qid_counter = itertools.count(1)
_current: "contextvars.ContextVar[Optional[QueryTrace]]" = \
    contextvars.ContextVar("tft_query_trace", default=None)
# tracing-off slow-query timer nesting guard: nested forcings must join
# the ambient (outermost) timed query exactly like traced queries join
# the ambient trace — without this, one API call logs one slow line per
# upstream frame it forces
_slow_active: "contextvars.ContextVar[bool]" = \
    contextvars.ContextVar("tft_slow_query_active", default=False)

# benchmark hook: strips the event layer entirely (even the enabled()
# check) so bench.py can measure the disabled layer's residual cost
_bypass = False

_last_lock = threading.Lock()
_last_query: Optional["QueryTrace"] = None

_ring_lock = threading.Lock()
_ring: "deque[Dict[str, Any]]" = deque(
    maxlen=_env_int("TFT_TRACE_RING", 8192))


class Event:
    """One typed trace event.

    ``ts``/``dur`` are seconds relative to the owning trace's start;
    ``track`` selects the chrome-trace row (0 = query-level, ``slot+1``
    for per-pipeline-slot block events); ``args`` carries the typed
    payload (block index, rows, bytes, error class, ...).
    """

    __slots__ = ("etype", "name", "ts", "dur", "track", "args")

    def __init__(self, etype: str, name: Optional[str], ts: float,
                 dur: Optional[float] = None, track: int = 0,
                 args: Optional[Dict[str, Any]] = None):
        self.etype = etype
        self.name = name
        self.ts = ts
        self.dur = dur
        self.track = track
        self.args = args

    def as_dict(self, query_id: Optional[str] = None) -> Dict[str, Any]:
        d: Dict[str, Any] = {"type": self.etype, "ts": self.ts,
                             "track": self.track}
        if query_id is not None:
            d["query_id"] = query_id
        if self.name is not None:
            d["name"] = self.name
        if self.dur is not None:
            d["dur"] = self.dur
        if self.args:
            d.update(self.args)
        return d

    def __repr__(self):
        return (f"Event({self.etype!r}, name={self.name!r}, "
                f"ts={self.ts:.6f}, dur={self.dur}, track={self.track}, "
                f"args={self.args!r})")


class QueryTrace:
    """All events of one public-API query, under one correlation id.

    Thread-safe: the pipeline's worker threads append through the
    contextvar carried by :func:`wrap_context`. The event list is bounded
    (``TFT_TRACE_MAX_EVENTS``, default 50k) — overflow increments
    ``dropped`` instead of growing without bound.
    """

    def __init__(self, op: str, meta: Optional[Dict[str, Any]] = None,
                 max_events: Optional[int] = None):
        self.query_id = f"q{next(_qid_counter)}"
        self.op = op
        self.meta = dict(meta or {})
        self.start_time = time.time()
        self._t0 = time.perf_counter()
        self.duration: Optional[float] = None
        self.events: List[Event] = []
        self.dropped = 0
        # per-query span attribution: name -> [count, total_seconds]
        self.stages: Dict[str, List[float]] = {}
        self._lock = threading.Lock()
        self._max_events = (max_events if max_events is not None
                            else _env_int("TFT_TRACE_MAX_EVENTS", 50_000))

    # -- recording ---------------------------------------------------------
    def clock(self) -> float:
        """Seconds since this trace opened (the event timebase)."""
        return time.perf_counter() - self._t0

    def add(self, etype: str, name: Optional[str] = None,
            ts: Optional[float] = None, dur: Optional[float] = None,
            track: int = 0, **args) -> Optional[Event]:
        if ts is None:
            ts = self.clock()
        ev = Event(etype, name, ts, dur, track, args or None)
        with self._lock:
            if len(self.events) >= self._max_events:
                self.dropped += 1
                return None
            self.events.append(ev)
        return ev

    def add_stage(self, name: str, dt: float) -> None:
        with self._lock:
            st = self.stages.get(name)
            if st is None:
                self.stages[name] = [1, dt]
            else:
                st[0] += 1
                st[1] += dt

    def _finish(self, error: Optional[str] = None) -> None:
        try:  # HBM watermark at query end (None fallback on CPU)
            from . import device as _device
            _device.sample(self, "query_end", per_device=True)
        except Exception as e:
            _log.debug("query-end memory sample failed: %s", e)
        self.duration = self.clock()
        if error is not None:
            # a failed query must stay distinguishable from a slow
            # success — in the latency histogram (its own series), the
            # slow-query log, and the exported trace/meta
            self.meta["error"] = error
        tracing.counters.inc("trace.queries")
        tracing.histograms.observe("query_latency_seconds", self.duration,
                                   op=self.op,
                                   outcome="error" if error else "ok")
        if self.dropped:
            tracing.counters.inc("trace.events_dropped", self.dropped)
        with self._lock:
            dicts = [ev.as_dict(self.query_id) for ev in self.events]
        with _ring_lock:
            _ring.extend(dicts)
        global _last_query
        with _last_lock:
            _last_query = self
        path = os.environ.get("TFT_TRACE_FILE")
        if path:
            self._write_jsonl(path, dicts)
        # durable query history: traced forcings archive too (the
        # serve scheduler archives its own richer record under the
        # serving id; a trace's "qN" id is a distinct entry). Skip the
        # serve op — its scheduler fold point already covers it.
        if self.op != "serve":
            from . import history as _history
            _history.record_finish(
                self.query_id, outcome="error" if error else "ok",
                error=error, run_s=self.duration,
                total_s=self.duration, source="trace",
                summary=self.op,
                decisions=_flight.for_query(self.query_id))
        ms = _slow_query_threshold_ms()
        if ms is not None and self.duration * 1000.0 >= ms:
            s = self.summary()
            rec = {"type": "slow_query", "query_id": self.query_id,
                   "op": self.op,
                   "duration_ms": round(self.duration * 1000.0, 3),
                   "blocks": s["blocks"], "retries": s["retries"],
                   "oom_splits": s["oom_splits"],
                   "sync_fallbacks": s["sync_fallbacks"]}
            if error is not None:
                rec["error"] = error
            if s["hbm"] is not None:
                rec["peak_hbm_bytes"] = s["hbm"]["peak"]
            _emit_slow(rec)

    def _write_jsonl(self, path: str, dicts: List[Dict[str, Any]]) -> None:
        head = {"type": "query", "query_id": self.query_id, "op": self.op,
                "start_time": self.start_time, "duration": self.duration,
                "dropped": self.dropped, **self.meta}
        lines = [json.dumps(head, default=str)]
        lines.extend(json.dumps(d, default=str) for d in dicts)
        try:
            # the shared size-capped sink (TFT_TRACE_FILE_MAX_BYTES,
            # keep-1 rollover to <path>.1) — a long-running serve
            # process must not grow the trace file without bound
            _flight.append_jsonl(path, lines)
        except OSError as e:
            _log.warning("TFT_TRACE_FILE=%s write failed: %s", path, e)

    # -- introspection -----------------------------------------------------
    def count(self, etype: str) -> int:
        with self._lock:
            return sum(1 for ev in self.events if ev.etype == etype)

    def summary(self) -> Dict[str, Any]:
        """Aggregate the event stream into the per-query totals
        ``explain()`` renders (blocks, rows, bytes, retries, fallbacks,
        compile-cache hits/misses, pipeline occupancy, per-device mesh
        stats with a straggler ratio, and HBM watermarks)."""
        s: Dict[str, Any] = {
            "query_id": self.query_id, "op": self.op,
            "duration_s": self.duration if self.duration is not None
            else self.clock(),
            "blocks": 0, "rows_in": 0, "rows_out": 0, "bytes_in": 0,
            "retries": 0, "giveups": 0, "oom_splits": 0,
            "pad_fallbacks": 0, "sync_fallbacks": 0,
            "compile_hits": 0, "compile_misses": 0,
            "compile_seconds": 0.0, "dispatches": 0,
            "mesh_dispatches": 0, "collectives": 0,
            "mesh_shrinks": 0, "rebalances": 0,
            "mesh_grows": 0, "preempts": 0, "resumed_blocks": 0,
            "spills": 0, "spill_bytes": 0, "faults": 0,
            "proactive_splits": 0, "external_sort_runs": 0,
            "events": 0, "dropped": self.dropped,
            "occupancy_mean": None, "slots": 0,
            "mesh": None, "hbm": None,
        }
        occ_total = 0.0
        occ_n = 0
        slots = set()
        # per-device accumulation: device -> [rows, bytes, time_s]
        devs: Dict[int, list] = {}
        hbm_live_start = hbm_live_end = hbm_peak = None
        with self._lock:
            events = list(self.events)
        for ev in events:
            a = ev.args or {}
            if ev.etype in ("block_submit", "block_run"):
                s["blocks"] += 1
                s["rows_in"] += int(a.get("rows") or 0)
                s["bytes_in"] += int(a.get("bytes") or 0)
                if ev.track > 0:
                    slots.add(ev.track)
            if ev.etype in ("block_drain", "block_run"):
                s["rows_out"] += int(a.get("rows_out") or 0)
            elif ev.etype == "retry":
                s["retries"] += 1
            elif ev.etype == "giveup":
                s["giveups"] += 1
            elif ev.etype == "oom_split":
                s["oom_splits"] += 1
            elif ev.etype == "pad_fallback":
                s["pad_fallbacks"] += 1
            elif ev.etype == "sync_fallback":
                s["sync_fallbacks"] += 1
            elif ev.etype == "compile_cache":
                if a.get("hit"):
                    s["compile_hits"] += 1
                else:
                    s["compile_misses"] += 1
            elif ev.etype == "compile":
                s["compile_seconds"] += float(ev.dur or 0.0)
            elif ev.etype == "dispatch":
                s["dispatches"] += 1
            elif ev.etype == "mesh_dispatch":
                s["mesh_dispatches"] += 1
            elif ev.etype == "collective":
                s["collectives"] += 1
            elif ev.etype == "mesh_shrink":
                s["mesh_shrinks"] += 1
            elif ev.etype == "mesh_grow":
                s["mesh_grows"] += 1
            elif ev.etype == "preempt_park":
                s["preempts"] += 1
            elif ev.etype == "resume":
                s["resumed_blocks"] += int(a.get("blocks") or 0)
            elif ev.etype == "rebalance":
                s["rebalances"] += 1
            elif ev.etype == "spill":
                s["spills"] += 1
                s["spill_bytes"] += int(a.get("bytes") or 0)
            elif ev.etype == "fault":
                s["faults"] += 1
            elif ev.etype == "proactive_split":
                s["proactive_splits"] += 1
            elif ev.etype == "external_sort":
                s["external_sort_runs"] += int(a.get("runs") or 0)
            elif ev.etype == "shard":
                d = a.get("device")
                if d is not None:
                    acc = devs.setdefault(int(d), [0, 0, 0.0])
                    acc[0] += int(a.get("rows") or 0)
                    acc[1] += int(a.get("bytes") or 0)
            elif ev.etype == "shard_compute":
                d = a.get("device")
                if d is not None:
                    acc = devs.setdefault(int(d), [0, 0, 0.0])
                    acc[2] += float(ev.dur or 0.0)
            elif ev.etype == "hbm_sample" and a.get("device") is None:
                live = int(a.get("live_bytes") or 0)
                peak = int(a.get("peak_bytes") or live)
                if hbm_live_start is None:
                    hbm_live_start = live
                hbm_live_end = live
                hbm_peak = max(hbm_peak or 0, peak, live)
            elif ev.etype == "occupancy":
                occ_total += float(a.get("value") or 0.0)
                occ_n += 1
        s["events"] = len(events)
        s["slots"] = len(slots)
        if occ_n:
            s["occupancy_mean"] = occ_total / occ_n
        if devs:
            times = [acc[2] for acc in devs.values() if acc[2] > 0.0]
            ratio = None
            if len(times) >= 2:
                import statistics
                med = statistics.median(times)
                if med > 0.0:
                    ratio = max(times) / med
            s["mesh"] = {
                "devices": {d: {"rows": acc[0], "bytes": acc[1],
                                "time_s": acc[2]}
                            for d, acc in sorted(devs.items())},
                "straggler_ratio": ratio,
            }
        if hbm_peak is not None:
            s["hbm"] = {"live_start": hbm_live_start,
                        "live_end": hbm_live_end, "peak": hbm_peak}
        return s

    def report(self) -> str:
        from .report import render
        return render(self)

    # -- chrome trace export ----------------------------------------------
    def to_chrome_trace(self, file: Optional[str] = None) -> str:
        """A chrome://tracing / Perfetto-loadable JSON timeline.

        One process per query; track (``tid``) 0 carries the query span
        and instantaneous events (retries, OOM splits, fallbacks), tracks
        1..depth are the in-flight pipeline slots with each block's
        submit/compute/drain phases, and tracks
        ``DEVICE_TRACK_BASE + i`` (named ``device i``) carry the mesh
        layer's per-device shard sizes, readiness timings, and HBM
        samples — occupancy, stall, and straggler patterns become visible
        at a glance. Returns the JSON string; ``file`` also writes it
        out.
        """
        pid = 1
        with self._lock:
            events = list(self.events)
        out: List[Dict[str, Any]] = []
        tracks = {0}
        for ev in events:
            tracks.add(ev.track)
            rec: Dict[str, Any] = {
                "name": ev.name or ev.etype,
                "cat": ev.etype,
                "pid": pid,
                "tid": ev.track,
                "ts": round(ev.ts * 1e6, 3),
                "args": {"query_id": self.query_id, **(ev.args or {})},
            }
            if ev.dur is not None:
                rec["ph"] = "X"
                rec["dur"] = round(max(ev.dur, 0.0) * 1e6, 3)
            else:
                rec["ph"] = "i"
                rec["s"] = "t"
            out.append(rec)
        dur = self.duration if self.duration is not None else self.clock()
        out.append({"name": f"{self.op} [{self.query_id}]",
                    "cat": "query", "ph": "X", "pid": pid, "tid": 0,
                    "ts": 0.0, "dur": round(dur * 1e6, 3),
                    "args": {"query_id": self.query_id, **self.meta}})
        out.sort(key=lambda r: (r["ts"], r["tid"]))
        meta: List[Dict[str, Any]] = [{
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "ts": 0.0,
            "args": {"name": f"tensorframes_tpu {self.query_id} "
                             f"({self.op})"}}]
        for tid in sorted(tracks):
            if tid == 0:
                tname = "query"
            elif tid >= DEVICE_TRACK_BASE:
                tname = f"device {tid - DEVICE_TRACK_BASE}"
            else:
                tname = f"slot {tid - 1}"
            meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                         "tid": tid, "ts": 0.0,
                         "args": {"name": tname}})
        doc = {"traceEvents": meta + out, "displayTimeUnit": "ms",
               "otherData": {"query_id": self.query_id, "op": self.op,
                             "start_time": self.start_time}}
        text = json.dumps(doc, default=str)
        if file:
            with open(file, "w") as f:
                f.write(text)
        return text

    def __repr__(self):
        return (f"QueryTrace({self.query_id}, op={self.op!r}, "
                f"events={len(self.events)}, "
                f"duration={self.duration})")


# ---------------------------------------------------------------------------
# context management
# ---------------------------------------------------------------------------

def current_trace() -> Optional[QueryTrace]:
    """The active :class:`QueryTrace`, or None (tracing off / no query)."""
    return _current.get()


_slow_malformed_warned = False


def _slow_query_threshold_ms() -> Optional[float]:
    """The ``TFT_SLOW_QUERY_MS`` threshold, or ``None`` when unset."""
    raw = os.environ.get("TFT_SLOW_QUERY_MS")
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        global _slow_malformed_warned
        if not _slow_malformed_warned:
            _log.warning("ignoring malformed TFT_SLOW_QUERY_MS=%r", raw)
            _slow_malformed_warned = True
        return None


def _emit_slow(rec: Dict[str, Any]) -> None:
    """One condensed slow-query JSONL line: to the ``TFT_TRACE_FILE``
    sink when set (size-capped rotation shared with the trace writer),
    else the logger. A slow query also triggers a flight-recorder dump
    when ``TFT_FLIGHT_DUMP`` is set — the decisions that made it slow
    are in the ring right now."""
    try:
        # the performance sentinel's live cost preview: the in-flight
        # cost vector, the plan fingerprint, and the worst deviation
        # against the stored baseline — a slow-query line should be
        # self-diagnosing without a follow-up tft.why(). Lazy import:
        # baseline imports flight, which this module already rides.
        from . import baseline as _baseline
        ctx = _baseline.slow_context()
        if ctx is not None:
            rec = {**rec, **ctx}
    except Exception as e:
        _log.debug("slow-query cost enrichment failed: %s", e)
    line = json.dumps(rec, default=str)
    _flight.maybe_dump("slow_query")
    path = os.environ.get("TFT_TRACE_FILE")
    if path:
        try:
            _flight.append_jsonl(path, [line])
            return
        except OSError as e:
            _log.warning("TFT_TRACE_FILE=%s write failed: %s", path, e)
    _log.warning("slow query: %s", line)


@contextlib.contextmanager
def query_trace(op: str, **meta) -> Iterator[Optional[QueryTrace]]:
    """Open a query-scoped trace around a public-API execution.

    Yields the new :class:`QueryTrace` — or ``None`` when tracing is
    disabled (zero-cost-when-off) or a trace is already active (nested
    API calls join the ambient query instead of fragmenting it; events
    they record attach to the outermost trace).

    ``TFT_SLOW_QUERY_MS``: top-level queries exceeding the threshold emit
    one condensed JSONL line even with full tracing OFF — the timing then
    is a bare ``perf_counter`` pair, no trace or events are allocated.
    """
    if _bypass:
        yield None
        return
    if not tracing.enabled() or _current.get() is not None:
        ms = _slow_query_threshold_ms()
        if ms is None or _current.get() is not None or _slow_active.get():
            yield None
            return
        token = _slow_active.set(True)
        t0 = time.perf_counter()
        err = None
        try:
            yield None
        except BaseException as e:
            err = type(e).__name__
            raise
        finally:
            _slow_active.reset(token)
            dur = time.perf_counter() - t0
            if dur * 1000.0 >= ms:
                rec = {"type": "slow_query", "op": op,
                       "duration_ms": round(dur * 1000.0, 3)}
                if err is not None:
                    rec["error"] = err
                _emit_slow(rec)
        return
    t = QueryTrace(op, meta)
    token = _current.set(t)
    try:  # HBM watermark at query start (None fallback on CPU)
        from . import device as _device
        _device.sample(t, "query_start", per_device=True)
    except Exception as e:
        _log.debug("query-start memory sample failed: %s", e)
    err = None
    try:
        yield t
    except BaseException as e:
        err = type(e).__name__
        raise
    finally:
        _current.reset(token)
        t._finish(error=err)


def add_event(etype: str, name: Optional[str] = None,
              dur: Optional[float] = None, track: int = 0,
              **args) -> None:
    """Attach a typed event to the active query trace (no-op without
    one). The cheap fire-and-forget hook every layer calls."""
    if _bypass:
        return
    t = _current.get()
    if t is not None:
        t.add(etype, name=name, dur=dur, track=track, **args)


def wrap_context(fn: Callable) -> Callable:
    """Bind ``fn`` to the CALLER's context so the query correlation id
    survives a hop onto a worker thread (``contextvars`` do not propagate
    into ``ThreadPoolExecutor`` tasks by themselves). Used by the native
    PJRT submit path; any executor that dispatches on its own threads
    should do the same."""
    ctx = contextvars.copy_context()

    def bound(*a, **k):
        return ctx.run(fn, *a, **k)

    return bound


def traced_query(op: str, meta_fn: Optional[Callable] = None):
    """Decorator form of :func:`query_trace` for eager API entry points
    (``reduce_*``, ``aggregate``, the mesh d-ops).

    ``meta_fn(*args, **kwargs) -> dict`` extracts entry metadata (mesh
    shape, shard count, fetch names) from the call so distributed traces
    are self-describing instead of bare op names. It runs ONLY when a
    trace actually opened (zero-cost-when-off) and is best-effort — a
    failure is logged, never raised into the query.
    """
    def deco(fn: Callable) -> Callable:
        import functools

        @functools.wraps(fn)
        def wrapper(*a, **k):
            with query_trace(op) as t:
                if t is not None and meta_fn is not None:
                    try:
                        t.meta.update(meta_fn(*a, **k) or {})
                    except Exception as e:
                        _log.debug("traced_query meta_fn for %s failed: "
                                   "%s", op, e)
                return fn(*a, **k)

        return wrapper

    return deco


def last_query() -> Optional[QueryTrace]:
    """The most recently finished :class:`QueryTrace` (any frame/op)."""
    with _last_lock:
        return _last_query


@contextlib.contextmanager
def bypass() -> Iterator[None]:
    """Short-circuit :func:`query_trace` and :func:`add_event` at their
    first check — the benchmark baseline for measuring what the
    (already disabled) event layer's hooks still cost on top of a bare
    flag test."""
    global _bypass
    was = _bypass
    _bypass = True
    try:
        yield
    finally:
        _bypass = was


# ---------------------------------------------------------------------------
# ring buffer sink
# ---------------------------------------------------------------------------

def recent_events() -> List[Dict[str, Any]]:
    """The bounded process-wide ring of recent events (across queries),
    oldest first. Size: ``TFT_TRACE_RING`` (default 8192)."""
    with _ring_lock:
        return list(_ring)


def clear_ring() -> None:
    """Drop buffered events and re-read ``TFT_TRACE_RING`` for the
    bound (tests flip it)."""
    global _ring
    with _ring_lock:
        _ring = deque(maxlen=_env_int("TFT_TRACE_RING", 8192))


def _reset_last_query() -> None:
    global _last_query
    with _last_lock:
        _last_query = None


# ---------------------------------------------------------------------------
# helpers for instrumented layers
# ---------------------------------------------------------------------------

def block_meta(b) -> Tuple[Optional[int], int]:
    """Best-effort ``(rows, bytes)`` of a block-ish object: an engine
    ``Block`` (``num_rows`` + ``columns``) or a plain mapping of arrays.
    Only called with an active trace, so the introspection never costs
    the untraced path anything."""
    rows = getattr(b, "num_rows", None)
    cols = getattr(b, "columns", None)
    if cols is None and isinstance(b, Mapping):
        cols = b
    nbytes = 0
    if cols:
        for v in cols.values():
            nb = getattr(v, "nbytes", None)
            if nb is not None:
                nbytes += int(nb)
        if rows is None:
            try:
                rows = len(next(iter(cols.values())))
            except (TypeError, StopIteration):
                rows = None
    return rows, nbytes


def _on_span(name: str, dt: float) -> None:
    """Span observer (registered with utils.tracing at package import):
    credit every span to the active query's per-stage breakdown."""
    t = _current.get()
    if t is not None:
        t.add_stage(name, dt)
