"""Always-on bounded telemetry timeline: a ring of periodic snapshots.

Every surface the runtime exposes — counters, gauges, histogram
families, ``metrics_text()`` — is point-in-time: it answers "what is
the value now", never "what changed in the last N minutes". A knob
flip, a mesh shrink, or a cache eviction that bends a rate is invisible
without an external Prometheus scraping the endpoint. This module keeps
a small in-process history so the question is answerable from a REPL on
the stricken host:

- :func:`maybe_sample` — the one opportunistic hook: callers on
  already-slow paths (query finish, stream batch boundaries, a metrics
  scrape) invite a sample, and one is taken only when
  ``TFT_TIMELINE_INTERVAL_S`` (default 5s) has elapsed since the last.
  No background thread: a quiet process takes no samples, a busy one
  samples at the interval. Each sample snapshots every counter, every
  gauge's last value, and every histogram family's ``(count, sum)``
  aggregated across label sets, into a bounded ring
  (``TFT_TIMELINE_SAMPLES``, default 720 — an hour at the default
  interval; overflow drops oldest and counts the drop).
- :func:`timeline` — ``tft.timeline(family, window_s=)``: the sampled
  series for one family (a counter name or prefix, a gauge, or a
  histogram family / ``<family>.count``) with consecutive deltas and
  per-second rates.

``TFT_TIMELINE=0`` bypasses the ENTIRE performance sentinel — this
ring, per-query cost attribution, and the baseline/regression detector
(:mod:`.baseline` delegates its gate here) — at one env check, like
``TFT_FLIGHT``. The sentinel is bench-enforced ≤2% on the serve mixed
workload (``bench.py sentinel_overhead``). Self-metrics
(``tft_timeline_*``) make the ring's own health scrapeable.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ..utils import tracing
from ..utils.logging import get_logger

__all__ = ["enabled", "maybe_sample", "sample_now", "timeline",
           "families", "recent_samples", "stats", "clear"]

_log = get_logger("observability.timeline")


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return int(raw)
    except ValueError:
        _log.warning("ignoring malformed %s=%r", name, raw)
        return default


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return float(raw)
    except ValueError:
        _log.warning("ignoring malformed %s=%r", name, raw)
        return default


def enabled() -> bool:
    """``TFT_TIMELINE`` gate (default ON). ``TFT_TIMELINE=0`` bypasses
    the whole performance sentinel — timeline sampling, cost
    attribution, and regression detection — at this one check,
    bit-identically."""
    return os.environ.get("TFT_TIMELINE", "") not in ("0", "false")


def _interval_s() -> float:
    return max(_env_float("TFT_TIMELINE_INTERVAL_S", 5.0), 0.0)


_lock = threading.Lock()
_ring: "deque[Dict[str, Any]]" = deque(
    maxlen=_env_int("TFT_TIMELINE_SAMPLES", 720))
_taken = 0    # lifetime samples taken (the ring drops, this does not)
_dropped = 0  # oldest samples pushed out of the ring
_last_mono: float = float("-inf")


def _take_sample_locked() -> None:
    """Snapshot the tracing registries into one ring entry. The
    registry snapshots take their own (finer) locks; nothing ever
    acquires the timeline lock while holding them, so the ordering is
    one-way."""
    global _taken, _dropped
    hist: Dict[str, Dict[str, float]] = {}
    for (fam, _labels), h in tracing.histograms.snapshot().items():
        agg = hist.setdefault(fam, {"count": 0, "sum": 0.0})
        agg["count"] += int(h["count"])
        agg["sum"] += float(h["sum"])
    gauges = {name: g["last"]
              for name, g in tracing.timings.gauges_snapshot().items()}
    sample = {"ts": time.time(),
              "counters": tracing.counters.snapshot(),
              "gauges": gauges,
              "hist": hist}
    if _ring.maxlen is not None and len(_ring) == _ring.maxlen:
        _dropped += 1
    _ring.append(sample)
    _taken += 1


def maybe_sample() -> bool:
    """Take one sample if the timeline is enabled and the interval has
    elapsed; returns whether one was taken. Safe (and cheap) to call
    from busy paths — the off-interval case is one monotonic read and
    one comparison after the env check."""
    global _last_mono
    if not enabled():
        return False
    now = time.monotonic()
    if now - _last_mono < _interval_s():
        return False
    with _lock:
        if now - _last_mono < _interval_s():
            return False  # lost the race: someone else just sampled
        _last_mono = now
        _take_sample_locked()
    return True


def sample_now() -> bool:
    """Force a sample regardless of the interval (still gated by
    ``TFT_TIMELINE=0``). Tests and interactive triage use this."""
    if not enabled():
        return False
    global _last_mono
    with _lock:
        _last_mono = time.monotonic()
        _take_sample_locked()
    return True


def recent_samples(window_s: Optional[float] = None
                   ) -> List[Dict[str, Any]]:
    """Ring snapshot, oldest first; ``window_s`` keeps samples newer
    than that many seconds."""
    with _lock:
        out = list(_ring)
    if window_s is not None:
        cutoff = time.time() - float(window_s)
        out = [s for s in out if s["ts"] >= cutoff]
    return out


def _value_of(sample: Dict[str, Any], family: str) -> Optional[float]:
    """One family's value in one sample: an exact counter, a prefix-sum
    over a counter namespace (``"serve"`` sums ``serve.*``), a gauge's
    last value, a histogram family's ``sum`` (seconds), or its
    ``.count``."""
    counters = sample["counters"]
    if family in counters:
        return float(counters[family])
    prefix = family + "."
    matched = [v for k, v in counters.items() if k.startswith(prefix)]
    if matched:
        return float(sum(matched))
    if family in sample["gauges"]:
        return float(sample["gauges"][family])
    hist = sample["hist"]
    if family in hist:
        return float(hist[family]["sum"])
    if family.endswith(".count") and family[:-6] in hist:
        return float(hist[family[:-6]]["count"])
    return None


def timeline(family: str,
             window_s: Optional[float] = None) -> Dict[str, Any]:
    """The sampled series for ``family`` with consecutive deltas and
    per-second rates — "what changed in the last N minutes" without an
    external scraper. Samples where the family had no value yet are
    skipped (a counter that first fired mid-window simply starts
    there)."""
    points = []
    for s in recent_samples(window_s):
        v = _value_of(s, family)
        if v is not None:
            points.append({"ts": s["ts"], "value": v})
    deltas = []
    for prev, cur in zip(points, points[1:]):
        dt = cur["ts"] - prev["ts"]
        dv = cur["value"] - prev["value"]
        deltas.append({"ts": cur["ts"], "delta": dv,
                       "rate_per_s": dv / dt if dt > 0 else 0.0})
    total = points[-1]["value"] - points[0]["value"] \
        if len(points) >= 2 else 0.0
    span = points[-1]["ts"] - points[0]["ts"] if len(points) >= 2 else 0.0
    return {"family": family, "samples": len(points), "points": points,
            "deltas": deltas, "total_delta": total,
            "rate_per_s": total / span if span > 0 else 0.0}


def families() -> List[str]:
    """Every family name present in the newest sample (counters,
    gauges, histogram families)."""
    with _lock:
        if not _ring:
            return []
        s = _ring[-1]
    return sorted(set(s["counters"]) | set(s["gauges"]) | set(s["hist"]))


def stats() -> Dict[str, Any]:
    with _lock:
        n = len(_ring)
        cap = _ring.maxlen
        taken, dropped = _taken, _dropped
    age = None
    if n:
        age = max(time.time() - recent_samples()[-1]["ts"], 0.0)
    return {"enabled": enabled(), "samples": n, "capacity": cap,
            "taken_total": taken, "dropped_total": dropped,
            "interval_s": _interval_s(), "last_sample_age_s": age}


def clear() -> None:
    """Drop the ring, reset the lifetime totals, and re-read
    ``TFT_TIMELINE_SAMPLES`` (tests flip it)."""
    global _ring, _taken, _dropped, _last_mono
    with _lock:
        _ring = deque(maxlen=_env_int("TFT_TIMELINE_SAMPLES", 720))
        _taken = _dropped = 0
        _last_mono = float("-inf")


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------

def _render_metrics() -> List[str]:
    # a scrape is itself a fine moment to sample — the endpoint is the
    # timeline's heartbeat on otherwise-idle processes
    maybe_sample()
    s = stats()
    return [
        "# HELP tft_timeline_samples_total Telemetry timeline samples "
        "taken (lifetime; the ring holds the newest).",
        "# TYPE tft_timeline_samples_total counter",
        f"tft_timeline_samples_total {s['taken_total']}",
        "# HELP tft_timeline_ring_samples Samples currently held in "
        "the bounded timeline ring.",
        "# TYPE tft_timeline_ring_samples gauge",
        f"tft_timeline_ring_samples {s['samples']}",
        "# HELP tft_timeline_dropped_total Oldest samples dropped from "
        "the ring on overflow.",
        "# TYPE tft_timeline_dropped_total counter",
        f"tft_timeline_dropped_total {s['dropped_total']}",
    ]


def _register_metrics() -> None:
    # deferred: metrics imports events, which imports flight first
    from .metrics import register_metrics_provider
    register_metrics_provider("timeline", _render_metrics)
