"""Durable query history + crash post-mortems: the flight-data archive.

Everything the observability stack knows is in process memory — the
flight ring (:mod:`.flight`), the trace ring (:mod:`.events`), the
timeline (:mod:`.timeline`), the sentinel's cost vectors
(:mod:`.baseline`) — and dies with the process unless an anomaly
happened to fire a ``TFT_FLIGHT_DUMP``. A serving fleet doing rolling
restarts as a matter of course needs the Spark-history-server answer:
every *finished* query remains inspectable after the fact, across ring
rotation AND process death. This module is that archive.

**What is recorded.** At every query-terminal fold point — the serve
scheduler's ``_finish``, a ``traced_query`` close, a stream
batch-window emit (and a poisoned-batch skip) — :func:`record_finish`
appends ONE compact record: query id, tenant, plan fingerprint + a
short summary, the sentinel's cost vector, a bounded digest of that
query's flight-ring decisions (the newest ``TFT_HISTORY_DECISIONS``
with a per-kind histogram of the rest), outcome / classified error
kind, the executing worker id, and queued/run/total wall times.

**How it is stored.** Append-only, size-rotated segments
(``seg-NNNNNN.hist``) under ``TFT_HISTORY_DIR`` (or
``<persist root>/history`` when the durable tier is on — a fabric that
configured persistence gets a history for free). Each record is framed
``magic + length + sha256(payload) + payload`` — the :mod:`..memory.persist`
discipline applied per record so a segment is appendable without
rewriting. A record lands in ONE ``write()`` on an ``O_APPEND``
descriptor, so a crash never tears a *completed* append; whatever a
crash does leave behind trips the checksum walk and the segment goes
COLD — counted (``history.segments_corrupt``), flight-recorded
(``history.segment_corrupt``), unlinked — never returning wrong
records (the PR 19 cold-never-wrong contract; earlier segments stay
readable). Rotation at ``TFT_HISTORY_MAX_BYTES`` per segment;
``TFT_HISTORY_RETENTION`` newest segments kept, older ones evicted and
counted. ``TFT_HISTORY=0`` bypasses every hook at one env check.

**Reading it back.** :func:`history` filters
(tenant/fingerprint/outcome/since/slow_only) and *stitches*: a query
that migrated across fabric workers (same query id, several
worker-stamped records) reads back as ONE record with the worker path
and migration count. :func:`causal_chain` feeds ``tft.why()``'s
durable fall-through (ring → flight dumps → history), so a causal
chain survives both ring rotation and a restart.

**Post-mortems.** The first append of a process drops a
``running-<pid>`` marker in the history dir, removed at clean
interpreter exit. Startup (or the first append after a dir becomes
visible) scans for markers of DEAD pids: finding one is an unclean
shutdown — counted, flight-recorded (``history.unclean_shutdown``,
surfaced by ``doctor()``/``health()``), and :func:`postmortem`
synthesizes the triage report: the marker's story, the history tail,
the last flight dump's summary, and timeline rates — one call after a
crash nobody watched.
"""

from __future__ import annotations

import atexit
import hashlib
import json
import os
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..utils.logging import get_logger
from ..utils.tracing import counters

__all__ = ["enabled", "active_dir", "record_finish", "history",
           "causal_chain", "postmortem", "stats", "clear"]

_log = get_logger("observability.history")

# per-record framing: magic + 4-byte payload length + sha256(payload)
# + payload. Same discipline as memory/persist.py (magic keys the
# layout, digest catches bit rot before JSON can parse wrong data) but
# applied per RECORD so segments stay append-only.
_MAGIC = b"TFTH\x01"
_DIGEST_LEN = 32
_HEAD_LEN = len(_MAGIC) + 4 + _DIGEST_LEN

_SEG_PREFIX = "seg-"
_SEG_SUFFIX = ".hist"
_MARKER_PREFIX = "running-"
_MARKER_SUFFIX = ".marker"

_DEFAULT_MAX_BYTES = 4 * 1024 * 1024
_DEFAULT_RETENTION = 8
_DEFAULT_DECISIONS = 32

_lock = threading.Lock()
# active-segment cache: (dir, seg_no, size) — re-resolved when the dir
# changes (tests flip TFT_HISTORY_DIR; the fabric configures persist)
_active: Optional[Tuple[str, int, int]] = None
# dirs whose stale-marker scan already ran (once per process per dir)
_scanned: set = set()
# markers this process created (removed at clean exit)
_markers: set = set()
# the newest unclean shutdown detected this process, or None
_unclean: Optional[Dict[str, Any]] = None

# lifetime counts for stats()/metrics (tracing counters mirror them so
# the timeline can rate them)
_written = 0
_rotations = 0
_evictions = 0
_corrupt = 0
_write_errors = 0
_unclean_total = 0


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return int(raw)
    except ValueError:
        _log.warning("ignoring malformed %s=%r", name, raw)
        return default


def enabled() -> bool:
    """``TFT_HISTORY`` gate (default ON — the archive exists for the
    crash nobody planned). ``TFT_HISTORY=0`` bypasses every hook at
    this one env check."""
    return os.environ.get("TFT_HISTORY", "") not in ("0", "false")


def active_dir() -> Optional[str]:
    """The history directory, or ``None`` (archive off): an explicit
    ``TFT_HISTORY_DIR``, else ``<persist root>/history`` when the
    durable tier (``memory/persist.py``) is configured — so a fabric
    run archives without any extra knob."""
    d = os.environ.get("TFT_HISTORY_DIR")
    if d:
        return d
    from ..memory import persist as _persist
    base = _persist.root()
    if base is None:
        return None
    return os.path.join(base, "history")


def _max_bytes() -> int:
    return max(_env_int("TFT_HISTORY_MAX_BYTES", _DEFAULT_MAX_BYTES), 1)


def _retention() -> int:
    return max(_env_int("TFT_HISTORY_RETENTION", _DEFAULT_RETENTION), 1)


def _decisions_keep() -> int:
    return max(_env_int("TFT_HISTORY_DECISIONS", _DEFAULT_DECISIONS), 0)


def _frame(payload: bytes) -> bytes:
    return (_MAGIC + struct.pack(">I", len(payload))
            + hashlib.sha256(payload).digest() + payload)


def _seg_no(name: str) -> Optional[int]:
    if not (name.startswith(_SEG_PREFIX)
            and name.endswith(_SEG_SUFFIX)):
        return None
    try:
        return int(name[len(_SEG_PREFIX):-len(_SEG_SUFFIX)])
    except ValueError:
        return None


def _seg_path(d: str, no: int) -> str:
    return os.path.join(d, f"{_SEG_PREFIX}{no:06d}{_SEG_SUFFIX}")


def _segments(d: str) -> List[Tuple[int, str]]:
    """(segment number, path) pairs, oldest first."""
    out: List[Tuple[int, str]] = []
    try:
        with os.scandir(d) as it:
            for e in it:
                no = _seg_no(e.name)
                if no is not None:
                    out.append((no, e.path))
    except OSError:
        return []
    out.sort()
    return out


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except OSError:
        return True  # EPERM etc: exists, just not ours
    return True


def _scan_stale_markers(d: str) -> None:
    """Unclean-shutdown detection: a ``running-<pid>`` marker whose pid
    is dead means that process never reached its clean-exit hook. The
    finding is counted, flight-recorded as an anomaly, consumed
    (marker unlinked), and kept for :func:`postmortem`."""
    global _unclean, _unclean_total
    try:
        with os.scandir(d) as it:
            names = [e.name for e in it
                     if e.name.startswith(_MARKER_PREFIX)
                     and e.name.endswith(_MARKER_SUFFIX)]
    except OSError:
        return
    for name in names:
        try:
            pid = int(name[len(_MARKER_PREFIX):-len(_MARKER_SUFFIX)])
        except ValueError:
            continue
        if pid == os.getpid() or _pid_alive(pid):
            continue
        path = os.path.join(d, name)
        info: Dict[str, Any] = {"pid": pid}
        try:
            with open(path) as f:
                body = json.loads(f.read())
            if isinstance(body, dict):
                info.update(body)
        except (OSError, ValueError) as e:
            _log.debug("unclean marker %s unreadable: %s", path, e)
        info["detected_ts"] = time.time()
        try:
            os.unlink(path)
        except OSError as e:
            _log.debug("unclean marker %s unlink failed: %s", path, e)
        with _lock:
            _unclean_total += 1
            if (_unclean is None
                    or info.get("started_ts", 0)
                    >= _unclean.get("started_ts", 0)):
                _unclean = info
        counters.inc("history.unclean_shutdowns")
        from . import flight as _flight
        _flight.record("history.unclean_shutdown", pid=pid,
                       started_ts=info.get("started_ts"),
                       worker=info.get("worker"), dir=d)
        _log.warning("history: UNCLEAN shutdown detected — pid %d died "
                     "without its clean-exit hook (marker %s); "
                     "tft.postmortem() has the triage report", pid, name)


def _ensure_dir() -> Optional[str]:
    """Resolve + create the history dir; run the stale-marker scan and
    drop this process's running marker the first time a dir is seen."""
    d = active_dir()
    if d is None:
        return None
    try:
        os.makedirs(d, exist_ok=True)
    except OSError as e:
        _log.warning("history dir unavailable (%s): %s", d, e)
        return None
    with _lock:
        first = d not in _scanned
        if first:
            _scanned.add(d)
    if first:
        _scan_stale_markers(d)
        marker = os.path.join(
            d, f"{_MARKER_PREFIX}{os.getpid()}{_MARKER_SUFFIX}")
        try:
            from . import flight as _flight
            body = {"pid": os.getpid(), "started_ts": time.time(),
                    "worker": _flight.current_worker()}
            with open(marker, "w") as f:
                f.write(json.dumps(body))
            with _lock:
                _markers.add(marker)
        except OSError as e:
            _log.warning("history running marker failed (%s): %s",
                         marker, e)
    return d


@atexit.register
def _clean_exit() -> None:
    # the clean-shutdown half of the post-mortem contract: markers
    # that survive this hook belonged to a process that crashed
    with _lock:
        markers = list(_markers)
        _markers.clear()
    for m in markers:
        try:
            os.unlink(m)
        except OSError:
            pass


# ---------------------------------------------------------------------------
# writing
# ---------------------------------------------------------------------------

def _digest_decisions(decisions: Optional[List[Dict[str, Any]]]
                      ) -> Tuple[List[Dict[str, Any]], Dict[str, int],
                                 int]:
    """Bound the per-query flight digest: the newest
    ``TFT_HISTORY_DECISIONS`` full records plus a per-kind histogram of
    everything, with the dropped count — a whale that rode 500 spills
    archives the shape, not 500 lines."""
    if not decisions:
        return [], {}, 0
    kinds: Dict[str, int] = {}
    for r in decisions:
        k = str(r.get("kind", "?"))
        kinds[k] = kinds.get(k, 0) + 1
    keep = _decisions_keep()
    kept = decisions[-keep:] if keep else []
    return list(kept), kinds, len(decisions) - len(kept)


def _rotate_locked(d: str, seg: int) -> int:
    """Start the next segment; evict the oldest past the retention."""
    global _rotations, _evictions
    seg += 1
    _rotations += 1
    counters.inc("history.segments_rotated")
    segs = _segments(d)
    excess = len(segs) + 1 - _retention()  # +1: the new segment
    for no, path in segs[:max(excess, 0)]:
        try:
            os.unlink(path)
            _evictions += 1
            counters.inc("history.segment_evictions")
            _log.debug("history segment %06d evicted (retention %d)",
                       no, _retention())
        except OSError as e:
            _log.debug("history segment eviction failed (%s): %s",
                       path, e)
    return seg


def record_finish(query_id: Any, *,
                  tenant: Optional[str] = None,
                  fingerprint: Optional[str] = None,
                  outcome: str = "ok",
                  error: Optional[str] = None,
                  error_kind: Optional[str] = None,
                  worker: Optional[str] = None,
                  cost: Optional[Dict[str, Any]] = None,
                  queued_s: Optional[float] = None,
                  run_s: Optional[float] = None,
                  total_s: Optional[float] = None,
                  est_rows: Optional[int] = None,
                  est_bytes: Optional[int] = None,
                  preemptions: int = 0,
                  source: str = "serve",
                  summary: Optional[str] = None,
                  decisions: Optional[List[Dict[str, Any]]] = None
                  ) -> bool:
    """Fold one finished query into the durable archive. Best-effort by
    design: every failure is logged and counted, never raised — a full
    disk must degrade the archive, not fail the query that was
    finishing. Returns whether a record landed."""
    if not enabled():
        return False
    try:
        d = _ensure_dir()
        if d is None:
            return False
        decs, kinds, dropped = _digest_decisions(decisions)
        rec: Dict[str, Any] = {
            "v": 1, "ts": time.time(), "query": str(query_id),
            "outcome": str(outcome), "source": source,
        }
        if tenant is not None:
            rec["tenant"] = str(tenant)
        if fingerprint is not None:
            rec["fingerprint"] = str(fingerprint)
        if summary is not None:
            rec["summary"] = str(summary)
        if worker is not None:
            rec["worker"] = str(worker)
        if error is not None:
            rec["error"] = str(error)[:300]
        if error_kind is not None:
            rec["error_kind"] = str(error_kind)
        if cost:
            rec["cost"] = dict(cost)
        for k, v in (("queued_s", queued_s), ("run_s", run_s),
                     ("total_s", total_s)):
            if v is not None:
                rec[k] = round(float(v), 6)
        if est_rows is not None:
            rec["est_rows"] = int(est_rows)
        if est_bytes is not None:
            rec["est_bytes"] = int(est_bytes)
        if preemptions:
            rec["preemptions"] = int(preemptions)
        if decs:
            rec["decisions"] = decs
        if kinds:
            rec["decision_kinds"] = kinds
        if dropped:
            rec["decisions_dropped"] = dropped
        payload = json.dumps(rec, default=str).encode()
        framed = _frame(payload)
        global _active, _written
        with _lock:
            if _active is None or _active[0] != d:
                segs = _segments(d)
                if segs:
                    no, path = segs[-1]
                    try:
                        size = os.path.getsize(path)
                    except OSError:
                        size = 0
                    _active = (d, no, size)
                else:
                    _active = (d, 0, 0)
            _, seg, size = _active
            if size and size + len(framed) > _max_bytes():
                seg = _rotate_locked(d, seg)
                size = 0
            # one write() on an O_APPEND descriptor: a crash between
            # records leaves whole records; a crash INSIDE this append
            # leaves a torn tail the checksum walk turns cold
            fd = os.open(_seg_path(d, seg),
                         os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
            try:
                os.write(fd, framed)
            finally:
                os.close(fd)
            _active = (d, seg, size + len(framed))
            _written += 1
        counters.inc("history.records")
        return True
    except Exception as e:  # noqa: BLE001 - archive is best-effort
        global _write_errors
        with _lock:
            _write_errors += 1
        counters.inc("history.write_errors")
        _log.warning("history append for query %s failed: %s",
                     query_id, e)
        return False


# ---------------------------------------------------------------------------
# reading
# ---------------------------------------------------------------------------

def _cold_segment(path: str, why: str) -> None:
    """The cold-never-wrong path: a segment that fails verification is
    counted, flight-recorded, and unlinked — the archive returns fewer
    records, never wrong ones."""
    global _corrupt
    with _lock:
        _corrupt += 1
    counters.inc("history.segments_corrupt")
    from . import flight as _flight
    _flight.record("history.segment_corrupt",
                   segment=os.path.basename(path), why=why)
    _log.warning("history segment corrupt (%s): %s — segment goes "
                 "cold, earlier segments remain readable", path, why)
    try:
        os.unlink(path)
    except OSError:
        pass
    global _active
    with _lock:
        _active = None  # re-resolve: the active segment may be gone


def _read_segment(path: str) -> List[Dict[str, Any]]:
    """Walk one segment's framed records, verifying each digest. ANY
    framing/checksum/parse failure sends the whole segment cold."""
    from ..resilience import faults as _faults
    data: Optional[bytes] = None
    try:
        try:
            _faults.check("disk")
        except _faults.InjectedFault as e:
            if "corrupt" not in str(e):
                raise
            # corruption-shaped injection (the persist.py idiom): read
            # the real bytes, flip one payload bit — the segment still
            # "reads fine" and must be caught by the checksum
            with open(path, "rb") as f:
                buf = bytearray(f.read())
            if buf:
                buf[-1] ^= 0x01
            data = bytes(buf)
        if data is None:
            with open(path, "rb") as f:
                data = f.read()
    except FileNotFoundError:
        return []
    except Exception as e:
        _cold_segment(path, f"read failed: {e}")
        return []
    out: List[Dict[str, Any]] = []
    off = 0
    n = len(data)
    while off < n:
        head = data[off:off + _HEAD_LEN]
        if len(head) < _HEAD_LEN or not head.startswith(_MAGIC):
            _cold_segment(path, f"bad record header at byte {off}")
            return []
        (plen,) = struct.unpack(">I", head[len(_MAGIC):len(_MAGIC) + 4])
        digest = head[len(_MAGIC) + 4:]
        payload = data[off + _HEAD_LEN:off + _HEAD_LEN + plen]
        if len(payload) < plen:
            _cold_segment(path, f"truncated record at byte {off}")
            return []
        if hashlib.sha256(payload).digest() != digest:
            _cold_segment(path, f"sha256 mismatch at byte {off}")
            return []
        try:
            rec = json.loads(payload)
        except ValueError as e:
            _cold_segment(path, f"unparseable record at byte {off}: {e}")
            return []
        if isinstance(rec, dict):
            out.append(rec)
        off += _HEAD_LEN + plen
    return out


def _raw_records() -> List[Dict[str, Any]]:
    d = active_dir()
    if d is None:
        return []
    out: List[Dict[str, Any]] = []
    for _, path in _segments(d):
        out.extend(_read_segment(path))
    return out


def _stitch(records: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Merge per-attempt records of one query id into one story: the
    worker path in order, the migration count, the terminal attempt's
    outcome/cost/times winning (a ``migrated`` record is an interim
    stamp, never the ending)."""
    by_qid: Dict[str, List[Dict[str, Any]]] = {}
    order: List[str] = []
    for r in records:
        q = str(r.get("query", "?"))
        if q not in by_qid:
            order.append(q)
        by_qid.setdefault(q, []).append(r)
    out: List[Dict[str, Any]] = []
    for q in order:
        grp = sorted(by_qid[q], key=lambda r: r.get("ts", 0))
        terminal = grp[-1]
        for r in reversed(grp):
            if r.get("outcome") != "migrated":
                terminal = r
                break
        stitched = dict(terminal)
        workers: List[str] = []
        for r in grp:
            w = r.get("worker")
            if w is not None and w not in workers:
                workers.append(str(w))
        if workers:
            stitched["workers"] = workers
        migrations = sum(1 for r in grp
                         if r.get("outcome") == "migrated")
        if migrations:
            stitched["migrations"] = migrations
        if len(grp) > 1:
            stitched["attempts"] = len(grp) - migrations
            stitched["ts_first"] = grp[0].get("ts")
            kinds: Dict[str, int] = {}
            decs: List[Dict[str, Any]] = []
            for r in grp:
                for k, v in (r.get("decision_kinds") or {}).items():
                    kinds[k] = kinds.get(k, 0) + int(v)
                decs.extend(r.get("decisions") or [])
            if kinds:
                stitched["decision_kinds"] = kinds
            if decs:
                decs.sort(key=lambda r: (r.get("ts", 0),
                                         r.get("seq", 0)))
                stitched["decisions"] = decs
        out.append(stitched)
    out.sort(key=lambda r: r.get("ts", 0))
    return out


def _slow_threshold_s() -> float:
    raw = os.environ.get("TFT_SLOW_QUERY_MS")
    try:
        return float(raw) / 1000.0 if raw else 1.0
    except ValueError:
        return 1.0


def history(tenant: Optional[str] = None,
            fingerprint: Optional[str] = None,
            outcome: Optional[str] = None,
            since: Optional[float] = None,
            slow_only: bool = False,
            limit: Optional[int] = None) -> List[Dict[str, Any]]:
    """The durable query log, oldest first, stitched per query id (a
    query that migrated across fabric workers reads as one record with
    its worker path). Filters: ``tenant`` (exact), ``fingerprint``
    (prefix — fingerprints are long hashes), ``outcome`` (the terminal
    key: ``completed``/``failed``/``shed``/...), ``since`` (epoch
    seconds), ``slow_only`` (total wall past ``TFT_SLOW_QUERY_MS``,
    default 1s). ``limit`` keeps the newest N after filtering."""
    _ensure_dir()  # stale-marker scan even on a read-only consumer
    recs = _stitch(_raw_records())
    if tenant is not None:
        recs = [r for r in recs if r.get("tenant") == tenant]
    if fingerprint is not None:
        recs = [r for r in recs
                if str(r.get("fingerprint", "")).startswith(fingerprint)]
    if outcome is not None:
        recs = [r for r in recs if r.get("outcome") == outcome]
    if since is not None:
        recs = [r for r in recs if r.get("ts", 0) >= float(since)]
    if slow_only:
        bar = _slow_threshold_s()
        recs = [r for r in recs
                if (r.get("total_s") or r.get("run_s") or 0) >= bar]
    if limit is not None and len(recs) > limit:
        recs = recs[-limit:]
    return recs


def causal_chain(query_id: Any
                 ) -> Tuple[Optional[Dict[str, Any]],
                            List[Dict[str, Any]]]:
    """``tft.why()``'s durable fall-through: the stitched history
    record for ``query_id`` and its archived decision digest —
    ``(None, [])`` when the archive has never seen the query."""
    qid = str(query_id)
    for r in _stitch(_raw_records()):
        if r.get("query") == qid:
            return r, list(r.get("decisions") or [])
    return None, []


# ---------------------------------------------------------------------------
# post-mortem synthesis
# ---------------------------------------------------------------------------

def unclean_shutdown() -> Optional[Dict[str, Any]]:
    """The newest unclean shutdown detected this process (pid,
    started_ts, worker, detected_ts), or ``None``. Detection runs at
    the first history-dir touch; calling this forces it."""
    _ensure_dir()
    with _lock:
        return dict(_unclean) if _unclean is not None else None


def _dump_summary() -> List[str]:
    """Summarize the last ``TFT_FLIGHT_DUMP`` snapshot: header of the
    newest section plus an anomaly-kind histogram over its records."""
    path = os.environ.get("TFT_FLIGHT_DUMP")
    if not path or not os.path.exists(path):
        return ["  flight dump: none (TFT_FLIGHT_DUMP unset or empty)"]
    from . import flight as _flight
    from .decisions import ANOMALY_KINDS
    head: Optional[Dict[str, Any]] = None
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) \
                        and rec.get("type") == "flight_dump":
                    head = rec  # last header wins: the newest snapshot
    except OSError as e:
        return [f"  flight dump: {path} unreadable ({e})"]
    merged = _flight.load_dumps(path)
    kinds: Dict[str, int] = {}
    for r in merged:
        k = r.get("kind")
        if k in ANOMALY_KINDS:
            kinds[k] = kinds.get(k, 0) + 1
    lines = []
    if head is not None:
        age = time.time() - float(head.get("ts", time.time()))
        lines.append(
            f"  flight dump: {path} — last snapshot {age:.0f}s ago "
            f"({head.get('reason')}, {head.get('records')} record(s)"
            + (f", worker {head['worker']}" if head.get("worker")
               else "") + ")")
    else:
        lines.append(f"  flight dump: {path} — no parseable snapshot")
    if kinds:
        lines.append("  dump anomalies: " + ", ".join(
            f"{k} x{n}" for k, n in sorted(kinds.items())))
    return lines


def postmortem(tail: int = 10) -> str:
    """One crash triage report: the unclean-shutdown finding (or its
    absence), the durable history tail, the last flight dump's
    summary, and recent timeline rates — merged so the first command
    after a restart answers "what was the process doing when it
    died"."""
    info = unclean_shutdown()
    lines = ["tft.postmortem() · crash triage report"]
    if info is not None:
        started = info.get("started_ts")
        up = (f", up {info['detected_ts'] - started:.0f}s"
              if started else "")
        w = f" (worker {info['worker']})" if info.get("worker") else ""
        lines.append(
            f"  UNCLEAN SHUTDOWN: pid {info.get('pid')}{w} died without "
            f"reaching its clean-exit hook{up} — records below are what "
            f"the archive saved before the crash")
    else:
        lines.append(
            "  no unclean shutdown detected (previous run exited "
            "cleanly, or no history dir is configured)")
    recs = history(limit=tail)
    if recs:
        lines.append(f"  history tail (newest {len(recs)} of the "
                     f"durable archive):")
        for r in recs:
            parts = [f"{r.get('outcome')}"]
            if r.get("total_s") is not None:
                parts.append(f"{r['total_s']:.3f}s")
            if r.get("tenant"):
                parts.append(f"tenant {r['tenant']!r}")
            if r.get("workers"):
                parts.append("worker " + "->".join(r["workers"]))
            elif r.get("worker"):
                parts.append(f"worker {r['worker']}")
            if r.get("error_kind"):
                parts.append(f"[{r['error_kind']}]")
            lines.append(f"    {r.get('query'):<16} "
                         + " · ".join(parts))
    else:
        lines.append("  history tail: empty (no archived queries)")
    lines.extend(_dump_summary())
    try:
        from . import timeline as _timeline
        tl_lines = []
        for fam in ("serve", "stream.batches", "retry",
                    "history.records"):
            tl = _timeline.timeline(fam)
            if tl["samples"] >= 2 and tl["total_delta"]:
                tl_lines.append(
                    f"    {fam}: {tl['total_delta']:g} over "
                    f"{tl['samples']} sample(s) "
                    f"({tl['rate_per_s']:.3g}/s)")
        if tl_lines:
            lines.append("  timeline rates (in-memory, this process):")
            lines.extend(tl_lines)
    except Exception as e:  # noqa: BLE001 - triage must render
        _log.debug("postmortem timeline section failed: %s", e)
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# introspection / metrics
# ---------------------------------------------------------------------------

def stats() -> Dict[str, Any]:
    """Archive snapshot for ``tft.health()``."""
    d = active_dir()
    segs = _segments(d) if d else []
    size = 0
    for _, path in segs:
        try:
            size += os.path.getsize(path)
        except OSError:
            continue
    with _lock:
        return {
            "enabled": enabled() and d is not None,
            "dir": d,
            "segments": len(segs),
            "bytes": size,
            "records_written": _written,
            "rotations": _rotations,
            "evictions": _evictions,
            "corrupt_segments": _corrupt,
            "write_errors": _write_errors,
            "unclean_shutdowns": _unclean_total,
            "unclean": dict(_unclean) if _unclean is not None else None,
        }


def clear() -> None:
    """Forget process-local archive state (tests flip dirs): the
    active-segment cache, the per-dir marker scans, the unclean
    finding. On-disk segments are untouched."""
    global _active, _unclean
    with _lock:
        _active = None
        _unclean = None
        _scanned.clear()


def _render_metrics() -> List[str]:
    s = stats()
    return [
        "# HELP tft_history_records_total Query records appended to "
        "the durable history archive (this process).",
        "# TYPE tft_history_records_total counter",
        f"tft_history_records_total {s['records_written']}",
        "# HELP tft_history_segments On-disk history segments.",
        "# TYPE tft_history_segments gauge",
        f"tft_history_segments {s['segments']}",
        "# HELP tft_history_bytes Bytes across on-disk history "
        "segments.",
        "# TYPE tft_history_bytes gauge",
        f"tft_history_bytes {s['bytes']}",
        "# HELP tft_history_rotations_total Segment rotations at "
        "TFT_HISTORY_MAX_BYTES.",
        "# TYPE tft_history_rotations_total counter",
        f"tft_history_rotations_total {s['rotations']}",
        "# HELP tft_history_evictions_total Segments evicted past "
        "TFT_HISTORY_RETENTION.",
        "# TYPE tft_history_evictions_total counter",
        f"tft_history_evictions_total {s['evictions']}",
        "# HELP tft_history_corrupt_total Segments sent cold by the "
        "checksum walk (bit rot / truncation; never wrong records).",
        "# TYPE tft_history_corrupt_total counter",
        f"tft_history_corrupt_total {s['corrupt_segments']}",
        "# HELP tft_history_unclean_shutdowns_total Stale running "
        "markers of dead pids found at startup.",
        "# TYPE tft_history_unclean_shutdowns_total counter",
        f"tft_history_unclean_shutdowns_total {s['unclean_shutdowns']}",
    ]


def _register_metrics() -> None:
    # deferred: metrics imports events which imports flight
    from .metrics import register_metrics_provider
    register_metrics_provider("history", _render_metrics)
