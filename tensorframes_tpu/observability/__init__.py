"""Query-scoped observability: correlated events, timelines, metrics.

The reference's only observability was ``logDebug`` narration and
self-timed perf suites (SURVEY.md §5); the port's first pass was a flat,
process-global ``span``/``counters`` registry (:mod:`..utils.tracing`).
Once the engine pipelines blocks and queries overlap, flat registries
stop answering the questions that matter — *which query's* retry tripped
the OOM split, where did block 17 of *this* query spend its time. This
package adds the query dimension on top of the existing primitives
(every ``span``/``counters`` call site keeps working unchanged):

- :mod:`.events` — :class:`QueryTrace` + contextvar correlation: every
  public API forcing gets a unique query id; engine, pipeline,
  resilience, and native-PJRT layers attach typed events (block
  submit/compute/drain, retries with their classified error, OOM splits,
  pad/sync fallbacks, compile-cache hits/misses, occupancy samples).
  Finished traces land in a bounded ring buffer and an optional JSONL
  sink (``TFT_TRACE_FILE``); :meth:`QueryTrace.to_chrome_trace` exports
  a Perfetto/chrome://tracing timeline with one track per pipeline slot.
- :mod:`.device` — HBM watermark sampling (``Device.memory_stats()``
  where the backend supports it, graceful ``None`` fallback on CPU) at
  query start/end and around block drains; OOM splits carry the
  observed watermark.
- :mod:`.metrics` — Prometheus text-format export
  (:func:`metrics_text`), including proper histogram families
  (``tft_query_latency_seconds``, ``tft_compile_seconds``), and an
  opt-in loopback HTTP endpoint (:func:`serve_metrics`,
  ``TFT_METRICS_PORT``; binds 127.0.0.1 only).
- :mod:`.report` — ``frame.explain()`` / :func:`last_query_report`:
  the human-readable per-stage breakdown, plus a mesh section
  (per-device rows/bytes/time, straggler ratio, imbalance warning)
  for queries that touched the distributed layer.
- :mod:`.flight` — the ALWAYS-ON flight recorder: a bounded ring of
  decision-level records (admission verdicts, re-plans, shrinks,
  spills — each with the inputs it was decided from), correlated by
  query id with ``TFT_TRACE`` off; JSONL auto-dumps on slow query /
  giveup / device loss / exit (``TFT_FLIGHT_DUMP``).
- :mod:`.decisions` — ``tft.why(query_id)`` (one query's causal chain
  from the ring, the on-disk flight dumps, or the durable history)
  and ``tft.doctor()`` (process triage).
- :mod:`.history` — the ALWAYS-ON durable query log: every finished
  query folds into checksummed append-only segments on disk
  (``TFT_HISTORY_DIR``; free under a fabric's durable tier), queried
  by ``tft.history()`` across restarts; unclean shutdowns are
  detected at startup and ``tft.postmortem()`` merges the last
  flight dump, the history tail, and timeline rates into one triage
  report (``TFT_HISTORY=0`` bypasses the whole layer).
- :mod:`.slo` — per-tenant latency objectives + error-budget burn
  rates from the existing serve latency histograms
  (``tft_serve_slo_*``, ``serve_report()`` lines, burn callbacks).
- :mod:`.health` — ``tft.health()``: one machine-readable snapshot
  across ledger, mesh, serve, caches, streams, SLOs.
- :mod:`.timeline` — the ALWAYS-ON telemetry timeline: a bounded ring
  of periodic counter/gauge/histogram snapshots (``tft.timeline()``
  answers "what changed in the last N minutes" without an external
  Prometheus; ``TFT_TIMELINE=0`` bypasses the whole sentinel).
- :mod:`.baseline` — per-query cost attribution keyed by plan
  fingerprint, rolling EWMA+MAD baselines (persisted via the durable
  tier), and the ``perf.regression`` detector
  (``TFT_REGRESSION_SIGMA``; ``tft.regressions()``).

Everything is zero-cost-when-off: with tracing disabled
(``TFT_TRACE`` unset), :func:`query_trace` yields ``None`` and every
hook is a single ``None`` check. See ``docs/observability.md``.
"""

from __future__ import annotations

import os

from ..utils import tracing as _tracing
from ..utils.logging import get_logger
from .events import (DEVICE_TRACK_BASE, Event, QueryTrace, add_event,
                     block_meta, bypass, clear_ring, current_trace,
                     last_query, query_trace, recent_events, traced_query,
                     wrap_context)
from . import device
from . import flight
from . import slo
from . import timeline
from . import baseline
from . import history
from .baseline import perf_stats, regressions
from .decisions import doctor, why
from .health import health
from .metrics import metrics_port, metrics_text, serve_metrics, stop_metrics
from .report import frame_report, last_query_report, render
from .slo import SLO, on_burn, set_slo, slo_status

__all__ = [
    "Event", "QueryTrace", "query_trace", "current_trace", "add_event",
    "wrap_context", "traced_query", "last_query", "recent_events",
    "clear_ring", "block_meta", "bypass", "DEVICE_TRACK_BASE", "device",
    "metrics_text", "serve_metrics", "stop_metrics", "metrics_port",
    "frame_report", "last_query_report", "render",
    "flight", "slo", "why", "doctor", "health",
    "SLO", "set_slo", "slo_status", "on_burn",
    "timeline", "baseline", "regressions", "perf_stats",
    "history",
]

_log = get_logger("observability")

# credit every span to the active query's stage breakdown as well as to
# the flat registry (one slot; this package owns it)
from .events import _on_span as _span_observer  # noqa: E402

_tracing.set_span_observer(_span_observer)

# the flight recorder's, SLO layer's, and performance sentinel's
# metrics families register once the provider registry exists
# (deferred: flight/slo are imported by metrics' own import chain)
flight._register_metrics()
slo._register_metrics()
timeline._register_metrics()
baseline._register_metrics()
history._register_metrics()


def _maybe_autostart() -> None:
    """Opt-in metrics endpoint: ``TFT_METRICS_PORT=<port>`` starts the
    loopback server at import (``0`` picks a free port)."""
    raw = os.environ.get("TFT_METRICS_PORT")
    if not raw:
        return
    try:
        port = int(raw)
    except ValueError:
        _log.warning("ignoring malformed TFT_METRICS_PORT=%r", raw)
        return
    try:
        serve_metrics(port)
    except (OSError, OverflowError, ValueError) as e:
        # OverflowError: the socket layer's out-of-range-port error —
        # a bad env value must warn, never break `import tensorframes_tpu`
        _log.warning("metrics endpoint failed to start on port %s: %s",
                     raw, e)


_maybe_autostart()
