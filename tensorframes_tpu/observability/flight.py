"""Always-on flight recorder: a bounded ring of runtime *decisions*.

The engine makes autonomous calls on every query — admission sheds or
parks a whale, the adaptive layer re-orders filters and re-plans
mid-run, the result cache admits and evicts, elastic meshes shrink and
grow, the ledger spills and overflow-admits — and until now each call
was visible only as a bare counter, with causal detail existing only
when ``TFT_TRACE`` was set *before* the query ran. A production server
needs the post-mortem answer to "why was this query slow / shed /
re-planned / run on 3 devices" *after the fact*, without reproducing.

This module is that black box:

- :func:`record` — the one hook every subsystem calls at a DECISION
  (never per-block): appends one structured dict (seq, wall-clock ts,
  kind, correlated query id, and the decision's *inputs* — estimate vs
  observation, threshold, knob value, chosen alternative) to a bounded
  lock-cheap ring (``TFT_FLIGHT_RING``, default 4096; overflow drops
  oldest).
- :func:`scope` — an always-on contextvar carrying the query id, so
  decisions made deep inside a forcing (a mesh shrink, a mid-plan
  re-plan) correlate to the serving query that rode them — with
  ``TFT_TRACE`` off. The serve scheduler scopes every execution; the
  contextvar survives the pipeline's worker threads through the same
  ``wrap_context`` copy the trace id uses.
- :func:`dump` / :func:`maybe_dump` — JSONL snapshots of the ring,
  auto-triggered on slow queries, classified giveups, and device losses
  and at process exit when ``TFT_FLIGHT_DUMP=<path>`` is set; writes
  share the trace-file sink's size-capped keep-1 rotation
  (``TFT_TRACE_FILE_MAX_BYTES``, :func:`append_jsonl`).

``tft.why(query_id)`` (:mod:`.decisions`) reconstructs a query's causal
chain from this ring; ``tft.health()`` (:mod:`.health`) reports its
liveness. ``TFT_FLIGHT=0`` bypasses the recorder bit-identically —
every hook returns at one env check, nothing is recorded or dumped.
The recorder is bench-enforced ≤2% on the serve mixed workload
(``bench.py flight_recorder_overhead``) — which it meets by recording
decisions, not blocks: the hot per-block paths never touch this module.
"""

from __future__ import annotations

import atexit
import contextlib
import contextvars
import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, Iterator, List, Optional

from ..utils.logging import get_logger

__all__ = ["enabled", "record", "scope", "current_query", "recent",
           "for_query", "dump", "maybe_dump", "clear", "append_jsonl",
           "stats", "set_worker_id", "current_worker", "load_dumps"]

_log = get_logger("observability.flight")


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return int(raw)
    except ValueError:
        _log.warning("ignoring malformed %s=%r", name, raw)
        return default


def enabled() -> bool:
    """``TFT_FLIGHT`` gate (default ON — the recorder exists for the
    queries nobody knew to trace). ``TFT_FLIGHT=0`` bypasses every hook
    at this one check, bit-identically."""
    return os.environ.get("TFT_FLIGHT", "") not in ("0", "false")


_seq = itertools.count(1)
_ring_lock = threading.Lock()
_ring: "deque[Dict[str, Any]]" = deque(
    maxlen=_env_int("TFT_FLIGHT_RING", 4096))
_recorded = 0  # lifetime total (the ring drops, this does not)
_dumps = 0
_dump_evictions = 0  # snapshot sections pruned past TFT_FLIGHT_DUMP_KEEP

# the always-on query correlation id (serve query ids, or whatever the
# caller scopes); independent of the TFT_TRACE query trace so decisions
# correlate even for queries that were never traced
_query: "contextvars.ContextVar[Optional[str]]" = \
    contextvars.ContextVar("tft_flight_query", default=None)


def current_query() -> Optional[str]:
    """The ambient flight-correlation query id, or None."""
    return _query.get()


# the worker identity dimension (serving fabric, docs/serving.md):
# a process-level default (set_worker_id — one worker id per process in
# a real multi-process fleet) plus a contextvar override for the
# in-process fabric, where several simulated workers share one ring and
# each scheduler execution must tag records with ITS worker, not a
# process global.
_worker_default: Optional[str] = None
_worker: "contextvars.ContextVar[Optional[str]]" = \
    contextvars.ContextVar("tft_flight_worker", default=None)


def set_worker_id(worker_id: Optional[str]) -> Optional[str]:
    """Set the process-default worker id stamped on every record (and
    on dump headers). Returns the previous value."""
    global _worker_default
    prev = _worker_default
    _worker_default = str(worker_id) if worker_id is not None else None
    return prev


def current_worker() -> Optional[str]:
    """The ambient worker id: the scope override when inside one, else
    the process default."""
    w = _worker.get()
    return w if w is not None else _worker_default


@contextlib.contextmanager
def scope(query_id: str,
          worker: Optional[str] = None) -> Iterator[None]:
    """Correlate every decision recorded inside the body to
    ``query_id`` (nested scopes shadow; the serve scheduler scopes each
    query's execution with its serving id). ``worker`` additionally
    tags records with the executing worker's id (the fabric sets each
    scheduler's ``worker_id``; ``None`` leaves the ambient worker)."""
    token = _query.set(str(query_id))
    wtoken = _worker.set(str(worker)) if worker is not None else None
    try:
        yield
    finally:
        if wtoken is not None:
            _worker.reset(wtoken)
        _query.reset(token)


def record(kind: str, query: Optional[str] = None, **inputs) -> None:
    """Record one decision. ``kind`` names it (``serve.shed``,
    ``plan.replan``, ``mesh.shrink``, ...); ``inputs`` carry what the
    decision SAW — the estimate and the observation, the threshold it
    compared against, the knob value, the alternative chosen — so the
    audit trail can reconstruct *why*, not just *that*. ``query``
    defaults to the ambient :func:`scope` id. Call this at decisions
    only, never from per-block hot paths."""
    if not enabled():
        return
    rec: Dict[str, Any] = {"ts": time.time(), "kind": kind}
    q = query if query is not None else _query.get()
    if q is not None:
        rec["query"] = q
    w = current_worker()
    if w is not None and "worker" not in inputs:
        rec["worker"] = w
    if inputs:
        rec.update(inputs)
    global _recorded
    # seq drawn under the ring lock so ring/dump order and seq order
    # always agree (a post-mortem consumer sorts dump lines by seq)
    with _ring_lock:
        rec["seq"] = next(_seq)
        _ring.append(rec)
        _recorded += 1


def recent(kind: Optional[str] = None,
           limit: Optional[int] = None) -> List[Dict[str, Any]]:
    """Ring snapshot, oldest first; ``kind`` filters (prefix match with
    a trailing ``.`` treated as a namespace, e.g. ``"mesh"``),
    ``limit`` keeps the newest N after filtering."""
    with _ring_lock:
        out = list(_ring)
    if kind is not None:
        out = [r for r in out
               if r["kind"] == kind or r["kind"].startswith(kind + ".")]
    if limit is not None and len(out) > limit:
        out = out[-limit:]
    return out


def for_query(query_id: str) -> List[Dict[str, Any]]:
    """Every recorded decision correlated to ``query_id``, oldest
    first (the ``tft.why()`` source)."""
    qid = str(query_id)
    with _ring_lock:
        return [r for r in _ring if r.get("query") == qid]


def stats() -> Dict[str, Any]:
    with _ring_lock:
        return {"enabled": enabled(), "records": len(_ring),
                "capacity": _ring.maxlen, "recorded_total": _recorded,
                "dumps": _dumps, "dump_evictions": _dump_evictions}


def clear() -> None:
    """Drop the ring and re-read ``TFT_FLIGHT_RING`` (tests flip it)."""
    global _ring
    with _ring_lock:
        _ring = deque(maxlen=_env_int("TFT_FLIGHT_RING", 4096))


# ---------------------------------------------------------------------------
# JSONL sink with size-capped keep-1 rotation
# ---------------------------------------------------------------------------

_file_lock = threading.Lock()


def _max_sink_bytes() -> int:
    """``TFT_TRACE_FILE_MAX_BYTES``: the shared JSONL-sink size cap (0 /
    unset = unbounded). One knob for the trace file AND flight dumps —
    a long-running serve process must not grow either without bound."""
    return max(_env_int("TFT_TRACE_FILE_MAX_BYTES", 0), 0)


def append_jsonl(path: str, lines: List[str]) -> None:
    """Append pre-serialized JSONL ``lines`` to ``path`` under the
    shared sink lock, rotating first when the write would push the file
    past ``TFT_TRACE_FILE_MAX_BYTES``: the current file moves to
    ``<path>.1`` (keep-1 rollover, replacing any previous ``.1``) and a
    fresh file starts. A single write larger than the cap still lands
    (capping it would truncate mid-record); it rotates out on the next
    write. Raises ``OSError`` like a plain append — callers keep their
    own degrade-to-log handling."""
    text = "\n".join(lines) + "\n"
    cap = _max_sink_bytes()
    with _file_lock:
        if cap:
            try:
                size = os.path.getsize(path)
            except OSError:
                size = 0
            if size and size + len(text.encode()) > cap:
                os.replace(path, path + ".1")
        with open(path, "a") as f:
            f.write(text)


# ---------------------------------------------------------------------------
# dumps
# ---------------------------------------------------------------------------

def _dump_keep() -> int:
    """``TFT_FLIGHT_DUMP_KEEP``: newest snapshot sections kept in the
    dump file (default 8; ``0`` disables pruning). Each anomaly appends
    one section, across restarts — without a bound the dump file is
    the one observability artifact that grows forever."""
    return max(_env_int("TFT_FLIGHT_DUMP_KEEP", 8), 0)


def _prune_dump_snapshots(path: str) -> int:
    """Drop the oldest snapshot sections past :func:`_dump_keep`,
    rewriting the file atomically under the shared sink lock; returns
    the number of sections evicted (counted in :func:`stats` and the
    ``tft_flight_dump_evictions_total`` metric)."""
    keep = _dump_keep()
    if not keep:
        return 0
    global _dump_evictions
    with _file_lock:
        try:
            with open(path) as f:
                lines = f.read().splitlines()
        except OSError:
            return 0
        heads = []
        for i, line in enumerate(lines):
            s = line.strip()
            if '"flight_dump"' not in s:
                continue
            try:
                rec = json.loads(s)
            except ValueError:
                continue
            if isinstance(rec, dict) \
                    and rec.get("type") == "flight_dump":
                heads.append(i)
        excess = len(heads) - keep
        if excess <= 0:
            return 0
        tmp = path + ".prune"
        try:
            with open(tmp, "w") as f:
                f.write("\n".join(lines[heads[excess]:]) + "\n")
            os.replace(tmp, path)
        except OSError as e:
            _log.warning("flight dump prune of %s failed: %s", path, e)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return 0
    with _ring_lock:
        _dump_evictions += excess
    _log.info("flight dump %s: %d old snapshot section(s) evicted "
              "(TFT_FLIGHT_DUMP_KEEP=%d)", path, excess, keep)
    return excess


def dump(path: Optional[str] = None,
         reason: str = "manual",
         worker: Optional[str] = None) -> Optional[str]:
    """Write the ring as one JSONL snapshot — a ``flight_dump`` header
    line (reason, timestamp, record count, and the dumping ``worker``
    when one is known) followed by one line per decision — to ``path``
    (default ``TFT_FLIGHT_DUMP``). Returns the path written, or None
    (no path configured / recorder bypassed). A failed write degrades
    to a warning log, never raises into the query that triggered it."""
    if not enabled():
        return None
    path = path or os.environ.get("TFT_FLIGHT_DUMP")
    if not path:
        return None
    with _ring_lock:
        records = list(_ring)
    head = {"type": "flight_dump", "reason": reason, "ts": time.time(),
            "records": len(records)}
    w = worker if worker is not None else current_worker()
    if w is not None:
        head["worker"] = w
    lines = [json.dumps(head, default=str)]
    lines.extend(json.dumps(r, default=str) for r in records)
    try:
        append_jsonl(path, lines)
    except OSError as e:
        _log.warning("TFT_FLIGHT_DUMP=%s write failed: %s", path, e)
        return None
    global _dumps
    with _ring_lock:
        _dumps += 1
    _prune_dump_snapshots(path)
    _log.info("flight recorder dumped %d decision(s) to %s (%s)",
              len(records), path, reason)
    return path


def maybe_dump(reason: str) -> Optional[str]:
    """Auto-dump hook for the trigger sites (slow query, classified
    giveup, device loss, process exit): dumps only when
    ``TFT_FLIGHT_DUMP`` is set, so the triggers cost one env read when
    it is not."""
    if not os.environ.get("TFT_FLIGHT_DUMP"):
        return None
    return dump(reason=reason)


def load_dumps(paths) -> List[Dict[str, Any]]:
    """Merge per-worker JSONL flight dumps back into one decision
    stream (``tft.doctor(flight_dumps=[...])``). Each file is the
    :func:`dump` format: ``flight_dump`` header lines carry the
    dumping worker's id, which is attributed to every following record
    that lacks its own ``worker`` field. Records merge across files
    sorted by wall-clock ``ts`` then ``seq`` — per-process seqs are
    independent, but ts orders the fleet's decisions well enough for a
    post-mortem. Unreadable files and malformed lines are skipped with
    a warning (a post-mortem tool must salvage what it can)."""
    if isinstance(paths, str):
        paths = [paths]
    merged: List[Dict[str, Any]] = []
    for path in paths:
        try:
            with open(path) as f:
                text = f.read()
        except OSError as e:
            _log.warning("flight dump %s unreadable: %s", path, e)
            continue
        header_worker: Optional[str] = None
        for ln, line in enumerate(text.splitlines(), 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                _log.warning("flight dump %s:%d: malformed line "
                             "skipped", path, ln)
                continue
            if not isinstance(rec, dict):
                continue
            if rec.get("type") == "flight_dump":
                header_worker = rec.get("worker")
                continue
            if "worker" not in rec and header_worker is not None:
                rec["worker"] = header_worker
            merged.append(rec)
    merged.sort(key=lambda r: (r.get("ts", 0), r.get("seq", 0)))
    return merged


@atexit.register
def _dump_at_exit() -> None:
    # the crash-adjacent case the recorder exists for: whatever was in
    # the ring when the process died is the last evidence
    try:
        if _ring:
            maybe_dump("exit")
    except Exception as e:  # noqa: BLE001 - interpreter is shutting down
        _log.debug("exit flight dump failed: %s", e)


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------

def _render_metrics() -> List[str]:
    s = stats()
    return [
        "# HELP tft_flight_records_total Decisions recorded by the "
        "flight recorder (lifetime; the ring holds the newest).",
        "# TYPE tft_flight_records_total counter",
        f"tft_flight_records_total {s['recorded_total']}",
        "# HELP tft_flight_ring_records Decisions currently held in "
        "the bounded flight ring.",
        "# TYPE tft_flight_ring_records gauge",
        f"tft_flight_ring_records {s['records']}",
        "# HELP tft_flight_dumps_total JSONL flight snapshots written "
        "(slow query / giveup / device loss / exit / manual).",
        "# TYPE tft_flight_dumps_total counter",
        f"tft_flight_dumps_total {s['dumps']}",
        "# HELP tft_flight_dump_evictions_total Old dump snapshot "
        "sections pruned past TFT_FLIGHT_DUMP_KEEP.",
        "# TYPE tft_flight_dump_evictions_total counter",
        f"tft_flight_dump_evictions_total {s['dump_evictions']}",
    ]


def _register_metrics() -> None:
    # deferred: metrics imports events which imports this module
    from .metrics import register_metrics_provider
    register_metrics_provider("flight", _render_metrics)
