"""Per-tenant SLOs: latency objectives, error budgets, burn rates.

The serving layer already measures everything an SLO needs — the
scheduler observes every completion into the always-on
``query_latency_seconds{op="serve",tenant=...,outcome=...}`` histogram
(:mod:`..serve.stats`) — but nobody turned the measurements into the
question an operator actually asks: *are we inside the promise, and how
fast are we spending the budget?* This module is that arithmetic layer.
It adds **zero** runtime accounting of its own: status is computed at
read time from the histograms the scheduler feeds anyway, which is what
keeps the always-on claim honest.

- :class:`SLO` — a latency objective (``objective_ms``) + a success
  target (``target``, e.g. 0.999 = "99.9% of queries finish under the
  objective, successfully"). Configure per tenant with
  :func:`set_slo`; unconfigured tenants fall back to the process
  default (``TFT_SLO_DEFAULT_MS``, 1000 ms / ``TFT_SLO_TARGET``,
  0.999), so the layer is zero-config.
- :func:`slo_status` — per-tenant compliance from the histogram
  buckets: ``good`` = successful queries at or under the objective
  (the objective rounds DOWN to the nearest histogram bucket edge — a
  conservative, exactly-reproducible rule; pick objectives on bucket
  edges for exact accounting), ``bad`` = everything else including
  failed/shed outcomes. ``burn_rate`` = (bad fraction) / (1 − target):
  1.0 burns the error budget exactly at the allowed rate; 2.0 exhausts
  it in half the window.
- :func:`on_burn` — an optional alerting hook: callbacks fire
  (edge-triggered, re-armed when the burn drops back under the
  threshold) from the scheduler's completion path, throttled to one
  evaluation per tenant per second so the check costs two clock reads
  on the completion path.

Surfaces: ``serve_report()`` renders an SLO line per tenant;
``tft_serve_slo_*`` metrics families render on every scrape;
``tft.health()`` embeds :func:`slo_status`. See
``docs/observability.md``.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional

from ..utils import tracing
from ..utils.logging import get_logger

__all__ = ["SLO", "set_slo", "clear_slos", "slo_for", "slo_status",
           "on_burn", "remove_burn_callback", "note_completion"]

_log = get_logger("observability.slo")

DEFAULT_OBJECTIVE_MS = 1000.0
DEFAULT_TARGET = 0.999


def _env_float(name: str, default: float) -> float:
    import os
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return float(raw)
    except ValueError:
        _log.warning("ignoring malformed %s=%r", name, raw)
        return default


@dataclasses.dataclass(frozen=True)
class SLO:
    """One tenant's promise: ``target`` of queries complete successfully
    within ``objective_ms``."""

    objective_ms: float
    target: float = DEFAULT_TARGET

    def __post_init__(self):
        if self.objective_ms <= 0:
            raise ValueError(
                f"objective_ms must be > 0, got {self.objective_ms}")
        if not 0.0 < self.target < 1.0:
            raise ValueError(
                f"target must be in (0, 1), got {self.target}")


_lock = threading.Lock()
_slos: Dict[str, SLO] = {}
# burn callbacks: name -> (fn(tenant, status_dict), threshold, fired set)
_callbacks: Dict[str, tuple] = {}
_fired: Dict[str, set] = {}
# completion-path throttle: tenant -> last evaluation monotonic time
_last_eval: Dict[str, float] = {}


def default_slo() -> SLO:
    """The zero-config fallback every unconfigured tenant gets."""
    return SLO(objective_ms=_env_float("TFT_SLO_DEFAULT_MS",
                                       DEFAULT_OBJECTIVE_MS),
               target=min(max(_env_float("TFT_SLO_TARGET",
                                         DEFAULT_TARGET), 1e-6),
                          1.0 - 1e-9))


def set_slo(tenant: str, objective_ms: float,
            target: float = DEFAULT_TARGET) -> SLO:
    """Pin ``tenant``'s latency objective and success target."""
    slo = SLO(objective_ms=float(objective_ms), target=float(target))
    with _lock:
        _slos[tenant] = slo
    return slo


def clear_slos() -> None:
    with _lock:
        _slos.clear()
        _fired.clear()
        _last_eval.clear()


def slo_for(tenant: str) -> SLO:
    with _lock:
        slo = _slos.get(tenant)
    return slo if slo is not None else default_slo()


def configured_tenants() -> List[str]:
    with _lock:
        return sorted(_slos)


# ---------------------------------------------------------------------------
# status arithmetic (read-time, from the serve latency histograms)
# ---------------------------------------------------------------------------

def _serve_series(tenant: Optional[str] = None) -> Dict[str, list]:
    """tenant -> [(outcome, hist_snapshot)] for op="serve" series."""
    out: Dict[str, list] = {}
    for (family, labels), h in tracing.histograms.snapshot().items():
        if family != "query_latency_seconds":
            continue
        lab = dict(labels)
        if lab.get("op") != "serve" or "tenant" not in lab:
            continue
        if tenant is not None and lab["tenant"] != tenant:
            continue
        out.setdefault(lab["tenant"], []).append(
            (lab.get("outcome", "ok"), h))
    return out


def _good_count(h, objective_s: float) -> int:
    """Observations at or under the largest bucket edge <= objective —
    the conservative, bucket-exact 'good' rule (module docstring)."""
    good = 0
    for le, c in zip(h["les"], h["counts"]):
        if le <= objective_s:
            good += c
        else:
            break
    return good


def _status_for(tenant: str, series: list) -> Dict[str, object]:
    slo = slo_for(tenant)
    objective_s = slo.objective_ms / 1000.0
    total = good = 0
    for outcome, h in series:
        total += h["count"]
        if outcome == "ok":
            good += _good_count(h, objective_s)
    bad = total - good
    compliance = good / total if total else None
    budget = 1.0 - slo.target
    burn = ((bad / total) / budget) if total else None
    return {
        "tenant": tenant,
        "objective_ms": slo.objective_ms,
        "target": slo.target,
        "total": total,
        "good": good,
        "bad": bad,
        "compliance": compliance,
        "error_budget": budget,
        # fraction of the budget left, cumulative over the histogram's
        # lifetime (negative = blown); None before any observation
        "budget_remaining": (1.0 - (bad / total) / budget) if total
        else None,
        "burn_rate": burn,
    }


def slo_status(tenant: Optional[str] = None) -> Dict[str, Dict]:
    """Per-tenant SLO status (module docstring for the field rules).
    Tenants appear once they have at least one completed serve query or
    an explicit :func:`set_slo`; cumulative over the process-global
    histogram registry, like every other ``tft_*`` series."""
    series = _serve_series(tenant)
    names = set(series)
    with _lock:
        cfg = set(_slos)
    if tenant is None:
        names |= cfg
    elif tenant in cfg:
        names.add(tenant)
    return {t: _status_for(t, series.get(t, [])) for t in sorted(names)}


# ---------------------------------------------------------------------------
# burn-rate alerting hook
# ---------------------------------------------------------------------------

def on_burn(fn: Callable[[str, Dict], None], threshold: float = 1.0,
            name: Optional[str] = None) -> str:
    """Register ``fn(tenant, status)`` to fire when a tenant's burn
    rate crosses ``threshold`` (edge-triggered; re-arms when it drops
    back under). Returns the registration name for
    :func:`remove_burn_callback`. Callbacks run on the scheduler's
    completion path — keep them cheap or hand off to a thread."""
    key = name or f"burn@{id(fn):x}"
    with _lock:
        _callbacks[key] = (fn, float(threshold))
        _fired[key] = set()
    return key


def remove_burn_callback(name: str) -> None:
    with _lock:
        _callbacks.pop(name, None)
        _fired.pop(name, None)


def note_completion(tenant: str) -> None:
    """Completion-path hook (called by the scheduler after it observes
    the latency): evaluates burn callbacks for ``tenant``, at most once
    per tenant per second. No callbacks registered = one lock + one
    dict probe."""
    with _lock:
        if not _callbacks:
            return
        now = time.monotonic()
        if now - _last_eval.get(tenant, 0.0) < 1.0:
            return
        _last_eval[tenant] = now
        cbs = list(_callbacks.items())
    status = slo_status(tenant).get(tenant)
    if status is None or status["burn_rate"] is None:
        return
    burn = status["burn_rate"]
    for key, (fn, threshold) in cbs:
        with _lock:
            fired = _fired.setdefault(key, set())
            if burn >= threshold and tenant not in fired:
                fired.add(tenant)
                should = True
            else:
                if burn < threshold:
                    fired.discard(tenant)
                should = False
        if should:
            try:
                fn(tenant, status)
            except Exception as e:  # noqa: BLE001 - alerting is advisory
                _log.error("burn callback %s failed for tenant %r: %s",
                           key, tenant, e)


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------

def _render_metrics() -> List[str]:
    status = slo_status()
    if not status:
        return []
    from .metrics import _escape_label as _esc
    fams = {
        "objective_ms": ("gauge", "Latency objective per tenant "
                                  "(configured or TFT_SLO_DEFAULT_MS)."),
        "target": ("gauge", "Success-fraction target per tenant."),
        # gauges, not counters: classification is recomputed at read
        # time against the CURRENT objective, so set_slo() mid-run can
        # legitimately move these in either direction
        "good_queries": ("gauge", "Queries at/under the current "
                                  "objective (bucket-edge rule)."),
        "bad_queries": ("gauge", "Queries over the current objective "
                                 "or failed/shed."),
        "burn_rate": ("gauge", "Error-budget burn rate (1.0 = spending "
                               "exactly the allowed rate)."),
        "budget_remaining": ("gauge", "Fraction of the error budget "
                                      "left (negative = blown)."),
    }
    key_of = {"good_queries": "good", "bad_queries": "bad"}
    lines: List[str] = []
    for suffix, (mtype, help_s) in fams.items():
        fam = f"tft_serve_slo_{suffix}"
        lines.append(f"# HELP {fam} {help_s}")
        lines.append(f"# TYPE {fam} {mtype}")
        for tenant, s in status.items():
            v = s[key_of.get(suffix, suffix)]
            if v is None:
                continue
            lines.append(f'{fam}{{tenant="{_esc(tenant)}"}} '
                         f'{v:.6g}' if isinstance(v, float)
                         else f'{fam}{{tenant="{_esc(tenant)}"}} {v}')
    return lines


def _register_metrics() -> None:
    from .metrics import register_metrics_provider
    register_metrics_provider("serve.slo", _render_metrics)
