"""Decision audit trail: ``tft.why(query_id)`` and ``tft.doctor()``.

The flight recorder (:mod:`.flight`) captures every runtime decision
with the inputs it was made from; this module turns the raw ring into
answers. :func:`why` reconstructs one query's causal chain — admission
verdict, preemptions, mid-plan re-plans, mesh shrinks it rode, spills
it forced, its terminal outcome — each line showing the *inputs* (the
estimate and the observation, the threshold, the knob) so "why was
this query shed" reads off directly, with ``TFT_TRACE`` off and the
query long gone. :func:`doctor` is the process-wide triage report: the
:func:`~.health.health` snapshot's warnings plus the recent anomalous
decisions (sheds, giveups, fallbacks, overflow admissions, shrinks)
grouped by kind.

Which tool when (``docs/observability.md`` has the full table):
``TFT_TRACE``/``explain()`` for per-block depth on a query you can
re-run; ``tft.why()`` for the decision chain of a query you cannot;
``metrics_text()`` for rates and trends; ``tft.health()``/``doctor()``
for "is the process OK right now".
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from . import flight as _flight
from .report import _fmt_bytes

__all__ = ["why", "doctor"]

# kinds that indicate something went sideways: doctor() surfaces these
ANOMALY_KINDS = (
    "serve.shed", "serve.reject", "serve.over_quota", "serve.preempt",
    "serve.admission_preempt", "serve.cancel", "resilience.giveup",
    "memory.overflow_admit", "memory.wait", "mesh.shrink",
    "mesh.rebalance", "plan.oom_fallback", "dplan.fallback",
    "pipeline.sync_fallback", "engine.oom_split", "preempt.park",
    "fabric.worker_lost", "fabric.worker_crash", "fabric.replace",
    "fabric.admit_probe_failed", "mesh.exchange_skew",
    "perf.regression", "invariant.violation", "serve.quarantine",
    "serve.quarantine_reject", "memory.persist_corrupt", "chaos.fire",
    "history.unclean_shutdown", "history.segment_corrupt",
)


def _detail(r: Dict[str, Any]) -> str:
    """One human line per decision kind, leading with the recorded
    inputs (estimate vs observation, threshold, alternative chosen);
    unknown kinds fall back to key=value so new record sites render
    without touching this table."""
    k = r["kind"]
    if k == "serve.start":
        return (f"started after {r.get('queue_wait_s', 0):.3f}s queued "
                f"(tenant {r.get('tenant')!r}, est "
                f"{_fmt_bytes(r.get('est_bytes') or 0)}"
                + (", resumed from checkpoint" if r.get("resumed")
                   else "") + ")")
    if k == "serve.admit":
        head = r.get("headroom")
        head_s = _fmt_bytes(head) if head is not None else "unlimited"
        wait = r.get("waited_s") or 0.0
        return (f"admitted: est {_fmt_bytes(r.get('est_bytes') or 0)} "
                f"vs headroom {head_s}"
                + (f" after waiting {wait:.3f}s" if wait else ""))
    if k == "serve.shed":
        return (f"SHED: est {_fmt_bytes(r.get('est_bytes') or 0)} "
                f"exceeds headroom "
                f"{_fmt_bytes(r.get('headroom') or 0)} and admission "
                f"could not clear within its "
                f"{r.get('budget_s')}s budget "
                f"(TFT_SERVE_ADMISSION_WAIT_S)")
    if k == "serve.reject":
        return (f"REJECTED at submit: tenant {r.get('tenant')!r} queue "
                f"full ({r.get('queued')}/{r.get('max_queue')})")
    if k == "serve.over_quota":
        return (f"REJECTED over quota: est {r.get('est_rows')} rows vs "
                f"{r.get('tokens') or 0:.0f} token(s) left of "
                f"{r.get('rate') or 0:g} rows/s")
    if k == "serve.preempt":
        return (f"asked to park: arriving tenant "
                f"{r.get('arriving')!r} (weight "
                f"{r.get('arriving_weight')}) outweighs "
                f"{r.get('victim_weight')} and all "
                f"{r.get('workers')} worker(s) were busy "
                f"(TFT_PREEMPT_AFTER_MS={r.get('after_ms')})")
    if k == "serve.admission_preempt":
        return (f"parked whale {r.get('victim')} "
                f"({_fmt_bytes(r.get('victim_bytes') or 0)}) to clear "
                f"{_fmt_bytes(r.get('shortfall') or 0)} of admission "
                f"shortfall instead of shedding")
    if k == "serve.cancel":
        return f"cancel requested while {r.get('state', 'live')}"
    if k == "serve.requeue":
        return (f"re-queued at its tenant-queue FRONT with "
                f"{r.get('parked_blocks')} block(s) checkpointed "
                f"(preemption #{r.get('preemptions')})")
    if k == "serve.finish":
        return (f"finished: {r.get('outcome')} after "
                f"{r.get('latency_s', 0):.3f}s end-to-end")
    if k == "preempt.park":
        return (f"parked at block boundary {r.get('blocks')}/"
                f"{r.get('total')} — "
                f"{_fmt_bytes(r.get('bytes') or 0)} checkpointed "
                f"off-device ({r.get('reason') or 'requested'})")
    if k == "preempt.resume":
        return (f"resumed: {r.get('blocks')}/{r.get('total')} block(s) "
                f"restored from checkpoint instead of re-dispatched")
    if k == "preempt.cancel":
        return (f"cancelled at a block boundary "
                f"({r.get('reason') or 'requested'})")
    if k == "plan.adaptive_layout":
        return (f"re-bucketed {r.get('blocks')} leaf block(s) into "
                f"{r.get('units')} unit(s) (coalesced "
                f"{r.get('coalesced')}, split {r.get('splits')}) "
                f"targeting {r.get('depth')} full pipeline slot(s)")
    if k == "plan.replan":
        return (f"mid-plan RE-PLAN at block {r.get('at_block')}: "
                f"filter selectivity observed {r.get('observed')} vs "
                f"priced {r.get('priced')} (deviation past "
                f"TFT_REPLAN_RATIO={r.get('ratio')}); remaining stages "
                f"re-ordered")
    if k == "plan.filter_reorder":
        return (f"filter run re-ordered by observed selectivity "
                f"{r.get('selectivities')} -> order {r.get('order')}")
    if k == "plan.oom_fallback":
        return (f"fused plan hit an unsplittable OOM ({r.get('error')}); "
                f"whole forcing re-ran per-op")
    if k == "dplan.fallback":
        return (f"fused mesh program failed ({r.get('error')}, "
                f"kind {r.get('error_kind')}); recorded chain replayed "
                f"per-op")
    if k == "plan.result_cache_hit":
        return (f"result cache HIT: {r.get('blocks')} block(s) / "
                f"{_fmt_bytes(r.get('bytes') or 0)} served with zero "
                f"dispatches")
    if k == "plan.result_cache_admit":
        return (f"result interned ({_fmt_bytes(r.get('bytes') or 0)}; "
                f"second sighting of the fingerprint)")
    if k == "plan.result_cache_evict":
        return (f"{r.get('entries')} result-cache entr(ies) "
                f"LRU-evicted under the budget")
    if k == "mesh.shrink":
        return (f"device {r.get('device')} LOST during "
                f"{r.get('op')!r}: mesh shrunk "
                f"{r.get('devices_before')} -> "
                f"{r.get('devices_after')} device(s), "
                f"{r.get('reshard_rows')} row(s) re-sharded through "
                f"the host")
    if k == "mesh.grow":
        return (f"device(s) {r.get('devices')} re-admitted after "
                f"probe+warm-up: mesh grown {r.get('devices_before')} "
                f"-> {r.get('devices_after')}")
    if k == "mesh.rebalance":
        return (f"persistent skew {r.get('ratio')} (> TFT_SKEW_WARN="
                f"{r.get('threshold')} for {r.get('streak')} "
                f"dispatches): rows re-partitioned {r.get('before')} "
                f"-> {r.get('after')}")
    if k == "mesh.exchange_skew":
        return (f"exchange partition imbalance {r.get('ratio')} "
                f"(> TFT_SKEW_WARN={r.get('threshold')}) during "
                f"{r.get('op')!r}: {r.get('rows')} row(s), per-shard "
                f"{r.get('per_shard')}")
    if k == "relational.join_route":
        est = r.get("est_build_bytes")
        est_s = _fmt_bytes(est) if est is not None else "unknown"
        return (f"join auto-routed to {r.get('strategy')!r} "
                f"({r.get('reason')}): est build {est_s} vs "
                f"TFT_BROADCAST_LIMIT_BYTES="
                f"{_fmt_bytes(r.get('limit') or 0)}, keys "
                f"{r.get('keys')}, how={r.get('how')}, shuffle "
                f"{'on' if r.get('shuffle') else 'off'}")
    if k == "mesh.salt":
        return (f"{r.get('count')} hot key group(s) (> "
                f"{r.get('fraction')} of rows, TFT_HOT_KEY_FRACTION) "
                f"salted across {r.get('slots')} slot(s)")
    if k == "memory.spill":
        return (f"spilled {r.get('name')} "
                f"({_fmt_bytes(r.get('bytes') or 0)}) to pinned host "
                f"under budget pressure")
    if k == "memory.fault":
        return (f"faulted {r.get('name')} "
                f"({_fmt_bytes(r.get('bytes') or 0)}) back to device")
    if k == "memory.overflow_admit":
        return (f"OVERFLOW admission: {_fmt_bytes(r.get('bytes') or 0)} "
                f"for {r.get('op')} over the "
                f"{_fmt_bytes(r.get('limit') or 0)} budget "
                f"({r.get('cause')})")
    if k == "memory.wait":
        return (f"admission waited: {_fmt_bytes(r.get('bytes') or 0)} "
                f"for {r.get('op')} had no headroom")
    if k == "memory.proactive_split":
        return (f"block split BEFORE dispatch: est "
                f"{_fmt_bytes(r.get('bytes') or 0)} would overflow the "
                f"{_fmt_bytes(r.get('limit') or 0)} budget")
    if k == "engine.oom_split":
        return (f"allocator OOM ({r.get('error')}): {r.get('rows')} "
                f"row(s) re-dispatched as halves")
    if k == "pipeline.sync_fallback":
        return (f"async submit failed ({r.get('error')}); block re-ran "
                f"synchronously through the retry machinery")
    if k == "resilience.giveup":
        return (f"GAVE UP on {r.get('op')} after {r.get('attempts')} "
                f"attempt(s): {r.get('error')} (classified "
                f"{r.get('error_kind')})")
    if k == "stream.batch_skip":
        return (f"batch {r.get('batch')} poisoned ({r.get('error')}, "
                f"classified {r.get('error_kind')}); skipped")
    if k == "plan.result_cache_warm_hit":
        return (f"result cache WARM hit: {r.get('blocks')} block(s) / "
                f"{_fmt_bytes(r.get('bytes') or 0)} re-admitted from "
                f"the durable tier (fingerprint "
                f"{r.get('fingerprint')}…) — survived a restart")
    if k == "plan.result_cache_persist":
        return (f"result persisted to the durable tier "
                f"({_fmt_bytes(r.get('bytes') or 0)}, fingerprint "
                f"{r.get('fingerprint')}…)")
    if k == "fabric.place":
        return (f"tenant {r.get('tenant')!r} placed on "
                f"{r.get('worker')} (least loaded: "
                f"{r.get('tenants_on_worker')} tenant(s) there)")
    if k == "fabric.replace":
        return (f"tenant {r.get('tenant')!r} re-placed "
                f"{r.get('source')} -> {r.get('worker')} "
                f"({r.get('reason')})")
    if k == "fabric.rebalance":
        return (f"tenant {r.get('tenant')!r} re-placed "
                f"{r.get('source')} -> {r.get('worker')}: SLO burn "
                f"{r.get('burn_rate')}x vs hottest peer "
                f"{r.get('peer_max')}x (> {r.get('factor')}x, "
                f"TFT_FABRIC_BURN_FACTOR)")
    if k == "fabric.worker_crash":
        return (f"worker {r.get('worker')} (epoch {r.get('epoch')}) "
                f"CRASHED: running queries parked to the durable "
                f"tier, in-memory caches died with it")
    if k == "fabric.worker_lost":
        return (f"worker {r.get('worker')} declared LOST after "
                f"{r.get('missed')} missed heartbeat(s) (classified "
                f"{r.get('classified')}); tenants re-placed, queries "
                f"re-dispatched")
    if k == "fabric.heartbeat_miss":
        return (f"worker {r.get('worker')} missed a heartbeat "
                f"({r.get('missed')}/{r.get('limit')} before the "
                f"lease expires)")
    if k == "fabric.resume_dispatch":
        cp = r.get("from_checkpoint")
        return (f"re-dispatched to {r.get('worker')} "
                f"({r.get('reason')}, attempt #{r.get('attempt')}): "
                + (f"{r.get('resumed_blocks')} block(s) resume from "
                   f"the persisted checkpoint" if cp
                   else "no checkpoint found — cold re-run"))
    if k == "fabric.worker_restart":
        return (f"rolling restart of {r.get('worker')}: epoch "
                f"{r.get('epoch')} -> {r.get('next_epoch')} (drain, "
                f"persist, re-admit via probe)")
    if k == "fabric.admit":
        return (f"worker {r.get('worker')} (epoch {r.get('epoch')}) "
                f"passed its admission probe")
    if k == "fabric.admit_probe_failed":
        return (f"worker {r.get('worker')} (epoch {r.get('epoch')}) "
                f"FAILED its admission probe ({r.get('error')}); not "
                f"admitted")
    if k == "history.unclean_shutdown":
        return (f"UNCLEAN SHUTDOWN: pid {r.get('pid')} "
                + (f"(worker {r['worker']}) " if r.get("worker") else "")
                + f"died without its clean-exit hook (history dir "
                f"{r.get('dir')}); tft.postmortem() has the triage "
                f"report")
    if k == "history.segment_corrupt":
        return (f"history segment {r.get('segment')} went COLD "
                f"({r.get('why')}); unlinked — fewer records, never "
                f"wrong ones")
    if k == "perf.regression":
        return (f"PERF REGRESSION: latency {r.get('latency_s')}s vs "
                f"baseline {r.get('baseline_latency_s')}s "
                f"({r.get('latency_sigma')} sigma > "
                f"TFT_REGRESSION_SIGMA) over {r.get('runs')} warm "
                f"run(s) of plan {r.get('fingerprint')}…; most-moved: "
                f"{r.get('component')} {r.get('baseline')} -> "
                f"{r.get('observed')} ({r.get('sigma')} sigma)")
    skip = {"seq", "ts", "kind", "query"}
    kv = " ".join(f"{k2}={v!r}" for k2, v in r.items() if k2 not in skip)
    return kv or k


def _dumped_records_for(qid: str) -> List[Dict[str, Any]]:
    """The query's decisions recovered from the on-disk
    ``TFT_FLIGHT_DUMP`` snapshots (current file + its ``.1``
    rotation), for queries the live ring has already forgotten."""
    import os
    base = os.environ.get("TFT_FLIGHT_DUMP")
    if not base:
        return []
    paths = [p for p in (base, base + ".1") if os.path.exists(p)]
    if not paths:
        return []
    try:
        merged = _flight.load_dumps(paths)
    except Exception:  # noqa: BLE001 - post-mortem salvages what it can
        return []
    return [r for r in merged if str(r.get("query")) == qid]


def _render_chain(qid, recs: List[Dict[str, Any]], source: str) -> str:
    t0 = recs[0].get("ts", 0)
    lines = [f"query {qid} · {len(recs)} decision(s) recorded "
             f"({source}; TFT_TRACE-independent)"]
    for r in recs:
        w = f" w={r['worker']}" if r.get("worker") else ""
        lines.append(f"  +{r.get('ts', t0) - t0:8.3f}s "
                     f"{r['kind']:<24}{w} {_detail(r)}")
    return "\n".join(lines)


def why(query_id, scheduler=None) -> str:
    """Reconstruct the decision chain of one query — with ``TFT_TRACE``
    off, after the fact, and (since the durable history layer) across a
    process restart. ``query_id`` is the serving id
    (``SubmittedQuery.query_id``, e.g. ``"serve-q17"``) or any id the
    work ran under a :func:`~.flight.scope` for; a ``SubmittedQuery``
    object is also accepted. Sources in order: the live flight ring,
    the on-disk ``TFT_FLIGHT_DUMP`` snapshots, then the durable query
    history (:func:`~.history.causal_chain`) — so a query that finished
    before a crash still answers from the archive. Lines render oldest
    first with offsets from the first decision."""
    qid = str(getattr(query_id, "query_id", query_id))
    recs = _flight.for_query(qid)
    if recs:
        return _render_chain(qid, recs, "flight ring")
    dumped = _dumped_records_for(qid)
    if dumped:
        return _render_chain(
            qid, dumped, "recovered from flight dump(s) on disk — the "
            "live ring has moved past it")
    from . import history as _history
    rec, decs = _history.causal_chain(qid)
    if rec is not None:
        lines = [f"query {qid} · durable history (ring and dumps hold "
                 f"no trace; archived record survives restarts)"]
        workers = rec.get("workers") or (
            [rec["worker"]] if rec.get("worker") else [])
        head = f"  outcome {rec.get('outcome')!r}"
        if rec.get("total_s") is not None:
            head += f" after {rec['total_s']:.3f}s end-to-end"
        if rec.get("tenant"):
            head += f" · tenant {rec['tenant']!r}"
        if workers:
            head += f" · worker(s) {' -> '.join(workers)}"
        if rec.get("migrations"):
            head += f" · {rec['migrations']} migration(s)"
        lines.append(head)
        if rec.get("summary"):
            lines.append(f"  {rec['summary']}")
        if rec.get("error"):
            lines.append(f"  error: {rec['error']}"
                         + (f" (classified {rec['error_kind']})"
                            if rec.get("error_kind") else ""))
        cost = rec.get("cost") or {}
        if cost:
            parts = [f"{k}={v}" for k, v in sorted(cost.items())
                     if isinstance(v, (int, float)) and v]
            if parts:
                lines.append("  cost: " + " ".join(parts[:8]))
        if decs:
            t0 = decs[0].get("ts", rec.get("ts", 0))
            lines.append(f"  {len(decs)} archived decision(s)"
                         + (f" (+{rec['decisions_dropped']} dropped by "
                            f"the digest cap, TFT_HISTORY_DECISIONS)"
                            if rec.get("decisions_dropped") else "")
                         + ":")
            for r in decs:
                w = f" w={r['worker']}" if r.get("worker") else ""
                lines.append(f"    +{r.get('ts', t0) - t0:8.3f}s "
                             f"{r['kind']:<24}{w} {_detail(r)}")
        return "\n".join(lines)
    if not _flight.enabled():
        return (f"(flight recorder disabled — TFT_FLIGHT=0; no "
                f"decisions recorded for {qid})")
    return (f"(no decisions recorded for query {qid!r} — it ran "
            f"before the flight ring's horizon, under no flight "
            f"scope, or never ran; the ring holds "
            f"{_flight.stats()['records']} decision(s), the dump and "
            f"the durable history hold no trace of it)")


def doctor(max_per_kind: int = 5,
           flight_dumps: Optional[Any] = None) -> str:
    """Process-wide triage: the :func:`~.health.health` snapshot's
    vitals and warnings, the SLO burn table, and the recent anomalous
    decisions from the flight ring grouped by kind (newest
    ``max_per_kind`` each). The "what should I look at" report for a
    process you did not watch.

    ``flight_dumps`` — a path or list of paths to per-worker
    ``TFT_FLIGHT_DUMP`` JSONL files: they merge into the anomaly scan
    via :func:`~.flight.load_dumps` (each record tagged with its
    worker from the dump header), so one doctor() call triages a whole
    fabric's worth of dead processes."""
    from .health import health as _health
    snap = _health()
    lines = ["tft.doctor() · process triage report"]
    mem = snap["memory"]
    if mem["limited"]:
        lines.append(
            f"  memory   : budget {_fmt_bytes(mem['limit_bytes'])} · "
            f"headroom {_fmt_bytes(mem['headroom_bytes'] or 0)} · "
            f"{mem['resident_buffers']} resident / "
            f"{mem['spilled_buffers']} spilled buffer(s) · "
            f"{mem['spills']} spill(s), "
            f"{mem['overflow_admissions']} overflow admission(s)")
    else:
        lines.append("  memory   : unlimited (no ledger budget)")
    mesh = snap["mesh"]
    lines.append(
        f"  mesh     : {mesh['visible_devices']} visible device(s) · "
        f"lost pool {mesh['lost_pool'] or 'empty'} · "
        f"{mesh['shrinks']} shrink(s) / {mesh['grows']} grow(s) / "
        f"{mesh['rebalances']} rebalance(s)")
    serve = snap["serve"]
    if serve.get("running"):
        lines.append(
            f"  serve    : {serve['name']!r} · {serve['queued']} "
            f"queued / {serve['inflight']} in flight across "
            f"{len(serve['tenants'])} tenant(s) · {serve['workers']} "
            f"worker(s), {serve['slots']} slot(s)")
    else:
        lines.append("  serve    : no scheduler running")
    fab = snap.get("fabric") or {}
    if fab.get("running"):
        ps = fab.get("persist") or {}
        lines.append(
            f"  fabric   : {fab['name']!r} · {fab['live']}/"
            f"{fab['workers']} worker(s) live, {fab['lost']} lost · "
            f"{fab['queries']['inflight']} quer(ies) in flight · "
            f"persist {_fmt_bytes((ps.get('checkpoint_bytes') or 0) + (ps.get('result_bytes') or 0))} "
            f"({ps.get('checkpoints', 0)} ckpt / "
            f"{ps.get('results', 0)} result)")
    for t, s in snap["slo"].items():
        if s["total"] == 0:
            continue
        lines.append(
            f"  slo      : tenant {t!r} — {s['objective_ms']:g} ms @ "
            f"{s['target']:.4g}: compliance "
            f"{s['compliance']:.4%} · burn {s['burn_rate']:.2f}x · "
            f"budget left {s['budget_remaining']:.1%}")
    for name, s in snap["streams"].items():
        lines.append(
            f"  stream   : {name!r} — {s['batches']} batch(es), "
            f"{s['batches_skipped']} skipped, watermark "
            f"{s['watermark']}, lag {s['batch_lag_s']}")
    fl = snap["flight"]
    lines.append(
        f"  flight   : {'on' if fl['enabled'] else 'OFF'} · "
        f"{fl['records']}/{fl['capacity']} decision(s) buffered · "
        f"{fl['dumps']} dump(s)")
    hs = snap.get("history") or {}
    if hs.get("enabled"):
        lines.append(
            f"  history  : {hs.get('segments', 0)} segment(s) "
            f"({_fmt_bytes(hs.get('bytes') or 0)}) · "
            f"{hs.get('records_written', 0)} record(s) archived this "
            f"process · {hs.get('corrupt_segments', 0)} cold segment(s)"
            + (" · UNCLEAN SHUTDOWN detected — tft.postmortem()"
               if hs.get("unclean") else ""))
    else:
        lines.append("  history  : OFF (no TFT_HISTORY_DIR and no "
                     "durable tier; tft.history() empty)")
    perf = snap.get("perf") or {}
    tls = perf.get("timeline") or {}
    lines.append(
        f"  perf     : {'on' if perf.get('enabled') else 'OFF'} · "
        f"{perf.get('warm_baselines', 0)}/{perf.get('baselines', 0)} "
        f"baseline(s) warm over "
        f"{perf.get('completions_total', 0)} completion(s) · "
        f"{perf.get('regressions_total', 0)} regression(s) · timeline "
        f"{tls.get('samples', 0)}/{tls.get('capacity', 0)} sample(s)")
    res = snap["resilience"]
    lines.append(
        f"  engine   : {res['retries']} retri(es), {res['giveups']} "
        f"giveup(s), {res['oom_splits']} oom split(s), "
        f"{res['sync_fallbacks']} sync fallback(s), "
        f"{res['plan_oom_fallbacks']}+{res['dplan_fallbacks']} plan "
        f"fallback(s)")
    inv = snap.get("invariants") or {}
    chaos = inv.get("chaos")
    chaos_s = (f" · chaos seed {chaos['seed']} rate {chaos['rate']:g} "
               f"({chaos['fired']} firing(s) over "
               f"{'|'.join(chaos['sites'])})" if chaos else "")
    lines.append(
        f"  invariant: audits {'on' if inv.get('enabled', True) else 'OFF'}"
        f"{' [strict]' if inv.get('strict') else ''} · "
        f"{inv.get('audits', 0)} audit(s), "
        f"{inv.get('violations', 0)} violation(s), "
        f"{inv.get('rows_tainted', 0)} tainted row ledger(s)"
        f"{chaos_s}")
    quar = snap.get("quarantine") or {}
    active_q = quar.get("active") or {}
    if active_q:
        lines.append(
            f"  quarantine: {len(active_q)} plan(s) fast-rejected "
            f"(after {quar.get('threshold')} permanent failures, TTL "
            f"{quar.get('ttl_s'):g}s — tft.unquarantine() lifts):")
        for fp, info in sorted(active_q.items()):
            lines.append(
                f"    {fp[:20]}… — {info['failures']} failure(s), "
                f"lifts in {info['ttl_remaining_s']:.0f}s: "
                f"{info['error'] or '?'}")
    if snap["warnings"]:
        lines.append("  WARNINGS :")
        for w in snap["warnings"]:
            lines.append(f"    ! {w}")
    else:
        lines.append("  WARNINGS : none")
    pool = list(_flight.recent())
    source = "flight ring"
    if flight_dumps:
        merged = _flight.load_dumps(flight_dumps)
        pool = sorted(pool + merged,
                      key=lambda r: (r.get("ts", 0), r.get("seq", 0)))
        source = (f"flight ring + {len(merged)} record(s) from "
                  f"per-worker dump(s)")
    by_kind: Dict[str, List[Dict[str, Any]]] = {}
    for r in pool:
        if r.get("kind") in ANOMALY_KINDS:
            by_kind.setdefault(r["kind"], []).append(r)
    if by_kind:
        lines.append(f"  recent anomalous decisions ({source}):")
        now = time.time()
        for k in sorted(by_kind):
            recs = by_kind[k][-max_per_kind:]
            lines.append(f"    {k} ({len(by_kind[k])} total):")
            for r in recs:
                q = f" [{r['query']}]" if r.get("query") else ""
                w = f" w={r['worker']}" if r.get("worker") else ""
                lines.append(f"      -{now - r['ts']:7.1f}s{q}{w} "
                             f"{_detail(r)}")
    else:
        lines.append("  recent anomalous decisions: none recorded")
    # perf regressions grouped by plan fingerprint ACROSS workers: the
    # same plan regressing on several workers is one fleet-wide story
    # (a knob change, an eviction), not N separate ones — the merged
    # per-worker dumps make that read off directly
    by_fp: Dict[str, List[Dict[str, Any]]] = {}
    for r in pool:
        if r.get("kind") == "perf.regression" and r.get("fingerprint"):
            by_fp.setdefault(str(r["fingerprint"]), []).append(r)
    if by_fp:
        lines.append(f"  perf regressions by plan fingerprint "
                     f"({source}):")
        now = time.time()
        for fp in sorted(by_fp):
            recs = by_fp[fp]
            workers = sorted({str(r["worker"]) for r in recs
                              if r.get("worker")})
            comps = sorted({str(r.get("component")) for r in recs})
            w_s = f", worker(s) {', '.join(workers)}" if workers else ""
            lines.append(f"    plan {fp}… ({len(recs)} "
                         f"regression(s){w_s}; component(s) "
                         f"{', '.join(comps)}):")
            for r in recs[-max_per_kind:]:
                q = f" [{r['query']}]" if r.get("query") else ""
                lines.append(
                    f"      -{now - r.get('ts', now):7.1f}s{q} "
                    f"{r.get('component')}: {r.get('baseline')} -> "
                    f"{r.get('observed')} ({r.get('sigma')} sigma; "
                    f"latency {r.get('latency_s')}s vs "
                    f"{r.get('baseline_latency_s')}s)")
    return "\n".join(lines)
