"""Prometheus-text-format export of the tracing registries.

:func:`metrics_text` renders the always-on :data:`~..utils.tracing.counters`
plus the span/gauge statistics of :data:`~..utils.tracing.timings` in the
Prometheus exposition format (text/plain; version=0.0.4), and
:func:`serve_metrics` serves it from a stdlib ``http.server`` endpoint —
opt-in, loopback-only.

Families:

- ``tft_counter_total{name="..."}`` — every named counter (retries,
  giveups, OOM splits, pipeline totals, trace queries/drops);
- ``tft_span_seconds_count/_sum{span="..."}`` (summary) with
  ``tft_span_seconds_min/_max{span="..."}`` gauges — the per-stage span
  histograms' statistics;
- ``tft_gauge{name="...",stat="mean|min|max|last"}`` and
  ``tft_gauge_samples_total{name="..."}`` — sampled levels (e.g.
  ``pipeline.occupancy``);
- proper Prometheus **histogram** families (cumulative ``le`` buckets +
  ``_sum``/``_count``):
  ``tft_query_latency_seconds{op="...",outcome="ok|error"}`` (one
  series per query op and outcome, observed at every traced query
  finish — failures never pollute the success-latency series) and
  ``tft_compile_seconds{engine="jax|native|native_mesh"}`` (observed at
  every compile-cache miss, always on);
- ``tft_trace_ring_events`` — events currently buffered in the ring.

Security note: the endpoint binds ``127.0.0.1`` ONLY — metrics names leak
workload structure, so exposing them beyond the host is an explicit
reverse-proxy decision, not a default. ``TFT_METRICS_PORT=<port>`` starts
the endpoint at import (``0`` picks a free port; see
``observability.__init__``).
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..utils import tracing
from ..utils.logging import get_logger
from . import events as _events

__all__ = ["metrics_text", "serve_metrics", "stop_metrics", "metrics_port",
           "register_metrics_provider", "unregister_metrics_provider",
           "registered_providers"]

_log = get_logger("observability.metrics")

# extra exposition-line providers (the serving layer's live per-tenant
# queue/inflight gauges): name -> zero-arg callable returning a list of
# already-formatted Prometheus text lines. Providers render LIVE state
# (queue depths change between scrapes), which the counter/span
# registries cannot express.
_providers_lock = threading.Lock()
_providers: dict = {}


def register_metrics_provider(name: str, fn) -> None:
    """Add ``fn() -> list[str]`` to every :func:`metrics_text` render
    under ``name`` (re-registering a name replaces it). A provider that
    raises is logged and skipped — it can never take the endpoint down.
    """
    with _providers_lock:
        _providers[name] = fn


def unregister_metrics_provider(name: str) -> None:
    with _providers_lock:
        _providers.pop(name, None)


def registered_providers() -> list:
    """Names of every registered provider (the metrics-conformance test
    sweeps them all: one ``# TYPE`` per family, escaped label values,
    no duplicate series — the contract every current and future
    provider must meet)."""
    with _providers_lock:
        return sorted(_providers)


def _escape_label(value: str) -> str:
    """Prometheus label-value escaping: backslash, double-quote, and
    newline (exposition format §label values)."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _num(v: float) -> str:
    if v != v:  # NaN
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    return format(v, ".10g")


def metrics_text() -> str:
    """The current counters/spans/gauges in Prometheus text format."""
    lines = []
    counts = tracing.counters.snapshot()
    lines.append("# HELP tft_counter_total Always-on framework event "
                 "counters (retries, fallbacks, pipeline totals).")
    lines.append("# TYPE tft_counter_total counter")
    for name in sorted(counts):
        lines.append(f'tft_counter_total{{name="{_escape_label(name)}"}} '
                     f'{counts[name]}')

    spans = tracing.timings.spans_snapshot()
    lines.append("# HELP tft_span_seconds Host wall time per traced "
                 "stage (recorded only while tracing is enabled).")
    lines.append("# TYPE tft_span_seconds summary")
    for name in sorted(spans):
        s = spans[name]
        lab = f'span="{_escape_label(name)}"'
        lines.append(f"tft_span_seconds_count{{{lab}}} {s['count']}")
        lines.append(f"tft_span_seconds_sum{{{lab}}} {_num(s['total_s'])}")
    for stat, fam in (("min_s", "tft_span_seconds_min"),
                      ("max_s", "tft_span_seconds_max")):
        lines.append(f"# TYPE {fam} gauge")
        for name in sorted(spans):
            lines.append(f'{fam}{{span="{_escape_label(name)}"}} '
                         f"{_num(spans[name][stat])}")

    gauges = tracing.timings.gauges_snapshot()
    lines.append("# HELP tft_gauge Sampled levels (window occupancy, "
                 "queue depths); dimensionless.")
    lines.append("# TYPE tft_gauge gauge")
    for name in sorted(gauges):
        g = gauges[name]
        lab = _escape_label(name)
        for stat in ("mean", "min", "max", "last"):
            lines.append(f'tft_gauge{{name="{lab}",stat="{stat}"}} '
                         f"{_num(g[stat])}")
    lines.append("# TYPE tft_gauge_samples_total counter")
    for name in sorted(gauges):
        lines.append(f'tft_gauge_samples_total{{name='
                     f'"{_escape_label(name)}"}} {gauges[name]["count"]}')

    lines.extend(_histogram_lines())

    with _providers_lock:
        providers = list(_providers.items())
    for pname, fn in providers:
        try:
            lines.extend(fn())
        except Exception as e:
            _log.warning("metrics provider %r failed (skipped this "
                         "scrape): %s", pname, e)

    lines.append("# HELP tft_trace_ring_events Events currently held in "
                 "the bounded trace ring buffer.")
    lines.append("# TYPE tft_trace_ring_events gauge")
    lines.append(f"tft_trace_ring_events {len(_events.recent_events())}")
    return "\n".join(lines) + "\n"


_HIST_HELP = {
    "query_latency_seconds":
        "Wall time of traced queries, by op (observed at query finish).",
    "compile_seconds":
        "XLA compile duration per compile-cache miss, by engine.",
}


def _histogram_lines() -> list:
    """Render every :data:`~..utils.tracing.histograms` family in the
    Prometheus histogram convention: cumulative ``le`` buckets (ending at
    ``+Inf``) plus ``_sum`` and ``_count`` per label set."""
    hists = tracing.histograms.snapshot()
    lines: list = []
    for fam in sorted({k[0] for k in hists}):
        metric = f"tft_{fam}"
        help_text = _HIST_HELP.get(
            fam, "Bucketed observations (seconds).")
        lines.append(f"# HELP {metric} {help_text}")
        lines.append(f"# TYPE {metric} histogram")
        series = sorted((k for k in hists if k[0] == fam),
                        key=lambda k: k[1])
        for key in series:
            h = hists[key]
            labels = ",".join(f'{n}="{_escape_label(v)}"'
                              for n, v in key[1])
            sep = "," if labels else ""
            cum = 0
            for le, c in zip(h["les"], h["counts"]):
                cum += c
                le_s = "+Inf" if le == float("inf") else _num(le)
                lines.append(f'{metric}_bucket{{{labels}{sep}le='
                             f'"{le_s}"}} {cum}')
            brace = f"{{{labels}}}" if labels else ""
            lines.append(f"{metric}_sum{brace} {_num(h['sum'])}")
            lines.append(f"{metric}_count{brace} {h['count']}")
    return lines


# ---------------------------------------------------------------------------
# loopback HTTP endpoint
# ---------------------------------------------------------------------------

_server_lock = threading.Lock()
_server: Optional[ThreadingHTTPServer] = None
_thread: Optional[threading.Thread] = None


class _MetricsHandler(BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler contract
        if self.path.split("?", 1)[0].rstrip("/") in ("", "/metrics"):
            body = metrics_text().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self.send_error(404)

    def log_message(self, fmt, *args):  # route http.server chatter to us
        _log.debug("metrics endpoint: " + fmt, *args)


def serve_metrics(port: Optional[int] = None) -> int:
    """Start (or return) the loopback metrics endpoint; returns the bound
    port. ``port=0`` (the default) picks a free one. Always binds
    ``127.0.0.1`` — never a routable interface. Requesting a DIFFERENT
    specific port while the endpoint is already running raises (silently
    returning the old port would leave the asked-for scrape target
    dead); ``stop_metrics()`` first to rebind."""
    global _server, _thread
    with _server_lock:
        if _server is not None:
            bound = _server.server_address[1]
            if port and port != bound:
                raise RuntimeError(
                    f"metrics endpoint already running on 127.0.0.1:"
                    f"{bound}; stop_metrics() before rebinding to "
                    f"{port}")
            return bound
        srv = ThreadingHTTPServer(("127.0.0.1", port or 0),
                                  _MetricsHandler)
        srv.daemon_threads = True
        t = threading.Thread(target=srv.serve_forever,
                             name="tft-metrics", daemon=True)
        t.start()
        _server, _thread = srv, t
        _log.info("metrics endpoint on http://127.0.0.1:%d/metrics",
                  srv.server_address[1])
        return srv.server_address[1]


def metrics_port() -> Optional[int]:
    """The running endpoint's port, or None."""
    with _server_lock:
        return _server.server_address[1] if _server is not None else None


def stop_metrics() -> None:
    """Shut the endpoint down (idempotent)."""
    global _server, _thread
    with _server_lock:
        srv, t = _server, _thread
        _server = _thread = None
    if srv is not None:
        srv.shutdown()
        srv.server_close()
    if t is not None:
        t.join(timeout=5)
