"""Per-query cost attribution and rolling per-fingerprint baselines.

The flight recorder answers "what did the runtime decide"; this module
answers "how fast did this plan USED to be, and which component moved".
Point-in-time surfaces cannot see a recurring query that quietly got 3x
slower after a knob change, a mesh shrink, or a cache eviction — and
pipelined, overlapped execution makes a bare end-to-end latency
ambiguous: the time may have gone to compiles, slot contention, spills,
or the fused stages themselves. So the sentinel attributes:

- :func:`capture` — the serve scheduler wraps every execution in one:
  it snapshots the handful of always-on counters the engine already
  keeps, accumulates fused-stage wall seconds
  (:func:`note_stage_wall`, fed by the plan layer's feedback hook) and
  measured slot waits (:func:`note_wait`, fed by the pipeline/stream
  slot leases), and at finish assembles the **cost vector**:
  ``latency_s``, ``compile_s`` (compile_seconds histogram delta),
  ``stage_wall_s``, ``slot_wait_s``, ``slot_waits``,
  ``admission_waits``, ``spill_bytes``, ``fault_bytes``,
  ``dispatches``, ``host_bytes``. Counter deltas are process-global, so
  concurrent queries contaminate each other's counts — accepted: the
  MAD-based detector below is robust to that noise, and the timed
  components (stage walls, slot waits) are attributed exactly.
- a rolling **baseline** per plan fingerprint (the PR 14 adaptive-layer
  key; portable parquet-rooted fingerprints persist through the
  ``memory/persist.py`` disk tier so restarts stay calibrated):
  EWMA + a window of the last K completions per component
  (``TFT_BASELINE_SAMPLES``, default 32), detection armed after
  ``TFT_BASELINE_MIN`` (default 5) warm runs.
- a **regression detector**: a completion whose latency sits beyond
  ``TFT_REGRESSION_SIGMA`` (default 4.0) robust deviations
  (``|x - median| / (1.4826 * MAD + floor)``) above its baseline —
  AND is both relatively (``TFT_REGRESSION_MIN_FRAC``, default +50%)
  and absolutely (``TFT_REGRESSION_MIN_S``, default 50 ms) slower, so
  fast-query jitter cannot trip the alarm — flags
  a ``perf.regression`` flight anomaly naming the **most-moved
  component** — "compile_s 0→1.2s" reads as a cache eviction,
  "slot_wait_s 3x" as contention — triggers
  ``flight.maybe_dump("regression")``, and surfaces in
  ``tft.regressions()``, ``tft.doctor()``, ``tft.health()`` warnings,
  ``serve_report()`` per-tenant rows, and the ``tft_perf_*`` metrics
  provider.

These baselines are also the calibration feed ROADMAP item 4's cost
model consumes (``docs/adaptive.md``). ``TFT_TIMELINE=0`` bypasses the
whole sentinel (the gate is :func:`.timeline.enabled`); the always-on
path is bench-enforced ≤2% (``bench.py sentinel_overhead``).
"""

from __future__ import annotations

import contextlib
import contextvars
import statistics
import threading
import time
import weakref
from collections import OrderedDict, deque
from typing import Any, Dict, Iterator, List, Optional, Tuple

from ..utils import tracing
from ..utils.logging import get_logger
from . import flight as _flight
from . import timeline as _timeline
from .timeline import _env_float, _env_int

__all__ = ["enabled", "capture", "note_stage_wall", "note_wait",
           "note_result_frame", "finalize", "slow_context",
           "baseline_for", "regressions", "perf_stats", "clear"]

_log = get_logger("observability.baseline")


def enabled() -> bool:
    """The sentinel shares the timeline's ``TFT_TIMELINE=0`` gate: one
    knob turns off sampling, cost capture, and regression detection
    together, bit-identically."""
    return _timeline.enabled()


# the counter families a capture deltas; every one is always-on
_TRACKED = ("pipeline.slot_waits", "stream.slot_waits",
            "serve.admission_waits", "memory.spill_bytes",
            "memory.fault_bytes", "pipeline.submitted",
            "mesh.dispatches", "mesh.interstage_host_bytes")

# cost-vector component order (stable for rendering)
COMPONENTS = ("latency_s", "compile_s", "stage_wall_s", "slot_wait_s",
              "slot_waits", "admission_waits", "spill_bytes",
              "fault_bytes", "dispatches", "host_bytes")


def _compile_sum() -> float:
    """Summed ``compile_seconds`` across engines (always-on histogram,
    observed at every compile-cache miss). ``family_sum`` reads the
    totals in place — a full ``snapshot()`` copies every bucket list of
    every histogram twice per query, which alone busts the 2% bench
    bar."""
    return float(tracing.histograms.family_sum("compile_seconds"))


class _Capture:
    """One query's in-flight cost accumulation (found by the hooks via
    the ambient contextvar; the pipeline's ``wrap_context`` copies it
    into worker threads the same way the flight scope rides)."""

    __slots__ = ("query_id", "tenant", "t0", "counters0", "compile0",
                 "stage_wall_s", "slot_wait_s", "fingerprint",
                 "portable", "lock")

    def __init__(self, query_id: str, tenant: Optional[str]) -> None:
        self.query_id = query_id
        self.tenant = tenant
        self.t0 = time.perf_counter()
        self.counters0 = tracing.counters.get_many(_TRACKED)
        self.compile0 = _compile_sum()
        self.stage_wall_s = 0.0
        self.slot_wait_s = 0.0
        self.fingerprint: Optional[str] = None
        self.portable = False
        self.lock = threading.Lock()

    def vector(self, latency_s: Optional[float] = None
               ) -> Dict[str, float]:
        snap = tracing.counters.get_many(_TRACKED)
        d = {k: snap[k] - self.counters0[k] for k in _TRACKED}
        with self.lock:
            stage, slot = self.stage_wall_s, self.slot_wait_s
        return {
            "latency_s": (time.perf_counter() - self.t0
                          if latency_s is None else float(latency_s)),
            "compile_s": max(_compile_sum() - self.compile0, 0.0),
            "stage_wall_s": stage,
            "slot_wait_s": slot,
            "slot_waits": float(d["pipeline.slot_waits"]
                                + d["stream.slot_waits"]),
            "admission_waits": float(d["serve.admission_waits"]),
            "spill_bytes": float(d["memory.spill_bytes"]),
            "fault_bytes": float(d["memory.fault_bytes"]),
            "dispatches": float(d["pipeline.submitted"]
                                + d["mesh.dispatches"]),
            "host_bytes": float(d["mesh.interstage_host_bytes"]),
        }


_active: "contextvars.ContextVar[Optional[_Capture]]" = \
    contextvars.ContextVar("tft_cost_capture", default=None)


@contextlib.contextmanager
def capture(query_id: str,
            tenant: Optional[str] = None) -> Iterator[None]:
    """Attribute everything the hooks see inside the body to this
    query. A query that exits without :func:`finalize` (error, requeue
    after preemption) simply discards its capture — partial runs must
    not calibrate baselines."""
    if not enabled():
        yield
        return
    token = _active.set(_Capture(str(query_id), tenant))
    try:
        yield
    finally:
        _active.reset(token)


def note_stage_wall(wall_s: float) -> None:
    """Accumulate one fused-stage / forcing wall into the active
    capture (called by the plan layer's feedback hook — already a
    per-forcing site, never per-block)."""
    cap = _active.get()
    if cap is None:
        return
    with cap.lock:
        cap.stage_wall_s += float(wall_s)


def note_wait(seconds: float) -> None:
    """Accumulate one measured slot/lease wait (pipeline and stream
    slot leases call this only on their contended path)."""
    cap = _active.get()
    if cap is None:
        return
    with cap.lock:
        cap.slot_wait_s += float(seconds)


# fingerprint memo: a resubmitted frame OBJECT re-walks the same op
# chain on every completion (~40 us on a short chain) — cache per frame,
# keyed by its version counter so ``uncache()`` invalidates. A leaf
# re-versioning UNDER a long-lived chain object is not seen (the chain's
# own counter does not move); that staleness only mis-keys which
# baseline calibrates, never a query result, and chains are rebuilt per
# request in every serving path we have.
_fp_memo: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def note_result_frame(frame) -> None:
    """Fingerprint the finished query's result chain while the frame is
    still in hand (the scheduler calls this right after the thunk; by
    ``_finish`` time only the capture remembers it)."""
    cap = _active.get()
    if cap is None or frame is None:
        return
    ver = getattr(frame, "_version", 0)
    try:
        hit = _fp_memo.get(frame)
    except TypeError:  # unhashable/unweakrefable frame type
        hit = None
    if hit is not None and hit[0] == ver:
        cap.fingerprint, cap.portable = hit[1], hit[2]
        return
    try:
        from ..plan import adaptive as _adaptive
        fp = _adaptive.query_fingerprint(frame)
    except Exception as e:
        _log.debug("query fingerprint failed for %s: %s",
                   cap.query_id, e)
        return
    if fp is not None:
        cap.fingerprint, cap.portable = fp
        with contextlib.suppress(TypeError):
            _fp_memo[frame] = (ver, fp[0], fp[1])


# ---------------------------------------------------------------------------
# rolling baselines
# ---------------------------------------------------------------------------

def _window_k() -> int:
    return max(_env_int("TFT_BASELINE_SAMPLES", 32), 2)


def _min_warm() -> int:
    return max(_env_int("TFT_BASELINE_MIN", 5), 2)


def _sigma() -> float:
    return max(_env_float("TFT_REGRESSION_SIGMA", 4.0), 0.5)


def _min_frac() -> float:
    """Relative guard: latency must exceed ``(1 + frac) * median``."""
    return max(_env_float("TFT_REGRESSION_MIN_FRAC", 0.5), 0.0)


def _min_delta_s() -> float:
    """Absolute guard: latency must exceed the median by this many
    seconds. Fast queries jitter by multiples of their own runtime
    (compile variance, scheduler noise) — a 16 ms query taking 50 ms is
    not an actionable regression, and without this floor it can clear
    both the sigma and the relative tests."""
    return max(_env_float("TFT_REGRESSION_MIN_S", 0.05), 0.0)


_EWMA_ALPHA = 0.2


def _floor(component: str) -> float:
    """Per-unit MAD floors so a perfectly stable component (MAD 0)
    cannot turn measurement jitter into infinite sigmas."""
    if component.endswith("_s"):
        return 0.005  # 5 ms: below scheduler/timer noise
    if component.endswith("_bytes"):
        return 4096.0
    return 1.0


class Baseline:
    """Rolling per-component statistics for one plan fingerprint.

    Concurrent serve workers finalize completions of the SAME
    fingerprint at once — every window read/write holds the
    per-baseline lock (a deque appended to mid-iteration raises)."""

    __slots__ = ("fingerprint", "portable", "count", "ewma", "window",
                 "updated_ts", "lock")

    def __init__(self, fingerprint: str, portable: bool) -> None:
        self.fingerprint = fingerprint
        self.portable = portable
        self.count = 0
        self.ewma: Dict[str, float] = {}
        self.window: Dict[str, deque] = {}
        self.updated_ts = 0.0
        self.lock = threading.Lock()

    def update(self, vec: Dict[str, float]) -> None:
        k = _window_k()
        with self.lock:
            for comp, x in vec.items():
                w = self.window.get(comp)
                if w is None or w.maxlen != k:
                    w = self.window[comp] = deque(w or (), maxlen=k)
                w.append(float(x))
                prev = self.ewma.get(comp)
                self.ewma[comp] = float(x) if prev is None else \
                    prev + _EWMA_ALPHA * (float(x) - prev)
            self.count += 1
            self.updated_ts = time.time()

    def deviation(self, comp: str, x: float) -> Tuple[float, float]:
        """``(robust_sigma, median)`` of ``x`` against this baseline's
        window for ``comp`` (0 sigma when the window is empty)."""
        with self.lock:
            w = self.window.get(comp)
            vals = list(w) if w else None
        if not vals:
            return 0.0, 0.0
        med = statistics.median(vals)
        mad = statistics.median(abs(v - med) for v in vals)
        scale = 1.4826 * mad + _floor(comp)
        return abs(float(x) - med) / scale, med

    def to_payload(self) -> Dict[str, Any]:
        with self.lock:
            return {"fingerprint": self.fingerprint,
                    "portable": self.portable, "count": self.count,
                    "ewma": dict(self.ewma),
                    "window": {c: list(w)
                               for c, w in self.window.items()},
                    "updated_ts": self.updated_ts}

    @classmethod
    def from_payload(cls, payload: Dict[str, Any]
                     ) -> Optional["Baseline"]:
        try:
            bl = cls(str(payload["fingerprint"]),
                     bool(payload.get("portable", True)))
            bl.count = int(payload.get("count", 0))
            bl.ewma = {str(c): float(v)
                       for c, v in payload.get("ewma", {}).items()}
            k = _window_k()
            bl.window = {
                str(c): deque((float(v) for v in vals), maxlen=k)
                for c, vals in payload.get("window", {}).items()}
            bl.updated_ts = float(payload.get("updated_ts", 0.0))
            return bl
        except (KeyError, TypeError, ValueError) as e:
            _log.warning("discarding malformed persisted baseline: %s",
                         e)
            return None


_bl_lock = threading.Lock()
_baselines: "OrderedDict[str, Baseline]" = OrderedDict()
_BASELINE_CAP = 512
_loaded_misses: set = set()  # portable fps whose disk load came back empty

_reg_lock = threading.Lock()
_regressions: "deque[Dict[str, Any]]" = deque(
    maxlen=_env_int("TFT_REGRESSIONS_RING", 256))
_completions = 0  # lifetime cost vectors folded into baselines
_reg_total = 0


def baseline_for(fingerprint: str) -> Optional[Baseline]:
    """The in-memory baseline for a fingerprint, falling through to the
    durable tier for portable fingerprints once per process."""
    with _bl_lock:
        bl = _baselines.get(fingerprint)
        if bl is not None:
            _baselines.move_to_end(fingerprint)
            return bl
        missed = fingerprint in _loaded_misses
    if missed:
        return None
    payload = _load_persisted(fingerprint)
    bl = Baseline.from_payload(payload) if payload else None
    with _bl_lock:
        if bl is not None and fingerprint not in _baselines:
            _admit_locked(fingerprint, bl)
        elif bl is None:
            _loaded_misses.add(fingerprint)
            if len(_loaded_misses) > 4096:
                _loaded_misses.clear()
        return _baselines.get(fingerprint)


def _admit_locked(fingerprint: str, bl: Baseline) -> None:
    _baselines[fingerprint] = bl
    _baselines.move_to_end(fingerprint)
    while len(_baselines) > _BASELINE_CAP:
        _baselines.popitem(last=False)


def _load_persisted(fingerprint: str) -> Optional[Dict[str, Any]]:
    try:
        from ..memory import persist as _persist
        if not _persist.enabled():
            return None
        return _persist.load_baseline(fingerprint)
    except Exception as e:
        _log.warning("baseline load for %s failed: %s",
                     fingerprint[:16], e)
        return None


def _save_persisted(bl: Baseline) -> None:
    if not bl.portable:
        return  # process-local fingerprints mean nothing after restart
    try:
        from ..memory import persist as _persist
        if _persist.enabled():
            _persist.save_baseline(bl.fingerprint, bl.to_payload())
    except Exception as e:
        _log.warning("baseline save for %s failed: %s",
                     bl.fingerprint[:16], e)


# ---------------------------------------------------------------------------
# finalize + regression detection
# ---------------------------------------------------------------------------

def finalize(latency_s: Optional[float] = None,
             outcome: str = "completed") -> Optional[Dict[str, Any]]:
    """Close out the active capture at query finish: assemble the cost
    vector, fold it into the fingerprint's baseline, and run the
    regression check. Only successful completions calibrate — a shed,
    failed, or preempted run's costs are not what the plan "usually"
    costs. Returns the cost vector (or None: sentinel off / no
    capture). Called by the serve scheduler's ``_finish``."""
    cap = _active.get()
    if cap is None:
        return None
    # the sentinel rides the serving completion path after the caller's
    # future already resolved — a bug here must degrade to a log line,
    # never to a failed worker thread
    try:
        vec = cap.vector(latency_s)
        _timeline.maybe_sample()  # query finish: the timeline's beat
        if outcome != "completed" or cap.fingerprint is None:
            return vec
        global _completions
        fp = cap.fingerprint
        bl = baseline_for(fp)
        regression = None
        if bl is None:
            bl = Baseline(fp, cap.portable)
            with _bl_lock:
                existing = _baselines.get(fp)
                if existing is not None:
                    bl = existing
                else:
                    _admit_locked(fp, bl)
        elif bl.count >= _min_warm():
            regression = _check_regression(bl, vec, cap)
        bl.update(vec)
        with _reg_lock:
            _completions += 1
        _save_persisted(bl)
        if regression is not None:
            _flag_regression(regression)
        return vec
    except Exception as e:  # noqa: BLE001 - never break the query
        _log.warning("sentinel finalize failed for query %s: %s",
                     cap.query_id, e)
        return None


def _check_regression(bl: Baseline, vec: Dict[str, float],
                      cap: _Capture) -> Optional[Dict[str, Any]]:
    lat = vec["latency_s"]
    # O(1) pre-gate on the EWMA before any window sort: the guards
    # below demand +frac relative AND +delta absolute over the window
    # MEDIAN, so a completion under HALF those margins over the EWMA
    # cannot pass them unless the EWMA has drifted ~20%+ above the
    # median — the overwhelmingly common healthy completion skips the
    # median/MAD sorts entirely (this check runs on EVERY warm serve
    # completion; bench.py sentinel_overhead holds the path to <2%)
    with bl.lock:
        ew = bl.ewma.get("latency_s")
    if ew is not None and (lat <= ew * (1.0 + 0.5 * _min_frac())
                           or lat - ew <= 0.5 * _min_delta_s()):
        return None
    sigma = _sigma()
    z_lat, med_lat = bl.deviation("latency_s", lat)
    # three guards, all required: statistically extreme (sigma),
    # relatively large (frac), and absolutely large (seconds) — the
    # last two keep fast-query jitter from tripping an always-on alarm
    if z_lat <= sigma or lat <= med_lat:
        return None  # got FASTER beyond sigma: fine, not a regression
    if lat <= med_lat * (1.0 + _min_frac()):
        return None
    if lat - med_lat <= _min_delta_s():
        return None
    # most-moved component: the largest robust deviation among the
    # attribution components that INCREASED — that is the "why"
    best = ("latency_s", z_lat, med_lat, vec["latency_s"])
    for comp in COMPONENTS:
        if comp == "latency_s":
            continue
        z, med = bl.deviation(comp, vec[comp])
        if vec[comp] > med and z > best[1]:
            best = (comp, z, med, vec[comp])
    comp, z, base, obs = best
    return {"ts": time.time(), "query": cap.query_id,
            "tenant": cap.tenant, "fingerprint": bl.fingerprint,
            "component": comp, "baseline": round(base, 6),
            "observed": round(obs, 6), "sigma": round(z, 2),
            "latency_s": round(vec["latency_s"], 6),
            "baseline_latency_s": round(med_lat, 6),
            "latency_sigma": round(z_lat, 2), "runs": bl.count}


def _flag_regression(reg: Dict[str, Any]) -> None:
    global _reg_total
    with _reg_lock:
        _regressions.append(reg)
        _reg_total += 1
    tracing.counters.inc("perf.regressions")
    inputs = {k: v for k, v in reg.items()
              if k not in ("ts", "query", "fingerprint")}
    inputs["fingerprint"] = reg["fingerprint"][:16]
    _flight.record("perf.regression", query=reg["query"], **inputs)
    _flight.maybe_dump("regression")
    _log.warning(
        "perf regression: query %s (plan %s) latency %.3fs vs baseline "
        "%.3fs (%.1f sigma); most-moved: %s %.6g -> %.6g (%.1f sigma)",
        reg["query"], reg["fingerprint"][:16], reg["latency_s"],
        reg["baseline_latency_s"], reg["latency_sigma"],
        reg["component"], reg["baseline"], reg["observed"],
        reg["sigma"])


def regressions(limit: Optional[int] = None) -> List[Dict[str, Any]]:
    """Flagged regressions, oldest first (``tft.regressions()``);
    ``limit`` keeps the newest N."""
    with _reg_lock:
        out = list(_regressions)
    if limit is not None and len(out) > limit:
        out = out[-limit:]
    return out


def slow_context() -> Optional[Dict[str, Any]]:
    """The active capture's live cost preview for slow-query JSONL
    enrichment: the partial vector, the fingerprint (when known), and
    the worst in-flight deviation against the stored baseline — so a
    ``TFT_SLOW_QUERY_MS`` dump line is self-diagnosing."""
    cap = _active.get()
    if cap is None:
        return None
    vec = cap.vector()
    out: Dict[str, Any] = {
        "cost": {k: round(v, 6) for k, v in vec.items()}}
    if cap.fingerprint is None:
        return out
    out["fingerprint"] = cap.fingerprint[:16]
    with _bl_lock:
        bl = _baselines.get(cap.fingerprint)
    if bl is not None and bl.count >= _min_warm():
        worst = None
        for comp in COMPONENTS:
            z, med = bl.deviation(comp, vec[comp])
            if vec[comp] > med and (worst is None or z > worst[1]):
                worst = (comp, z, med, vec[comp])
        if worst is not None:
            out["baseline_deviation"] = {
                "component": worst[0], "sigma": round(worst[1], 2),
                "baseline": round(worst[2], 6),
                "observed": round(worst[3], 6)}
    return out


def perf_stats() -> Dict[str, Any]:
    """The sentinel's health snapshot (``tft.health()['perf']``)."""
    with _bl_lock:
        n_bl = len(_baselines)
        warm = sum(1 for b in _baselines.values()
                   if b.count >= _min_warm())
    with _reg_lock:
        regs = list(_regressions)
        total = _reg_total
        comps = _completions
    recent = [{"query": r["query"], "fingerprint": r["fingerprint"][:16],
               "component": r["component"], "sigma": r["sigma"],
               "ts": r["ts"]} for r in regs[-5:]]
    return {"enabled": enabled(), "baselines": n_bl,
            "warm_baselines": warm, "completions_total": comps,
            "regressions_total": total, "recent_regressions": recent,
            "timeline": _timeline.stats()}


def clear() -> None:
    """Drop baselines, regressions, and the loaded-miss memo (tests);
    re-reads the ring-size knobs."""
    global _regressions, _completions, _reg_total
    with _bl_lock:
        _baselines.clear()
        _loaded_misses.clear()
    with _reg_lock:
        _regressions = deque(maxlen=_env_int("TFT_REGRESSIONS_RING",
                                             256))
        _completions = 0
        _reg_total = 0


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------

def _render_metrics() -> List[str]:
    s = perf_stats()
    return [
        "# HELP tft_perf_baselines Plan fingerprints with a rolling "
        "cost baseline in memory.",
        "# TYPE tft_perf_baselines gauge",
        f"tft_perf_baselines {s['baselines']}",
        "# HELP tft_perf_warm_baselines Baselines warm enough to arm "
        "the regression detector.",
        "# TYPE tft_perf_warm_baselines gauge",
        f"tft_perf_warm_baselines {s['warm_baselines']}",
        "# HELP tft_perf_completions_total Query completions folded "
        "into cost baselines.",
        "# TYPE tft_perf_completions_total counter",
        f"tft_perf_completions_total {s['completions_total']}",
        "# HELP tft_perf_regressions_total Completions flagged beyond "
        "TFT_REGRESSION_SIGMA of their baseline.",
        "# TYPE tft_perf_regressions_total counter",
        f"tft_perf_regressions_total {s['regressions_total']}",
    ]


def _register_metrics() -> None:
    # deferred: metrics imports events which imports flight
    from .metrics import register_metrics_provider
    register_metrics_provider("perf", _render_metrics)
