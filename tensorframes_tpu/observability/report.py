"""Human-readable per-query reports: ``frame.explain()`` and
``tft.last_query_report()``.

The structured replacement for the reference's ``logDebug`` narration
(SURVEY.md §5): instead of grepping interleaved log lines, one call
renders what a query actually did — rows, blocks, bytes marshalled,
retries, OOM splits, sync fallbacks, compile-cache behavior, wall time
by stage, and (for mesh queries) the per-device breakdown: rows/bytes/
time per device, a straggler ratio (max/median device time, warned
above ``TFT_SKEW_WARN``, default 2.0), and HBM watermarks — all from
the query's own :class:`~.events.QueryTrace`, so overlapping queries
can no longer contaminate each other's numbers.
"""

from __future__ import annotations

import os
from typing import Optional

from ..utils import tracing
from . import events as _events

__all__ = ["render", "frame_report", "last_query_report"]

DEFAULT_SKEW_WARN = 2.0

_skew_malformed_warned = False


def _skew_threshold() -> float:
    raw = os.environ.get("TFT_SKEW_WARN")
    if not raw:
        return DEFAULT_SKEW_WARN
    try:
        return float(raw)
    except ValueError:
        global _skew_malformed_warned
        if not _skew_malformed_warned:
            from ..utils.logging import get_logger
            get_logger("observability.report").warning(
                "ignoring malformed TFT_SKEW_WARN=%r (using %g)", raw,
                DEFAULT_SKEW_WARN)
            _skew_malformed_warned = True
        return DEFAULT_SKEW_WARN


def _fmt_bytes(n) -> str:
    # tolerant of None/strings: the decision-audit renderer feeds it
    # whatever a flight record carried
    try:
        v = float(n)
    except (TypeError, ValueError):
        return str(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(v) < 1024.0 or unit == "TiB":
            return f"{v:.1f} {unit}" if unit != "B" else f"{int(v)} B"
        v /= 1024.0
    return f"{int(n)} B"


def _fmt_secs(s: float) -> str:
    if s < 1e-3:
        return f"{s * 1e6:.0f} us"
    if s < 1.0:
        return f"{s * 1e3:.2f} ms"
    return f"{s:.3f} s"


def render(trace: "_events.QueryTrace") -> str:
    """Render one finished (or in-flight) trace as an aligned report."""
    s = trace.summary()
    lines = [
        f"query {s['query_id']} · {s['op']} · "
        f"{_fmt_secs(s['duration_s'])} · {s['blocks']} block(s)",
        f"  rows     : {s['rows_in']} in / {s['rows_out']} out · "
        f"{_fmt_bytes(s['bytes_in'])} marshalled",
    ]
    occ = (f", mean occupancy {s['occupancy_mean']:.2f}"
           if s["occupancy_mean"] is not None else "")
    if s["slots"] or occ:
        lines.append(f"  pipeline : {s['slots']} in-flight slot(s){occ}, "
                     f"{s['sync_fallbacks']} sync fallback(s)")
    lines.append(
        f"  resilience: {s['retries']} retried, {s['giveups']} gave up, "
        f"{s['oom_splits']} oom split(s), "
        f"{s['pad_fallbacks']} pad fallback(s)")
    compile_s = (f" · {_fmt_secs(s['compile_seconds'])} compiling"
                 if s["compile_seconds"] else "")
    lines.append(
        f"  compile  : {s['compile_misses']} miss(es) / "
        f"{s['compile_hits']} hit(s){compile_s}")
    if trace.meta:
        meta = " ".join(f"{k}={v}" for k, v in sorted(trace.meta.items())
                        if k != "plan")
        if meta:
            lines.append(f"  query    : {meta}")
    mesh = s["mesh"]
    if mesh is not None:
        ratio = mesh["straggler_ratio"]
        ratio_s = (f"straggler ratio {ratio:.2f} (max/median device time)"
                   if ratio is not None else "straggler ratio n/a")
        lines.append(f"  mesh     : {len(mesh['devices'])} device(s), "
                     f"{s['mesh_dispatches']} dispatch(es), "
                     f"{s['collectives']} collective(s), {ratio_s}")
        for d, acc in mesh["devices"].items():
            lines.append(f"    device {d}: {acc['rows']} rows · "
                         f"{_fmt_bytes(acc['bytes'])} · "
                         f"{_fmt_secs(acc['time_s'])}")
        if ratio is not None and ratio > _skew_threshold():
            lines.append(
                f"  WARNING  : device time imbalance — the slowest "
                f"device ran {ratio:.2f}x the median (threshold "
                f"{_skew_threshold():g}; straggling shard or skewed "
                f"rows, see the per-device table above; persistent "
                f"skew triggers re-partitioning, docs/resilience.md)")
    for ev in list(trace.events):
        if ev.etype == "adaptive_layout":
            a = ev.args or {}
            lines.append(
                f"  adaptive : {a.get('blocks')} leaf block(s) "
                f"re-bucketed into {a.get('units')} (coalesced "
                f"{a.get('coalesced', 0)}, split {a.get('splits', 0)}) "
                f"— original boundaries restored (docs/adaptive.md)")
        elif ev.etype == "replan":
            a = ev.args or {}
            lines.append(
                f"  adaptive : mid-plan re-plan at block "
                f"{a.get('at_block')} — observed selectivity deviated "
                f"past TFT_REPLAN_RATIO; remaining filter stages "
                f"re-ordered (docs/adaptive.md)")
        elif ev.etype == "result_cache_hit":
            a = ev.args or {}
            lines.append(
                f"  adaptive : result cache HIT — {a.get('blocks')} "
                f"block(s) / {a.get('bytes')} B served with zero "
                f"dispatches (docs/adaptive.md)")
        elif ev.etype == "sched_admission_preempt":
            a = ev.args or {}
            lines.append(
                f"  admission: preempted query {a.get('victim')} "
                f"({a.get('victim_bytes')} B) to clear headroom "
                f"instead of shedding (docs/serving.md)")
    for ev in list(trace.events):
        if ev.etype == "fused_stage":
            a = ev.args or {}
            res = (f", {a.get('resident')} column(s) pass through "
                   f"device-resident" if a.get("resident") else "")
            lines.append(
                f"  dplan    : fused stage '{ev.name}' — "
                f"{a.get('ops')} op(s) in ONE GSPMD program, "
                f"{a.get('filters', 0)} in-program filter(s){res} "
                f"(docs/plan.md)")
            if a.get("wall_s") is not None:
                # the per-stage shard-time record the fused dispatch
                # feeds into the adaptive feedback registry — surfaced
                # here and in DistributedFrame.explain()
                lines.append(
                    f"    stage shard time: {_fmt_secs(a['wall_s'])} "
                    f"across {a.get('shards')} shard(s) "
                    f"(~{_fmt_secs(a['wall_s'] / max(a.get('shards') or 1, 1))}"
                    f"/shard amortized)")
    if s["mesh_shrinks"]:
        for ev in list(trace.events):
            if ev.etype == "mesh_shrink":
                a = ev.args or {}
                lines.append(
                    f"  elastic  : device {a.get('device')} lost — mesh "
                    f"shrunk {a.get('devices_before')} -> "
                    f"{a.get('devices_after')} device(s), "
                    f"{a.get('reshard_rows')} row(s) re-sharded")
    if s["mesh_grows"]:
        for ev in list(trace.events):
            if ev.etype == "mesh_grow":
                a = ev.args or {}
                lines.append(
                    f"  elastic  : device(s) {a.get('devices')} "
                    f"re-admitted (probe + warm-up) — mesh grown "
                    f"{a.get('devices_before')} -> "
                    f"{a.get('devices_after')} device(s)")
    if s["rebalances"]:
        for ev in list(trace.events):
            if ev.etype == "rebalance":
                a = ev.args or {}
                lines.append(
                    f"  rebalance: skew {a.get('ratio')} — per-shard "
                    f"rows {a.get('before')} -> {a.get('after')}")
    if s["preempts"] or s["resumed_blocks"]:
        lines.append(
            f"  preempt  : parked {s['preempts']} time(s); "
            f"{s['resumed_blocks']} block(s) restored from checkpoint "
            f"instead of re-dispatched (docs/serving.md)")
    if s["hbm"] is not None:
        h = s["hbm"]
        lines.append(f"  memory   : peak HBM {_fmt_bytes(h['peak'])} "
                     f"(live {_fmt_bytes(h['live_start'])} -> "
                     f"{_fmt_bytes(h['live_end'])})")
    if (s["spills"] or s["faults"] or s["proactive_splits"]
            or s["external_sort_runs"]):
        ext = (f", external sort in {s['external_sort_runs']} run(s)"
               if s["external_sort_runs"] else "")
        lines.append(
            f"  spill    : {s['spills']} spill(s) "
            f"({_fmt_bytes(s['spill_bytes'])} to host), "
            f"{s['faults']} fault(s), {s['proactive_splits']} proactive "
            f"split(s){ext} (docs/memory.md)")
    extra = f" (+{s['dropped']} dropped)" if s["dropped"] else ""
    lines.append(f"  events   : {s['events']} recorded{extra}")
    if trace.stages:
        lines.append("  wall time by stage:")
        width = max(len(k) for k in trace.stages)
        for name in sorted(trace.stages,
                           key=lambda k: -trace.stages[k][1]):
            count, total = trace.stages[name]
            lines.append(f"    {name:<{width}} {int(count):6d}x "
                         f"{total:12.6f}s")
    return "\n".join(lines)


def frame_report(df) -> str:
    """``TensorFrame.explain()`` backend: the execution report of the
    frame's forcing.

    If the frame was already forced while tracing was on, its recorded
    trace renders directly. Otherwise the frame is (re-)forced once with
    tracing temporarily enabled — ``explain()`` is an explicit request
    for observability, so it pays for one traced execution rather than
    returning nothing.
    """
    def with_plan(report: str) -> str:
        # the optimized logical plan of the forcing (docs/plan.md):
        # fused groups, pruned columns, resident edges — recorded by
        # plan.execute when the fused path ran; absent under TFT_FUSE=0
        # or when the chain fell back to the per-op path
        info = getattr(df, "_plan_info", None)
        if info:
            report = report + "\n" + "\n".join(info)
        hot = getattr(df, "_hot_keys", None)
        if hot:
            # hot-key observations from the producing daggregate's
            # salting (docs/joins.md): which keys were skewed enough to
            # trigger it, and how hot they ran
            for h in hot:
                kv = ", ".join(f"{k}={v!r}"
                               for k, v in h["keys"].items())
                frac = (f"{h['fraction']:.0%} of rows"
                        if h.get("fraction") is not None else "hot")
                report += (f"\n  hot key  : {{{kv}}} — {frac}, salted "
                           f"across {h['salt_slots']} slot(s) "
                           f"(frame.hot_keys())")
        route = getattr(df, "_join_route", None)
        if route:
            # the join auto-routing decision (also in the flight ring
            # as relational.join_route — tft.why() renders it)
            est = route.get("est_build_bytes")
            est_s = _fmt_bytes(est) if est is not None else "unknown"
            report += (f"\n  join     : auto-routed to "
                       f"{route['strategy']!r} ({route['reason']}) — "
                       f"est build {est_s} vs limit "
                       f"{_fmt_bytes(route['limit'])}, shuffle "
                       f"{'on' if route.get('shuffle') else 'off'}")
        pinfo = getattr(df, "_partitioned_info", None)
        if pinfo:
            report += (f"\n  shuffle  : partitioned build across "
                       f"{pinfo['shards']} shard(s) — max per-device "
                       f"build {_fmt_bytes(pinfo['max_build_bytes'])} "
                       f"of {_fmt_bytes(pinfo['global_build_bytes'])} "
                       f"global")
        ex = getattr(df, "_exchange_skew", None) \
            or getattr(df, "_exchange", None)
        if ex:
            flag = (" OVER TFT_SKEW_WARN"
                    if ex["ratio"] > ex["threshold"] else "")
            report += (f"\n  exchange : partition imbalance "
                       f"{ex['ratio']:.2f} (threshold "
                       f"{ex['threshold']:.2f}{flag}); per-shard rows "
                       f"{ex['per_shard']}")
        return report

    t = getattr(df, "_trace", None)
    if t is None:
        if _events.current_trace() is not None:
            # re-forcing inside an active query would join that trace
            # and record nothing for this frame: full cost, no report
            return ("(no query trace recorded — explain() was called "
                    "inside another active query; call it after that "
                    "query finishes)")
        was = tracing.enabled()
        if not was:
            tracing.enable()
        old_cache = df._cache
        try:
            df._cache = None  # re-force under a trace
            df.blocks()
        except BaseException:
            df._cache = old_cache  # a failed re-force must not lose
            raise                  # the previously computed result
        finally:
            if not was:
                tracing.disable()
        t = getattr(df, "_trace", None)
    if t is None:
        return with_plan(
            "(no query trace recorded — the frame was forced inside "
            "another query or tracing stayed off)")
    return with_plan(render(t))


def last_query_report() -> str:
    """Report of the most recently finished query (eager ops — reduce /
    aggregate / the mesh d-ops — have no frame to hang ``explain()``
    on; this is their equivalent)."""
    t = _events.last_query()
    if t is None:
        return ("(no query recorded yet — enable tracing with TFT_TRACE=1 "
                "or tensorframes_tpu.utils.tracing.enable() and run a "
                "query)")
    return render(t)
