"""Scalar dtype registry and host/device dtype policy.

The reference supports exactly Double / Int / Long in its engine
(``/root/reference/src/main/scala/org/tensorframes/impl/datatypes.scala:202-239``)
plus Float at the Python boundary (``core.py:357-360``). This module keeps the
same user-facing dtype vocabulary but separates:

- **storage dtype**: how column data lives in host columnar buffers (numpy);
- **device dtype**: what the TPU actually computes in.

TPUs have no fp64 ALUs; ``double`` columns compute in float32 on TPU (or
float64 on CPU when jax x64 mode is on) and are cast back on collect. This is
the TPU-native substitute for the reference's one-converter-per-scalar design
(``ScalarTypeOperation``), where the cast is an explicit, documented policy
instead of a JNI buffer-fill specialization.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

import numpy as np

__all__ = [
    "DType",
    "double",
    "float32",
    "int32",
    "int64",
    "bfloat16",
    "by_name",
    "from_numpy",
    "from_python_value",
    "supported_dtypes",
    "widen",
    "device_dtype",
]


@dataclass(frozen=True)
class DType:
    """A framework scalar type.

    ``name`` is the canonical user-facing name; ``np_storage`` the host
    columnar dtype; ``priority`` orders numeric widening (wider wins);
    ``tensor`` marks types that can feed device computations — non-tensor
    types (string) are pass-through/group-key only, the way the reference
    carries non-numeric Spark columns alongside tensor columns
    (``geom_mean.py:21-24``: "non numeric columns (string)" was a found bug).
    """

    name: str
    np_storage: np.dtype
    priority: int
    tensor: bool = True

    def __repr__(self) -> str:
        return self.name

    @property
    def is_floating(self) -> bool:
        return self.tensor and np.issubdtype(self.np_storage, np.floating)

    @property
    def itemsize(self) -> int:
        return self.np_storage.itemsize


double = DType("double", np.dtype(np.float64), 40)
float32 = DType("float", np.dtype(np.float32), 30)
int64 = DType("long", np.dtype(np.int64), 20)
int32 = DType("int", np.dtype(np.int32), 10)
# bfloat16 is TPU-native extra surface (not in the reference); stored as f32 on
# host, computed as bf16 on device.
bfloat16 = DType("bfloat16", np.dtype(np.float32), 25)
# pass-through only: valid as a column / group-by key, never a tensor input
string = DType("string", np.dtype(object), 0, tensor=False)

_BY_NAME: Dict[str, DType] = {
    "double": double,
    "float64": double,
    "f64": double,
    "float": float32,
    "float32": float32,
    "f32": float32,
    "long": int64,
    "int64": int64,
    "i64": int64,
    "int": int32,
    "int32": int32,
    "i32": int32,
    "bfloat16": bfloat16,
    "bf16": bfloat16,
    "string": string,
    "str": string,
}

_CORE = (double, float32, int64, int32)


def supported_dtypes():
    return _CORE


def by_name(name: str) -> DType:
    try:
        return _BY_NAME[name]
    except KeyError:
        raise ValueError(
            f"Unknown dtype {name!r}; supported: {sorted(set(_BY_NAME))}"
        ) from None


def from_numpy(dt) -> DType:
    """Map a numpy dtype to the framework dtype (widening unsupported ints)."""
    dt = np.dtype(dt)
    if dt == np.float64:
        return double
    if dt == np.float32:
        return float32
    if dt == np.int64:
        return int64
    if dt in (np.int32, np.int16, np.int8, np.uint8, np.uint16):
        return int32
    if dt.kind == "f":  # float16 etc
        return float32
    if str(dt) == "bfloat16":
        return bfloat16
    if dt == np.bool_:
        return int32
    if dt.kind in ("U", "S"):
        return string
    # object arrays are NOT classified here: without the values there is no
    # way to tell a string column from arbitrary Python objects — callers
    # with data in hand (Schema.from_numpy_columns) decide
    raise ValueError(f"Unsupported numpy dtype for tensorframes: {dt}")


def from_python_value(x) -> DType:
    if isinstance(x, bool):
        return int32
    if isinstance(x, int):
        return int64
    if isinstance(x, float):
        return double
    if isinstance(x, np.generic):
        return from_numpy(x.dtype)
    raise ValueError(f"Unsupported python scalar {type(x)}")


def widen(a: DType, b: DType) -> DType:
    """Numeric widening for mixed-type DSL constants."""
    if a.is_floating != b.is_floating:
        return double if (a is double or b is double) else float32
    return a if a.priority >= b.priority else b


def device_dtype(dt: DType, platform: Optional[str] = None) -> np.dtype:
    """The dtype the computation runs in on the target platform.

    - On TPU: double -> float32 (no fp64 ALUs), long -> int32 when x64 is off.
    - On CPU: follows jax's x64 flag.
    """
    import jax

    if not dt.tensor:
        raise ValueError(f"{dt.name} columns cannot be device tensors")
    if platform is None:
        platform = jax.default_backend()
    x64 = bool(jax.config.read("jax_enable_x64"))
    if dt is bfloat16:
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    if dt is double:
        if platform == "tpu" or not x64:
            return np.dtype(np.float32)
        return np.dtype(np.float64)
    if dt is int64 and not x64:
        return np.dtype(np.int32)
    return dt.np_storage
