"""Shared shard_map/vma plumbing for the Pallas kernels in this package.

Two facts every kernel here must honor when traced inside
``jax.shard_map(..., check_vma=True)``:

* ``pallas_call``'s ``out_shape`` must declare which mesh axes the output
  varies over (``jax.ShapeDtypeStruct(..., vma=...)``), or tracing fails
  with "`vma` ... must not be `None`" — for a per-shard kernel the output
  varies wherever any input does (:func:`vma_union`).
* jax's Pallas **HLO interpreter** cannot replay kernel bodies under vma
  tracking: block values carry varying mesh axes but jaxpr-internal iotas
  do not, so every mixed ``eq``/``add`` trips the checker. The Mosaic
  (real-TPU) path is unaffected — kernels trace with plain ref avals.
  Interpreted runs inside a mesh must therefore fall back to the kernel's
  XLA oracle (:func:`interpret_blocked_by_vma`).

Any new Pallas kernel should route through both helpers; see
``segment_reduce.py`` / ``flash_attention.py`` for the pattern.
"""

from __future__ import annotations

from typing import FrozenSet

from ..utils.compat import vma_of

__all__ = ["vma_union", "interpret_blocked_by_vma"]


def vma_union(*arrays) -> FrozenSet[str]:
    """Union of the varying-mesh-axes of every input — the ``vma`` a
    per-shard kernel's ``out_shape`` must declare. Empty on jax builds
    without vma tracking (nothing to declare there)."""
    out: FrozenSet[str] = frozenset()
    for a in arrays:
        out = out | vma_of(a)
    return out


def interpret_blocked_by_vma(*arrays) -> bool:
    """True when an ``impl="interpret"`` run must use the XLA oracle
    instead: some input varies over a mesh axis, which the Pallas HLO
    interpreter cannot replay (see module docstring)."""
    return bool(vma_union(*arrays))
