"""Flash attention as a Pallas TPU kernel.

Exact softmax attention computed blockwise so the [Sq, Sk] score matrix is
never materialized in HBM: for each (batch*head, q-block) the kernel sweeps
k-blocks, maintaining the online-softmax statistics (running max ``m``,
normalizer ``l``, unnormalized accumulator ``acc``) in VMEM scratch, and
writes the normalized output once at the last k-step. Matmuls hit the MXU in
f32 accumulation regardless of the input dtype (bf16 in, f32 acc, input
dtype out).

This is the single-device kernel; sequence parallelism composes *around* it:
:func:`~tensorframes_tpu.parallel.ring.ring_attention` rotates k/v shards
over the ICI ring and uses the same online-softmax update per local block
pair.

The ``impl="xla"`` path is the semantic reference (plain jnp softmax
attention); CPU tests run the Pallas kernel with ``interpret=True``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils.compat import shape_dtype_struct, tpu_compiler_params
from ._pallas_mesh import interpret_blocked_by_vma, vma_union

__all__ = ["flash_attention"]

_LANES = 128  # VMEM lane width: m/l scratch keeps stats broadcast over lanes

_NEG_INF = -1e30  # large-negative, not -inf: keeps fully-masked rows NaN-free


def _kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
            scale: float, causal: bool, block_q: int, block_k: int,
            sk_valid: int):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _update():
        q = q_ref[0]  # [block_q, d]
        k = k_ref[0]  # [block_k, d]
        v = v_ref[0]  # [block_k, d]
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        k_pos = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = k_pos < sk_valid  # pad k rows contribute nothing
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask = jnp.logical_and(mask, q_pos >= k_pos)
        scores = jnp.where(mask, scores, _NEG_INF)

        m_prev = m_ref[:, 0]  # [bq]
        l_prev = l_ref[:, 0]
        m_cur = jnp.max(scores, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)          # rescale of old stats
        p = jnp.exp(scores - m_new[:, None])     # [bq, bk]
        p = jnp.where(mask, p, 0.0)              # exp(-1e30-…) underflows, but be exact
        l_new = alpha * l_prev + jnp.sum(p, axis=-1)
        acc_ref[:] = acc_ref[:] * alpha[:, None] + jax.lax.dot_general(
            p, v.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    if causal:
        # skip k-blocks fully above the diagonal
        @pl.when(kj * block_k <= qi * block_q + (block_q - 1))
        def _():
            _update()
    else:
        _update()

    @pl.when(kj == nk - 1)
    def _finalize():
        l = l_ref[:, 0]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / l_safe[:, None]).astype(o_ref.dtype)


def _pad_to(x, axis: int, multiple: int):
    n = x.shape[axis]
    pad = (-n) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _pallas_attention(q, k, v, *, causal: bool, scale: float,
                      block_q: int, block_k: int, interpret: bool):
    bh, sq, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    qp = _pad_to(q, 1, block_q)
    kp = _pad_to(k, 1, block_k)
    vp = _pad_to(v, 1, block_k)
    nq = qp.shape[1] // block_q
    nk = kp.shape[1] // block_k

    kern = functools.partial(_kernel, scale=scale, causal=causal,
                             block_q=block_q, block_k=block_k, sk_valid=sk)
    out = pl.pallas_call(
        kern,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=shape_dtype_struct(
            (bh, qp.shape[1], d), q.dtype,
            # shard_map(check_vma=True) requires declaring the mesh axes the
            # output varies over — the attention output varies like q/k/v
            vma=vma_union(q, k, v)),
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # m
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # l
            pltpu.VMEM((block_q, d), jnp.float32),       # acc
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(qp, kp, vp)
    return out[:, :sq]


def _xla_attention(q, k, v, *, causal: bool, scale: float):
    scores = jnp.einsum("bqd,bkd->bqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = scores.shape[-2:]
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        scores = jnp.where(mask, scores, _NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v.astype(p.dtype)).astype(q.dtype)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    impl: Optional[str] = None) -> jax.Array:
    """Exact attention, ``[B, S, H, D]`` layout (matching the model zoo).

    ``impl``: ``"pallas"`` (TPU kernel), ``"xla"`` (plain jnp reference),
    ``"interpret"`` (Pallas interpreter — CPU tests), or None to pick
    automatically (Pallas on TPU backends, XLA elsewhere).
    """
    if impl is None:
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl == "interpret" and interpret_blocked_by_vma(q, k, v):
        impl = "xla"  # see ops/_pallas_mesh.py: interpreter can't do vma
    b, sq, h, d = q.shape
    sk = k.shape[1]
    if scale is None:
        scale = float(1.0 / (d ** 0.5))
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    if impl == "xla":
        o = _xla_attention(qf, kf, vf, causal=causal, scale=scale)
    elif impl in ("pallas", "interpret"):
        o = _pallas_attention(qf, kf, vf, causal=causal, scale=scale,
                              block_q=block_q, block_k=block_k,
                              interpret=(impl == "interpret"))
    else:
        raise ValueError(f"Unknown flash_attention impl {impl!r}")
    return o.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
