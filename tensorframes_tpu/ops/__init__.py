"""Hand-written TPU kernels (Pallas) for the framework's hot ops.

The reference's compute path bottoms out in whatever libtensorflow's C++
kernels do (SURVEY.md §2.2); here XLA covers the general case and this
package holds the ops worth hand-scheduling on the TPU's memory hierarchy:

- :func:`flash_attention` — blockwise attention with online softmax; the
  quadratic-memory score matrix never leaves VMEM.
- :func:`segment_sum` — keyed segment reduction via one-hot matmul on the
  MXU; the device-side core of ``aggregate`` and the k-means
  ``unsorted_segment_sum`` pattern.

Every kernel has a pure-XLA fallback (`impl="xla"`) that is the semantic
reference; CPU tests run the Pallas path in interpret mode.
"""

from .flash_attention import flash_attention
from .segment_reduce import segment_sum

__all__ = ["flash_attention", "segment_sum"]
