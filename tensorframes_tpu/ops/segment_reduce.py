"""Keyed segment reduction as a Pallas TPU kernel.

``segment_sum(values, segment_ids, num_segments)`` is the device-side core
of keyed aggregation — the TPU-native answer to the reference's
``unsorted_segment_sum`` k-means pattern (``kmeans_demo.py:128-140``) and
the UDAF shuffle+reduce (``DebugRowOps.scala:533-578``).

XLA lowers ``jax.ops.segment_sum`` to scatter-add, which serializes on the
TPU. This kernel instead expresses the reduction as a **one-hot matmul**:
for each row-block, build the ``[block_rows, num_segments]`` one-hot matrix
of segment ids and contract it against the values block on the MXU —
``[S, bn] @ [bn, d] -> [S, d]`` — accumulating partials into the output
block across the sequential grid. Out-of-range ids (e.g. -1 pad rows)
produce an all-zero one-hot row and contribute nothing, for free.

Fallback (`impl="xla"`): ``jax.ops.segment_sum``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils.compat import shape_dtype_struct, tpu_compiler_params
from ._pallas_mesh import interpret_blocked_by_vma, vma_union

__all__ = ["segment_sum"]


def _kernel(ids_ref, vals_ref, out_ref, *, block_rows: int,
            num_segments: int):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    ids = ids_ref[:]                       # [bn, 1] int32
    vals = vals_ref[:]                     # [bn, d]
    seg = jax.lax.broadcasted_iota(jnp.int32, (block_rows, num_segments), 1)
    onehot = (ids == seg).astype(jnp.float32)            # [bn, S]
    partial = jax.lax.dot_general(
        onehot, vals.astype(jnp.float32),
        (((0,), (0,)), ((), ())),          # contract the row dim: [S, d]
        precision=jax.lax.Precision.HIGHEST,  # exact f32: this is an
        preferred_element_type=jnp.float32)   # aggregation, not attention
    out_ref[:] = out_ref[:] + partial.astype(out_ref.dtype)


def _pallas_segment_sum(values, segment_ids, num_segments: int,
                        block_rows: int, interpret: bool):
    n, d = values.shape
    # callers guarantee floating values (segment_sum routes ints to XLA)
    acc_dtype = jnp.float32
    if n == 0:
        return jnp.zeros((num_segments, d), values.dtype)
    block_rows = min(block_rows, n)
    pad = (-n) % block_rows
    if pad:
        values = jnp.pad(values, ((0, pad), (0, 0)))
        # pad ids with -1: matches no segment, so pad rows vanish
        segment_ids = jnp.pad(segment_ids, (0, pad), constant_values=-1)
    nblocks = values.shape[0] // block_rows

    # under shard_map(check_vma=True) the out_shape must declare which mesh
    # axes it varies over; the reduction output varies wherever its inputs do
    vma = vma_union(values, segment_ids)
    kern = functools.partial(_kernel, block_rows=block_rows,
                             num_segments=num_segments)
    out = pl.pallas_call(
        kern,
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((num_segments, d), lambda i: (0, 0)),
        out_shape=shape_dtype_struct((num_segments, d), acc_dtype,
                                     vma=vma),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(segment_ids.astype(jnp.int32).reshape(-1, 1), values)
    return out.astype(values.dtype)


def segment_sum(values: jax.Array, segment_ids: jax.Array,
                num_segments: int, block_rows: int = 512,
                impl: Optional[str] = None) -> jax.Array:
    """Sum ``values`` rows into ``num_segments`` buckets by ``segment_ids``.

    ``values``: [N, ...] (trailing dims flattened for the kernel and
    restored); ``segment_ids``: [N] ints in [0, num_segments) — rows with
    out-of-range ids are dropped. Returns [num_segments, ...].

    ``impl``: ``"pallas"`` / ``"xla"`` / ``"interpret"``; None picks Pallas
    on TPU.
    """
    if impl not in (None, "pallas", "interpret", "xla"):
        raise ValueError(f"Unknown segment_sum impl {impl!r}")
    values = jnp.asarray(values)
    segment_ids = jnp.asarray(segment_ids)
    if not jnp.issubdtype(values.dtype, jnp.floating):
        # the one-hot matmul accumulates in f32, which is only exact to
        # 2^24 — integer aggregation must stay exact, so it always takes
        # the scatter-add path
        if impl in ("pallas", "interpret"):
            raise ValueError(
                f"segment_sum impl={impl!r} accumulates in f32 and is "
                "inexact for integer values; use impl='xla'")
        impl = "xla"
    elif impl is None:
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl == "interpret" and interpret_blocked_by_vma(values, segment_ids):
        impl = "xla"  # see ops/_pallas_mesh.py: interpreter can't do vma
    if impl == "xla":
        valid = (segment_ids >= 0) & (segment_ids < num_segments)
        shaped = jnp.where(
            valid.reshape((-1,) + (1,) * (values.ndim - 1)), values, 0)
        ids = jnp.where(valid, segment_ids, 0)
        return jax.ops.segment_sum(shaped, ids, num_segments=num_segments)
    tail = values.shape[1:]
    d = 1
    for t in tail:
        d *= t
    flat = values.reshape(values.shape[0], d)
    out = _pallas_segment_sum(flat, segment_ids, num_segments,
                              block_rows, interpret=(impl == "interpret"))
    return out.reshape((num_segments,) + tail)
