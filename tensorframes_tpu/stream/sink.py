"""Stream sinks: where per-batch outputs and emitted windows land.

A sink is any object with ``write(frame)`` (called once per output
:class:`~..frame.TensorFrame`) and optionally ``close()`` (called when
the stream finalizes or stops). Three built-ins:

- :class:`CollectSink` — buffers frames for polling (the explicit form
  of the handle's built-in ``collect_updates()`` buffer);
- :class:`CallbackSink` — adapts a plain callable;
- :class:`ParquetSink` — appends every frame to one growing parquet
  file, one row group per block, through a single open writer. The
  output of a parquet-sink'd stream is itself tail-able by a
  :class:`~.source.ParquetTailSource` — streams compose end to end.
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional

from ..frame import TensorFrame
from ..utils.logging import get_logger

__all__ = ["CollectSink", "CallbackSink", "ParquetSink"]

_log = get_logger("stream.sink")


class CollectSink:
    """Buffer output frames; ``collect()`` drains them (thread-safe)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._frames: List[TensorFrame] = []

    def write(self, frame: TensorFrame) -> None:
        with self._lock:
            self._frames.append(frame)

    def collect(self) -> List[TensorFrame]:
        with self._lock:
            out, self._frames = self._frames, []
        return out

    def close(self) -> None:
        pass  # nothing to release; buffered frames stay collectable


class CallbackSink:
    """Adapt ``fn(frame)`` as a sink (``on_update=`` does this for
    you; the class exists for composing sinks explicitly)."""

    def __init__(self, fn: Callable[[TensorFrame], None],
                 on_close: Optional[Callable[[], None]] = None):
        self._fn = fn
        self._on_close = on_close

    def write(self, frame: TensorFrame) -> None:
        self._fn(frame)

    def close(self) -> None:
        if self._on_close is not None:
            self._on_close()


class ParquetSink:
    """Append every output frame to ``path`` as parquet row groups.

    One ``pyarrow.parquet.ParquetWriter`` stays open across writes (the
    schema is pinned by the first frame); each block becomes one row
    group, so the file is incrementally tail-able. ``close()`` (called
    by the stream handle at finalize/stop) finishes the footer —
    readers see all row groups written so far only after a footer
    exists, i.e. parquet tailing composes with ATOMIC replace-style
    writers; this sink's own file is complete at close.
    """

    def __init__(self, path: str):
        self.path = path
        self._writer = None
        self._lock = threading.Lock()

    def write(self, frame: TensorFrame) -> None:
        import pyarrow.parquet as pq

        from ..io import _frame_block_to_table

        with self._lock:
            for b in frame.blocks():
                if b.num_rows == 0:
                    continue
                tbl = _frame_block_to_table(b, frame.schema)
                if self._writer is None:
                    self._writer = pq.ParquetWriter(self.path, tbl.schema)
                self._writer.write_table(tbl)

    def close(self) -> None:
        with self._lock:
            w, self._writer = self._writer, None
        if w is not None:
            w.close()
            _log.info("parquet sink closed: %s", self.path)
