"""StreamingFrame: the relational surface over a block source.

A :class:`StreamingFrame` is a :class:`~.source.BlockSource` plus a
chain of per-batch transformations. The row-local relational ops —
``map_blocks`` / ``map_rows`` / ``filter_rows`` / ``select`` — are the
SAME ops the finite engine runs (``engine.ops``), applied batch by
batch, with two streaming-specific guarantees:

- **definition-time resolution**: fetches are adapted to a canonical
  :class:`~..computation.Computation` ONCE, when the op is chained
  (through ``engine.ops.cached_map_computation``, the same cache the
  batch path and the serving layer's interner use) — so every batch
  re-dispatches the same compiled program instead of re-tracing.
  Schema validation happens here too: a bad fetch fails when the stream
  is DEFINED, not on batch 1.
- **finite equivalence**: because each batch runs through the unchanged
  engine ops, streaming a finite frame through any chain of these ops
  produces bit-identical results (ordering included) to the batch
  ``TensorFrame`` path — the contract ``tests/test_stream.py`` asserts
  op by op.

``group_by(...)`` hands off to the incremental keyed-aggregation layer
(:mod:`.aggregate`); ``start()`` builds the pump
(:class:`~.runtime.StreamHandle`) that actually drives batches.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple

from ..engine import ops as _ops
from ..frame import TensorFrame
from ..schema import Schema

__all__ = ["StreamingFrame", "GroupedStream"]


class StreamingFrame:
    """A lazily-described stream of blocks with per-batch relational ops.

    Construct from a source (``stream.from_source(src)`` or directly);
    chain ops like a ``TensorFrame``; then ``start()`` to pump batches.
    Transformations share the upstream source object — one stream
    definition is driven by one handle at a time.
    """

    def __init__(self, source, schema: Optional[Schema] = None,
                 transforms: Tuple[Callable[[TensorFrame], TensorFrame],
                                   ...] = (),
                 plan: Optional[str] = None):
        self.source = source
        self._schema = schema if schema is not None else source.schema
        self._transforms = tuple(transforms)
        self._plan = plan or f"stream({type(source).__name__})"

    # -- properties --------------------------------------------------------
    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def columns(self) -> List[str]:
        return self._schema.names

    def __repr__(self):
        return (f"StreamingFrame[{', '.join(self._schema.names)}] "
                f"(plan={self._plan})")

    # -- batch application (used by the runtime pump) ----------------------
    def _apply(self, df: TensorFrame) -> TensorFrame:
        for t in self._transforms:
            df = t(df)
        return df

    def _chain(self, fn: Callable[[TensorFrame], TensorFrame],
               out_schema: Schema, label: str) -> "StreamingFrame":
        return StreamingFrame(self.source, out_schema,
                              self._transforms + (fn,),
                              plan=f"{label}({self._plan})")

    # -- relational ops (per-batch; engine.ops semantics) ------------------
    def select(self, names: Sequence[str]) -> "StreamingFrame":
        # materialize FIRST: a one-shot iterable consumed by the schema
        # check would leave every batch selecting zero columns
        names = list(names)
        out_schema = self._schema.select(names)
        return self._chain(lambda df: df.select(names), out_schema,
                           f"select{tuple(names)}")

    def map_blocks(self, fetches, trim: bool = False,
                   executor=None) -> "StreamingFrame":
        """Per-batch ``map_blocks`` (lazy-op semantics, forced by the
        pump). The fetches resolve to ONE canonical Computation here, so
        batches share its compile cache."""
        comp = _ops.cached_map_computation(fetches, self._schema,
                                           block_level=True)
        out_schema = _ops._validate_map(comp, self._schema,
                                        block_level=True, trim=trim)
        return self._chain(
            lambda df: _ops.map_blocks(comp, df, trim=trim,
                                       executor=executor),
            out_schema, "map_blocks")

    def map_rows(self, fetches, executor=None) -> "StreamingFrame":
        comp = _ops.cached_map_computation(fetches, self._schema,
                                           block_level=False)
        out_schema = _ops._validate_map(comp, self._schema,
                                        block_level=False, trim=False)
        return self._chain(
            lambda df: _ops.map_rows(comp, df, executor=executor),
            out_schema, "map_rows")

    def filter_rows(self, predicate, executor=None) -> "StreamingFrame":
        comp = _ops._filter_computation(predicate, self._schema)
        return self._chain(
            lambda df: _ops.filter_rows(comp, df, executor=executor),
            self._schema, "filter_rows")

    # TensorFrame spells it `filter`; keep the alias for symmetry
    filter = filter_rows

    def join(self, table, on, how: str = "left",
             indicator: Optional[str] = None) -> "StreamingFrame":
        """Enrich each batch against a STATIC table (the stream-table
        join): the right side factorizes into a broadcast
        :class:`~..relational.join.BuildTable` ONCE, here at definition
        time — schema validation included — and every batch probes it
        through the same per-block path the batch ``broadcast_join``
        uses (one fused device gather per block, resilient executor,
        ledger-admitted build residency). Default ``how="left"``: an
        enrichment must not drop stream rows silently; pass
        ``how="inner"`` to keep only matches. See ``docs/joins.md``."""
        from ..relational.join import (BuildTable, broadcast_join,
                                       join_schema)
        build = BuildTable(table, on)
        out_schema = join_schema(self._schema, build.schema, build.on,
                                 how, indicator)
        return self._chain(
            lambda df: broadcast_join(df, build=build, how=how,
                                      indicator=indicator),
            out_schema, f"join[{how}]")

    # -- aggregation handoff -----------------------------------------------
    def group_by(self, *keys: str) -> "GroupedStream":
        for k in keys:
            f = self._schema.get(k)
            if f is None:
                raise KeyError(
                    f"No column {k!r}; columns: {self._schema.names}")
            if f.sql_rank != 0:
                raise ValueError(
                    f"group_by key {k!r} must be a scalar column")
        if not keys:
            raise ValueError("group_by needs at least one key column")
        return GroupedStream(self, list(keys))

    # -- execution ---------------------------------------------------------
    def start(self, sink=None, on_update=None, name: Optional[str] = None,
              max_buffered: Optional[int] = None, batch_rows=None):
        """Build a :class:`~.runtime.StreamHandle` pumping this stream's
        batches: each batch's resulting frame is buffered for
        ``collect_updates()`` and delivered to ``sink`` / ``on_update``.
        ``batch_rows`` sizes batches: ``"adaptive"`` coalesces
        already-available source blocks toward a runtime-feedback row
        target (``docs/adaptive.md``), an int pins a fixed target,
        ``None`` keeps one source block per batch. Coalescing changes
        batch BOUNDARIES — use it for row-local chains; a per-batch
        cross-row ``map_blocks`` (``x - x.mean()``) sees the merged
        batch (``docs/streaming.md``)."""
        from .runtime import StreamHandle
        return StreamHandle(self, sink=sink, on_update=on_update,
                            name=name, max_buffered=max_buffered,
                            batch_rows=batch_rows)


class GroupedStream:
    """``StreamingFrame.group_by(...)`` result — consumed by
    :meth:`aggregate` (the incremental keyed-aggregation layer)."""

    def __init__(self, frame: StreamingFrame, keys: List[str]):
        self.frame = frame
        self.keys = keys

    def aggregate(self, fetches, window=None, time_col: Optional[str] = None,
                  watermark_delay: float = 0.0,
                  max_state_rows: Optional[int] = None, mesh=None):
        """Incremental keyed aggregation over the stream: ``fetches`` is
        a ``{column: combiner-name}`` mapping (sum/min/max/prod — the
        monoid set ``aggregate`` and ``daggregate`` serve), combined
        per batch in one segment-reduce dispatch per column against
        bounded device-resident state. ``window``
        (:func:`~.aggregate.tumbling` / :func:`~.aggregate.sliding`)
        plus ``time_col`` enable windowing; ``watermark_delay`` is the
        allowed event-time lateness before a window emits and evicts.
        ``mesh=`` (a :class:`~..parallel.mesh.DeviceMesh`) scales the
        per-batch fold past one device: each batch's partial tables
        compute as ONE fused GSPMD program over the mesh's data axis
        (the ``daggregate`` fragment — ``docs/plan.md``).
        Returns a :class:`~.aggregate.StreamingAggregation`; call
        ``.start()`` on it. See ``docs/streaming.md``."""
        from .aggregate import StreamingAggregation
        return StreamingAggregation(
            self.frame, self.keys, fetches, window=window,
            time_col=time_col, watermark_delay=watermark_delay,
            max_state_rows=max_state_rows, mesh=mesh)

    def __repr__(self):
        return f"GroupedStream(keys={self.keys}, frame={self.frame!r})"
