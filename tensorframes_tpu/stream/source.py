"""Block sources: where a stream's batches come from.

A :class:`BlockSource` is the streaming analogue of a ``TensorFrame``
constructor — instead of a finite list of blocks materialized up front,
it yields schema-checked :class:`~..frame.Block`s over time. Three
concrete sources cover the scenario family (dashboards, feature
pipelines, replay):

- :class:`GeneratorSource` — any Python iterable/generator of blocks or
  column dicts (synthetic feeds, adapters for message buses);
- :class:`QueueSource` — a bounded in-memory queue another thread
  ``put()``s into; the bound IS the ingestion backpressure (a full
  queue blocks or rejects the producer, it never buffers unboundedly);
- :class:`ParquetTailSource` — follows a parquet file as row groups are
  appended, re-reading NOTHING: consumed row groups are skipped via
  ``io.read_parquet(row_group_offset=...)``, so each poll costs only
  the new groups (plus one footer read).

Every source checks each produced block against its schema
(:func:`check_block`) — a producer that drifts (missing column, wrong
dtype) fails at the source boundary with a named error, not deep inside
a compiled dispatch.

The pull contract (driven by :class:`~.runtime.StreamHandle`):
``poll(timeout)`` returns the next :class:`Block` or ``None`` when
nothing is available yet; ``done()`` reports permanent exhaustion
(finite sources / closed queues), which is what lets a finite stream
terminate and flush its windows.
"""

from __future__ import annotations

import queue as _queue
import threading
import time
from collections import deque
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Union

import numpy as np

from ..frame import Block
from ..schema import Schema
from ..utils.logging import get_logger

__all__ = ["SchemaMismatch", "BlockSource", "GeneratorSource",
           "QueueSource", "ParquetTailSource", "check_block"]

_log = get_logger("stream.source")


class SchemaMismatch(ValueError):
    """A source produced a block that does not match its declared schema
    (missing/extra column or wrong storage dtype). Raised at the source
    boundary — classified permanent, so a drifting producer poisons its
    batch (skipped-and-counted), never wedges the retry loop."""


def _as_block(data: Union[Block, Dict[str, np.ndarray]]) -> Block:
    """Accept a Block or a dict of columns (arrays coerced)."""
    if isinstance(data, Block):
        return data
    if isinstance(data, dict):
        cols = {}
        for n, c in data.items():
            cols[n] = c if isinstance(c, list) else np.asarray(c)
        return Block(cols)
    raise TypeError(
        f"Source produced {type(data).__name__}; expected a Block or a "
        f"dict of columns")


def check_block(schema: Schema, block: Block) -> Block:
    """Validate a produced block against the source schema.

    Column NAMES must match exactly (no missing, no extras — a silent
    extra column would change downstream ``trim``/select semantics) and
    dense columns must arrive in the field's storage dtype. Ragged
    (list-backed) columns skip the dtype check — their cells are
    validated lazily by the ops that consume them.
    """
    missing = [f.name for f in schema if f.name not in block.columns]
    extra = [n for n in block.columns if n not in schema]
    if missing or extra:
        raise SchemaMismatch(
            f"block columns {sorted(block.columns)} do not match the "
            f"stream schema {schema.names}"
            + (f"; missing {missing}" if missing else "")
            + (f"; unexpected {extra}" if extra else ""))
    for f in schema:
        col = block.columns[f.name]
        if not isinstance(col, np.ndarray):
            continue  # ragged: cells checked by the consuming op
        expect = np.dtype(f.dtype.np_storage)
        if col.dtype != expect:
            raise SchemaMismatch(
                f"column {f.name!r} arrived as {col.dtype}, schema "
                f"declares {expect} ({f.dtype.name}); cast at the "
                f"producer — streams never cast implicitly")
    return block


class BlockSource:
    """Base protocol for stream sources (see the module docstring).

    Subclasses implement :meth:`poll` / :meth:`done` and expose
    :attr:`schema`; :meth:`close` is optional cleanup.
    """

    @property
    def schema(self) -> Schema:
        raise NotImplementedError

    def poll(self, timeout: float = 0.0) -> Optional[Block]:
        """The next block, or ``None`` when nothing is available within
        ``timeout`` seconds (0 = non-blocking)."""
        raise NotImplementedError

    def done(self) -> bool:
        """True once the source can never produce another block."""
        return False

    def close(self) -> None:
        """Release any resources; idempotent."""

    def __repr__(self):
        return f"{type(self).__name__}(schema={self.schema.names})"


class GeneratorSource(BlockSource):
    """Wrap any iterable of blocks / column dicts as a source.

    The schema is taken from ``schema=`` or inferred from the first
    produced block (``Schema.from_numpy_columns``); every block is
    checked against it. Finite iterables end the stream cleanly
    (``done()`` turns True at ``StopIteration``).
    """

    def __init__(self, it: Iterable, schema: Optional[Schema] = None):
        self._it: Iterator = iter(it)
        self._schema = schema
        self._done = False
        self._peeked: Optional[Block] = None

    def _infer(self, block: Block) -> Schema:
        dense = {n: c for n, c in block.columns.items()
                 if isinstance(c, np.ndarray)}
        if len(dense) != len(block.columns):
            raise SchemaMismatch(
                "cannot infer a schema from a block with ragged "
                "columns; pass schema= to GeneratorSource")
        return Schema.from_numpy_columns(dense)

    @property
    def schema(self) -> Schema:
        if self._schema is None:
            # peek one block to type the stream (held for the next poll)
            b = self.poll()
            if b is None:
                raise RuntimeError(
                    "GeneratorSource needs schema= when the iterator is "
                    "empty or not ready at definition time")
            self._peeked = b
        return self._schema

    def poll(self, timeout: float = 0.0) -> Optional[Block]:
        if self._peeked is not None:
            b, self._peeked = self._peeked, None
            return b
        if self._done:
            return None
        try:
            data = next(self._it)
        except StopIteration:
            self._done = True
            return None
        b = _as_block(data)
        if self._schema is None:
            self._schema = self._infer(b)
        return check_block(self._schema, b)

    def done(self) -> bool:
        return self._done and self._peeked is None


class QueueSource(BlockSource):
    """A bounded in-memory queue source — the producer-side API.

    ``put()`` converts + schema-checks at the PRODUCER (so a drifting
    producer hears about it synchronously) and blocks when the queue is
    at ``maxsize`` — the queue bound is the stream's ingestion
    backpressure; with ``timeout`` it raises ``queue.Full`` instead.
    ``close()`` ends the stream once the queued blocks drain.
    """

    def __init__(self, schema: Schema, maxsize: int = 64):
        if maxsize < 1:
            raise ValueError(f"maxsize must be >= 1, got {maxsize}")
        self._schema = schema
        self._q: "_queue.Queue[Block]" = _queue.Queue(maxsize=maxsize)
        self._closed = threading.Event()

    @property
    def schema(self) -> Schema:
        return self._schema

    def put(self, data: Union[Block, Dict[str, np.ndarray]],
            timeout: Optional[float] = None) -> None:
        """Enqueue one block (or dict of columns). Blocks while the
        queue is full (backpressure); ``timeout`` bounds the wait and
        raises ``queue.Full``. Raises after :meth:`close`."""
        if self._closed.is_set():
            raise RuntimeError("QueueSource is closed")
        b = check_block(self._schema, _as_block(data))
        self._q.put(b, block=True, timeout=timeout)

    def poll(self, timeout: float = 0.0) -> Optional[Block]:
        try:
            if timeout and timeout > 0:
                return self._q.get(block=True, timeout=timeout)
            return self._q.get_nowait()
        except _queue.Empty:
            return None

    def qsize(self) -> int:
        return self._q.qsize()

    def done(self) -> bool:
        return self._closed.is_set() and self._q.empty()

    def close(self) -> None:
        self._closed.set()


class ParquetTailSource(BlockSource):
    """Follow a parquet file, one block per NEW row group.

    Consumed row groups are never re-read: each poll reads the footer
    (row-group count only) and, when the file has grown, loads just the
    new groups via ``io.read_parquet(row_group_offset=consumed)``. A
    writer that appends row groups (or atomically replaces the file
    with a longer one, the parquet idiom) feeds the stream incrementally.

    ``follow=False`` makes the source FINITE: it drains the row groups
    present as polling proceeds and reports ``done()`` once the count at
    construction time is consumed — the replay mode the equivalence
    tests use. The file must exist at construction (the schema is read
    from its footer, via an empty typed frame).
    """

    def __init__(self, path: str, columns: Optional[Sequence[str]] = None,
                 follow: bool = True,
                 skip_unreadable_after_s: float = 2.0):
        from .. import io as _io

        self._path = path
        self._columns = list(columns) if columns is not None else None
        self._follow = follow
        self._consumed = 0
        self._buffer: "deque[Block]" = deque()
        self._end_at: Optional[int] = None
        self._fail_streak = 0
        self._first_fail_at = 0.0
        # wall-clock floor before a repeatedly-unreadable row group is
        # skipped (loud data loss beats a livelocked tail)
        self._skip_after_s = float(skip_unreadable_after_s)
        total = self._row_groups()
        if not follow:
            self._end_at = total
        # schema probe: offset past the end hits read_parquet's
        # empty-table path, typing the columns from the parquet footer
        # without touching a single row group
        probe = _io.read_parquet(path, columns=self._columns,
                                 row_group_offset=max(total, 1))
        self._schema = probe.schema

    def _row_groups(self) -> int:
        import pyarrow.parquet as pq

        with pq.ParquetFile(self._path) as pf:
            return pf.num_row_groups

    @property
    def schema(self) -> Schema:
        return self._schema

    def poll(self, timeout: float = 0.0) -> Optional[Block]:
        if self._buffer:
            return check_block(self._schema, self._buffer.popleft())
        if self.done():
            return None
        from .. import io as _io

        try:
            total = self._row_groups()
        except Exception as e:
            # mid-replace window: a non-atomic writer leaves a missing
            # or truncated file whose footer read raises OSError OR
            # pyarrow ArrowInvalid ("magic bytes not found") — both are
            # transient here, healed by the writer's next footer
            _log.debug("parquet tail %s unreadable this poll: %s",
                       self._path, e)
            return None
        if self._end_at is not None:
            total = min(total, self._end_at)
        if total <= self._consumed:
            return None
        # after any failure, degrade to ONE group per read: a failing
        # single-group read is attributed to exactly that group, so the
        # eventual skip can never discard a readable group that merely
        # shared a multi-group read with a corrupt later one
        read_n = (total - self._consumed if self._fail_streak == 0
                  else 1)
        try:
            # the EAGER reader, deliberately: public read_parquet is
            # footer-lazy (docs/plan.md), which would (a) pay a second
            # footer read at blocks() — this source's contract is ONE
            # footer read per poll — and (b) move decode errors outside
            # this guard, livelocking the corrupt-group skip machinery
            frame = _io._read_parquet_eager(
                self._path, columns=self._columns, num_partitions=None,
                pad_ragged=False, row_group_offset=self._consumed,
                row_group_limit=read_n)
            blocks = frame.blocks()
        except Exception:
            # mid-replace windows heal on the next poll; a PERSISTENTLY
            # unreadable group (corrupt append) must not livelock the
            # stream re-raising at the same offset forever. Step past
            # ONE group (its rows are lost, loudly) only once
            # SINGLE-GROUP reads of it have failed repeatedly AND for a
            # wall-clock floor — tight poll loops alone (run()'s 10ms
            # default) can never discard a group a slow writer is still
            # replacing. The raise is counted by the runtime's
            # skip-and-count path.
            now = time.monotonic()
            if self._fail_streak == 0:
                self._first_fail_at = now
            self._fail_streak += 1
            if self._fail_streak >= 3 and read_n == 1 and \
                    now - self._first_fail_at >= self._skip_after_s:
                _log.error(
                    "parquet tail %s: row group %d unreadable for "
                    "%.1fs (%d attempts); skipping it (its rows are "
                    "lost)", self._path, self._consumed,
                    now - self._first_fail_at, self._fail_streak)
                self._consumed += 1
                self._fail_streak = 0
            raise
        self._fail_streak = 0
        # one block per row group; a finite (follow=False) source whose
        # file grew mid-replay keeps only the groups inside its end mark
        self._buffer.extend(blocks[: total - self._consumed])
        self._consumed = min(total, self._consumed + read_n)
        if self._buffer:
            return check_block(self._schema, self._buffer.popleft())
        return None

    def done(self) -> bool:
        return (self._end_at is not None
                and self._consumed >= self._end_at
                and not self._buffer)
