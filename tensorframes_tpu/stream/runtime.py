"""The stream pump: batches through the engine with failure isolation.

:class:`StreamHandle` drives one stream definition (a
:class:`~.frame.StreamingFrame`, optionally terminated by a
:class:`~.aggregate.StreamingAggregation`): it polls the source, wraps
each block as a one-partition ``TensorFrame``, applies the per-batch
transforms (which stream through the pipelined engine like any finite
forcing), folds aggregations, and delivers outputs to sinks.

**Failure isolation** (the streaming row of ``docs/resilience.md``'s
matrix): each batch runs under the process
:class:`~..resilience.RetryPolicy` — transient failures retry with
backoff exactly like a block dispatch; a batch that still fails (a
permanent error, an unsplittable OOM, an exhausted retry budget, or the
deterministic ``batch`` fault site) is **skipped and counted**
(``stream.batches_skipped``, a ``batch_skip`` trace event with the
classified kind) and the stream keeps running. A poisoned batch can
never kill the stream; ``TFT_STREAM_FAIL_FAST=1`` flips skipping off
for debugging (the classified error raises out of ``step()``). Two
classes of error are never counted as poisoned data: a ``device_lost``
is structural (the elastic layer shrank the mesh; the batch retries
once on the survivors — and when a recovered device is re-admitted,
``parallel.elastic.admit_devices``, the pump picks up the grown mesh at
its next batch's dispatch boundary automatically), and a
``preempted``/``cancelled`` interruption is the operator stopping work
(it raises out of ``step()`` instead of incrementing the skip counter).

**Backpressure & multi-tenant composition**: bounded sources
(``QueueSource``) push back on producers; inside a batch, the engine's
own pipelined window bounds in-flight blocks. When the serving layer's
:class:`~..engine.pipeline.SlotPool` is installed, the pump leases one
slot for each single-block batch (exactly the case where the engine's
per-block leasing does not engage), so streams and scheduled queries
share ONE global in-flight bound; waits are counted in
``stream.slot_waits`` and honor the ambient resilience deadline.
Multi-block batches lease per block through the engine as usual —
never both, which is what keeps the leasing deadlock-free.

**Observability**: each batch runs inside a ``stream.batch`` query
trace (the forcing's block/retry/compile events correlate to it);
always-on counters (``stream.batches`` / ``stream.rows`` /
``stream.batches_skipped`` / ``stream.late_rows`` /
``stream.windows_emitted``); live per-stream gauges on the Prometheus
endpoint (``tft_stream_*``: batch lag, watermark, state rows/bytes,
skipped batches) via a metrics provider registered while handles are
alive. ``handle.metrics()`` returns the same numbers as a dict.
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from ..engine import pipeline as _pipeline
from ..frame import TensorFrame
from ..observability import baseline as _baseline
from ..observability import events as _obs
from ..observability import metrics as _metrics
from ..observability import timeline as _timeline
from ..resilience import invariants as _invariants
from ..resilience import (QueryInterrupted, check_deadline,
                          default_policy, env_bool, env_int, error_kind,
                          faults)
from ..utils.logging import get_logger
from ..utils.tracing import counters, gauge, span

__all__ = ["StreamHandle", "live_handles"]

_log = get_logger("stream.runtime")

# live handles for the metrics provider (weak: a dropped handle
# unregisters itself by dying)
_live_lock = threading.Lock()
_live: "weakref.WeakSet[StreamHandle]" = weakref.WeakSet()
_provider_registered = False


def live_handles() -> List["StreamHandle"]:
    """Every live stream handle (``tft.health()``'s stream section and
    the metrics provider read the same set)."""
    with _live_lock:
        return list(_live)


def _register_provider() -> None:
    global _provider_registered
    with _live_lock:
        if _provider_registered:
            return
        _provider_registered = True
    _metrics.register_metrics_provider("stream", _render_metrics)


def _render_metrics() -> List[str]:
    with _live_lock:
        handles = list(_live)
    lines: List[str] = []
    if not handles:
        return lines
    lines.append("# HELP tft_stream_batches_total Batches processed per "
                 "stream (skipped ones excluded).")
    lines.append("# TYPE tft_stream_batches_total counter")
    rows: List[str] = ["# TYPE tft_stream_rows_total counter"]
    skipped: List[str] = ["# TYPE tft_stream_skipped_total counter"]
    late: List[str] = ["# TYPE tft_stream_late_rows_total counter"]
    state_rows: List[str] = [
        "# HELP tft_stream_state_rows Live aggregation state rows "
        "(device-resident) per stream.",
        "# TYPE tft_stream_state_rows gauge",
    ]
    state_bytes: List[str] = ["# TYPE tft_stream_state_bytes gauge"]
    watermark: List[str] = ["# TYPE tft_stream_watermark gauge"]
    lag: List[str] = ["# TYPE tft_stream_batch_lag_seconds gauge"]
    for h in handles:
        m = h.metrics()
        lab = f'stream="{_metrics._escape_label(h.name)}"'
        lines.append(f"tft_stream_batches_total{{{lab}}} {m['batches']}")
        rows.append(f"tft_stream_rows_total{{{lab}}} {m['rows']}")
        skipped.append(
            f"tft_stream_skipped_total{{{lab}}} {m['batches_skipped']}")
        late.append(f"tft_stream_late_rows_total{{{lab}}} "
                    f"{m['late_rows']}")
        state_rows.append(
            f"tft_stream_state_rows{{{lab}}} {m['state_rows']}")
        state_bytes.append(
            f"tft_stream_state_bytes{{{lab}}} {m['state_bytes']}")
        if m["watermark"] is not None:
            watermark.append(
                f"tft_stream_watermark{{{lab}}} {m['watermark']}")
        if m["batch_lag_s"] is not None:
            lag.append(f"tft_stream_batch_lag_seconds{{{lab}}} "
                       f"{m['batch_lag_s']:.6f}")
    out = lines + rows + skipped + late + state_rows + state_bytes
    # families with no samples this scrape render nothing, not a bare
    # TYPE header
    if len(watermark) > 1:
        out += watermark
    if len(lag) > 1:
        out += lag
    return out


class StreamHandle:
    """One running stream: pump, sinks, metrics. Created by
    ``StreamingFrame.start()`` / ``StreamingAggregation.start()``.

    Drive it synchronously — :meth:`step` processes at most one batch,
    :meth:`run` loops until exhaustion/limits — or start the background
    pump thread with :meth:`start_background`. Outputs buffer for
    :meth:`collect_updates` (bounded; overflow drops oldest, counted in
    ``stream.updates_dropped``) and flow to the ``sink`` object
    (``write(frame)``/``close()``) and the ``on_update`` callback.
    """

    def __init__(self, sframe, aggregation=None, sink=None,
                 on_update: Optional[Callable[[TensorFrame], None]] = None,
                 name: Optional[str] = None,
                 max_buffered: Optional[int] = None,
                 batch_rows=None):
        self._sframe = sframe
        self._agg = aggregation
        self._sink = sink
        self._on_update = on_update
        self.name = name or f"stream-{id(self) & 0xffff:x}"
        # adaptive batch sizing (docs/adaptive.md): "adaptive" sizes
        # batches from runtime feedback (AIMD over the measured batch
        # wall inside the ledger ceiling), an int pins a fixed row
        # target; None (the default) processes one source block per
        # batch, bit-identical to every prior release. Both opt-in
        # modes degrade to pass-through under TFT_ADAPTIVE=0.
        self._batcher = None
        self._fixed_rows: Optional[int] = None
        if batch_rows == "adaptive":
            from ..memory.estimate import schema_row_bytes
            try:
                rb = max(int(schema_row_bytes(sframe.source.schema)), 1)
            except Exception:  # noqa: BLE001 - sizing hint only
                rb = 8
            from ..plan.adaptive import AdaptiveBatcher
            self._batcher = AdaptiveBatcher(row_bytes=rb)
        elif batch_rows is not None:
            self._fixed_rows = max(int(batch_rows), 1)
        cap = (max_buffered if max_buffered is not None
               else env_int("TFT_STREAM_BUFFER", 1024))
        self._updates: "deque[TensorFrame]" = deque(maxlen=max(1, cap))
        self._lock = threading.Lock()
        self._batches = 0
        self._rows = 0
        self._skipped = 0
        self._last_batch_s: Optional[float] = None
        self._last_done_at: Optional[float] = None
        self._finalized = False
        self._stopped = False
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        # the error that stopped a background pump (fail-fast mode)
        self.error: Optional[BaseException] = None
        with _live_lock:
            _live.add(self)
        _register_provider()

    # -- properties --------------------------------------------------------
    @property
    def schema(self):
        """The OUTPUT schema (aggregation's when terminal, else the
        transformed frame's)."""
        return (self._agg.schema if self._agg is not None
                else self._sframe.schema)

    def done(self) -> bool:
        """Source permanently exhausted and final windows flushed."""
        return self._finalized or self._stopped

    # -- pump --------------------------------------------------------------
    def step(self, timeout: float = 0.0) -> bool:
        """Process at most one batch; returns True when one was consumed
        (even if it was skipped). ``timeout`` bounds the source poll."""
        if self.done():
            return False
        try:
            block = self._sframe.source.poll(timeout)
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:
            # a block the source rejects (schema drift, decode error) is
            # a poisoned batch too: skipped-and-counted, never fatal —
            # the offending item was consumed, so the stream proceeds
            kind = error_kind(e)
            counters.inc("stream.batches_skipped")
            with self._lock:
                self._skipped += 1
            _obs.add_event("batch_skip", name=self.name, site="source",
                           error=type(e).__name__, kind=kind)
            if env_bool("TFT_STREAM_FAIL_FAST", False):
                raise
            _log.error(
                "stream %s: source rejected a batch (%s: %s; classified "
                "%s); skipped — the stream continues", self.name,
                type(e).__name__, e, kind)
            return True
        if block is None:
            if self._sframe.source.done():
                self._finalize()
            return False
        if self._batcher is not None or self._fixed_rows is not None:
            block = self._fill_batch(block)
        processed_before = self._batches
        self._process(block)
        # batch-boundary quiesce point (resilience/invariants.py):
        # between batches every lease is back in the pool and the
        # ledger balances; catching a leak HERE names the batch that
        # caused it instead of whichever query closes last
        _invariants.audit("stream.batch")
        if self._batcher is not None and self._last_batch_s is not None \
                and self._batches > processed_before:
            # only a batch that actually EXECUTED feeds the sizer: a
            # poisoned/skipped batch leaves _last_batch_s at the prior
            # batch's wall, and observing that pair would ratchet the
            # target on work that never ran
            self._batcher.observe(block.num_rows, self._last_batch_s)
        return True

    def _batch_target(self, buffered_rows: int) -> bool:
        """Keep filling the current batch? (docs/adaptive.md)"""
        from ..plan import adaptive as _adaptive
        if not _adaptive.enabled():
            return False  # TFT_ADAPTIVE=0: one source block per batch
        if self._fixed_rows is not None:
            return buffered_rows < self._fixed_rows
        return self._batcher.want_more(buffered_rows)

    def _fill_batch(self, first):
        """Coalesce already-available source blocks up to the row
        target (never waits: a batch is whatever the source has NOW,
        so latency is untouched). A poisoned poll mid-fill counts its
        skip and the buffered rows still process."""
        bufs = [first]
        rows = first.num_rows
        while self._batch_target(rows):
            try:
                nxt = self._sframe.source.poll(0.0)
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                kind = error_kind(e)
                counters.inc("stream.batches_skipped")
                with self._lock:
                    self._skipped += 1
                _obs.add_event("batch_skip", name=self.name,
                               site="source", error=type(e).__name__,
                               kind=kind)
                if env_bool("TFT_STREAM_FAIL_FAST", False):
                    raise
                _log.error(
                    "stream %s: source rejected a batch mid-fill "
                    "(%s: %s; classified %s); skipped — the buffered "
                    "rows still process", self.name,
                    type(e).__name__, e, kind)
                break
            if nxt is None:
                break
            bufs.append(nxt)
            rows += nxt.num_rows
        if len(bufs) == 1:
            return first
        from ..frame import Block
        counters.inc("stream.batches_coalesced", len(bufs) - 1)
        return Block.concat(bufs, self._sframe.source.schema)

    def run(self, max_batches: Optional[int] = None,
            timeout_s: Optional[float] = None,
            poll_interval: float = 0.01) -> int:
        """Pump until the source is exhausted (finite streams), or until
        ``max_batches`` / ``timeout_s``; returns batches consumed."""
        n = 0
        give_up = (time.monotonic() + timeout_s
                   if timeout_s is not None else None)
        while not self.done():
            if max_batches is not None and n >= max_batches:
                break
            if give_up is not None and time.monotonic() >= give_up:
                break
            if self.step(timeout=poll_interval):
                n += 1
        return n

    def start_background(self, poll_interval: float = 0.05
                         ) -> "StreamHandle":
        """Pump on a daemon thread until :meth:`stop` or exhaustion.
        An error escaping :meth:`step` (only possible under
        ``TFT_STREAM_FAIL_FAST=1`` — the skip path swallows everything
        else) stops the pump and lands on :attr:`error` instead of
        dying silently on the daemon thread."""
        if self._thread is not None:
            raise RuntimeError(f"stream {self.name!r} already pumping")

        def pump():
            while not self._stop_evt.is_set() and not self.done():
                try:
                    self.step(timeout=poll_interval)
                except Exception as e:
                    self.error = e
                    counters.inc("stream.pump_errors")
                    _log.error(
                        "stream %s: background pump stopped on %s: %s",
                        self.name, type(e).__name__, e)
                    return
            # fall out on stop/exhaustion; finalize happens in step()

        self._thread = threading.Thread(
            target=pump, name=f"tft-{self.name}", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        """Stop pumping and close the sink (without finalizing windows —
        use ``run()`` to exhaustion for a clean flush). Idempotent."""
        self._stop_evt.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
        self._stopped = True
        self._close_sink()

    # -- one batch ---------------------------------------------------------
    def _process(self, block) -> None:
        i = self._batches + self._skipped
        t0 = time.perf_counter()

        def attempt():
            faults.check("batch")
            df = TensorFrame.from_blocks([block],
                                         self._sframe.source.schema)
            df = self._sframe._apply(df)
            df.blocks()  # force the per-batch plan
            return df

        pool = None
        try:
            with _obs.query_trace("stream.batch", stream=self.name,
                                  batch=i):
                with span("stream.batch"):
                    # everything failure-prone — slot wait (deadline
                    # expiry), forcing, fold — lives inside this try: an
                    # escape anywhere must hit the skip path below,
                    # never kill a pump thread
                    pool = self._lease_slot()
                    try:
                        df = default_policy().call(attempt,
                                                   op="stream.batch")
                    except Exception as e:
                        # a device_lost error is structural, not
                        # poisoned data: the elastic layer has shrunk
                        # the mesh underneath it, so ONE re-attempt runs
                        # the batch on the surviving devices before the
                        # skip path gets to count it
                        if error_kind(e) != "device_lost":
                            raise
                        counters.inc("stream.device_lost_retries")
                        _obs.add_event("device_lost_retry",
                                       name=self.name, batch=i)
                        _log.warning(
                            "stream %s: batch %d hit a device loss "
                            "(%s); retrying once on the shrunken mesh",
                            self.name, i, e)
                        df = default_policy().call(attempt,
                                                   op="stream.batch")
                    # fold AFTER the retried forcing, exactly once: the
                    # retry policy must never wrap ingest, whose commit
                    # mutates window state (a retried ingest would
                    # double-count the batch). ingest is all-or-nothing,
                    # so a failure here skips the whole batch with live
                    # state untouched.
                    outputs = (self._agg.ingest(df)
                               if self._agg is not None else [df])
        except (KeyboardInterrupt, SystemExit, QueryInterrupted):
            # a cancel/preempt is the OPERATOR stopping work, not
            # poisoned data: counting it as a skipped batch would hide a
            # deliberate interruption inside the data-quality counter
            raise
        except Exception as e:
            kind = error_kind(e)
            counters.inc("stream.batches_skipped")
            with self._lock:
                self._skipped += 1
            _obs.add_event("batch_skip", name=self.name, batch=i,
                           error=type(e).__name__, kind=kind)
            from ..observability import flight as _flight
            _flight.record("stream.batch_skip", stream=self.name,
                           batch=i, error=type(e).__name__,
                           error_kind=kind)
            # durable query history: a poisoned batch is exactly the
            # record a post-mortem wants to find after the process dies
            from ..observability import history as _history
            _history.record_finish(
                f"{self.name}-b{i}", tenant=self.name,
                outcome="skipped", error=f"{type(e).__name__}: {e}",
                error_kind=kind, source="stream",
                summary=f"stream {self.name!r} batch {i} skipped")
            if env_bool("TFT_STREAM_FAIL_FAST", False):
                raise
            _log.error(
                "stream %s: batch %d poisoned (%s: %s; classified %s); "
                "skipped — the stream continues", self.name, i,
                type(e).__name__, e, kind)
            return
        finally:
            if pool is not None:
                pool.release()
        dt = time.perf_counter() - t0
        rows = sum(b.num_rows for b in df.blocks())
        with self._lock:
            self._batches += 1
            self._rows += rows
            self._last_batch_s = dt
            self._last_done_at = time.monotonic()
        counters.inc("stream.batches")
        counters.inc("stream.rows", rows)
        gauge("stream.batch_seconds", dt)
        # batch boundaries are the timeline's beat on streaming-only
        # processes (interval-gated; off-interval cost is one compare)
        _timeline.maybe_sample()
        if self._agg is not None and outputs:
            # durable query history: a window EMIT is the stream's
            # query-terminal moment (committed results left the
            # runtime) — per emit, never per batch, so plain pass-
            # through streams pay nothing here
            from ..observability import history as _history
            _history.record_finish(
                f"{self.name}-b{i}", tenant=self.name, outcome="ok",
                run_s=dt, total_s=dt, est_rows=rows, source="stream",
                summary=f"stream {self.name!r} batch {i}: "
                        f"{len(outputs)} window frame(s) emitted")
        for frame in outputs:
            self._deliver(frame)

    # -- slot-pool composition --------------------------------------------
    def _lease_slot(self):
        """Lease ONE pool slot per batch when a serving scheduler's
        :class:`~..engine.pipeline.SlotPool` is installed, so streams
        and scheduled queries share the global in-flight bound. Safe by
        construction: stream batches are single-block frames, which the
        engine runs on its serial path WITHOUT leasing (``run_pipelined``
        only leases multi-block pipelined streams) — the handle and the
        engine never both hold slots for the same batch, so a slots=1
        pool cannot deadlock against its own forcing. Waits honor the
        ambient resilience deadline. Returns the pool to release, or
        None."""
        pool = _pipeline.current_slot_pool()
        if pool is None:
            return None
        if pool.try_acquire():
            return pool
        counters.inc("stream.slot_waits")
        tr = _obs.current_trace()
        t0 = tr.clock() if tr is not None else 0.0
        # measured always-on (contended path only) for the sentinel's
        # per-query slot_wait_s attribution
        w0 = time.perf_counter()
        while not pool.try_acquire(timeout=0.05):
            check_deadline("stream.slot")
        _baseline.note_wait(time.perf_counter() - w0)
        if tr is not None:
            tr.add("slot_wait", ts=t0, dur=tr.clock() - t0)
        return pool

    # -- delivery ----------------------------------------------------------
    def _deliver(self, frame: TensorFrame) -> None:
        with self._lock:
            if len(self._updates) == self._updates.maxlen:
                counters.inc("stream.updates_dropped")
            self._updates.append(frame)
        if self._on_update is not None:
            try:
                self._on_update(frame)
            except Exception as e:
                counters.inc("stream.sink_errors")
                _log.error("stream %s: on_update callback failed: %s",
                           self.name, e)
        if self._sink is not None:
            try:
                self._sink.write(frame)
            except Exception as e:
                counters.inc("stream.sink_errors")
                _log.error("stream %s: sink write failed: %s",
                           self.name, e)

    def collect_updates(self) -> List[TensorFrame]:
        """Drain the buffered output frames (per-batch results, or
        emitted windows for aggregations) accumulated since the last
        call."""
        with self._lock:
            out = list(self._updates)
            self._updates.clear()
        return out

    def _finalize(self) -> None:
        if self._finalized:
            return
        self._finalized = True
        if self._agg is not None:
            try:
                frames = self._agg.finalize()
            except Exception as e:
                # a failed final flush must not kill the pump (or leave
                # the sink open): counted and logged, remaining windows
                # stay queryable through the aggregation object
                counters.inc("stream.finalize_errors")
                _log.error("stream %s: final window flush failed: %s",
                           self.name, e)
                frames = []
            for frame in frames:
                self._deliver(frame)
        self._close_sink()

    def _close_sink(self) -> None:
        sink = self._sink
        if sink is None:
            return
        close = getattr(sink, "close", None)
        if close is None:
            return
        try:
            close()
        except Exception as e:
            counters.inc("stream.sink_errors")
            _log.error("stream %s: sink close failed: %s", self.name, e)

    # -- introspection -----------------------------------------------------
    def metrics(self) -> Dict[str, Any]:
        """Live stream metrics (the dict twin of the ``tft_stream_*``
        Prometheus series)."""
        with self._lock:
            lag = (time.monotonic() - self._last_done_at
                   if self._last_done_at is not None else None)
            out = {
                "batches": self._batches,
                "rows": self._rows,
                "batches_skipped": self._skipped,
                "last_batch_s": self._last_batch_s,
                "batch_lag_s": lag,
                "late_rows": 0,
                "state_rows": 0,
                "state_bytes": 0,
                "live_windows": 0,
                "watermark": None,
                "windows_emitted": 0,
                "state_evictions": 0,
                "state_spills": 0,
                "state_faults": 0,
                "buffered_updates": len(self._updates),
            }
        if self._agg is not None:
            out["late_rows"] = self._agg.late_rows
            out["state_rows"] = self._agg.state_rows
            out["state_bytes"] = self._agg.state_bytes
            out["live_windows"] = self._agg.live_windows
            out["watermark"] = self._agg.watermark
            out["windows_emitted"] = self._agg.windows_emitted
            out["state_evictions"] = self._agg.state_evictions
            out["state_spills"] = self._agg.state_spills
            out["state_faults"] = self._agg.state_faults
        return out

    def __repr__(self):
        m = self.metrics()
        return (f"StreamHandle({self.name!r}, batches={m['batches']}, "
                f"skipped={m['batches_skipped']}, "
                f"state_rows={m['state_rows']}, done={self.done()})")
