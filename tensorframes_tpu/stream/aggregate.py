"""Incremental keyed aggregation: windows, watermarks, bounded state.

The streaming counterpart of ``aggregate``'s monoid path
(``engine.ops._monoid_aggregate``) and the mesh ``daggregate``: fetches
are a ``{column: combiner-name}`` mapping over the associative monoids
(sum / min / max / prod — ``parallel.collectives.COMBINERS``), so a
batch folds into running state EXACTLY (combine order is free).

Per batch, per live window present in the batch:

1. the batch rows' keys factorize to dense ids on the host
   (``engine.ops._factorize_keys`` — the same key→id shuffle
   replacement the finite aggregate uses);
2. each fetch column reduces in ONE device dispatch through
   ``engine.ops._segment_reduce`` — the same kernels the finite
   ``aggregate`` and the mesh ``daggregate`` program dispatch (the
   one-hot-matmul Pallas ``segment_sum`` for float sums on TPU, XLA
   segment primitives otherwise);
3. the per-batch partial merges into the window's **device-resident
   state table** with one cached compiled merge program (scatter-set of
   the old table + scatter-combine of the partial into the key-union
   table). Merge programs are jit-cached by signature — steady-state
   batches (same key universe, same batch profile) are pure cache hits,
   no retracing (``stream.merge_compiles`` counts builds).

**Windows & watermarks**: rows are assigned to tumbling or sliding
windows by an event-time column; the watermark trails the maximum
event time seen by ``watermark_delay``. A window whose end falls at or
below the watermark EMITS (one output frame: window_start + keys +
aggregates, keys lexicographically sorted) and its state is evicted —
state is bounded by the number of windows the watermark keeps open
times the live key cardinality. Rows for an already-closed window are
**late**: counted (``stream.late_rows``) and dropped, never resurrect
state. ``max_state_rows`` adds a hard cap on DEVICE-resident state
rows: under an active memory manager (``docs/memory.md``) the oldest
window SPILLS to pinned host buffers (``stream.state_spills``) —
staying logically live, faulting back on its next touch — and only
without a budget does it force-emit early
(``stream.state_evictions``), the pre-spill behavior.

Without a window the aggregation runs in **update mode**: one global
state table, and each batch emits the updated rows for the keys it
touched (the dashboard delta feed).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Mapping, NamedTuple, Optional, Tuple

import numpy as np

from .. import dtypes as _dt
from ..engine.ops import (InvalidTypeError, _factorize_keys, _field_spec,
                          _segment_reduce, _validate_monoid_fetches)
from ..frame import Block, TensorFrame
from ..observability import events as _obs
from ..schema import Field, Schema
from ..shape import Shape, Unknown
from ..utils.logging import get_logger
from ..utils.tracing import counters, span

__all__ = ["Window", "tumbling", "sliding", "StreamingAggregation",
           "WINDOW_COL"]

_log = get_logger("stream.aggregate")

# the window-start column prepended to every windowed emission
WINDOW_COL = "window_start"


class Window(NamedTuple):
    """An event-time window spec: ``size`` seconds (or whatever unit the
    time column carries) advancing every ``slide``. ``slide == size`` is
    tumbling; ``slide < size`` is sliding (each row lands in
    ``ceil(size/slide)`` windows). Window starts align to multiples of
    ``slide``; a row at time t belongs to windows with
    ``start <= t < start + size``."""

    size: float
    slide: float


def tumbling(size: float) -> Window:
    """Non-overlapping windows of ``size`` event-time units."""
    if size <= 0:
        raise ValueError(f"window size must be > 0, got {size}")
    return Window(float(size), float(size))


def sliding(size: float, slide: float) -> Window:
    """Overlapping windows: ``size`` long, a new one every ``slide``."""
    if size <= 0 or slide <= 0:
        raise ValueError(
            f"window size/slide must be > 0, got {size}/{slide}")
    if slide > size:
        raise ValueError(
            f"slide {slide} > size {size} would drop rows between "
            f"windows; use tumbling({slide}) or shrink the slide")
    return Window(float(size), float(slide))


class _WState:
    """One window's live state: host key table + device value tables.

    ``spilled`` state holds its value tables as pinned host numpy
    instead (``spill()``): logically identical — the merge programs
    accept host arrays and re-place them on the device at the next fold
    (the transparent fault-back) — but costing zero device bytes, which
    is what lets ``max_state_rows`` bound DEVICE state without
    force-emitting incomplete windows (``docs/memory.md``).

    A state is also a duck-typed entry in the global memory manager's
    LRU (the ``mem_*`` protocol, ``memory/manager.py``): under an active
    budget the LEDGER drives spills too — an admission squeeze anywhere
    in the process can push the coldest window to host, not just the
    stream's own ``max_state_rows`` cap. Registered at commit (weakly
    held: an emitted window's entry dies with the object).
    """

    __slots__ = ("keys_u", "values", "rows", "spilled", "on_spill",
                 "on_fault", "_spill_lock", "__weakref__")

    def __init__(self, keys_u: List[np.ndarray], values: Dict[str, object],
                 rows: int):
        self.keys_u = keys_u        # per key column: sorted unique values
        self.values = values        # fetch -> device array [rows, ...]
        self.rows = rows
        self.spilled = False
        # per-stream spill/fault accounting hooks (set when
        # ledger-registered)
        self.on_spill = None
        self.on_fault = None
        # the ledger LRU (its own lock) and the max_state_rows eviction
        # path (the aggregation's state lock) can both pick this state;
        # a per-state lock + re-check keeps one spill from counting (or
        # copying) twice
        self._spill_lock = threading.Lock()

    def spill(self) -> int:
        """Move the device value tables to pinned host buffers; returns
        the device bytes freed (0 when a concurrent spill won the
        race). Bit-identical round trip (the host view keeps the device
        dtype, bfloat16 included)."""
        from .. import memory as _memory
        with self._spill_lock:
            if self.spilled:
                return 0
            freed = 0
            for f, v in list(self.values.items()):
                if _memory.is_device_value(v):
                    freed += _memory.array_nbytes(v)
                    self.values[f] = _memory.to_pinned_host(v)
            self.spilled = True
        return freed

    # -- memory-ledger entry protocol (docs/memory.md) ---------------------
    def mem_name(self) -> str:
        return "stream-window"

    def mem_is_spilled(self) -> bool:
        return self.spilled

    def mem_device_bytes(self) -> int:
        if self.spilled:
            return 0
        from .. import memory as _memory
        return sum(_memory.array_nbytes(v) for v in self.values.values()
                   if _memory.is_device_value(v))

    def mem_host_bytes(self) -> int:
        if not self.spilled:
            return 0
        return sum(int(v.nbytes) for v in self.values.values()
                   if isinstance(v, np.ndarray))

    def mem_spill(self) -> int:
        """Ledger-driven spill (called under the ledger lock). The
        counters the manual ``max_state_rows`` path increments by hand
        come from the ``on_spill`` hook + the ledger's own accounting.
        ``spill()`` is race-guarded: a loser returns 0 and counts
        nothing."""
        freed = self.spill()
        if freed:
            cb = self.on_spill
            if cb is not None:
                cb(freed)
        return freed

    def mem_fault(self) -> int:
        """Restore the value tables to the device. The fold path faults
        lazily on its own (the merge programs accept host arrays), so
        this only runs when the ledger explicitly touches the entry.
        Same race guard as :meth:`spill` — a fault interleaving with a
        concurrent spill must not leave device arrays behind a
        ``spilled=True`` flag (the ledger would under-count them)."""
        import jax
        with self._spill_lock:
            if not self.spilled:
                return 0
            restored = 0
            for f, v in list(self.values.items()):
                if isinstance(v, np.ndarray):
                    self.values[f] = jax.device_put(v)
                    restored += int(v.nbytes)
            self.spilled = False
        if restored:
            cb = self.on_fault  # symmetric with the spill-side hook
            if cb is not None:
                cb(restored)
        return restored

    @property
    def nbytes(self) -> int:
        n = sum(int(np.asarray(k).nbytes) for k in self.keys_u)
        for v in self.values.values():
            nb = getattr(v, "nbytes", None)
            n += int(nb) if nb is not None else 0
        return n


# cached compiled merge programs: (combiner, M, G, H, tail, dtype) ->
# jitted fn. LRU-capped; every touch under the lock (jit itself is not).
_merge_cache: "OrderedDict[Tuple, object]" = OrderedDict()
_merge_lock = threading.Lock()
_MERGE_CACHE_CAP = 128


def _merge_program(cname: str, m: int, g: int, h: int,
                   tail: Tuple[int, ...], dtype):
    """The cached scatter-merge: old state [g,...] + batch partial
    [h,...] -> union table [m,...]. Every union position receives the
    old value (set) and/or the partial (combine against the monoid's
    neutral — the same per-combiner identity COMBINERS serves the mesh
    padding path), so overlap, old-only, and new-only keys are all
    exact."""
    import jax
    import jax.numpy as jnp

    from ..parallel.collectives import COMBINERS

    key = (cname, m, g, h, tail, str(dtype))
    with _merge_lock:
        fn = _merge_cache.get(key)
        if fn is not None:
            _merge_cache.move_to_end(key)
            return fn

    neutral = COMBINERS[cname].neutral(dtype)

    def prog(old, old_idx, new, new_idx):
        out = jnp.full((m,) + tail, neutral, dtype=old.dtype)
        out = out.at[old_idx].set(old)
        if cname == "sum":
            return out.at[new_idx].add(new)
        if cname == "prod":
            return out.at[new_idx].multiply(new)
        if cname == "min":
            return out.at[new_idx].min(new)
        return out.at[new_idx].max(new)

    fn = jax.jit(prog)
    with _merge_lock:
        fn = _merge_cache.setdefault(key, fn)
        _merge_cache.move_to_end(key)
        if len(_merge_cache) > _MERGE_CACHE_CAP:
            _merge_cache.popitem(last=False)
    counters.inc("stream.merge_compiles")
    return fn


class StreamingAggregation:
    """The terminal operator a :class:`~.frame.GroupedStream` builds —
    see the module docstring for semantics, ``docs/streaming.md`` for
    the user guide. Drive it with :meth:`start` (a
    :class:`~.runtime.StreamHandle` whose per-batch outputs are the
    emitted window frames)."""

    def __init__(self, upstream, keys: List[str],
                 col_combiners: Mapping[str, str],
                 window: Optional[Window] = None,
                 time_col: Optional[str] = None,
                 watermark_delay: float = 0.0,
                 max_state_rows: Optional[int] = None,
                 mesh=None):
        from ..engine.ops import _is_sketch
        if not (isinstance(col_combiners, Mapping) and col_combiners
                and all(isinstance(v, str) or _is_sketch(v)
                        for v in col_combiners.values())):
            raise TypeError(
                "streaming aggregate fetches must be a non-empty "
                "{column: combiner} mapping (the monoid form — "
                "sum/min/max/prod names or relational sketch "
                "combiners; arbitrary reduce computations cannot fold "
                "incrementally)")
        schema = upstream.schema
        self.upstream = upstream
        self.keys = list(keys)
        self.window = window
        self.time_col = time_col
        self.watermark_delay = float(watermark_delay)
        self.max_state_rows = max_state_rows
        # mesh=: per-batch window folds ride the fused mesh path — each
        # batch's keyed partial tables compute as ONE GSPMD program
        # (per-shard segment reduce + psum-family collective, the
        # daggregate fragment) over the mesh's data axis, so one
        # windowed stream scales past one device. The [groups, ...]
        # partial then merges into the same device-resident window
        # state. Float sums may reassociate across shards, like any
        # daggregate; integer folds stay exact. A 1-shard mesh (or
        # None) keeps the single-device segment-reduce dispatch, and so
        # do multi-process meshes — the batch arrays are process-local,
        # so sharding them as if they were the global rows would be
        # wrong (the same guard the lazy d-op recorder applies).
        import jax as _jax
        self.mesh = mesh if (mesh is not None
                             and mesh.num_data_shards > 1
                             and _jax.process_count() == 1) else None
        if watermark_delay < 0:
            raise ValueError(
                f"watermark_delay must be >= 0, got {watermark_delay}")
        if window is not None:
            if time_col is None:
                raise ValueError(
                    "windowed aggregation needs time_col= (the event-"
                    "time column windows and the watermark read)")
            f = schema.get(time_col)
            if f is None:
                raise KeyError(f"No time column {time_col!r}; columns: "
                               f"{schema.names}")
            if f.sql_rank != 0 or not f.dtype.tensor or \
                    np.dtype(f.dtype.np_storage).kind not in "iuf":
                raise InvalidTypeError(
                    f"time_col {time_col!r} must be a numeric scalar "
                    f"column, got {f.type_string()}")
            if WINDOW_COL in schema:
                raise ValueError(
                    f"column {WINDOW_COL!r} already exists; windowed "
                    f"emission needs that name for the window-start "
                    f"column")
        else:
            if time_col is not None:
                raise ValueError("time_col= only applies with window=")
            if max_state_rows is not None:
                raise ValueError(
                    "max_state_rows bounds WINDOW state; update-mode "
                    "(window=None) state is the live key cardinality — "
                    "cap the key universe upstream instead")
        if max_state_rows is not None and max_state_rows < 1:
            raise ValueError(
                f"max_state_rows must be >= 1, got {max_state_rows}")
        value_names = [n for n in schema.names
                       if n not in self.keys and n != time_col]
        _validate_monoid_fetches(col_combiners, value_names,
                                 "upstream with select()", schema=schema)
        self.col_combiners = dict(col_combiners)
        self.fetch_names = sorted(col_combiners)
        # sketch combiners (docs/joins.md): their per-window state
        # folds through the SAME scatter-merge machinery when the
        # sketch merges elementwise (HLL registers: max; quantile
        # bucket counts: sum); host-merged sketches (top-k) keep host
        # state tables — zero device bytes by construction
        self.sketches = {f: c for f, c in self.col_combiners.items()
                         if _is_sketch(c)}
        fields: List[Field] = []
        if window is not None:
            # window starts are always float64 (event-time arithmetic
            # happens in f64 regardless of the time column's storage)
            fields.append(Field(WINDOW_COL, _dt.double,
                                block_shape=Shape(Unknown), sql_rank=0))
        fields += [schema[k] for k in self.keys]
        for f in self.fetch_names:
            sk = self.sketches.get(f)
            if sk is not None:
                fields.extend(sk.out_fields(f, schema[f]))
            else:
                fields.append(Field(
                    f, schema[f].dtype,
                    block_shape=_field_spec(schema[f], True,
                                            "stream aggregate")
                    .with_lead(Unknown),
                    sql_rank=schema[f].sql_rank))
        self.out_schema = Schema(fields)
        # -- live state ----------------------------------------------------
        # _windows is read by metrics scrapes on other threads while the
        # pump folds batches: every structural mutation (commit, emit
        # pop) and every introspection snapshot happens under this lock
        self._state_lock = threading.Lock()
        self._windows: Dict[Optional[float], _WState] = {}
        self._max_ts = -np.inf
        # windows with start <= this are closed: emitted (watermark) or
        # force-evicted; rows mapping into them are late
        self._closed_through = -np.inf
        # emitted-but-not-yet-returned window frames: _emit appends
        # here the moment a window is popped, and ingest/finalize drain
        # it as their return value — so an exception AFTER some windows
        # of a batch emitted (a later window's D2H failing) can never
        # lose the already-popped ones; they ride out on the next
        # successful batch. Pump-thread only.
        self._emitted_backlog: List[TensorFrame] = []
        # per-instance twins of the global counters (the stream handle's
        # metrics are per-stream, the flat counters process-wide)
        self.late_rows = 0
        self.windows_emitted = 0
        self.state_evictions = 0
        self.state_spills = 0
        self.state_faults = 0

    # -- introspection (the runtime's metrics read these) -----------------
    @property
    def schema(self) -> Schema:
        return self.out_schema

    @property
    def state_rows(self) -> int:
        with self._state_lock:
            return sum(w.rows for w in self._windows.values())

    @property
    def state_bytes(self) -> int:
        with self._state_lock:
            return sum(w.nbytes for w in self._windows.values())

    @property
    def live_windows(self) -> int:
        with self._state_lock:
            return len(self._windows)

    @property
    def watermark(self) -> Optional[float]:
        if self.window is None or self._max_ts == -np.inf:
            return None
        return self._max_ts - self.watermark_delay

    # -- ingestion ---------------------------------------------------------
    def ingest(self, df: TensorFrame) -> List[TensorFrame]:
        """Fold one batch into state; returns the frames this batch
        caused to emit (closed windows, or the update-mode delta).

        ALL-OR-NOTHING: the batch folds into fresh staging state
        (:meth:`_fold` never mutates a live ``_WState``) and commits in
        one locked update at the end — an exception anywhere mid-fold
        (a failed dispatch, a bad column) leaves the live state exactly
        as it was, so the runtime's skip-and-count path drops the WHOLE
        batch and a retried batch can never double-count
        (``runtime.StreamHandle`` relies on this: the retry policy
        wraps only the forcing, and ingest runs exactly once after it).
        """
        blocks = df.blocks()
        merged = blocks[0] if len(blocks) == 1 \
            else Block.concat(blocks, df.schema)
        if merged.num_rows == 0:
            return []
        for k in self.keys:
            if merged.is_ragged(k) or merged.dense(k).ndim != 1:
                raise InvalidTypeError(
                    f"Key column {k!r} must be scalar-typed")
        key_arrays = [merged.dense(k) for k in self.keys]
        val_arrays = {f: merged.dense(f) for f in self.fetch_names}
        if self.window is None:
            state, touched = self._fold(self._windows.get(None),
                                        key_arrays, val_arrays)
            with self._state_lock:
                self._windows[None] = state
            self._register_state(state)
            return [self._update_frame(touched)]
        ts = np.asarray(merged.dense(self.time_col), np.float64)
        if ts.ndim != 1:
            raise InvalidTypeError(
                f"time_col {self.time_col!r} must be scalar per row")
        new_max = max(self._max_ts, float(ts.max()))
        size, slide = self.window.size, self.window.slide
        n_off = int(np.ceil(size / slide))
        q = np.floor(ts / slide)
        late = 0
        pending: Dict[Optional[float], _WState] = {}
        with span("stream.aggregate.ingest"):
            for i in range(n_off):
                starts = (q - i) * slide
                valid = ts < starts + size
                if not valid.any():
                    continue
                for s in np.unique(starts[valid]):
                    m = valid & (starts == s)
                    if s <= self._closed_through:
                        late += int(m.sum())
                        continue
                    s = float(s)
                    # a sliding batch can hit the same window from two
                    # offsets (disjoint row subsets): chain through the
                    # staged state
                    base = pending.get(s, self._windows.get(s))
                    pending[s], _ = self._fold(
                        base, [a[m] for a in key_arrays],
                        {f: v[m] for f, v in val_arrays.items()})
        # commit point: live state changes only once the WHOLE batch
        # folded cleanly
        with self._state_lock:
            self._windows.update(pending)
        for st in pending.values():
            self._register_state(st)
        self._max_ts = new_max
        if late:
            self.late_rows += late
            counters.inc("stream.late_rows", late)
            _obs.add_event("late_rows", rows=late,
                           watermark=self.watermark)
        self._emit_ready()
        self._evict_over_cap()
        return self._drain_backlog()

    def finalize(self) -> List[TensorFrame]:
        """Flush every live window (finite source drained): emitted in
        window order; update mode emits one full-table snapshot."""
        if self.window is None:
            with self._state_lock:
                state = self._windows.get(None)
            if state is None:
                return []
            return [self._update_frame(np.arange(state.rows))]
        with self._state_lock:
            remaining = sorted(k for k in self._windows)
        for s in remaining:
            self._emit(s)
            self._closed_through = max(self._closed_through, s)
        return self._drain_backlog()

    # -- internals ---------------------------------------------------------
    def _register_state(self, state: _WState) -> None:
        """Join the global memory LRU (PR 8 follow-on): the ledger —
        not just ``max_state_rows`` — drives this window's spills once
        a device budget is active. Registered OUTSIDE ``_state_lock``
        (the ledger takes its own lock and may spill immediately)."""
        from .. import memory as _memory
        mgr = _memory.active()
        if mgr is not None and mgr.spill_enabled:
            state.on_spill = self._note_ledger_spill
            state.on_fault = self._note_ledger_fault
            mgr.register(state)

    def _note_ledger_spill(self, freed: int) -> None:
        self.state_spills += 1
        counters.inc("stream.state_spills")
        _log.debug("memory ledger spilled a stream window (%d B) to "
                   "host; it stays live and faults back on its next "
                   "touch", freed)

    def _note_ledger_fault(self, restored: int) -> None:
        self.state_faults += 1
        counters.inc("stream.state_faults")

    def _fold(self, base: Optional[_WState],
              key_arrays: List[np.ndarray],
              val_arrays: Dict[str, np.ndarray]
              ) -> Tuple[_WState, np.ndarray]:
        """Fold one window's batch rows against ``base`` (possibly
        None), returning a FRESH ``_WState`` plus the union-table
        positions the batch touched (update mode reads them). Pure with
        respect to ``base`` — the merge programs write new device
        arrays — which is what makes :meth:`ingest` transactional."""
        import jax.numpy as jnp

        from .. import native as _native

        schema = self.upstream.schema
        fact = _factorize_keys(key_arrays)
        scalar_names = [f for f in self.fetch_names
                        if f not in self.sketches]
        converted = {}
        for f in scalar_names:
            v = val_arrays[f]
            dd = _dt.device_dtype(schema[f].dtype)
            if v.dtype != dd:
                v = _native.convert(v, dd)
            converted[f] = v
        parts = {}
        if self.mesh is not None and scalar_names:
            # the distributed-plan path: one fused GSPMD program per
            # batch (rows shard over the data axis, partial tables
            # combine with one collective) — docs/plan.md
            from ..plan import dist as _dplan
            mesh_parts = _dplan.mesh_segment_partial(
                self.mesh,
                {f: self.col_combiners[f] for f in scalar_names},
                fact.ids.astype(np.int32), converted, fact.num_groups)
            parts = {f: jnp.asarray(mesh_parts[f])
                     for f in scalar_names}
        elif scalar_names:
            with span("stream.aggregate.segment_reduce"):
                for f in scalar_names:
                    parts[f] = jnp.asarray(_segment_reduce(
                        self.col_combiners[f], converted[f], fact.ids,
                        fact.num_groups))
        if self.sketches:
            # sketch partials bucket/hash on the host (the cross-path
            # determinism contract, docs/joins.md); elementwise states
            # join the device-resident tables, host-merged states
            # (top-k) stay host numpy
            with span("stream.aggregate.sketch_fold"):
                for f, sk in self.sketches.items():
                    part = sk.block_partial(
                        np.asarray(val_arrays[f]), fact.ids,
                        fact.num_groups)
                    counters.inc("relational.sketch_folds")
                    parts[f] = (jnp.asarray(part)
                                if sk.elementwise is not None else part)
        if base is None:
            return _WState([np.asarray(u) for u in fact.uniques], parts,
                           fact.num_groups), np.arange(fact.num_groups)
        if base.spilled:
            # transparent fault-back: the merge programs re-place the
            # host tables on the device as part of the fold (the result
            # state is device-resident again)
            from .. import memory as _memory
            self.state_faults += 1
            counters.inc("stream.state_faults")
            mgr = _memory.active()
            if mgr is not None:
                mgr.note_fault(
                    sum(_memory.array_nbytes(v)
                        for v in base.values.values()),
                    name="stream-window")
        g, h = base.rows, fact.num_groups
        cat = [np.concatenate([o, n])
               for o, n in zip(base.keys_u, fact.uniques)]
        gf = _factorize_keys(cat)
        m = gf.num_groups
        idx_dt = np.int32 if m < 2 ** 31 else np.int64
        idx_old = gf.ids[:g].astype(idx_dt)
        idx_new = gf.ids[g:].astype(idx_dt)
        values: Dict[str, object] = {}
        with span("stream.aggregate.merge"):
            for f in self.fetch_names:
                old = base.values[f]
                sk = self.sketches.get(f)
                if sk is not None and sk.elementwise is None:
                    # host-merged sketch state (top-k): the union-table
                    # fold runs in numpy — never device-resident
                    values[f] = sk.merge_tables(
                        np.asarray(old), idx_old,
                        np.asarray(parts[f]), idx_new, m)
                    continue
                cname = (sk.elementwise if sk is not None
                         else self.col_combiners[f])
                # .shape/.dtype read device metadata only — never
                # np.asarray the state here, which would drag the whole
                # device-resident table to host every batch
                fn = _merge_program(cname, m, g, h,
                                    tuple(old.shape[1:]), old.dtype)
                values[f] = fn(old, idx_old, parts[f], idx_new)
        return _WState([np.asarray(u) for u in gf.uniques], values,
                       m), idx_new

    def _drain_backlog(self) -> List[TensorFrame]:
        out, self._emitted_backlog = self._emitted_backlog, []
        return out

    def _emit_ready(self) -> None:
        wm = self.watermark
        if wm is None:
            return
        size = self.window.size
        with self._state_lock:
            ready = sorted(k for k in self._windows if k + size <= wm)
        for s in ready:
            self._emit(s)
        self._closed_through = max(self._closed_through, wm - size)

    def _evict_over_cap(self) -> None:
        """Bound live DEVICE state to ``max_state_rows``.

        Under an active memory manager the oldest window SPILLS to
        pinned host buffers instead of force-emitting — the window
        stays logically live (late rows keep folding in after a
        transparent fault-back at the next touch) and only stops
        costing device bytes (``stream.state_spills``). Without a
        budget, the pre-spill behavior stands: the oldest window
        force-emits early (``stream.state_evictions``)."""
        if self.max_state_rows is None:
            return
        from .. import memory as _memory
        mgr = _memory.active()
        spill_ok = mgr is not None and mgr.spill_enabled
        while True:
            with self._state_lock:
                live = [(k, w) for k, w in self._windows.items()
                        if not w.spilled]
                total = sum(w.rows for _, w in live)
                if total <= self.max_state_rows or not live:
                    return
                oldest = min(k for k, _ in live)
                state = self._windows[oldest]
                rows = state.rows
                if spill_ok:
                    freed = state.spill()
            if spill_ok:
                if freed:  # a concurrent ledger spill may have won
                    self.state_spills += 1
                    counters.inc("stream.state_spills")
                    mgr.note_spill(freed, name=f"stream-window@{oldest}")
                    _log.debug(
                        "stream state over max_state_rows=%d; spilled "
                        "window %s (%d rows, %d B) to host — it stays "
                        "live and faults back on the next touch",
                        self.max_state_rows, oldest, rows, freed)
                continue
            self.state_evictions += 1
            counters.inc("stream.state_evictions")
            _obs.add_event("state_eviction", window=oldest, rows=rows)
            _log.warning(
                "stream state over max_state_rows=%d; force-emitting "
                "window %s early (%d rows) — widen the cap or shrink "
                "the watermark delay if this is not intended",
                self.max_state_rows, oldest, rows)
            self._emit(oldest)
            self._closed_through = max(self._closed_through, oldest)

    def _values_to_host(self, state: _WState,
                        sel: Optional[np.ndarray] = None
                        ) -> Dict[str, np.ndarray]:
        schema = self.upstream.schema
        cols = {}
        for f in self.fetch_names:
            v = np.asarray(state.values[f])
            if sel is not None:
                v = v[sel]
            sk = self.sketches.get(f)
            if sk is not None:
                # sketch states finalize into their estimate columns
                # at emission (the state itself never leaves the fold)
                cols.update(sk.finalize(f, v))
                continue
            fld = schema[f]
            if v.dtype != fld.dtype.np_storage \
                    and fld.dtype is not _dt.bfloat16:
                v = v.astype(fld.dtype.np_storage)
            cols[f] = v
        return cols

    def _emit(self, s: float) -> None:
        # build the output frame BEFORE popping: a failed D2H
        # conversion must leave the window's accumulated state live
        # (the batch that triggered the emit skips; the window emits on
        # a later batch) — the same all-or-nothing contract as ingest.
        # The finished frame lands in the backlog the moment the pop
        # commits, so a failure on a LATER window cannot lose it.
        with self._state_lock:
            state = self._windows[s]
        cols: Dict[str, np.ndarray] = {
            WINDOW_COL: np.full(state.rows, s, np.float64)}
        for k, u in zip(self.keys, state.keys_u):
            cols[k] = u
        cols.update(self._values_to_host(state))
        frame = TensorFrame.from_blocks(
            [Block({f.name: cols[f.name] for f in self.out_schema},
                   state.rows)], self.out_schema)
        with self._state_lock:
            self._windows.pop(s, None)
        self._emitted_backlog.append(frame)
        self.windows_emitted += 1
        counters.inc("stream.windows_emitted")
        counters.inc("stream.rows_emitted", state.rows)
        _obs.add_event("window_emit", window=s, rows=state.rows)

    def _update_frame(self, touched: np.ndarray) -> TensorFrame:
        with self._state_lock:
            state = self._windows[None]
        sel = np.sort(np.asarray(touched))
        cols: Dict[str, np.ndarray] = {}
        for k, u in zip(self.keys, state.keys_u):
            cols[k] = u[sel]
        cols.update(self._values_to_host(state, sel))
        counters.inc("stream.rows_emitted", len(sel))
        return TensorFrame.from_blocks(
            [Block({f.name: cols[f.name] for f in self.out_schema},
                   len(sel))], self.out_schema)

    # -- execution ---------------------------------------------------------
    def start(self, sink=None, on_update=None, name: Optional[str] = None,
              max_buffered: Optional[int] = None, batch_rows=None):
        """A :class:`~.runtime.StreamHandle` pumping the upstream and
        folding each batch into this aggregation; emitted window frames
        flow to ``collect_updates()`` / ``sink`` / ``on_update``.
        ``batch_rows`` sizes batches (``docs/adaptive.md``): the fold
        is a keyed monoid, so coalesced batches combine to the same
        state as the per-block ones; with out-of-order event times the
        per-merged-batch watermark can only ADMIT rows the per-block
        cadence would have dropped late, never the reverse
        (``docs/streaming.md``)."""
        from .runtime import StreamHandle
        return StreamHandle(self.upstream, aggregation=self, sink=sink,
                            on_update=on_update, name=name,
                            max_buffered=max_buffered,
                            batch_rows=batch_rows)

    def __repr__(self):
        w = (f"window={self.window.size}/{self.window.slide}"
             if self.window else "update-mode")
        return (f"StreamingAggregation(keys={self.keys}, "
                f"fetches={self.col_combiners}, {w}, "
                f"state_rows={self.state_rows})")
