"""Streaming execution: continuous block sources over the same engine.

The pipelined engine (``docs/pipeline.md``) already streams a finite
frame's blocks through a bounded in-flight window; this package extends
that from "one finite frame" to CONTINUOUS sources — the pipelined-
streaming semantics of "Extending TensorFlow's Semantics with Pipelined
Execution" (PAPERS.md) over this engine, with keyed incremental state
staying device-resident across batches (the DrJAX sharded-MapReduce
shape). A whole scenario family the reference never had: live
dashboards, feature pipelines, file tailing.

The pieces (see ``docs/streaming.md`` for the guide):

- **sources** (:mod:`.source`): ``BlockSource`` protocol with
  ``ParquetTailSource`` (re-reads nothing: consumed row groups skip via
  ``io.read_parquet(row_group_offset=...)``), ``GeneratorSource``, and
  the bounded ``QueueSource`` (the queue bound is the ingestion
  backpressure);
- **relational ops** (:mod:`.frame`): ``StreamingFrame`` applies
  ``map_blocks`` / ``map_rows`` / ``filter_rows`` / ``select`` batch by
  batch through the UNCHANGED engine ops — fetches resolve to one
  canonical Computation at definition time, so every batch is a
  compile-cache hit and finite streams are bit-identical to the batch
  path;
- **incremental aggregation** (:mod:`.aggregate`): keyed monoid
  aggregation (sum/min/max/prod) folding each batch into bounded
  device-resident state in one segment-reduce dispatch per column,
  with tumbling/sliding windows, watermark-driven emission, late-row
  accounting, and state eviction;
- **runtime** (:mod:`.runtime`): the ``StreamHandle`` pump — per-batch
  failure isolation through the resilience retry/classification matrix
  (a poisoned batch is skipped-and-counted, never kills the stream),
  slot-pool sharing with the serving scheduler, per-batch query traces,
  and live ``tft_stream_*`` Prometheus gauges;
- **sinks** (:mod:`.sink`): ``collect_updates()`` polling, callbacks,
  and a parquet appender whose output is itself tail-able.

Quick start::

    import tensorframes_tpu as tft
    from tensorframes_tpu import stream

    src = stream.ParquetTailSource("events.parquet")
    agg = (stream.from_source(src)
           .filter_rows(lambda amount: amount > 0)
           .group_by("user")
           .aggregate({"amount": "sum"},
                      window=stream.tumbling(60.0), time_col="ts",
                      watermark_delay=5.0))
    handle = agg.start(name="spend")
    handle.run(timeout_s=10)            # or handle.start_background()
    for frame in handle.collect_updates():
        frame.show()
"""

from .aggregate import (StreamingAggregation, Window, WINDOW_COL, sliding,
                        tumbling)
from .frame import GroupedStream, StreamingFrame
from .runtime import StreamHandle
from .sink import CallbackSink, CollectSink, ParquetSink
from .source import (BlockSource, GeneratorSource, ParquetTailSource,
                     QueueSource, SchemaMismatch, check_block)

__all__ = [
    "BlockSource", "GeneratorSource", "QueueSource", "ParquetTailSource",
    "SchemaMismatch", "check_block",
    "StreamingFrame", "GroupedStream", "from_source",
    "StreamingAggregation", "Window", "WINDOW_COL", "tumbling", "sliding",
    "StreamHandle",
    "CollectSink", "CallbackSink", "ParquetSink",
]


def from_source(source: BlockSource) -> StreamingFrame:
    """The entry point: wrap a block source as a ``StreamingFrame``."""
    return StreamingFrame(source)
