#!/usr/bin/env bash
# Test entry point (the reference's python/run-tests.sh analogue):
# builds the native runtime from source FIRST — a broken native build fails
# the run loudly instead of silently exercising only the numpy fallbacks —
# then runs the suite on the CPU backend with 8 virtual devices.
set -euo pipefail
cd "$(dirname "$0")"

echo "== static check: no bare 'except:' under tensorframes_tpu/ =="
python tools/check_no_bare_except.py

# --resilience: run only the retry/fallback/fault-injection lane
# (tests/test_resilience.py) — fast, CPU-only, no native build needed
if [ "${1:-}" = "--resilience" ]; then
  shift
  echo "== resilience lane (pytest -m resilience, CPU) =="
  exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m resilience "$@"
fi

# --pipeline: run only the pipelined block-execution lane
# (tests/test_pipeline.py) — fast, CPU-only, no native build needed
if [ "${1:-}" = "--pipeline" ]; then
  shift
  echo "== pipeline lane (pytest -m pipeline, CPU) =="
  exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m pipeline "$@"
fi

# --observability: run only the query-trace/metrics/explain lane
# (tests/test_observability.py, incl. the mesh/device half: per-device
# tracks, HBM watermarks, skew reports, histograms, slow-query log) —
# fast, CPU-only (8 virtual devices via conftest), no native build needed
if [ "${1:-}" = "--observability" ]; then
  shift
  echo "== observability lane (pytest -m observability, CPU) =="
  exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m observability "$@"
fi

# --serve: run only the multi-tenant serving lane (tests/test_serve.py:
# scheduler fairness, admission control, quotas, shared compile cache,
# slot leasing) — fast, CPU-only, no native build needed
if [ "${1:-}" = "--serve" ]; then
  shift
  echo "== serve lane (pytest -m serve, CPU) =="
  exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m serve "$@"
fi

# --stream: run only the streaming lane (tests/test_stream.py: block
# sources, finite equivalence, windows/watermarks, poisoned-batch
# isolation, bounded state) — fast, CPU-only, no native build needed
if [ "${1:-}" = "--stream" ]; then
  shift
  echo "== stream lane (pytest -m stream, CPU) =="
  exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m stream "$@"
fi

# --elastic: run only the elastic-mesh lane (tests/test_elastic.py:
# device-loss recovery, skew-adaptive repartitioning, hot-key salting)
# — fast, CPU-only, no native build needed
if [ "${1:-}" = "--elastic" ]; then
  shift
  echo "== elastic lane (pytest -m elastic, CPU) =="
  exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m elastic "$@"
fi

# --memory: run only the device-memory manager lane
# (tests/test_memory.py: budget ledger, spill/fault bit-identity,
# external dsort, larger-than-budget relational suite) — fast,
# CPU-only, no native build needed
if [ "${1:-}" = "--memory" ]; then
  shift
  echo "== memory lane (pytest -m memory, CPU) =="
  exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m memory "$@"
fi

# --plan: run only the logical-plan lane (tests/test_plan.py: fused vs
# TFT_FUSE=0 bit-identity across the relational chains, column pruning,
# device-resident stage chaining, plan-derived estimates, fault
# injection on fused computations) — fast, CPU-only, no native build
if [ "${1:-}" = "--plan" ]; then
  shift
  echo "== plan lane (pytest -m plan, CPU) =="
  exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m plan "$@"
fi

# --dplan: run only the distributed logical-plan lane
# (tests/test_dplan.py: lazy d-op chains fused vs TFT_FUSE=0
# bit-identity, folded dreduce/daggregate, device-loss recovery through
# fused programs, ledger spills of resident shard edges) — fast,
# CPU-only (8 virtual devices via conftest), no native build needed
if [ "${1:-}" = "--dplan" ]; then
  shift
  echo "== dplan lane (pytest -m dplan, CPU) =="
  exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m dplan "$@"
fi

# --join: run only the relational lane (tests/test_relational.py:
# broadcast/sort-merge joins vs the CPU host oracle, ledger-chunked
# builds, device-loss recovery, sketch error bounds through
# aggregate/daggregate/streams, parquet predicate pushdown, hot keys)
# — fast, CPU-only (8 virtual devices via conftest), no native build
if [ "${1:-}" = "--join" ]; then
  shift
  echo "== relational lane (pytest -m 'join or sketch', CPU) =="
  exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'join or sketch' "$@"
fi

# --preempt: run only the preemption/cancellation/elastic-growth lane
# (tests/test_preempt.py + growth tests: checkpointed park/resume
# bit-identity, scheduler cancel races, priority preemption, mesh
# admit/churn) — fast, CPU-only (8 virtual devices), no native build
if [ "${1:-}" = "--preempt" ]; then
  shift
  echo "== preempt lane (pytest -m preempt, CPU) =="
  exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m preempt "$@"
fi

# --adaptive: run only the adaptive-execution lane
# (tests/test_adaptive.py: feedback-driven block re-bucketing vs the
# static layout, filter re-ordering/re-plans, result-cache hits +
# invalidation, adaptive stream batches, preempt-aware admission) —
# fast, CPU-only, no native build needed
if [ "${1:-}" = "--adaptive" ]; then
  shift
  echo "== adaptive lane (pytest -m adaptive, CPU) =="
  exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m adaptive "$@"
fi

# --flight: run only the flight-recorder/decision-audit/SLO/health lane
# (tests/test_flight.py: decision ring + tft.why() reconstruction with
# tracing off, dump-on-slow-query/giveup with rotation, SLO burn math,
# tft.health(), metrics-provider conformance) — fast, CPU-only, no
# native build needed
if [ "${1:-}" = "--flight" ]; then
  shift
  echo "== flight lane (pytest -m flight, CPU) =="
  exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m flight "$@"
fi

# --fabric: run only the multi-host serving-fabric lane
# (tests/test_fabric.py: tenant sharding across workers, worker-loss
# leases with checkpointed cross-worker resume, durable
# checkpoint/result tiers surviving rolling restarts warm, SLO-burn
# re-placement, TFT_FABRIC=0 parity) — fast, CPU-only, no native
# build needed
if [ "${1:-}" = "--fabric" ]; then
  shift
  echo "== fabric lane (pytest -m fabric, CPU) =="
  exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m fabric "$@"
fi

# --shuffle: run only the hash-repartition exchange lane
# (tests/test_shuffle.py: placement/conservation properties, the
# partitioned hash join vs the broadcast oracle, shuffle daggregate
# parity, TFT_SHUFFLE=0 bit-identity, device-loss recovery
# mid-exchange) — fast, CPU-only (8 virtual devices), no native build
if [ "${1:-}" = "--shuffle" ]; then
  shift
  echo "== shuffle lane (pytest -m shuffle, CPU) =="
  exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m shuffle "$@"
fi

# --sentinel: run only the performance-regression sentinel lane
# (tests/test_sentinel.py: timeline ring + TFT_TIMELINE=0 bypass
# bit-identity, cost attribution, rolling baselines + persistence,
# the scripted TFT_FAULTS=perf:1 regression drill) — fast, CPU-only,
# no native build needed
if [ "${1:-}" = "--sentinel" ]; then
  shift
  echo "== sentinel lane (pytest -m sentinel, CPU) =="
  exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m sentinel "$@"
fi

# --chaos: run only the chaos/invariant lanes (tests/test_chaos.py:
# seeded multi-site schedules + replay, cross-cutting invariant
# auditors in strict and always-on modes, poison-query quarantine,
# persist checksums, the bounded mixed-workload acceptance drill) —
# fast, CPU-only (8 virtual devices), no native build needed
if [ "${1:-}" = "--chaos" ]; then
  shift
  echo "== chaos lane (pytest -m 'chaos or invariants', CPU) =="
  exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'chaos or invariants' "$@"
fi

# --history: run only the durable query-history/post-mortem lane
# (tests/test_history.py: checksummed segment framing + rotation +
# retention, corrupt/truncated segments going cold under fault
# injection, history filters + cross-worker stitching, unclean-
# shutdown markers + tft.postmortem(), cross-restart tft.why(),
# flight-dump pruning) — fast, CPU-only, no native build needed
if [ "${1:-}" = "--history" ]; then
  shift
  echo "== history lane (pytest -m history, CPU) =="
  exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m history "$@"
fi

# --timing: run only the wall-clock-sensitive deadline tests, serially
# (they flake under concurrent suite load; TFT_TIMING_MARGIN widens
# their assertion bounds further on badly oversubscribed boxes)
if [ "${1:-}" = "--timing" ]; then
  shift
  echo "== timing lane (pytest -m timing, CPU, serial) =="
  exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m timing "$@"
fi

echo "== building native runtime (libtfruntime.so) =="
make -C native

HAVE_TF=0
if python -c "import tensorflow" >/dev/null 2>&1; then
  HAVE_TF=1
fi

if [ "$HAVE_TF" = 1 ]; then
  echo "== building native PJRT core (libtfrpjrt.so) =="
  make -C native pjrt
else
  echo "== tensorflow C++ libs not present; skipping libtfrpjrt.so =="
fi

echo "== running test suite (timing-marked deadline tests deferred) =="
python -m pytest tests/ -q -m 'not timing' "$@"

# deadline tests run SERIALLY after the main suite: their wall-clock
# assertions flake when they share the box with the concurrent suite.
# Exit code 5 = nothing collected (passthrough args like -k can
# deselect every timing test) — that is not a failure of the run.
echo "== timing lane (deadline tests, serial) =="
timing_rc=0
python -m pytest tests/ -q -m timing "$@" || timing_rc=$?
if [ "$timing_rc" -ne 0 ] && [ "$timing_rc" -ne 5 ]; then
  exit "$timing_rc"
fi

if [ "$HAVE_TF" = 1 ]; then
  echo "== op suite again through the native PJRT core (TFT_EXECUTOR=pjrt) =="
  TFT_EXECUTOR=pjrt exec python -m pytest tests/test_ops.py \
    tests/test_demos.py -q
fi
