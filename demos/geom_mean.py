"""Harmonic and geometric means per key — chained pipeline demo.

Parity with ``tensorframes_snippets/geom_mean.py:26-49``, the workload that
"found some bugs" in the reference (non-numeric string columns riding along,
unused columns, outputs with children). The pipeline shape is the same:

  map_blocks (per-row transform) -> select -> group_by + aggregate (keyed
  sums) -> map_blocks (final ratio)

and it exercises exactly those bug surfaces: ``key`` is a *string* column
that passes through the tensor engine untouched, and the first map leaves
the original ``x`` column unused downstream (dropped by ``select``).

The harmonic mean of group g is  n_g / sum(1/x_i);  the geometric mean is
exp(mean(log x_i)) — both algebraic, so the keyed aggregation is the same
sum-shaped reduce the reference's UDAF performs.
"""

from __future__ import annotations

import numpy as np

import tensorframes_tpu as tft


def harmonic_mean_per_key(df: tft.TensorFrame,
                          col_key: str = "key") -> tft.TensorFrame:
    """Value column is ``x`` (the traced functions bind it by name)."""
    import jax.numpy as jnp

    def invs_and_count(x):
        inv = 1.0 / x
        return {"invs": inv, "count": jnp.ones_like(inv)}

    df2 = tft.map_blocks(invs_and_count, df)
    gb = df2.select([col_key, "invs", "count"]).group_by(col_key)

    def sums(invs_input, count_input):
        return {"invs": invs_input.sum(0), "count": count_input.sum(0)}

    df3 = tft.aggregate(sums, gb)

    def ratio(invs, count):
        return {"harmonic_mean": count / invs}

    return tft.map_blocks(ratio, df3).select([col_key, "harmonic_mean"])


def geometric_mean_per_key(df: tft.TensorFrame,
                           col_key: str = "key") -> tft.TensorFrame:
    """Value column is ``x`` (the traced functions bind it by name)."""
    import jax.numpy as jnp

    def logs_and_count(x):
        lg = jnp.log(x)
        return {"logs": lg, "count": jnp.ones_like(lg)}

    df2 = tft.map_blocks(logs_and_count, df)
    gb = df2.select([col_key, "logs", "count"]).group_by(col_key)

    def sums(logs_input, count_input):
        return {"logs": logs_input.sum(0), "count": count_input.sum(0)}

    df3 = tft.aggregate(sums, gb)

    def finish(logs, count):
        return {"geometric_mean": jnp.exp(logs / count)}

    return tft.map_blocks(finish, df3).select([col_key, "geometric_mean"])


def make_data(n: int = 60, num_partitions: int = 3, seed: int = 7):
    rng = np.random.default_rng(seed)
    x = rng.uniform(0.5, 4.0, n)
    key = np.array([f"g{i % 3}" for i in range(n)], dtype=object)
    return tft.frame({"key": key, "x": x}, num_partitions=num_partitions)


def main():
    df = make_data()
    print("harmonic:", sorted(harmonic_mean_per_key(df).collect()))
    print("geometric:", sorted(geometric_mean_per_key(df).collect()))


if __name__ == "__main__":
    from tensorframes_tpu.utils.platform import force_cpu_if_requested

    force_cpu_if_requested()
    main()
