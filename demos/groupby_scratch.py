"""Minimal keyed aggregation + README examples — the smallest demos.

Parity with ``tensorframes_snippets/groupby_scratch.py`` (string-keyed
``aggregate`` of a sum) and the reference ``README.md:56-124`` examples:
the ``x + 3`` map over a 5-row frame, and ``analyze`` + reduce over a
vector column.
"""

from __future__ import annotations

import numpy as np

import tensorframes_tpu as tft


def groupby_sum():
    """groupby_scratch.py: sum x per string key '0'/'1'."""
    rows = [(str(x // 3), float(x)) for x in range(1, 6)]
    df = tft.frame(rows, columns=["key", "x"])
    gb = df.group_by("key")
    out = tft.aggregate(lambda x_input: {"x": x_input.sum(0)}, gb)
    return sorted(out.collect())


def readme_map_blocks():
    """README.md:56-87 — add 3 to every element of the x column."""
    df = tft.frame([(float(x),) for x in range(5)], columns=["x"])
    df2 = tft.map_blocks(lambda x: {"z": x + 3.0}, df)
    return df2.collect()


def readme_reduce_vector():
    """README.md:92-124 — analyze, then reduce_sum / reduce_min over a
    vector column."""
    import jax.numpy as jnp

    df = tft.frame([([1.0, 1.0],), ([2.0, 2.0],)], columns=["x"])
    df = tft.analyze(df)
    s = tft.reduce_blocks(lambda x_input: {"x": x_input.sum(0)}, df)
    m = tft.reduce_rows(lambda x_1, x_2: {"x": jnp.minimum(x_1, x_2)}, df)
    return s, m


def readme_dsl_map():
    """README.md:154-172 — the Scala-DSL mapBlocks on a double column,
    here via the operator DSL front end."""
    from tensorframes_tpu import dsl

    df = tft.frame({"x": np.arange(5.0) * 0.1})
    with dsl.with_graph():
        x = tft.block(df, "x")
        z = (x + 3.0).named("z")
        out = tft.map_blocks(z, df)
    return out.collect()


def main():
    print("groupby_sum:", groupby_sum())
    print("readme_map_blocks:", readme_map_blocks())
    s, m = readme_reduce_vector()
    print("reduce_sum:", s, "reduce_min:", m)
    print("dsl_map:", readme_dsl_map())


if __name__ == "__main__":
    from tensorframes_tpu.utils.platform import force_cpu_if_requested

    force_cpu_if_requested()
    main()
