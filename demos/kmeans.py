"""Distributed K-Means on TensorFrames-TPU — the reference's flagship demo.

Capability parity with ``tensorframes_snippets/kmeans.py:85-164`` and
``kmeans_demo.py:47-148`` (three coordination patterns over the same math),
re-designed TPU-first:

 - the distance computation is ONE batched matmul (``|x|^2 + |c|^2 - 2 x.c``)
   that XLA tiles onto the MXU — no expand/tile scaffolding like the
   reference's graph needed (its ``tf.tile``/``tf.pack`` dance exists only
   because TF1 graph building lacked broadcasting ergonomics);
 - variant A (``step_aggregate``): map_blocks computes per-point
   assignments, then a keyed ``aggregate`` regroups by centroid index —
   the reference's ``run_one_step`` (groupBy shuffle path);
 - variant B (``step_preaggregate``): the whole per-block centroid update is
   pre-aggregated IN-GRAPH via segment-sum (the
   ``tf.unsorted_segment_sum`` pattern of ``kmeans_demo.py:128-140``, here
   the framework's one-hot-matmul Pallas kernel on TPU) with ``trim=True``
   emitting one row per block, then a tiny ``reduce_blocks`` combine —
   communication drops from O(points) to O(blocks * k);
 - variant C (``step_device_resident``): variant B's math on a
   ``distribute``d frame — data stays in device HBM across iterations, the
   driver only moves k x m centroids per round (the TPU-native ideal: the
   reference re-marshals every row through the JVM every iteration);
 - variant D (``step_daggregate``): the groupBy shuffle itself at mesh
   scale — ``dmap_blocks`` appends assignments, ``daggregate`` with
   DEVICE-side keys folds the centroid table on the mesh (the reference's
   cross-executor shuffle became one segment-reduce + collective, and the
   key column never visits the driver).

The driver loop (``kmeans``) matches the reference's: centroids live on the
driver and are embedded as constants into the next round's computation
(``kmeans.py:148-163``).
"""

from __future__ import annotations

from typing import Callable, Tuple

import numpy as np

import tensorframes_tpu as tft
from tensorframes_tpu.ops.segment_reduce import segment_sum


def _distances(points, centers):
    """[n, k] squared distances; one MXU matmul plus broadcasting."""
    import jax.numpy as jnp

    sq = jnp.sum(points * points, axis=1, keepdims=True)        # [n, 1]
    csq = jnp.sum(centers * centers, axis=1)                    # [k]
    return sq + csq[None, :] - 2.0 * points @ centers.T         # [n, k]


# -- variant A: map_blocks + keyed aggregate (reference run_one_step) -------

def step_aggregate(df: tft.TensorFrame,
                   centers: np.ndarray) -> Tuple[np.ndarray, float]:
    import jax.numpy as jnp

    k = centers.shape[0]
    c = jnp.asarray(centers)

    def assign(features):
        d = _distances(features, c)
        return {
            "indexes": jnp.argmin(d, axis=1).astype(jnp.int32),
            "count": jnp.ones(features.shape[0], jnp.int64),
            "min_distances": jnp.min(d, axis=1),
        }

    df2 = tft.map_blocks(assign, df)
    gb = df2.group_by("indexes")

    def summarize(features_input, count_input, min_distances_input):
        return {
            "features": features_input.sum(0),
            "count": count_input.sum(0),
            "min_distances": min_distances_input.sum(0),
        }

    df3 = tft.aggregate(summarize, gb)
    new_centers = centers.copy()
    total = 0.0
    for row in df3.collect():
        idx = int(row["indexes"])
        new_centers[idx] = np.asarray(row["features"]) / row["count"]
        total += float(row["min_distances"])
    return new_centers, total


# -- variant B: in-graph segment-sum pre-aggregation (run_one_step2) --------

def _preagg_computation(centers: np.ndarray,
                        n_valid: int = None) -> Callable:
    """``n_valid`` masks pad rows on the device-resident path: their segment
    id becomes -1 (dropped by segment_sum) and their distance 0."""
    import jax.numpy as jnp

    k = centers.shape[0]
    c = jnp.asarray(centers)

    def preagg(features):
        d = _distances(features, c)
        idx = jnp.argmin(d, axis=1).astype(jnp.int32)
        mind = jnp.min(d, axis=1)
        if n_valid is not None:
            valid = jnp.arange(features.shape[0]) < n_valid
            idx = jnp.where(valid, idx, -1)
            mind = jnp.where(valid, mind, 0.0)
        ones = jnp.ones((features.shape[0], 1), features.dtype)
        # one row per BLOCK: [1, k, m] sums, [1, k] counts, [1] distance
        pts = segment_sum(features, idx, k)
        cnt = segment_sum(ones, idx, k)[:, 0]
        return {
            "agg_points": pts[None],
            "agg_counts": cnt[None],
            "agg_distances": mind.sum()[None],
        }

    return preagg


def _combine_partials(rows_pts, rows_cnt, rows_dst, centers):
    pts = rows_pts.sum(0)                      # [k, m]
    cnt = rows_cnt.sum(0)                      # [k]
    new = np.where(cnt[:, None] > 0, pts / np.maximum(cnt, 1.0)[:, None],
                   centers)                    # empty cluster keeps center
    return new.astype(centers.dtype), float(rows_dst.sum())


def step_preaggregate(df: tft.TensorFrame,
                      centers: np.ndarray) -> Tuple[np.ndarray, float]:
    from tensorframes_tpu.engine import ops as engine_ops

    df2 = tft.map_blocks(_preagg_computation(centers), df, trim=True)
    red = engine_ops.reduce_blocks(
        lambda agg_points_input, agg_counts_input, agg_distances_input: {
            "agg_points": agg_points_input.sum(0),
            "agg_counts": agg_counts_input.sum(0),
            "agg_distances": agg_distances_input.sum(0),
        }, df2)
    return _combine_partials(red["agg_points"][None],
                             red["agg_counts"][None],
                             np.asarray([red["agg_distances"]]), centers)


# -- variant C: device-resident frame, centroids-only traffic ---------------

def step_device_resident(dist, centers: np.ndarray) -> Tuple[np.ndarray, float]:
    """One step on a ``distribute``d frame (see ``parallel.distributed``).

    ``dist`` stays in HBM; per-step host traffic is just the k x m centroid
    matrix out and k x (m+2) partials back.
    """
    from tensorframes_tpu.computation import Computation, TensorSpec
    from tensorframes_tpu.parallel.distributed import dmap_blocks
    from tensorframes_tpu import dtypes as _dt
    from tensorframes_tpu.shape import Shape, Unknown

    m = centers.shape[1]
    comp = Computation.trace(
        _preagg_computation(centers, n_valid=dist.num_rows),
        [TensorSpec("features", _dt.double, Shape(Unknown, m))])
    out = dmap_blocks(comp, dist, trim=True, row_aligned=False)
    return _combine_partials(np.asarray(out.columns["agg_points"]),
                             np.asarray(out.columns["agg_counts"]),
                             np.asarray(out.columns["agg_distances"]),
                             centers)


def step_daggregate(dist, centers: np.ndarray) -> Tuple[np.ndarray, float]:
    """One step as a mesh-level keyed SHUFFLE (variant A at mesh scale).

    The reference's groupBy path moved every row between executors by
    centroid key; here ``dmap_blocks`` appends the assignment + per-point
    partials and ``daggregate(max_groups=k)`` folds them into the k-row
    table with DEVICE-side keys — per-step host traffic is the k x (m+2)
    table, and the key column never visits the driver.
    """
    import jax.numpy as jnp

    from tensorframes_tpu.parallel.distributed import daggregate, dmap_blocks

    k, m = centers.shape
    c = centers

    def assign_fn(features):
        d = _distances(features, c)
        a = jnp.argmin(d, axis=1).astype(jnp.int32)
        return {"assign": a,
                "mind": jnp.min(d, axis=1),
                "ones": jnp.ones((features.shape[0],), features.dtype)}

    scored = dmap_blocks(assign_fn, dist)
    # pad rows never reach the shuffle: daggregate marks them out via its
    # validity-aware group-id construction
    table = daggregate({"features": "sum", "mind": "sum", "ones": "sum"},
                       scored, "assign", max_groups=k)
    rows = table.collect()
    sums = np.zeros_like(centers)
    counts = np.zeros((k,))
    dist_total = 0.0
    for r in rows:
        i = int(r["assign"])
        sums[i] = np.asarray(r["features"])
        counts[i] = r["ones"]
        dist_total += float(r["mind"])
    safe = np.maximum(counts, 1.0)[:, None]
    new_centers = np.where(counts[:, None] > 0, sums / safe, centers)
    return new_centers, float(dist_total)


# -- variant E: the WHOLE loop device-resident in the native C++ core -------

def kmeans_native_resident(dist, init_centers: np.ndarray,
                           num_iters: int = 20) -> np.ndarray:
    """Run ``num_iters`` k-means rounds as a native device-resident loop.

    Variant C still pays one host round-trip per round (centroids out,
    partials back). Here the loop state — the sharded feature matrix
    (constant pass-through) and the replicated centroid table — lives in
    device buffers held by the C++ core
    (:meth:`NativeMeshExecutor.run_sharded_loop`): the features upload
    ONCE, every round's assignment/segment-sum/psum/centroid-update runs
    as one GSPMD dispatch feeding its output buffers straight into the
    next, and only the final centroids return to the host. The
    reference's executor loop re-marshalled every row through the JVM
    per round (``DebugRowOps.scala:755-794``); this is its inversion.

    Requires ``TFT_EXECUTOR=pjrt`` + ``libtfrpjrt.so``.
    """
    import jax
    import jax.numpy as jnp
    from tensorframes_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    from tensorframes_tpu.parallel import native_mesh

    mesh = dist.mesh
    ex = native_mesh.executor_for(mesh)
    if ex is None:
        raise RuntimeError(
            "kmeans_native_resident needs TFT_EXECUTOR=pjrt and a built "
            "native/libtfrpjrt.so")
    axis = mesh.data_axis
    feats = np.asarray(dist.columns["features"])
    k, _m = np.shape(init_centers)
    rows_per = feats.shape[0] // mesh.num_data_shards
    n_valid = dist.num_rows

    def build():
        def step(features, centers):
            me = jax.lax.axis_index(axis)
            rowid = me * rows_per + jnp.arange(rows_per)
            valid = (rowid < n_valid).astype(features.dtype)
            d = _distances(features, centers)
            a = jnp.argmin(d, axis=1)
            onehot = (jax.nn.one_hot(a, k, dtype=features.dtype)
                      * valid[:, None])
            sums = jax.lax.psum(onehot.T @ features, axis)
            counts = jax.lax.psum(onehot.sum(axis=0), axis)
            new_c = jnp.where(
                counts[:, None] > 0,
                sums / jnp.maximum(counts, 1.0)[:, None], centers)
            return (features, new_c)
        return shard_map(step, mesh=mesh.mesh,
                         in_specs=(P(axis, None), P()),
                         out_specs=(P(axis, None), P()))

    in_sh = [mesh.row_sharding(2), mesh.replicated()]
    out_sh = [mesh.row_sharding(2), mesh.replicated()]
    outs = ex.run_sharded_loop(
        ("kmeans_resident", mesh.mesh, feats.shape, str(feats.dtype), k,
         n_valid), build,
        [feats, np.asarray(init_centers, feats.dtype)], in_sh, out_sh,
        mesh, iters=num_iters)
    if outs is None:
        raise RuntimeError(
            "kmeans resident program was not natively routable")
    return outs[1]


# -- driver loop (reference kmeans.py:148-163) ------------------------------

def kmeans(df: tft.TensorFrame, init_centers: np.ndarray,
           num_iters: int = 50, step=step_preaggregate,
           verbose: bool = False):
    """Iterate until the total distance stops improving."""
    c = np.asarray(init_centers, np.float64)
    d = np.inf
    history = []
    for i in range(num_iters):
        c1, d1 = step(df, c)
        if verbose:
            print(f"Step = {i} , overall distance = {d1}")
        c = c1
        if d == d1:
            break
        d = d1
        history.append(d1)
    return c, history


def make_data(n: int = 1000, num_features: int = 4, k: int = 2,
              num_partitions: int = 4, seed: int = 1):
    """Gaussian blobs around k corners (the RandomRDDs.normalVectorRDD
    analogue, but separable so convergence is checkable)."""
    rng = np.random.default_rng(seed)
    true_centers = rng.uniform(-5, 5, (k, num_features))
    assign = rng.integers(0, k, n)
    pts = true_centers[assign] + rng.normal(0, 0.3, (n, num_features))
    df = tft.frame({"features": pts}, num_partitions=num_partitions)
    df = tft.analyze(df)   # "For now, analysis is still required." — ditto
    init = pts[rng.choice(n, k, replace=False)]
    return df, init, true_centers


def main():
    df, init, true_centers = make_data()
    for name, step in [("aggregate", step_aggregate),
                       ("preaggregate", step_preaggregate)]:
        centers, history = kmeans(df, init, step=step, verbose=True)
        print(f"[{name}] converged after {len(history)} steps; "
              f"final distance {history[-1]:.3f}")
    print("centers:\n", centers)

    # mesh variants (C: device-resident frame, D: mesh keyed shuffle)
    from tensorframes_tpu.parallel.distributed import distribute
    from tensorframes_tpu.parallel.mesh import local_mesh

    dist = distribute(df, local_mesh())
    for name, step in [("device_resident", step_device_resident),
                       ("daggregate", step_daggregate)]:
        centers, history = kmeans(dist, init, step=step)
        print(f"[{name}] converged after {len(history)} steps; "
              f"final distance {history[-1]:.3f}")

    # variant E: the whole loop in the native C++ core, when available
    import os

    from tensorframes_tpu import native_pjrt

    if native_pjrt.available() and os.environ.get("TFT_EXECUTOR") == "pjrt":
        try:
            centers = kmeans_native_resident(dist, init, num_iters=20)
        except RuntimeError as e:
            # executor_for can still decline (multi-process, client
            # failure, too few native devices) after the cheap checks
            print(f"[native_resident] skipped ({e})")
        else:
            print("[native_resident] centers:\n", np.asarray(centers))
    else:
        print("[native_resident] skipped (needs TFT_EXECUTOR=pjrt and "
              "a built native/libtfrpjrt.so)")


if __name__ == "__main__":
    from tensorframes_tpu.utils.platform import force_cpu_if_requested

    force_cpu_if_requested()
    main()
