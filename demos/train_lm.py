"""End-to-end language-model training through the framework.

The reference's demos stop at k-means driver loops with state re-embedded
as constants each round (``kmeans.py:85-148``); it has no training loop,
no checkpointing, no model zoo. This demo is the TPU-native framework
doing what that design could not: every subsystem in one workload —

 - the **frame layer** as the data path: the token corpus is a
   ``TensorFrame`` whose partitions are the batches (the reference's
   map-over-partitions pattern, ``DebugRowOps.scala:372-386``, reused as
   a data loader);
 - the **mesh train step**: ``TransformerLM.make_sharded_train_step``
   compiles ONE SPMD program (adam + tensor-parallel params +
   data-parallel batch) over a ``data`` × ``model`` device mesh;
 - **checkpoint / resume**: ``utils.checkpoint.save_step`` /
   ``restore_step`` — stop anywhere, resume on the same mesh with every
   shard restored to its device, and continue as if never interrupted.

The task is next-token prediction on modular-increment sequences
(``tokens[t+1] = (tokens[t] + step) % vocab`` with a per-sequence step of
1 or 2): a two-layer model drives loss down an order of magnitude in a
few dozen steps, so correctness shows up as learning, fast, on CPU.

Run:  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python -m demos.train_lm
"""

from __future__ import annotations

import os
import tempfile
from typing import Dict, List, Optional, Tuple

import numpy as np

import tensorframes_tpu as tft
from tensorframes_tpu.models import TransformerConfig, TransformerLM
from tensorframes_tpu.parallel.mesh import DeviceMesh
from tensorframes_tpu.utils import checkpoint as ckpt_lib

__all__ = ["corpus_frame", "train", "main"]


def corpus_frame(n_batches: int, batch: int, seq_len: int,
                 vocab: int, seed: int = 0) -> "tft.TensorFrame":
    """The training corpus AS A FRAME: one partition per batch.

    Each row is one training sequence (``seq_len + 1`` tokens: inputs are
    ``[:-1]``, targets ``[1:]``). Partition-per-batch makes the frame's
    ``blocks()`` iterator the data loader.
    """
    rng = np.random.default_rng(seed)
    n = n_batches * batch
    starts = rng.integers(0, vocab, (n, 1))
    steps = rng.integers(1, 3, (n, 1))          # +1 or +2 sequences
    pos = np.arange(seq_len + 1)[None, :]
    toks = (starts + steps * pos) % vocab
    df = tft.analyze(tft.frame({"tokens": toks.astype(np.int64)},
                               num_partitions=n_batches))
    df.cache()
    return df


def _batches(df) -> List[np.ndarray]:
    return [b.dense("tokens").astype(np.int32) for b in df.blocks()]


def train(mesh: DeviceMesh, *, n_steps: int = 40, batch: int = 16,
          seq_len: int = 32, vocab: int = 64,
          checkpoint_root: Optional[str] = None,
          checkpoint_every: int = 0,
          resume: bool = False,
          config: Optional[TransformerConfig] = None,
          learning_rate: float = 3e-3) -> Tuple[Dict, List[float]]:
    """Train on ``mesh``; returns ``(final_state, per-step losses)``.

    With ``checkpoint_root`` + ``checkpoint_every``, saves the train state
    every C steps; with ``resume=True``, restores the latest step first
    and continues from there (cold start when nothing is saved).
    """
    cfg = config or TransformerConfig(
        vocab_size=vocab, d_model=64, n_heads=8, n_layers=2, d_ff=128)
    model = TransformerLM(cfg)
    model_axis = "model" if "model" in mesh.axis_names else None
    step, init_state = model.make_sharded_train_step(
        mesh, data_axis=mesh.data_axis, model_axis=model_axis,
        learning_rate=learning_rate)

    state = init_state()
    start = 0
    if resume and checkpoint_root:
        restored, at = ckpt_lib.restore_step(checkpoint_root, state)
        if restored is not None:
            state, start = restored, at
    if start >= n_steps:
        return state, []

    df = corpus_frame(n_batches=8, batch=batch, seq_len=seq_len,
                      vocab=vocab)
    data = _batches(df)

    losses: List[float] = []
    for i in range(start, n_steps):
        toks = data[i % len(data)]
        state, loss = step(state, toks[:, :-1], toks[:, 1:])
        losses.append(float(loss))
        if (checkpoint_root and checkpoint_every
                and (i + 1) % checkpoint_every == 0):
            ckpt_lib.save_step(checkpoint_root, i + 1, state)
    return state, losses


def main() -> Dict:
    from tensorframes_tpu.parallel.mesh import local_mesh

    mesh = local_mesh()  # every visible device on the data axis
    root = os.path.join(tempfile.mkdtemp(prefix="tft_lm_"), "ckpt")

    # phase 1: train 30 steps, checkpointing every 10
    _, losses = train(mesh, n_steps=30, checkpoint_root=root,
                      checkpoint_every=10)
    resumed_from = ckpt_lib.latest_step(root)
    # phase 2: "crash" after step 30, resume from disk, finish to 40
    state, more = train(mesh, n_steps=40, checkpoint_root=root,
                        checkpoint_every=10, resume=True)

    first, last = losses[0], more[-1]
    print(f"step   1: loss {first:.4f}")
    print(f"step  40: loss {last:.4f}  (resumed from step "
          f"{resumed_from} checkpoint)")
    assert last < first / 3, (first, last)

    # and the trained model actually speaks the language: greedily
    # continue a +1 sequence with the KV-cache decode loop
    import jax
    import jax.numpy as jnp

    cfg = TransformerConfig(vocab_size=64, d_model=64, n_heads=8,
                            n_layers=2, d_ff=128)
    model = TransformerLM(cfg)
    params = jax.device_put(state["params"])
    prompt = jnp.asarray([[10 + i for i in range(8)]], jnp.int32)
    out = model.generate(params, prompt, max_new_tokens=8)
    completion = np.asarray(out[0, 8:]).tolist()
    print(f"prompt 10..17 -> continuation {completion}")
    return {"first_loss": first, "final_loss": last,
            "resumed_from": 30, "total_steps": 40,
            "continuation": completion}


if __name__ == "__main__":
    from tensorframes_tpu.utils.platform import force_cpu_if_requested

    force_cpu_if_requested()
    main()
