"""End-to-end analytics pipeline: IO -> relational ops -> mesh -> report.

The reference's users composed this exact shape of job from Spark SQL
plus TensorFrames ops (load, filter, groupBy+aggregate, orderBy, show);
this demo is the same pipeline standing on this framework alone:

  read_csv -> analyze -> filter -> distribute -> daggregate (composite
  device-side keys) -> order_by -> show

Workload: per-sensor statistics over a synthetic readings table — drop
error-code rows, sum values per (site, sensor) on the mesh, rank the
groups by total on the host (daggregate returns a host frame).

Run:  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python -m demos.analytics
"""

from __future__ import annotations

import os
import tempfile
from typing import Dict

import numpy as np

import tensorframes_tpu as tft
from tensorframes_tpu import parallel as par

__all__ = ["make_csv", "pipeline", "main"]


def make_csv(path: str, n: int = 20_000, sites: int = 4,
             sensors: int = 8, seed: int = 0) -> None:
    """A readings table: site/sensor ids, a value, and some error rows
    (coded as negative values) that the pipeline must drop."""
    rng = np.random.default_rng(seed)
    site = rng.integers(0, sites, n)
    sensor = rng.integers(0, sensors, n)
    value = np.abs(rng.normal(10.0, 3.0, n))
    err = rng.random(n) < 0.05
    value[err] = -1.0                      # error code
    with open(path, "w") as f:
        f.write("site,sensor,value\n")
        for s, d, v in zip(site, sensor, value):
            f.write(f"{s},{d},{v:.6f}\n")


def pipeline(csv_path: str, mesh=None) -> "tft.TensorFrame":
    """The full pipeline; returns the ranked per-(site, sensor) report."""
    mesh = mesh or par.local_mesh()
    # int32 keys at parse time: device-side grouping needs a device-exact
    # key dtype (x64 is off on TPU, so int64 keys would narrow)
    df = tft.analyze(tft.io.read_csv(
        csv_path, num_partitions=4,
        dtypes={"site": "int32", "sensor": "int32"}))
    clean = df.filter(lambda value: value >= 0.0)

    dist = par.distribute(clean, mesh)
    agg = par.daggregate({"value": "sum"}, dist, ["site", "sensor"],
                         max_groups=64)
    ranked = agg.order_by("value", descending=True)
    return ranked


def main() -> Dict:
    d = tempfile.mkdtemp(prefix="tft_analytics_")
    csv_path = os.path.join(d, "readings.csv")
    make_csv(csv_path)
    ranked = pipeline(csv_path)
    ranked.show(5)
    rows = ranked.collect()
    top = rows[0]
    print(f"{len(rows)} (site, sensor) groups; top: site {top['site']} "
          f"sensor {top['sensor']} total {top['value']:.1f}")
    totals = [r["value"] for r in rows]
    assert totals == sorted(totals, reverse=True)
    return {"groups": len(rows), "top_total": top["value"]}


if __name__ == "__main__":
    from tensorframes_tpu.utils.platform import force_cpu_if_requested

    force_cpu_if_requested()
    main()
