#!/usr/bin/env python
"""Chaos soak: the mixed workload under a seeded multi-site schedule.

Every recovery path has a tier-1 test that arms ONE fault site and
asserts one contract. This driver is the composed version — the
``resilience/chaos.py`` schedule fires device losses, worker crashes,
OOMs, preemptions, and rotten persist artifacts *into each other*
while a mixed workload runs (multi-tenant serve, streams, a broadcast
join, fused distributed plans, preempt/park/resume, shrink + re-admit)
— and asserts the global contract the per-site tests each assert
locally:

- **never wrong**: every result bit-identical to the fault-free run
  (zero lost rows, zero duplicated rows);
- **never leaked**: zero slot-pool leases, zero ledger reservations,
  no worker threads left behind;
- **never unclassified**: every surfaced failure has a
  ``resilience.error_kind`` other than the permanent fallback;
- **replayable**: the firing schedule is a pure function of
  ``(seed, site, step)`` — per site, two runs agree on every firing
  up to their common consult count.

Usage (standalone soak, minutes):

    JAX_PLATFORMS=cpu python tools/chaos_soak.py --seed 42 \
        --rate 0.08 --sites device,worker,oom,preempt,disk --rounds 20

The bounded acceptance drill in ``tests/test_chaos.py`` imports
:func:`run_drill` with small parameters (seconds, tier-1); the
``slow``-marked soak test runs more rounds of the same code. The
``batch`` and ``oom`` sites are deliberately NOT in the default mix:
a fault surfacing inside a stream batch is *skipped and counted* by
contract (``stream/runtime`` — and an injected OOM on a
smallest-splittable block surfaces exactly there), which is correct
but lossy by design, not bit-identical — soak those separately.
"""

import argparse
import sys
import threading
import time

import numpy as np

DEFAULT_SITES = ("device", "worker", "preempt", "disk")


def _digest(forced) -> tuple:
    """A TensorFrame's values as a hashable per-column identity —
    bit-exact over the row sequence, so two runs compare equal iff
    every value matches. Deliberately blind to BLOCK boundaries: an
    elastic shrink mid-run legitimately re-shards (7-way instead of
    8-way), and the contract is row-order bit-identity, not identical
    partitioning."""
    cols = {}
    for b in forced.blocks():
        for name in sorted(b.columns):
            cols.setdefault(name, []).append(np.asarray(b.columns[name]))
    return tuple(
        (name, np.concatenate(parts).tobytes() if parts else b"")
        for name, parts in sorted(cols.items()))


def _submit_with_retries(sched, frame, fetches, tenant, failures,
                         attempts=8):
    """Submit until success, recording every surfaced failure's
    classified kind. Chaos budgets are one-shot, so a failed attempt's
    fault is consumed and the resubmission makes progress."""
    from tensorframes_tpu.resilience import error_kind
    last = None
    for _ in range(attempts):
        fut = sched.submit(frame, fetches, tenant=tenant)
        try:
            return fut.result(timeout=120)
        except Exception as e:  # noqa: BLE001 - recorded + re-raised below
            failures.append((error_kind(e), f"{type(e).__name__}: {e}"))
            last = e
    raise last


def run_workload(rounds, failures, persist_dir=None):
    """One pass of the mixed workload. Returns ``{key: digest}`` over
    every query result — the bit-identity record.

    Deterministic by construction (fixed data, fixed plans) so the
    fault-free and chaos passes are comparable; every surfaced failure
    lands in ``failures`` as ``(kind, repr)``.
    """
    import tensorframes_tpu as tft
    from tensorframes_tpu import parallel as par
    from tensorframes_tpu import relational as rel
    from tensorframes_tpu import stream
    from tensorframes_tpu.memory import persist as _persist
    from tensorframes_tpu.plan import adaptive as _adaptive
    from tensorframes_tpu.serve import QueryScheduler, TenantQuota

    prev_persist = _persist.configure(persist_dir)
    # a shared result cache would let the chaos pass serve the
    # reference pass's blocks without executing anything — the drill
    # must re-earn every result
    _adaptive.invalidate_results()
    results = {}
    quotas = {"etl": TenantQuota(weight=2.0, max_inflight=2),
              "adhoc": TenantQuota(weight=1.0, max_inflight=2)}
    try:
        with QueryScheduler(quotas=quotas, workers=2,
                            name="chaos-drill") as sched:
            for r in range(rounds):
                # multi-tenant serve: row-local map chains, plus a
                # filter chain that drives the row-conservation ledger
                for k in range(3):
                    df = tft.frame(
                        {"x": np.arange(48.0) + 16 * r + k},
                        num_partitions=3)
                    results[("etl", r, k)] = _digest(
                        _submit_with_retries(
                            sched, df, lambda x: {"z": x * 2.0 + 1.0},
                            "etl", failures))
                fdf = tft.frame({"x": np.arange(64.0) + r},
                                num_partitions=4)
                results[("filter", r)] = _digest(
                    _submit_with_retries(
                        sched,
                        fdf.filter(lambda x: x % 3.0 == 0.0),
                        lambda x: {"z": x + 0.5}, "adhoc", failures))

                # broadcast join (forced inline: the relational layer
                # rides the same executor fault sites)
                left = tft.frame(
                    {"k": np.arange(24.0) % 6, "v": np.arange(24.0) + r})
                right = tft.frame(
                    {"k": np.arange(6.0), "w": np.arange(6.0) * 10})
                results[("join", r)] = _digest(
                    rel.broadcast_join(left, right, on="k"))

                # fused distributed plan over the 8-device mesh: the
                # device site fires here and the elastic layer shrinks;
                # re-admit after so the next round greys back to full
                mesh = par.local_mesh()
                dist = par.distribute(
                    tft.frame({"x": np.arange(32.0) + r}), mesh)
                out = par.dmap_blocks(lambda x: {"z": x * 3.0 - 1.0},
                                      dist)
                results[("dist", r)] = _digest(out.collect_frame())
                from tensorframes_tpu.parallel import elastic as _el
                if _el.lost_pool():
                    par.admit_devices(mesh)

                # the durable tier under rot: write one artifact and
                # read it back a few times. Under chaos the disk site
                # fails or corrupts reads and the tier must go COLD
                # (None) — returning different bytes would be the
                # silent-wrong-data failure the checksums exist to
                # prevent
                if persist_dir is not None:
                    saved = [{"x": np.arange(16.0) + r}]
                    _persist.save_result(f"soak-probe-{r}", saved)
                    for _ in range(3):
                        got = _persist.load_result(f"soak-probe-{r}")
                        assert got is None or np.array_equal(
                            np.asarray(got[0]["x"]), saved[0]["x"]), \
                            "persist tier returned wrong data"

                # a bounded stream (no chaos `batch` site in the mix,
                # so nothing is skipped and the digest is exact)
                def batches(base):
                    for i in range(4):
                        yield {"x": np.arange(8.0) + base + i}
                handle = (stream.from_source(
                              stream.GeneratorSource(batches(100 * r)))
                          .map_blocks(lambda x: {"z": x - 2.0})
                          .start(name=f"soak-{r}"))
                handle.run(timeout_s=60)
                updates = handle.collect_updates()
                results[("stream", r)] = tuple(
                    _digest(f) for f in updates)
    finally:
        _persist.configure(prev_persist)
    return results


def check_prefix_replay(fp_a, consults_a, fp_b, consults_b):
    """Per-site replay check: over the consult counts BOTH runs
    reached, the firing steps must agree exactly (the schedule is a
    pure function of ``(seed, site, step)``; recovery work may change
    how MANY consults a site sees, never which steps fire)."""
    mismatches = []
    sites = set(consults_a) | set(consults_b)
    for site in sites:
        common = min(consults_a.get(site, 0), consults_b.get(site, 0))
        a = [s for (x, s) in fp_a if x == site and s <= common]
        b = [s for (x, s) in fp_b if x == site and s <= common]
        if a != b:
            mismatches.append((site, a, b))
    return mismatches


def run_drill(seed=42, rate=0.08, sites=DEFAULT_SITES, rounds=1,
              persist_dir=None, thread_grace_s=15.0):
    """The bounded chaos acceptance drill. Returns a report dict;
    raises ``AssertionError`` on any broken contract."""
    import tensorframes_tpu  # noqa: F401 - backend up before baselining
    from tensorframes_tpu import memory as _memory
    from tensorframes_tpu.engine import pipeline as _pipeline
    from tensorframes_tpu.resilience import chaos, invariants

    baseline_threads = threading.active_count()

    # fault-free reference
    ref_failures = []
    reference = run_workload(rounds, ref_failures)
    assert not ref_failures, f"fault-free run failed: {ref_failures}"

    # the same workload under chaos
    failures = []
    with chaos.inject(chaos.ChaosSchedule(seed, rate, list(sites))) as sc:
        chaotic = run_workload(rounds, failures,
                               persist_dir=persist_dir)
        stats = sc.stats()
        fp = sc.fingerprint()

    # bit-identity: zero lost rows, zero duplicated rows, zero wrong
    # values — the chaos run earned exactly the reference's answers
    assert set(chaotic) == set(reference), (
        f"result set drifted: {set(chaotic) ^ set(reference)}")
    wrong = [k for k in reference if chaotic[k] != reference[k]]
    assert not wrong, f"results not bit-identical under chaos: {wrong}"

    # every surfaced failure classified (the permanent fallback means
    # the classifier did NOT recognize it — a chaos fault must never
    # surface unrecognized)
    unclassified = [f for f in failures if f[0] == "permanent"]
    assert not unclassified, f"unclassified failures: {unclassified}"

    # zero leaks: no slot pool installed, no ledger reservations, the
    # worker/stream threads wound down
    assert _pipeline.current_slot_pool() is None, "slot pool leaked"
    mgr = _memory.active()
    if mgr is not None:
        assert not mgr.audit(), f"ledger audit failed: {mgr.audit()}"
    deadline = time.monotonic() + thread_grace_s
    while (threading.active_count() > baseline_threads
           and time.monotonic() < deadline):
        time.sleep(0.05)
    leaked = threading.active_count() - baseline_threads
    assert leaked <= 0, (
        f"{leaked} thread(s) leaked: "
        f"{sorted(t.name for t in threading.enumerate())}")

    # the cross-cutting auditors agree, loudly
    with invariants.strict():
        invariants.audit("chaos.soak")

    # replay: same seed + same workload => same per-site firing steps
    replay_failures = []
    with chaos.inject(chaos.ChaosSchedule(seed, rate, list(sites))) as sc2:
        run_workload(rounds, replay_failures, persist_dir=persist_dir)
        stats2 = sc2.stats()
        fp2 = sc2.fingerprint()
    mismatches = check_prefix_replay(fp, stats["consults"],
                                     fp2, stats2["consults"])
    assert not mismatches, f"schedule did not replay: {mismatches}"

    return {"seed": seed, "rate": rate, "sites": list(sites),
            "rounds": rounds, "fired": stats["fired"],
            "consults": stats["consults"], "firings": list(fp),
            "failures": failures, "replay_fired": stats2["fired"]}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--rate", type=float, default=0.08)
    ap.add_argument("--sites", default=",".join(DEFAULT_SITES),
                    help="comma- or |-separated fault sites")
    ap.add_argument("--rounds", type=int, default=20,
                    help="workload rounds per pass (3 passes run: "
                         "reference, chaos, replay)")
    ap.add_argument("--persist-dir", default=None,
                    help="durable-tier dir for the chaos passes "
                         "(default: a fresh temp dir, so the disk "
                         "site has artifacts to rot)")
    args = ap.parse_args(argv)
    sites = [s for s in args.sites.replace("|", ",").split(",") if s]
    persist_dir = args.persist_dir
    if persist_dir is None:
        import tempfile
        persist_dir = tempfile.mkdtemp(prefix="tft-chaos-soak-")
    t0 = time.monotonic()
    report = run_drill(seed=args.seed, rate=args.rate, sites=sites,
                       rounds=args.rounds, persist_dir=persist_dir)
    dt = time.monotonic() - t0
    print(f"chaos soak PASSED in {dt:.1f}s: seed {report['seed']} "
          f"rate {report['rate']:g} over {report['rounds']} round(s)")
    print(f"  consults: {report['consults']}")
    print(f"  fired {report['fired']} fault(s): {report['firings']}")
    print(f"  surfaced failures (all classified, all recovered by "
          f"resubmission): {report['failures'] or 'none'}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
