#!/usr/bin/env python
"""Static checks on exception handling under tensorframes_tpu/.

1. No bare ``except:`` anywhere: a bare except swallows ``BaseException``
   — including KeyboardInterrupt, DeadlineExceeded, and injected faults —
   which blinds the resilience layer's transient/oom/permanent
   classifier. ``except Exception`` (or a narrower type) is always
   available instead.

2. No ``except Exception: pass`` under ``tensorframes_tpu/observability/``,
   — a rule that now covers the always-on flight-recorder layer
   (``observability/flight.py``, ``decisions.py``, ``slo.py``,
   ``health.py``), the performance sentinel
   (``observability/timeline.py``, ``baseline.py``), and the durable
   query history (``observability/history.py``): a silently
   swallowed ring write, dump, SLO burn evaluation, health probe,
   timeline sample, baseline update/persist, or history append /
   segment walk would erase exactly the post-mortem evidence the
   layer exists to keep (a flight recorder that loses its own records
   without a log line is worse than none, a regression detector that
   silently stops calibrating reports "all fast" forever, and a crash
   archive that drops a record silently answers the next post-mortem
   with a hole exactly where the interesting query was) —
   ``tensorframes_tpu/serve/``, ``tensorframes_tpu/stream/``, or
   ``tensorframes_tpu/parallel/``: the observability layer is the last
   place a failure may vanish silently — an event sink or metrics
   endpoint that swallows an error without at least logging it hides
   exactly the evidence it exists to surface — the serving layer's whole
   contract is CLASSIFIED failure (a scheduler that silently eats an
   error turns a rejection into a hang), the streaming layer's
   batch-skip contract is skip-AND-COUNT (a silently swallowed batch
   error is a data-loss bug with no trace), the parallel layer's
   elastic recovery depends on device-loss errors REACHING its
   classifier (a swallowed mesh error turns a recoverable loss into
   silent corruption or a later hang — and that includes the shuffle
   exchange, ``parallel/exchange.py``: a swallowed error between its
   two all_to_all phases would silently lose or duplicate rows, and
   its row-conservation check exists precisely to turn that into a
   loud failure), and the memory layer's spill /
   fault-back path moves user data between device and host (a silently
   swallowed spill error is silent data loss), the plan layer's
   fall-back-to-per-op decisions must be LOGGED (a silently swallowed
   optimizer error would hide why a chain stopped fusing — and that
   now includes ``plan/adaptive.py``: a swallowed re-plan, layout, or
   result-cache error would silently pin the static path or hide why
   an interned result vanished), and the
   relational layer's join/sketch degradations (chunked builds, host
   segment-fold fallbacks, unpushable predicates) must likewise leave a
   trace — a join that silently dropped to a slower path is a perf bug
   nobody can find — and the engine layer now carries the preemption
   token path (``engine/preempt.py``): a silently swallowed error
   between a park and its resume is a lost checkpoint, i.e. silently
   re-run work — and the resilience layer itself is now strict too
   (``resilience/chaos.py``, ``invariants.py``): a chaos scheduler
   that silently drops a firing breaks seed-replay determinism, and an
   invariant auditor that swallows its own error is the one watchdog
   that must never sleep on the job (a crashed auditor is REPORTED as
   a violation, never ignored). Handle it or log it (``_log.debug`` is
   enough).

AST-based, so strings and comments never false-positive.
"""

import ast
import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent / "tensorframes_tpu"
# packages where `except Exception: pass` (silent swallow) is also banned
STRICT_ROOTS = (ROOT / "observability", ROOT / "serve", ROOT / "stream",
                ROOT / "parallel", ROOT / "memory", ROOT / "plan",
                ROOT / "relational", ROOT / "engine",
                ROOT / "resilience")


def _is_exception_name(node) -> bool:
    return isinstance(node, ast.Name) and node.id == "Exception"


def _swallows_silently(handler: ast.ExceptHandler) -> bool:
    """``except Exception: pass`` (or ``...``): no logging, no re-raise,
    no handling — the silent-swallow shape."""
    if not _is_exception_name(handler.type):
        return False
    if len(handler.body) != 1:
        return False
    stmt = handler.body[0]
    return isinstance(stmt, ast.Pass) or (
        isinstance(stmt, ast.Expr)
        and isinstance(stmt.value, ast.Constant)
        and stmt.value.value is Ellipsis)


def main() -> int:
    bad = []
    for path in sorted(ROOT.rglob("*.py")):
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except SyntaxError as e:
            bad.append(f"{path}:{e.lineno}: syntax error: {e.msg}")
            continue
        in_strict = any(r in path.parents for r in STRICT_ROOTS)
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                bad.append(
                    f"{path}:{node.lineno}: bare 'except:' — catch "
                    f"'Exception' (or narrower) so the resilience "
                    f"classifier can see what failed")
            elif in_strict and _swallows_silently(node):
                bad.append(
                    f"{path}:{node.lineno}: 'except Exception: pass' — "
                    f"the observability/serving layers must not swallow "
                    f"errors silently; log the failure (or catch "
                    f"narrower)")
    for line in bad:
        print(line, file=sys.stderr)
    if bad:
        print(f"check_no_bare_except: {len(bad)} violation(s)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
