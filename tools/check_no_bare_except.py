#!/usr/bin/env python
"""Static check: no bare ``except:`` clauses under tensorframes_tpu/.

A bare except swallows ``BaseException`` — including KeyboardInterrupt,
DeadlineExceeded, and injected faults — which blinds the resilience
layer's transient/oom/permanent classifier. ``except Exception`` (or a
narrower type) is always available instead. AST-based, so strings and
comments never false-positive.
"""

import ast
import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent / "tensorframes_tpu"


def main() -> int:
    bad = []
    for path in sorted(ROOT.rglob("*.py")):
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except SyntaxError as e:
            bad.append(f"{path}:{e.lineno}: syntax error: {e.msg}")
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.ExceptHandler) and node.type is None:
                bad.append(
                    f"{path}:{node.lineno}: bare 'except:' — catch "
                    f"'Exception' (or narrower) so the resilience "
                    f"classifier can see what failed")
    for line in bad:
        print(line, file=sys.stderr)
    if bad:
        print(f"check_no_bare_except: {len(bad)} violation(s)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
