"""Multi-tenant serving suite (tier-1; marker ``serve``).

Proves the serving-layer contract end-to-end on CPU:

- the acceptance workload — a mixed 3-tenant (small/medium/large) mix
  submitted concurrently through ``serve.QueryScheduler`` completes with
  zero lost or duplicated results vs serial execution, per-tenant
  fairness within 2x of the configured weights, classified rejections
  for full queues and exhausted quotas (no hangs, no OOMs), and >= 1
  cross-tenant shared-compile-cache hit for identical signatures;
- weighted-fair (stride) selection order, deadline sheds, HBM admission
  control against fake devices (wait-then-admit and wait-then-shed);
- the shared compile cache's structural signatures (identical programs
  merge, different programs never do);
- pipeline slot leasing (bounded cross-query in-flight blocks, no lease
  leaks on errors);
- the engine compile-cache's cross-thread safety (8 threads hammering
  one executor / the fetches cache compile exactly once per signature);
- concurrent traced queries: distinct correlation ids, no track
  collisions, per-tenant latency series;
- the metrics endpoint: live ``tft_serve_*`` gauges and the
  ``charset=utf-8`` content type.
"""

import threading
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

import tensorframes_tpu as tft
from conftest import timing_margin
from tensorframes_tpu import observability as obs
from tensorframes_tpu import serve
from tensorframes_tpu.computation import Computation, TensorSpec
from tensorframes_tpu.dtypes import double
from tensorframes_tpu.engine import ops as engine_ops
from tensorframes_tpu.engine import pipeline as engine_pipeline
from tensorframes_tpu.engine.executor import BlockExecutor
from tensorframes_tpu.observability import device as obs_device
from tensorframes_tpu.observability import events as obs_events
from tensorframes_tpu.resilience import (AdmissionDeadline,
                                         DeadlineExceeded, OverQuota,
                                         QueueFull, ServeRejected,
                                         error_kind, is_permanent,
                                         is_transient)
from tensorframes_tpu.serve import (QueryScheduler, SharedCompileCache,
                                    TenantQuota, computation_signature)
from tensorframes_tpu.shape import Shape, Unknown
from tensorframes_tpu.utils import tracing

pytestmark = pytest.mark.serve


@pytest.fixture(autouse=True)
def _clean_serve():
    tracing.disable()
    tracing.timings.reset()
    tracing.counters.reset()
    tracing.histograms.reset()
    obs.clear_ring()
    obs_events._reset_last_query()
    obs_device._reset()
    yield
    serve.shutdown_default_scheduler()
    tracing.disable()
    tracing.timings.reset()
    tracing.counters.reset()
    tracing.histograms.reset()
    obs.clear_ring()
    obs_events._reset_last_query()
    obs_device._reset()
    assert engine_pipeline.current_slot_pool() is None


def _frame(n, offset=0.0, parts=2):
    return tft.frame({"x": np.arange(float(n)) + offset},
                     num_partitions=parts)


def _z(forced):
    return np.concatenate([np.asarray(b.columns["z"])
                           for b in forced.blocks()])


# ---------------------------------------------------------------------------
# acceptance: the mixed 3-tenant workload
# ---------------------------------------------------------------------------

class TestMixedWorkloadAcceptance:
    def test_three_tenant_mix_correct_fair_and_shared(self):
        """ISSUE 6 acceptance: concurrent submission, zero lost or
        duplicated results vs serial, classified rejections, >= 1
        cross-tenant compile-cache hit."""
        sizes = {"small": 40, "medium": 400, "large": 4000}
        per_tenant = 6
        expected = {}
        for tenant, n in sizes.items():
            for k in range(per_tenant):
                expected[(tenant, k)] = np.arange(float(n)) + k + 3.0

        quotas = {t: TenantQuota(weight=2.0 if t == "large" else 1.0,
                                 max_inflight=2)
                  for t in sizes}
        results = {}
        with QueryScheduler(quotas=quotas, workers=3,
                            name="accept") as sched:
            futs = {}

            def submit_all(tenant):
                n = sizes[tenant]
                for k in range(per_tenant):
                    # a FRESH lambda per query: structurally identical,
                    # distinct objects — the shared cache's job
                    futs[(tenant, k)] = sched.submit(
                        _frame(n, offset=k),
                        lambda x: {"z": x + 3.0}, tenant=tenant)

            threads = [threading.Thread(target=submit_all, args=(t,))
                       for t in sizes]
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            for key, fut in futs.items():
                results[key] = _z(fut.result(timeout=120))

            # zero lost, zero duplicated, bit-correct vs serial
            assert set(results) == set(expected)
            for key in expected:
                np.testing.assert_allclose(results[key], expected[key])

            # >= 1 cross-tenant shared-compile hit (18 structurally
            # identical programs -> 1 canonical computation)
            cc = sched.compile_cache.stats()
            assert cc["hits"] >= 1
            assert cc["misses"] <= 2  # identical signature family

            snap = sched.snapshot()
            for tenant in sizes:
                s = snap[tenant]
                assert s["completed"] == per_tenant
                assert s["failed"] == s["rejected"] == s["shed"] == 0
            report = serve.serve_report(sched)
            assert "shared compile cache" in report

    def test_rejections_are_classified_not_hangs(self):
        with QueryScheduler(workers=0, name="cls") as sched:
            sched.register_tenant("t", TenantQuota(max_queue=1))
            sched.submit(_frame(8), tenant="t")
            with pytest.raises(QueueFull) as ei:
                sched.submit(_frame(8), tenant="t")
            assert error_kind(ei.value) == "rejected"
            assert is_transient(ei.value)  # retry later is legitimate

            sched.register_tenant("q", TenantQuota(rows_per_sec=10.0))
            df = _frame(1000)
            df.cache()  # cached -> rows are estimable
            with pytest.raises(OverQuota) as ei:
                sched.submit(df, tenant="q")
            assert error_kind(ei.value) == "over_quota"
            assert is_transient(ei.value)


# ---------------------------------------------------------------------------
# weighted fairness
# ---------------------------------------------------------------------------

class TestFairness:
    def test_stride_selection_tracks_weights_within_2x(self):
        quotas = {"a": TenantQuota(weight=1.0),
                  "b": TenantQuota(weight=1.0),
                  "c": TenantQuota(weight=2.0)}
        completion = []
        with QueryScheduler(quotas=quotas, workers=0,
                            name="fair") as sched:
            futs = []
            for tenant in ("a", "b", "c"):
                for k in range(8):
                    futs.append((tenant, sched.submit(
                        _frame(16, offset=k), lambda x: {"z": x + 1.0},
                        tenant=tenant)))
            fut_by_id = {f.query_id: t for t, f in futs}
            done_before = set()
            # drive deterministically, one scheduling decision at a time
            while sched.step():
                done_now = {f.query_id for _, f in futs if f.done()}
                for qid in done_now - done_before:
                    completion.append(fut_by_id[qid])
                done_before = done_now
            snap = sched.snapshot()
            assert all(snap[t]["completed"] == 8 for t in quotas)
        # in the first 8 completions, shares must be within 2x of the
        # weight ratio (weights 1:1:2 -> ideal 2:2:4)
        head = completion[:8]
        counts = {t: head.count(t) for t in ("a", "b", "c")}
        total_w = 4.0
        for t, w in (("a", 1.0), ("b", 1.0), ("c", 2.0)):
            ideal = 8 * w / total_w
            assert counts[t] <= 2 * ideal + 1e-9, (counts, t)
            assert counts[t] >= ideal / 2 - 1e-9, (counts, t)

    def test_idle_tenant_does_not_bank_credit(self):
        quotas = {"busy": TenantQuota(weight=1.0),
                  "idle": TenantQuota(weight=1.0)}
        with QueryScheduler(quotas=quotas, workers=0,
                            name="bank") as sched:
            for k in range(6):
                sched.submit(_frame(8, offset=k), tenant="busy")
            for _ in range(6):
                sched.step()
            # idle arrives late: it must not get 6 consecutive turns
            futs = []
            for k in range(3):
                futs.append(sched.submit(_frame(8, offset=k),
                                         tenant="idle"))
                futs.append(sched.submit(_frame(8, offset=k),
                                         tenant="busy"))
            first_two = []
            for _ in range(2):
                assert sched.step()
                snap = sched.snapshot()
                first_two.append((snap["idle"]["completed"],
                                  snap["busy"]["completed"]))
            # after two steps, both tenants progressed (no banked burst)
            idle_done = first_two[-1][0]
            assert 1 <= idle_done <= 2


# ---------------------------------------------------------------------------
# deadlines and admission control
# ---------------------------------------------------------------------------

class _FakeDevice:
    def __init__(self, live, peak, limit):
        self.stats = {"bytes_in_use": live, "peak_bytes_in_use": peak,
                      "bytes_limit": limit}

    def memory_stats(self):
        return self.stats


@pytest.mark.timing
class TestDeadlinesAndAdmission:
    # wall-clock-sensitive deadline/admission assertions (marker
    # `timing`): the sleeps and result() timeouts carry wide margins so
    # concurrent suite load cannot flake them — each assertion proves a
    # deadline FIRED or an admission CLEARED, never how fast
    def test_queued_past_deadline_is_shed_classified(self):
        with QueryScheduler(workers=0, name="dl") as sched:
            fut = sched.submit(_frame(8), tenant="t", deadline=0.01)
            time.sleep(0.05)
            assert sched.step()
            with pytest.raises(DeadlineExceeded):
                fut.result(timeout=timing_margin(5))
            assert fut.state == "failed"
            snap = sched.snapshot()
            assert snap["t"]["failed"] == 1

    def test_admission_sheds_when_no_headroom(self, monkeypatch):
        monkeypatch.setattr(obs_device, "_local_devices",
                            lambda: [_FakeDevice(950, 950, 1000)])
        obs_device._reset()
        monkeypatch.setenv("TFT_SERVE_ADMISSION_WAIT_S", "0.05")
        monkeypatch.setenv("TFT_SERVE_ADMISSION_POLL_S", "0.01")
        with QueryScheduler(workers=0, name="adm") as sched:
            fut = sched.submit(_frame(8), tenant="t", est_bytes=500)
            assert sched.step()
            with pytest.raises(AdmissionDeadline) as ei:
                fut.result(timeout=timing_margin(5))
            assert error_kind(ei.value) == "deadline_admission"
            assert not is_transient(ei.value)
            assert is_permanent(ei.value)
            assert fut.state == "shed"
            assert sched.snapshot()["t"]["shed"] == 1

    def test_admission_waits_for_headroom_then_runs(self, monkeypatch):
        dev = _FakeDevice(950, 950, 1000)
        calls = []

        def devices():
            calls.append(1)
            if len(calls) >= 3:  # pressure clears on the third poll
                dev.stats["bytes_in_use"] = 100
            return [dev]

        monkeypatch.setattr(obs_device, "_local_devices", devices)
        obs_device._reset()
        monkeypatch.setenv("TFT_SERVE_ADMISSION_WAIT_S", "5")
        monkeypatch.setenv("TFT_SERVE_ADMISSION_POLL_S", "0.01")
        with QueryScheduler(workers=0, name="admw") as sched:
            fut = sched.submit(_frame(8), lambda x: {"z": x + 1.0},
                               tenant="t", est_bytes=500)
            assert sched.step()
            out = fut.result(timeout=timing_margin(5))
            np.testing.assert_allclose(_z(out), np.arange(8.0) + 1.0)
            assert len(calls) >= 3
            assert tracing.counters.get("serve.admission_waits") == 1

    def test_cpu_backend_admits_freely(self):
        # no memory stats (the real CPU backend): admission must pass
        with QueryScheduler(workers=0, name="cpu") as sched:
            fut = sched.submit(_frame(8), tenant="t",
                               est_bytes=10 ** 15)
            assert sched.step()
            fut.result(timeout=timing_margin(5))
            assert fut.state == "done"


# ---------------------------------------------------------------------------
# shared compile cache
# ---------------------------------------------------------------------------

class TestSharedCompileCache:
    def _comp(self, fn):
        return Computation.trace(
            fn, [TensorSpec("x", double, Shape(Unknown))])

    def test_identical_programs_intern_to_one(self):
        cache = SharedCompileCache(capacity=8)
        c1 = self._comp(lambda x: {"z": x + 3.0})
        c2 = self._comp(lambda x: {"z": x + 3.0})
        assert computation_signature(c1) == computation_signature(c2)
        assert cache.intern(c1) is c1
        assert cache.intern(c2) is c1
        st = cache.stats()
        assert st["hits"] == 1 and st["misses"] == 1

    def test_different_programs_never_merge(self):
        cache = SharedCompileCache(capacity=8)
        add = self._comp(lambda x: {"z": x + 3.0})
        mul = self._comp(lambda x: {"z": x * 3.0})
        assert computation_signature(add) != computation_signature(mul)
        assert cache.intern(add) is add
        assert cache.intern(mul) is mul

    def test_captured_array_constants_distinguish(self):
        a = np.arange(4.0)
        b = np.arange(4.0) + 1.0
        ca = self._comp(lambda x: {"z": x[:4] + a})
        cb = self._comp(lambda x: {"z": x[:4] + b})
        sa, sb = computation_signature(ca), computation_signature(cb)
        if sa is not None and sb is not None:
            assert sa != sb

    def test_executor_hook_skips_recompiles(self):
        ex = BlockExecutor()
        x = np.arange(32.0)
        with QueryScheduler(workers=0, name="cc") as sched:
            c1 = self._comp(lambda x: {"z": x + 7.0})
            c2 = self._comp(lambda x: {"z": x + 7.0})
            ex.run(c1, {"x": x})
            ex.run(c2, {"x": x})  # interned -> same weak-keyed jit entry
            assert ex.compile_count == 1
            assert sched.compile_cache.stats()["hits"] >= 1
        # hook uninstalled on close: a fresh equivalent compiles anew
        c3 = self._comp(lambda x: {"z": x + 7.0})
        ex.run(c3, {"x": x})
        assert ex.compile_count == 2

    def test_lru_bound(self):
        cache = SharedCompileCache(capacity=2)
        comps = [self._comp(lambda x, k=float(k): {"z": x + k})
                 for k in range(4)]
        for c in comps:
            cache.intern(c)
        assert len(cache) == 2


# ---------------------------------------------------------------------------
# pipeline slot leasing
# ---------------------------------------------------------------------------

class TestSlotLeasing:
    def test_bounded_cross_stream_in_flight(self, monkeypatch):
        monkeypatch.setenv("TFT_PIPELINE_DEPTH", "3")
        pool = engine_pipeline.SlotPool(1)
        prev = engine_pipeline.install_slot_pool(pool)
        try:
            df = _frame(64, parts=8)
            out = df.map_blocks(lambda x: {"z": x * 2.0}, trim=True)
            z = np.concatenate([np.asarray(b.columns["z"])
                                for b in out.blocks()])
            np.testing.assert_allclose(z, np.arange(64.0) * 2.0)
            # one slot + depth 3 over 8 blocks MUST have waited
            assert tracing.counters.get("pipeline.slot_waits") >= 1
            # all leases returned
            assert pool._sem.acquire(blocking=False)
            pool.release()
        finally:
            engine_pipeline.install_slot_pool(prev)

    def test_no_lease_leak_on_error(self, monkeypatch):
        from tensorframes_tpu.resilience import faults

        monkeypatch.setenv("TFT_PIPELINE_DEPTH", "2")
        pool = engine_pipeline.SlotPool(2)
        prev = engine_pipeline.install_slot_pool(pool)
        try:
            df = _frame(16, parts=4)
            out = df.map_blocks(lambda x: {"z": x + 1.0}, trim=True)
            # every dispatch fails permanently: the drain raises with
            # blocks still in the window — their leases must come back
            with faults.inject("dispatch", fail_n=100, transient=False):
                with pytest.raises(Exception):
                    out.blocks()
            # both slots must be free again after the failed stream
            assert pool._sem.acquire(blocking=False)
            assert pool._sem.acquire(blocking=False)
            pool.release()
            pool.release()
        finally:
            engine_pipeline.install_slot_pool(prev)

    def test_concurrent_streams_share_the_pool(self, monkeypatch):
        monkeypatch.setenv("TFT_PIPELINE_DEPTH", "3")
        pool = engine_pipeline.SlotPool(3)
        prev = engine_pipeline.install_slot_pool(pool)
        try:
            def force(i):
                df = _frame(96, offset=i, parts=6)
                out = df.map_blocks(lambda x: {"z": x + 1.0}, trim=True)
                return np.concatenate(
                    [np.asarray(b.columns["z"]) for b in out.blocks()])

            with ThreadPoolExecutor(max_workers=4) as tp:
                outs = list(tp.map(force, range(4)))
            for i, z in enumerate(outs):
                np.testing.assert_allclose(z, np.arange(96.0) + i + 1.0)
            for _ in range(3):
                assert pool._sem.acquire(blocking=False)
        finally:
            engine_pipeline.install_slot_pool(prev)


# ---------------------------------------------------------------------------
# engine compile-cache thread safety (satellite fix)
# ---------------------------------------------------------------------------

class TestEngineCacheConcurrency:
    def test_block_executor_8_threads_one_compile_per_signature(self):
        ex = BlockExecutor()
        comp = Computation.trace(
            lambda x: {"z": x * 2.0 + 1.0},
            [TensorSpec("x", double, Shape(Unknown))])
        shapes = [16, 32, 64]
        errors = []

        def hammer(seed):
            rng = np.random.default_rng(seed)
            for _ in range(12):
                n = shapes[int(rng.integers(len(shapes)))]
                x = np.arange(float(n))
                out = ex.run(comp, {"x": x})
                if not np.allclose(out["z"], x * 2.0 + 1.0):
                    errors.append((seed, n))

        threads = [threading.Thread(target=hammer, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        # one compile per distinct signature, regardless of racing
        assert ex.compile_count == len(shapes)

    def test_fetches_cache_converges_on_one_computation(self):
        fetch = lambda x: {"z": x + 5.0}  # noqa: E731 - shared object
        df = _frame(8)
        schema = df.schema
        seen = set()
        lock = threading.Lock()

        def build():
            comp = engine_ops.cached_map_computation(
                fetch, schema, block_level=True)
            with lock:
                seen.add(id(comp))

        threads = [threading.Thread(target=build) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(seen) == 1  # all 8 threads share ONE Computation

    def test_concurrent_forcings_through_shared_executor(self):
        # the serving layer's real access pattern: many frames forced in
        # parallel through the process-default executors
        def make_fetch(i):
            return lambda x: {"z": x - float(i)}

        def work(i):
            df = _frame(64, offset=i, parts=4)
            out = df.map_blocks(make_fetch(i), trim=True)
            z = np.concatenate([np.asarray(b.columns["z"])
                                for b in out.blocks()])
            np.testing.assert_allclose(z, np.arange(64.0))

        with ThreadPoolExecutor(max_workers=8) as tp:
            list(tp.map(work, range(16)))


# ---------------------------------------------------------------------------
# concurrent traced queries under the scheduler (satellite)
# ---------------------------------------------------------------------------

class TestConcurrentTracedQueries:
    def test_distinct_ids_no_track_collisions_fair_completion(
            self, monkeypatch):
        monkeypatch.setenv("TFT_PIPELINE_DEPTH", "3")
        tracing.enable()
        tenants = ["t0", "t1", "t2"]
        per = 4
        quotas = {t: TenantQuota(weight=1.0) for t in tenants}
        with QueryScheduler(quotas=quotas, workers=3,
                            name="traced") as sched:
            futs = {}
            for t in tenants:
                for k in range(per):
                    futs[(t, k)] = sched.submit(
                        _frame(60, offset=k, parts=5),
                        lambda x: {"z": x + 2.0}, tenant=t)
            for (t, k), fut in futs.items():
                z = _z(fut.result(timeout=120))
                np.testing.assert_allclose(z,
                                           np.arange(60.0) + k + 2.0)
            snap = sched.snapshot()
            # fair completion: equal weights -> equal shares (exactly,
            # since every query completed)
            done = [snap[t]["completed"] for t in tenants]
            assert done == [per] * len(tenants)

        # distinct correlation ids: one per serving query
        events = obs.recent_events()
        serve_starts = [e for e in events if e["type"] == "sched_start"]
        qids = {e["query_id"] for e in serve_starts}
        assert len(serve_starts) == len(tenants) * per
        assert len(qids) == len(tenants) * per  # no id reuse
        # no track collisions: per query, block events stay on the slot
        # tracks (1..depth) or device tracks; track 0 is the query span
        by_query = {}
        for e in events:
            if e["type"] in ("block_submit", "block_drain", "block_run"):
                by_query.setdefault(e["query_id"], set()).add(e["track"])
        for qid, tracks in by_query.items():
            assert all(
                1 <= tr <= 3 or tr >= obs.DEVICE_TRACK_BASE
                for tr in tracks), (qid, tracks)
        # per-tenant latency series exist for the p99 surface
        fams = {k[1] for k in tracing.histograms.snapshot()
                if k[0] == "query_latency_seconds"}
        labelled = {dict(lab).get("tenant") for lab in fams}
        assert set(tenants) <= labelled


# ---------------------------------------------------------------------------
# metrics endpoint (satellite)
# ---------------------------------------------------------------------------

class TestServeMetrics:
    def test_live_gauges_and_charset(self):
        with QueryScheduler(workers=0, name="met") as sched:
            sched.register_tenant("alpha", TenantQuota(max_queue=4))
            sched.submit(_frame(8), tenant="alpha")
            text = obs.metrics_text()
            assert 'tft_serve_queue_depth{tenant="alpha"} 1' in text
            assert 'tft_serve_inflight{tenant="alpha"} 0' in text
            assert ('tft_serve_queries_total{tenant="alpha",'
                    'outcome="submitted"} 1') in text
            port = obs.serve_metrics(0)
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/metrics",
                        timeout=5) as resp:
                    ctype = resp.headers.get("Content-Type", "")
                    body = resp.read().decode("utf-8")
                assert "charset=utf-8" in ctype
                assert "tft_serve_queue_depth" in body
            finally:
                obs.stop_metrics()
            # draining the queue zeroes the live gauge
            assert sched.step()
            text = obs.metrics_text()
            assert 'tft_serve_queue_depth{tenant="alpha"} 0' in text
        # provider unregistered with the scheduler
        assert "tft_serve_queue_depth" not in obs.metrics_text()

    def test_provider_failure_never_breaks_the_endpoint(self):
        from tensorframes_tpu.observability import metrics as obs_metrics

        obs_metrics.register_metrics_provider(
            "boom", lambda: (_ for _ in ()).throw(RuntimeError("x")))
        try:
            text = obs.metrics_text()
            assert "tft_counter_total" in text  # still renders
        finally:
            obs_metrics.unregister_metrics_provider("boom")


# ---------------------------------------------------------------------------
# API entry points and lifecycle
# ---------------------------------------------------------------------------

class TestApiAndLifecycle:
    def test_tft_submit_and_frame_submit(self):
        df = _frame(16)
        fut = tft.submit(df, lambda x: {"z": x + 9.0}, tenant="api")
        np.testing.assert_allclose(_z(fut.result(timeout=60)),
                                   np.arange(16.0) + 9.0)
        fut2 = _frame(8).submit(tenant="api")
        forced = fut2.result(timeout=60)
        assert forced.count() == 8
        assert "api" in serve.serve_report()
        serve.shutdown_default_scheduler()

    def test_close_fails_queued_queries_classified(self):
        sched = QueryScheduler(workers=0, name="close")
        fut = sched.submit(_frame(8), tenant="t")
        sched.close()
        with pytest.raises(ServeRejected):
            fut.result(timeout=1)
        # the three stats surfaces agree: state, per-tenant counts, and
        # the flat counter all say "rejected"
        assert fut.state == "rejected"
        assert sched.snapshot()["t"]["rejected"] == 1
        assert tracing.counters.get("serve.rejected") == 1
        with pytest.raises(RuntimeError):
            sched.submit(_frame(8), tenant="t")
        sched.close()  # idempotent

    def test_requota_active_tenant_keeps_queue_and_inflight(self):
        with QueryScheduler(workers=0, name="requota") as sched:
            sched.register_tenant("t", TenantQuota(max_queue=1))
            fut = sched.submit(_frame(8), lambda x: {"z": x + 1.0},
                               tenant="t")
            # re-quota while a query is queued: the queue must survive
            sched.register_tenant("t", TenantQuota(max_queue=8,
                                                   weight=3.0))
            assert sched.snapshot()["t"]["queued"] == 1
            for _ in range(3):  # widened cap admits more
                sched.submit(_frame(4), tenant="t")
            assert sched.step()
            np.testing.assert_allclose(_z(fut.result(timeout=30)),
                                       np.arange(8.0) + 1.0)
            while sched.step():
                pass
            snap = sched.snapshot()
            assert snap["t"]["completed"] == 4
            assert snap["t"]["inflight"] == 0  # accounting intact

    def test_scheduler_restores_previous_hooks(self):
        pool = engine_pipeline.SlotPool(7)
        prev = engine_pipeline.install_slot_pool(pool)
        try:
            with QueryScheduler(workers=0, name="nest"):
                assert engine_pipeline.current_slot_pool() is not pool
            assert engine_pipeline.current_slot_pool() is pool
        finally:
            engine_pipeline.install_slot_pool(prev)

    def test_out_of_order_close_keeps_live_scheduler_hooks(self):
        from tensorframes_tpu.engine import executor as engine_executor

        a = QueryScheduler(workers=0, name="older")
        b = QueryScheduler(workers=0, name="newer")
        try:
            # closing the OLDER scheduler first must not strip the live
            # newer one of its slot pool or interner, nor resurrect the
            # older one's on b.close()
            a.close()
            assert engine_pipeline.current_slot_pool() is b.slot_pool
            assert engine_executor.current_computation_interner() \
                is b._interner_fn
        finally:
            b.close()
        assert engine_pipeline.current_slot_pool() is None
        assert engine_executor.current_computation_interner() is None
