"""Schema/metadata layer tests (ColumnInformation/DataFrameInfo analogue)."""

import numpy as np
import pytest

from tensorframes_tpu import dtypes as dt
from tensorframes_tpu.schema import Field, Schema, SHAPE_KEY, TYPE_KEY
from tensorframes_tpu.shape import Shape, Unknown


def test_dtype_registry():
    assert dt.by_name("double") is dt.double
    assert dt.by_name("f32") is dt.float32
    assert dt.from_numpy(np.float64) is dt.double
    assert dt.from_numpy(np.int16) is dt.int32
    assert dt.from_python_value(1.5) is dt.double
    assert dt.from_python_value(3) is dt.int64
    with pytest.raises(ValueError):
        dt.by_name("complex128")


def test_widen():
    assert dt.widen(dt.int32, dt.int64) is dt.int64
    assert dt.widen(dt.float32, dt.double) is dt.double
    assert dt.widen(dt.int64, dt.float32) is dt.float32
    assert dt.widen(dt.int32, dt.double) is dt.double


def test_scalar_field_block_shape():
    s = Schema.of(x="double", n="int")
    assert s["x"].block_shape == Shape(Unknown)
    assert s["x"].cell_shape == Shape.empty
    assert s["n"].dtype is dt.int32


def test_schema_duplicate_names_rejected():
    with pytest.raises(ValueError, match="Duplicate"):
        Schema([Field("x", dt.double), Field("x", dt.int32)])


def test_meta_roundtrip():
    f = Field("v", dt.double).with_block_shape(Shape(Unknown, 3))
    meta = f.to_meta()
    assert meta[SHAPE_KEY] == [Unknown, 3]
    assert meta[TYPE_KEY] == "double"
    g = Field.from_meta("v", dt.double, meta, sql_rank=1)
    assert g.block_shape == Shape(Unknown, 3)
    assert g.cell_shape == Shape(3)


def test_field_merge_refines_unknowns():
    a = Field("v", dt.double).with_block_shape(Shape(Unknown, Unknown))
    b = Field("v", dt.double).with_block_shape(Shape(Unknown, 3))
    assert a.merged(b).block_shape == Shape(Unknown, 3)
    # concrete info wins over none
    c = Field("v", dt.double, sql_rank=1)
    assert c.merged(b).block_shape == Shape(Unknown, 3)
    with pytest.raises(ValueError, match="ranks differ"):
        a.merged(Field("v", dt.double).with_block_shape(Shape(Unknown)))
    with pytest.raises(ValueError, match="dims conflict"):
        b.merged(Field("v", dt.double).with_block_shape(Shape(Unknown, 4)))
    with pytest.raises(ValueError, match="dtypes differ"):
        b.merged(Field("v", dt.int32).with_block_shape(Shape(Unknown, 3)))


def test_from_meta_derives_sql_rank():
    f = Field("v", dt.double).with_block_shape(Shape(Unknown, 3))
    g = Field.from_meta("v", dt.double, f.to_meta())
    assert g.sql_rank == 1
    assert g.type_string() == "array<double>"


def test_schema_from_numpy_columns():
    s = Schema.from_numpy_columns({
        "x": np.zeros((5,), np.float64),
        "v": np.zeros((5, 3), np.float32),
    })
    assert s["x"].block_shape == Shape(Unknown)
    assert s["v"].block_shape == Shape(Unknown, 3)
    assert s["v"].sql_rank == 1
    assert s["v"].type_string() == "array<float>"


def test_schema_select_append_replace():
    s = Schema.of(a="double", b="int", c="long")
    assert s.select(["c", "a"]).names == ["c", "a"]
    s2 = s.append([Field("d", dt.float32)])
    assert s2.names == ["a", "b", "c", "d"]
    f = s["b"].with_block_shape(Shape(Unknown))
    assert s.replace_field(f)["b"].block_shape == Shape(Unknown)
    with pytest.raises(KeyError):
        s["nope"]


def test_tree_string():
    s = Schema.of(x="double")
    out = s.tree_string()
    assert "root" in out and "x: double" in out and "[?]" in out


def test_object_column_rejected_unless_strings():
    import numpy as np
    import pytest
    import tensorframes_tpu as tft
    from tensorframes_tpu.schema import Schema

    with pytest.raises(ValueError, match="non-string Python objects"):
        Schema.from_numpy_columns(
            {"c": np.array([{"a": 1}, {"b": 2}], dtype=object)})
    s = Schema.from_numpy_columns({"k": np.array(["a", "b"], dtype=object)})
    assert s["k"].dtype.name == "string"
    assert not s["k"].dtype.tensor
