"""End-to-end op integration tests.

Mirrors the reference's test strategy (SURVEY.md §4): build a computation,
run an op on a real local frame, compare collected rows — including
multi-partition frames to force the cross-partition reduce/merge paths, and
type-parametric replication over double/int/long
(reference ``BasicOperationsSuite.scala``, ``type_suites.scala``).
"""

import jax.numpy as jnp
import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu import dtypes as dt
from tensorframes_tpu.engine import (
    CompactionBuffer, InputNotFoundError, InvalidShapeError, InvalidTypeError)
from tensorframes_tpu.engine import ops as engine_ops
from tensorframes_tpu.frame import Block, TensorFrame
from tensorframes_tpu.schema import Field, Schema
from tensorframes_tpu.shape import Shape, Unknown


# ---------------------------------------------------------------------------
# map_blocks
# ---------------------------------------------------------------------------

def test_map_blocks_readme_x_plus_3():
    # README.md:56-87 — the flagship acceptance slice
    df = tft.frame({"x": np.arange(10.0)}, num_partitions=3)
    df2 = tft.map_blocks(lambda x: {"z": x + 3.0}, df)
    rows = df2.collect()
    assert df2.columns == ["x", "z"]
    assert [r["z"] for r in rows] == [x + 3.0 for x in range(10)]


def test_map_blocks_is_lazy():
    # The computation is frozen (traced) at call time — like the reference,
    # where the GraphDef is serialized eagerly (core.py:183-184) — but no
    # block executes until the frame is forced.
    df = tft.frame({"x": np.arange(4.0)})
    df2 = tft.map_blocks(lambda x: {"z": x * 2}, df)
    assert df2._cache is None  # nothing materialized yet
    df2.collect()
    assert df2._cache is not None


def test_map_blocks_multiple_fetches_sorted():
    df = tft.frame({"x": np.arange(4.0)})
    df2 = tft.map_blocks(lambda x: {"b": x + 1, "a": x - 1}, df)
    assert df2.columns == ["x", "a", "b"]  # fetches sorted by name


def test_map_blocks_vector_column():
    df = tft.frame({"v": np.arange(12.0).reshape(6, 2)}, num_partitions=2)
    df2 = tft.map_blocks(lambda v: {"s": jnp.sum(v, axis=1)}, df)
    np.testing.assert_allclose(
        [r["s"] for r in df2.collect()],
        np.arange(12.0).reshape(6, 2).sum(axis=1))


def test_map_blocks_2d_cells():
    m = np.arange(24.0).reshape(2, 3, 4)
    df = tft.frame({"m": m})
    df2 = tft.map_blocks(lambda m: {"t": m * 2.0}, df)
    np.testing.assert_allclose(df2.collect()[1]["t"], m[1] * 2)


def test_map_blocks_name_collision():
    df = tft.frame({"x": np.arange(3.0)})
    with pytest.raises(ValueError, match="collides"):
        tft.map_blocks(lambda x: {"x": x}, df)


def test_map_blocks_missing_column():
    df = tft.frame({"x": np.arange(3.0)})
    with pytest.raises(InputNotFoundError, match="no matching column"):
        tft.map_blocks(lambda y: {"z": y}, df)


def test_map_blocks_dtype_mismatch():
    from tensorframes_tpu.computation import Computation, TensorSpec
    comp = Computation.trace(
        lambda x: {"z": x + 1},
        [TensorSpec("x", dt.int32, Shape(Unknown))])
    df = tft.frame({"x": np.arange(3.0)})  # double column
    with pytest.raises(InvalidTypeError, match="no implicit casting"):
        tft.map_blocks(comp, df)


def test_map_blocks_row_count_change_requires_trim():
    df = tft.frame({"x": np.arange(6.0)})
    bad = tft.map_blocks(lambda x: {"z": x[:3]}, df)
    with pytest.raises(InvalidShapeError, match="trim"):
        bad.collect()


def test_map_blocks_trim_fewer_rows():
    # TrimmingOperationsSuite analogue: per-block row-count change
    df = tft.frame({"x": np.arange(6.0)}, num_partitions=2)
    df2 = tft.map_blocks(lambda x: {"z": x[:2]}, df, trim=True)
    assert df2.columns == ["z"]
    assert df2.count() == 4  # 2 per partition


def test_map_blocks_trim_more_rows():
    df = tft.frame({"x": np.arange(2.0)})
    df2 = tft.map_blocks(
        lambda x: {"z": jnp.concatenate([x, x, x])}, df, trim=True)
    assert df2.count() == 6


def test_map_blocks_empty_partition():
    s = Schema.of(x="double")
    blocks = [Block({"x": np.array([1.0, 2.0])}),
              Block({"x": np.empty((0,))}, 0)]
    df = TensorFrame.from_blocks(blocks, s)
    df2 = tft.map_blocks(lambda x: {"z": x + 1.0}, df)
    assert [r["z"] for r in df2.collect()] == [2.0, 3.0]


def test_map_blocks_block_global_computation():
    # non-row-local computations must see the true block (no padding)
    df = tft.frame({"x": np.arange(5.0)})
    df2 = tft.map_blocks(lambda x: {"c": x - jnp.mean(x)}, df)
    np.testing.assert_allclose(
        [r["c"] for r in df2.collect()],
        np.arange(5.0) - 2.0)


# ---------------------------------------------------------------------------
# map_rows
# ---------------------------------------------------------------------------

def test_map_rows_scalar():
    df = tft.frame({"x": np.arange(5.0)}, num_partitions=2)
    df2 = tft.map_rows(lambda x: {"z": x * x}, df)
    assert [r["z"] for r in df2.collect()] == [x * x for x in range(5)]


def test_map_rows_ragged_cells():
    # BasicOperationsSuite "Identity - 1 dim with unknown size" analogue
    s = Schema([Field("v", dt.double, sql_rank=1)])
    df = TensorFrame.from_rows([([1.0, 2.0],), ([3.0, 4.0, 5.0],)], schema=s)
    df = tft.analyze(df)  # stamps cell shape [?]
    df2 = tft.map_rows(lambda v: {"s": jnp.sum(v)}, df)
    assert [r["s"] for r in df2.collect()] == [3.0, 12.0]


def test_map_rows_ragged_identity_output():
    s = Schema([Field("v", dt.double, sql_rank=1)])
    df = TensorFrame.from_rows([([1.0, 2.0],), ([3.0],)], schema=s)
    df = tft.analyze(df)
    df2 = tft.map_rows(lambda v: {"w": v * 2.0}, df)
    rows = df2.collect()
    np.testing.assert_allclose(rows[0]["w"], [2.0, 4.0])
    np.testing.assert_allclose(rows[1]["w"], [6.0])


def test_map_rows_collision():
    df = tft.frame({"x": np.arange(3.0)})
    with pytest.raises(ValueError, match="collides"):
        tft.map_rows(lambda x: {"x": x}, df)


def test_map_rows_compile_cache_bounded():
    # SURVEY.md §7 hard part #1: a stream of odd-sized blocks must NOT
    # compile once per distinct row count — the default map_rows executor
    # pads rows to power-of-two buckets, so 50 sizes share O(log) compiles.
    from tensorframes_tpu.engine.executor import BlockExecutor
    ex = BlockExecutor(pad_rows=True)
    s = Schema.of(x="double")
    sizes = list(range(1, 51))
    blocks = [Block({"x": np.arange(float(n))}, n) for n in sizes]
    df = TensorFrame.from_blocks(blocks, s)
    df2 = engine_ops.map_rows(lambda x: {"z": x + 1.0}, df, executor=ex)
    rows = df2.collect()
    assert len(rows) == sum(sizes)
    expect = [x + 1.0 for n in sizes for x in np.arange(float(n))]
    assert [r["z"] for r in rows] == expect
    # buckets 8,16,32,64 -> at most ceil(log2(50)) distinct signatures
    assert ex.compile_count <= 6, ex.compile_count


def test_map_rows_ragged_compile_cache_bounded():
    # ragged cells: group sizes bucket the same way (one compile per
    # power-of-two bucket x cell-shape, not one per distinct group size)
    from tensorframes_tpu.engine.executor import BlockExecutor
    ex = BlockExecutor(pad_rows=True)
    s = Schema([Field("v", dt.double, sql_rank=1)])
    rng = np.random.default_rng(7)
    rows = []
    for width in (2, 3):  # two distinct cell shapes
        for _ in range(30):
            rows.append((list(rng.normal(size=width)),))
    df = tft.analyze(TensorFrame.from_rows(rows, schema=s))
    df2 = engine_ops.map_rows(lambda v: {"sm": jnp.sum(v)}, df, executor=ex)
    got = [r["sm"] for r in df2.collect()]
    np.testing.assert_allclose(got, [np.sum(r[0]) for r in rows], rtol=1e-9)
    # 2 cell shapes x <= ceil(log2(30)) buckets
    assert ex.compile_count <= 12, ex.compile_count


# ---------------------------------------------------------------------------
# reduce_rows / reduce_blocks
# ---------------------------------------------------------------------------

def test_reduce_rows_sum():
    df = tft.frame({"x": np.arange(10.0)}, num_partitions=3)
    out = tft.reduce_rows(lambda x_1, x_2: {"x": x_1 + x_2}, df)
    assert out == pytest.approx(45.0)


def test_reduce_rows_single_partition_single_row():
    df = tft.frame({"x": np.array([7.0])})
    assert tft.reduce_rows(lambda x_1, x_2: {"x": x_1 + x_2}, df) == 7.0


def test_reduce_rows_naming_contract():
    df = tft.frame({"x": np.arange(4.0)})
    with pytest.raises(InputNotFoundError, match="naming"):
        tft.reduce_rows(lambda a, b: {"x": a + b}, df)


def test_reduce_blocks_sum_min_vector():
    # README reduce example over a vector column
    v = np.arange(12.0).reshape(4, 3)
    df = tft.frame({"x": v}, num_partitions=2)
    out = tft.reduce_blocks(
        lambda x_input: {"x": jnp.sum(x_input, axis=0)}, df)
    np.testing.assert_allclose(out, v.sum(axis=0))
    out = tft.reduce_blocks(
        lambda x_input: {"x": jnp.min(x_input, axis=0)}, df)
    np.testing.assert_allclose(out, v.min(axis=0))


def test_reduce_blocks_multiple_fetches():
    df = tft.frame({"x": np.arange(6.0), "y": np.arange(6.0) * 2},
                   num_partitions=2)
    out = tft.reduce_blocks(
        lambda x_input, y_input: {"x": jnp.sum(x_input, axis=0),
                                  "y": jnp.max(y_input, axis=0)}, df)
    # fetches sorted by name: x then y
    assert out[0] == pytest.approx(15.0)
    assert out[1] == pytest.approx(10.0)


def test_reduce_blocks_unused_column_ignored():
    # ported reference scenario (BasicOperationsSuite.scala:178-187):
    # a string ride-along column the reduction does not consume is
    # ignored — reduce_sum over x returns 4.1, key2 simply drops out
    df = tft.frame({"key2": np.array(["1", "2", "3"], object),
                    "x": np.array([1.0, 1.1, 2.0])})
    out = tft.reduce_blocks(lambda x_input: {"x": jnp.sum(x_input)}, df)
    assert float(out) == pytest.approx(4.1)


def test_reduce_blocks_unused_numeric_column_ignored_multipartition():
    # reference BasicOperationsSuite.scala:189-198: same tolerance with
    # an explicit 2-partition frame forcing the cross-partition combine
    df = tft.frame({"x": np.array([1.0, 2.0]),
                    "junk": np.array([7.0, 8.0])}, num_partitions=2)
    out = tft.reduce_blocks(lambda x_input: {"x": jnp.sum(x_input)}, df)
    assert float(out) == pytest.approx(3.0)


def test_reduce_blocks_missing_input_for_fetch():
    df = tft.frame({"x": np.arange(4.0)})
    with pytest.raises(InputNotFoundError, match="missing required"):
        from tensorframes_tpu.computation import Computation, TensorSpec
        comp = Computation.trace(
            lambda x_input: {"x": jnp.sum(x_input), "y": jnp.sum(x_input)},
            [TensorSpec("x_input", dt.double, Shape(Unknown))])
        tft.reduce_blocks(comp, df)


def test_reduce_blocks_empty_frame():
    df = tft.frame({"x": np.empty((0,))})
    with pytest.raises(ValueError, match="empty"):
        tft.reduce_blocks(lambda x_input: {"x": jnp.sum(x_input)}, df)


def test_reduce_blocks_empty_partition_skipped():
    s = Schema.of(x="double")
    blocks = [Block({"x": np.array([1.0, 2.0])}),
              Block({"x": np.empty((0,))}, 0),
              Block({"x": np.array([3.0])})]
    df = TensorFrame.from_blocks(blocks, s)
    out = tft.reduce_blocks(lambda x_input: {"x": jnp.sum(x_input)}, df)
    assert out == pytest.approx(6.0)


# ---------------------------------------------------------------------------
# aggregate
# ---------------------------------------------------------------------------

def test_aggregate_sum_by_key():
    df = tft.frame(
        {"key": np.array([1, 1, 2, 2, 2], np.int64),
         "x": np.array([1.0, 2.0, 3.0, 4.0, 5.0])},
        num_partitions=2)
    out = tft.aggregate(lambda x_input: {"x": jnp.sum(x_input, axis=0)},
                        df.group_by("key"))
    rows = sorted(out.collect(), key=lambda r: r["key"])
    assert [(r["key"], r["x"]) for r in rows] == [(1, 3.0), (2, 12.0)]


def test_aggregate_compaction_over_buffer_size():
    n = 37  # > buffer_size to force compactions
    df = tft.frame({"key": np.ones(n, np.int64),
                    "x": np.arange(float(n))})
    out = tft.aggregate(lambda x_input: {"x": jnp.sum(x_input, axis=0)},
                        df.group_by("key"), buffer_size=4)
    assert out.collect()[0]["x"] == pytest.approx(sum(range(n)))


def test_aggregate_vector_values_and_multi_key():
    df = tft.frame(
        {"k1": np.array([0, 0, 1, 1], np.int64),
         "k2": np.array([0, 1, 0, 0], np.int64),
         "v": np.arange(8.0).reshape(4, 2)})
    out = tft.aggregate(lambda v_input: {"v": jnp.sum(v_input, axis=0)},
                        df.group_by("k1", "k2"))
    rows = sorted(out.collect(), key=lambda r: (r["k1"], r["k2"]))
    assert len(rows) == 3
    np.testing.assert_allclose(rows[2]["v"], [10.0, 12.0])  # rows 2+3


def test_aggregate_monoid_matches_compaction_path():
    # the {col: combiner} fast path must agree with the generic UDAF path
    rng = np.random.default_rng(3)
    n, g = 5_000, 100
    keys = rng.integers(0, g, n)
    vals = rng.normal(size=n)
    df = tft.frame({"key": keys, "x": vals}, num_partitions=4)
    fast = tft.aggregate({"x": "sum"}, df.group_by("key"))
    slow = tft.aggregate(lambda x_input: {"x": jnp.sum(x_input, axis=0)},
                         df.group_by("key"))
    f = {r["key"]: r["x"] for r in fast.collect()}
    s = {r["key"]: r["x"] for r in slow.collect()}
    assert set(f) == set(s)
    for k in f:
        assert f[k] == pytest.approx(s[k], rel=1e-9)


def test_aggregate_monoid_many_keys_single_dispatch_scale():
    # 200k rows x 10k keys completes through ONE segment-reduce launch per
    # fetch (the generic path would pay 10k compaction loops)
    rng = np.random.default_rng(4)
    n, g = 200_000, 10_000
    keys = rng.integers(0, g, n)
    vals = np.ones(n)
    df = tft.frame({"key": keys, "x": vals})
    out = tft.aggregate({"x": "sum"}, df.group_by("key"))
    rows = out.collect()
    assert len(rows) == len(np.unique(keys))
    counts = np.bincount(keys, minlength=g)
    got = {r["key"]: r["x"] for r in rows}
    for k in (0, 1, g - 1):
        if counts[k]:
            assert got[k] == pytest.approx(counts[k])
    assert sum(got.values()) == pytest.approx(n)


def test_aggregate_monoid_min_max_multi_key_vector():
    rng = np.random.default_rng(5)
    df = tft.frame(
        {"k1": np.array([0, 0, 1, 1, 1], np.int64),
         "k2": np.array([0, 1, 0, 0, 1], np.int64),
         "v": rng.normal(size=(5, 3))})
    out = tft.aggregate({"v": "min"}, df.group_by("k1", "k2"))
    rows = sorted(out.collect(), key=lambda r: (r["k1"], r["k2"]))
    data = df.blocks()[0].dense("v")
    np.testing.assert_allclose(rows[2]["v"], data[2:4].min(axis=0))


def test_aggregate_monoid_integer_sum_exact():
    # int aggregation must stay exact (routes to the XLA scatter path)
    n = 100_000
    df = tft.frame({"key": np.zeros(n, np.int64),
                    "x": np.full(n, 16_777_217, np.int64)})  # > 2^24
    out = tft.aggregate({"x": "sum"}, df.group_by("key"))
    assert out.collect()[0]["x"] == n * 16_777_217


def test_aggregate_monoid_unknown_column_and_combiner():
    df = tft.frame({"key": np.zeros(3, np.int64), "x": np.arange(3.0)})
    with pytest.raises(InputNotFoundError, match="match no value column"):
        tft.aggregate({"y": "sum"}, df.group_by("key"))
    with pytest.raises(ValueError, match="Unknown combiner"):
        tft.aggregate({"x": "mean"}, df.group_by("key"))


def test_aggregate_unused_value_column_ignored():
    # consistent with the reduce ride-along contract: the extra value
    # column drops out of the per-group result rows
    df = tft.frame({"key": np.zeros(3, np.int64), "x": np.arange(3.0),
                    "extra": np.arange(3.0)})
    out = tft.aggregate(lambda x_input: {"x": jnp.sum(x_input, axis=0)},
                        df.group_by("key"))
    rows = out.collect()
    assert len(rows) == 1 and rows[0]["x"] == pytest.approx(3.0)
    assert "extra" not in out.schema.names


# ---------------------------------------------------------------------------
# type-parametric replication (type_suites.scala analogue)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("np_dt,expected_dt", [
    (np.float64, "double"), (np.int32, "int"), (np.int64, "long"),
    (np.float32, "float"),
])
def test_map_and_reduce_all_scalar_types(np_dt, expected_dt):
    data = np.arange(1, 7).astype(np_dt)
    df = tft.frame({"x": data}, num_partitions=2)
    assert df.schema["x"].dtype.name == expected_dt
    df2 = tft.map_blocks(lambda x: {"z": x + x}, df)
    assert [r["z"] for r in df2.collect()] == [2 * x for x in range(1, 7)]
    # jnp.sum promotes int32 -> int64; the contract demands exact dtype
    # equality between fetch and input, so the cast is explicit.
    out = tft.reduce_blocks(
        lambda x_input: {"x": jnp.sum(x_input, axis=0).astype(x_input.dtype)},
        df)
    assert out == pytest.approx(21)


# ---------------------------------------------------------------------------
# CompactionBuffer unit tests (TensorFlowUDAF contract)
# ---------------------------------------------------------------------------

def _sum_reduce(block):
    return {"x": np.sum(block["x"], axis=0)}


def test_compaction_buffer_update_and_evaluate():
    buf = CompactionBuffer(["x"], _sum_reduce, buffer_size=3)
    for i in range(7):
        buf.update({"x": np.float64(i)})
        assert len(buf) < 3  # compacts at the threshold
    assert buf.evaluate()["x"] == 21.0


def test_compaction_buffer_merge():
    a = CompactionBuffer(["x"], _sum_reduce, buffer_size=10)
    b = CompactionBuffer(["x"], _sum_reduce, buffer_size=10)
    for i in range(4):
        a.update({"x": np.float64(i)})
        b.update({"x": np.float64(10 + i)})
    a.merge(b)
    assert a.evaluate()["x"] == sum(range(4)) + sum(range(10, 14))


def test_compaction_buffer_empty_evaluate_raises():
    buf = CompactionBuffer(["x"], _sum_reduce)
    with pytest.raises(ValueError, match="empty"):
        buf.evaluate()


# ---------------------------------------------------------------------------
# analyze / print_schema / explain
# ---------------------------------------------------------------------------

def test_analyze_stamps_vector_shape():
    s = Schema([Field("v", dt.double, sql_rank=1)])
    df = TensorFrame.from_rows([([1.0, 2.0],), ([3.0, 4.0],)], schema=s)
    assert df.schema["v"].block_shape is None
    df2 = tft.analyze(df)
    assert df2.schema["v"].block_shape == Shape(2, 2)
    # ops now accept the vector column
    out = tft.reduce_blocks(
        lambda v_input: {"v": jnp.sum(v_input, axis=0)}, df2)
    np.testing.assert_allclose(out, [4.0, 6.0])


def test_analyze_variable_sizes_to_unknown():
    # ExtraOperationsSuite analogue: disagreeing dims become Unknown
    s = Schema([Field("v", dt.double, sql_rank=1)])
    df = TensorFrame.from_rows([([1.0, 2.0],), ([3.0],)], schema=s)
    df2 = tft.analyze(df)
    assert df2.schema["v"].block_shape == Shape(2, Unknown)


def test_analyze_multi_partition_lead_dim():
    df = tft.frame({"x": np.arange(5.0)}, num_partitions=2)  # sizes 3,2
    df2 = tft.analyze(df)
    assert df2.schema["x"].block_shape == Shape(Unknown)


def test_explain_and_print_schema(capsys):
    df = tft.frame({"x": np.arange(3.0)})
    text = tft.explain(df)
    assert "x: double" in text
    tft.print_schema(df)
    out = capsys.readouterr().out
    assert "root" in out and "x: double" in out


def test_block_ops_without_analyze_rejected():
    s = Schema([Field("v", dt.double, sql_rank=1)])
    df = TensorFrame.from_rows([([1.0, 2.0],)], schema=s)
    with pytest.raises(InvalidShapeError, match="analyze"):
        tft.map_blocks(lambda v: {"z": v * 2}, df)


def test_aggregate_generic_many_groups_single_program():
    # The generic (non-monoid) path must not degrade to O(groups)
    # dispatches: 10k distinct keys fold through one compiled segmented
    # scan (VERDICT r2 weak #6). Correctness vs numpy per group.
    import jax.numpy as jnp

    rng = np.random.default_rng(5)
    n, G = 40_000, 10_000
    key = rng.integers(0, G, n).astype(np.int32)
    x = rng.standard_normal(n)
    df = tft.analyze(tft.frame({"k": key, "x": x}))
    out = tft.aggregate(lambda x_input: {"x": jnp.sqrt((x_input**2).sum(0))},
                        df.group_by("k"))
    rows = out.collect()
    assert len(rows) == len(np.unique(key))
    got = {r["k"]: r["x"] for r in rows}
    for k in list(got)[:50]:
        np.testing.assert_allclose(
            got[k], np.sqrt((x[key == k] ** 2).sum()), rtol=1e-5)


class TestFilterRows:
    def test_basic_predicate(self):
        df = tft.frame({"x": np.arange(10, dtype=np.float64)})
        out = tft.filter_rows(lambda x: x >= 4.0, df)
        assert [r["x"] for r in out.collect()] == [4.0, 5.0, 6.0, 7.0,
                                                   8.0, 9.0]
        # schema unchanged, laziness: a fresh collect recomputes fine
        assert out.schema.names == ["x"]
        assert len(out.collect()) == 6

    def test_fluent_and_multi_column(self):
        df = tft.frame({"x": np.arange(8, dtype=np.float64),
                        "y": np.array([1.0, -1.0] * 4)})
        out = df.filter(lambda x, y: (x > 2.0) & (y > 0.0)).collect()
        assert [(r["x"], r["y"]) for r in out] == [(4.0, 1.0), (6.0, 1.0)]

    def test_vector_column_predicate(self):
        df = tft.analyze(tft.frame({"v": np.arange(12.0).reshape(4, 3)}))
        out = tft.filter_rows(lambda v: v.sum(axis=1) > 10.0, df).collect()
        assert len(out) == 3

    def test_string_columns_ride_through(self):
        df = tft.frame({"k": np.array(["a", "b", "c", "d"], object),
                        "x": np.arange(4, dtype=np.float64)})
        rows = tft.filter_rows(lambda x: x % 2.0 == 0.0, df).collect()
        assert [(r["k"], r["x"]) for r in rows] == [("a", 0.0), ("c", 2.0)]

    def test_empty_blocks_and_all_dropped(self):
        df = tft.frame({"x": np.arange(6, dtype=np.float64)},
                       num_partitions=3)
        out = tft.filter_rows(lambda x: x < 0.0, df)
        assert out.collect() == []
        assert out.count() == 0

    def test_validation(self):
        df = tft.analyze(tft.frame({"x": np.arange(4, dtype=np.float64),
                                    "v": np.ones((4, 2))}))
        with pytest.raises(engine_ops.InvalidShapeError,
                           match="exactly one fetch"):
            tft.filter_rows(lambda x: {"a": x > 0, "b": x < 0}, df)
        with pytest.raises(engine_ops.InvalidShapeError, match="rank-1"):
            tft.filter_rows(lambda v: v > 0.0, df)
        with pytest.raises(engine_ops.InvalidTypeError,
                           match="boolean or integer"):
            tft.filter_rows(lambda x: x * 2.0, df)
