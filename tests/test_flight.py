"""Flight-recorder / decision-audit / SLO / health suite (tier-1;
marker ``flight``; ``run-tests.sh --flight``).

The load-bearing contracts:

- the flight ring is ALWAYS-ON, bounded, and decision-level — hot
  per-block paths never write to it (zero-cost assertions), and
  ``TFT_FLIGHT=0`` bypasses every hook bit-identically;
- ``tft.why(query_id)`` reconstructs the causal chain — with its
  recorded inputs (estimates, observations, thresholds, knobs) — for a
  query that was SHED, one that was PREEMPTED, one that was RE-PLANNED,
  and one that rode a MESH SHRINK, all with ``TFT_TRACE`` off;
- slow queries and classified giveups auto-dump a parseable JSONL
  flight snapshot (``TFT_FLIGHT_DUMP``), sharing the trace-file sink's
  size-capped keep-1 rotation (``TFT_TRACE_FILE_MAX_BYTES``);
- SLO burn math matches hand-computed histogram fixtures; the burn
  callback is edge-triggered; ``serve_report()`` renders the SLO line;
- ``tft.health()`` aggregates every subsystem into one snapshot;
- every registered ``metrics_text()`` provider conforms: exactly one
  ``# TYPE`` header per family, escaped label values, no duplicate
  series.

Latency-bound assertions are ``timing``-marked with ``timing_margin()``
per the tier-1 flake note.
"""

import json
import re
import threading

import numpy as np
import pytest

import tensorframes_tpu as tft
from conftest import timing_margin
from tensorframes_tpu import parallel as par
from tensorframes_tpu import resilience as rz
from tensorframes_tpu import serve, stream
from tensorframes_tpu.engine import preempt as engine_preempt
from tensorframes_tpu.observability import (flight, health, metrics,
                                            slo)
from tensorframes_tpu.observability import device as obs_device
from tensorframes_tpu.parallel import elastic
from tensorframes_tpu.resilience import faults
from tensorframes_tpu.serve import QueryScheduler, TenantQuota
from tensorframes_tpu.utils import tracing
from tensorframes_tpu.utils.tracing import counters, histograms

pytestmark = pytest.mark.flight


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.setenv("TFT_RETRY_BASE_DELAY", "0.001")
    monkeypatch.setenv("TFT_RETRY_MAX_DELAY", "0.01")
    for var in ("TFT_FLIGHT", "TFT_FLIGHT_DUMP", "TFT_FLIGHT_RING",
                "TFT_TRACE_FILE", "TFT_TRACE_FILE_MAX_BYTES",
                "TFT_SLOW_QUERY_MS"):
        monkeypatch.delenv(var, raising=False)
    tracing.disable()
    faults.reset()
    flight.clear()
    slo.clear_slos()
    elastic._lost_pool.clear()
    elastic._tracker.clear()
    elastic._upgrades.clear()
    yield
    faults.reset()
    flight.clear()
    slo.clear_slos()
    elastic._lost_pool.clear()
    elastic._tracker.clear()
    elastic._upgrades.clear()
    tracing.disable()


def _frame(n=16, parts=4):
    return tft.frame({"x": np.arange(float(n))}, num_partitions=parts)


# ---------------------------------------------------------------------------
# the ring
# ---------------------------------------------------------------------------

class TestRing:
    def test_ring_bounds_and_eviction_order(self, monkeypatch):
        monkeypatch.setenv("TFT_FLIGHT_RING", "8")
        flight.clear()
        for i in range(20):
            flight.record("test.kind", i=i)
        recs = flight.recent("test.kind")
        assert len(recs) == 8, "ring must drop oldest at the bound"
        assert [r["i"] for r in recs] == list(range(12, 20)), \
            "eviction must be oldest-first, order preserved"
        seqs = [r["seq"] for r in recs]
        assert seqs == sorted(seqs)

    def test_records_carry_inputs_and_scope(self):
        with flight.scope("q-scope"):
            assert flight.current_query() == "q-scope"
            flight.record("test.decision", estimate=100, observed=412,
                          threshold=4.0)
        assert flight.current_query() is None
        recs = flight.for_query("q-scope")
        assert len(recs) == 1
        r = recs[0]
        assert r["estimate"] == 100 and r["observed"] == 412
        assert r["threshold"] == 4.0
        assert r["query"] == "q-scope"
        assert "ts" in r and "seq" in r

    def test_scope_survives_worker_threads(self):
        from tensorframes_tpu.observability.events import wrap_context
        got = {}

        def work():
            flight.record("test.threaded")
            got["q"] = flight.current_query()

        with flight.scope("q-thread"):
            t = threading.Thread(target=wrap_context(work))
            t.start()
            t.join()
        assert got["q"] == "q-thread"
        assert flight.for_query("q-thread")

    def test_kind_filter_is_namespace_aware(self):
        flight.record("mesh.shrink", device=1)
        flight.record("mesh.grow", devices=[1])
        flight.record("meshy.other")
        assert {r["kind"] for r in flight.recent("mesh")} == \
            {"mesh.shrink", "mesh.grow"}
        assert len(flight.recent("mesh.shrink")) == 1

    def test_bypass_is_total(self, monkeypatch):
        monkeypatch.setenv("TFT_FLIGHT", "0")
        flight.record("test.kind", x=1)
        assert flight.recent() == []
        assert flight.dump(reason="manual") is None
        assert "disabled" in tft.why("anything")

    def test_flight_off_forcing_bit_identical(self, monkeypatch):
        df_on = _frame(32, 8).map_rows(lambda x: {"z": x * 2.0})
        on = [np.asarray(b.columns["z"]) for b in df_on.blocks()]
        monkeypatch.setenv("TFT_FLIGHT", "0")
        df_off = _frame(32, 8).map_rows(lambda x: {"z": x * 2.0})
        off = [np.asarray(b.columns["z"]) for b in df_off.blocks()]
        assert len(on) == len(off)
        for a, b in zip(on, off):
            assert a.dtype == b.dtype and np.array_equal(a, b)


# ---------------------------------------------------------------------------
# zero-cost: hot per-block paths never touch the ring
# ---------------------------------------------------------------------------

class TestZeroCost:
    def test_no_ring_writes_from_per_block_paths(self):
        before = flight.stats()["recorded_total"]
        df = _frame(64, 16).map_rows(lambda x: {"z": x + 1.0})
        df.blocks()
        after = flight.stats()["recorded_total"]
        assert after == before, (
            f"a healthy multi-block forcing recorded "
            f"{after - before} flight decision(s); the ring is for "
            f"DECISIONS, not blocks: {flight.recent(limit=10)}")

    def test_healthy_stream_batches_record_nothing(self):
        def gen():
            for i in range(6):
                yield {"v": np.arange(4, dtype=np.float64) + i}

        before = flight.stats()["recorded_total"]
        h = stream.from_source(stream.GeneratorSource(gen())) \
            .map_rows(lambda v: {"z": v * 2.0}).start()
        h.run()
        assert flight.stats()["recorded_total"] == before


# ---------------------------------------------------------------------------
# dumps: slow query, giveup, device loss, rotation
# ---------------------------------------------------------------------------

def _parse_dump(path):
    lines = path.read_text().splitlines()
    assert lines, "dump file is empty"
    recs = [json.loads(ln) for ln in lines]  # every line parses
    heads = [r for r in recs if r.get("type") == "flight_dump"]
    assert heads, "no flight_dump header line"
    return heads, recs


class TestDumps:
    def test_manual_dump_parseable_jsonl(self, tmp_path):
        flight.record("test.kind", detail="with \"quotes\" and\nnewline")
        out = tmp_path / "flight.jsonl"
        assert flight.dump(str(out), reason="manual") == str(out)
        heads, recs = _parse_dump(out)
        assert heads[0]["reason"] == "manual"
        assert heads[0]["records"] == 1
        assert any(r.get("kind") == "test.kind" for r in recs)

    def test_dump_on_slow_query(self, tmp_path, monkeypatch):
        out = tmp_path / "dump.jsonl"
        monkeypatch.setenv("TFT_FLIGHT_DUMP", str(out))
        monkeypatch.setenv("TFT_SLOW_QUERY_MS", "0")
        flight.record("test.context", hint="pre-slow-query state")
        assert not tracing.enabled()
        _frame(8, 2).map_rows(lambda x: {"z": x + 1.0}).blocks()
        heads, recs = _parse_dump(out)
        assert any(h["reason"] == "slow_query" for h in heads)
        assert any(r.get("kind") == "test.context" for r in recs)

    def test_dump_on_classified_giveup(self, tmp_path, monkeypatch):
        out = tmp_path / "dump.jsonl"
        monkeypatch.setenv("TFT_FLIGHT_DUMP", str(out))

        def always_transient():
            raise RuntimeError("UNAVAILABLE: flaky backend")

        with pytest.raises(RuntimeError):
            rz.RetryPolicy(max_attempts=2, base_delay=0.001,
                           jitter=0.0).call(always_transient, op="t")
        heads, recs = _parse_dump(out)
        assert any(h["reason"] == "giveup" for h in heads)
        give = [r for r in recs if r.get("kind") == "resilience.giveup"]
        assert give and give[-1]["attempts"] == 2
        assert give[-1]["error_kind"] == "transient"

    def test_dump_on_device_loss(self, tmp_path, monkeypatch):
        out = tmp_path / "dump.jsonl"
        monkeypatch.setenv("TFT_FLIGHT_DUMP", str(out))
        dist = par.distribute(_frame(40, 1), par.local_mesh(8))
        with faults.inject("device", 1):
            par.dmap_blocks(lambda x: {"z": x * 2.0}, dist)
        heads, _ = _parse_dump(out)
        assert any(h["reason"] == "device_lost" for h in heads)

    def test_sink_rotation_keep_one(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TFT_TRACE_FILE_MAX_BYTES", "400")
        path = tmp_path / "sink.jsonl"
        line = json.dumps({"type": "filler", "pad": "x" * 60})
        for _ in range(12):
            flight.append_jsonl(str(path), [line])
        rolled = tmp_path / "sink.jsonl.1"
        assert rolled.exists(), "keep-1 rollover file missing"
        assert path.stat().st_size <= 400 + len(line) + 1
        # both generations stay line-parseable
        for p in (path, rolled):
            for ln in p.read_text().splitlines():
                json.loads(ln)

    def test_trace_file_rides_the_rotation(self, tmp_path, monkeypatch):
        path = tmp_path / "trace.jsonl"
        monkeypatch.setenv("TFT_TRACE_FILE", str(path))
        monkeypatch.setenv("TFT_TRACE_FILE_MAX_BYTES", "2000")
        tracing.enable()
        try:
            for _ in range(8):
                _frame(8, 2).map_rows(lambda x: {"z": x + 1.0}).blocks()
        finally:
            tracing.disable()
        assert path.exists()
        assert (tmp_path / "trace.jsonl.1").exists(), \
            "TFT_TRACE_FILE must rotate at TFT_TRACE_FILE_MAX_BYTES"


# ---------------------------------------------------------------------------
# tft.why(): the acceptance chains, all with TFT_TRACE off
# ---------------------------------------------------------------------------

class _FakeDevice:
    def __init__(self, live, peak, limit):
        self.stats = {"bytes_in_use": live, "peak_bytes_in_use": peak,
                      "bytes_limit": limit}

    def memory_stats(self):
        return self.stats


class TestWhy:
    @pytest.mark.timing
    def test_why_reconstructs_a_shed_query(self, monkeypatch):
        monkeypatch.setattr(obs_device, "_local_devices",
                            lambda: [_FakeDevice(950, 950, 1000)])
        obs_device._reset()
        monkeypatch.setenv("TFT_SERVE_ADMISSION_WAIT_S", "0.05")
        monkeypatch.setenv("TFT_SERVE_ADMISSION_POLL_S", "0.01")
        assert not tracing.enabled()
        with QueryScheduler(workers=0, name="fshed") as sched:
            fut = sched.submit(_frame(8), tenant="t", est_bytes=500)
            assert sched.step()
            with pytest.raises(rz.AdmissionDeadline):
                fut.result(timeout=timing_margin(5))
            report = tft.why(fut.query_id)
        kinds = [r["kind"] for r in flight.for_query(fut.query_id)]
        assert "serve.shed" in kinds and "serve.finish" in kinds
        shed = [r for r in flight.for_query(fut.query_id)
                if r["kind"] == "serve.shed"][0]
        # the decision's INPUTS: estimate vs headroom vs wait budget
        assert shed["est_bytes"] == 500
        assert shed["headroom"] is not None and shed["headroom"] < 500
        assert shed["budget_s"] == pytest.approx(0.05)
        assert "SHED" in report and "500 B" in report
        obs_device._reset()

    def test_why_reconstructs_a_preempted_query(self):
        df = _frame(40, 8).map_rows(lambda x: {"z": x + 1.0})
        sc = engine_preempt.PreemptionScope("q-preempted")
        faults.arm("preempt", 1)
        with pytest.raises(rz.QueryPreempted):
            with engine_preempt.activate(sc):
                df.blocks()
        faults.reset()
        with engine_preempt.activate(sc):
            df.blocks()  # resume restores the parked prefix
        recs = flight.for_query("q-preempted")
        kinds = [r["kind"] for r in recs]
        assert "preempt.park" in kinds and "preempt.resume" in kinds
        park = [r for r in recs if r["kind"] == "preempt.park"][0]
        resume = [r for r in recs if r["kind"] == "preempt.resume"][0]
        assert park["total"] == 8 and 1 <= park["blocks"] < 8
        assert resume["blocks"] == park["blocks"]
        assert "injected fault" in park["reason"]
        report = tft.why("q-preempted")
        assert "parked at block boundary" in report
        assert "restored from checkpoint" in report

    def test_why_reconstructs_a_replanned_query(self, monkeypatch):
        monkeypatch.setenv("TFT_REPLAN_RATIO", "3")
        assert not tracing.enabled()
        q1 = lambda v: v > -1.0                   # noqa: E731
        q2 = lambda v: v < 50.0                   # noqa: E731

        def chain(frame):
            return frame.filter(q1).filter(q2)

        warm = tft.frame({"v": np.arange(30, dtype=np.float64)},
                         num_partitions=30)
        warm.cache()
        chain(warm).blocks()   # priced ~keep-everything
        chain(warm).blocks()   # feedback for the plan shape
        big = tft.frame({"v": np.arange(6000, dtype=np.float64)},
                        num_partitions=30)
        big.cache()
        with flight.scope("q-replan"):
            chain(big).blocks()
        recs = flight.for_query("q-replan")
        replans = [r for r in recs if r["kind"] == "plan.replan"]
        assert replans, f"no replan recorded; got {recs}"
        r = replans[0]
        # inputs: what the plan priced vs what the blocks showed, and
        # the knob the deviation was compared against
        assert r["ratio"] == pytest.approx(3.0)
        assert r["priced"] > 0 and r["observed"] > 0
        assert max(r["priced"], r["observed"]) \
            / min(r["priced"], r["observed"]) > 3.0
        report = tft.why("q-replan")
        assert "RE-PLAN" in report and "TFT_REPLAN_RATIO" in report

    def test_why_reconstructs_a_mesh_shrink(self):
        assert not tracing.enabled()
        dist = par.distribute(_frame(40, 1), par.local_mesh(8))
        with flight.scope("q-shrink"):
            with faults.inject("device", 1):
                out = par.dmap_blocks(lambda x: {"z": x * 2.0}, dist)
        assert out.mesh.num_devices == 7
        recs = flight.for_query("q-shrink")
        shr = [r for r in recs if r["kind"] == "mesh.shrink"]
        assert len(shr) == 1
        assert shr[0]["devices_before"] == 8
        assert shr[0]["devices_after"] == 7
        assert shr[0]["device"] == 0
        assert shr[0]["reshard_rows"] > 0
        report = tft.why("q-shrink")
        assert "LOST" in report and "8 -> 7" in report

    def test_why_unknown_query_is_helpful(self):
        msg = tft.why("serve-q99999")
        assert "no decisions recorded" in msg


# ---------------------------------------------------------------------------
# SLO: burn math vs hand-computed fixtures
# ---------------------------------------------------------------------------

class TestSLO:
    def test_burn_math_matches_hand_computed_buckets(self):
        tenant = "slo-fixture-a"
        slo.set_slo(tenant, objective_ms=250.0, target=0.99)
        # 8 fast successes (<= 0.25s bucket edge), 1 slow success
        # (lands in the 0.5 bucket), 1 failure: good=8, bad=2 of 10
        for _ in range(8):
            histograms.observe("query_latency_seconds", 0.01,
                               op="serve", tenant=tenant, outcome="ok")
        histograms.observe("query_latency_seconds", 0.3, op="serve",
                           tenant=tenant, outcome="ok")
        histograms.observe("query_latency_seconds", 0.01, op="serve",
                           tenant=tenant, outcome="error")
        s = slo.slo_status(tenant)[tenant]
        assert s["total"] == 10
        assert s["good"] == 8
        assert s["bad"] == 2
        assert s["compliance"] == pytest.approx(0.8)
        # burn = (bad fraction) / (1 - target) = 0.2 / 0.01 = 20x
        assert s["burn_rate"] == pytest.approx(20.0)
        assert s["budget_remaining"] == pytest.approx(1.0 - 20.0)

    def test_objective_rounds_down_to_bucket_edge(self):
        tenant = "slo-fixture-b"
        # objective 300 ms sits between the 0.25 and 0.5 edges: the
        # conservative rule counts only <= 0.25 as good
        slo.set_slo(tenant, objective_ms=300.0, target=0.999)
        histograms.observe("query_latency_seconds", 0.3, op="serve",
                           tenant=tenant, outcome="ok")
        s = slo.slo_status(tenant)[tenant]
        assert s["good"] == 0 and s["bad"] == 1

    def test_default_slo_env_knobs(self, monkeypatch):
        monkeypatch.setenv("TFT_SLO_DEFAULT_MS", "123")
        monkeypatch.setenv("TFT_SLO_TARGET", "0.95")
        d = slo.default_slo()
        assert d.objective_ms == 123.0
        assert d.target == pytest.approx(0.95)

    def test_slo_validation(self):
        with pytest.raises(ValueError):
            slo.SLO(objective_ms=0)
        with pytest.raises(ValueError):
            slo.SLO(objective_ms=100, target=1.5)

    def test_burn_callback_edge_triggered(self):
        tenant = "slo-fixture-c"
        slo.set_slo(tenant, objective_ms=250.0, target=0.99)
        histograms.observe("query_latency_seconds", 5.0, op="serve",
                           tenant=tenant, outcome="error")
        fired = []
        key = slo.on_burn(lambda t, s: fired.append((t, s["burn_rate"])),
                          threshold=1.0)
        try:
            slo.note_completion(tenant)
            assert fired and fired[0][0] == tenant
            assert fired[0][1] > 1.0
            # edge-triggered: still over threshold, no second fire
            slo._last_eval.clear()  # defeat the 1s throttle for the test
            slo.note_completion(tenant)
            assert len(fired) == 1
        finally:
            slo.remove_burn_callback(key)

    def test_serve_report_renders_the_slo_line(self):
        with QueryScheduler(workers=0, name="fslo") as sched:
            fut = sched.submit(_frame(8), lambda x: {"z": x + 1.0},
                               tenant="slo-report")
            sched.step()
            fut.result(timeout=timing_margin(10))
            report = serve.serve_report(sched)
        assert "SLO" in report and "burn" in report

    def test_always_on_accounting_via_scheduler(self):
        # zero-config: a tenant with no explicit set_slo still gets a
        # status from the default objective once it completes a query
        with QueryScheduler(workers=0, name="fdflt") as sched:
            fut = sched.submit(_frame(8), tenant="slo-default-t")
            sched.step()
            fut.result(timeout=timing_margin(10))
        s = slo.slo_status("slo-default-t")["slo-default-t"]
        assert s["total"] >= 1
        assert s["objective_ms"] == slo.default_slo().objective_ms


# ---------------------------------------------------------------------------
# tft.health()
# ---------------------------------------------------------------------------

class TestHealth:
    def test_snapshot_keys(self):
        _frame(4, 1).map_rows(lambda x: {"z": x + 1.0}).blocks()
        snap = health()
        assert set(snap) >= {"ts", "memory", "mesh", "serve", "caches",
                             "streams", "slo", "flight", "resilience",
                             "warnings"}
        assert set(snap["memory"]) >= {
            "limited", "limit_bytes", "headroom_bytes", "spills",
            "faults", "overflow_admissions", "resident_bytes",
            "spilled_bytes"}
        assert set(snap["mesh"]) >= {"visible_devices", "lost_pool",
                                     "shrinks", "grows", "rebalances"}
        assert set(snap["flight"]) >= {"enabled", "records", "capacity",
                                       "recorded_total", "dumps"}
        assert snap["mesh"]["visible_devices"] == 8
        assert isinstance(snap["warnings"], list)

    def test_health_sees_serve_and_streams(self):
        def gen():
            for i in range(3):
                yield {"v": np.arange(4, dtype=np.float64) + i}

        h = stream.from_source(stream.GeneratorSource(gen())) \
            .map_rows(lambda v: {"z": v * 2.0}) \
            .start(name="flight-health-stream")
        h.run()
        with QueryScheduler(workers=0, name="fhlth") as sched:
            fut = sched.submit(_frame(8), tenant="t")
            sched.step()
            fut.result(timeout=timing_margin(10))
            snap = health()
            assert snap["serve"]["running"] is True
            assert "t" in snap["serve"]["tenants"]
        assert "flight-health-stream" in snap["streams"]
        st = snap["streams"]["flight-health-stream"]
        assert st["batches"] == 3 and st["batches_skipped"] == 0

    def test_lost_pool_surfaces_and_warns(self):
        dist = par.distribute(_frame(40, 1), par.local_mesh(8))
        with faults.inject("device", 1):
            par.dmap_blocks(lambda x: {"z": x * 2.0}, dist)
        snap = health()
        assert snap["mesh"]["lost_pool"] == [0]
        assert any("lost" in w for w in snap["warnings"])
        elastic._lost_pool.clear()

    def test_doctor_renders(self):
        flight.record("serve.shed", query="doc-q", tenant="t",
                      est_bytes=500, headroom=50, budget_s=5.0)
        out = tft.doctor()
        assert "triage" in out
        assert "serve.shed" in out
        assert "memory" in out and "mesh" in out and "flight" in out


# ---------------------------------------------------------------------------
# metrics conformance: every registered provider
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(\{(?:[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\["\\n])*",?)*\})?'
    r' (NaN|[+-]?Inf|[-+0-9.eE]+)$')


class TestMetricsConformance:
    def test_every_registered_provider_conforms(self):
        # touch every subsystem so its provider is registered and has
        # live series — including a label value that NEEDS escaping
        from tensorframes_tpu import memory as _memory
        _memory.manager()

        def gen():
            yield {"v": np.arange(4, dtype=np.float64)}

        h = stream.from_source(stream.GeneratorSource(gen())) \
            .start(name='we"ird\\stream\nname')
        h.run()
        serve.shutdown_default_scheduler()
        weird_tenant = 'ten"ant\\with\nnewline'
        with QueryScheduler(workers=0, name="fconf",
                            quotas={weird_tenant: TenantQuota()}) as s:
            fut = s.submit(_frame(8), tenant=weird_tenant)
            s.step()
            fut.result(timeout=timing_margin(10))
            providers = metrics.registered_providers()
            # the sweep must actually cover the fleet
            for expected in ("flight", "serve.slo", "plan.adaptive",
                             "mesh", "memory", "relational", "stream",
                             "perf", "timeline", "history"):
                assert expected in providers, providers
            assert any(p.startswith("serve:") for p in providers)
            text = metrics.metrics_text()
        self._assert_conformant(text)

    def _assert_conformant(self, text):
        type_counts = {}
        series_seen = set()
        declared_type = {}
        for line in text.splitlines():
            if line.startswith("# TYPE "):
                parts = line.split()
                assert len(parts) == 4, f"malformed TYPE line: {line!r}"
                fam, mtype = parts[2], parts[3]
                assert mtype in ("counter", "gauge", "histogram",
                                 "summary"), line
                type_counts[fam] = type_counts.get(fam, 0) + 1
                declared_type[fam] = mtype
                continue
            if line.startswith("#") or not line.strip():
                continue
            m = _SAMPLE_RE.match(line)
            assert m, f"unparseable sample line: {line!r}"
            key = (m.group(1), m.group(2) or "")
            assert key not in series_seen, f"duplicate series: {key}"
            series_seen.add(key)
        dupes = {f: n for f, n in type_counts.items() if n != 1}
        assert not dupes, f"families with != 1 TYPE header: {dupes}"
        # every sample belongs to a declared family (histogram/summary
        # suffixes resolve to their base family)
        fams = set(declared_type)
        for name, _ in series_seen:
            base = name
            for suf in ("_bucket", "_sum", "_count"):
                if name.endswith(suf) and name[:-len(suf)] in fams:
                    base = name[:-len(suf)]
                    break
            assert base in fams, f"sample {name} has no TYPE header"

    def test_escaping_helper_is_the_single_rule(self):
        # providers must escape through metrics._escape_label: the
        # exposition format's backslash/quote/newline rules
        assert metrics._escape_label('a"b\\c\nd') == 'a\\"b\\\\c\\nd'
