"""Native runtime core tests: builds ``native/libtfruntime.so`` on demand
(skipped when no C++ toolchain is available), then checks every kernel
against its numpy fallback — the fast-vs-reference-path testing pattern of
the reference (``DataOps.scala:40``)."""

import os
import shutil
import subprocess

import numpy as np
import pytest

import tensorframes_tpu.native as native

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="session", autouse=True)
def built_lib():
    if shutil.which("make") is None or shutil.which("g++") is None:
        pytest.skip("no C++ toolchain; native fallback paths only")
    # always invoke make: a no-op when the .so is newer than the sources,
    # and the only way edits to tfruntime.cpp actually get tested
    subprocess.run(["make", "-C", os.path.join(REPO, "native")],
                   check=True, capture_output=True)
    # reset the module's load cache in case an earlier import missed the .so
    native._load_attempted = False
    native._lib = None
    assert native.available(), "libtfruntime.so built but failed to load"
    yield


def test_version():
    assert native.lib_version().startswith("tfruntime")


@pytest.mark.parametrize("src,dst", [
    (np.float64, np.float32), (np.float32, np.float64),
    (np.int64, np.int32), (np.int32, np.int64),
    (np.int64, np.float32), (np.float64, np.int64),
])
def test_convert_matches_astype(rng, src, dst):
    a = (rng.normal(size=300_000) * 100).astype(src)
    got = native.convert(a, dst)
    np.testing.assert_array_equal(got, a.astype(dst))


def test_convert_small_and_same_dtype(rng):
    a = rng.normal(size=10)
    assert native.convert(a, np.float64) is a
    np.testing.assert_array_equal(native.convert(a, np.float32),
                                  a.astype(np.float32))


def test_gather_rows(rng):
    src = rng.normal(size=(50_000, 8))
    idx = rng.integers(0, 50_000, size=30_000)
    np.testing.assert_array_equal(native.gather_rows(src, idx), src[idx])


def test_gather_rows_bad_index(rng):
    src = rng.normal(size=(50_000, 8))
    with pytest.raises(IndexError):
        native.gather_rows(src, np.array([0, 50_000]))


def test_pack_ragged(rng):
    cells = [rng.normal(size=rng.integers(0, 2000)) for _ in range(200)]
    values, offsets = native.pack_ragged(cells)
    assert offsets[0] == 0 and offsets[-1] == sum(c.size for c in cells)
    for i, c in enumerate(cells):
        np.testing.assert_array_equal(values[offsets[i]:offsets[i + 1]], c)


def test_pad_ragged(rng):
    cells = [rng.normal(size=rng.integers(1, 500)) for _ in range(300)]
    dense, mask = native.pad_ragged(cells)
    max_len = max(c.size for c in cells)
    assert dense.shape == (300, max_len) and mask.shape == (300, max_len)
    for i, c in enumerate(cells):
        np.testing.assert_array_equal(dense[i, :c.size], c)
        assert (dense[i, c.size:] == 0).all()
        assert mask[i, :c.size].all() and not mask[i, c.size:].any()


def test_pad_ragged_overflow(rng):
    with pytest.raises(ValueError):
        native.pad_ragged([np.ones(100_000)], max_len=10)


def test_empty_aligned_pool_roundtrip():
    native.pool_trim()
    a = native.empty_aligned((100_000,), np.float32)
    assert a.ctypes.data % 64 == 0
    a[:] = 1.5
    assert (a == 1.5).all()
    del a
    import gc
    gc.collect()
    assert native.pool_bytes() > 0  # returned to the pool, not the OS
    b = native.empty_aligned((100_000,), np.float32)
    assert b.ctypes.data % 64 == 0
    del b
    gc.collect()
    native.pool_trim()
    assert native.pool_bytes() == 0


def test_engine_uses_native_paths(rng):
    """End-to-end: aggregate + executor run with the native lib loaded."""
    import tensorframes_tpu as tft

    keys = rng.integers(0, 5, size=1000).astype(np.int64)
    vals = rng.normal(size=1000)
    df = tft.frame({"k": keys, "v": vals}, num_partitions=3)
    out = tft.aggregate(lambda v_input: {"v": v_input.sum(axis=0)},
                        df.group_by("k"))
    rows = sorted(out.collect(), key=lambda r: r["k"])
    for r in rows:
        np.testing.assert_allclose(r["v"], vals[keys == r["k"]].sum(),
                                   rtol=1e-9)
