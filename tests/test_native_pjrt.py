"""Native PJRT execution core tests.

The analogue of the reference's native-runtime smoke tests
(``TFInitializationSuite.scala:12-34``) plus the engine-parity contract:
a serialized computation executed through the C++ core must be
bit-identical to the jax in-process path on the same backend (CPU here;
the plugin backend runs the same code against libtpu.so on TPU hosts).

The library is built on demand; if the toolchain or the TF C++ libraries
are present but the build fails, that is a FAILURE, not a skip
(VERDICT.md round-1 #8: a broken native build must not pass silently).
"""

import os
import shutil
import subprocess

import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu import dtypes as dt
from tensorframes_tpu.computation import Computation, TensorSpec
from tensorframes_tpu.engine.executor import BlockExecutor
from tensorframes_tpu.shape import Shape, Unknown

NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "native")
LIB = os.path.join(NATIVE_DIR, "libtfrpjrt.so")


def _tf_available() -> bool:
    import importlib.util

    return importlib.util.find_spec("tensorflow") is not None


@pytest.fixture(scope="module")
def core():
    if shutil.which("g++") is None or not _tf_available():
        pytest.skip("no C++ toolchain / tensorflow C++ libs in this env")
    if not os.path.exists(LIB):
        proc = subprocess.run(["make", "-C", NATIVE_DIR, "pjrt"],
                              capture_output=True, text=True)
        assert proc.returncode == 0, (
            f"native PJRT core failed to build:\n{proc.stderr[-2000:]}")
    from tensorframes_tpu import native_pjrt

    assert native_pjrt.available(), "libtfrpjrt.so built but not loadable"
    return native_pjrt


@pytest.fixture(scope="module")
def client(core):
    c = core.PjrtCoreClient("cpu")
    yield c
    c.close()


def test_client_basics(client):
    assert client.platform == "cpu"
    assert client.device_count >= 1


def test_raw_stablehlo_compile_execute(core, client):
    hlo = b"""
module @jit_f {
  func.func public @main(%arg0: tensor<4xf32>) -> tensor<4xf32> {
    %0 = stablehlo.add %arg0, %arg0 : tensor<4xf32>
    return %0 : tensor<4xf32>
  }
}"""
    exe = client.compile(hlo)
    (out,) = exe.execute([np.array([1, 2, 3, 4], np.float32)])
    np.testing.assert_array_equal(out, [2, 4, 6, 8])
    exe.close()


def test_compile_error_surfaces(core, client):
    with pytest.raises(core.PjrtCoreError, match="compile failed"):
        client.compile(b"this is not stablehlo")


def test_bit_identical_to_jax_path(core):
    comp = Computation.trace(
        lambda x: {"z": x * 2.5 + 1.0},
        [TensorSpec("x", dt.double, Shape(Unknown))])
    arrays = {"x": np.linspace(-3, 7, 101)}
    jax_out = BlockExecutor().run(comp, arrays)
    ex = core.PjrtBlockExecutor("cpu")
    nat_out = ex.run(comp, arrays)
    assert jax_out.keys() == nat_out.keys()
    assert jax_out["z"].dtype == nat_out["z"].dtype
    np.testing.assert_array_equal(jax_out["z"], nat_out["z"])  # bit-identical


def test_multi_io_and_integer_dtypes(core):
    import jax.numpy as jnp

    comp = Computation.trace(
        lambda a, b: {"s": a + b, "m": jnp.minimum(a, b)},
        [TensorSpec("a", dt.int64, Shape(Unknown)),
         TensorSpec("b", dt.int64, Shape(Unknown))])
    arrays = {"a": np.arange(10, dtype=np.int64),
              "b": np.arange(10, dtype=np.int64)[::-1].copy()}
    jax_out = BlockExecutor().run(comp, arrays)
    nat_out = core.PjrtBlockExecutor("cpu").run(comp, arrays)
    for k in ("s", "m"):
        np.testing.assert_array_equal(jax_out[k], nat_out[k])


def test_compile_cache_reused(core):
    ex = core.PjrtBlockExecutor("cpu")
    comp = Computation.trace(
        lambda x: {"z": x + 1.0},
        [TensorSpec("x", dt.double, Shape(Unknown))])
    for _ in range(4):
        ex.run(comp, {"x": np.arange(8.0)})
    assert ex.compile_count == 1
    ex.run(comp, {"x": np.arange(9.0)})  # new shape -> one more compile
    assert ex.compile_count == 2


def test_map_blocks_through_native_core(core):
    from tensorframes_tpu.engine import ops as engine_ops

    df = tft.frame({"x": np.arange(10.0)}, num_partitions=3)
    ex = core.PjrtBlockExecutor("cpu")
    out = engine_ops.map_blocks(lambda x: {"z": x + 3.0}, df, executor=ex)
    assert [r["z"] for r in out.collect()] == [i + 3.0 for i in range(10)]


def test_serialized_computation_roundtrip_through_core(core):
    # serialize on the "driver", deserialize (another process's computation,
    # builder.py path), execute through the C++ core — the full
    # graphSerial -> broadcast -> C++ Session.Run analogue
    comp = Computation.trace(
        lambda x: {"z": x * x},
        [TensorSpec("x", dt.double, Shape(Unknown))])
    blob = comp.serialize()
    comp2 = Computation.deserialize(blob)
    arrays = {"x": np.arange(6.0)}
    nat = core.PjrtBlockExecutor("cpu").run(comp2, arrays)
    np.testing.assert_array_equal(nat["z"], np.arange(6.0) ** 2)


def test_2d_and_f32(core):
    comp = Computation.trace(
        lambda m: {"t": m @ m.T},
        [TensorSpec("m", dt.float32, Shape(3, 4))])
    m = np.arange(12, dtype=np.float32).reshape(3, 4)
    jax_out = BlockExecutor().run(comp, {"m": m})
    nat_out = core.PjrtBlockExecutor("cpu").run(comp, {"m": m})
    np.testing.assert_array_equal(jax_out["t"], nat_out["t"])


def test_deserialized_runs_native_dynamic_path(core):
    # A shipped computation must compile through the native refinement
    # (comp._native_dynamic), not re-enter jax tracing: parity with the
    # jax path at two different row counts (two refined signatures).
    import jax.numpy as jnp

    comp = Computation.trace(
        lambda x: {"z": jnp.sin(x) * 2.0},
        [TensorSpec("x", dt.by_name("float"), Shape(Unknown, 3))])
    blob = comp.serialize()
    shipped = Computation.deserialize(blob)
    assert getattr(shipped, "_native_dynamic", None), \
        "serialize() must carry the raw dynamic module"
    ex = core.PjrtBlockExecutor(backend="cpu")
    jax_ex = BlockExecutor()
    for n in (5, 11):
        arrays = {"x": np.arange(n * 3, dtype=np.float32).reshape(n, 3)}
        out_native = ex.run(shipped, arrays)
        out_jax = jax_ex.run(shipped, arrays, pad_ok=False)
        np.testing.assert_allclose(out_native["z"], out_jax["z"],
                                   rtol=1e-6)
    assert ex.compile_count == 2


def test_jax_free_subprocess_executes_serialized(core, tmp_path):
    # The executor-side contract (VERDICT round-2 missing #5): a host
    # WITHOUT jax deserializes and executes a shipped computation through
    # the native core. The subprocess installs an import hook that makes
    # any jax import an error, then drives native_runtime.py loaded by
    # file path (no package import).
    import jax.numpy as jnp

    comp = Computation.trace(
        lambda x, y: {"s": x + y, "p": x * y},
        [TensorSpec("x", dt.by_name("float"), Shape(Unknown)),
         TensorSpec("y", dt.by_name("float"), Shape(Unknown))])
    blob = comp.serialize()
    blob_path = tmp_path / "comp.tft"
    blob_path.write_bytes(blob)
    runtime_py = os.path.join(os.path.dirname(__file__), "..",
                              "tensorframes_tpu", "native_runtime.py")

    script = f"""
import sys, json
import importlib.abc, importlib.util

class JaxBlocker(importlib.abc.MetaPathFinder):
    def find_spec(self, name, path=None, target=None):
        if name == "jax" or name.startswith("jax."):
            raise ImportError("jax is blocked on this executor host")
        return None

sys.meta_path.insert(0, JaxBlocker())
for m in list(sys.modules):
    if m == "jax" or m.startswith("jax."):
        del sys.modules[m]

import numpy as np
spec = importlib.util.spec_from_file_location(
    "native_runtime", {runtime_py!r})
mod = importlib.util.module_from_spec(spec)
spec.loader.exec_module(mod)

nc = mod.load_computation(open({str(blob_path)!r}, "rb").read())
rt = mod.NativeRuntime("cpu")
x = np.arange(6, dtype=np.float32)
y = np.arange(6, dtype=np.float32) * 10
out = rt.run(nc, {{"x": x, "y": y}})
assert np.allclose(out["s"], x + y), out["s"]
assert np.allclose(out["p"], x * y), out["p"]
# a second shape exercises a second native refinement
x2 = np.arange(9, dtype=np.float32)
out2 = rt.run(nc, {{"x": x2, "y": x2}})
assert np.allclose(out2["s"], x2 * 2)
assert "jax" not in sys.modules
print(json.dumps({{"ok": True, "platform": rt.platform}}))
"""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    proc = subprocess.run([os.sys.executable, "-c", script],
                          capture_output=True, text=True, env=env,
                          timeout=300)
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert '"ok": true' in proc.stdout


def test_replicated_execution_across_devices(core):
    # SPMD replication in the C++ core: one compile for 4 devices, one
    # native call runs every replica (VERDICT r2 weak #2: the core was
    # single-device). Parity with the sequential path per replica.
    import jax.numpy as jnp

    client = core.PjrtCoreClient("cpu:4")
    try:
        assert client.device_count == 4
        hlo = (
            b"module @f {\n"
            b"  func.func public @main(%a: tensor<6xf32>)"
            b" -> tensor<6xf32> {\n"
            b"    %0 = stablehlo.multiply %a, %a : tensor<6xf32>\n"
            b"    func.return %0 : tensor<6xf32>\n  }\n}\n")
        exe = client.compile_replicated(hlo, 4)
        reps = [np.arange(6, dtype=np.float32) + 10 * r for r in range(4)]
        outs = exe.execute([[a] for a in reps])
        assert len(outs) == 4
        for r, out in enumerate(outs):
            np.testing.assert_allclose(out[0], reps[r] ** 2)
        exe.close()
    finally:
        client.close()


def test_device_resident_buffers_loop(core):
    # keep_outputs=True detaches results as device-resident handles that
    # feed straight back as inputs: one upload, N device-side dispatches,
    # one download (the residency contract of tfr_pjrt_buffer)
    client = core.PjrtCoreClient("cpu:4")
    try:
        hlo = (
            b"module @f {\n"
            b"  func.func public @main(%a: tensor<4xf32>)"
            b" -> tensor<4xf32> {\n"
            b"    %c = stablehlo.constant dense<1.0> : tensor<4xf32>\n"
            b"    %0 = stablehlo.add %a, %c : tensor<4xf32>\n"
            b"    func.return %0 : tensor<4xf32>\n  }\n}\n")
        exe = client.compile_replicated(hlo, 4)
        reps = [np.arange(4, dtype=np.float32) + 10 * r for r in range(4)]
        bufs = exe.execute([[a] for a in reps], keep_outputs=True)
        for rep in bufs:
            b = rep[0]
            assert isinstance(b, core.PjrtDeviceBuffer)
            assert b.shape == (4,) and b.dtype == np.float32
        for _ in range(4):
            bufs = exe.execute(bufs, keep_outputs=True)
        outs = exe.execute(bufs, keep_outputs=False)
        for r, out in enumerate(outs):
            np.testing.assert_array_equal(out[0], reps[r] + 6.0)
        # handles are reusable (not consumed): run one of them again
        outs2 = exe.execute(bufs, keep_outputs=False)
        for r, out in enumerate(outs2):
            np.testing.assert_array_equal(out[0], reps[r] + 6.0)
        for rep in bufs:
            rep[0].close()
        exe.close()
    finally:
        client.close()


def test_run_blocks_parallel_matches_sequential(core):
    import jax.numpy as jnp

    ex = core.PjrtBlockExecutor(backend="cpu:4")
    comp = Computation.trace(
        lambda x: {"z": jnp.sin(x) + 1.0},
        [TensorSpec("x", dt.by_name("float"), Shape(Unknown, 2))])
    rng = np.random.default_rng(0)
    blocks = [{"x": rng.standard_normal((5, 2)).astype(np.float32)}
              for _ in range(4)]
    par_out = ex.run_blocks_parallel(comp, blocks)
    assert ex.compile_count == 1  # one replicated compile for the wave
    for b, o in zip(blocks, par_out):
        seq = ex.run(comp, b, pad_ok=False)
        np.testing.assert_allclose(o["z"], seq["z"], rtol=1e-6)

    # ragged wave (different shapes) falls back to the sequential path
    ragged = blocks + [{"x": rng.standard_normal((3, 2)).astype(np.float32)}]
    rag_out = ex.run_blocks_parallel(comp, ragged)
    assert len(rag_out) == 5
    np.testing.assert_allclose(
        rag_out[-1]["z"], np.sin(ragged[-1]["x"]) + 1.0, rtol=1e-6)


def test_run_blocks_parallel_waves_and_shipped_computation(core):
    # 8 uniform blocks on 4 devices chunk into two replicated waves
    # (one compile), and a SHIPPED (deserialized) computation routes
    # through the native dynamic refinement even on the parallel path.
    import jax.numpy as jnp

    ex = core.PjrtBlockExecutor(backend="cpu:4")
    comp = Computation.trace(
        lambda x: {"z": x * 3.0},
        [TensorSpec("x", dt.by_name("float"), Shape(Unknown))])
    shipped = Computation.deserialize(comp.serialize())
    rng = np.random.default_rng(1)
    blocks = [{"x": rng.standard_normal(6).astype(np.float32)}
              for _ in range(8)]
    out = ex.run_blocks_parallel(shipped, blocks)
    assert ex.compile_count == 1
    assert len(out) == 8
    for b, o in zip(blocks, out):
        np.testing.assert_allclose(o["z"], b["x"] * 3.0, rtol=1e-6)


def test_padding_executor_wraps_native(core):
    # map_rows' bucketed padding composed with the C++ core: odd-sized
    # blocks share one compiled program (O(log) signatures), rows match
    # the jax path.
    import jax.numpy as jnp

    from tensorframes_tpu.engine.executor import (BlockExecutor,
                                                  PaddingExecutor)

    ex = PaddingExecutor(core.PjrtBlockExecutor(backend="cpu"))
    jax_ex = BlockExecutor(pad_rows=True)
    comp = Computation.trace(
        lambda x: {"z": jnp.sin(x) * 2.0},
        [TensorSpec("x", dt.by_name("float"), Shape(Unknown))])
    rng = np.random.default_rng(0)
    for n in (5, 6, 7, 11, 13):      # all bucket to 8 / 16
        arrays = {"x": rng.standard_normal(n).astype(np.float32)}
        got = ex.run(comp, arrays)
        want = jax_ex.run(comp, arrays)
        np.testing.assert_allclose(got["z"], want["z"], rtol=1e-6)
        assert got["z"].shape == (n,)
    assert ex.compile_count == 2     # buckets 8 and 16 only
