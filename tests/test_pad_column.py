"""pad_column: ragged -> dense+mask+len, then block ops on the result."""

import numpy as np
import pytest

import tensorframes_tpu as tft


def _ragged_frame(parts=2):
    rows = [(np.arange(i + 1, dtype=np.float64),) for i in range(7)]
    return tft.frame(rows, columns=["v"], num_partitions=parts)


def test_pad_column_shapes_and_mask():
    df = _ragged_frame().pad_column("v")
    assert df.columns == ["v", "v_mask", "v_len"]
    rows = df.collect()
    assert len(rows) == 7
    for i, r in enumerate(rows):
        assert r["v"].shape == (7,)
        np.testing.assert_array_equal(r["v"][: i + 1], np.arange(i + 1))
        assert (r["v"][i + 1:] == 0).all()
        np.testing.assert_array_equal(
            r["v_mask"], (np.arange(7) < i + 1).astype(np.int32))
        assert r["v_len"] == i + 1


def test_pad_column_pow2_and_block_op():
    df = _ragged_frame().pad_column("v", pow2=True)
    rows = df.collect()
    assert rows[0]["v"].shape == (8,)  # 7 -> 8

    # the padded frame is block-op capable: masked per-row mean
    out = df.map_blocks(
        lambda v, v_mask, v_len: {
            "mean": (v * v_mask).sum(axis=1) / v_len})
    for i, r in enumerate(out.collect()):
        assert r["mean"] == pytest.approx(np.arange(i + 1).mean())


def test_pad_column_rejects_collision_and_rank():
    df = _ragged_frame()
    with pytest.raises(ValueError):
        df.pad_column("v", mask_col="v")
    dense = tft.frame({"m": np.zeros((3, 2, 2))})
    with pytest.raises(ValueError):
        dense.pad_column("m")


def test_pad_column_explicit_max_len_overflow():
    df = _ragged_frame()
    with pytest.raises(ValueError):
        df.pad_column("v", max_len=3).blocks()
