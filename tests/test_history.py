"""Durable query-history / crash post-mortem suite (tier-1; marker
``history``; ``run-tests.sh --history``).

The load-bearing contracts:

- every finished query folds into checksummed append-only segments
  (``TFT_HISTORY_DIR``), rotated at ``TFT_HISTORY_MAX_BYTES`` with the
  ``TFT_HISTORY_RETENTION`` newest kept; ``TFT_HISTORY=0`` bypasses the
  recording hooks at one env check;
- COLD-NEVER-WRONG: a bit-rotted or truncated segment is counted,
  flight-recorded (``history.segment_corrupt``), and unlinked — the
  archive returns fewer records, never wrong ones, and a kill
  mid-append leaves every PRIOR segment readable;
- ``tft.history()`` stitches per-attempt records (a query migrated
  across fabric workers reads as one record with its worker path) and
  filters by tenant / fingerprint prefix / outcome / since / slow_only;
- ``tft.why(qid)`` falls through ring → flight dumps → durable history,
  so a causal chain survives ring rotation AND a process restart, with
  ``TFT_TRACE`` off;
- a ``running-<pid>`` marker whose pid is dead means an unclean
  shutdown: counted, flight-recorded, surfaced by ``tft.postmortem()``
  / ``doctor()`` / ``health()``;
- the flight-dump file keeps only the newest ``TFT_FLIGHT_DUMP_KEEP``
  snapshot sections (evictions counted) instead of growing forever;
- the restart drill: a mixed serve workload hard-killed with SIGKILL
  restarts into a process where ``postmortem()`` flags the unclean
  shutdown, ``history()`` returns every completed query's record with
  its cost vector and outcome, and ``why(qid)`` reconstructs the
  pre-kill causal chain from durable state alone.
"""

import json
import os
import signal
import struct
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import tensorframes_tpu as tft
from conftest import timing_margin
from tensorframes_tpu.observability import decisions, flight, health
from tensorframes_tpu.observability import history as hist
from tensorframes_tpu.observability import metrics
from tensorframes_tpu.resilience import faults
from tensorframes_tpu.serve import QueryScheduler, TenantQuota
from tensorframes_tpu.utils.tracing import counters

pytestmark = pytest.mark.history


@pytest.fixture(autouse=True)
def _clean(monkeypatch, tmp_path):
    for var in ("TFT_HISTORY", "TFT_HISTORY_MAX_BYTES",
                "TFT_HISTORY_RETENTION", "TFT_HISTORY_DECISIONS",
                "TFT_FLIGHT", "TFT_FLIGHT_DUMP", "TFT_FLIGHT_DUMP_KEEP",
                "TFT_SLOW_QUERY_MS"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("TFT_HISTORY_DIR", str(tmp_path / "hist"))
    faults.reset()
    flight.clear()
    hist.clear()
    yield
    faults.reset()
    flight.clear()
    hist.clear()


def _seg_paths():
    d = hist.active_dir()
    return sorted(os.path.join(d, n) for n in os.listdir(d)
                  if n.startswith("seg-") and n.endswith(".hist"))


# ---------------------------------------------------------------------------
# framing + round-trip
# ---------------------------------------------------------------------------

class TestFraming:
    def test_round_trip_preserves_the_record(self):
        assert hist.record_finish(
            "q-rt", tenant="acme", fingerprint="fp-abc123",
            outcome="completed", worker="w0",
            cost={"compute_s": 0.5, "bytes_out": 1024},
            queued_s=0.01, run_s=0.5, total_s=0.51,
            est_rows=100, est_bytes=800, source="serve",
            summary="round trip")
        recs = tft.history()
        assert len(recs) == 1
        r = recs[0]
        assert r["query"] == "q-rt"
        assert r["tenant"] == "acme"
        assert r["fingerprint"] == "fp-abc123"
        assert r["outcome"] == "completed"
        assert r["worker"] == "w0"
        assert r["cost"] == {"compute_s": 0.5, "bytes_out": 1024}
        assert r["total_s"] == pytest.approx(0.51)
        assert r["est_rows"] == 100

    def test_on_disk_frame_is_magic_length_sha(self):
        import hashlib
        hist.record_finish("q-frame", outcome="ok")
        (path,) = _seg_paths()
        with open(path, "rb") as f:
            data = f.read()
        assert data.startswith(b"TFTH\x01")
        (plen,) = struct.unpack(">I", data[5:9])
        digest, payload = data[9:41], data[41:41 + plen]
        assert len(payload) == plen and not data[41 + plen:]
        assert hashlib.sha256(payload).digest() == digest
        assert json.loads(payload)["query"] == "q-frame"

    def test_bypass_env_records_nothing(self, monkeypatch):
        monkeypatch.setenv("TFT_HISTORY", "0")
        assert hist.record_finish("q-off", outcome="ok") is False
        monkeypatch.delenv("TFT_HISTORY")
        assert tft.history() == []

    def test_no_dir_no_persist_is_off(self, monkeypatch):
        monkeypatch.delenv("TFT_HISTORY_DIR")
        hist.clear()
        if hist.active_dir() is None:  # a live persist tier may supply one
            assert hist.record_finish("q-nodir", outcome="ok") is False
            assert hist.stats()["enabled"] is False

    def test_decision_digest_is_bounded(self, monkeypatch):
        monkeypatch.setenv("TFT_HISTORY_DECISIONS", "2")
        decs = [{"kind": f"serve.k{i % 3}", "ts": float(i), "seq": i}
                for i in range(5)]
        hist.record_finish("q-digest", outcome="ok", decisions=decs)
        (r,) = tft.history()
        assert len(r["decisions"]) == 2
        assert r["decisions"][-1]["seq"] == 4  # newest kept
        assert sum(r["decision_kinds"].values()) == 5
        assert r["decisions_dropped"] == 3


# ---------------------------------------------------------------------------
# rotation + retention
# ---------------------------------------------------------------------------

class TestRotationRetention:
    def test_rotation_at_max_bytes(self, monkeypatch):
        monkeypatch.setenv("TFT_HISTORY_MAX_BYTES", "1")
        for i in range(4):
            hist.record_finish(f"q-rot{i}", outcome="ok")
        assert len(_seg_paths()) == 4  # one record per segment
        assert len(tft.history()) == 4

    def test_retention_evicts_oldest(self, monkeypatch):
        monkeypatch.setenv("TFT_HISTORY_MAX_BYTES", "1")
        monkeypatch.setenv("TFT_HISTORY_RETENTION", "3")
        ev0 = hist.stats()["evictions"]
        for i in range(8):
            hist.record_finish(f"q-ret{i}", outcome="ok")
        assert len(_seg_paths()) <= 3
        assert hist.stats()["evictions"] - ev0 >= 5
        qids = [r["query"] for r in tft.history()]
        assert "q-ret7" in qids and "q-ret0" not in qids


# ---------------------------------------------------------------------------
# cold-never-wrong
# ---------------------------------------------------------------------------

class TestColdNeverWrong:
    def _two_segments(self, monkeypatch):
        monkeypatch.setenv("TFT_HISTORY_MAX_BYTES", "1")
        hist.record_finish("q-old", outcome="ok")
        hist.record_finish("q-new", outcome="ok")
        paths = _seg_paths()
        assert len(paths) == 2
        return paths

    def test_bit_rot_sends_segment_cold_earlier_readable(
            self, monkeypatch):
        old_seg, new_seg = self._two_segments(monkeypatch)
        c0 = hist.stats()["corrupt_segments"]
        with open(new_seg, "rb") as f:
            data = bytearray(f.read())
        data[-1] ^= 0x01  # rot inside the payload: checksum must catch
        with open(new_seg, "wb") as f:
            f.write(bytes(data))
        qids = [r["query"] for r in tft.history()]
        assert qids == ["q-old"]  # fewer records, never wrong ones
        assert hist.stats()["corrupt_segments"] - c0 == 1
        assert not os.path.exists(new_seg), "cold segment not unlinked"
        recs = flight.recent(kind="history.segment_corrupt")
        assert recs and "sha256" in recs[-1]["why"]

    def test_kill_mid_append_prior_segments_readable(self, monkeypatch):
        # a torn tail is what a SIGKILL inside the one write() leaves:
        # the newest segment goes cold, every prior one stays readable
        old_seg, new_seg = self._two_segments(monkeypatch)
        with open(old_seg, "rb") as f:
            frame = f.read()
        with open(new_seg, "ab") as f:
            f.write(frame[:len(frame) // 2])  # torn half-record
        qids = [r["query"] for r in tft.history()]
        assert qids == ["q-old"]
        assert not os.path.exists(new_seg)

    def test_garbage_header_cold(self, monkeypatch):
        _, new_seg = self._two_segments(monkeypatch)
        with open(new_seg, "wb") as f:
            f.write(b"not a framed segment")
        assert [r["query"] for r in tft.history()] == ["q-old"]

    def test_disk_fault_corruption_mode(self):
        # the chaos drill's disk site, corruption-shaped (persist.py
        # idiom): bytes read fine, one bit flipped — checksum catches
        hist.record_finish("q-chaos", outcome="ok")
        c0 = hist.stats()["corrupt_segments"]
        with faults.inject("disk", message="injected corrupt segment"):
            assert tft.history() == []
        assert hist.stats()["corrupt_segments"] - c0 == 1
        assert _seg_paths() == []  # consumed cold
        assert counters.get("history.segments_corrupt") >= 1

    def test_write_failure_degrades_never_raises(self, monkeypatch):
        monkeypatch.setenv("TFT_HISTORY_DIR", "/proc/nonexistent/hist")
        hist.clear()
        e0 = hist.stats()["write_errors"]
        assert hist.record_finish("q-nowrite", outcome="ok") is False
        # the unwritable dir is caught at _ensure_dir (returns None, no
        # error counted) — both shapes are "degrade, never raise"
        assert hist.stats()["write_errors"] - e0 in (0, 1)


# ---------------------------------------------------------------------------
# stitching + filters
# ---------------------------------------------------------------------------

class TestStitchingAndFilters:
    def test_migrated_query_reads_as_one_record(self):
        hist.record_finish("q-mig", tenant="t", outcome="migrated",
                           worker="w0", source="fabric")
        hist.record_finish("q-mig", tenant="t", outcome="completed",
                           worker="w1", total_s=1.5)
        (r,) = tft.history()
        assert r["outcome"] == "completed"
        assert r["workers"] == ["w0", "w1"]
        assert r["migrations"] == 1

    def test_filters(self, monkeypatch):
        monkeypatch.setenv("TFT_SLOW_QUERY_MS", "1000")
        hist.record_finish("q-a", tenant="a", fingerprint="fp-aaa",
                           outcome="completed", total_s=0.1)
        hist.record_finish("q-b", tenant="b", fingerprint="fp-bbb",
                           outcome="failed", total_s=2.0)
        assert [r["query"] for r in tft.history(tenant="a")] == ["q-a"]
        assert [r["query"]
                for r in tft.history(fingerprint="fp-b")] == ["q-b"]
        assert [r["query"]
                for r in tft.history(outcome="failed")] == ["q-b"]
        assert [r["query"]
                for r in tft.history(slow_only=True)] == ["q-b"]
        all_ts = [r["ts"] for r in tft.history()]
        assert [r["query"] for r in tft.history(since=max(all_ts))] \
            == ["q-b"]
        assert len(tft.history(limit=1)) == 1


# ---------------------------------------------------------------------------
# serve integration: the scheduler fold site
# ---------------------------------------------------------------------------

class TestServeFold:
    def test_completed_queries_archive_with_cost_and_decisions(self):
        with QueryScheduler(quotas={"t": TenantQuota()}, workers=1,
                            name="histserve") as s:
            fr = tft.frame({"x": np.arange(16.0)}, num_partitions=2)
            futs = [s.submit(fr, lambda x: {"z": x + 1.0}, tenant="t")
                    for _ in range(3)]
            for f in futs:
                f.result(timeout=timing_margin(30))
        recs = tft.history(outcome="completed")
        assert len(recs) == 3
        for r in recs:
            assert r["tenant"] == "t"
            assert r["source"] == "serve"
            assert "cost" in r
            assert "serve.finish" in r.get("decision_kinds", {})


# ---------------------------------------------------------------------------
# why() fall-through
# ---------------------------------------------------------------------------

class TestWhyFallthrough:
    def test_why_reads_archive_after_ring_rotation(self):
        with flight.scope("q-why"):
            flight.record("serve.start", query="q-why", tenant="t",
                          queue_wait_s=0.0)
            flight.record("serve.finish", query="q-why", outcome="ok",
                          latency_s=0.2)
        hist.record_finish("q-why", tenant="t", outcome="completed",
                           total_s=0.2, worker="w0",
                           decisions=flight.for_query("q-why"))
        flight.clear()  # the ring forgets; the archive must not
        out = tft.why("q-why")
        assert "durable history" in out
        assert "completed" in out and "w0" in out
        assert "archived decision" in out and "serve.finish" in out

    def test_why_unknown_query_names_all_sources(self):
        out = tft.why("q-never-ran")
        assert "durable history" in out

    def test_ring_still_wins_when_live(self):
        flight.record("serve.start", query="q-live", tenant="t",
                      queue_wait_s=0.0)
        assert "flight ring" in tft.why("q-live")


# ---------------------------------------------------------------------------
# unclean shutdown + postmortem
# ---------------------------------------------------------------------------

def _dead_pid():
    p = subprocess.Popen([sys.executable, "-c", "pass"])
    p.wait()
    return p.pid


class TestUncleanShutdown:
    def test_stale_marker_of_dead_pid_is_detected(self):
        d = hist.active_dir()
        os.makedirs(d, exist_ok=True)
        pid = _dead_pid()
        with open(os.path.join(d, f"running-{pid}.marker"), "w") as f:
            f.write(json.dumps({"pid": pid, "started_ts": 123.0,
                                "worker": "w9"}))
        u0 = hist.stats()["unclean_shutdowns"]
        hist.clear()  # a fresh consumer over the same dir
        info = hist.unclean_shutdown()
        assert info is not None and info["pid"] == pid
        assert info["worker"] == "w9"
        assert hist.stats()["unclean_shutdowns"] - u0 == 1
        assert flight.recent(kind="history.unclean_shutdown")
        assert not os.path.exists(
            os.path.join(d, f"running-{pid}.marker"))  # consumed
        pm = tft.postmortem()
        assert "UNCLEAN SHUTDOWN" in pm and str(pid) in pm
        # surfaced by health() warnings and doctor()
        assert any("UNCLEAN" in w.upper()
                   for w in health()["warnings"])
        assert "tft.postmortem()" in decisions.doctor()

    def test_own_marker_is_not_unclean(self):
        hist.record_finish("q-own", outcome="ok")  # drops our marker
        hist.clear()
        assert hist.unclean_shutdown() is None
        assert "no unclean shutdown" in tft.postmortem()

    def test_postmortem_renders_history_tail(self):
        hist.record_finish("q-pm", tenant="t", outcome="completed",
                           total_s=0.3, worker="w0")
        pm = tft.postmortem()
        assert "q-pm" in pm and "completed" in pm


# ---------------------------------------------------------------------------
# satellite: flight-dump pruning
# ---------------------------------------------------------------------------

class TestDumpPrune:
    def _sections(self, path):
        with open(path) as f:
            return [json.loads(s) for s in f
                    if s.strip()
                    and json.loads(s).get("type") == "flight_dump"]

    def test_keep_newest_sections(self, monkeypatch, tmp_path):
        path = str(tmp_path / "dump.jsonl")
        monkeypatch.setenv("TFT_FLIGHT_DUMP", path)
        monkeypatch.setenv("TFT_FLIGHT_DUMP_KEEP", "2")
        ev0 = flight.stats()["dump_evictions"]
        for i in range(5):
            flight.record("test.kind", i=i)
            flight.dump(reason=f"r{i}")
        heads = self._sections(path)
        assert len(heads) == 2
        assert [h["reason"] for h in heads] == ["r3", "r4"]
        assert flight.stats()["dump_evictions"] - ev0 == 3
        # the surviving sections still parse through load_dumps
        assert flight.load_dumps(path)

    def test_keep_zero_disables_pruning(self, monkeypatch, tmp_path):
        path = str(tmp_path / "dump0.jsonl")
        monkeypatch.setenv("TFT_FLIGHT_DUMP", path)
        monkeypatch.setenv("TFT_FLIGHT_DUMP_KEEP", "0")
        for i in range(3):
            flight.record("test.kind", i=i)
            flight.dump(reason=f"r{i}")
        assert len(self._sections(path)) == 3


# ---------------------------------------------------------------------------
# metrics + surfaces
# ---------------------------------------------------------------------------

class TestSurfaces:
    def test_metrics_provider_renders(self):
        hist.record_finish("q-met", outcome="ok")
        text = metrics.metrics_text()
        assert "tft_history_records_total" in text
        assert "tft_history_segments" in text
        assert "tft_flight_dump_evictions_total" in text

    def test_health_section(self):
        hist.record_finish("q-health", outcome="ok")
        hs = health()["history"]
        assert hs["enabled"] and hs["segments"] >= 1

    def test_doctor_names_the_archive(self):
        hist.record_finish("q-doc", outcome="ok")
        assert "history  :" in decisions.doctor()


# ---------------------------------------------------------------------------
# the restart drill (acceptance): hard-kill a serve workload, restart
# ---------------------------------------------------------------------------

class TestRestartDrill:
    def test_sigkill_then_postmortem_history_why(self, tmp_path,
                                                 monkeypatch):
        d = str(tmp_path / "drill-hist")
        child = textwrap.dedent("""
            import os, signal
            import numpy as np
            import tensorframes_tpu as tft
            from tensorframes_tpu.serve import (QueryScheduler,
                                                TenantQuota)

            sched = QueryScheduler(quotas={"a": TenantQuota(),
                                           "b": TenantQuota()},
                                   workers=2, name="drill")
            futs = []
            for i in range(6):
                fr = tft.frame({"x": np.arange(64.0) + i},
                               num_partitions=2)
                futs.append(sched.submit(
                    fr, lambda x: {"z": x + 1.0},
                    tenant="a" if i % 2 else "b"))
            for f in futs:
                f.result(timeout=60)
            # quiesce: the future resolves a hair before _finish's
            # archive append; wait for all 6 records to be durable so
            # the SIGKILL tests crash-after-completion, not a race
            import time
            for _ in range(200):
                if len(tft.history(outcome="completed")) >= 6:
                    break
                time.sleep(0.05)
            print("DRILL-DONE", flush=True)
            os.kill(os.getpid(), signal.SIGKILL)
        """)
        env = dict(os.environ)
        env.update({"JAX_PLATFORMS": "cpu", "TFT_HISTORY_DIR": d})
        env.pop("TFT_HISTORY", None)
        proc = subprocess.run([sys.executable, "-c", child], env=env,
                              capture_output=True, text=True,
                              timeout=timing_margin(300))
        assert "DRILL-DONE" in proc.stdout, proc.stderr[-2000:]
        assert proc.returncode == -signal.SIGKILL

        # the restart: a fresh consumer over the same dir, tracing off,
        # this process's flight ring knowing nothing about the child
        monkeypatch.setenv("TFT_HISTORY_DIR", d)
        hist.clear()
        flight.clear()
        pm = tft.postmortem()
        assert "UNCLEAN SHUTDOWN" in pm
        recs = tft.history(outcome="completed")
        assert len(recs) == 6
        for r in recs:
            assert r["outcome"] == "completed"
            assert "cost" in r, "cost vector missing from the archive"
            assert r["tenant"] in ("a", "b")
        qid = recs[0]["query"]
        out = tft.why(qid)
        assert "durable history" in out
        assert "archived decision" in out and "serve.finish" in out
