"""Test fixture: CPU backend with 8 virtual devices.

The analogue of the reference's shared `local[1]` Spark fixture with
`spark.sql.shuffle.partitions=4`
(`TensorFlossTestSparkContext.scala:10-43`): unit tests run on the CPU
backend of the same code path that targets TPU, and mesh/partition tests use
8 virtual devices via XLA_FLAGS, per SURVEY.md §4.

Note: this image's sitecustomize registers the TPU (axon) backend at
interpreter startup and exports JAX_PLATFORMS=axon, so plain env-var
overrides are too late/ignored; `jax.config.update` before first backend use
is the reliable switch. x64 is enabled so `double`/`long` columns stay exact
in tests (on real TPU they compute as f32/i32 by policy — see dtypes.py).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def timing_margin(seconds: float) -> float:
    """Scale a deadline-test assertion bound by ``TFT_TIMING_MARGIN``.

    The `timing`-marked tests assert that a deadline FIRED within a
    generous wall-clock bound; on badly oversubscribed boxes even those
    margins flake. ``TFT_TIMING_MARGIN=2`` doubles every bound (the
    ``run-tests.sh --timing`` lane runs them serially for the same
    reason). Malformed or missing values mean 1.0 — the written bound.
    """
    raw = os.environ.get("TFT_TIMING_MARGIN", "")
    try:
        margin = float(raw) if raw else 1.0
    except ValueError:
        margin = 1.0
    return seconds * max(margin, 1.0)


@pytest.fixture
def rng():
    return np.random.default_rng(42)


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: heavier smoke tests (model-sized benchmarks)")
    config.addinivalue_line(
        "markers", "resilience: retry/fallback/fault-injection suite "
                   "(run-tests.sh runs this lane standalone too)")
    config.addinivalue_line(
        "markers", "pipeline: pipelined block-execution suite "
                   "(run-tests.sh --pipeline runs this lane standalone)")
    config.addinivalue_line(
        "markers", "observability: query-trace/metrics/explain suite "
                   "(run-tests.sh --observability runs this lane "
                   "standalone)")
    config.addinivalue_line(
        "markers", "serve: multi-tenant scheduler/admission/quota suite "
                   "(run-tests.sh --serve runs this lane standalone)")
    config.addinivalue_line(
        "markers", "stream: streaming sources/windows/watermarks suite "
                   "(run-tests.sh --stream runs this lane standalone)")
    config.addinivalue_line(
        "markers", "elastic: device-loss recovery / skew-adaptive "
                   "repartitioning suite (run-tests.sh --elastic runs "
                   "this lane standalone)")
    config.addinivalue_line(
        "markers", "memory: device-memory manager suite — budget "
                   "ledger, spill/fault-back, external sort, "
                   "larger-than-budget queries (run-tests.sh --memory "
                   "runs this lane standalone)")
    config.addinivalue_line(
        "markers", "plan: logical-plan IR suite — operator fusion "
                   "bit-identity vs TFT_FUSE=0, column pruning, "
                   "device-resident stage chaining, plan-derived "
                   "estimates (run-tests.sh --plan runs this lane "
                   "standalone)")
    config.addinivalue_line(
        "markers", "dplan: distributed logical-plan suite — lazy d-op "
                   "chains fused into one GSPMD program per mesh stage, "
                   "bit-identity vs TFT_FUSE=0, folded reductions, "
                   "elastic recovery through fused programs, "
                   "resident-shard-edge spills (run-tests.sh --dplan "
                   "runs this lane standalone)")
    config.addinivalue_line(
        "markers", "join: relational join suite — broadcast hash join "
                   "and mesh sort-merge join vs the CPU host oracle, "
                   "ledger-chunked builds, stream enrichment, parquet "
                   "predicate pushdown, hot-key surfacing "
                   "(run-tests.sh --join runs this lane standalone)")
    config.addinivalue_line(
        "markers", "sketch: approximate-aggregate suite — HLL distinct "
                   "counts, relative-error quantiles, top-k heavy "
                   "hitters, error bounds + cross-path bit-identity "
                   "through aggregate/daggregate/windowed streams "
                   "(run-tests.sh --join runs this lane too)")
    config.addinivalue_line(
        "markers", "preempt: preemption/cancellation/elastic-growth "
                   "suite — checkpointed park/resume bit-identity, "
                   "scheduler cancel races, priority preemption, device "
                   "re-admission + shrink/grow churn (run-tests.sh "
                   "--preempt runs this lane standalone)")
    config.addinivalue_line(
        "markers", "adaptive: adaptive-execution suite — feedback-"
                   "driven block re-bucketing, observed-selectivity "
                   "filter re-ordering and mid-plan re-plans, the "
                   "plan-fingerprint result cache, adaptive stream "
                   "batch sizing, preempt-aware admission; every "
                   "decision bit-identical vs TFT_ADAPTIVE=0 / "
                   "TFT_RESULT_CACHE=0 (run-tests.sh --adaptive runs "
                   "this lane standalone)")
    config.addinivalue_line(
        "markers", "flight: flight-recorder/decision-audit/SLO/health "
                   "suite — always-on decision ring + tft.why() causal "
                   "chains with TFT_TRACE off, JSONL auto-dumps with "
                   "rotation, SLO burn math, tft.health(), metrics-"
                   "provider conformance (run-tests.sh --flight runs "
                   "this lane standalone)")
    config.addinivalue_line(
        "markers", "fabric: multi-host serving-fabric suite — tenant "
                   "sharding across workers, heartbeat/lease worker "
                   "loss with checkpointed cross-worker resume "
                   "(bit-identical), durable checkpoint/result tiers "
                   "surviving rolling restarts warm, SLO-burn-driven "
                   "re-placement, TFT_FABRIC=0 single-process parity "
                   "(run-tests.sh --fabric runs this lane standalone)")
    config.addinivalue_line(
        "markers", "shuffle: hash-repartition exchange suite — "
                   "placement/conservation properties, partitioned "
                   "hash join vs the broadcast oracle, shuffle "
                   "daggregate parity, TFT_SHUFFLE=0 bit-identity, "
                   "device-loss recovery mid-exchange (run-tests.sh "
                   "--shuffle runs this lane standalone)")
    config.addinivalue_line(
        "markers", "sentinel: performance-regression sentinel suite — "
                   "telemetry timeline ring + TFT_TIMELINE=0 bypass "
                   "bit-identity, per-query cost attribution, rolling "
                   "plan-fingerprint baselines with persistence, the "
                   "scripted regression drill (TFT_FAULTS=perf:1) "
                   "through every operator surface (run-tests.sh "
                   "--sentinel runs this lane standalone)")
    config.addinivalue_line(
        "markers", "chaos: seeded multi-site chaos-schedule suite — "
                   "reproducible fault composition over the existing "
                   "sites (TFT_CHAOS), the bounded mixed-workload "
                   "acceptance drill (bit-identity vs fault-free, zero "
                   "leaks, every failure classified), poison-query "
                   "quarantine, persist checksums (run-tests.sh --chaos "
                   "runs this lane standalone)")
    config.addinivalue_line(
        "markers", "invariants: cross-cutting invariant-auditor suite — "
                   "slot-lease balance, ledger reservation balance, "
                   "row conservation, checkpoint cursor consistency, "
                   "scheduler/fabric accounting; strict vs always-on "
                   "modes (run-tests.sh --chaos runs this lane too)")
    config.addinivalue_line(
        "markers", "history: durable query-history/post-mortem suite — "
                   "checksummed append-only segments with rotation and "
                   "retention, corrupt-segment cold behavior under "
                   "fault injection, tft.history() filters and "
                   "stitching, unclean-shutdown markers + "
                   "tft.postmortem(), cross-restart tft.why(), "
                   "TFT_HISTORY=0 bypass (run-tests.sh --history runs "
                   "this lane standalone)")
    config.addinivalue_line(
        "markers", "timing: wall-clock-sensitive deadline assertions — "
                   "margins are widened for loaded machines "
                   "(TFT_TIMING_MARGIN multiplies the bounds; "
                   "run-tests.sh --timing runs this lane serially); "
                   "deselect with -m 'not timing' when a box is badly "
                   "oversubscribed")
