"""Test fixture: CPU backend with 8 virtual devices.

The analogue of the reference's shared `local[1]` Spark fixture with
`spark.sql.shuffle.partitions=4`
(`TensorFlossTestSparkContext.scala:10-43`): unit tests run on the CPU
backend of the same code path that targets TPU, and mesh/partition tests use
8 virtual devices via XLA_FLAGS, per SURVEY.md §4.
"""

import os

# Must be set before jax is imported anywhere.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(42)
