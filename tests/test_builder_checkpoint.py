"""Serialized-computation import path (the PythonOpBuilder analogue) and
checkpoint/resume."""

import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu import dtypes as _dt
from tensorframes_tpu.builder import (aggregate_builder, load_computation,
                                      map_blocks_builder,
                                      reduce_blocks_builder,
                                      save_computation)
from tensorframes_tpu.computation import Computation, TensorSpec
from tensorframes_tpu.shape import Shape, Unknown


def _map_comp():
    return Computation.trace(
        lambda x: {"z": x + 3.0, "w": x * 2.0},
        [TensorSpec("x", _dt.double, Shape(Unknown))])


def test_builder_roundtrips_serialized_map():
    df = tft.frame({"x": np.arange(6.0)}, num_partitions=2)
    blob = _map_comp().serialize()
    out = map_blocks_builder(df).graph(blob).build()
    rows = out.collect()
    assert [r["z"] for r in rows] == [i + 3.0 for i in range(6)]
    assert [r["w"] for r in rows] == [i * 2.0 for i in range(6)]


def test_builder_fetches_subset():
    df = tft.frame({"x": np.arange(4.0)})
    blob = _map_comp().serialize()
    out = map_blocks_builder(df).graph(blob).fetches(["z"]).build()
    assert out.schema.names == ["x", "z"]
    with pytest.raises(ValueError, match="not among computation outputs"):
        map_blocks_builder(df).graph(blob).fetches(["nope"]).build()


def test_builder_reduce_and_aggregate():
    df = tft.frame({"x": np.arange(6.0)}, num_partitions=2)
    red = Computation.trace(
        lambda x_input: {"x": x_input.sum(0)},
        [TensorSpec("x_input", _dt.double, Shape(Unknown))])
    out = reduce_blocks_builder(df).graph(red.serialize()).build()
    assert float(out["x"]) == 15.0

    kdf = tft.frame({"key": np.array(["a", "b", "a", "b"]),
                     "x": np.arange(4.0)})
    agg = aggregate_builder(kdf.group_by("key")) \
        .graph(red.serialize()).build()
    got = {r["key"]: r["x"] for r in agg.collect()}
    assert got == {"a": 2.0, "b": 4.0}


def test_builder_requires_graph():
    df = tft.frame({"x": np.arange(3.0)})
    with pytest.raises(ValueError, match="No computation attached"):
        map_blocks_builder(df).build()


def test_save_load_computation_file(tmp_path):
    p = str(tmp_path / "comp.tftc")
    save_computation(_map_comp(), p)
    comp = load_computation(p)
    df = tft.frame({"x": np.arange(3.0)})
    rows = tft.map_blocks(comp, df).collect()
    assert [r["z"] for r in rows] == [3.0, 4.0, 5.0]


# -- checkpoint/resume ------------------------------------------------------

def test_checkpoint_roundtrip_host(tmp_path):
    from tensorframes_tpu.utils import checkpoint as ckpt

    state = {"w": np.arange(6.0).reshape(2, 3), "b": np.float32(1.5)}
    ckpt.save(str(tmp_path / "c1"), state)
    back = ckpt.restore(str(tmp_path / "c1"))
    np.testing.assert_array_equal(back["w"], state["w"])
    assert float(back["b"]) == 1.5


def test_checkpoint_resume_sharded_state(tmp_path):
    import jax
    import jax.numpy as jnp
    from tensorframes_tpu.utils import checkpoint as ckpt
    from tensorframes_tpu.models.logreg import LogisticRegression
    from tensorframes_tpu.parallel.mesh import local_mesh

    model = LogisticRegression(num_features=8)
    mesh = local_mesh()
    step = model.make_sharded_train_step(mesh)
    params = jax.tree_util.tree_map(jnp.asarray, model.init())

    root = str(tmp_path / "run")
    assert ckpt.latest_step(root) is None
    assert ckpt.restore_step(root) == (None, None)
    ckpt.save_step(root, 3, params)
    ckpt.save_step(root, 7, params)
    assert ckpt.latest_step(root) == 7

    restored, step_n = ckpt.restore_step(root, state_like=params)
    assert step_n == 7
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        restored, params)
