"""Query-scoped observability suite (tier-1; marker ``observability``).

Proves the PR-3 contract end-to-end on CPU: query-id correlation across
the pipeline (including worker threads), chrome-trace export validity,
Prometheus text-format rendering + escaping, ring-buffer bounding, the
explain()/counters consistency, the gauge stat-family fix, the merged
stats report, profile()/span() exception safety — and that with tracing
disabled the event layer records nothing at all.

The mesh/device half (this PR): per-device shard events and Perfetto
tracks for the distributed ops, self-describing ``traced_query``
metadata, straggler-ratio mesh sections in ``explain()``, HBM watermark
sampling (graceful None on CPU; fake devices prove the recording),
OOM-split watermark tagging, Prometheus histogram families, and the
``TFT_SLOW_QUERY_MS`` slow-query log.
"""

import json
import re
import threading
import urllib.request
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu import dtypes as _dt
from tensorframes_tpu import observability as obs
from tensorframes_tpu.computation import Computation, TensorSpec
from tensorframes_tpu.engine.executor import BlockExecutor
from tensorframes_tpu.observability import device as obs_device
from tensorframes_tpu.observability import events as obs_events
from tensorframes_tpu.parallel.distributed import (daggregate, dfilter,
                                                   dmap_blocks,
                                                   dreduce_blocks, dsort,
                                                   distribute)
from tensorframes_tpu.parallel.mesh import local_mesh
from tensorframes_tpu.resilience import faults
from tensorframes_tpu.shape import Shape, Unknown
from tensorframes_tpu.utils import tracing

pytestmark = pytest.mark.observability


@pytest.fixture(autouse=True)
def _clean_observability():
    tracing.disable()
    tracing.timings.reset()
    tracing.counters.reset()
    tracing.histograms.reset()
    obs.clear_ring()
    obs_events._reset_last_query()
    obs_device._reset()
    yield
    tracing.disable()
    tracing.timings.reset()
    tracing.counters.reset()
    tracing.histograms.reset()
    obs.clear_ring()
    obs_events._reset_last_query()
    obs_device._reset()


def _depth(monkeypatch, d):
    monkeypatch.setenv("TFT_PIPELINE_DEPTH", str(d))


def _traced_map(monkeypatch, n=30, parts=6, depth=3):
    _depth(monkeypatch, depth)
    tracing.enable()
    df = tft.frame({"x": np.arange(float(n))}, num_partitions=parts)
    out = df.map_blocks(lambda x: {"y": x + 1.0})
    out.blocks()
    return df, out, out._trace


# ---------------------------------------------------------------------------
# correlation / context propagation
# ---------------------------------------------------------------------------

class TestCorrelation:
    def test_forcing_opens_query_trace(self, monkeypatch):
        _, out, t = _traced_map(monkeypatch)
        assert t is not None
        assert t.op == "map_blocks"
        assert re.fullmatch(r"q\d+", t.query_id)
        assert t.duration is not None and t.duration >= 0

    def test_query_ids_unique_per_query(self, monkeypatch):
        _, _, t1 = _traced_map(monkeypatch)
        _, _, t2 = _traced_map(monkeypatch)
        assert t1.query_id != t2.query_id

    def test_nested_forcings_join_outer_query(self, monkeypatch):
        # a chained lazy plan forces upstream frames inside one query:
        # exactly ONE trace, owned by the outermost forcing
        _depth(monkeypatch, 3)
        tracing.enable()
        df = tft.frame({"x": np.arange(20.0)}, num_partitions=4)
        mid = df.map_blocks(lambda x: {"y": x + 1.0})
        top = mid.map_blocks(lambda y: {"z": y * 2.0})
        top.blocks()
        assert top._trace is not None
        assert mid._trace is None  # joined the ambient query
        assert obs.last_query() is top._trace

    def test_query_id_survives_worker_threads(self):
        tracing.enable()
        seen = []
        with obs.query_trace("threaded") as t:
            with ThreadPoolExecutor(max_workers=2) as pool:
                fs = [pool.submit(obs.wrap_context(obs.current_trace))
                      for _ in range(4)]
                seen = [f.result() for f in fs]
            # an UNwrapped hop must not see the trace (that would mean
            # thread-inherited globals, not context propagation)
            with ThreadPoolExecutor(max_workers=1) as pool:
                bare = pool.submit(obs.current_trace).result()
        assert all(s is t for s in seen)
        assert bare is None

    def test_pipeline_worker_thread_events_attach_to_query(self,
                                                           monkeypatch):
        """An executor that dispatches on its own worker thread (the
        native-PJRT submit pattern, via wrap_context) records events that
        land on the submitting query's trace."""
        _depth(monkeypatch, 3)
        tracing.enable()
        inner = BlockExecutor()
        worker_qids = []

        class ThreadedExecutor:
            pad_rows = False

            def __init__(self):
                self._pool = ThreadPoolExecutor(max_workers=1)

            @property
            def compile_count(self):
                return inner.compile_count

            def run(self, comp, arrays, pad_ok=True):
                return inner.run(comp, arrays, pad_ok=pad_ok)

            def submit(self, comp, arrays, pad_ok=True):
                def work():
                    t = obs.current_trace()
                    worker_qids.append(t.query_id if t else None)
                    obs.add_event("worker_dispatch")
                    return inner.run(comp, arrays, pad_ok=pad_ok)

                fut = self._pool.submit(obs.wrap_context(work))

                class P:
                    def drain(self):
                        return fut.result()

                return P()

            def clear(self):
                inner.clear()

        df = tft.frame({"x": np.arange(24.0)}, num_partitions=6)
        out = df.map_blocks(lambda x: {"y": x - 1.0},
                            executor=ThreadedExecutor())
        got = np.asarray([r["y"] for r in out.collect()], float).ravel()
        np.testing.assert_array_equal(got, np.arange(24.0) - 1.0)
        t = out._trace
        assert t is not None
        assert worker_qids == [t.query_id] * 6
        assert t.count("worker_dispatch") == 6

    def test_eager_reduce_records_last_query(self, monkeypatch):
        _depth(monkeypatch, 3)
        tracing.enable()
        df = tft.frame({"x": np.arange(12.0)}, num_partitions=3)
        val = tft.reduce_blocks(lambda x_input: {"x": x_input.sum(0)}, df)
        assert float(val) == float(np.arange(12.0).sum())
        t = obs.last_query()
        assert t is not None and t.op == "reduce_blocks"
        assert "reduce_blocks" in tft.last_query_report()


# ---------------------------------------------------------------------------
# chrome trace export
# ---------------------------------------------------------------------------

class TestChromeTrace:
    def test_chrome_trace_valid_and_sorted(self, monkeypatch, tmp_path):
        _, out, t = _traced_map(monkeypatch, n=30, parts=6, depth=3)
        path = tmp_path / "trace.json"
        text = t.to_chrome_trace(file=str(path))
        doc = json.loads(text)
        assert json.loads(path.read_text()) == doc
        evs = doc["traceEvents"]
        assert evs, "no events exported"
        for e in evs:
            for field in ("ph", "ts", "pid", "tid"):
                assert field in e, (field, e)
        ts = [e["ts"] for e in evs]
        assert ts == sorted(ts)

    def test_per_block_events_on_slot_tracks_share_query_id(
            self, monkeypatch):
        _, out, t = _traced_map(monkeypatch, n=30, parts=6, depth=3)
        doc = json.loads(t.to_chrome_trace())
        evs = [e for e in doc["traceEvents"] if e["ph"] != "M"]
        assert {e["args"]["query_id"] for e in evs} == {t.query_id}
        by_cat = {}
        for e in evs:
            by_cat.setdefault(e.get("cat"), []).append(e)
        assert len(by_cat["block_submit"]) == 6
        assert len(by_cat["block_compute"]) == 6
        assert len(by_cat["block_drain"]) == 6
        # per-slot tracks: depth 3 -> tids 1..3, plus the query track 0
        block_tids = {e["tid"] for cat in ("block_submit", "block_drain")
                      for e in by_cat[cat]}
        assert block_tids == {1, 2, 3}
        # slot thread names exported for perfetto
        names = {e["args"]["name"] for e in doc["traceEvents"]
                 if e["ph"] == "M" and e["name"] == "thread_name"}
        assert {"query", "slot 0", "slot 1", "slot 2"} <= names

    def test_serial_depth_records_block_runs(self, monkeypatch):
        _, out, t = _traced_map(monkeypatch, n=12, parts=3, depth=1)
        assert t.count("block_run") == 3
        s = t.summary()
        assert s["blocks"] == 3 and s["rows_in"] == 12


# ---------------------------------------------------------------------------
# explain() / summary vs counters
# ---------------------------------------------------------------------------

class TestExplain:
    def test_explain_counts_match_counters(self, monkeypatch):
        base = tracing.counters.snapshot()
        _, out, t = _traced_map(monkeypatch, n=30, parts=6, depth=3)
        s = t.summary()
        now = tracing.counters.snapshot()

        def delta(name):
            return now.get(name, 0) - base.get(name, 0)

        assert s["blocks"] == delta("pipeline.submitted") == 6
        assert s["rows_in"] == 30 and s["rows_out"] == 30
        assert s["bytes_in"] == 30 * np.dtype(float).itemsize
        assert s["sync_fallbacks"] == delta("pipeline.sync_fallbacks") == 0
        assert s["compile_misses"] == delta("compile_cache.misses")
        assert s["compile_hits"] == delta("compile_cache.hits")
        report = out.explain()
        assert "30 in / 30 out" in report
        assert "6 block(s)" in report
        assert t.query_id in report
        assert "wall time by stage" in report

    def test_explain_reports_sync_fallbacks(self, monkeypatch):
        _depth(monkeypatch, 3)
        tracing.enable()
        base = tracing.counters.snapshot()
        df = tft.frame({"x": np.arange(20.0)}, num_partitions=4)
        out = df.map_blocks(lambda x: {"y": x + 1.0})
        # two async submit faults -> two blocks recover through the sync
        # fallback path; the trace must agree with the global counters
        with faults.inject("dispatch", fail_n=2):
            out.blocks()
        got = np.asarray([r["y"] for r in out.collect()], float).ravel()
        np.testing.assert_array_equal(got, np.arange(20.0) + 1.0)
        t = out._trace
        s = t.summary()
        now = tracing.counters.snapshot()

        def delta(name):
            return now.get(name, 0) - base.get(name, 0)

        assert s["sync_fallbacks"] == delta("pipeline.sync_fallbacks") == 2
        fb = [e for e in t.events if e.etype == "sync_fallback"]
        assert [e.args["error"] for e in fb] == ["InjectedFault"] * 2
        assert "2 sync fallback(s)" in out.explain()

    def test_explain_reports_retries_with_classified_error(
            self, monkeypatch):
        _depth(monkeypatch, 1)  # serial path: the fault hits the retry
        monkeypatch.setenv("TFT_RETRY_BASE_DELAY", "0.001")
        tracing.enable()
        base = tracing.counters.snapshot()
        df = tft.frame({"x": np.arange(12.0)}, num_partitions=3)
        out = df.map_blocks(lambda x: {"y": x + 1.0})
        with faults.inject("dispatch", fail_n=1):
            out.blocks()
        t = out._trace
        s = t.summary()
        now = tracing.counters.snapshot()
        delta = (now.get("retry.executor.dispatch.retries", 0)
                 - base.get("retry.executor.dispatch.retries", 0))
        assert s["retries"] == delta == 1
        retry = [e for e in t.events if e.etype == "retry"][0]
        assert retry.args["error"] == "InjectedFault"
        assert retry.args["kind"] == "transient"
        assert "1 retried" in out.explain()

    def test_explain_forces_untraced_frame(self, monkeypatch):
        _depth(monkeypatch, 3)
        df = tft.frame({"x": np.arange(10.0)}, num_partitions=2)
        out = df.map_blocks(lambda x: {"y": x * 3.0})
        out.blocks()  # forced with tracing OFF: no trace recorded
        assert out._trace is None
        report = out.explain()  # re-forces once, tracing temporarily on
        assert out._trace is not None
        assert "map_blocks" in report
        assert not tracing.enabled()  # restored

    def test_last_query_report_without_queries(self):
        assert "no query recorded" in tft.last_query_report()


# ---------------------------------------------------------------------------
# sinks: ring buffer + JSONL file
# ---------------------------------------------------------------------------

class TestSinks:
    def test_ring_buffer_bounded_under_10k_events(self, monkeypatch):
        monkeypatch.setenv("TFT_TRACE_RING", "1000")
        obs.clear_ring()
        tracing.enable()
        with obs.query_trace("flood") as t:
            for i in range(10_500):
                t.add("tick", i=i)
        ring = obs.recent_events()
        assert len(ring) == 1000  # bounded, newest kept
        assert ring[-1]["i"] == 10_499
        assert t.dropped == 0  # per-trace bound is separate

    def test_per_trace_event_bound_drops_and_counts(self):
        tracing.enable()
        with obs.query_trace("flood") as t:
            t._max_events = 10
            for i in range(25):
                t.add("tick", i=i)
        assert len(t.events) == 10
        assert t.dropped == 15
        assert tracing.counters.get("trace.events_dropped") == 15
        assert "+15 dropped" in t.report()

    def test_jsonl_file_sink(self, monkeypatch, tmp_path):
        path = tmp_path / "events.jsonl"
        monkeypatch.setenv("TFT_TRACE_FILE", str(path))
        _, out, t = _traced_map(monkeypatch, n=12, parts=3, depth=3)
        lines = [json.loads(line) for line in
                 path.read_text().splitlines()]
        heads = [r for r in lines if r["type"] == "query"]
        assert any(h["query_id"] == t.query_id for h in heads)
        evs = [r for r in lines if r.get("query_id") == t.query_id
               and r["type"] != "query"]
        assert len(evs) == len(t.events)
        assert all("ts" in e for e in evs)


# ---------------------------------------------------------------------------
# Prometheus metrics
# ---------------------------------------------------------------------------

_PROM_LINE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z_][a-zA-Z0-9_]*='
    r'"(?:[^"\\\n]|\\["\\n])*"(,[a-zA-Z_][a-zA-Z0-9_]*='
    r'"(?:[^"\\\n]|\\["\\n])*")*\})? '
    r'(-?\d+(\.\d+)?([eE][+-]?\d+)?|[+-]Inf|NaN)$')


class TestMetrics:
    def test_metrics_text_parses_as_prometheus(self, monkeypatch):
        _traced_map(monkeypatch)
        text = obs.metrics_text()
        assert text.endswith("\n")
        for line in text.splitlines():
            if not line or line.startswith("#"):
                continue
            assert _PROM_LINE.match(line), line
        assert 'tft_counter_total{name="pipeline.submitted"} 6' \
            in text
        assert 'tft_span_seconds_count{span="pipeline.submit"} 6' in text
        assert 'tft_gauge{name="pipeline.occupancy",stat="mean"}' in text
        assert "tft_trace_ring_events" in text

    def test_label_escaping(self):
        tracing.counters.inc('weird"name\\with\nnasties')
        text = obs.metrics_text()
        line = next(ln for ln in text.splitlines()
                    if "weird" in ln)
        assert _PROM_LINE.match(line), line
        assert '\\"' in line and "\\\\" in line and "\\n" in line
        assert "\n" not in line  # the raw newline never leaks through

    def test_endpoint_serves_metrics_on_loopback(self):
        tracing.counters.inc("endpoint.smoke")
        port = obs.serve_metrics(0)
        try:
            assert obs.metrics_port() == port
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
                assert r.status == 200
                assert "text/plain" in r.headers["Content-Type"]
                body = r.read().decode()
            assert 'tft_counter_total{name="endpoint.smoke"} 1' in body
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/nope", timeout=10)
        finally:
            obs.stop_metrics()
        assert obs.metrics_port() is None

    def test_rebind_to_different_port_raises(self):
        port = obs.serve_metrics(0)
        try:
            assert obs.serve_metrics(0) == port  # idempotent
            assert obs.serve_metrics(port) == port
            with pytest.raises(RuntimeError, match="already running"):
                obs.serve_metrics(port + 1)  # silently dead scrape target
        finally:
            obs.stop_metrics()


# ---------------------------------------------------------------------------
# zero-cost-when-off
# ---------------------------------------------------------------------------

class TestZeroCostWhenOff:
    def test_no_events_recorded_with_tracing_disabled(self, monkeypatch):
        _depth(monkeypatch, 3)
        assert not tracing.enabled()
        df = tft.frame({"x": np.arange(20.0)}, num_partitions=4)
        out = df.map_blocks(lambda x: {"y": x + 1.0})
        out.blocks()
        tft.reduce_blocks(lambda x_input: {"x": x_input.sum(0)}, df)
        assert out._trace is None
        assert obs.last_query() is None
        assert obs.recent_events() == []
        assert obs.current_trace() is None
        assert tracing.timings.snapshot() == {}

    def test_add_event_without_trace_is_noop(self):
        obs.add_event("orphan", detail="nothing listens")
        assert obs.recent_events() == []

    def test_bypass_strips_layer_even_when_enabled(self):
        tracing.enable()
        with obs_events.bypass():
            with obs.query_trace("stripped") as t:
                assert t is None
        assert obs.last_query() is None


# ---------------------------------------------------------------------------
# satellite: gauge stat family + merged report + dump_stats
# ---------------------------------------------------------------------------

class TestStatsSatellites:
    def test_gauge_has_own_stat_family(self):
        tracing.enable()
        for v in (1.0, 3.0, 2.0):
            tracing.gauge("my.level", v)
        snap = tracing.timings.snapshot()
        g = snap["my.level"]
        assert g == {"count": 3, "mean": 2.0, "min": 1.0, "max": 3.0,
                     "last": 2.0}
        assert "mean_s" not in g  # no vestigial seconds suffix

    def test_occupancy_legacy_aliases_removed(self):
        # the pre-0.2 duration-suffixed aliases were kept for exactly
        # one release (PR 3); they are gone now, as promised
        tracing.enable()
        tracing.gauge("pipeline.occupancy", 2.0)
        tracing.gauge("pipeline.occupancy", 4.0)
        occ = tracing.timings.snapshot()["pipeline.occupancy"]
        assert occ["mean"] == 3.0 and occ["last"] == 4.0
        assert "mean_s" not in occ
        assert "min_s" not in occ
        assert "max_s" not in occ

    def test_report_merges_counters_and_gauges(self):
        tracing.enable()
        with tracing.span("stagey"):
            pass
        tracing.gauge("leveley", 5.0)
        tracing.counters.inc("county.things", 3)
        rep = tracing.timings.report()
        assert "stagey" in rep
        assert "leveley" in rep
        assert "county.things" in rep
        assert "gauge" in rep and "counter" in rep

    def test_dump_stats_prints_everything(self, capsys):
        tracing.enable()
        with tracing.span("dumped.span"):
            pass
        tracing.gauge("dumped.gauge", 1.0)
        tracing.counters.inc("dumped.counter")
        tft.dump_stats()
        out = capsys.readouterr().out
        for name in ("dumped.span", "dumped.gauge", "dumped.counter"):
            assert name in out


# ---------------------------------------------------------------------------
# mesh & device observability
# ---------------------------------------------------------------------------

def _mesh_comp(factor=2.0):
    return Computation.trace(
        lambda x: {"y": x * factor},
        [TensorSpec("x", _dt.double, Shape(Unknown))])


def _mesh_fixture(n=64):
    mesh = local_mesh()
    df = tft.frame({"x": np.arange(float(n))})
    dist = distribute(df, mesh)
    return mesh, dist


class TestMeshObservability:
    def test_dmap_records_shard_events_and_entry_meta(self):
        tracing.enable()
        mesh, dist = _mesh_fixture()
        S = mesh.num_data_shards
        dmap_blocks(_mesh_comp(), dist)
        t = obs.last_query()
        assert t is not None and t.op == "dmap_blocks"
        # traced_query entry metadata: self-describing, not a bare name
        assert t.meta["shards"] == S
        assert t.meta["mesh_shape"] == dict(mesh.mesh.shape)
        assert t.meta["fetches"] == ["y"]
        assert t.meta["rows"] == 64
        # one shard event and one readiness timing per data shard
        assert t.count("shard") == S
        assert t.count("shard_compute") == S
        assert t.count("mesh_dispatch") == 1
        s = t.summary()
        assert s["mesh"] is not None
        devs = s["mesh"]["devices"]
        assert set(devs) == set(range(S))
        assert all(d["rows"] == 64 // S for d in devs.values())
        assert all(d["bytes"] > 0 for d in devs.values())
        assert all(d["time_s"] >= 0.0 for d in devs.values())

    def test_chrome_trace_one_track_per_device(self):
        tracing.enable()
        mesh, dist = _mesh_fixture()
        dmap_blocks(_mesh_comp(), dist)
        t = obs.last_query()
        doc = json.loads(t.to_chrome_trace())
        names = {e["args"]["name"] for e in doc["traceEvents"]
                 if e["ph"] == "M" and e["name"] == "thread_name"}
        want = {f"device {i}" for i in range(mesh.num_data_shards)}
        assert want <= names
        # device events land on device tracks (tid >= DEVICE_TRACK_BASE)
        dev_tids = {e["tid"] for e in doc["traceEvents"]
                    if e.get("cat") in ("shard", "shard_compute")}
        assert dev_tids == {obs.DEVICE_TRACK_BASE + i
                            for i in range(mesh.num_data_shards)}

    def test_explain_mesh_section_with_straggler_ratio(self):
        tracing.enable()
        mesh, dist = _mesh_fixture()
        dmap_blocks(_mesh_comp(), dist)
        report = tft.last_query_report()
        assert "mesh" in report
        assert "straggler ratio" in report
        for i in range(mesh.num_data_shards):
            assert f"device {i}:" in report
        ratio = obs.last_query().summary()["mesh"]["straggler_ratio"]
        assert ratio is None or ratio >= 1.0

    def test_skew_warning_above_threshold(self, monkeypatch):
        monkeypatch.setenv("TFT_SKEW_WARN", "1.5")
        tracing.enable()
        with obs.query_trace("skewed") as t:
            for i in range(4):
                t.add("shard", device=i, rows=10, bytes=80,
                      track=obs.DEVICE_TRACK_BASE + i)
                t.add("shard_compute", device=i, ts=0.0,
                      dur=1.0 if i == 3 else 0.1,
                      track=obs.DEVICE_TRACK_BASE + i)
        report = obs.render(t)
        assert "WARNING" in report and "imbalance" in report
        assert t.summary()["mesh"]["straggler_ratio"] == pytest.approx(10.0)

    def test_mesh_ops_record_collectives(self):
        tracing.enable()
        mesh, dist = _mesh_fixture()
        dreduce_blocks({"x": "sum"}, dist)
        t = obs.last_query()
        assert t.op == "dreduce_blocks"
        coll = [e for e in t.events if e.etype == "collective"]
        assert [e.name for e in coll] == ["psum"]
        dsort("x", dist)
        t = obs.last_query()
        names = {e.name for e in t.events if e.etype == "collective"}
        assert ({"all_to_all", "ppermute"} <= names
                or mesh.num_data_shards == 1)

    def test_dfilter_and_daggregate_record_mesh_events(self):
        tracing.enable()
        mesh, dist = _mesh_fixture()
        S = mesh.num_data_shards
        pred = Computation.trace(
            lambda x: {"keep": x < 32.0},
            [TensorSpec("x", _dt.double, Shape(Unknown))])
        dfilter(pred, dist)
        t = obs.last_query()
        assert t.op == "dfilter" and t.count("shard") == S
        assert t.count("mesh_dispatch") == 1
        df2 = tft.frame({"k": np.arange(16) % 4,
                         "v": np.arange(16.0)})
        dist2 = distribute(df2, mesh)
        daggregate({"v": "sum"}, dist2, "k")
        t = obs.last_query()
        assert t.op == "daggregate"
        assert t.meta["keys"] == ["k"] and t.meta["fetches"] == ["v"]
        assert t.count("collective") == 1
        assert t.count("mesh_dispatch") == 1

    def test_interleaved_queries_distinct_ids_no_track_collisions(self):
        tracing.enable()
        mesh, dist = _mesh_fixture(n=32)
        comp = _mesh_comp()
        dmap_blocks(comp, dist)  # warm the jit so both workers overlap
        barrier = threading.Barrier(2)

        def worker(i):
            barrier.wait()
            with obs.query_trace(f"interleaved") as t:
                dmap_blocks(comp, dist)
            return t

        with ThreadPoolExecutor(max_workers=2) as pool:
            ts = list(pool.map(worker, range(2)))
        assert all(t is not None for t in ts)
        assert ts[0].query_id != ts[1].query_id
        for t in ts:
            doc = json.loads(t.to_chrome_trace())
            evs = [e for e in doc["traceEvents"] if e["ph"] != "M"]
            # every event carries exactly this trace's correlation id
            assert {e["args"]["query_id"] for e in evs} == {t.query_id}
            tracks = [(e["tid"], e["args"]["name"])
                      for e in doc["traceEvents"]
                      if e["ph"] == "M" and e["name"] == "thread_name"]
            assert len(tracks) == len(set(tracks))  # no collisions
            assert t.count("shard") == mesh.num_data_shards

    def test_ring_allreduce_records_collective_event(self):
        import jax

        from tensorframes_tpu.parallel.ring import ring_allreduce
        tracing.enable()
        mesh = local_mesh()
        n = mesh.num_data_shards
        x = jax.device_put(np.arange(float(n * 4)).reshape(n, 4),
                           mesh.row_sharding(2))
        with obs.query_trace("ring") as t:
            out = ring_allreduce(x, mesh)
        np.testing.assert_allclose(
            np.asarray(out)[0], np.asarray(x).sum(axis=0))
        ev = [e for e in t.events if e.etype == "collective"]
        assert len(ev) == 1 and ev[0].name == "ring_allreduce"
        assert ev[0].args["hops"] == 2 * (n - 1)
        assert ev[0].dur is not None and ev[0].dur >= 0.0

    def test_mesh_ops_record_nothing_with_tracing_off(self):
        assert not tracing.enabled()
        mesh, dist = _mesh_fixture(n=16)
        dmap_blocks(_mesh_comp(), dist)
        dreduce_blocks({"x": "sum"}, dist)
        assert obs.last_query() is None
        assert obs.recent_events() == []


# ---------------------------------------------------------------------------
# device memory (HBM watermarks)
# ---------------------------------------------------------------------------

class _FakeDevice:
    def __init__(self, live, peak):
        self._stats = {"bytes_in_use": live, "peak_bytes_in_use": peak}

    def memory_stats(self):
        return self._stats


class TestDeviceMemory:
    def test_cpu_backend_is_a_graceful_none(self):
        # the real CPU backend reports nothing (or an empty dict):
        # sampling must return None and latch off, never raise
        tracing.enable()
        with obs.query_trace("probe") as t:
            got = obs_device.sample(t, "probe")
        if got is None:
            assert not obs_device.supported()
        else:  # a backend that DOES report stats records the event
            assert t.count("hbm_sample") >= 1

    def test_fake_devices_record_watermarks_in_explain(self, monkeypatch):
        monkeypatch.setattr(obs_device, "_local_devices",
                            lambda: [_FakeDevice(100, 300),
                                     _FakeDevice(50, 200)])
        obs_device._reset()
        tracing.enable()
        df = tft.frame({"x": np.arange(8.0)}, num_partitions=2)
        out = df.map_blocks(lambda x: {"y": x + 1.0})
        out.blocks()
        t = out._trace
        s = t.summary()
        assert s["hbm"] is not None
        assert s["hbm"]["peak"] == 500  # summed across devices
        assert s["hbm"]["live_start"] == 150
        report = out.explain()
        assert "peak HBM" in report
        # per-device samples land on the device tracks at query start/end
        per_dev = [e for e in t.events if e.etype == "hbm_sample"
                   and (e.args or {}).get("device") is not None]
        assert {e.args["device"] for e in per_dev} == {0, 1}

    def test_oom_split_tagged_with_watermark(self, monkeypatch):
        monkeypatch.setattr(obs_device, "_local_devices",
                            lambda: [_FakeDevice(111, 222)])
        obs_device._reset()
        tracing.enable()
        df = tft.frame({"x": np.arange(16.0)}, num_partitions=1)
        out = df.map_rows(lambda x: {"y": x * 3.0})
        with faults.inject("oom", fail_n=1):
            out.blocks()
        t = out._trace
        splits = [e for e in t.events if e.etype == "oom_split"]
        assert splits and splits[0].args["hbm_peak_bytes"] == 222
        assert splits[0].args["hbm_live_bytes"] == 111

    def test_no_memory_stats_calls_with_tracing_off(self, monkeypatch):
        calls = []

        def probed():
            calls.append(1)
            return []

        monkeypatch.setattr(obs_device, "_local_devices", probed)
        obs_device._reset()
        assert not tracing.enabled()
        df = tft.frame({"x": np.arange(8.0)}, num_partitions=2)
        df.map_blocks(lambda x: {"y": x + 1.0}).blocks()
        assert calls == []  # zero-cost-when-off: no device probing at all


# ---------------------------------------------------------------------------
# satellite: Prometheus histograms
# ---------------------------------------------------------------------------

class TestHistograms:
    def test_histogram_families_valid_and_cumulative(self, monkeypatch):
        _traced_map(monkeypatch)  # one compile miss + one finished query
        text = obs.metrics_text()
        for line in text.splitlines():
            if not line or line.startswith("#"):
                continue
            assert _PROM_LINE.match(line), line
        assert "# TYPE tft_query_latency_seconds histogram" in text
        assert "# TYPE tft_compile_seconds histogram" in text
        buckets, count = [], None
        for line in text.splitlines():
            if line.startswith(
                    'tft_query_latency_seconds_bucket{op="map_blocks"'):
                buckets.append(int(line.rsplit(" ", 1)[1]))
            elif line.startswith(
                    'tft_query_latency_seconds_count{op="map_blocks"'):
                count = int(line.rsplit(" ", 1)[1])
        assert buckets, "no latency buckets rendered"
        assert buckets == sorted(buckets)  # cumulative le semantics
        assert buckets[-1] == count == 1   # +Inf bucket equals _count
        assert 'le="+Inf"' in text
        assert 'tft_compile_seconds_bucket{engine="jax",le="+Inf"} 1' \
            in text
        assert "tft_compile_seconds_sum" in text

    def test_compile_seconds_observed_even_untraced(self, monkeypatch):
        # the histogram is always-on (like counters): a compile miss with
        # tracing off still observes
        _depth(monkeypatch, 1)
        assert not tracing.enabled()
        df = tft.frame({"x": np.arange(6.0)}, num_partitions=2)
        df.map_blocks(lambda x: {"y": x - 1.0}).blocks()
        snap = tracing.histograms.snapshot()
        key = ("compile_seconds", (("engine", "jax"),))
        assert key in snap and snap[key]["count"] >= 1

    def test_counter_and_gauge_output_unchanged(self, monkeypatch):
        # byte-compatibility: the pre-histogram families render the same
        _traced_map(monkeypatch)
        text = obs.metrics_text()
        assert 'tft_counter_total{name="pipeline.submitted"} 6' in text
        assert 'tft_gauge{name="pipeline.occupancy",stat="mean"}' in text
        assert "tft_trace_ring_events" in text


# ---------------------------------------------------------------------------
# satellite: slow-query log
# ---------------------------------------------------------------------------

class TestSlowQueryLog:
    def test_logs_jsonl_with_tracing_off(self, monkeypatch, tmp_path):
        path = tmp_path / "slow.jsonl"
        monkeypatch.setenv("TFT_SLOW_QUERY_MS", "0")
        monkeypatch.setenv("TFT_TRACE_FILE", str(path))
        assert not tracing.enabled()
        df = tft.frame({"x": np.arange(8.0)}, num_partitions=2)
        df.map_blocks(lambda x: {"y": x + 1.0}).blocks()
        recs = [json.loads(line) for line in
                path.read_text().splitlines()]
        slow = [r for r in recs if r["type"] == "slow_query"]
        assert len(slow) == 1  # one condensed line, no event stream
        assert slow[0]["op"] == "map_blocks"
        assert slow[0]["duration_ms"] >= 0.0
        assert "query_id" not in slow[0]  # no trace existed

    def test_includes_summary_fields_when_traced(self, monkeypatch,
                                                 tmp_path):
        path = tmp_path / "slow.jsonl"
        monkeypatch.setenv("TFT_SLOW_QUERY_MS", "0")
        monkeypatch.setenv("TFT_TRACE_FILE", str(path))
        tracing.enable()
        df = tft.frame({"x": np.arange(12.0)}, num_partitions=3)
        out = df.map_blocks(lambda x: {"y": x + 1.0})
        out.blocks()
        recs = [json.loads(line) for line in
                path.read_text().splitlines()]
        slow = [r for r in recs if r["type"] == "slow_query"]
        assert len(slow) == 1
        assert slow[0]["query_id"] == out._trace.query_id
        assert slow[0]["blocks"] == 3
        assert slow[0]["retries"] == 0

    def test_fast_queries_stay_silent(self, monkeypatch, tmp_path):
        path = tmp_path / "slow.jsonl"
        monkeypatch.setenv("TFT_SLOW_QUERY_MS", "60000")
        monkeypatch.setenv("TFT_TRACE_FILE", str(path))
        df = tft.frame({"x": np.arange(8.0)}, num_partitions=2)
        df.map_blocks(lambda x: {"y": x + 1.0}).blocks()
        if path.exists():
            recs = [json.loads(line) for line in
                    path.read_text().splitlines()]
            assert not [r for r in recs if r["type"] == "slow_query"]

    def test_failed_query_marked_in_log_and_histogram(self, monkeypatch,
                                                      tmp_path):
        path = tmp_path / "slow.jsonl"
        monkeypatch.setenv("TFT_SLOW_QUERY_MS", "0")
        monkeypatch.setenv("TFT_TRACE_FILE", str(path))
        tracing.enable()
        with pytest.raises(RuntimeError, match="boom"):
            with obs.query_trace("doomed"):
                raise RuntimeError("boom")
        recs = [json.loads(line) for line in
                path.read_text().splitlines()]
        slow = [r for r in recs if r["type"] == "slow_query"]
        assert slow and slow[0]["error"] == "RuntimeError"
        key = ("query_latency_seconds",
               (("op", "doomed"), ("outcome", "error")))
        assert tracing.histograms.snapshot()[key]["count"] == 1
        assert obs.last_query().meta["error"] == "RuntimeError"
        # the tracing-off timer branch carries the marker too
        tracing.disable()
        with pytest.raises(ValueError):
            with obs.query_trace("doomed2"):
                raise ValueError("x")
        recs = [json.loads(line) for line in
                path.read_text().splitlines()]
        assert any(r.get("op") == "doomed2"
                   and r.get("error") == "ValueError" for r in recs)

    def test_malformed_threshold_ignored(self, monkeypatch):
        monkeypatch.setenv("TFT_SLOW_QUERY_MS", "not-a-number")
        df = tft.frame({"x": np.arange(4.0)}, num_partitions=1)
        df.map_blocks(lambda x: {"y": x + 1.0}).blocks()  # must not raise


# ---------------------------------------------------------------------------
# satellite: profile()/span() exception safety
# ---------------------------------------------------------------------------

class TestTracingExceptionSafety:
    def test_profile_stop_failure_does_not_mask_body_error(
            self, monkeypatch, tmp_path):
        import jax

        # fake session: a real one left open by the raising stop_trace
        # would wedge every later jax.profiler user in the process
        monkeypatch.setattr(jax.profiler, "start_trace",
                            lambda log_dir, **k: None)
        monkeypatch.setattr(jax.profiler, "stop_trace",
                            _raise_runtime_error)
        with pytest.raises(ValueError, match="body failed"):
            with tracing.profile(str(tmp_path)):
                raise ValueError("body failed")
        assert not tracing.enabled()

    def test_profile_stop_failure_does_not_fail_successful_body(
            self, monkeypatch, tmp_path):
        import jax

        monkeypatch.setattr(jax.profiler, "start_trace",
                            lambda log_dir, **k: None)
        monkeypatch.setattr(jax.profiler, "stop_trace",
                            _raise_runtime_error)
        with tracing.profile(str(tmp_path)):
            pass  # succeeded; the failing stop must be swallowed+logged
        assert not tracing.enabled()

    def test_span_survives_annotation_exit_failure(self, monkeypatch):
        class EvilAnnotation:
            def __enter__(self):
                return self

            def __exit__(self, *exc):
                raise RuntimeError("annotation teardown exploded")

        monkeypatch.setattr(tracing, "_device_annotation",
                            lambda name: EvilAnnotation())
        tracing.enable()
        with tracing.span("guarded"):
            pass  # must not raise
        snap = tracing.timings.snapshot()
        assert snap["guarded"]["count"] == 1  # timing still recorded

    def test_span_survives_annotation_enter_failure(self, monkeypatch):
        class Unenterable:
            def __enter__(self):
                raise RuntimeError("no profiler session")

            def __exit__(self, *exc):
                raise AssertionError("never entered, never exited")

        monkeypatch.setattr(tracing, "_device_annotation",
                            lambda name: Unenterable())
        tracing.enable()
        with tracing.span("guarded2"):
            pass
        assert tracing.timings.snapshot()["guarded2"]["count"] == 1


def _raise_runtime_error(*a, **k):
    raise RuntimeError("profiler session already gone")
