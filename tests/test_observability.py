"""Query-scoped observability suite (tier-1; marker ``observability``).

Proves the PR-3 contract end-to-end on CPU: query-id correlation across
the pipeline (including worker threads), chrome-trace export validity,
Prometheus text-format rendering + escaping, ring-buffer bounding, the
explain()/counters consistency, the gauge stat-family fix, the merged
stats report, profile()/span() exception safety — and that with tracing
disabled the event layer records nothing at all.
"""

import json
import re
import threading
import urllib.request
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu import observability as obs
from tensorframes_tpu.engine.executor import BlockExecutor
from tensorframes_tpu.observability import events as obs_events
from tensorframes_tpu.resilience import faults
from tensorframes_tpu.utils import tracing

pytestmark = pytest.mark.observability


@pytest.fixture(autouse=True)
def _clean_observability():
    tracing.disable()
    tracing.timings.reset()
    tracing.counters.reset()
    obs.clear_ring()
    obs_events._reset_last_query()
    yield
    tracing.disable()
    tracing.timings.reset()
    tracing.counters.reset()
    obs.clear_ring()
    obs_events._reset_last_query()


def _depth(monkeypatch, d):
    monkeypatch.setenv("TFT_PIPELINE_DEPTH", str(d))


def _traced_map(monkeypatch, n=30, parts=6, depth=3):
    _depth(monkeypatch, depth)
    tracing.enable()
    df = tft.frame({"x": np.arange(float(n))}, num_partitions=parts)
    out = df.map_blocks(lambda x: {"y": x + 1.0})
    out.blocks()
    return df, out, out._trace


# ---------------------------------------------------------------------------
# correlation / context propagation
# ---------------------------------------------------------------------------

class TestCorrelation:
    def test_forcing_opens_query_trace(self, monkeypatch):
        _, out, t = _traced_map(monkeypatch)
        assert t is not None
        assert t.op == "map_blocks"
        assert re.fullmatch(r"q\d+", t.query_id)
        assert t.duration is not None and t.duration >= 0

    def test_query_ids_unique_per_query(self, monkeypatch):
        _, _, t1 = _traced_map(monkeypatch)
        _, _, t2 = _traced_map(monkeypatch)
        assert t1.query_id != t2.query_id

    def test_nested_forcings_join_outer_query(self, monkeypatch):
        # a chained lazy plan forces upstream frames inside one query:
        # exactly ONE trace, owned by the outermost forcing
        _depth(monkeypatch, 3)
        tracing.enable()
        df = tft.frame({"x": np.arange(20.0)}, num_partitions=4)
        mid = df.map_blocks(lambda x: {"y": x + 1.0})
        top = mid.map_blocks(lambda y: {"z": y * 2.0})
        top.blocks()
        assert top._trace is not None
        assert mid._trace is None  # joined the ambient query
        assert obs.last_query() is top._trace

    def test_query_id_survives_worker_threads(self):
        tracing.enable()
        seen = []
        with obs.query_trace("threaded") as t:
            with ThreadPoolExecutor(max_workers=2) as pool:
                fs = [pool.submit(obs.wrap_context(obs.current_trace))
                      for _ in range(4)]
                seen = [f.result() for f in fs]
            # an UNwrapped hop must not see the trace (that would mean
            # thread-inherited globals, not context propagation)
            with ThreadPoolExecutor(max_workers=1) as pool:
                bare = pool.submit(obs.current_trace).result()
        assert all(s is t for s in seen)
        assert bare is None

    def test_pipeline_worker_thread_events_attach_to_query(self,
                                                           monkeypatch):
        """An executor that dispatches on its own worker thread (the
        native-PJRT submit pattern, via wrap_context) records events that
        land on the submitting query's trace."""
        _depth(monkeypatch, 3)
        tracing.enable()
        inner = BlockExecutor()
        worker_qids = []

        class ThreadedExecutor:
            pad_rows = False

            def __init__(self):
                self._pool = ThreadPoolExecutor(max_workers=1)

            @property
            def compile_count(self):
                return inner.compile_count

            def run(self, comp, arrays, pad_ok=True):
                return inner.run(comp, arrays, pad_ok=pad_ok)

            def submit(self, comp, arrays, pad_ok=True):
                def work():
                    t = obs.current_trace()
                    worker_qids.append(t.query_id if t else None)
                    obs.add_event("worker_dispatch")
                    return inner.run(comp, arrays, pad_ok=pad_ok)

                fut = self._pool.submit(obs.wrap_context(work))

                class P:
                    def drain(self):
                        return fut.result()

                return P()

            def clear(self):
                inner.clear()

        df = tft.frame({"x": np.arange(24.0)}, num_partitions=6)
        out = df.map_blocks(lambda x: {"y": x - 1.0},
                            executor=ThreadedExecutor())
        got = np.asarray([r["y"] for r in out.collect()], float).ravel()
        np.testing.assert_array_equal(got, np.arange(24.0) - 1.0)
        t = out._trace
        assert t is not None
        assert worker_qids == [t.query_id] * 6
        assert t.count("worker_dispatch") == 6

    def test_eager_reduce_records_last_query(self, monkeypatch):
        _depth(monkeypatch, 3)
        tracing.enable()
        df = tft.frame({"x": np.arange(12.0)}, num_partitions=3)
        val = tft.reduce_blocks(lambda x_input: {"x": x_input.sum(0)}, df)
        assert float(val) == float(np.arange(12.0).sum())
        t = obs.last_query()
        assert t is not None and t.op == "reduce_blocks"
        assert "reduce_blocks" in tft.last_query_report()


# ---------------------------------------------------------------------------
# chrome trace export
# ---------------------------------------------------------------------------

class TestChromeTrace:
    def test_chrome_trace_valid_and_sorted(self, monkeypatch, tmp_path):
        _, out, t = _traced_map(monkeypatch, n=30, parts=6, depth=3)
        path = tmp_path / "trace.json"
        text = t.to_chrome_trace(file=str(path))
        doc = json.loads(text)
        assert json.loads(path.read_text()) == doc
        evs = doc["traceEvents"]
        assert evs, "no events exported"
        for e in evs:
            for field in ("ph", "ts", "pid", "tid"):
                assert field in e, (field, e)
        ts = [e["ts"] for e in evs]
        assert ts == sorted(ts)

    def test_per_block_events_on_slot_tracks_share_query_id(
            self, monkeypatch):
        _, out, t = _traced_map(monkeypatch, n=30, parts=6, depth=3)
        doc = json.loads(t.to_chrome_trace())
        evs = [e for e in doc["traceEvents"] if e["ph"] != "M"]
        assert {e["args"]["query_id"] for e in evs} == {t.query_id}
        by_cat = {}
        for e in evs:
            by_cat.setdefault(e.get("cat"), []).append(e)
        assert len(by_cat["block_submit"]) == 6
        assert len(by_cat["block_compute"]) == 6
        assert len(by_cat["block_drain"]) == 6
        # per-slot tracks: depth 3 -> tids 1..3, plus the query track 0
        block_tids = {e["tid"] for cat in ("block_submit", "block_drain")
                      for e in by_cat[cat]}
        assert block_tids == {1, 2, 3}
        # slot thread names exported for perfetto
        names = {e["args"]["name"] for e in doc["traceEvents"]
                 if e["ph"] == "M" and e["name"] == "thread_name"}
        assert {"query", "slot 0", "slot 1", "slot 2"} <= names

    def test_serial_depth_records_block_runs(self, monkeypatch):
        _, out, t = _traced_map(monkeypatch, n=12, parts=3, depth=1)
        assert t.count("block_run") == 3
        s = t.summary()
        assert s["blocks"] == 3 and s["rows_in"] == 12


# ---------------------------------------------------------------------------
# explain() / summary vs counters
# ---------------------------------------------------------------------------

class TestExplain:
    def test_explain_counts_match_counters(self, monkeypatch):
        base = tracing.counters.snapshot()
        _, out, t = _traced_map(monkeypatch, n=30, parts=6, depth=3)
        s = t.summary()
        now = tracing.counters.snapshot()

        def delta(name):
            return now.get(name, 0) - base.get(name, 0)

        assert s["blocks"] == delta("pipeline.submitted") == 6
        assert s["rows_in"] == 30 and s["rows_out"] == 30
        assert s["bytes_in"] == 30 * np.dtype(float).itemsize
        assert s["sync_fallbacks"] == delta("pipeline.sync_fallbacks") == 0
        assert s["compile_misses"] == delta("compile_cache.misses")
        assert s["compile_hits"] == delta("compile_cache.hits")
        report = out.explain()
        assert "30 in / 30 out" in report
        assert "6 block(s)" in report
        assert t.query_id in report
        assert "wall time by stage" in report

    def test_explain_reports_sync_fallbacks(self, monkeypatch):
        _depth(monkeypatch, 3)
        tracing.enable()
        base = tracing.counters.snapshot()
        df = tft.frame({"x": np.arange(20.0)}, num_partitions=4)
        out = df.map_blocks(lambda x: {"y": x + 1.0})
        # two async submit faults -> two blocks recover through the sync
        # fallback path; the trace must agree with the global counters
        with faults.inject("dispatch", fail_n=2):
            out.blocks()
        got = np.asarray([r["y"] for r in out.collect()], float).ravel()
        np.testing.assert_array_equal(got, np.arange(20.0) + 1.0)
        t = out._trace
        s = t.summary()
        now = tracing.counters.snapshot()

        def delta(name):
            return now.get(name, 0) - base.get(name, 0)

        assert s["sync_fallbacks"] == delta("pipeline.sync_fallbacks") == 2
        fb = [e for e in t.events if e.etype == "sync_fallback"]
        assert [e.args["error"] for e in fb] == ["InjectedFault"] * 2
        assert "2 sync fallback(s)" in out.explain()

    def test_explain_reports_retries_with_classified_error(
            self, monkeypatch):
        _depth(monkeypatch, 1)  # serial path: the fault hits the retry
        monkeypatch.setenv("TFT_RETRY_BASE_DELAY", "0.001")
        tracing.enable()
        base = tracing.counters.snapshot()
        df = tft.frame({"x": np.arange(12.0)}, num_partitions=3)
        out = df.map_blocks(lambda x: {"y": x + 1.0})
        with faults.inject("dispatch", fail_n=1):
            out.blocks()
        t = out._trace
        s = t.summary()
        now = tracing.counters.snapshot()
        delta = (now.get("retry.executor.dispatch.retries", 0)
                 - base.get("retry.executor.dispatch.retries", 0))
        assert s["retries"] == delta == 1
        retry = [e for e in t.events if e.etype == "retry"][0]
        assert retry.args["error"] == "InjectedFault"
        assert retry.args["kind"] == "transient"
        assert "1 retried" in out.explain()

    def test_explain_forces_untraced_frame(self, monkeypatch):
        _depth(monkeypatch, 3)
        df = tft.frame({"x": np.arange(10.0)}, num_partitions=2)
        out = df.map_blocks(lambda x: {"y": x * 3.0})
        out.blocks()  # forced with tracing OFF: no trace recorded
        assert out._trace is None
        report = out.explain()  # re-forces once, tracing temporarily on
        assert out._trace is not None
        assert "map_blocks" in report
        assert not tracing.enabled()  # restored

    def test_last_query_report_without_queries(self):
        assert "no query recorded" in tft.last_query_report()


# ---------------------------------------------------------------------------
# sinks: ring buffer + JSONL file
# ---------------------------------------------------------------------------

class TestSinks:
    def test_ring_buffer_bounded_under_10k_events(self, monkeypatch):
        monkeypatch.setenv("TFT_TRACE_RING", "1000")
        obs.clear_ring()
        tracing.enable()
        with obs.query_trace("flood") as t:
            for i in range(10_500):
                t.add("tick", i=i)
        ring = obs.recent_events()
        assert len(ring) == 1000  # bounded, newest kept
        assert ring[-1]["i"] == 10_499
        assert t.dropped == 0  # per-trace bound is separate

    def test_per_trace_event_bound_drops_and_counts(self):
        tracing.enable()
        with obs.query_trace("flood") as t:
            t._max_events = 10
            for i in range(25):
                t.add("tick", i=i)
        assert len(t.events) == 10
        assert t.dropped == 15
        assert tracing.counters.get("trace.events_dropped") == 15
        assert "+15 dropped" in t.report()

    def test_jsonl_file_sink(self, monkeypatch, tmp_path):
        path = tmp_path / "events.jsonl"
        monkeypatch.setenv("TFT_TRACE_FILE", str(path))
        _, out, t = _traced_map(monkeypatch, n=12, parts=3, depth=3)
        lines = [json.loads(line) for line in
                 path.read_text().splitlines()]
        heads = [r for r in lines if r["type"] == "query"]
        assert any(h["query_id"] == t.query_id for h in heads)
        evs = [r for r in lines if r.get("query_id") == t.query_id
               and r["type"] != "query"]
        assert len(evs) == len(t.events)
        assert all("ts" in e for e in evs)


# ---------------------------------------------------------------------------
# Prometheus metrics
# ---------------------------------------------------------------------------

_PROM_LINE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z_][a-zA-Z0-9_]*='
    r'"(?:[^"\\\n]|\\["\\n])*"(,[a-zA-Z_][a-zA-Z0-9_]*='
    r'"(?:[^"\\\n]|\\["\\n])*")*\})? '
    r'(-?\d+(\.\d+)?([eE][+-]?\d+)?|[+-]Inf|NaN)$')


class TestMetrics:
    def test_metrics_text_parses_as_prometheus(self, monkeypatch):
        _traced_map(monkeypatch)
        text = obs.metrics_text()
        assert text.endswith("\n")
        for line in text.splitlines():
            if not line or line.startswith("#"):
                continue
            assert _PROM_LINE.match(line), line
        assert 'tft_counter_total{name="pipeline.submitted"} 6' \
            in text
        assert 'tft_span_seconds_count{span="pipeline.submit"} 6' in text
        assert 'tft_gauge{name="pipeline.occupancy",stat="mean"}' in text
        assert "tft_trace_ring_events" in text

    def test_label_escaping(self):
        tracing.counters.inc('weird"name\\with\nnasties')
        text = obs.metrics_text()
        line = next(ln for ln in text.splitlines()
                    if "weird" in ln)
        assert _PROM_LINE.match(line), line
        assert '\\"' in line and "\\\\" in line and "\\n" in line
        assert "\n" not in line  # the raw newline never leaks through

    def test_endpoint_serves_metrics_on_loopback(self):
        tracing.counters.inc("endpoint.smoke")
        port = obs.serve_metrics(0)
        try:
            assert obs.metrics_port() == port
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
                assert r.status == 200
                assert "text/plain" in r.headers["Content-Type"]
                body = r.read().decode()
            assert 'tft_counter_total{name="endpoint.smoke"} 1' in body
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/nope", timeout=10)
        finally:
            obs.stop_metrics()
        assert obs.metrics_port() is None

    def test_rebind_to_different_port_raises(self):
        port = obs.serve_metrics(0)
        try:
            assert obs.serve_metrics(0) == port  # idempotent
            assert obs.serve_metrics(port) == port
            with pytest.raises(RuntimeError, match="already running"):
                obs.serve_metrics(port + 1)  # silently dead scrape target
        finally:
            obs.stop_metrics()


# ---------------------------------------------------------------------------
# zero-cost-when-off
# ---------------------------------------------------------------------------

class TestZeroCostWhenOff:
    def test_no_events_recorded_with_tracing_disabled(self, monkeypatch):
        _depth(monkeypatch, 3)
        assert not tracing.enabled()
        df = tft.frame({"x": np.arange(20.0)}, num_partitions=4)
        out = df.map_blocks(lambda x: {"y": x + 1.0})
        out.blocks()
        tft.reduce_blocks(lambda x_input: {"x": x_input.sum(0)}, df)
        assert out._trace is None
        assert obs.last_query() is None
        assert obs.recent_events() == []
        assert obs.current_trace() is None
        assert tracing.timings.snapshot() == {}

    def test_add_event_without_trace_is_noop(self):
        obs.add_event("orphan", detail="nothing listens")
        assert obs.recent_events() == []

    def test_bypass_strips_layer_even_when_enabled(self):
        tracing.enable()
        with obs_events.bypass():
            with obs.query_trace("stripped") as t:
                assert t is None
        assert obs.last_query() is None


# ---------------------------------------------------------------------------
# satellite: gauge stat family + merged report + dump_stats
# ---------------------------------------------------------------------------

class TestStatsSatellites:
    def test_gauge_has_own_stat_family(self):
        tracing.enable()
        for v in (1.0, 3.0, 2.0):
            tracing.gauge("my.level", v)
        snap = tracing.timings.snapshot()
        g = snap["my.level"]
        assert g == {"count": 3, "mean": 2.0, "min": 1.0, "max": 3.0,
                     "last": 2.0}
        assert "mean_s" not in g  # no vestigial seconds suffix

    def test_occupancy_legacy_alias_kept_one_release(self):
        tracing.enable()
        tracing.gauge("pipeline.occupancy", 2.0)
        tracing.gauge("pipeline.occupancy", 4.0)
        occ = tracing.timings.snapshot()["pipeline.occupancy"]
        assert occ["mean"] == 3.0 and occ["last"] == 4.0
        # deprecated aliases (pre-0.2 key names) still readable
        assert occ["mean_s"] == occ["mean"]
        assert occ["max_s"] == occ["max"]

    def test_report_merges_counters_and_gauges(self):
        tracing.enable()
        with tracing.span("stagey"):
            pass
        tracing.gauge("leveley", 5.0)
        tracing.counters.inc("county.things", 3)
        rep = tracing.timings.report()
        assert "stagey" in rep
        assert "leveley" in rep
        assert "county.things" in rep
        assert "gauge" in rep and "counter" in rep

    def test_dump_stats_prints_everything(self, capsys):
        tracing.enable()
        with tracing.span("dumped.span"):
            pass
        tracing.gauge("dumped.gauge", 1.0)
        tracing.counters.inc("dumped.counter")
        tft.dump_stats()
        out = capsys.readouterr().out
        for name in ("dumped.span", "dumped.gauge", "dumped.counter"):
            assert name in out


# ---------------------------------------------------------------------------
# satellite: profile()/span() exception safety
# ---------------------------------------------------------------------------

class TestTracingExceptionSafety:
    def test_profile_stop_failure_does_not_mask_body_error(
            self, monkeypatch, tmp_path):
        import jax

        # fake session: a real one left open by the raising stop_trace
        # would wedge every later jax.profiler user in the process
        monkeypatch.setattr(jax.profiler, "start_trace",
                            lambda log_dir, **k: None)
        monkeypatch.setattr(jax.profiler, "stop_trace",
                            _raise_runtime_error)
        with pytest.raises(ValueError, match="body failed"):
            with tracing.profile(str(tmp_path)):
                raise ValueError("body failed")
        assert not tracing.enabled()

    def test_profile_stop_failure_does_not_fail_successful_body(
            self, monkeypatch, tmp_path):
        import jax

        monkeypatch.setattr(jax.profiler, "start_trace",
                            lambda log_dir, **k: None)
        monkeypatch.setattr(jax.profiler, "stop_trace",
                            _raise_runtime_error)
        with tracing.profile(str(tmp_path)):
            pass  # succeeded; the failing stop must be swallowed+logged
        assert not tracing.enabled()

    def test_span_survives_annotation_exit_failure(self, monkeypatch):
        class EvilAnnotation:
            def __enter__(self):
                return self

            def __exit__(self, *exc):
                raise RuntimeError("annotation teardown exploded")

        monkeypatch.setattr(tracing, "_device_annotation",
                            lambda name: EvilAnnotation())
        tracing.enable()
        with tracing.span("guarded"):
            pass  # must not raise
        snap = tracing.timings.snapshot()
        assert snap["guarded"]["count"] == 1  # timing still recorded

    def test_span_survives_annotation_enter_failure(self, monkeypatch):
        class Unenterable:
            def __enter__(self):
                raise RuntimeError("no profiler session")

            def __exit__(self, *exc):
                raise AssertionError("never entered, never exited")

        monkeypatch.setattr(tracing, "_device_annotation",
                            lambda name: Unenterable())
        tracing.enable()
        with tracing.span("guarded2"):
            pass
        assert tracing.timings.snapshot()["guarded2"]["count"] == 1


def _raise_runtime_error(*a, **k):
    raise RuntimeError("profiler session already gone")
